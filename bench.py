#!/usr/bin/env python
"""TPU benchmark: sampled engine vs the native serial C++ baseline.

Protocol (BASELINE.md): the reference's "speed" harness times sampler
wall clock (c_lib/test/Makefile:34-37); its sampled r10 variant is
measured against the serial full-traversal C++ sampler. Here:

- workload: GEMM N (default 4096, the north-star config), THREAD_NUM=4,
  CHUNK=4, DS=8, CLS=64
  — the reference machine model at scale;
- ours: the vectorized random-start sampled engine (ratio 10%) on the
  default JAX device (one TPU chip under the driver), timed after a
  compile warm-up;
- baseline: the native C++ serial full-traversal sampler
  (pluss_sampler_optimization_tpu/native), single core, same host —
  the reference's own accuracy/speed oracle re-implemented over the IR;
- accuracy: MRC L1 error between the sampled MRC and the serial MRC
  after the full CRI + AET pipeline on both.

Output protocol (the driver tails stdout and parses the LAST line):
  earlier line + BENCH_EVIDENCE.json sidecar: the full record
  {"metric", "value", "unit", "vs_baseline", "extra" {...}};
  FINAL line: a compact headline (<500 bytes — emit_result) with
  metric/value/unit/vs_baseline/device and an evidence pointer.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


_PROBE_MARKER = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_cache", "accel_ok"
)
_PROBE_TTL_S = 3600.0


_PROBE_SCRIPT = """\
import time

t0 = time.time()
import jax
print("IMPORT_OK", round(time.time() - t0, 1), flush=True)
t0 = time.time()
d = jax.devices()
print("DEVICES_OK", round(time.time() - t0, 1), d[0].platform,
      d[0].device_kind, flush=True)
t0 = time.time()
import jax.numpy as jnp
jax.jit(lambda x: (x @ x).sum())(jnp.ones((128, 128))).block_until_ready()
print("JIT_OK", round(time.time() - t0, 1), flush=True)
"""


def probe_accelerator(timeout_s: float) -> tuple[bool, list]:
    """Check in subprocesses that the default JAX backend can COMPILE.

    The accelerator sits behind a tunnel whose setup can stall
    indefinitely — and `jax.devices()` succeeding does not imply the
    compile service behind it is up (a dead remote-compile endpoint
    once failed 25 minutes into warm-up). So each probe attempt runs a
    tiny jit end-to-end with staged progress markers; a hang hits the
    subprocess timeout and the parent pins JAX_PLATFORMS=cpu before it
    ever imports jax. Retries with a backoff schedule (a tunnel can
    come up late) and returns (ok, attempt evidence) — the evidence
    records, per attempt, how far init got (IMPORT/DEVICES/JIT marker),
    the elapsed time, and the stderr tail, so an unreachable chip
    leaves a root-causable record in the bench JSON rather than a bare
    "fell back to CPU". A successful probe is cached for an hour so
    healthy repeat runs skip the duplicate backend init.
    """
    try:
        if time.time() - os.path.getmtime(_PROBE_MARKER) < _PROBE_TTL_S:
            return True, [{"cached": True}]
    except OSError:
        pass

    evidence: list = []
    # The accelerator plugin in this environment dials a loopback relay
    # (pool IPs from the env); a dead relay means jax.devices() blocks
    # forever in the claim loop. A 2s TCP check per service port turns
    # "the probe timed out" into "nothing is listening at the relay" —
    # the difference between a mystery and a root cause.
    pool_ips = os.environ.get("PALLAS_AXON_POOL_IPS", "")
    # first IP only, 1s per port: worst case 3s, charged against the
    # budget below (and skipped entirely when the budget is too small
    # to absorb it) so the flag's contract holds
    if pool_ips and timeout_s > 10.0:
        import socket

        t0 = time.perf_counter()
        reach = {}
        ip = pool_ips.split(",")[0].strip()
        for port in (8081, 8082, 8083):
            try:
                with socket.create_connection((ip, port), 1):
                    reach[f"{ip}:{port}"] = "open"
            except OSError as e:
                reach[f"{ip}:{port}"] = type(e).__name__
        scan_s = time.perf_counter() - t0
        evidence.append(
            {"relay_tcp": reach, "seconds": round(scan_s, 1)}
        )
        timeout_s = max(1.0, timeout_s - scan_s)

    # ~1/4 of the budget for a quick first look, the rest for one long
    # patient attempt (slow-but-alive tunnels need minutes to init).
    # The total never exceeds timeout_s — that is the flag's contract.
    first = min(max(30.0, timeout_s / 4), timeout_s)
    schedule = [first]
    if timeout_s - first > 1.0:
        schedule.append(timeout_s - first)
    ok = False
    for i, t_limit in enumerate(schedule):
        t0 = time.perf_counter()
        proc = subprocess.Popen(
            [sys.executable, "-u", "-c", _PROBE_SCRIPT],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            out, err = proc.communicate(timeout=t_limit)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
            rc = "timeout"
        stages = [ln for ln in out.splitlines()
                  if ln.startswith(("IMPORT_OK", "DEVICES_OK", "JIT_OK"))]
        evidence.append({
            "attempt": i + 1,
            "timeout_s": t_limit,
            "rc": rc,
            "seconds": round(time.perf_counter() - t0, 1),
            "stages": stages[-3:],
            "stderr_tail": err.strip().splitlines()[-2:],
        })
        ok = rc == 0 and "JIT_OK" in out
        if ok:
            break
    if ok:
        try:
            os.makedirs(os.path.dirname(_PROBE_MARKER), exist_ok=True)
            with open(_PROBE_MARKER, "w"):
                pass
        except OSError:
            pass
    return ok, evidence


def guarded_backend_init(
    init_fn, timeout_s: float, on_timeout=None, probe_was_cached=True,
    spent_fn=None,
):
    """Run the first backend touches (device claim AND first compile)
    under a watchdog bounded by the --warmup-timeout budget.

    Two ways the probe can pass while the main process still hangs:
    a cached probe marker (< _PROBE_TTL_S old) skips the subprocess
    probe entirely and the tunnel may have died inside the TTL; or the
    live probe's jit succeeded and the tunnel/compile service died in
    the seconds between probe exit and the main process's own init.
    Either way the main process would block with no bound — exactly
    the failure mode the watchdog exists to prevent. The watchdog
    cannot interrupt a call stuck inside a PJRT plugin's claim loop
    (Python threads are not killable), so the default timeout action
    deletes the (possibly stale) marker and re-execs this process with
    --accel-hang-fallback {cached,live}, which pins the CPU backend
    before any jax state is touched; the restart records the accurate
    root cause in the bench JSON. `on_timeout` overrides that action
    (tests/test_bench.py pins the budget with a recording handler).
    Returns init_fn()'s result when it completes in time."""
    import threading

    done = threading.Event()

    def fire():
        if done.wait(timeout_s):
            return
        if on_timeout is not None:
            on_timeout()
            return
        try:
            os.remove(_PROBE_MARKER)
        except OSError:
            pass
        kind = "cached" if probe_was_cached else "live"
        sys.stderr.write(
            f"bench: backend init/first-compile exceeded {timeout_s:.0f}s "
            f"after a {kind} probe pass; marker deleted, re-executing "
            "on the CPU backend\n"
        )
        sys.stderr.flush()
        argv = [
            a for i, a in enumerate(sys.argv)
            if a not in ("--accel-hang-fallback", "--extras-spent")
            and (i == 0 or sys.argv[i - 1] not in (
                "--accel-hang-fallback", "--extras-spent"))
        ]
        extra_argv = ["--accel-hang-fallback", kind]
        if spent_fn is not None:
            # the re-exec'd process must keep charging the wall time
            # this one burned against --extras-deadline — without it
            # the fresh process would happily start extras 30+ min
            # into the harness's outer budget
            extra_argv += ["--extras-spent", f"{spent_fn():.0f}"]
        os.execv(sys.executable, [sys.executable] + argv + extra_argv)

    threading.Thread(target=fire, daemon=True).start()
    try:
        return init_fn()
    finally:
        done.set()


# Host fingerprint, CPU-features hash, cgroup throttle reads, and the
# jax.monitoring compile counters all moved to the shared telemetry
# layer (pluss_sampler_optimization_tpu/runtime/telemetry.py) — this
# script consumes them like any other caller. Imported lazily inside
# main() so the probe/watchdog path stays import-light.

EVIDENCE_SIDECAR = "BENCH_EVIDENCE.json"  # `latest` pointer, kept stable
BENCH_OUT_DIR = "bench_out"  # stamped evidence/telemetry files land here
HEADLINE_MAX_BYTES = 500

_RUN_SEQ = [0]  # process-local tiebreak: same-second same-pid calls


def _stamped_sidecar_name(metric: str,
                          prefix: str = "BENCH_EVIDENCE") -> str:
    """Per-run sidecar filename: metric + run id (UTC timestamp, pid,
    in-process sequence). Back-to-back or concurrent bench invocations
    each keep their own evidence instead of clobbering one shared file
    — round 5's on-disk BENCH_EVIDENCE.json held a different run than
    the headline pointing at it (VERDICT weak #4). The telemetry
    sidecar uses the same scheme under the BENCH_TELEMETRY prefix."""
    import re

    safe = re.sub(r"[^A-Za-z0-9._-]+", "-", metric)[:60]
    _RUN_SEQ[0] += 1
    rid = "%s-%d-%d" % (
        time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
        os.getpid(), _RUN_SEQ[0],
    )
    return f"{prefix}_{safe}_{rid}.json"


def emit_result(headline: dict, extra: dict, sidecar_dir: str | None = None,
                out=None) -> str:
    """Print the full evidence record, then a compact FINAL line.

    The driver tails stdout and parses the LAST line. Round 4's lesson:
    one giant JSON line (headline + all evidence inlined) outgrew the
    tail capture and `BENCH_r04.json` recorded `parsed: null` — the
    round's number was simply lost. So the full record goes on an
    EARLIER stdout line and into a STAMPED sidecar file (metric +
    run id in the name, next to this script) that the headline's
    `evidence` field names; `BENCH_EVIDENCE.json` is maintained as a
    `latest` pointer to the stamped file for tooling that greps the
    fixed name. The final line is a small headline —
    metric/value/unit/vs_baseline plus the few numbers a reader needs
    at a glance and the evidence pointer — ENFORCED under
    HEADLINE_MAX_BYTES (optional keys drop first, then the metric
    string itself truncates) so it survives any reasonable tail.

    Returns the final line (for tests).
    """
    out = out if out is not None else sys.stdout
    full = dict(headline)
    full["extra"] = extra
    print(json.dumps(full), file=out)

    sidecar_dir = sidecar_dir or os.path.dirname(os.path.abspath(__file__))
    stamped = _stamped_sidecar_name(str(headline.get("metric", "run")))
    # stamped files accumulate one per run, so they live under
    # bench_out/ (gitignored) instead of littering the repo root; the
    # fixed-name `latest` pointer stays at sidecar_dir and the headline
    # `evidence` ref carries the bench_out/ prefix so readers resolve
    # it relative to the pointer's directory
    evidence_ref = os.path.join(BENCH_OUT_DIR, stamped)
    try:
        # atomic (tmp+rename): a killed bench never leaves a truncated
        # evidence file for the driver's collectors to choke on
        from pluss_sampler_optimization_tpu.runtime.io import (
            atomic_write_json,
        )

        os.makedirs(os.path.join(sidecar_dir, BENCH_OUT_DIR),
                    exist_ok=True)
        atomic_write_json(os.path.join(sidecar_dir, evidence_ref), full)
    except OSError:
        evidence_ref = "stdout line above (sidecar write failed)"
    else:
        # `latest` pointer at the old fixed name: a symlink where the
        # filesystem allows it, else a tiny JSON pointer file — never
        # a second copy of the evidence (the copy WAS the staleness
        # hazard: it described whichever run wrote it last)
        latest = os.path.join(sidecar_dir, EVIDENCE_SIDECAR)
        try:
            if os.path.islink(latest) or os.path.exists(latest):
                os.remove(latest)
            os.symlink(evidence_ref, latest)
        except OSError:
            try:
                atomic_write_json(latest, {"latest": evidence_ref},
                                  indent=None)
            except OSError:
                pass

    compact = dict(headline)
    compact["device"] = extra.get("device")
    # at-a-glance numbers, droppable if the line ever outgrows the cap
    optional = {}
    if "mrc_l1_err" in extra:
        optional["mrc_l1_err"] = extra["mrc_l1_err"]
    pex = extra.get("periodic_exact") or {}
    if isinstance(pex, dict) and "vs_baseline" in pex:
        optional["periodic_exact_vs"] = pex["vs_baseline"]
    aex = extra.get("analytic_exact") or {}
    if isinstance(aex, dict) and "engine" in aex:
        # the exact router's secondary row, engine label included —
        # the driver's tail is where an `"engine": "analytic"` row
        # must be visible (VERDICT round 5, next-round #5)
        optional["exact_secondary"] = {
            k: aex[k]
            for k in ("engine", "vs_baseline", "model")
            if k in aex
        }
    compact.update(optional)
    compact["evidence"] = evidence_ref
    line = json.dumps(compact)
    for key in list(optional):
        if len(line.encode()) <= HEADLINE_MAX_BYTES:
            break
        compact.pop(key)
        line = json.dumps(compact)
    if len(line.encode()) > HEADLINE_MAX_BYTES:
        # required fields alone overflow (unbounded metric name or the
        # sidecar-failure fallback text): truncate the longest string
        # fields until the contract holds instead of assuming it
        for key in ("metric", "evidence"):
            over = len(line.encode()) - HEADLINE_MAX_BYTES
            if over <= 0:
                break
            s = str(compact.get(key, ""))
            compact[key] = s.encode()[: max(8, len(s.encode()) - over)
                                      ].decode("utf-8", "ignore")
            line = json.dumps(compact)
    assert len(line.encode()) <= HEADLINE_MAX_BYTES, (
        f"headline still {len(line.encode())} bytes after truncation"
    )
    print(line, file=out)
    return line


def _bench_hist_kernel_on_device() -> dict:
    """TPU-only: equality + timing of the Pallas pow2 histogram kernel
    vs the portable scatter-add (`exp_hist`) on a realistic batch.

    Runs only when the bench actually landed on a TPU, so BENCH JSON
    carries device-executed evidence for the kernel. The kernel is
    default-ON (SamplerConfig.use_pallas_hist) from the 2026-07-31
    v5e measurement (bit-equal, 4.4x at 4M intervals); this block
    re-validates that default on every TPU bench run.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    from pluss_sampler_optimization_tpu.ops.histogram import exp_hist
    from pluss_sampler_optimization_tpu.ops.pallas_hist import pow2_hist

    rng = np.random.default_rng(0)
    n = 1 << 22  # ~4M intervals, the sharded engine's per-call scale
    values = jnp.asarray(
        rng.integers(1, 1 << 62, size=n, dtype=np.int64))
    weights = jnp.asarray(rng.integers(0, 2, size=n, dtype=np.int64))

    out = {"n": n}
    try:
        a = np.asarray(jax.block_until_ready(pow2_hist(values, weights)))
        b = np.asarray(jax.block_until_ready(exp_hist(values, weights)))
        out["equal_on_device"] = bool((a == b).all())

        def med(fn, reps=5):
            ts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(values, weights))
                ts.append(time.perf_counter() - t0)
            return sorted(ts)[len(ts) // 2]

        out["pallas_s"] = round(med(pow2_hist), 5)
        out["exp_hist_s"] = round(med(exp_hist), 5)
        out["speedup"] = round(out["exp_hist_s"] / out["pallas_s"], 2)
    except Exception as e:  # never sink the headline metric
        out["error"] = repr(e)
    return out


def replica_scaling_extra(requests=None, timeout: float = 600.0) -> dict:
    """Replica-pool scaling evidence: the given concurrent DISTINCT
    sampled requests served from cold caches with no pool (the
    pre-replica baseline), replicas=1 (routing overhead), and
    replicas=4 (device groups serving concurrently). Per config:
    wall clock, throughput, the set of replica ids that executed, and
    the quarantine count; across configs: MRC-digest bit-identity,
    the replicas=1 overhead vs baseline, and the replicas=4 speedup.
    main() records this as the `replica_scaling` extra;
    tests/test_replicas.py exercises it directly at small N."""
    import shutil
    import tempfile

    from pluss_sampler_optimization_tpu.service import (
        AnalysisRequest,
        AnalysisService,
    )

    reqs = requests if requests is not None else [
        AnalysisRequest(model="gemm", n=24, engine="sampled",
                        ratio=0.2, seed=11),
        AnalysisRequest(model="gemm", n=32, engine="sampled",
                        ratio=0.2, seed=12),
        AnalysisRequest(model="2mm", n=12, engine="sampled",
                        ratio=0.2, seed=13),
        AnalysisRequest(model="mvt", n=48, engine="sampled",
                        ratio=0.2, seed=14),
    ]
    rs: dict = {
        "requests": [
            {"model": r.model, "n": r.n, "seed": r.seed}
            for r in reqs
        ],
    }
    digests: dict = {}
    for label, replicas in (("baseline", None),
                            ("replicas_1", 1),
                            ("replicas_4", 4)):
        svc_dir = tempfile.mkdtemp(prefix=f"bench_replicas_{label}_")
        try:
            t0 = time.perf_counter()
            with AnalysisService(
                max_workers=4, cache_dir=svc_dir, replicas=replicas,
            ) as svc:
                tickets = [svc.submit(r) for r in reqs]
                resps = [svc.result(t, timeout=timeout)
                         for t in tickets]
                snap = svc.stats()["executor"].get("replicas") or {}
            dt = time.perf_counter() - t0
            digests[label] = [r.mrc_digest for r in resps]
            rids = sorted(
                {r.replica_id for r in resps
                 if r.replica_id is not None}
            )
            rs[label] = {
                "wall_s": round(dt, 4),
                "throughput_rps": round(len(reqs) / dt, 3),
                "ok": all(r.ok for r in resps),
                "replica_ids": rids,
                "distinct_replicas": len(rids),
                "quarantined": snap.get("quarantined", 0),
            }
        finally:
            shutil.rmtree(svc_dir, ignore_errors=True)
    # the acceptance evidence: identical MRC digests for any replica
    # count, <5% routing overhead at replicas=1, and the 4-replica
    # scaling factor
    rs["bit_identical"] = (
        digests["baseline"] == digests["replicas_1"]
        == digests["replicas_4"]
    )
    base_s = rs["baseline"]["wall_s"]
    rs["replicas_1_overhead_pct"] = round(
        100.0 * (rs["replicas_1"]["wall_s"] - base_s)
        / max(1e-9, base_s), 2,
    )
    rs["replicas_4_speedup"] = round(
        rs["replicas_1"]["wall_s"]
        / max(1e-9, rs["replicas_4"]["wall_s"]), 2,
    )
    return rs


def overload_shedding_extra(timeout: float = 120.0) -> dict:
    """Pinned-overload shedding evidence: the SAME deterministic
    Poisson arrival sequence (tools/loadgen.py) offered at ~3x the
    service's capacity, once with the admission gate on and once
    with it off. Records goodput and tail latency for both runs plus
    the p95 collapse factor — the acceptance claim is that shedding
    trades a bounded number of structured `shed` responses for a p95
    that stays near queue_limit x service_time, while the shed-off
    baseline's p95 collapses toward queue-drain time.
    main() records this as the `overload_shedding` extra;
    tools/check_chaos.py gates the same comparison per seed."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"
    ))
    import loadgen

    cmp = loadgen.overload_comparison(
        n=120, rate_rps=300.0, queue_limit=6, max_workers=2,
        service_time_s=0.03, seed=0, timeout_s=timeout,
    )
    on, off = cmp["shed_on"], cmp["shed_off"]
    return {
        "offered_rps": on["offered_rps"],
        "capacity_rps": on["capacity_rps"],
        "queue_limit": on["queue_limit"],
        "shed_on": {
            "ok": on["ok"], "shed": on["shed"],
            "goodput_rps": on["goodput_rps"],
            "latency_p50_s": on["latency_p50_s"],
            "latency_p95_s": on["latency_p95_s"],
        },
        "shed_off": {
            "ok": off["ok"], "shed": off["shed"],
            "goodput_rps": off["goodput_rps"],
            "latency_p50_s": off["latency_p50_s"],
            "latency_p95_s": off["latency_p95_s"],
        },
        "p95_collapse_factor": cmp["p95_collapse_factor"],
        "tail_held": (off["latency_p95_s"] or 0.0)
        > (on["latency_p95_s"] or 0.0),
        "no_losses": on["failed"] == 0 and off["failed"] == 0,
    }


def progressive_precision_extra(model: str = "gemm", n: int = 32,
                                ratio: float = 0.3, seed: int = 0,
                                tolerance: float = 0.15) -> dict:
    """Progressive-precision evidence: what the confidence-banded
    round schedule (sampler/sampled.py::run_sampled_progressive)
    buys and what it costs. Three runs of the same (model, n, ratio,
    seed): the one-shot sampled engine (the static full-ratio
    baseline), the full progressive schedule (tolerance 0 — must
    land the SAME MRC digest, the bit-identity claim), and a
    tolerance-stopped run recording samples-to-tolerance — how many
    samples the early exit left unclassified once the bootstrap band
    was narrow enough. main() records this as the
    `progressive_precision` extra; tools/check_precision.py gates
    the bit-identity and replay halves per seed."""
    from pluss_sampler_optimization_tpu.config import (
        MachineConfig, SamplerConfig,
    )
    from pluss_sampler_optimization_tpu.models import (
        build as build_model,
    )
    from pluss_sampler_optimization_tpu.runtime.aet import aet_mrc
    from pluss_sampler_optimization_tpu.runtime.cri import (
        cri_distribute,
    )
    from pluss_sampler_optimization_tpu.runtime.obs import (
        ledger as obs_ledger,
    )
    from pluss_sampler_optimization_tpu.sampler.sampled import (
        run_sampled, run_sampled_progressive,
    )

    program = build_model(model, n)
    machine = MachineConfig()
    T = machine.thread_num

    def digest(state):
        return obs_ledger.mrc_digest(
            aet_mrc(cri_distribute(state, T, T), machine)
        )

    t0 = time.perf_counter()
    state_o, results_o = run_sampled(
        program, machine, SamplerConfig(ratio=ratio, seed=seed)
    )
    wall_one = time.perf_counter() - t0
    one_samples = int(sum(r.n_samples for r in results_o))
    digest_one = digest(state_o)

    t0 = time.perf_counter()
    state_f, _results_f, info_f = run_sampled_progressive(
        program, machine,
        SamplerConfig(ratio=ratio, seed=seed, tolerance=0.0),
    )
    wall_full = time.perf_counter() - t0
    digest_full = digest(state_f)

    t0 = time.perf_counter()
    state_t, results_t, info_t = run_sampled_progressive(
        program, machine,
        SamplerConfig(ratio=ratio, seed=seed, tolerance=tolerance),
    )
    wall_tol = time.perf_counter() - t0
    tol_samples = int(sum(r.n_samples for r in results_t))

    return {
        "model": model, "n": n, "ratio": ratio, "seed": seed,
        "tolerance": tolerance,
        "one_shot": {
            "samples": one_samples, "wall_s": round(wall_one, 4),
            "mrc_digest": digest_one,
        },
        "full_schedule": {
            "rounds": info_f["rounds"],
            "band_width": round(info_f["band_width"], 6),
            "wall_s": round(wall_full, 4),
            "mrc_digest": digest_full,
            "round_overhead_frac": round(
                wall_full / max(1e-9, wall_one) - 1.0, 4
            ),
        },
        "tolerance_stop": {
            "rounds": info_t["rounds"],
            "rounds_total": info_t["rounds_total"],
            "band_width": round(info_t["band_width"], 6),
            "converged": info_t["converged"],
            "samples": tol_samples,
            "wall_s": round(wall_tol, 4),
            "mrc_digest": digest(state_t),
        },
        "digest_parity": digest_full == digest_one,
        "stopped_early": info_t["rounds"] < info_t["rounds_total"],
        "samples_saved_frac": round(
            1.0 - tol_samples / max(1, one_samples), 4
        ),
    }


def lock_witness_extra(timeout: float = 120.0) -> dict:
    """Lockdep-witness overhead on the serving path: the same
    deterministic request set served witness-off and witness-on
    (median-of-3 wall each), pinned under the same 2% budget as the
    registry/recorder overheads. Also records the pure-observer
    evidence — bit-identical MRC digests both ways and zero observed
    lock-order inversions. main() records this as the `lock_witness`
    extra; tools/check_chaos.py gates the same properties per seed."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"
    ))
    import loadgen

    from pluss_sampler_optimization_tpu.runtime import lockwitness
    from pluss_sampler_optimization_tpu.service import AnalysisService

    reqs = loadgen.make_requests(24, seed=5, unique_frac=0.75)

    def one_pass():
        with AnalysisService(
            max_workers=4,
            runner=loadgen.synthetic_runner(0.002, seed=5),
        ) as svc:
            tickets = [svc.submit(r) for r in reqs]
            return [svc.result(t, timeout=timeout) for t in tickets]

    def med3():
        ts, resps = [], None
        for _ in range(3):
            t0 = time.perf_counter()
            resps = one_pass()
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[1], resps

    one_pass()  # warm the runner memo off the clock
    was_enabled = lockwitness.enabled()
    lockwitness.disable()
    try:
        off_s, off_resps = med3()
        lockwitness.reset()
        lockwitness.enable()
        on_s, on_resps = med3()
        witness = lockwitness.report()
    finally:
        if not was_enabled:
            lockwitness.disable()
            lockwitness.reset()
    overhead_pct = round(100.0 * (on_s - off_s) / max(1e-9, off_s), 2)
    return {
        "requests": len(reqs),
        "disabled_s": round(off_s, 4),
        "enabled_s": round(on_s, 4),
        "overhead_pct": overhead_pct,
        "within_budget": overhead_pct < 2.0,
        "budget_pct": 2.0,
        "bit_identical": [r.mrc_digest for r in off_resps]
        == [r.mrc_digest for r in on_resps],
        "ok": all(r.ok for r in off_resps + on_resps),
        "observed_edges": len(witness["edges"]),
        "inversions": witness["inversion_count"],
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    # default = the north-star config (BASELINE.json: GEMM N=4096);
    # its serial baseline ships recorded in baselines/
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--model", default="gemm")
    ap.add_argument("--engine", default="sampled",
                    choices=["sampled", "dense", "stream", "periodic",
                             "analytic", "exact"],
                    help="sampled = random-start closed-form engine "
                    "(the r10 equivalent); dense/stream = exact "
                    "full-traversal engines (the ri/ri-opt speed "
                    "rows); periodic = exact engine from O(1) "
                    "two-period windows (sampler/periodic.py); "
                    "analytic = exact closed-form next-use per period "
                    "(sampler/analytic.py — covers the classes "
                    "periodic rejects); exact = fastest applicable "
                    "exact path (periodic -> analytic -> dense "
                    "auto-route, same as the package CLI)")
    ap.add_argument("--ratio", type=float, default=0.1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--reps", type=int, default=3,
                    help="timed repetitions after warm-up; the median "
                    "is reported (reference speed mode runs 10)")
    ap.add_argument("--chunk-m", type=int, default=None,
                    help="stream engine: parallel-iteration chunk size")
    ap.add_argument("--second-model", default="2mm",
                    help="extra sampled-engine metric on a second model "
                    "at --second-n ('' disables)")
    ap.add_argument("--second-n", type=int, default=1024,
                    help="default matches the recorded 2mm baseline in "
                    "baselines/ (large enough that the sampled run is "
                    "not dispatch-bound)")
    ap.add_argument("--exact-model", default="syrk",
                    help="extra EXACT-router metric on a periodic-"
                    "rejected model so the driver artifact carries an "
                    "analytic-engine row ('' disables; default syrk — "
                    "mixed parallel coefficients route it to the "
                    "analytic engine, and a recorded serial baseline "
                    "exists at --exact-n 1024)")
    ap.add_argument("--exact-n", type=int, default=1024,
                    help="size for --exact-model (default matches the "
                    "recorded syrk baseline in baselines/)")
    ap.add_argument("--skip-baseline", action="store_true",
                    help="report throughput only, without measuring or "
                    "loading the serial baseline (for configs whose "
                    "serial run is infeasible, e.g. GEMM N=8192 at "
                    "~19h of single-core time)")
    ap.add_argument("--device-timeout", type=float, default=240.0,
                    help="accelerator PROBE budget in seconds; a dead "
                    "tunnel is declared within this bound and the "
                    "bench falls back to CPU (0 = trust the backend, "
                    "no probe, no watchdog)")
    ap.add_argument("--extras-deadline", type=float, default=2400.0,
                    help="wall-clock budget (seconds, from process "
                    "start) for the post-headline extras (the "
                    "periodic-exact secondary row and the second "
                    "model): if the headline work already consumed "
                    "the budget — e.g. a cold-cache TPU warm-up at "
                    "~1-1.5 min per remote compile — the extras are "
                    "skipped WITH a recorded reason so the one JSON "
                    "line the driver consumes is never lost to a "
                    "harness timeout mid-extra (0 = no deadline)")
    ap.add_argument("--warmup-timeout", type=float, default=1800.0,
                    help="separate watchdog for init+warm-up AFTER a "
                    "probe pass: the chip is known alive, but kernel "
                    "compiles through the remote AOT helper run "
                    "~1-1.5 min each (measured 2026-07-31, BASELINE.md "
                    "on-device section) so a cold cache legitimately "
                    "needs ~10-15 min — under the old shared budget a "
                    "reachable TPU with a cold cache was indistinguish"
                    "able from a hang and fell back to CPU. A warm "
                    "cache passes in seconds; a genuine mid-warm-up "
                    "hang (round 2 saw a compile service die 25 min "
                    "in) is still bounded by this flag "
                    "(0 = no warm-up watchdog)")
    ap.add_argument("--ledger", default="LEDGER.jsonl",
                    help="append this run's headline row (and the MRC "
                    "digest) to the run ledger at this path, relative "
                    "to the script directory; the evidence JSON "
                    "cross-references it and `cli stats` / "
                    "tools/check_ledger.py consume it ('' disables)")
    ap.add_argument("--kernel-backend", default=None,
                    choices=["auto", "xla", "pallas", "native"],
                    help="sampled engine: classify+histogram kernel "
                    "for the headline runs (SamplerConfig."
                    "kernel_backend; default auto). The "
                    "kernel_roofline extra measures all backends "
                    "regardless")
    ap.add_argument("--require-accelerator", action="store_true",
                    help="exit nonzero instead of benchmarking on the "
                    "CPU backend (probe fallback or a CPU-only host): "
                    "for drivers whose numbers are only meaningful as "
                    "accelerator evidence")
    ap.add_argument("--extras-spent", type=float, default=0.0,
                    help=argparse.SUPPRESS)  # internal: wall seconds
    # already burned by a predecessor process before an accel-hang
    # re-exec; charged against --extras-deadline
    ap.add_argument("--accel-hang-fallback", choices=["cached", "live"],
                    default=None, help=argparse.SUPPRESS)  # internal:
    # set by the guarded_backend_init re-exec when the probe passed
    # (via a cached marker or a live attempt) but the main process's
    # backend init/first compile hung; forces the CPU path
    args = ap.parse_args()
    t_process_start = time.monotonic()

    def extras_budget_left(tag: str, extra: dict) -> bool:
        """Post-headline extras run only inside --extras-deadline; a
        skip records which extra and why, so the JSON explains the
        missing row instead of silently omitting it."""
        if args.extras_deadline <= 0:
            return True
        spent = time.monotonic() - t_process_start + args.extras_spent
        if spent < args.extras_deadline:
            return True
        extra.setdefault("extras_skipped", []).append({
            "extra": tag,
            "reason": f"wall clock {spent:.0f}s exceeded "
            f"--extras-deadline {args.extras_deadline:.0f}s before "
            "this extra started (headline work, e.g. a cold-cache "
            "device warm-up, consumed the budget)",
        })
        return False

    device_fallback = False
    probe_evidence: list = []
    probe_was_cached = False
    if args.accel_hang_fallback:
        device_fallback = True
        how = (
            "cached accel_ok marker passed the probe"
            if args.accel_hang_fallback == "cached"
            else "live probe passed but the tunnel died before the "
            "main process's own init"
        )
        probe_evidence = [{
            "accel_hang": f"{how}; backend init/first compile then "
            "hung past the --warmup-timeout budget; marker deleted "
            "and process re-executed on the CPU backend"
        }]
    elif args.device_timeout > 0:
        ok, probe_evidence = probe_accelerator(args.device_timeout)
        device_fallback = not ok
        probe_was_cached = probe_evidence == [{"cached": True}]

    import jax

    from pluss_sampler_optimization_tpu.runtime import telemetry

    # register the monitoring listeners BEFORE the first backend touch
    # (so warm-up compiles are counted), then start the bench's
    # telemetry run; the full record ships as a stamped sidecar next
    # to the evidence files and summarizes on stderr at exit.
    try:
        telemetry.register_jax_hooks()
        have_counters = True
    except Exception:
        have_counters = False
    tele = telemetry.enable()
    telemetry.event(
        "accel_probe",
        fallback=device_fallback,
        cached=probe_was_cached,
        attempts=len([e for e in probe_evidence if "attempt" in e]),
    )
    # the fallback must never be silent: a 0/1 gauge in every sidecar
    # (greppable across rounds) plus a once-per-process stderr banner —
    # a CPU number filed as accelerator evidence poisons the ledger
    telemetry.gauge("device_fallback", 1.0 if device_fallback else 0.0)
    if device_fallback:
        telemetry.warn_once(
            "device_fallback",
            "accelerator probe/init failed — this bench run executes "
            "on the CPU backend; its numbers are NOT accelerator "
            "evidence (pass --require-accelerator to refuse instead)",
        )
        if args.require_accelerator:
            print(
                "bench: --require-accelerator set but the accelerator "
                "backend is unavailable (probe fallback); refusing to "
                "benchmark on CPU",
                file=sys.stderr,
            )
            return 2

    if device_fallback:
        # The env may pin JAX_PLATFORMS to an accelerator plugin from
        # sitecustomize before this process's code runs; the config
        # override below is the only reliable escape hatch (see
        # tests/conftest.py for the same pattern).
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    try:  # persistent cache: repeat driver runs skip recompilation
        cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    def _scope_cache_for_backend(platform: str) -> None:
        """CPU executables are machine-specific: scope the cache by the
        host's CPU features so this run never loads AOT code compiled
        on (or tuned for) another host — the loader only WARNS on a
        machine-type mismatch ('... could lead to execution errors
        such as SIGILL') and mismatched codegen silently skews
        timings, a round-3 spread candidate. Keyed on the CLAIMED
        backend (not the probe-fallback flag), so probe-disabled runs
        on CPU-only hosts scope too; the TPU path keeps the shared
        dir — its kernels target the chip, not the host. Called after
        the device claim and before the first compile (warm-up)."""
        if platform != "cpu":
            # any accelerator's executables target the chip, not the
            # host CPU — scoping them by host-CPU features would only
            # fragment a shareable cache into spurious cold compiles
            return
        try:
            jax.config.update(
                "jax_compilation_cache_dir",
                os.path.join(
                    cache_dir, "cpu-" + telemetry.cpu_features_hash()
                ),
            )
        except Exception:
            pass

    from pluss_sampler_optimization_tpu import MachineConfig, SamplerConfig
    from pluss_sampler_optimization_tpu.models import REGISTRY
    from pluss_sampler_optimization_tpu.runtime.aet import aet_mrc, mrc_l1_error
    from pluss_sampler_optimization_tpu.runtime.cri import cri_distribute
    from pluss_sampler_optimization_tpu.sampler.sampled import (
        run_sampled,
        warmup,
    )

    machine = MachineConfig()
    # validate every model name BEFORE the (possibly hour-long) runs —
    # a typo in --second-model must not discard the headline metric
    for name in filter(
        None, (args.model, args.second_model, args.exact_model)
    ):
        if name not in REGISTRY:
            raise SystemExit(
                f"unknown model {name!r} "
                f"(known: {', '.join(sorted(REGISTRY))})"
            )
    prog = REGISTRY[args.model](args.n)
    cfg_kw = {}
    if args.kernel_backend is not None:
        cfg_kw["kernel_backend"] = args.kernel_backend
    cfg = SamplerConfig(ratio=args.ratio, seed=args.seed, **cfg_kw)

    def timed_engine_run():
        """One timed run; returns (state, work units for the rate)."""
        if args.engine == "sampled":
            state, results = run_sampled(prog, machine, cfg)
            return state, sum(r.n_samples for r in results)
        if args.engine == "dense":
            from pluss_sampler_optimization_tpu.sampler.dense import run_dense

            res = run_dense(prog, machine)
            return res.state, res.total_accesses
        if args.engine == "periodic":
            from pluss_sampler_optimization_tpu.sampler.periodic import (
                run_periodic,
            )

            res = run_periodic(prog, machine)
            return res.state, res.total_accesses
        if args.engine == "analytic":
            from pluss_sampler_optimization_tpu.sampler.analytic import (
                run_analytic,
            )

            res = run_analytic(prog, machine)
            return res.state, res.total_accesses
        if args.engine == "exact":
            from pluss_sampler_optimization_tpu.sampler.periodic import (
                run_exact,
            )

            res = run_exact(prog, machine)
            return res.state, res.total_accesses
        from pluss_sampler_optimization_tpu.sampler.stream import run_stream

        res = run_stream(prog, machine, chunk_m=args.chunk_m)
        return res.state, res.total_accesses

    # First backend touches: device claim + warm-up compile of every
    # kernel at the run's batch shapes. Both can hang on a half-dead
    # tunnel even after a probe pass (a compile service once failed 25
    # minutes into warm-up), so on the accelerator path both run under
    # a watchdog with its own --warmup-timeout budget: a cold compile
    # cache needs ~10-15 min of legitimately slow remote compiles,
    # which the probe budget must not conflate with a hang (0 =
    # disable the watchdog, symmetric with --device-timeout 0).
    stamps: dict = {}
    t0 = time.perf_counter()

    def first_touch():
        with telemetry.span("backend_init"):
            stamps["dev"] = jax.devices()[0]
        stamps["init_s"] = time.perf_counter() - t0
        _scope_cache_for_backend(str(stamps["dev"].platform))
        t1 = time.perf_counter()
        with telemetry.span("warmup", engine=args.engine):
            if args.engine == "sampled":
                warmup(prog, machine, cfg)
            else:
                timed_engine_run()
        stamps["warmup_s"] = time.perf_counter() - t1
        if have_counters:
            stamps["warmup_compiles"] = (
                telemetry.compile_counters_snapshot()
            )

    if (
        not device_fallback
        and args.device_timeout > 0
        and args.warmup_timeout > 0
    ):
        guarded_backend_init(
            first_touch,
            args.warmup_timeout,
            probe_was_cached=probe_was_cached,
            spent_fn=lambda: (
                time.monotonic() - t_process_start + args.extras_spent
            ),
        )
    else:
        first_touch()
    dev = stamps["dev"]
    init_s = stamps["init_s"]
    warmup_s = stamps["warmup_s"]
    if args.require_accelerator and str(dev.platform) == "cpu":
        # probe passed (or was disabled) but the claimed device is
        # still CPU — e.g. a CPU-only host with --device-timeout 0
        print(
            "bench: --require-accelerator set but the claimed device "
            f"is {dev.platform}; refusing to benchmark on CPU",
            file=sys.stderr,
        )
        return 2

    times = []
    rep_stats = []
    throttle0 = telemetry.read_cpu_throttle()
    for rep_i in range(max(1, args.reps)):
        t0 = time.perf_counter()
        c0 = time.process_time()
        with telemetry.span("rep", i=rep_i, engine=args.engine):
            state, work = timed_engine_run()
        w = time.perf_counter() - t0
        c = time.process_time() - c0
        times.append(w)
        # cpu/wall per rep: on a contended host wall inflates while
        # process CPU stays put, so a low ratio (vs the quiet-host
        # ratio) self-identifies a load-skewed measurement — the
        # round-2 driver/judge 98s-vs-54s spread was invisible without
        # this
        rep_stats.append({
            "wall_s": round(w, 4), "cpu_s": round(c, 4),
            "cpu_wall": round(c / w, 2) if w > 0 else None,
        })
    # read immediately after the reps loop: the fingerprint's CPU speed
    # probe below would otherwise add its own throttle events to a
    # delta meant to characterize only the timed rep window
    throttle1 = telemetry.read_cpu_throttle()
    t_tpu = sorted(times)[len(times) // 2]  # median

    unit_name = "samples" if args.engine == "sampled" else "accesses"
    extra = {
        "model": args.model,
        "n": args.n,
        "engine": args.engine,
        "ratio": args.ratio if args.engine == "sampled" else None,
        "device": str(dev.platform),
        unit_name: work,
        "engine_s_median": round(t_tpu, 4),
        "engine_s_all": [round(t, 4) for t in times],
        "rep_cpu_wall": rep_stats,
        "device_init_s": round(init_s, 2),
        "warmup_s": round(warmup_s, 2),
        # load conditions, so throughput claims are reproducible
        "cpus": os.cpu_count(),
        "loadavg_1m": round(os.getloadavg()[0], 2),
        # host identity + measured speed: a slow-but-quiet run (cpu_wall
        # ~1.0 yet high wall time) self-identifies as a slower/other
        # host via cpu_model/boot_id/speed_probe_s instead of leaving
        # an unexplained spread (round-3 weak point 1)
        "host": telemetry.host_fingerprint(speed_probe=True),
    }
    if have_counters:
        # cold vs warm .jax_cache state, split at the warm-up boundary
        cc_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
        extra["compile_cache"] = {
            "dir": os.path.relpath(
                cc_dir, os.path.dirname(os.path.abspath(__file__))
            ) if cc_dir else "unset",
            "warmup": stamps.get("warmup_compiles"),
            "total": telemetry.compile_counters_snapshot(),
        }
    if throttle0 is not None and throttle1 is not None:
        extra["cgroup_throttle_delta"] = {
            k: throttle1[k] - throttle0[k] for k in throttle1
        }
    if str(dev.platform) == "tpu":
        extra["hist_kernel"] = _bench_hist_kernel_on_device()

    if device_fallback:
        if args.accel_hang_fallback:
            extra["device_fallback"] = probe_evidence[0]["accel_hang"]
        else:
            attempts = [e for e in probe_evidence if "attempt" in e]
            probe_s = sum(e.get("seconds", 0.0) for e in attempts)
            extra["device_fallback"] = (
                f"accelerator backend did not initialize within "
                f"{args.device_timeout:.0f}s across {len(attempts)} "
                f"attempts (total probe {probe_s:.0f}s); ran on CPU"
            )
        extra["probe"] = probe_evidence

    # baseline: native C++ serial full traversal, single core. The
    # north-star config (N=4096) takes ~1 h serially, so a recorded
    # run (tools/make_baseline.py -> baselines/) is preferred; absent
    # that, measure live.
    serial_cache: dict = {}  # (model, n) -> (t_cpp, state, acc, how)

    def _serial_baseline(model, n, sprog, out):
        """Recorded (preferred) or live-measured serial oracle for one
        config, cached so the headline score and the periodic_exact
        row never pay for (or re-run) the same serial measurement and
        MRC twice."""
        key = (model, n)
        if key in serial_cache:
            t_cpp, base_state, acc, how = serial_cache[key]
            out["serial_accesses"] = acc
            out[how] = round(t_cpp, 4)
            return t_cpp, base_state
        from pluss_sampler_optimization_tpu.runtime.baseline import (
            load_baseline,
        )

        try:
            stored = load_baseline(model, n, machine)
        except Exception as e:  # corrupt: fall back to live measure
            stored = None
            out["baseline_load_error"] = repr(e)
        if stored is not None:
            t_cpp = float(stored["serial_seconds"])
            base_state = stored["state"]
            acc, how = int(stored["total_accesses"]), "serial_cpp_s_recorded"
        else:
            from pluss_sampler_optimization_tpu import native
            from pluss_sampler_optimization_tpu.runtime.timing import (
                flush_cache,
            )

            flush_cache()
            t0 = time.perf_counter()
            # generous share capacity up front: an undersized buffer
            # silently regrows and RE-WALKS inside this timed window,
            # doubling the reported serial time (triangular nests need
            # ~1e5-1e6 pairs; 1<<20 covers every recorded config)
            base = native.run_serial_native(
                sprog, machine, share_cap=1 << 20
            )
            t_cpp = time.perf_counter() - t0
            base_state = base.state
            acc, how = base.total_accesses, "serial_cpp_s"
        serial_cache[key] = (t_cpp, base_state, acc, how)
        out["serial_accesses"] = acc
        out[how] = round(t_cpp, 4)
        return t_cpp, base_state

    mrc_cache: dict = {}  # (model, n) -> serial MRC

    def score_vs_serial(model, n, sprog, engine_state, engine_s, out):
        """Score one engine run against the serial oracle into `out`:
        serial wall time, accesses, the speedup, and the MRC L1 error;
        records load errors instead of hiding them. Returns the
        speedup (0.0 when the toolchain is absent)."""
        try:
            t_cpp, base_state = _serial_baseline(model, n, sprog, out)
            T = machine.thread_num
            mrc_engine = aet_mrc(cri_distribute(engine_state, T, T), machine)
            if (model, n) not in mrc_cache:
                mrc_cache[(model, n)] = aet_mrc(
                    cri_distribute(base_state, T, T), machine
                )
            out["mrc_l1_err"] = round(
                mrc_l1_error(mrc_engine, mrc_cache[(model, n)]), 6
            )
            # the run ledger keys accuracy on this digest; identical
            # engine output digests identically across rounds
            from pluss_sampler_optimization_tpu.runtime.obs import (
                ledger as obs_ledger,
            )

            out["mrc_digest"] = obs_ledger.mrc_digest(mrc_engine)
            return t_cpp / engine_s
        except RuntimeError as e:  # no toolchain: throughput only
            out["baseline_error"] = str(e)
            return 0.0

    vs_baseline = 0.0
    if args.skip_baseline:
        extra["baseline_skipped"] = True
    else:
        vs_baseline = score_vs_serial(
            args.model, args.n, prog, state, t_tpu, extra
        )

    # Exact-path secondary row: when the headline engine is sampled
    # and the model passes the periodic engine's preconditions, time
    # one exact full-traversal run against the same serial baseline —
    # the round-3 exact path is within ~1.4x of the 10%-sampled run at
    # the north-star config with zero approximation error, and the
    # driver's JSON should carry that evidence.
    if (
        args.engine == "sampled"
        and not args.skip_baseline
        and extras_budget_left("periodic_exact", extra)
    ):
        px: dict = {}
        extra["periodic_exact"] = px  # filled in place: a later
        # scoring error must not discard the measured run
        try:
            from pluss_sampler_optimization_tpu.sampler.periodic import (
                run_exact,
                validate_periodic,
            )

            # the full exact router (periodic -> analytic -> dense), so
            # models the periodic engine rejects (triangular nests,
            # mixed parallel coefficients) still get an exact secondary
            # row instead of an "inapplicable" note. The guard below
            # pre-routes ONLY to refuse the sort-bound dense fallback
            # at large N (it would blow the extras budget mid-run);
            # this warms the host-side gates/trace caches, but the
            # device kernel compiles remain inside the timed run.
            if args.n > 512:
                try:
                    validate_periodic(prog, machine)
                except NotImplementedError:
                    from pluss_sampler_optimization_tpu.sampler import (
                        analytic,
                    )

                    analytic.validate_analytic(prog, machine)
                    # raises NotImplementedError -> "inapplicable" when
                    # dense would be the route
            # One cold run: evaluating the windows IS the bulk of the
            # cost, so a separate warm-up would double the added wall
            # time for a second-order metric. BASELINE.md records the
            # warm medians; this row's time includes jit compile (and,
            # above N=512, cache-warm validation) and is labeled as
            # such. px["engine"] records the router's choice.
            t0 = time.perf_counter()
            c0 = time.process_time()
            pres = run_exact(prog, machine)
            pw = time.perf_counter() - t0
            pc = time.process_time() - c0
            px["engine"] = pres.engine
            px["engine_s_incl_compile"] = round(pw, 4)
            px["cpu_wall"] = round(pc / pw, 2) if pw > 0 else None
            px["accesses"] = pres.total_accesses
            # mrc_l1_err lands from score_vs_serial; the engines are
            # bit-exact so it must come back 0.0
            px["vs_baseline"] = round(
                score_vs_serial(
                    args.model, args.n, prog, pres.state, pw, px
                ), 2,
            )
        except NotImplementedError as e:
            px["inapplicable"] = str(e)[:160]
        except Exception as e:  # never sink the headline metric
            px["error"] = repr(e)

    # Analytic-router secondary row: one periodic-REJECTED model
    # through the exact router, so the driver artifact itself carries
    # an `"engine": "analytic"` row with a vs-serial score (round 5
    # shipped the engine but its evidence lived only in BASELINE.md —
    # VERDICT weak #3 / next-round #5). Separate from the
    # periodic_exact row above, which runs the router on the HEADLINE
    # model (periodic for gemm).
    if (
        args.engine == "sampled"
        and not args.skip_baseline
        and args.exact_model
        and extras_budget_left("analytic_exact", extra)
    ):
        ax: dict = {"model": args.exact_model, "n": args.exact_n}
        extra["analytic_exact"] = ax  # filled in place: a later
        # scoring error must not discard the measured run
        try:
            from pluss_sampler_optimization_tpu.sampler.periodic import (
                run_exact,
            )

            aprog = REGISTRY[args.exact_model](args.exact_n)
            t0 = time.perf_counter()
            c0 = time.process_time()
            ares = run_exact(aprog, machine)
            aw = time.perf_counter() - t0
            ac = time.process_time() - c0
            ax["engine"] = ares.engine
            ax["engine_s_incl_compile"] = round(aw, 4)
            ax["cpu_wall"] = round(ac / aw, 2) if aw > 0 else None
            ax["accesses"] = ares.total_accesses
            # mrc_l1_err lands from score_vs_serial; exact engines are
            # bit-exact so it must come back 0.0
            ax["vs_baseline"] = round(
                score_vs_serial(
                    args.exact_model, args.exact_n, aprog, ares.state,
                    aw, ax,
                ), 2,
            )
        except NotImplementedError as e:
            ax["inapplicable"] = str(e)[:160]
        except Exception as e:  # never sink the headline metric
            ax["error"] = repr(e)

    # Second model, sampled engine vs the serial oracle: evidence that
    # the IR-generic engine's throughput story is not GEMM-specific.
    if args.second_model and extras_budget_left("second_model", extra):
        sprog = REGISTRY[args.second_model](args.second_n)
        try:
            warmup(sprog, machine, cfg)
            t0 = time.perf_counter()
            sstate, sresults = run_sampled(sprog, machine, cfg)
            t_s = time.perf_counter() - t0
            sm = {
                "model": args.second_model,
                "n": args.second_n,
                "samples": sum(r.n_samples for r in sresults),
                "sampled_s": round(t_s, 4),
            }
            sm["vs_baseline"] = round(
                score_vs_serial(
                    args.second_model, args.second_n, sprog, sstate, t_s, sm
                ), 2,
            )
            extra["second_model"] = sm
        except Exception as e:  # the headline metric must still print
            extra["second_model_error"] = repr(e)

    # Cross-ref fused dispatch: wall time + dispatch count, fused vs
    # unfused, same sampled config — the measured evidence behind the
    # --fuse-refs default, with bit-identity asserted on the per-ref
    # results (the fusion contract). Bounded at N<=1024 so the extra
    # never rivals the headline run.
    if extras_budget_left("ref_fusion", extra):
        rf: dict = {}
        extra["ref_fusion"] = rf
        try:
            import dataclasses as _dc

            n_rf = min(args.n, 1024)
            fprog = (prog if n_rf == args.n
                     else REGISTRY[args.model](n_rf))
            rf.update({"model": args.model, "n": n_rf})
            fused_results: dict = {}
            for label, fuse in (("fused", True), ("unfused", False)):
                # kernel_backend pinned: this extra isolates the
                # fusion axis, and the per-ref RESULT comparison below
                # needs both legs on the same kernel representation
                # (auto resolves the unfused CPU leg to native, whose
                # per-ref noshare keys are ladder-binned — same folded
                # state, different raw result objects)
                fcfg = _dc.replace(
                    cfg, fuse_refs=fuse, kernel_backend="xla"
                )
                warmup(fprog, machine, fcfg)
                d0 = tele.counters.get("dispatches", 0)
                t0 = time.perf_counter()
                _fstate, fres = run_sampled(fprog, machine, fcfg)
                dt = time.perf_counter() - t0
                fused_results[label] = fres
                rf[label] = {
                    "wall_s": round(dt, 4),
                    "dispatches": int(
                        tele.counters.get("dispatches", 0) - d0
                    ),
                }
                if fuse:
                    rf[label]["ref_buckets"] = tele.gauges.get(
                        "ref_buckets"
                    )
                    rf[label]["expected_chunks"] = tele.gauges.get(
                        "expected_chunks"
                    )
                    rf[label]["refs_per_dispatch"] = tele.gauges.get(
                        "refs_per_dispatch"
                    )
            rf["bit_identical"] = (
                fused_results["fused"] == fused_results["unfused"]
            )
            rf["dispatch_ratio"] = round(
                rf["unfused"]["dispatches"]
                / max(1, rf["fused"]["dispatches"]), 2,
            )
            rf["speedup"] = round(
                rf["unfused"]["wall_s"]
                / max(1e-9, rf["fused"]["wall_s"]), 2,
            )
        except Exception as e:  # never sink the headline metric
            rf["error"] = repr(e)

    # Kernel roofline: the sampled hot loop (classify + histogram)
    # measured per kernel backend on the same config — wall split into
    # per-stage span seconds (draw/dispatch/fetch/merge), dispatch
    # deltas, modeled bytes/FLOPs for the classify traffic, and the
    # MRC digest so identity across backends is pinned in the same
    # evidence row as the speedup. The model-sized rows run the fused
    # XLA baseline and the native CPU fast path; interpret-mode pallas
    # cold-compiles one pallas_call per ref (~10-60s EACH on CPU), so
    # the model-sized pallas row only runs when --kernel-backend
    # pallas asks for it — the three-way digest identity is instead
    # pinned on a bounded 2-ref program below, every run.
    if extras_budget_left("kernel_roofline", extra):
        kr: dict = {}
        extra["kernel_roofline"] = kr
        try:
            import dataclasses as _dc

            from pluss_sampler_optimization_tpu.ir import (
                Loop,
                ParallelNest,
                Program,
                Ref,
            )
            from pluss_sampler_optimization_tpu.runtime.obs import (
                ledger as obs_ledger,
            )

            def _kr_digest(state):
                T = machine.thread_num
                return obs_ledger.mrc_digest(
                    aet_mrc(cri_distribute(state, T, T), machine)
                )

            _STAGES = ("draw", "dispatch", "fetch", "merge")

            def _kr_measure(kprog, kcfg, depth):
                """One warmed + one timed run: wall, per-stage span
                seconds, dispatch deltas, modeled traffic, digest."""
                run_sampled(kprog, machine, kcfg)  # warm: compile/build
                marks = {s: len(tele.find_spans(s)) for s in _STAGES}
                d0 = tele.counters.get("dispatches", 0)
                dn0 = tele.counters.get("dispatches_native", 0)
                t0 = time.perf_counter()
                kstate, kres = run_sampled(kprog, machine, kcfg)
                wall = time.perf_counter() - t0
                stage_s = {
                    s: round(sum(
                        sp.wall_s for sp in tele.find_spans(s)[marks[s]:]
                    ), 4)
                    for s in _STAGES
                }
                samples = sum(r.n_samples for r in kres)
                # modeled per-sample classify traffic: 8B key in, 8B
                # packed + 1B found out, ~8B amortized histogram
                # update; ~4 ops per decode level + ~16 classify ops.
                # Crude by design — it exists to place the measured
                # rates on a roofline, not to be a simulator.
                bytes_ = samples * 25
                ops = samples * (4 * depth + 16)
                return {
                    "wall_s": round(wall, 4),
                    "stage_s": stage_s,
                    # everything that is not drawing keys IS the hot
                    # loop (classify+reduce+merge, incl. dispatch
                    # overhead — the quantity the backends compete on)
                    "hot_loop_s": round(
                        max(1e-9, wall - stage_s["draw"]), 4
                    ),
                    "samples": samples,
                    "samples_per_s": (
                        round(samples / wall, 1) if wall > 0 else None
                    ),
                    "dispatches": int(
                        tele.counters.get("dispatches", 0) - d0
                    ),
                    "dispatches_native": int(
                        tele.counters.get("dispatches_native", 0) - dn0
                    ),
                    "modeled_bytes": int(bytes_),
                    "modeled_flops": int(ops),
                    "arith_intensity": round(ops / max(1, bytes_), 3),
                    "mrc_digest": _kr_digest(kstate),
                }

            n_kr = min(args.n, 512)
            kprog = (prog if n_kr == args.n
                     else REGISTRY[args.model](n_kr))
            kr_depth = max(len(nst.loops) for nst in kprog.nests)
            kr.update({"model": args.model, "n": n_kr,
                       "ratio": args.ratio})
            backends = ["xla", "native"]
            if args.kernel_backend == "pallas":
                backends.append("pallas")
            rows: dict = {}
            kr["backends"] = rows
            for b in backends:
                try:
                    kcfg = (
                        _dc.replace(cfg, kernel_backend="xla",
                                    fuse_refs=True)
                        if b == "xla"  # the r05 fused-XLA baseline
                        else _dc.replace(cfg, kernel_backend=b)
                    )
                    rows[b] = _kr_measure(kprog, kcfg, kr_depth)
                except Exception as e:
                    rows[b] = {"error": repr(e)}
            ok_rows = {b: r for b, r in rows.items()
                       if "hot_loop_s" in r}
            if "xla" in ok_rows:
                base_s = ok_rows["xla"]["hot_loop_s"]
                for b, r in ok_rows.items():
                    if b != "xla":
                        r["hot_loop_speedup_vs_xla"] = round(
                            base_s / r["hot_loop_s"], 2
                        )
            kr["digests_identical"] = len(
                {r["mrc_digest"] for r in ok_rows.values()}
            ) <= 1
            # three-way digest identity (xla vs pallas vs native) on a
            # bounded 2-ref program: one pallas_call to cold-compile,
            # so the parity pin costs seconds, not the minutes a full
            # model would
            mini = Program(name="roofline-mini", nests=(ParallelNest(
                loops=(Loop(8), Loop(8)),
                refs=(
                    Ref("A0", "A", level=1, coeffs=(8, 1)),
                    Ref("B0", "B", level=1, coeffs=(0, 1),
                        share_threshold=9),
                ),
            ),))
            digs = {}
            for b in ("xla", "pallas", "native"):
                mstate, _mres = run_sampled(
                    mini, machine, _dc.replace(cfg, kernel_backend=b)
                )
                digs[b] = _kr_digest(mstate)
            kr["digest_parity"] = {
                "model": "roofline-mini", "n": 8, "digests": digs,
                "identical": len(set(digs.values())) == 1,
            }
        except Exception as e:  # never sink the headline metric
            kr["error"] = repr(e)

    # Request-serving latency: the analysis service's cold-vs-warm
    # story measured on this host — one small exact request cold (the
    # engine executes and the result lands in a content-addressed
    # store), then warm from the same service (memory tier), then warm
    # from a FRESH service instance (disk tier). The warm/cold ratio
    # is the driver-visible evidence for `--cache-dir` serving
    # (README "Serving"); warm repeats perform zero engine work.
    if extras_budget_left("service_cache", extra):
        sc: dict = {}
        extra["service_cache"] = sc
        try:
            import shutil
            import tempfile

            from pluss_sampler_optimization_tpu.service import (
                AnalysisRequest,
                AnalysisService,
            )

            svc_dir = tempfile.mkdtemp(prefix="bench_service_cache_")
            try:
                req = AnalysisRequest(
                    model=args.model, n=min(args.n, 128),
                    engine="exact",
                )
                with AnalysisService(cache_dir=svc_dir) as svc:
                    t0 = time.perf_counter()
                    cold = svc.analyze(req)
                    cold_s = time.perf_counter() - t0
                    t0 = time.perf_counter()
                    warm = svc.analyze(req)
                    warm_s = time.perf_counter() - t0
                with AnalysisService(cache_dir=svc_dir) as svc2:
                    t0 = time.perf_counter()
                    disk = svc2.analyze(req)
                    disk_s = time.perf_counter() - t0
                sc.update({
                    "model": req.model,
                    "n": req.n,
                    "engine_used": cold.engine_used,
                    "cold_s": round(cold_s, 4),
                    "warm_mem_s": round(warm_s, 6),
                    "warm_disk_s": round(disk_s, 6),
                    # tier labels double as correctness evidence: the
                    # run is useless if the "warm" requests missed
                    "cold_cache": cold.cache,
                    "warm_mem_cache": warm.cache,
                    "warm_disk_cache": disk.cache,
                    "warm_speedup": (
                        round(cold_s / warm_s, 1) if warm_s > 0
                        else None
                    ),
                })
            finally:
                shutil.rmtree(svc_dir, ignore_errors=True)
        except Exception as e:  # never sink the headline metric
            sc["error"] = repr(e)

    # Cross-request continuous batching: K=4 concurrent DISTINCT
    # sampled requests served twice from cold caches — without a batch
    # window (K solo engine executions) and with one (requests merge
    # into a single union-bucket fused dispatch plan). Dispatch and
    # execution counts come from the live telemetry; bit-identity is
    # asserted on the per-request MRC digests (the batching contract:
    # a member's MRC must match its solo run byte for byte).
    if extras_budget_left("cross_request_batching", extra):
        cb: dict = {}
        extra["cross_request_batching"] = cb
        try:
            import shutil
            import tempfile

            from pluss_sampler_optimization_tpu.service import (
                AnalysisRequest,
                AnalysisService,
            )

            reqs = [
                AnalysisRequest(model="gemm", n=24, engine="sampled",
                                ratio=0.2, seed=11),
                AnalysisRequest(model="gemm", n=32, engine="sampled",
                                ratio=0.2, seed=12),
                AnalysisRequest(model="2mm", n=12, engine="sampled",
                                ratio=0.2, seed=13),
                AnalysisRequest(model="mvt", n=48, engine="sampled",
                                ratio=0.2, seed=14),
            ]
            cb["requests"] = [
                {"model": r.model, "n": r.n, "seed": r.seed}
                for r in reqs
            ]
            digests: dict = {}
            for label, window in (("unbatched", None),
                                  ("batched", 250.0)):
                svc_dir = tempfile.mkdtemp(
                    prefix=f"bench_batching_{label}_"
                )
                try:
                    d0 = tele.counters.get("dispatches", 0)
                    e0 = tele.counters.get("service_exec_started", 0)
                    b0 = tele.counters.get("batches_formed", 0)
                    m0 = tele.counters.get("batch_members", 0)
                    t0 = time.perf_counter()
                    with AnalysisService(
                        max_workers=4, cache_dir=svc_dir,
                        batch_window_ms=window,
                    ) as svc:
                        tickets = [svc.submit(r) for r in reqs]
                        resps = [svc.result(t, timeout=600)
                                 for t in tickets]
                    dt = time.perf_counter() - t0
                    digests[label] = [r.mrc_digest for r in resps]
                    cb[label] = {
                        "wall_s": round(dt, 4),
                        "dispatches": int(
                            tele.counters.get("dispatches", 0) - d0
                        ),
                        "executions": int(
                            tele.counters.get(
                                "service_exec_started", 0
                            ) - e0
                        ),
                        "ok": all(r.ok for r in resps),
                    }
                    if window is not None:
                        cb[label]["batch_window_ms"] = window
                        cb[label]["batches_formed"] = int(
                            tele.counters.get("batches_formed", 0)
                            - b0
                        )
                        cb[label]["batch_members"] = int(
                            tele.counters.get("batch_members", 0)
                            - m0
                        )
                        cb[label]["ref_buckets_union"] = (
                            tele.gauges.get("ref_buckets_union")
                        )
                finally:
                    shutil.rmtree(svc_dir, ignore_errors=True)
            # the acceptance evidence: K merged requests must cost
            # strictly fewer dispatches than K solo runs, with every
            # member's MRC digest unchanged
            cb["bit_identical"] = (
                digests["unbatched"] == digests["batched"]
            )
            cb["dispatch_delta"] = (
                cb["unbatched"]["dispatches"]
                - cb["batched"]["dispatches"]
            )
            cb["speedup"] = round(
                cb["unbatched"]["wall_s"]
                / max(1e-9, cb["batched"]["wall_s"]), 2,
            )
        except Exception as e:  # never sink the headline metric
            cb["error"] = repr(e)

    # Replica-pool scaling: K=4 concurrent DISTINCT sampled requests
    # (batching off, so each is one engine execution) served from cold
    # caches under three configurations — no pool (the PR 9 baseline),
    # replicas=1 (pool routing overhead must stay <5% of baseline),
    # and replicas=4 (concurrent requests spread across device
    # groups). Bit-identity is asserted on the per-request MRC digests
    # across all three: replica count is a pure perf knob.
    if extras_budget_left("replica_scaling", extra):
        rs: dict = {}
        extra["replica_scaling"] = rs
        try:
            rs.update(replica_scaling_extra())
        except Exception as e:  # never sink the headline metric
            rs["error"] = repr(e)

    # Admission-controlled load shedding: the same deterministic
    # open-loop arrival sequence at ~3x capacity with the admission
    # gate on vs off. Shed-on must hold p95 (bounded queue) at the
    # cost of structured shed responses; shed-off serves everything
    # but its p95 collapses — both outcomes ship in the evidence
    # sidecar as the overload acceptance record.
    if extras_budget_left("overload_shedding", extra):
        ov: dict = {}
        extra["overload_shedding"] = ov
        try:
            ov.update(overload_shedding_extra())
        except Exception as e:  # never sink the headline metric
            ov["error"] = repr(e)

    # Progressive precision: samples-to-tolerance vs the static
    # full-ratio cost. One-shot baseline, the full progressive
    # schedule (digest parity = the bit-identity claim in the
    # evidence sidecar), and a tolerance-stopped run recording how
    # many samples the confidence-band early exit saved.
    if extras_budget_left("progressive_precision", extra):
        pp: dict = {}
        extra["progressive_precision"] = pp
        try:
            pp.update(progressive_precision_extra())
        except Exception as e:  # never sink the headline metric
            pp["error"] = repr(e)

    # Live-metrics registry overhead: the serve path enables the
    # rolling registry unconditionally, so its cost on the hot engine
    # path is a standing claim — median of 3 hot reps with the registry
    # disabled vs enabled (every telemetry.count/gauge mirrored into
    # rolling windows) must stay within a 2% wall budget. Also smokes
    # the SLO sentinel over the accumulated bench ledger.
    if extras_budget_left("slo_sentinel", extra):
        so: dict = {}
        extra["slo_sentinel"] = so
        try:
            from pluss_sampler_optimization_tpu.runtime.obs import (
                metrics as obs_metrics,
            )

            def med3():
                ts = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    timed_engine_run()
                    ts.append(time.perf_counter() - t0)
                return sorted(ts)[1]

            timed_engine_run()  # re-warm after the service extras
            off_s = med3()
            obs_metrics.enable()
            try:
                on_s = med3()
            finally:
                obs_metrics.disable()
            overhead_pct = round(100.0 * (on_s - off_s) / off_s, 2)
            so["registry_overhead"] = {
                "engine": args.engine,
                "disabled_s": round(off_s, 4),
                "enabled_s": round(on_s, 4),
                "overhead_pct": overhead_pct,
                "within_budget": overhead_pct < 2.0,
                "budget_pct": 2.0,
            }
            if args.ledger:
                lp = os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    args.ledger,
                )
                if os.path.isfile(lp):
                    from pluss_sampler_optimization_tpu.runtime.obs import (
                        ledger as obs_ledger,
                        slo as obs_slo,
                    )

                    report = obs_slo.evaluate(
                        rows=obs_ledger.read_rows(lp)
                    )
                    so["ledger_slo"] = {
                        "ok": report["ok"],
                        "checks": [c["name"]
                                   for c in report["checks"]],
                    }
        except Exception as e:  # never sink the headline metric
            so["error"] = repr(e)

    # Flight-recorder overhead on the hot engine path: the recorder
    # hooks the telemetry event sink and (in serve mode) ingests one
    # record per request, so "observation only" is a measurable
    # claim — median-of-3 engine wall with the recorder installed vs
    # not, pinned under the same 2% budget as the registry overhead.
    if extras_budget_left("flight_recorder", extra):
        fr: dict = {}
        extra["flight_recorder"] = fr
        try:
            import shutil
            import tempfile

            from pluss_sampler_optimization_tpu.runtime.obs import (
                recorder as obs_recorder,
            )

            def med3_fr():
                ts = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    timed_engine_run()
                    ts.append(time.perf_counter() - t0)
                return sorted(ts)[1]

            timed_engine_run()  # re-warm after the preceding extras
            off_s = med3_fr()
            bundle_dir = tempfile.mkdtemp(prefix="pluss_bundles_")
            rec = obs_recorder.enable(bundle_dir)
            try:
                on_s = med3_fr()
                rec_stats = rec.stats()
            finally:
                obs_recorder.disable()
                shutil.rmtree(bundle_dir, ignore_errors=True)
            overhead_pct = round(100.0 * (on_s - off_s) / off_s, 2)
            fr["recorder_overhead"] = {
                "engine": args.engine,
                "disabled_s": round(off_s, 4),
                "enabled_s": round(on_s, 4),
                "overhead_pct": overhead_pct,
                "within_budget": overhead_pct < 2.0,
                "budget_pct": 2.0,
                # the hot path must not spuriously trigger: no
                # bundles may appear during clean engine runs
                "bundles_written": rec_stats["bundles_written"],
            }
        except Exception as e:  # never sink the headline metric
            fr["error"] = repr(e)

    # Where the wall time goes: the headline plateau is diagnosable
    # only if the evidence says which stage ate the wall. Re-run the
    # hot engine path under the sampling wall-clock profiler
    # (runtime/obs/profiler.py) and attribute every sample to its
    # telemetry span path: per-stage fractions (executing / sync /
    # queue / unattributed, summing to ~1.0 by construction) plus the
    # top-k attributed stacks, so a reader can tell interpreter
    # overhead from device-sync stalls without reproducing the run.
    if extras_budget_left("where_time_goes", extra):
        wt: dict = {}
        extra["where_time_goes"] = wt
        try:
            from pluss_sampler_optimization_tpu.runtime.obs import (
                attribution as obs_attribution,
                profiler as obs_profiler,
            )

            timed_engine_run()  # re-warm after the preceding extras
            prof = obs_profiler.enable(hz=250.0)
            try:
                t0 = time.perf_counter()
                reps_done = 0
                # enough reps for a statistically useful sample count
                # on fast configs, bounded so slow ones stay cheap
                while reps_done < 3 or (
                    time.perf_counter() - t0 < 0.5 and reps_done < 50
                ):
                    with telemetry.span("rep", engine=args.engine):
                        timed_engine_run()
                    reps_done += 1
            finally:
                obs_profiler.disable()
            snap = prof.snapshot()
            br = obs_attribution.sample_breakdown(snap)
            wt.update({
                "engine": args.engine,
                "hz": snap["hz"],
                "reps": reps_done,
                "samples": snap["samples"],
                "attribution_completeness":
                    snap["attribution_completeness"],
                "breakdown": br,
                "top_stacks": [
                    {
                        "span": s["span"],
                        "count": s["count"],
                        "seconds": s["seconds"],
                        "leaf": s["frames"][-1] if s["frames"]
                        else None,
                    }
                    for s in snap["stacks"][:10]
                ],
            })
        except Exception as e:  # never sink the headline metric
            wt["error"] = repr(e)

    # Lockdep-witness overhead on the serving path: the witness wraps
    # every service lock when armed, so "pure observer" is a
    # measurable claim — served wall witness-on vs off under the same
    # 2% budget, plus digest identity and zero inversions.
    if extras_budget_left("lock_witness", extra):
        lw: dict = {}
        extra["lock_witness"] = lw
        try:
            lw.update(lock_witness_extra())
        except Exception as e:  # never sink the headline metric
            lw["error"] = repr(e)

    # Static-analyzer (analysis/) wall time per registry model: the
    # preflight gate runs on EVERY service submission, so its cost is
    # a standing serving claim — the evidence records per-model
    # analyzer wall (validation + dependence tests + bounds) and the
    # verdict, at the bench model's size for the bench model and a
    # small reference size for the rest of the registry.
    if extras_budget_left("ir_preflight", extra):
        ip: dict = {}
        extra["ir_preflight"] = ip
        try:
            from pluss_sampler_optimization_tpu import analysis
            from pluss_sampler_optimization_tpu.models import (
                REGISTRY,
            )
            from pluss_sampler_optimization_tpu.models import (
                build as build_model,
            )

            per_model: dict = {}
            for name in sorted(REGISTRY):
                bn = args.n if name == args.model else 24
                rep = analysis.analyze_program(
                    build_model(name, bn), machine
                )
                per_model[name] = {
                    "n": bn,
                    "verdict": rep.verdict,
                    "races": len(rep.races),
                    "wall_ms": round(rep.wall_s * 1e3, 3),
                }
            ip["models"] = per_model
            ip["total_wall_ms"] = round(
                sum(m["wall_ms"] for m in per_model.values()), 3
            )
        except Exception as e:  # never sink the headline metric
            ip["error"] = repr(e)

    # Program-frontend (frontend/) serving claim: parsing an inline
    # JSON document back into canonical IR must be noise next to the
    # request it fronts — the evidence records the parse+preflight
    # wall for the bench model's own dump as a fraction of the
    # headline request latency, plus a short generative-fuzz sweep
    # (the cheap contract: round-trip + exact-engine bit-identity +
    # mutant rejection; the sampled sweep is tools/fuzz_ir.py's job).
    if extras_budget_left("custom_frontend", extra):
        cf: dict = {}
        extra["custom_frontend"] = cf
        try:
            from pluss_sampler_optimization_tpu import analysis
            from pluss_sampler_optimization_tpu.frontend import (
                fuzz as frontend_fuzz,
            )
            from pluss_sampler_optimization_tpu.frontend import (
                parse_program,
                program_to_json,
            )
            from pluss_sampler_optimization_tpu.models import (
                build as build_model,
            )

            doc = program_to_json(build_model(args.model, args.n))
            # parse through JSON text, as a serve payload arrives
            text = json.dumps(doc)
            walls = []
            for _ in range(5):
                t0 = time.perf_counter()
                parsed = parse_program(json.loads(text))
                analysis.analyze_program(parsed, machine)
                walls.append(time.perf_counter() - t0)
            parse_ms = sorted(walls)[len(walls) // 2] * 1e3
            cf["parse_preflight_ms"] = round(parse_ms, 3)
            cf["headline_latency_s"] = round(t_tpu, 6)
            cf["overhead_frac"] = round(parse_ms / 1e3 / t_tpu, 5)
            sweep = frontend_fuzz.run_seeds(8, sampled=False)
            cf["fuzz_seeds_passed"] = (
                f"{sweep['passed']}/{sweep['seeds']}"
            )
            if sweep["failed"]:
                cf["fuzz_failures"] = sweep["failures"]
        except Exception as e:  # never sink the headline metric
            cf["error"] = repr(e)

    if have_counters and "compile_cache" in extra:
        # final snapshot: the extras (periodic_exact, second model) may
        # have compiled too; "total" must mean the whole process
        extra["compile_cache"]["total"] = (
            telemetry.compile_counters_snapshot()
        )

    metric = f"{args.model}{args.n}_{args.engine}_throughput"

    # run-ledger row: the longitudinal record across BENCH_r*.json
    # rounds — headline value, latency, and the MRC digest, appended
    # BEFORE emit_result so the evidence JSON can cross-reference the
    # ledger path (and a ledger failure never sinks the headline)
    if args.ledger:
        ledger_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), args.ledger
        )
        try:
            from pluss_sampler_optimization_tpu.runtime.obs import (
                ledger as obs_ledger,
            )

            obs_ledger.append(ledger_path, {
                "kind": "bench",
                "source": "bench",
                "ok": True,
                "metric": metric,
                "value": round(work / t_tpu, 1),
                "unit": f"{unit_name}/s/chip",
                "vs_baseline": round(vs_baseline, 2),
                "engine": args.engine,
                "model": args.model,
                "n": args.n,
                "latency_s": round(t_tpu, 6),
                "device": str(dev.platform),
                # rows from a probe-fallback run are self-identifying:
                # longitudinal consumers (cli stats, the SLO sentinel)
                # must never mistake a CPU number for device evidence
                "device_fallback": bool(device_fallback),
                "mrc_l1_err": extra.get("mrc_l1_err"),
                "mrc_digest": extra.get("mrc_digest"),
            })
            extra["ledger"] = args.ledger
        except Exception as e:
            extra["ledger_error"] = repr(e)

    # full telemetry record (span tree, counters, jax monitoring delta,
    # device/host metrics) as a stamped sidecar next to the evidence
    # files; the evidence JSON names it so the two cross-reference
    telemetry.disable()
    tele_name = _stamped_sidecar_name(metric, prefix="BENCH_TELEMETRY")
    tele_ref = os.path.join(BENCH_OUT_DIR, tele_name)
    try:
        script_dir = os.path.dirname(os.path.abspath(__file__))
        os.makedirs(os.path.join(script_dir, BENCH_OUT_DIR),
                    exist_ok=True)
        tele.write_json(os.path.join(script_dir, tele_ref))
        extra["telemetry"] = tele_ref
    except OSError:
        extra["telemetry"] = "unwritable"
    tele.print_summary()

    emit_result(
        {
            "metric": metric,
            "value": round(work / t_tpu, 1),
            "unit": f"{unit_name}/s/chip",
            "vs_baseline": round(vs_baseline, 2),
        },
        extra,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
