#!/usr/bin/env python
"""TPU benchmark: sampled engine vs the native serial C++ baseline.

Protocol (BASELINE.md): the reference's "speed" harness times sampler
wall clock (c_lib/test/Makefile:34-37); its sampled r10 variant is
measured against the serial full-traversal C++ sampler. Here:

- workload: GEMM N (default 4096, the north-star config), THREAD_NUM=4,
  CHUNK=4, DS=8, CLS=64
  — the reference machine model at scale;
- ours: the vectorized random-start sampled engine (ratio 10%) on the
  default JAX device (one TPU chip under the driver), timed after a
  compile warm-up;
- baseline: the native C++ serial full-traversal sampler
  (pluss_sampler_optimization_tpu/native), single core, same host —
  the reference's own accuracy/speed oracle re-implemented over the IR;
- accuracy: MRC L1 error between the sampled MRC and the serial MRC
  after the full CRI + AET pipeline on both.

Prints ONE JSON line:
  {"metric", "value" (samples/s/chip), "unit", "vs_baseline"
   (serial-seconds / sampled-seconds speedup), "extra" {...}}
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


_PROBE_MARKER = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".jax_cache", "accel_ok"
)
_PROBE_TTL_S = 3600.0


def probe_accelerator(timeout_s: float) -> tuple[bool, float]:
    """Check in a subprocess that the default JAX backend can COMPILE.

    The accelerator may sit behind a tunnel whose setup can stall
    indefinitely — and `jax.devices()` succeeding does not imply the
    compile service behind it is up (a dead remote-compile endpoint
    once failed 25 minutes into warm-up). So the probe runs a tiny
    jit end-to-end; a hang hits the subprocess timeout and the parent
    pins JAX_PLATFORMS=cpu before it ever imports jax. A successful
    probe is cached for an hour so healthy repeat runs skip the
    duplicate backend init. Returns (accelerator_ok, probe_seconds).
    """
    try:
        if time.time() - os.path.getmtime(_PROBE_MARKER) < _PROBE_TTL_S:
            return True, 0.0
    except OSError:
        pass
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp; "
             "jax.jit(lambda x: x @ x)(jnp.ones((128, 128)))"
             ".block_until_ready(); print('ok')"],
            timeout=timeout_s, capture_output=True, text=True,
        )
        ok = proc.returncode == 0 and "ok" in proc.stdout
    except subprocess.TimeoutExpired:
        ok = False
    if ok:
        try:
            os.makedirs(os.path.dirname(_PROBE_MARKER), exist_ok=True)
            with open(_PROBE_MARKER, "w"):
                pass
        except OSError:
            pass
    return ok, time.perf_counter() - t0


def main() -> int:
    ap = argparse.ArgumentParser()
    # default = the north-star config (BASELINE.json: GEMM N=4096);
    # its serial baseline ships recorded in baselines/
    ap.add_argument("--n", type=int, default=4096)
    ap.add_argument("--ratio", type=float, default=0.1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--device-timeout", type=float, default=240.0,
                    help="seconds to wait for the accelerator backend "
                    "before falling back to CPU (0 = trust it)")
    args = ap.parse_args()

    device_fallback = False
    probe_s = 0.0
    if args.device_timeout > 0:
        ok, probe_s = probe_accelerator(args.device_timeout)
        device_fallback = not ok

    import jax

    if device_fallback:
        # The env may pin JAX_PLATFORMS to an accelerator plugin from
        # sitecustomize before this process's code runs; the config
        # override below is the only reliable escape hatch (see
        # tests/conftest.py for the same pattern).
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    try:  # persistent cache: repeat driver runs skip recompilation
        cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    from pluss_sampler_optimization_tpu import MachineConfig, SamplerConfig
    from pluss_sampler_optimization_tpu.models.gemm import gemm
    from pluss_sampler_optimization_tpu.runtime.aet import aet_mrc, mrc_l1_error
    from pluss_sampler_optimization_tpu.runtime.cri import cri_distribute
    from pluss_sampler_optimization_tpu.sampler.sampled import (
        run_sampled,
        warmup,
    )

    machine = MachineConfig()
    prog = gemm(args.n)
    cfg = SamplerConfig(ratio=args.ratio, seed=args.seed)
    t0 = time.perf_counter()
    dev = jax.devices()[0]
    init_s = time.perf_counter() - t0

    # warm-up: compiles every per-ref kernel at the run's batch shapes
    t0 = time.perf_counter()
    warmup(prog, machine, cfg)
    warmup_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    state, results = run_sampled(prog, machine, cfg)
    t_tpu = time.perf_counter() - t0
    total_samples = sum(r.n_samples for r in results)

    extra = {
        "n": args.n,
        "ratio": args.ratio,
        "device": str(dev.platform),
        "samples": total_samples,
        "tpu_sampled_s": round(t_tpu, 4),
        "device_init_s": round(init_s, 2),
        "warmup_s": round(warmup_s, 2),
    }
    if device_fallback:
        extra["device_fallback"] = (
            f"accelerator backend did not initialize within "
            f"{args.device_timeout:.0f}s (probe {probe_s:.0f}s); ran on CPU"
        )

    # baseline: native C++ serial full traversal, single core. The
    # north-star config (N=4096) takes ~1 h serially, so a recorded
    # run (tools/make_baseline.py -> baselines/) is preferred; absent
    # that, measure live.
    vs_baseline = 0.0
    try:
        from pluss_sampler_optimization_tpu.runtime.baseline import (
            load_baseline,
        )

        try:
            stored = load_baseline("gemm", args.n, machine)
        except Exception as e:  # corrupt file: fall back to live measure
            stored = None
            extra["baseline_load_error"] = repr(e)
        if stored is not None:
            t_cpp = float(stored["serial_seconds"])
            base_state = stored["state"]
            extra["serial_accesses"] = int(stored["total_accesses"])
            extra["serial_cpp_s_recorded"] = round(t_cpp, 4)
        else:
            from pluss_sampler_optimization_tpu import native

            t0 = time.perf_counter()
            base = native.run_serial_native(prog, machine)
            t_cpp = time.perf_counter() - t0
            base_state = base.state
            extra["serial_accesses"] = base.total_accesses
            extra["serial_cpp_s"] = round(t_cpp, 4)
        vs_baseline = t_cpp / t_tpu

        T = machine.thread_num
        mrc_sampled = aet_mrc(cri_distribute(state, T, T), machine)
        mrc_serial = aet_mrc(cri_distribute(base_state, T, T), machine)
        extra["mrc_l1_err"] = round(mrc_l1_error(mrc_sampled, mrc_serial), 6)
    except RuntimeError as e:  # no toolchain: report throughput only
        extra["baseline_error"] = str(e)

    print(
        json.dumps(
            {
                "metric": f"gemm{args.n}_sampled_throughput",
                "value": round(total_samples / t_tpu, 1),
                "unit": "samples/s/chip",
                "vs_baseline": round(vs_baseline, 2),
                "extra": extra,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
