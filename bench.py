#!/usr/bin/env python
"""TPU benchmark: sampled engine vs the native serial C++ baseline.

Protocol (BASELINE.md): the reference's "speed" harness times sampler
wall clock (c_lib/test/Makefile:34-37); its sampled r10 variant is
measured against the serial full-traversal C++ sampler. Here:

- workload: GEMM N (default 1024), THREAD_NUM=4, CHUNK=4, DS=8, CLS=64
  — the reference machine model at scale;
- ours: the vectorized random-start sampled engine (ratio 10%) on the
  default JAX device (one TPU chip under the driver), timed after a
  compile warm-up;
- baseline: the native C++ serial full-traversal sampler
  (pluss_sampler_optimization_tpu/native), single core, same host —
  the reference's own accuracy/speed oracle re-implemented over the IR;
- accuracy: MRC L1 error between the sampled MRC and the serial MRC
  after the full CRI + AET pipeline on both.

Prints ONE JSON line:
  {"metric", "value" (samples/s/chip), "unit", "vs_baseline"
   (serial-seconds / sampled-seconds speedup), "extra" {...}}
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1024)
    ap.add_argument("--ratio", type=float, default=0.1)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    import jax

    from pluss_sampler_optimization_tpu import MachineConfig, SamplerConfig
    from pluss_sampler_optimization_tpu.models.gemm import gemm
    from pluss_sampler_optimization_tpu.runtime.aet import aet_mrc, mrc_l1_error
    from pluss_sampler_optimization_tpu.runtime.cri import cri_distribute
    from pluss_sampler_optimization_tpu.sampler.sampled import run_sampled

    machine = MachineConfig()
    prog = gemm(args.n)
    cfg = SamplerConfig(ratio=args.ratio, seed=args.seed)
    dev = jax.devices()[0]

    # warm-up: compiles every per-ref kernel at the run's batch shapes
    run_sampled(prog, machine, cfg)
    t0 = time.perf_counter()
    state, results = run_sampled(prog, machine, cfg)
    t_tpu = time.perf_counter() - t0
    total_samples = sum(r.n_samples for r in results)

    extra = {
        "n": args.n,
        "ratio": args.ratio,
        "device": str(dev.platform),
        "samples": total_samples,
        "tpu_sampled_s": round(t_tpu, 4),
    }

    # baseline: native C++ serial full traversal, single core
    vs_baseline = 0.0
    try:
        from pluss_sampler_optimization_tpu import native

        t0 = time.perf_counter()
        base = native.run_serial_native(prog, machine)
        t_cpp = time.perf_counter() - t0
        vs_baseline = t_cpp / t_tpu
        extra["serial_cpp_s"] = round(t_cpp, 4)
        extra["serial_accesses"] = base.total_accesses

        T = machine.thread_num
        mrc_sampled = aet_mrc(cri_distribute(state, T, T), machine)
        mrc_serial = aet_mrc(cri_distribute(base.state, T, T), machine)
        extra["mrc_l1_err"] = round(mrc_l1_error(mrc_sampled, mrc_serial), 6)
    except RuntimeError as e:  # no toolchain: report throughput only
        extra["baseline_error"] = str(e)

    print(
        json.dumps(
            {
                "metric": f"gemm{args.n}_sampled_throughput",
                "value": round(total_samples / t_tpu, 1),
                "unit": "samples/s/chip",
                "vs_baseline": round(vs_baseline, 2),
                "extra": extra,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
