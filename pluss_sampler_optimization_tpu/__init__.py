"""PLUSS-TPU: a TPU-native parallel-locality static-sampling framework.

A ground-up re-design of PLUSS (Parallel Locality analysis Using Static
Sampling; reference implementation: sauceeeeage/PLUSS_Sampler_Optimization)
for TPU hardware via JAX/XLA.

The reference simulates the interleaved execution of THREAD_NUM OpenMP
threads over a parallel loop nest, measures reuse intervals (RI) per
simulated thread, applies a concurrent-reuse-interval (CRI) probability
model, and integrates the result into an LRU miss-ratio curve (MRC).
Its execution engine is a serial (or modestly threaded) C++/Rust state
machine walk over the interleaved iteration space
(reference: c_lib/test/sampler/*.cpp, src/gemm_sampler*.rs).

This framework keeps the *model semantics* bit-exact but replaces the
execution engine with array programs:

- the per-simulated-thread access stream is a closed-form indexed
  sequence (core/trace.py), not a stateful walk;
- full-traversal RI measurement is a lexsort + segmented diff
  (sampler/dense.py), jit-compiled and vmapped over simulated threads;
- random-start sampling (the reference's `rs-ri-opt-r10` variant,
  c_lib/test/sampler/gemm-t4-pluss-pro-model-rs-ri-opt-r10.cpp) becomes a
  vmapped O(1)-per-sample closed-form next-use solver (sampler/sampled.py)
  instead of an amortized serial fast-forward walk;
- histogram reductions use dense pow2-binned vectors with
  `jax.lax.psum` across a device mesh (parallel/), replacing the
  reference's mutex / thread-local-merge reductions
  (src/unsafe_utils.rs:105-151, pluss_utils.cpp:4-14);
- the CRI model (negative-binomial spread + racetrack pow2 split,
  pluss_utils.h:987-1208) and AET->MRC integration (pluss_utils.h:758-804)
  run on host, consuming device-side histograms.

64-bit integers are required: per-thread trace positions exceed 2^31 for
N >= 2048 (a tid's trace has (N/T)*N*(4N+2) accesses for GEMM).
"""

import jax as _jax

_jax.config.update("jax_enable_x64", True)

from .config import MachineConfig, SamplerConfig  # noqa: E402
from .ir import Loop, Ref, ParallelNest, Program  # noqa: E402

__version__ = "0.1.0"

__all__ = [
    "MachineConfig",
    "SamplerConfig",
    "Loop",
    "Ref",
    "ParallelNest",
    "Program",
]
