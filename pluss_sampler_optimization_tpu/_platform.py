"""Virtual multi-device CPU platform pinning.

This environment's sitecustomize registers an experimental accelerator
PJRT plugin and pins JAX_PLATFORMS to it in every interpreter; its
client init can hang, and env-var overrides are too late once jax is
imported. Backend creation is lazy, though: overriding the
jax_platforms *config* before the first computation reliably selects
CPU, and XLA_FLAGS is read when the CPU client is created, which also
hasn't happened yet.

Single source of truth for the pinning recipe — used by both
tests/conftest.py and the driver's __graft_entry__.dryrun_multichip.
"""

from __future__ import annotations

import os
import re

_FLAG = "xla_force_host_platform_device_count"


def force_virtual_cpu(n_devices: int) -> None:
    """Pin jax to a virtual ``n_devices``-device CPU platform.

    Must run before any jax backend touch. Raises RuntimeError if a
    backend already exists on another platform or exposes fewer
    devices than requested (the caller would otherwise silently
    validate nothing).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    opt = f"--{_FLAG}={n_devices}"
    pat = re.compile(rf"--?{_FLAG}=\S*")
    if pat.search(flags):
        # A stale value (e.g. a smaller count from the outer env) must
        # be rewritten, not kept — the CPU client honours whatever
        # number is in the string when it comes up.
        os.environ["XLA_FLAGS"] = pat.sub(opt, flags)
    else:
        os.environ["XLA_FLAGS"] = (flags + " " + opt).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

    platform = jax.devices()[0].platform
    if platform != "cpu":
        raise RuntimeError(
            f"requested a virtual CPU mesh but jax is on platform "
            f"{platform!r}; a backend was initialized before "
            "force_virtual_cpu could pin the platform"
        )
    if jax.local_device_count() < n_devices:
        raise RuntimeError(
            f"virtual CPU mesh wants {n_devices} devices but jax sees "
            f"{jax.local_device_count()}; the CPU client was created "
            f"before force_virtual_cpu could set --{_FLAG}"
        )
