"""Static IR analysis: validation, dependence/race detection, bounds.

Three passes over `ir.Program` (pure numpy + stdlib — importable
without jax, so the CLI `analyze` mode and tools/check_ir.py stay
instant):

1. `validate` — structural well-formedness diagnostics (V_* codes).
2. `deps` — affine dependence classification and race flags (W_RACE).
3. `bounds` — cache-line footprints, compulsory-miss lower bound, and
   the MRC asymptote cross-checks.

`analyze_program` runs all three and folds them into one
`AnalysisReport`; `preflight` is the service-facing gate: it raises
`PreflightError` (diagnostics attached) for invalid IR and returns the
report — verdict "ok" or "race" — for everything simulable.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional

from ..config import MachineConfig
from .bounds import (  # noqa: F401  (re-exported API)
    DEFAULT_EXACT_LIMIT,
    BoundsReport,
    check_static_bounds,
    compute_bounds,
    drift_priors,
)
from .deps import (  # noqa: F401
    DEP_CARRIED,
    DEP_INDEPENDENT,
    DEP_NONE,
    Dependence,
    analyze_dependences,
)
from .validate import (  # noqa: F401
    ERROR_CODES,
    W_RACE,
    Diagnostic,
    canonicalize,
    malformed_fixtures,
    structural_signature,
    validate_program,
)

VERDICT_OK = "ok"
VERDICT_RACE = "race"  # simulable, but the modeled OpenMP program races
VERDICT_INVALID = "invalid"


@dataclasses.dataclass
class AnalysisReport:
    """Everything the three passes learned about one program."""

    program_name: str
    verdict: str  # VERDICT_OK | VERDICT_RACE | VERDICT_INVALID
    diagnostics: list  # [Diagnostic] — errors first, then W_RACE warnings
    dependences: list  # [Dependence] — empty when invalid
    races: list  # [Dependence] subset with race=True
    signature: Optional[tuple]  # structural signature (None when invalid)
    bounds: Optional[BoundsReport]  # None when invalid
    machine: Optional[MachineConfig]
    wall_s: float

    @property
    def ok(self) -> bool:
        return self.verdict != VERDICT_INVALID

    def summary(self) -> dict:
        """The compact dict that rides responses and ledger rows."""
        d: dict = {"verdict": self.verdict}
        if self.races:
            d["races"] = len(self.races)
        errors = [x for x in self.diagnostics if x.severity == "error"]
        if errors:
            d["diagnostics"] = [x.to_dict() for x in errors]
        return d

    def to_dict(self) -> dict:
        return {
            "program": self.program_name,
            "verdict": self.verdict,
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "dependences": [d.to_dict() for d in self.dependences],
            "races": [d.to_dict() for d in self.races],
            "bounds": self.bounds.to_dict() if self.bounds else None,
            "wall_s": self.wall_s,
        }


class PreflightError(ValueError):
    """Invalid IR rejected before fingerprint/cache/engines. Carries
    the machine-readable diagnostics for structured error responses."""

    def __init__(self, message: str, diagnostics: list):
        super().__init__(message)
        self.diagnostics = diagnostics


def analyze_program(program: Any,
                    machine: Optional[MachineConfig] = None,
                    exact_limit: int = DEFAULT_EXACT_LIMIT
                    ) -> AnalysisReport:
    """Run all three passes. Never raises on malformed input: an
    invalid program yields verdict "invalid" with the diagnostics."""
    t0 = time.perf_counter()
    machine = machine if machine is not None else MachineConfig()
    name = str(getattr(program, "name", "<unnamed>"))
    diagnostics = validate_program(program)
    errors = [d for d in diagnostics if d.severity == "error"]
    if errors:
        return AnalysisReport(
            program_name=name, verdict=VERDICT_INVALID,
            diagnostics=diagnostics, dependences=[], races=[],
            signature=None, bounds=None, machine=machine,
            wall_s=time.perf_counter() - t0)
    prog = canonicalize(program)
    deps = analyze_dependences(prog)
    race_list = [d for d in deps if d.race]
    for r in race_list:
        diagnostics.append(Diagnostic(
            code=W_RACE, severity="warning",
            path=f"nests[{r.nest}]",
            message=(f"write-involved dependence on {r.array!r} between "
                     f"{r.ref_a} and {r.ref_b} may be carried by the "
                     "parallel loop: the modeled OpenMP program races "
                     "(simulation is still well-defined)")))
    report = AnalysisReport(
        program_name=prog.name,
        verdict=VERDICT_RACE if race_list else VERDICT_OK,
        diagnostics=diagnostics,
        dependences=deps,
        races=race_list,
        signature=structural_signature(prog),
        bounds=compute_bounds(prog, machine, exact_limit=exact_limit),
        machine=machine,
        wall_s=0.0)
    report.wall_s = time.perf_counter() - t0
    return report


def preflight(program: Any,
              machine: Optional[MachineConfig] = None,
              exact_limit: int = DEFAULT_EXACT_LIMIT) -> AnalysisReport:
    """Service gate: analyze and raise `PreflightError` when invalid."""
    report = analyze_program(program, machine, exact_limit=exact_limit)
    if not report.ok:
        errors = [d for d in report.diagnostics if d.severity == "error"]
        first = errors[0]
        raise PreflightError(
            f"ir preflight rejected {report.program_name!r}: "
            f"{first.code} at {first.path}: {first.message}"
            + (f" (+{len(errors) - 1} more)" if len(errors) > 1 else ""),
            diagnostics=errors)
    return report
