"""Pass 3: static locality bounds (footprints, compulsory misses,
the MRC asymptote) and the runtime cross-checks against engine MRCs.

Two fidelity modes, chosen by total access count:

* **exact** (small domains): enumerate every flat index per ref with
  numpy, replicate the oracle's per-(nest, thread, array) last-access
  tables as distinct-line sets. `cold_model` then equals the engine's
  cold count *exactly* (oracle/serial.py flushes each surviving LAT
  line as one reuse==-1 event per nest), so `asymptote =
  cold_model / total_accesses` matches the MRC tail bit-for-bit
  (runtime/aet.py::_build_p seeds its accumulator with hist[-1]).
* **interval** (large domains, the preflight default above
  `exact_limit` accesses): per-ref line-footprint brackets from the
  affine form — an O(1) arithmetic-progression count along each axis
  gives a certified lower bound (a single-axis walk is a subset of the
  touched set), the span/iteration-count minimum an upper bound.

Either way `compulsory_lower` (per-array distinct lines over the whole
program) is a true lower bound on the engine's cold misses: every
distinct line must miss at least once, and the per-nest LAT flush only
ever *adds* cold misses beyond it.

`check_static_bounds(report, mrc)` turns these into violations a test
or the drift monitor can assert on; `drift_priors(report)` is the
compact per-model prior row fed alongside drift audits.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from ..config import MachineConfig
from ..ir import Program
from .deps import AffineForm, normalized_form

# Above this many modeled accesses the exact numpy enumeration is
# skipped in favor of interval bounds (preflight must stay negligible
# next to engine time; 2^21 int64 grids are ~16 MB and low ms).
DEFAULT_EXACT_LIMIT = 1 << 21


@dataclasses.dataclass(frozen=True)
class RefBounds:
    """Static facts for one reference."""

    nest: int
    name: str
    array: str
    accesses: int  # exact modeled access count (trip product over domain)
    lines_lower: int  # certified lower bound on distinct cache lines
    lines_upper: int  # certified upper bound
    lines_exact: Optional[int]  # present in exact mode only

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class BoundsReport:
    """Program-wide locality bounds."""

    total_accesses: int
    exact: bool  # True when the numpy enumeration ran
    refs: tuple[RefBounds, ...]
    array_lines: dict  # array -> distinct lines (exact) or [lo, hi]
    compulsory_lower: int  # lower bound on engine cold misses
    cold_model: Optional[int]  # exact per-(nest,tid,array) cold count
    asymptote: Optional[float]  # cold_model / total_accesses

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["refs"] = [r.to_dict() for r in self.refs]
        return d


def _nest_access_counts(program: Program, nest_index: int) -> list[int]:
    """Exact per-ref access counts (handles triangular trips)."""
    nest = program.nests[nest_index]
    l0 = nest.loops[0]
    v0 = l0.start + l0.step * np.arange(l0.trip, dtype=np.int64)
    counts = []
    for r in nest.refs:
        prod = np.ones_like(v0)
        for k in range(1, r.level + 1):
            lp = nest.loops[k]
            prod = prod * np.maximum(0, lp.trip + lp.trip_coeff * v0)
        counts.append(int(prod.sum()))
    return counts


def _progression_lines(const: int, stride: int, count: int,
                       machine: MachineConfig) -> int:
    """Distinct lines of {(const + stride*u) * ds // cls : 0 <= u < count}
    in O(1): monotone progressions either advance a full line per step
    or sweep every line in their span."""
    if count <= 0:
        return 0
    ds, cls = machine.ds, machine.cls
    if stride == 0:
        return 1
    if abs(stride) * ds >= cls:
        return count
    first = const * ds // cls
    last = (const + stride * (count - 1)) * ds // cls
    return abs(last - first) + 1


def _axis_lower_bound(form: AffineForm, nest, machine: MachineConfig) -> int:
    """Certified lower bound on a ref's distinct lines: the best
    single-axis walk (every other counter pinned to a value where the
    axis is known non-empty) touches a subset of the ref's line set."""
    nvars = len(form.hull)
    l0 = nest.loops[0]

    def inner_trips(u0: int) -> list[int]:
        v0 = l0.start + l0.step * u0
        return [nest.loops[m].trip + nest.loops[m].trip_coeff * v0
                for m in range(1, nvars)]

    best = 0
    # u0 walk, inner counters at 0: a consecutive run of parallel
    # values whose every (triangular) inner level still executes
    for end in (0, l0.trip - 1):
        if all(t >= 1 for t in inner_trips(end)):
            run = _live_u0_run(nest, nvars, end)
            stride = form.coeffs[0] if end == 0 else -form.coeffs[0]
            base = form.const + form.coeffs[0] * end
            best = max(best, _progression_lines(base, stride, run, machine))
    # inner-axis walks at a parallel endpoint where all levels execute
    for u0 in (0, l0.trip - 1):
        trips = inner_trips(u0)
        if any(t < 1 for t in trips):
            continue
        base = form.const + form.coeffs[0] * u0
        for k in range(1, nvars):
            best = max(best, _progression_lines(
                base, form.coeffs[k], trips[k - 1], machine))
    return best


def _live_u0_run(nest, nvars: int, end: int) -> int:
    """Length of the consecutive run of u0 values, starting from the
    given end (0 or trip-1), where every inner triangular level has
    trip >= 1 (so the all-zero inner counter vector is in-domain)."""
    l0 = nest.loops[0]
    run = 0
    rng = range(l0.trip) if end == 0 else range(l0.trip - 1, -1, -1)
    for u0 in rng:
        v0 = l0.start + l0.step * u0
        if all(nest.loops[m].trip + nest.loops[m].trip_coeff * v0 >= 1
               for m in range(1, nvars)):
            run += 1
        else:
            break
    return run


def _span_upper_bound(form: AffineForm, accesses: int,
                      machine: MachineConfig) -> int:
    if accesses == 0:
        return 0
    lo = form.const + sum(min(0, c) * (u - 1)
                          for c, u in zip(form.coeffs, form.hull))
    hi = form.const + sum(max(0, c) * (u - 1)
                          for c, u in zip(form.coeffs, form.hull))
    span = hi * machine.ds // machine.cls - lo * machine.ds // machine.cls + 1
    return min(accesses, span)


def _enumerate_nest_lines(program: Program, nest_index: int,
                          machine: MachineConfig):
    """Exact per-ref line arrays plus per-(tid, array) distinct sets for
    one nest, mirroring oracle/serial.py's schedule and LAT keying."""
    nest = program.nests[nest_index]
    l0 = nest.loops[0]
    u0 = np.arange(l0.trip, dtype=np.int64)
    v0 = l0.start + l0.step * u0
    tid_of = (u0 // machine.chunk_size) % machine.thread_num
    ref_lines: list[np.ndarray] = []
    per_tid_array: dict[tuple[int, str], list[np.ndarray]] = {}
    for r in nest.refs:
        form = normalized_form(nest, r)
        shape = [l0.trip] + [max(1, u) for u in form.hull[1:]]
        flat = np.full(tuple(shape), form.const, dtype=np.int64)
        mask = np.ones(tuple(shape), dtype=bool)
        for k, c in enumerate(form.coeffs):
            uk = np.arange(shape[k], dtype=np.int64)
            sh = [1] * len(shape)
            sh[k] = shape[k]
            flat += c * uk.reshape(sh)
            if k >= 1:
                lp = nest.loops[k]
                trips = np.maximum(0, lp.trip + lp.trip_coeff * v0)
                sh0 = [1] * len(shape)
                sh0[0] = shape[0]
                mask &= uk.reshape(sh) < trips.reshape(sh0)
        lines = np.floor_divide(flat * machine.ds, machine.cls)
        ref_lines.append(lines[mask])
        for t in range(machine.thread_num):
            sel = tid_of == t
            if not sel.any():
                continue
            tl = lines[sel][mask[sel]]
            if tl.size:
                per_tid_array.setdefault((t, r.array), []).append(
                    np.unique(tl))
    return ref_lines, per_tid_array


def compute_bounds(program: Program, machine: MachineConfig,
                   exact_limit: int = DEFAULT_EXACT_LIMIT) -> BoundsReport:
    per_nest_counts = [_nest_access_counts(program, ni)
                       for ni in range(len(program.nests))]
    total = sum(sum(c) for c in per_nest_counts)
    exact = 0 < total <= exact_limit

    refs: list[RefBounds] = []
    array_sets: dict[str, list[np.ndarray]] = {}
    array_brackets: dict[str, list[int]] = {}
    cold_model: Optional[int] = 0 if exact else None

    for ni, nest in enumerate(program.nests):
        if exact:
            ref_lines, per_tid_array = _enumerate_nest_lines(
                program, ni, machine)
            for (t, a), chunks in per_tid_array.items():
                cold_model += int(np.unique(np.concatenate(chunks)).size)
        for ri, r in enumerate(nest.refs):
            form = normalized_form(nest, r)
            acc = per_nest_counts[ni][ri]
            if exact:
                uniq = np.unique(ref_lines[ri])
                n_lines = int(uniq.size)
                lo = hi = n_lines
                if uniq.size:
                    array_sets.setdefault(r.array, []).append(uniq)
            else:
                n_lines = None
                lo = _axis_lower_bound(form, nest, machine)
                hi = _span_upper_bound(form, acc, machine)
                lo = min(lo, hi)
            refs.append(RefBounds(
                nest=ni, name=r.name, array=r.array, accesses=acc,
                lines_lower=lo, lines_upper=hi, lines_exact=n_lines))
            if not exact:
                br = array_brackets.setdefault(r.array, [0, 0])
                br[0] = max(br[0], lo)
                br[1] += hi

    array_lines: dict = {}
    if exact:
        for a, chunks in array_sets.items():
            array_lines[a] = int(np.unique(np.concatenate(chunks)).size)
        for nest in program.nests:  # arrays with zero surviving accesses
            for r in nest.refs:
                array_lines.setdefault(r.array, 0)
        compulsory = sum(array_lines.values())
    else:
        for a, (lo, hi) in array_brackets.items():
            array_lines[a] = [lo, hi]
        compulsory = sum(lo for lo, _ in array_brackets.values())

    return BoundsReport(
        total_accesses=total,
        exact=exact,
        refs=tuple(refs),
        array_lines=array_lines,
        compulsory_lower=compulsory,
        cold_model=cold_model,
        asymptote=(cold_model / total if exact and total else None),
    )


def check_static_bounds(report, mrc: np.ndarray,
                        machine: Optional[MachineConfig] = None,
                        atol: float = 1e-9) -> list[str]:
    """Cross-check an engine MRC against a report's static bounds.

    Accepts an AnalysisReport (with .bounds and .machine) or a bare
    BoundsReport plus an explicit machine. Returns violation strings
    (empty == every bound holds).
    """
    bounds = getattr(report, "bounds", report)
    machine = machine or getattr(report, "machine", None)
    if bounds is None:
        return ["no bounds report (validation failed before pass 3)"]
    out: list[str] = []
    mrc = np.asarray(mrc, dtype=np.float64)
    if mrc.size == 0 or bounds.total_accesses <= 0:
        return ["empty MRC or zero modeled accesses"]
    tail = float(mrc[-1])
    lower_frac = bounds.compulsory_lower / bounds.total_accesses
    if lower_frac > tail + atol:
        out.append(
            f"compulsory-miss bound violated: static lower "
            f"{bounds.compulsory_lower}/{bounds.total_accesses}"
            f"={lower_frac:.6g} > MRC tail {tail:.6g}")
    # The tail approaches the cold fraction only when the curve was not
    # truncated at the cache capacity (runtime/aet.py caps the domain
    # at machine.cache_lines). Even untruncated, AET's last point sits
    # a hair ABOVE the asymptote: the eviction-time solve at cache size
    # min(max_rt, cache_lines) lands just short of the largest reuse
    # times, so mrc[-1] >= cold/total with a small one-sided overshoot
    # (empirically <1% of the tail across the registry). The check is
    # therefore one-sided-exact below, banded above.
    truncated = machine is not None and mrc.size >= machine.cache_lines + 1
    if bounds.exact and not truncated:
        if bounds.asymptote > tail + atol:
            out.append(
                f"footprint asymptote exceeds MRC tail: static cold "
                f"{bounds.cold_model}/{bounds.total_accesses}"
                f"={bounds.asymptote:.12g} > MRC tail {tail:.12g}")
        elif tail - bounds.asymptote > 0.05 * max(tail, atol) + atol:
            out.append(
                f"footprint asymptote mismatch: static cold "
                f"{bounds.cold_model}/{bounds.total_accesses}"
                f"={bounds.asymptote:.12g} vs MRC tail {tail:.12g}")
    return out


def drift_priors(report) -> dict:
    """Compact static-prior row for the drift monitor: the facts a
    drift audit can sanity-check a measured MRC against."""
    bounds = getattr(report, "bounds", report)
    if bounds is None:
        return {}
    d = {
        "total_accesses": bounds.total_accesses,
        "compulsory_lower": bounds.compulsory_lower,
        "bounds_exact": bounds.exact,
    }
    if bounds.exact:
        d["cold_model"] = bounds.cold_model
        d["asymptote"] = bounds.asymptote
    return d
