"""Static concurrency analyzer for the serving runtime.

PLUSS reasons statically about interleavings of simulated threads;
this package applies the same spirit to the project's own
`threading` code. It is jax-free and AST-based — PR 11's IR analyzer
covers loop-nest programs, this one covers the Python that serves
them — and emits machine-readable C_* diagnostics in the shared
`analysis.lint_common` shape:

- C_LOCK_CYCLE        lock-order inversion (potential deadlock)
- C_RELOCK            non-reentrant lock reacquired on one path
- C_BLOCKING_UNDER_LOCK  blocking call while holding a lock
- C_SINK_UNDER_LOCK   telemetry sink call while holding a lock
- C_UNGUARDED_STATE   field written both with and without a lock
- C_SIGNAL_UNSAFE     signal handler beyond flag-set + raise

The static lock-order graph uses the same lock names
("Class._attr" / "modstem._name") as the runtime witness in
`runtime/lockwitness.py`, so `tools/check_concurrency.py` can prove
the static graph is a superset of every order actually observed
under the chaos gate.

Entry points: `analyze_files` (the repo gate), `analyze_source`
(fixtures/tests), `default_targets` (the scanned module set).
"""

from __future__ import annotations

import dataclasses
import os

from ..lint_common import Violation
from . import graph as _graph
from . import lints as _lints
from ._scan import scan_module
from .fixtures import FIXTURES

__all__ = [
    "AnalysisResult",
    "FIXTURES",
    "Violation",
    "analyze_files",
    "analyze_source",
    "default_targets",
    "repo_root",
]

#: modules under analysis: everything that owns threads, locks, or
#: signal handlers. Pure-math modules (sampler/, ir/, frontend/) are
#: single-threaded by design and stay out to keep the graph honest.
_TARGET_DIRS = (
    "pluss_sampler_optimization_tpu/service",
    "pluss_sampler_optimization_tpu/runtime/obs",
)
#: runtime/lockwitness.py is deliberately absent: it is the
#: measuring instrument, not the measured system — its wrapper
#: classes hold the wrapped primitive plus one leaf bookkeeping lock,
#: and scanning it would inject those internals as junk nodes into
#: the very graph it exists to validate.
_TARGET_FILES = (
    "pluss_sampler_optimization_tpu/runtime/telemetry.py",
    "pluss_sampler_optimization_tpu/runtime/faults.py",
    "pluss_sampler_optimization_tpu/cli.py",
)


@dataclasses.dataclass
class AnalysisResult:
    violations: list
    edges: dict        # (src, dst) -> [(path, qualname, line), ...]
    inventory: dict
    n_files: int
    n_functions: int

    def edge_pairs(self) -> list:
        """Sorted (src, dst) lock-order pairs — the static graph the
        runtime witness is checked against."""
        return sorted(self.edges)

    def to_dict(self) -> dict:
        return {
            "edges": [
                {
                    "src": a, "dst": b,
                    "sites": [
                        {"path": p, "qualname": q, "line": ln}
                        for p, q, ln in sites
                    ],
                }
                for (a, b), sites in sorted(self.edges.items())
            ],
            "inventory": self.inventory,
            "n_files": self.n_files,
            "n_functions": self.n_functions,
        }


def repo_root() -> str:
    """The checkout root (two levels above the package dir)."""
    pkg = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.dirname(pkg)


def default_targets(root: str | None = None) -> list[str]:
    """Repo-relative paths of every module under analysis."""
    root = root or repo_root()
    out = []
    for d in _TARGET_DIRS:
        full = os.path.join(root, d)
        if not os.path.isdir(full):
            continue
        for name in sorted(os.listdir(full)):
            if name.endswith(".py"):
                out.append(f"{d}/{name}")
    for f in _TARGET_FILES:
        if os.path.exists(os.path.join(root, f)):
            out.append(f)
    return out


def _inventory(scans: list) -> dict:
    locks = []
    for s in scans:
        for name, (kind, line) in sorted(s.module_locks.items()):
            locks.append({
                "id": f"{s.stem}.{name}", "kind": kind,
                "path": s.path, "line": line, "scope": "module",
            })
        for cls, attrs in sorted(s.class_locks.items()):
            for attr, (kind, line) in sorted(attrs.items()):
                locks.append({
                    "id": f"{cls}.{attr}", "kind": kind,
                    "path": s.path, "line": line, "scope": "class",
                })
    threads = [
        {"target": tgt, "qualname": q, "path": s.path, "line": ln}
        for s in scans for tgt, q, ln in s.threads
    ]
    executors = [
        {"qualname": q, "path": s.path, "line": ln}
        for s in scans for q, ln in s.executors
    ]
    handlers = [
        {"signal": sig, "qualname": q, "path": s.path, "line": ln}
        for s in scans for sig, _node, q, ln in s.signal_handlers
    ]
    sinks = [
        {"install": fn, "qualname": q, "path": s.path, "line": ln}
        for s in scans for fn, q, ln in s.sink_installs
    ]
    cross = sorted({
        f"{s.stem}.{cls}"
        for s in scans
        for cls in (set(s.class_locks) | set(s.thread_targets))
    })
    return {
        "locks": locks, "threads": threads, "executors": executors,
        "signal_handlers": handlers, "sink_installs": sinks,
        "cross_thread_classes": cross,
    }


def _analyze_scans(scans: list) -> AnalysisResult:
    program = _graph.Program(scans)
    violations, edges = _graph.analyze(program)
    violations = violations + _lints.shared_state_lint(scans)
    violations = violations + _lints.signal_audit(scans)
    violations.sort(key=lambda v: (v.path, v.line, v.rule, v.detail))
    return AnalysisResult(
        violations=violations,
        edges=edges,
        inventory=_inventory(scans),
        n_files=len(scans),
        n_functions=sum(len(s.functions) for s in scans),
    )


def analyze_files(paths: list[str] | None = None,
                  root: str | None = None) -> AnalysisResult:
    """Analyze repo files (repo-relative paths) as one program."""
    root = root or repo_root()
    paths = paths if paths is not None else default_targets(root)
    scans = []
    for rel in paths:
        with open(os.path.join(root, rel)) as fh:
            scans.append(scan_module(fh.read(), rel))
    return _analyze_scans(scans)


def analyze_source(source: str, path: str = "<source>"
                   ) -> AnalysisResult:
    """Analyze one synthetic module (fixtures, tests)."""
    return _analyze_scans([scan_module(source, path)])


def lint_source(source: str, path: str = "<source>") -> list:
    """`lint_common.check_fixtures`-compatible entry point."""
    return analyze_source(source, path).violations
