"""Per-file AST scan: lock inventory + per-function summaries.

One parse per source file produces everything the interprocedural
stage (graph.py) and the lints (lints.py) need:

- the lock inventory: every `self.X = threading.Lock()` /
  `RLock` / `Condition` / `Event` (or the lockwitness factory
  equivalents `make_lock`/`make_rlock`/`make_condition`) and every
  module-level lock, with a stable lock id — `Class.attr` for
  instance locks, `modstem.name` for module locks. The runtime
  witness (runtime/lockwitness.py) names its wrapped locks with the
  same `Class.attr` strings, so the observed-order graph and this
  static graph share a node vocabulary.
- per-function summaries: lock acquisitions (`with`, `.acquire()`)
  with the held-stack at each point, calls (with the held-stack
  snapshot, for the interprocedural closure), blocking operations,
  telemetry sink calls, instance-attribute writes (guarded or not),
  thread/executor creation sites, and signal-handler registrations.

The walk is a deliberate approximation: statements are visited in
source order with a single held-lock stack (no path sensitivity), a
`.acquire()` without a matching `.release()` in the same function
holds to the end of the function, and lambda/nested-def bodies are
walked as separate functions with an empty held stack (they run
later, not at definition). That is the right fidelity for a lint:
every construct in this codebase's threaded modules is a `with`
block or a short acquire/release pair.
"""

from __future__ import annotations

import ast
import dataclasses

#: kinds a lock id can have; Event is tracked for wait-blocking only.
LOCK_KINDS = ("Lock", "RLock", "Condition", "Event")

#: threading constructors (and witness factories) -> lock kind
_LOCK_CTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "threading.Event": "Event",
    "lockwitness.make_lock": "Lock",
    "lockwitness.make_rlock": "RLock",
    "lockwitness.make_condition": "Condition",
}

#: telemetry entry points that fan out into the sink registries; the
#: virtual lock ids model the locks the sinks take so the lock-order
#: graph sees cross-module edges without dynamic dispatch. event()
#: reaches the flight recorder (runtime/obs/recorder.py) whose
#: ingest/trigger path takes its RLock; count()/gauge() reach the
#: metrics registry (runtime/obs/metrics.py).
SINK_CALLS = {
    "count": (("MetricsRegistry._lock", "Lock"),),
    "gauge": (("MetricsRegistry._lock", "Lock"),),
    "event": (("FlightRecorder._lock", "RLock"),
              ("MetricsRegistry._lock", "Lock")),
    "warn_once": (("FlightRecorder._lock", "RLock"),
                  ("MetricsRegistry._lock", "Lock")),
}

#: span() takes the telemetry module lock on enter (root spans append
#: under it) — an ordering edge, not a sink violation.
_SPAN_ACQUIRES = (("telemetry._lock", "Lock"),)

#: dotted-call names that block the calling thread
_BLOCK_EXACT = {
    "time.sleep": "time.sleep",
    "os.replace": "file I/O (os.replace)",
    "os.rename": "file I/O (os.rename)",
    "os.fsync": "file I/O (os.fsync)",
    "os.makedirs": "file I/O (os.makedirs)",
    "json.dump": "file I/O (json.dump)",
}
_BLOCK_PREFIX = ("socket.", "subprocess.", "shutil.", "urllib.",
                 "requests.", "http.")
#: bare-name calls that block: builtin file open, the repo's atomic
#: writer, and the engine entry points (an engine execution under a
#: lock is the PR 3 bug class)
_BLOCK_NAMES = {
    "open": "file I/O (open)",
    "atomic_write_json": "file I/O (atomic_write_json)",
    "run_sampled": "engine execution (run_sampled)",
    "run_exact": "engine execution (run_exact)",
    "run_serial": "engine execution (run_serial)",
    "run_numpy": "engine execution (run_numpy)",
    "run_sampled_multi": "engine execution (run_sampled_multi)",
    "run_sampled_sharded": "engine execution (run_sampled_sharded)",
    "run_dense": "engine execution (run_dense)",
    "run_periodic": "engine execution (run_periodic)",
}
#: attribute-call names that block regardless of receiver
_BLOCK_ATTRS = {
    "result": "Future.result()",
    "join": "join()",
    "communicate": "subprocess communicate()",
}

#: method names that mutate their receiver in place (shared-state lint
#: counts `self.attr.append(...)` as a write to `attr`)
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "popitem", "clear", "add", "discard", "update",
    "setdefault", "move_to_end",
}


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclasses.dataclass
class FuncSummary:
    module: str          # module stem ("executor")
    path: str            # repo-relative path
    qualname: str        # "Class.method" / "func" / "Class.m.<nested>"
    cls: str | None      # enclosing class name
    acquires: list = dataclasses.field(default_factory=list)
    # [(lock_id, kind, line)] — locks this function itself takes
    edges: list = dataclasses.field(default_factory=list)
    # [(held_id, acquired_id, acquired_kind, line)] — direct nesting
    calls: list = dataclasses.field(default_factory=list)
    # [(held_tuple, callee_key, line)]; callee_key is
    # ("local", name) | ("self", name) | ("mod", stem, name)
    blocking: list = dataclasses.field(default_factory=list)
    # [(detail, line, held_tuple)] — held_tuple may be empty
    sink_calls: list = dataclasses.field(default_factory=list)
    # [(sink_name, line, held_tuple)]
    writes: list = dataclasses.field(default_factory=list)
    # [(attr, guarded: bool, line)]
    relocks: list = dataclasses.field(default_factory=list)
    # [(lock_id, line)] — non-reentrant lock taken while already held


@dataclasses.dataclass
class ModuleScan:
    path: str
    stem: str
    aliases: dict       # local alias -> imported module stem
    module_locks: dict  # name -> (kind, line)
    class_locks: dict   # class -> {attr: (kind, line)}
    functions: dict     # qualname -> FuncSummary
    threads: list       # [(target_repr, qualname, line)]
    executors: list     # [(qualname, line)]
    thread_targets: dict  # class -> set of method names run on threads
    signal_handlers: list
    # [(signame, handler_node | func_name, qualname, line)]
    sink_installs: list   # [(fn, qualname, line)]
    fn_nodes: dict = dataclasses.field(default_factory=dict)
    # module-level function name -> FunctionDef AST (signal audit)


def _is_lock_ctor(node: ast.AST, aliases: dict) -> str | None:
    """Lock kind when `node` is a lock-constructor call."""
    if not isinstance(node, ast.Call):
        return None
    name = _dotted(node.func)
    if name is None:
        return None
    head = name.split(".", 1)[0]
    # resolve `from ..runtime import lockwitness as lw` style aliases
    resolved = aliases.get(head, head)
    name = ".".join([resolved] + name.split(".")[1:])
    return _LOCK_CTORS.get(name)


def scan_module(source: str, relpath: str) -> ModuleScan:
    tree = ast.parse(source, filename=relpath)
    stem = relpath.rsplit("/", 1)[-1].rsplit(".", 1)[0]

    aliases: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name.split(".")[-1]
                    if a.asname
                    else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                aliases[a.asname or a.name] = a.name

    # pass 1: module-level locks + per-class lock attributes
    module_locks: dict = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            kind = _is_lock_ctor(node.value, aliases)
            if isinstance(t, ast.Name) and kind:
                module_locks[t.id] = (kind, node.lineno)

    class_locks: dict = {}
    for cls in tree.body:
        if not isinstance(cls, ast.ClassDef):
            continue
        attrs: dict = {}
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                kind = _is_lock_ctor(node.value, aliases)
                if (
                    kind
                    and isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    attrs[t.attr] = (kind, node.lineno)
        if attrs:
            class_locks[cls.name] = attrs

    scan = ModuleScan(
        path=relpath, stem=stem, aliases=aliases,
        module_locks=module_locks, class_locks=class_locks,
        functions={}, threads=[], executors=[], thread_targets={},
        signal_handlers=[], sink_installs=[],
        fn_nodes={
            n.name: n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        },  # all defs incl. nested: handlers are often closures
    )

    # pass 2: walk every function (methods, module funcs, nested defs)
    def walk_func(node, qual: str, cls: str | None):
        f = FuncSummary(module=stem, path=relpath, qualname=qual,
                        cls=cls)
        scan.functions[qual] = f
        _FuncWalker(scan, f).run(node)
        for sub in ast.walk(node):
            if sub is node:
                continue
            if isinstance(sub, (ast.FunctionDef,
                                ast.AsyncFunctionDef)):
                # nested defs run later (callbacks): walk each as its
                # own function with an empty held stack, once (only
                # direct children of this body — deeper nesting
                # recurses naturally)
                if _encloses_directly(node, sub):
                    walk_func(sub, f"{qual}.{sub.name}", cls)

    def _encloses_directly(outer, inner) -> bool:
        for sub in ast.walk(outer):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)) and sub is not outer:
                if inner is sub:
                    return True
                if any(inner is x for x in ast.walk(sub)
                       if x is not sub):
                    return False
        return False

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk_func(node, node.name, None)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    walk_func(sub, f"{node.name}.{sub.name}",
                              node.name)
    return scan


class _FuncWalker(ast.NodeVisitor):
    """Source-order walk of one function body with a held-lock
    stack."""

    def __init__(self, scan: ModuleScan, f: FuncSummary):
        self.scan = scan
        self.f = f
        self.held: list[tuple[str, str]] = []  # (lock_id, kind)

    def run(self, node) -> None:
        for stmt in node.body:
            self.visit(stmt)

    # -- lock identity -------------------------------------------------

    def _resolve_lock(self, node: ast.AST):
        """(lock_id, kind) for a lock-valued expression, else None."""
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
        ):
            base = node.value.id
            if base == "self" and self.f.cls:
                attrs = self.scan.class_locks.get(self.f.cls, {})
                if node.attr in attrs:
                    return (f"{self.f.cls}.{node.attr}",
                            attrs[node.attr][0])
            # module-qualified lock (telemetry._lock style)
            mod = self.scan.aliases.get(base)
            if mod is not None and mod == base:
                mod = base
            if mod is not None:
                # cross-module lock references resolve in graph.py
                # (we only know stems here); emit the id optimistically
                return (f"{mod}.{node.attr}", None)
        elif isinstance(node, ast.Name):
            if node.id in self.scan.module_locks:
                return (f"{self.scan.stem}.{node.id}",
                        self.scan.module_locks[node.id][0])
        return None

    def _held_ids(self) -> tuple:
        return tuple(h for h, _k in self.held)

    # -- acquisition ---------------------------------------------------

    def _acquire(self, lid: str, kind: str | None, line: int) -> None:
        if kind == "Lock" and any(h == lid for h, _ in self.held):
            self.f.relocks.append((lid, line))
        for h, _k in self.held:
            if h != lid:
                self.f.edges.append((h, lid, kind, line))
        self.f.acquires.append((lid, kind, line))
        self.held.append((lid, kind or "Lock"))

    def _release(self, lid: str) -> None:
        for i in range(len(self.held) - 1, -1, -1):
            if self.held[i][0] == lid:
                del self.held[i]
                return

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            got = self._resolve_lock(item.context_expr)
            if got is not None and got[1] != "Event":
                self._acquire(got[0], got[1], node.lineno)
                acquired.append(got[0])
            else:
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for lid in reversed(acquired):
            self._release(lid)

    visit_AsyncWith = visit_With

    # -- calls ---------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        line = node.lineno
        held = self._held_ids()

        # X.acquire() / X.release()
        if isinstance(func, ast.Attribute) and func.attr in (
            "acquire", "release"
        ):
            got = self._resolve_lock(func.value)
            if got is not None and got[1] != "Event":
                if func.attr == "acquire":
                    self._acquire(got[0], got[1], line)
                else:
                    self._release(got[0])
                return

        dotted = _dotted(func)

        # telemetry sinks + spans (virtual lock acquisitions)
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
        ):
            base_mod = self.scan.aliases.get(func.value.id,
                                             func.value.id)
            if base_mod == "telemetry" or (
                self.scan.stem == "telemetry"
                and func.value.id == "telemetry"
            ):
                if func.attr in SINK_CALLS:
                    self.f.sink_calls.append((func.attr, line, held))
                    for lid, kind in SINK_CALLS[func.attr]:
                        for h in held:
                            if h != lid:
                                self.f.edges.append((h, lid, kind,
                                                     line))
                        self.f.acquires.append((lid, kind, line))
                    self.generic_visit(node)
                    return
                if func.attr == "span":
                    for lid, kind in _SPAN_ACQUIRES:
                        for h in held:
                            if h != lid:
                                self.f.edges.append((h, lid, kind,
                                                     line))
                        self.f.acquires.append((lid, kind, line))
                    self.generic_visit(node)
                    return

        # threading.Thread(target=...) / ThreadPoolExecutor(...)
        if dotted in ("threading.Thread", "Thread"):
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = _dotted(kw.value) or "<expr>"
            self.scan.threads.append((target or "<none>",
                                      self.f.qualname, line))
            if (
                target
                and target.startswith("self.")
                and self.f.cls
            ):
                self.scan.thread_targets.setdefault(
                    self.f.cls, set()
                ).add(target.split(".", 1)[1])
            self.generic_visit(node)
            return
        if dotted and dotted.split(".")[-1] == "ThreadPoolExecutor":
            self.scan.executors.append((self.f.qualname, line))
        if dotted and dotted.split(".")[-1] in (
            "set_metrics_sink", "set_record_sink"
        ):
            self.scan.sink_installs.append(
                (dotted, self.f.qualname, line)
            )

        # signal.signal(SIG, handler)
        if dotted == "signal.signal" and len(node.args) >= 2:
            signame = _dotted(node.args[0]) or "<sig>"
            self.scan.signal_handlers.append(
                (signame, node.args[1], self.f.qualname, line)
            )

        # blocking operations
        blocked = None
        if dotted is not None:
            if dotted in _BLOCK_EXACT:
                blocked = _BLOCK_EXACT[dotted]
            elif dotted.startswith(_BLOCK_PREFIX):
                blocked = f"blocking call ({dotted})"
            elif "." not in dotted and dotted in _BLOCK_NAMES:
                blocked = _BLOCK_NAMES[dotted]
        if (
            blocked is None
            and isinstance(func, ast.Attribute)
            and func.attr in _BLOCK_ATTRS
        ):
            blocked = _BLOCK_ATTRS[func.attr]
        if blocked is None and isinstance(func, ast.Attribute) \
                and func.attr == "wait":
            got = self._resolve_lock(func.value)
            waited = got[0] if got else None
            others = [h for h in held if h != waited]
            if others:
                blocked = (
                    f"wait() on "
                    f"{waited or 'a foreign object'} with other "
                    f"locks held"
                )
        if blocked is not None:
            self.f.blocking.append((blocked, line, held))
            self.generic_visit(node)
            return

        # mutator method on a self attribute -> shared-state write
        if (
            isinstance(func, ast.Attribute)
            and func.attr in _MUTATORS
            and isinstance(func.value, ast.Attribute)
            and isinstance(func.value.value, ast.Name)
            and func.value.value.id == "self"
        ):
            self.f.writes.append(
                (func.value.attr, bool(held), line)
            )

        # interprocedural call record
        key = None
        if isinstance(func, ast.Name):
            key = ("local", func.id)
        elif (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
        ):
            if func.value.id == "self":
                key = ("self", func.attr)
            else:
                mod = self.scan.aliases.get(func.value.id)
                if mod is not None:
                    key = ("mod", mod, func.attr)
        if key is not None:
            self.f.calls.append((held, key, line))
        self.generic_visit(node)

    # -- writes --------------------------------------------------------

    def _note_write_target(self, t: ast.AST, line: int) -> None:
        if (
            isinstance(t, ast.Attribute)
            and isinstance(t.value, ast.Name)
            and t.value.id == "self"
        ):
            self.f.writes.append((t.attr, bool(self.held), line))
        elif isinstance(t, ast.Subscript):
            v = t.value
            if (
                isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name)
                and v.value.id == "self"
            ):
                self.f.writes.append((v.attr, bool(self.held), line))
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._note_write_target(el, line)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._note_write_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._note_write_target(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._note_write_target(node.target, node.lineno)
        self.generic_visit(node)

    # -- scope boundaries ---------------------------------------------

    def visit_FunctionDef(self, node) -> None:
        pass  # nested defs run later; scanned separately

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node) -> None:
        pass  # ditto

    def visit_ClassDef(self, node) -> None:
        pass
