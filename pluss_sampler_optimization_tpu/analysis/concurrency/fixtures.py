"""Seeded bad-pattern fixtures: the analyzer's own regression suite.

Each fixture is a small synthetic module exhibiting exactly one bug
class; `tools/check_concurrency.py --fixtures` (and tier-1 through
tests/test_concurrency_lint.py) asserts every fixture still trips its
expected C_* code. A refactor that silently blinds a rule fails here
before it can let a real deadlock through.

FIXTURES maps name -> (source, expected_rule) in the shared
`lint_common.check_fixtures` convention.
"""

from __future__ import annotations

import textwrap


def _f(src: str) -> str:
    return textwrap.dedent(src).lstrip("\n")


FIXTURES: dict = {
    # two code paths take the same two locks in opposite orders
    "inversion_pair": (_f("""
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        pass

            def backward(self):
                with self._b:
                    with self._a:
                        pass
    """), "C_LOCK_CYCLE"),

    # Future.result() inside a critical section (the PR 3 bug class)
    "result_under_lock": (_f("""
        import threading

        class Exec:
            def __init__(self):
                self._lock = threading.Lock()
                self._pending = {}

            def submit(self, key, fut):
                with self._lock:
                    prior = self._pending.get(key)
                    if prior is not None:
                        return prior.result()
                    self._pending[key] = fut
                return fut
    """), "C_BLOCKING_UNDER_LOCK"),

    # time.sleep while holding a lock
    "sleep_under_lock": (_f("""
        import threading
        import time

        class Poller:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def poll(self):
                with self._lock:
                    time.sleep(0.1)
                    self.n += 1
    """), "C_BLOCKING_UNDER_LOCK"),

    # file I/O inside a critical section
    "io_under_lock": (_f("""
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()

            def save(self, path, data):
                with self._lock:
                    with open(path, "w") as fh:
                        fh.write(data)
    """), "C_BLOCKING_UNDER_LOCK"),

    # waiting on one condition while holding an unrelated lock
    "foreign_wait": (_f("""
        import threading

        class Handoff:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition()

            def take(self):
                with self._lock:
                    with self._cv:
                        self._cv.wait()
    """), "C_BLOCKING_UNDER_LOCK"),

    # non-reentrant lock reacquired on the same path
    "relock": (_f("""
        import threading

        class Nested:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    with self._lock:
                        pass
    """), "C_RELOCK"),

    # telemetry sink call under a held lock
    "sink_under_lock": (_f("""
        import threading
        from ..runtime import telemetry

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.hits = 0

            def hit(self):
                with self._lock:
                    self.hits += 1
                    telemetry.count("hits")
    """), "C_SINK_UNDER_LOCK"),

    # instance counter written with and without the lock
    "unguarded_counter": (_f("""
        import threading

        class Stats:
            def __init__(self):
                self._lock = threading.Lock()
                self.served = 0

            def record(self):
                with self._lock:
                    self.served += 1

            def record_fast(self):
                self.served += 1
    """), "C_UNGUARDED_STATE"),

    # signal handler that takes a lock and does I/O
    "unsafe_signal": (_f("""
        import signal
        import threading

        _lock = threading.Lock()

        def _on_term(signum, frame):
            with _lock:
                with open("/tmp/state", "w") as fh:
                    fh.write("bye")

        def install():
            signal.signal(signal.SIGTERM, _on_term)
    """), "C_SIGNAL_UNSAFE"),

    # joining a worker thread while holding the lock it needs
    "join_under_lock": (_f("""
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._threads = []

            def close(self):
                with self._lock:
                    for t in self._threads:
                        t.join()
    """), "C_BLOCKING_UNDER_LOCK"),

    # inversion only visible through the call graph: helper takes B
    # then calls into A-then-B order established elsewhere
    "interprocedural_inversion": (_f("""
        import threading

        class Split:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def _grab_b(self):
                with self._b:
                    pass

            def forward(self):
                with self._a:
                    self._grab_b()

            def _grab_a(self):
                with self._a:
                    pass

            def backward(self):
                with self._b:
                    self._grab_a()
    """), "C_LOCK_CYCLE"),

    # blocking call hidden two frames deep under a held lock
    "blocking_transitive": (_f("""
        import threading
        import time

        class Deep:
            def __init__(self):
                self._lock = threading.Lock()

            def _nap(self):
                time.sleep(0.5)

            def _work(self):
                self._nap()

            def serve(self):
                with self._lock:
                    self._work()
    """), "C_BLOCKING_UNDER_LOCK"),
}
