"""Interprocedural lock-order graph + blocking/sink/relock rules.

Consumes the per-function summaries from `_scan` and produces:

- the global lock-acquisition graph: a directed edge `A -> B` means
  some code path acquires lock B while holding lock A. Direct edges
  come from nested `with` scopes; interprocedural edges come from a
  call made under a held lock to a function whose transitive closure
  acquires other locks.
- C_LOCK_CYCLE: a cycle in that graph (two code paths acquire the
  same locks in opposite orders — the classic deadlock recipe).
- C_RELOCK: a non-reentrant `threading.Lock` acquired while already
  held on the same path (self-deadlock).
- C_BLOCKING_UNDER_LOCK: a blocking operation (Future.result, join,
  time.sleep, file/socket I/O, engine execution, wait on a foreign
  object) reached — directly or through calls — while a lock is held.
- C_SINK_UNDER_LOCK: a telemetry sink call (count/gauge/event)
  reached while a lock is held. Sinks take their own registry locks
  and the flight-recorder path does real work, so emitting from
  inside a critical section both extends hold times and creates
  cross-module lock edges; the fix is always "snapshot under the
  lock, emit after release".

Call resolution is name-based and deliberately modest: `self.m()` to
a method of the same class, `f()` to a function of the same module,
`alias.f()` to a function of another scanned module (resolved by
stem). Unresolved calls contribute nothing — the analyzer trades
recall at dynamic-dispatch sites for zero-noise diagnostics
everywhere else, and the runtime lockwitness covers the dynamic
remainder.
"""

from __future__ import annotations

from ..lint_common import Violation

# cycle-path cap purely for readable diagnostics
_MAX_CYCLE = 12


class Program:
    """All scanned modules, indexed for call resolution."""

    def __init__(self, scans: list):
        self.scans = scans
        self.functions: dict = {}   # "path::qualname" -> FuncSummary
        self._by_stem: dict = {}    # module stem -> scan (unambiguous)
        stems_seen: dict = {}
        for s in scans:
            stems_seen.setdefault(s.stem, []).append(s)
            for qual, f in s.functions.items():
                self.functions[f"{s.path}::{qual}"] = f
        for stem, group in stems_seen.items():
            if len(group) == 1:
                self._by_stem[stem] = group[0]
        self._scan_of = {s.path: s for s in scans}
        # closure memos
        self._acq: dict = {}
        self._blk: dict = {}
        self._snk: dict = {}

    # -- call resolution ----------------------------------------------

    def resolve(self, caller_key: str, callee) -> str | None:
        f = self.functions[caller_key]
        scan = self._scan_of[f.path]
        kind = callee[0]
        if kind == "self" and f.cls:
            k = f"{f.path}::{f.cls}.{callee[1]}"
            return k if k in self.functions else None
        if kind == "local":
            k = f"{f.path}::{callee[1]}"
            return k if k in self.functions else None
        if kind == "mod":
            target = self._by_stem.get(callee[1])
            if target is not None:
                k = f"{target.path}::{callee[2]}"
                return k if k in self.functions else None
        return None

    # -- transitive closures (memoised DFS, cycle-safe) ---------------

    def acquires_all(self, key: str, _stack=None) -> frozenset:
        """Lock ids (with kinds) transitively acquired by `key`."""
        if key in self._acq:
            return self._acq[key]
        stack = _stack if _stack is not None else set()
        if key in stack:
            return frozenset()
        stack.add(key)
        f = self.functions[key]
        out = {(lid, kind) for lid, kind, _ln in f.acquires}
        for _held, callee, _ln in f.calls:
            ck = self.resolve(key, callee)
            if ck is not None:
                out |= self.acquires_all(ck, stack)
        stack.discard(key)
        if _stack is None or not stack:
            self._acq[key] = frozenset(out)
        return frozenset(out)

    def _reaches(self, key: str, field: str, memo: dict, _stack=None):
        """First (detail, chain) where `field` is nonempty on the
        transitive call graph from `key`, else None."""
        if key in memo:
            return memo[key]
        stack = _stack if _stack is not None else set()
        if key in stack:
            return None
        stack.add(key)
        f = self.functions[key]
        own = getattr(f, field)
        result = None
        if own:
            detail = own[0][0]
            result = (detail, [f.qualname])
        else:
            for _held, callee, _ln in f.calls:
                ck = self.resolve(key, callee)
                if ck is None:
                    continue
                sub = self._reaches(ck, field, memo, stack)
                if sub is not None:
                    result = (sub[0], [f.qualname] + sub[1])
                    break
        stack.discard(key)
        if _stack is None or not stack:
            memo[key] = result
        return result

    def may_block(self, key: str):
        return self._reaches(key, "blocking", self._blk)

    def may_sink(self, key: str):
        return self._reaches(key, "sink_calls", self._snk)


def analyze(program: Program):
    """(violations, edges) for the whole program.

    edges: {(src_lock, dst_lock): [(path, qualname, line), ...]}
    """
    violations: list[Violation] = []
    edges: dict = {}

    def edge(a: str, b: str, site) -> None:
        edges.setdefault((a, b), []).append(site)

    for key, f in program.functions.items():
        site_base = (f.path, f.qualname)

        # direct nesting edges
        for held, acquired, _kind, line in f.edges:
            edge(held, acquired, (*site_base, line))

        # direct relocks
        for lid, line in f.relocks:
            violations.append(Violation(
                path=f.path, qualname=f.qualname, rule="C_RELOCK",
                line=line,
                detail=(
                    f"non-reentrant lock {lid} acquired while already "
                    f"held on the same path (self-deadlock)"
                ),
            ))

        # direct blocking under a held lock
        for detail, line, held in f.blocking:
            if held:
                violations.append(Violation(
                    path=f.path, qualname=f.qualname,
                    rule="C_BLOCKING_UNDER_LOCK", line=line,
                    detail=(
                        f"{detail} while holding "
                        f"{', '.join(held)}"
                    ),
                ))

        # direct sink calls under a held lock
        for sink, line, held in f.sink_calls:
            if held:
                violations.append(Violation(
                    path=f.path, qualname=f.qualname,
                    rule="C_SINK_UNDER_LOCK", line=line,
                    detail=(
                        f"telemetry.{sink}() while holding "
                        f"{', '.join(held)}; snapshot under the lock "
                        f"and emit after release"
                    ),
                ))

        # interprocedural: calls made while holding locks
        for held, callee, line in f.calls:
            ck = program.resolve(key, callee)
            if ck is None:
                continue
            if held:
                blk = program.may_block(ck)
                if blk is not None:
                    chain = " -> ".join(blk[1])
                    violations.append(Violation(
                        path=f.path, qualname=f.qualname,
                        rule="C_BLOCKING_UNDER_LOCK", line=line,
                        detail=(
                            f"call chain {chain} reaches {blk[0]} "
                            f"while holding {', '.join(held)}"
                        ),
                    ))
                snk = program.may_sink(ck)
                if snk is not None:
                    chain = " -> ".join(snk[1])
                    violations.append(Violation(
                        path=f.path, qualname=f.qualname,
                        rule="C_SINK_UNDER_LOCK", line=line,
                        detail=(
                            f"call chain {chain} reaches a telemetry "
                            f"sink while holding {', '.join(held)}"
                        ),
                    ))
            # lock-order edges through the callee's closure (recorded
            # whether or not it also blocks: edges feed the cycle
            # check, violations are separate)
            if held:
                for lid, kind in program.acquires_all(ck):
                    for h in held:
                        if h == lid:
                            if kind == "Lock":
                                violations.append(Violation(
                                    path=f.path, qualname=f.qualname,
                                    rule="C_RELOCK", line=line,
                                    detail=(
                                        f"call into "
                                        f"{'.'.join(callee[1:])} "
                                        f"re-acquires non-reentrant "
                                        f"{lid} already held here"
                                    ),
                                ))
                        else:
                            edge(h, lid, (*site_base, line))

    # cycle detection over the final edge set
    violations.extend(_find_cycles(edges))

    # stable order + dedup (same function can hit a rule repeatedly)
    seen = set()
    out = []
    for v in sorted(violations, key=lambda v: (v.path, v.line,
                                               v.rule, v.detail)):
        k = (v.path, v.qualname, v.rule, v.line)
        if k not in seen:
            seen.add(k)
            out.append(v)
    return out, edges


def _find_cycles(edges: dict) -> list[Violation]:
    """One C_LOCK_CYCLE per strongly connected component with >1 node
    (self-edges never enter `edges`; relocks are reported
    separately)."""
    adj: dict = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())

    # Tarjan SCC, iterative
    index: dict = {}
    low: dict = {}
    onstack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]

    def strongconnect(root: str) -> None:
        work = [(root, iter(sorted(adj[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        onstack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                if w in onstack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])

    for node in sorted(adj):
        if node not in index:
            strongconnect(node)

    out = []
    for comp in sccs:
        cycle = _cycle_path(comp, adj)
        # anchor the diagnostic at a real site on the first edge
        sites = edges.get((cycle[0], cycle[1]), [("<lock-graph>",
                                                  "<cycle>", 0)])
        path, qual, line = sites[0]
        out.append(Violation(
            path=path, qualname="<lock-graph>", rule="C_LOCK_CYCLE",
            line=line,
            detail=(
                "lock-order inversion: "
                + " -> ".join(cycle[:_MAX_CYCLE])
                + f" -> {cycle[0]} (acquired in opposite orders; "
                f"first edge at {path}:{line} in {qual})"
            ),
        ))
    return out


def _cycle_path(comp: list, adj: dict) -> list:
    """A concrete cycle through an SCC (DFS restricted to the
    component)."""
    comp_set = set(comp)
    start = comp[0]
    stack = [(start, [start])]
    seen = set()
    while stack:
        node, path = stack.pop()
        for nxt in sorted(adj.get(node, ())):
            if nxt == start and len(path) > 1:
                return path
            if nxt in comp_set and nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return comp  # fallback: list the component itself
