"""Shared-state and signal-handler lints over the scan results.

C_UNGUARDED_STATE — in a class whose instances cross threads, an
instance attribute written both inside a lock scope and outside any
lock scope. Half-guarded state is the tell of a data race: either the
lock is needed (the unguarded write races) or it is not (the guarded
write is noise hiding the real protocol). `__init__`-time writes are
construction, not sharing, and are excluded.

A class "crosses threads" when it owns a lock/condition attribute
(locks exist to be contended) or when one of its methods is the
target of `threading.Thread(target=self...)`.

Methods named `*_locked` are, by this codebase's convention, only
ever called with the class lock already held; their writes count as
guarded. The interprocedural stage still verifies the convention the
other way around — a `*_locked` method reached from a path that does
not hold the lock shows up as a missing edge in the lock graph, and
the runtime witness sees the real order.

C_SIGNAL_UNSAFE — a signal handler doing anything beyond the
async-signal-safe core: setting a flag, re-raising, calling signal.*
functions, or delegating to a local function that itself passes the
same audit. Handlers run on the main thread at arbitrary bytecode
boundaries — inside the executor's critical sections, halfway through
a recorder bundle write — so lock acquisition, I/O, or telemetry in a
handler is a reentrancy deadlock waiting for load to find it.
"""

from __future__ import annotations

import ast

from ..lint_common import Violation

#: methods where instance-attr writes are construction, not sharing
_CTOR_METHODS = {"__init__", "__new__", "__post_init__", "__del__",
                 "__enter__"}

#: attribute suffixes that are themselves synchronisation or
#: thread-handle objects — assigning them is setup, not shared state
_SYNC_ATTR_HINTS = ("_lock", "_cv", "_cond", "_thread", "_threads",
                    "_stop", "_event", "_pool", "_executor")


def shared_state_lint(scans: list) -> list[Violation]:
    out: list[Violation] = []
    for scan in scans:
        cross = set(scan.class_locks)
        cross |= set(scan.thread_targets)
        for cls in sorted(cross):
            # attr -> {"guarded": [(qual, line)], "bare": [...]}
            writes: dict = {}
            for qual, f in scan.functions.items():
                if f.cls != cls:
                    continue
                method = qual.split(".", 1)[1].split(".", 1)[0] \
                    if "." in qual else qual
                if method in _CTOR_METHODS:
                    continue
                assume_held = method.endswith("_locked")
                for attr, guarded, line in f.writes:
                    if attr.endswith(_SYNC_ATTR_HINTS):
                        continue
                    slot = writes.setdefault(
                        attr, {"guarded": [], "bare": []}
                    )
                    key = "guarded" if (guarded or assume_held) \
                        else "bare"
                    slot[key].append((qual, line))
            for attr in sorted(writes):
                slot = writes[attr]
                if slot["guarded"] and slot["bare"]:
                    gq, gl = slot["guarded"][0]
                    for bq, bl in slot["bare"]:
                        out.append(Violation(
                            path=scan.path, qualname=bq,
                            rule="C_UNGUARDED_STATE", line=bl,
                            detail=(
                                f"{cls}.{attr} written without a lock "
                                f"here but under a lock in {gq} "
                                f"(line {gl}); pick one protocol"
                            ),
                        ))
    return out


# -- signal-handler audit ---------------------------------------------

#: call targets a handler may make (beyond local delegation)
_SAFE_CALL_PREFIXES = ("signal.",)
_SAFE_CALL_NAMES = {"print"}  # write(2) on CPython; accepted for
# diagnostics-on-shutdown handlers


def signal_audit(scans: list) -> list[Violation]:
    out: list[Violation] = []
    for scan in scans:
        fn_nodes = _function_nodes(scan)
        for signame, handler, qual, line in scan.signal_handlers:
            problem = _audit_handler(handler, scan, fn_nodes,
                                     depth=0)
            if problem is not None:
                out.append(Violation(
                    path=scan.path, qualname=qual,
                    rule="C_SIGNAL_UNSAFE", line=line,
                    detail=(
                        f"{signame} handler is not async-signal-safe:"
                        f" {problem}; restrict handlers to flag-set +"
                        f" raise"
                    ),
                ))
    return out


def _function_nodes(scan) -> dict:
    """name -> FunctionDef AST for module-level functions (captured
    by the scan pass for exactly this audit)."""
    return scan.fn_nodes if scan.signal_handlers else {}


def _audit_handler(handler, scan, fn_nodes: dict, depth: int):
    """None when safe, else a human-readable problem string."""
    if depth > 2:
        return "delegation deeper than 2 calls"
    if isinstance(handler, ast.Lambda):
        return _audit_expr_body(handler.body, scan, fn_nodes, depth)
    if isinstance(handler, ast.Attribute):
        d = _dotted(handler)
        if d in ("signal.SIG_IGN", "signal.SIG_DFL"):
            return None
        return f"handler {d or '<expr>'} is not auditable"
    if isinstance(handler, ast.Name):
        node = fn_nodes.get(handler.id)
        if node is None:
            return f"handler {handler.id} not found for audit"
        return _audit_body(node.body, scan, fn_nodes, depth)
    return "handler expression is not auditable"


def _audit_body(body, scan, fn_nodes, depth):
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Raise, ast.Return,
                             ast.Global, ast.Nonlocal, ast.Break,
                             ast.Continue)):
            continue
        if isinstance(stmt, (ast.Assign, ast.AugAssign,
                             ast.AnnAssign)):
            # flag-set; the value must not itself call anything unsafe
            val = getattr(stmt, "value", None)
            if val is not None and _has_unsafe_call(val, scan,
                                                    fn_nodes, depth):
                return "assignment value performs an unsafe call"
            continue
        if isinstance(stmt, ast.If):
            p = _audit_body(stmt.body, scan, fn_nodes, depth) \
                or _audit_body(stmt.orelse, scan, fn_nodes, depth)
            if p:
                return p
            continue
        if isinstance(stmt, ast.Expr):
            p = _audit_expr_body(stmt.value, scan, fn_nodes, depth)
            if p:
                return p
            continue
        return f"{type(stmt).__name__} statement at line {stmt.lineno}"
    return None


def _audit_expr_body(expr, scan, fn_nodes, depth):
    if isinstance(expr, ast.Call):
        return _audit_call(expr, scan, fn_nodes, depth)
    if isinstance(expr, ast.Constant):
        return None
    if _has_unsafe_call(expr, scan, fn_nodes, depth):
        return "expression performs an unsafe call"
    return None


def _audit_call(call: ast.Call, scan, fn_nodes, depth):
    d = _dotted(call.func)
    if d is not None:
        if d.startswith(_SAFE_CALL_PREFIXES) or d in _SAFE_CALL_NAMES:
            return None
        if "." not in d and d in fn_nodes:
            return _audit_handler(ast.Name(id=d), scan, fn_nodes,
                                  depth + 1)
    return f"call to {d or '<expr>'} at line {call.lineno}"


def _has_unsafe_call(expr, scan, fn_nodes, depth) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            if _audit_call(node, scan, fn_nodes, depth) is not None:
                return True
    return False


def _dotted(node) -> str | None:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
