"""Pass 2: affine dependence analysis / race detection.

For every pair of references sharing an array within one parallel nest
(the per-nest LAT flush makes nests independent, and each nest is its
own `#pragma pluss parallel` region with an implicit barrier), classify
the dependence by testing integer feasibility of the flat-index
equality over the iteration domain:

    flat_a(u_a) = flat_b(u_b)       (element granularity — false
                                     sharing is locality, not a race)

in *normalized* iteration space u_k in [0, trip_k): triangular bounds
fold their start_coeff contribution into the affine form exactly and
their trip bound is relaxed to the rectangular hull (sound: the hull
only ever widens the domain, so "no dependence" verdicts stay proofs).

Three independence tests, cheapest first (the classic GCD + Banerjee
pair plus a modular-interval refinement):

  gcd       gcd of the equation's coefficients does not divide the rhs.
  interval  rhs outside the [min, max] of the LHS over the box
            (Banerjee bounds).
  modular   for a modulus M drawn from the coefficients, the terms not
            divisible by M can never be congruent to the rhs (mod M)
            within their interval — this is what proves adi's
            column-major writes (stride-1 on the parallel variable,
            stride-n inner) independent where plain Banerjee cannot.

A dependence not proven absent is classified *loop-independent* when a
cross-parallel-iteration solution (u_b0 = u_a0 + d, |d| >= 1) is
refuted by the same tests, else *carried* by the parallel loop.

Write modeling: the IR has no read/write bit. The generated-sampler
convention (models/gemm.py: "RHS operands in source order before the
write") makes every store a read-modify-write *pair* of refs with the
identical affine map, so >= 2 refs in one nest with the same (array,
coeffs, const) mark that map — and its array — write-involved. A
carried dependence touching a write-involved map is flagged as a
**race**: still simulable (the machine models the interleaving), but
the modeled OpenMP program is racy. The tests are conservative: a
race flag means "not provably race-free" (covariance's triangular
symmetric write-back is a known may-alias the hull cannot refute).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

from ..ir import ParallelNest, Program

DEP_NONE = "none"
DEP_INDEPENDENT = "independent"
DEP_CARRIED = "carried"


@dataclasses.dataclass(frozen=True)
class AffineForm:
    """flat(u) = const + sum(coeffs[k] * u_k) over normalized iteration
    counters u_k in [0, hull[k]); hull is the rectangular relaxation of
    (possibly triangular) trip counts."""

    const: int
    coeffs: tuple[int, ...]
    hull: tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class Dependence:
    """One classified ref pair (unordered, nest-local; a == b is a ref
    against its own other iterations)."""

    nest: int
    array: str
    ref_a: str
    ref_b: str
    kind: str  # DEP_NONE | DEP_INDEPENDENT | DEP_CARRIED
    race: bool
    write_involved: bool
    reason: str  # deciding test ("gcd"/"interval"/"modular"/"feasible"/...)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def normalized_form(nest: ParallelNest, ref: Any) -> AffineForm:
    """Exact affine form of a ref's flat index over normalized counters.

    v0 = start0 + step0*u0;  i_k = start_k + start_coeff_k*v0 + step_k*u_k.
    Triangular start_coeff contributions land on u0's coefficient, so
    the *map* stays exact — only the trip bound is hulled.
    """
    loops = nest.loops
    l0 = loops[0]
    lv = ref.level
    const = ref.const + ref.coeffs[0] * l0.start
    c0 = ref.coeffs[0] * l0.step
    coeffs = [0] * (lv + 1)
    for k in range(1, lv + 1):
        lp, c = loops[k], ref.coeffs[k]
        const += c * (lp.start + lp.start_coeff * l0.start)
        c0 += c * lp.start_coeff * l0.step
        coeffs[k] = c * lp.step
    coeffs[0] = c0
    v0_ends = (l0.start, l0.start + (l0.trip - 1) * l0.step)
    hull = [l0.trip]
    for k in range(1, lv + 1):
        lp = loops[k]
        hull.append(max(0, *(lp.trip + lp.trip_coeff * v0 for v0 in v0_ends)))
    return AffineForm(const=const, coeffs=tuple(coeffs), hull=tuple(hull))


def _interval(coeffs: list[int], ranges: list[tuple[int, int]]):
    lo = hi = 0
    for c, (a, b) in zip(coeffs, ranges):
        if c >= 0:
            lo += c * a
            hi += c * b
        else:
            lo += c * b
            hi += c * a
    return lo, hi


def _congruent_in(lo: int, hi: int, rhs: int, mod: int) -> bool:
    """Is there y in [lo, hi] with y == rhs (mod mod)?"""
    first = rhs + math.ceil((lo - rhs) / mod) * mod
    return first <= hi


def eq_feasible(coeffs: list[int], ranges: list[tuple[int, int]],
                rhs: int) -> tuple[bool, str]:
    """May `sum(c_i * x_i) == rhs` have an integer solution with each
    x_i in its inclusive range? Returns (feasible, deciding_test);
    False is a proof, True is conservative ("feasible")."""
    for a, b in ranges:
        if a > b:
            return False, "empty"
    live = [(c, r) for c, r in zip(coeffs, ranges) if c != 0]
    if not live:
        return (rhs == 0), ("feasible" if rhs == 0 else "gcd")
    cs = [c for c, _ in live]
    rs = [r for _, r in live]
    g = 0
    for c in cs:
        g = math.gcd(g, c)
    if rhs % g != 0:
        return False, "gcd"
    lo, hi = _interval(cs, rs)
    if rhs < lo or rhs > hi:
        return False, "interval"
    # modular-interval: modulus M from the coefficient magnitudes; the
    # M-divisible terms vanish (mod M), the rest must reach a value
    # congruent to rhs (mod M) inside their own interval
    for mod in sorted({abs(c) for c in cs if abs(c) > 1}):
        rem = [(c, r) for c, r in live if c % mod != 0]
        if len(rem) == len(live):
            continue
        rlo, rhi = _interval([c for c, _ in rem], [r for _, r in rem])
        if not _congruent_in(rlo, rhi, rhs, mod):
            return False, "modular"
    return True, "feasible"


def _base_equation(fa: AffineForm, fb: AffineForm):
    coeffs = list(fa.coeffs) + [-c for c in fb.coeffs]
    ranges = ([(0, u - 1) for u in fa.hull]
              + [(0, u - 1) for u in fb.hull])
    return coeffs, ranges, fb.const - fa.const


def _cross_feasible(fa: AffineForm, fb: AffineForm, trip0: int
                    ) -> tuple[bool, str]:
    """Feasibility of flat_a(u_a) = flat_b(u_b) with u_b0 = u_a0 + d,
    |d| >= 1 (a solution on two distinct parallel iterations, hence
    potentially two distinct simulated threads)."""
    # vars: u_a0, u_a1.., u_b1.., d
    coeffs = ([fa.coeffs[0] - fb.coeffs[0]] + list(fa.coeffs[1:])
              + [-c for c in fb.coeffs[1:]] + [-fb.coeffs[0]])
    base = ([(0, trip0 - 1)] + [(0, u - 1) for u in fa.hull[1:]]
            + [(0, u - 1) for u in fb.hull[1:]])
    rhs = fb.const - fa.const
    reasons = []
    for dlo, dhi in ((1, trip0 - 1), (-(trip0 - 1), -1)):
        ok, why = eq_feasible(coeffs, base + [(dlo, dhi)], rhs)
        if ok:
            return True, why
        reasons.append(why)
    return False, "/".join(reasons)


def write_involved_maps(nest: ParallelNest) -> set[tuple]:
    """Affine maps that are stores.

    An explicit `Ref.write=True` marks the map directly. Refs with
    `write=None` fall under the read-modify-write pair convention: >= 2
    unmarked refs of one nest sharing an (array, coeffs, const) map
    mean a load+store pair. `write=False` refs never contribute."""
    explicit: set[tuple] = set()
    counts: dict[tuple, int] = {}
    for r in nest.refs:
        key = (r.array, tuple(r.coeffs), r.const)
        w = getattr(r, "write", None)
        if w is True:
            explicit.add(key)
        elif w is None:
            counts[key] = counts.get(key, 0) + 1
    return explicit | {k for k, n in counts.items() if n >= 2}


def analyze_nest(program: Program, nest_index: int) -> list[Dependence]:
    nest = program.nests[nest_index]
    refs = nest.refs
    forms = [normalized_form(nest, r) for r in refs]
    writes = write_involved_maps(nest)
    is_write = [(r.array, tuple(r.coeffs), r.const) in writes for r in refs]
    trip0 = nest.loops[0].trip
    out: list[Dependence] = []
    for i in range(len(refs)):
        for j in range(i, len(refs)):
            a, b = refs[i], refs[j]
            if a.array != b.array:
                continue
            wr = is_write[i] or is_write[j]
            coeffs, ranges, rhs = _base_equation(forms[i], forms[j])
            ok, why = eq_feasible(coeffs, ranges, rhs)
            if not ok:
                kind, race = DEP_NONE, False
            else:
                ok, why = _cross_feasible(forms[i], forms[j], trip0)
                kind = DEP_CARRIED if ok else DEP_INDEPENDENT
                race = ok and wr
            out.append(Dependence(
                nest=nest_index, array=a.array, ref_a=a.name, ref_b=b.name,
                kind=kind, race=race, write_involved=wr, reason=why))
    return out


def analyze_dependences(program: Program) -> list[Dependence]:
    """All classified ref pairs, program order."""
    out: list[Dependence] = []
    for ni in range(len(program.nests)):
        out.extend(analyze_nest(program, ni))
    return out


def races(dependences: list[Dependence]) -> list[Dependence]:
    return [d for d in dependences if d.race]
