"""Shared plumbing for the repo's source-level lint gates.

tools/lint_determinism.py (the bit-identity lint) and
tools/check_concurrency.py (the concurrency analyzer) are the same
kind of tool: an AST pass over the project's own source emitting
machine-readable diagnostics, suppressed one-by-one through a reviewed
allowlist file, wired into tier-1 with a `--fixtures` self-test that
proves the pass still catches the bug classes it exists for. This
module is the one copy of that scaffolding:

- `Violation`: the diagnostic record both tools emit. `id`
  (`relpath::qualname::rule`) is the allowlist key; `rule` is the
  machine-readable code (`wallclock`, `C_LOCK_CYCLE`, ...).
- `read_allowlist` / `split_allowed`: one-id-per-line allowlist files
  with '#' comments, applied after human review.
- `report_doc`: the shared `--json` report shape
  (tool/targets/violations/suppressed/ok) so downstream tooling can
  consume either gate without caring which one produced the report.
- `check_fixtures`: the self-test convention — every seeded
  bad-pattern fixture must produce its expected diagnostic code, so a
  refactor that silently blinds a rule fails the gate immediately.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys


@dataclasses.dataclass(frozen=True)
class Violation:
    path: str  # repo-relative
    qualname: str
    rule: str
    line: int
    detail: str

    @property
    def id(self) -> str:
        return f"{self.path}::{self.qualname}::{self.rule}"

    def __str__(self) -> str:
        return f"{self.path}:{self.line} [{self.rule}] {self.detail}"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["id"] = self.id
        return d


def read_allowlist(path: str) -> set[str]:
    """Violation ids from an allowlist file (one per line, '#'
    comments); missing file reads as empty."""
    if not os.path.exists(path):
        return set()
    out: set[str] = set()
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if line:
                out.add(line)
    return out


def split_allowed(
    violations: list[Violation], allow: set[str]
) -> tuple[list[Violation], int]:
    """(unallowed violations, suppressed count)."""
    kept = [v for v in violations if v.id not in allow]
    return kept, len(violations) - len(kept)


def report_doc(tool: str, targets: int, violations: list[Violation],
               suppressed: int = 0, extra: dict | None = None) -> dict:
    """The shared JSON report shape for every lint gate."""
    doc = {
        "tool": tool,
        "targets": targets,
        "violations": [v.to_dict() for v in violations],
        "suppressed": suppressed,
        "ok": not violations,
    }
    if extra:
        doc.update(extra)
    return doc


def print_report(doc: dict, as_json: bool, stream=None) -> None:
    """Human or `--json` output for a report_doc. Violations go to
    stderr in human mode (the summary line stays on stdout), so piped
    gate output is still one parseable line."""
    if as_json:
        print(json.dumps(doc, indent=2, sort_keys=True))
        return
    err = stream if stream is not None else sys.stderr
    for v in doc["violations"]:
        print(
            f"{v['path']}:{v['line']} [{v['rule']}] {v['detail']}",
            file=err,
        )
    print(
        f"{doc['tool']}: {doc['targets']} target(s), "
        f"{len(doc['violations'])} violation(s), "
        f"{doc['suppressed']} allowlisted"
    )


def check_fixtures(fixtures: dict, lint_fn) -> list[str]:
    """Self-test: every fixture must produce its expected code.

    `fixtures` maps name -> (source, expected_rule); `lint_fn(source,
    path)` returns the Violations for one synthetic source file.
    Returns problem strings (empty == the pass still catches every
    seeded bad pattern)."""
    problems: list[str] = []
    for name in sorted(fixtures):
        source, want = fixtures[name]
        try:
            got = {v.rule for v in lint_fn(source, f"<fixture:{name}>")}
        except Exception as e:
            problems.append(f"fixture {name}: lint raised {e!r}")
            continue
        if want not in got:
            problems.append(
                f"fixture {name}: expected {want}, got {sorted(got)}"
            )
    return problems
