"""Pass 1: structural well-formedness validation + canonical signatures.

The engines assume every `Program` handed to them satisfies the IR
invariants that `ir.py`'s `__post_init__` hooks enforce at
construction — but ROADMAP item 4 (loop nests as untrusted request
payloads) means programs will arrive as *data*, built by frontends that
bypass those constructors, and an invariant violation today surfaces as
an engine-side IndexError/ValueError deep inside a jit trace. This
pass re-checks every invariant duck-typed (no isinstance on the ir
classes), returns machine-readable diagnostics instead of raising, and
adds the domain checks the constructors cannot see (empty iteration
domains, triangular levels that never execute).

Also home to the *structural signature*: a size-invariant canonical
summary of a program's shape (loop classes, affine coefficient sign
classes, share markers) used by `sampler/analytic.py` to derive the
audited-family verdict from program structure instead of a hardcoded
name list.
"""

from __future__ import annotations

import dataclasses
import numbers
from typing import Any, Iterable, Optional

from ..ir import MAX_DEPTH, Loop, ParallelNest, Program, Ref

# Diagnostic codes (the glossary lives in README "Static analysis &
# preflight"). V_* are errors: the program cannot be simulated. W_* are
# warnings: simulable, but the modeled OpenMP program is suspect.
V_NO_NESTS = "V_NO_NESTS"  # program has no (sequence of) nests
V_DEPTH = "V_DEPTH"  # nest depth outside 1..MAX_DEPTH
V_PARALLEL_TRIANGULAR = "V_PARALLEL_TRIANGULAR"  # loops[0] not rectangular
V_STEP_ZERO = "V_STEP_ZERO"  # loop step == 0
V_EMPTY_DOMAIN = "V_EMPTY_DOMAIN"  # a level never executes any iteration
V_COEFF_SHAPE = "V_COEFF_SHAPE"  # non-integer / wrongly-shaped affine data
V_REF_LEVEL = "V_REF_LEVEL"  # ref level outside the nest's depth
V_SLOT = "V_SLOT"  # bad slot, or post at the deepest level
V_SHARE = "V_SHARE"  # share_threshold/share_ratio not a positive int
W_RACE = "W_RACE"  # write-involved dependence carried by the parallel loop

ERROR_CODES = frozenset({
    V_NO_NESTS, V_DEPTH, V_PARALLEL_TRIANGULAR, V_STEP_ZERO,
    V_EMPTY_DOMAIN, V_COEFF_SHAPE, V_REF_LEVEL, V_SLOT, V_SHARE,
})


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One machine-readable finding: code + IR path + human message."""

    code: str
    path: str  # e.g. "nests[2].loops[1]", "nests[0].refs[3](B0)"
    message: str
    severity: str = "error"  # "error" | "warning"

    def to_dict(self) -> dict:
        return {
            "code": self.code,
            "path": self.path,
            "message": self.message,
            "severity": self.severity,
        }


def _is_int(v: Any) -> bool:
    return isinstance(v, numbers.Integral) and not isinstance(v, bool)


def _ref_path(ni: int, ri: int, ref: Any) -> str:
    name = getattr(ref, "name", None)
    tag = f"({name})" if isinstance(name, str) else ""
    return f"nests[{ni}].refs[{ri}]{tag}"


def _validate_loop(lp: Any, path: str, parallel: bool,
                   parallel_loop: Any) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    fields = ("trip", "start", "step", "trip_coeff", "start_coeff")
    vals = {f: getattr(lp, f, None) for f in fields}
    bad = [f for f, v in vals.items() if not _is_int(v)]
    if bad:
        return [Diagnostic(V_COEFF_SHAPE, path,
                           f"loop fields must be integers: {bad}")]
    if vals["step"] == 0:
        diags.append(Diagnostic(V_STEP_ZERO, path, "loop step is zero"))
    triangular = vals["trip_coeff"] != 0 or vals["start_coeff"] != 0
    if parallel:
        if triangular:
            diags.append(Diagnostic(
                V_PARALLEL_TRIANGULAR, path,
                "the parallel level (loops[0]) must be rectangular "
                f"(trip_coeff={vals['trip_coeff']}, "
                f"start_coeff={vals['start_coeff']})"))
        elif vals["trip"] < 1:
            diags.append(Diagnostic(
                V_EMPTY_DOMAIN, path,
                f"parallel trip {vals['trip']} < 1: no iterations"))
        return diags
    if not triangular:
        if vals["trip"] < 1:
            diags.append(Diagnostic(
                V_EMPTY_DOMAIN, path,
                f"trip {vals['trip']} < 1: the level never executes"))
        return diags
    # triangular inner level: empty only if trip_at(v0) < 1 for EVERY
    # parallel value (trisolv's j-loop is legitimately empty at i=0)
    if parallel_loop is not None and vals["step"] != 0:
        p_trip = getattr(parallel_loop, "trip", None)
        p_start = getattr(parallel_loop, "start", None)
        p_step = getattr(parallel_loop, "step", None)
        if all(_is_int(v) for v in (p_trip, p_start, p_step)) and p_trip >= 1:
            ends = (p_start, p_start + (p_trip - 1) * p_step)
            max_trip = max(vals["trip"] + vals["trip_coeff"] * v0
                           for v0 in ends)
            if max_trip < 1:
                diags.append(Diagnostic(
                    V_EMPTY_DOMAIN, path,
                    f"triangular trip {vals['trip']}"
                    f"{vals['trip_coeff']:+d}*v0 < 1 for every parallel "
                    "value: the level never executes"))
    return diags


def _validate_ref(ref: Any, path: str, depth: int) -> list[Diagnostic]:
    diags: list[Diagnostic] = []
    name = getattr(ref, "name", None)
    array = getattr(ref, "array", None)
    if not isinstance(name, str) or not isinstance(array, str):
        diags.append(Diagnostic(V_COEFF_SHAPE, path,
                                "ref name/array must be strings"))
    level = getattr(ref, "level", None)
    if not _is_int(level):
        return diags + [Diagnostic(V_COEFF_SHAPE, path,
                                   "ref level must be an integer")]
    if level < 0 or level >= MAX_DEPTH or (depth > 0 and level >= depth):
        hi = min(MAX_DEPTH, depth) if depth > 0 else MAX_DEPTH
        diags.append(Diagnostic(
            V_REF_LEVEL, path,
            f"ref level {level} outside [0,{hi}) for this nest"))
        return diags
    coeffs = getattr(ref, "coeffs", None)
    if (not isinstance(coeffs, (tuple, list))
            or len(coeffs) != level + 1
            or not all(_is_int(c) for c in coeffs)):
        diags.append(Diagnostic(
            V_COEFF_SHAPE, path,
            f"coeffs must be {level + 1} integers (level+1); got "
            f"{coeffs!r}"))
    if not _is_int(getattr(ref, "const", 0)):
        diags.append(Diagnostic(V_COEFF_SHAPE, path,
                                "ref const must be an integer"))
    slot = getattr(ref, "slot", "pre")
    if slot not in ("pre", "post"):
        diags.append(Diagnostic(V_SLOT, path,
                                f"slot must be 'pre' or 'post', got {slot!r}"))
    elif depth > 0 and level == depth - 1 and slot == "post":
        diags.append(Diagnostic(
            V_SLOT, path,
            "deepest level has no subloop; use slot='pre'"))
    for f in ("share_threshold", "share_ratio"):
        v = getattr(ref, f, None)
        if v is not None and (not _is_int(v) or v < 1):
            diags.append(Diagnostic(
                V_SHARE, path, f"{f} must be a positive integer, got {v!r}"))
    w = getattr(ref, "write", None)
    if w is not None and not isinstance(w, bool):
        diags.append(Diagnostic(
            V_COEFF_SHAPE, path, f"write must be True/False/None, got {w!r}"))
    return diags


def validate_program(program: Any) -> list[Diagnostic]:
    """All structural diagnostics for a (possibly duck-typed) program.

    Never raises: malformed shapes come back as V_COEFF_SHAPE /
    V_NO_NESTS diagnostics so the service can reject with a structured
    error instead of a traceback.
    """
    nests = getattr(program, "nests", None)
    if not isinstance(nests, (tuple, list)) or len(nests) == 0:
        return [Diagnostic(V_NO_NESTS, "program",
                           "program needs at least one parallel nest")]
    diags: list[Diagnostic] = []
    for ni, nest in enumerate(nests):
        npath = f"nests[{ni}]"
        loops = getattr(nest, "loops", None)
        refs = getattr(nest, "refs", None)
        if not isinstance(loops, (tuple, list)) or not isinstance(
                refs, (tuple, list)):
            diags.append(Diagnostic(
                V_COEFF_SHAPE, npath,
                "nest must carry loops and refs sequences"))
            continue
        if not 1 <= len(loops) <= MAX_DEPTH:
            diags.append(Diagnostic(
                V_DEPTH, npath,
                f"nest depth {len(loops)} outside 1..{MAX_DEPTH}"))
            continue
        parallel_loop = loops[0]
        for li, lp in enumerate(loops):
            diags.extend(_validate_loop(
                lp, f"{npath}.loops[{li}]", parallel=(li == 0),
                parallel_loop=parallel_loop))
        for ri, ref in enumerate(refs):
            diags.extend(_validate_ref(
                ref, _ref_path(ni, ri, ref), depth=len(loops)))
    return diags


def canonicalize(program: Any) -> Program:
    """Rebuild a validated duck-typed program as real ir dataclasses
    (coercing numpy ints etc. to python ints). Raises ValueError with
    the first diagnostic when the program is invalid."""
    diags = [d for d in validate_program(program) if d.severity == "error"]
    if diags:
        d = diags[0]
        raise ValueError(f"{d.code} at {d.path}: {d.message}")
    nests = []
    for nest in program.nests:
        loops = tuple(
            Loop(trip=int(lp.trip), start=int(lp.start), step=int(lp.step),
                 trip_coeff=int(lp.trip_coeff),
                 start_coeff=int(lp.start_coeff))
            for lp in nest.loops)
        refs = tuple(
            Ref(name=str(r.name), array=str(r.array), level=int(r.level),
                coeffs=tuple(int(c) for c in r.coeffs),
                const=int(getattr(r, "const", 0)),
                slot=str(getattr(r, "slot", "pre")),
                share_threshold=(None if getattr(r, "share_threshold", None)
                                 is None else int(r.share_threshold)),
                share_ratio=(None if getattr(r, "share_ratio", None) is None
                             else int(r.share_ratio)),
                write=(None if getattr(r, "write", None) is None
                       else bool(r.write)))
            for r in nest.refs)
        nests.append(ParallelNest(loops=loops, refs=refs))
    return Program(name=str(program.name), nests=tuple(nests))


# ---------------------------------------------------------------------------
# Structural signatures (size-invariant program shape).
# ---------------------------------------------------------------------------


def _coeff_class(v: int) -> object:
    """{0, 1, -1, "+", "-"}: literal unit strides stay distinguishable
    from size-derived strides (n, n*n, ...) at any practical size."""
    if v in (0, 1, -1):
        return v
    return "+" if v > 0 else "-"


def _sign_class(v: int) -> object:
    return 0 if v == 0 else ("+" if v > 0 else "-")


def _loop_signature(lp: Loop) -> tuple:
    step = lp.step if lp.step in (1, -1) else ("+" if lp.step > 0 else "-")
    return (step, _sign_class(lp.start), _sign_class(lp.trip_coeff),
            _sign_class(lp.start_coeff))


def _ref_signature(ref: Ref, array_ids: dict[str, int]) -> tuple:
    return (
        array_ids[ref.array],
        ref.level,
        tuple(_coeff_class(c) for c in ref.coeffs),
        _coeff_class(ref.const),
        ref.slot,
        ref.share_threshold is not None,
    )


def structural_signature(program: Program) -> tuple:
    """Size- and tsteps-invariant shape of a program.

    Nest signatures are deduplicated in first-seen order so time-model
    unrollings ((nest_b, nest_a) * tsteps) collapse to one period; array
    identity is program-wide first-occurrence order so multi-nest
    producer/consumer structure (2mm vs gemm) stays distinguishable.
    """
    array_ids: dict[str, int] = {}
    for nest in program.nests:
        for r in nest.refs:
            array_ids.setdefault(r.array, len(array_ids))
    seen: dict[tuple, None] = {}
    for nest in program.nests:
        sig = (
            len(nest.loops),
            tuple(_loop_signature(lp) for lp in nest.loops),
            tuple(_ref_signature(r, array_ids) for r in nest.refs),
        )
        seen.setdefault(sig, None)
    return tuple(seen)


# ---------------------------------------------------------------------------
# Malformed fixtures (shared by tests and tools/check_ir.py --fixtures).
# ---------------------------------------------------------------------------


class _Bag:
    """Attribute bag standing in for ir dataclasses: lets fixtures
    express invariant violations the real constructors would reject."""

    def __init__(self, **kw: Any) -> None:
        self.__dict__.update(kw)


def _bag_loop(trip: int = 4, start: int = 0, step: int = 1,
              trip_coeff: int = 0, start_coeff: int = 0) -> _Bag:
    return _Bag(trip=trip, start=start, step=step, trip_coeff=trip_coeff,
                start_coeff=start_coeff)


def _bag_ref(name: str = "R0", array: str = "A", level: int = 0,
             coeffs: Any = (1,), const: Any = 0, slot: str = "pre",
             share_threshold: Optional[int] = None,
             share_ratio: Optional[int] = None) -> _Bag:
    return _Bag(name=name, array=array, level=level, coeffs=coeffs,
                const=const, slot=slot, share_threshold=share_threshold,
                share_ratio=share_ratio)


def _bag_nest(loops: Iterable[Any], refs: Iterable[Any]) -> _Bag:
    return _Bag(loops=tuple(loops), refs=tuple(refs))


def malformed_fixtures() -> dict[str, tuple[Any, str]]:
    """name -> (program-like object, expected diagnostic code)."""
    return {
        "depth_overflow": (
            _Bag(name="bad-depth", nests=(_bag_nest(
                [_bag_loop()] * (MAX_DEPTH + 1),
                [_bag_ref()]),)),
            V_DEPTH),
        "parallel_triangular": (
            _Bag(name="bad-par", nests=(_bag_nest(
                [_bag_loop(trip_coeff=1), _bag_loop()],
                [_bag_ref(level=1, coeffs=(4, 1))]),)),
            V_PARALLEL_TRIANGULAR),
        "empty_domain": (
            _Bag(name="bad-empty", nests=(_bag_nest(
                [_bag_loop(trip=0)], [_bag_ref()]),)),
            V_EMPTY_DOMAIN),
        "empty_triangular": (
            _Bag(name="bad-empty-tri", nests=(_bag_nest(
                [_bag_loop(trip=4), _bag_loop(trip=0, trip_coeff=-1)],
                [_bag_ref(level=1, coeffs=(4, 1))]),)),
            V_EMPTY_DOMAIN),
        "coeff_shape": (
            _Bag(name="bad-coeffs", nests=(_bag_nest(
                [_bag_loop(), _bag_loop()],
                [_bag_ref(level=1, coeffs=(1.5, 2.0))]),)),
            V_COEFF_SHAPE),
        "coeff_length": (
            _Bag(name="bad-coeff-len", nests=(_bag_nest(
                [_bag_loop(), _bag_loop()],
                [_bag_ref(level=1, coeffs=(4, 1, 1))]),)),
            V_COEFF_SHAPE),
        "step_zero": (
            _Bag(name="bad-step", nests=(_bag_nest(
                [_bag_loop(step=0)], [_bag_ref()]),)),
            V_STEP_ZERO),
        "ref_too_deep": (
            _Bag(name="bad-level", nests=(_bag_nest(
                [_bag_loop()],
                [_bag_ref(level=2, coeffs=(4, 1, 1))]),)),
            V_REF_LEVEL),
        "bad_slot": (
            _Bag(name="bad-slot", nests=(_bag_nest(
                [_bag_loop()], [_bag_ref(slot="mid")]),)),
            V_SLOT),
        "bad_share": (
            _Bag(name="bad-share", nests=(_bag_nest(
                [_bag_loop()], [_bag_ref(share_threshold=0)]),)),
            V_SHARE),
        "no_nests": (_Bag(name="bad-empty-prog", nests=()), V_NO_NESTS),
    }
