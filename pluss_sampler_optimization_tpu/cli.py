"""Command-line driver — the run.sh / Makefile / main() layer (L4).

The reference drives everything through `sh run.sh {acc|speed}` and
`make {acc|speed|sample}` (run.sh:3-12, c_lib/test/Makefile:34-44),
with per-binary main()s selecting the mode
(...ri-omp.cpp:334-360, src/main.rs:17-44). One CLI replaces them:

  python -m pluss_sampler_optimization_tpu acc    --model gemm --n 128
  python -m pluss_sampler_optimization_tpu speed  --engine dense --reps 10
  python -m pluss_sampler_optimization_tpu sample --ratio 0.1 --mrc-out f

- `acc`: one run, then the reference's accuracy dumps — noshare/share
  private-reuse histograms, the distributed RI histogram, the MRC, and
  the max-iteration count (...ri-omp-seq.cpp:334-362). Engines are
  interchangeable so dumps can be diffed across implementations exactly
  like the reference's output.txt protocol (README.md:10-12).
- `speed`: N timed repetitions (Makefile:34-37 runs 10).
- `sample`: the sampled r10-equivalent path with per-ref dumps and the
  merged histogram + MRC (...rs-ri-opt-r10.cpp:3277-3293).
"""

from __future__ import annotations

import argparse
import sys


def _build_model(name: str, n: int, tsteps: int):
    from .models import build

    try:
        return build(name, n, tsteps)
    except (KeyError, ValueError) as e:
        raise SystemExit(str(e.args[0] if e.args else e))


def _dump_ir(args) -> int:
    """`--dump-ir MODEL` / `--dump-ir-dir DIR`: registry models as
    frontend JSON documents — copy-paste templates for custom nests,
    pinned (tests/test_frontend.py) to parse back fingerprint-
    identical to the registry request."""
    import json as _json
    import os

    from .frontend.schema import program_to_json
    from .models import REGISTRY

    if args.dump_ir:
        prog = _build_model(args.dump_ir, args.n, args.tsteps)
        print(_json.dumps(program_to_json(prog), indent=2))
        return 0
    os.makedirs(args.dump_ir_dir, exist_ok=True)
    for name in sorted(REGISTRY):
        try:
            prog = _build_model(name, args.n, args.tsteps)
        except SystemExit:
            # models without a time axis reject --tsteps != 1; dump
            # them at their only valid tsteps instead of skipping
            prog = _build_model(name, args.n, 1)
        path = os.path.join(args.dump_ir_dir, f"{name}.json")
        with open(path, "w") as f:
            _json.dump(program_to_json(prog), f, indent=2)
            f.write("\n")
        print(f"{name:<12} -> {path}")
    return 0


def _load_program_json(args, machine):
    """Load + strictly parse a frontend document for --program-json.

    Returns (program, machine-with-document-knobs) and rewrites
    args.model/"_program_doc" so ledger rows say model:"custom" and
    service-routed requests carry the document inline. Rejections
    exit with the same diagnostics serve returns for the document."""
    import json as _json

    from .frontend.parse import parse_program_doc
    from .frontend.schema import machine_from_doc

    try:
        with open(args.program_json) as f:
            doc = _json.load(f)
    except (OSError, ValueError) as e:
        raise SystemExit(
            f"cannot read program JSON {args.program_json!r}: {e}"
        )
    res = parse_program_doc(doc)
    if not res.ok:
        lines = [f"{args.program_json}: frontend rejected program"]
        lines += [
            f"  [{d.severity}] {d.code} at {d.path or '/'}: "
            f"{d.message}"
            for d in res.errors()
        ]
        raise SystemExit("\n".join(lines))
    args.model = "custom"
    args._program_doc = doc
    return res.program, machine_from_doc(doc, machine)


def _list_models() -> int:
    """The 18-model registry with family/engine-audit status: which
    exact-router families are PROVEN bit-identical through the
    analytic route (sampler/analytic.py::AUDITED_FAMILIES) and which
    inherit the probe-backed ledger."""
    from .models import REGISTRY, build
    from .sampler.analytic import audited_family

    rows = []
    for name in sorted(REGISTRY):
        prog = build(name, 8)
        rows.append((
            name,
            len(prog.nests),
            sum(len(nest.refs) for nest in prog.nests),
            max(nest.depth for nest in prog.nests),
            any(nest.is_triangular for nest in prog.nests),
            audited_family(prog.name),
        ))
    print(f"{'model':<12} {'nests':>5} {'refs':>4} {'depth':>5} "
          f"{'triangular':>10} {'analytic-audit':>14}")
    for name, nests, refs, depth, tri, audited in rows:
        print(f"{name:<12} {nests:>5} {refs:>4} {depth:>5} "
              f"{'yes' if tri else 'no':>10} "
              f"{'audited' if audited else 'probe-backed':>14}")
    print(
        f"{len(rows)} models; 'audited' = exact-router analytic "
        "exactness proven by tests/test_analytic.py or recorded "
        "tools/verify_analytic.py audits (README \"Exactness "
        "coverage\")"
    )
    return 0


def _run_engine(engine: str, program, machine, args):
    """One run -> (OracleResult-like, per-ref sampled results or None)."""
    if engine == "oracle":
        from .oracle.serial import run_serial

        return run_serial(
            program, machine, v2=args.runtime == "v2",
            schedule=args.schedule,
        ), None
    if args.schedule == "dynamic":
        raise SystemExit(
            "--schedule dynamic is modeled by the oracle engine only "
            "(the reference's dynamic dispatcher arm is dead code with "
            "no live sampler; use --engine oracle)"
        )
    if engine == "numpy":
        from .oracle.numpy_ref import run_numpy

        return run_numpy(program, machine), None
    if engine == "native":
        from . import native

        return native.run_serial_native(program, machine), None
    if engine == "native-par":
        from . import native

        return native.run_parallel_native(program, machine), None
    if engine in ("periodic", "analytic", "exact") and args.shard:
        from .parallel import (
            build_mesh,
            run_analytic_sharded,
            run_exact_sharded,
            run_periodic_sharded,
        )

        fn = {
            "periodic": run_periodic_sharded,
            "analytic": run_analytic_sharded,
            "exact": run_exact_sharded,
        }[engine]
        return fn(program, machine, build_mesh()), None
    if engine == "dense":
        from .sampler.dense import run_dense

        return run_dense(program, machine), None
    if engine == "stream":
        from .sampler.stream import run_stream

        return run_stream(program, machine), None
    if engine == "periodic":
        from .sampler.periodic import run_periodic

        return run_periodic(program, machine), None
    if engine == "exact":
        from .sampler.periodic import run_exact

        return run_exact(program, machine), None
    if engine == "analytic":
        from .sampler.analytic import run_analytic

        return run_analytic(program, machine), None
    if engine in ("sampled", "sharded"):
        from .config import SamplerConfig

        kw = {}
        if args.pallas_hist is not None:  # None = keep config default
            kw["use_pallas_hist"] = args.pallas_hist
        if args.device_draw is not None:  # None = auto per backend
            kw["device_draw"] = args.device_draw
        if args.fuse_refs is not None:  # None = keep config default
            kw["fuse_refs"] = args.fuse_refs
        if args.kernel_backend is not None:  # None = auto
            kw["kernel_backend"] = args.kernel_backend
        if args.pipeline_depth is not None:
            kw["pipeline_depth"] = args.pipeline_depth
        progressive = any(
            v is not None for v in (args.tolerance, args.max_rounds,
                                    args.round_schedule)
        )
        if args.tolerance is not None:
            kw["tolerance"] = args.tolerance
        if args.max_rounds is not None:
            kw["max_rounds"] = args.max_rounds
        if args.round_schedule is not None:
            kw["round_schedule"] = _parse_round_schedule(
                args.round_schedule
            )
        cfg = SamplerConfig(ratio=args.ratio, seed=args.seed, **kw)
        v2 = args.runtime == "v2"
        if engine == "sampled" and progressive:
            from .sampler.sampled import run_sampled_progressive

            state, results, info = run_sampled_progressive(
                program, machine, cfg, v2=v2,
            )
            print(
                f"progressive: rounds "
                f"{info['rounds']}/{info['rounds_total']}, band "
                f"{info['band_width']:.6f}, converged "
                f"{info['converged']}",
                file=sys.stderr,
            )
        elif engine == "sampled":
            from .sampler.sampled import run_sampled

            state, results = run_sampled(
                program, machine, cfg, v2=v2,
                checkpoint_dir=args.checkpoint_dir,
            )
        else:
            from .parallel import build_mesh, run_sampled_sharded

            state, results = run_sampled_sharded(
                program, machine, cfg, build_mesh(), v2=v2
            )

        import types

        # sampled engines track samples, not accesses
        res = types.SimpleNamespace(
            state=state,
            total_accesses=sum(r.n_samples for r in results),
        )
        return res, results
    raise SystemExit(f"unknown engine {engine!r}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="pluss_sampler_optimization_tpu")
    ap.add_argument("mode", nargs="?",
                    choices=["acc", "speed", "sample", "trace",
                             "serve", "serve-worker", "serve-router",
                             "stats", "analyze"])
    ap.add_argument("--list-models", action="store_true",
                    help="print the model registry (nest/ref geometry "
                    "+ exact-router analytic audit status, from "
                    "sampler/analytic.py::AUDITED_FAMILIES) and exit")
    ap.add_argument("--model", default="gemm",
                    help="gemm | 2mm | 3mm | syrk | jacobi-2d | mvt | bicg "
                    "| gesummv | atax | gemver | doitgen | fdtd-2d | heat-3d"
                    " | syrk-tri | trmm | trisolv | covariance | adi")
    ap.add_argument("--n", type=int, default=128)
    ap.add_argument("--tsteps", type=int, default=1,
                    help="time steps (jacobi-2d, fdtd-2d, heat-3d, adi)")
    ap.add_argument("--dump-ir", default=None, metavar="MODEL",
                    help="print MODEL's canonical IR as a frontend "
                    "JSON document (at --n/--tsteps) and exit; the "
                    "dump round-trips through --program-json / the "
                    "serve 'program' field fingerprint-identically "
                    "to the registry request")
    ap.add_argument("--dump-ir-dir", default=None, metavar="DIR",
                    help="write every registry model's frontend JSON "
                    "to DIR/<model>.json (at --n) and exit")
    ap.add_argument("--program-json", default=None, metavar="PATH",
                    help="load the program from a frontend JSON "
                    "document instead of the model registry "
                    "(acc|speed|sample|analyze; overrides --model/"
                    "--n/--tsteps; document machine knobs override "
                    "--threads/--chunk). Rejections print the same "
                    "machine-readable diagnostics the serve path "
                    "returns")
    ap.add_argument(
        "--engine",
        default=None,
        help="oracle | numpy | native | native-par | dense | stream | "
        "periodic | analytic | exact | sampled | sharded (default: "
        "dense; sample mode forces sampled; 'exact' picks the fastest "
        "applicable exact engine: periodic when its preconditions "
        "hold, then analytic (closed-form next-use per period — covers "
        "triangular nests and mixed parallel coefficients), else dense "
        "with its memory auto-route. Exactness is PROVEN bit-identical "
        "for the model families pinned in tests/test_analytic.py and "
        "the recorded tools/verify_analytic.py audits; other families "
        "routed to analytic inherit its probe-backed verification — "
        "run tools/verify_analytic.py once per new (program, machine) "
        "to remove the residual assumption)",
    )
    ap.add_argument("--shard", action="store_true",
                    help="run the exact engines (periodic|analytic|"
                    "exact) mesh-sharded over all devices: periodic "
                    "lays its window axis over the mesh, analytic "
                    "shards every classify dispatch's key axis; "
                    "results are bit-identical to the single-device "
                    "run (the sampled engine's mesh path is "
                    "--engine sharded)")
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=4)
    ap.add_argument("--schedule", choices=["static", "dynamic"],
                    default="static",
                    help="chunk ownership: static round-robin (the "
                    "reference's live path) or the FIFO dynamic "
                    "dispatcher arm (oracle engine only; equals "
                    "static for rectangular nests)")
    ap.add_argument("--ratio", type=float, default=0.1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--pallas-hist", default=None,
                    action=argparse.BooleanOptionalAction,
                    help="sharded engine: reduce histograms with the "
                    "Pallas TPU kernel instead of the portable "
                    "scatter-add (config default: ON since the "
                    "2026-07-31 on-device measurement — bit-equal, "
                    "4.4x; the kernel only ever engages on a TPU "
                    "backend)")
    ap.add_argument("--device-draw", default=None,
                    action=argparse.BooleanOptionalAction,
                    help="sampled/sharded engines: draw sample keys "
                    "on the device with the threefry PRNG instead of "
                    "numpy on the host (default: auto — ON for "
                    "accelerator backends, OFF for CPU; each is that "
                    "backend's measured best, see "
                    "SamplerConfig.device_draw)")
    ap.add_argument("--fuse-refs", default=None,
                    action=argparse.BooleanOptionalAction,
                    help="sampled/sharded engines: stack refs sharing "
                    "a kernel-signature bucket into ONE vmapped "
                    "dispatch per bucket (default: auto per backend — "
                    "ON off-CPU, OFF on CPU; results are bit-identical "
                    "either way — --no-fuse-refs keeps the per-ref "
                    "serial loop as the parity oracle)")
    ap.add_argument("--kernel-backend", default=None,
                    choices=["auto", "xla", "pallas", "native"],
                    help="sampled engine: which classify+histogram "
                    "kernel runs the hot loop — xla (scan/fused jit, "
                    "the parity oracle), pallas (fused on-chip "
                    "histogram kernel, interpret mode on CPU), native "
                    "(SIMD C++ batched classify+reduce via ctypes, "
                    "CPU only), or auto (default: native on CPU when "
                    "the shared library builds, xla otherwise). All "
                    "backends produce bit-identical MRCs; the choice "
                    "stays out of the request fingerprint like "
                    "--fuse-refs")
    ap.add_argument("--pipeline-depth", type=int, default=None,
                    help="sampled engine: max in-flight dispatches "
                    "awaiting their device->host fetch before the "
                    "oldest is drained (config default: 4; forced "
                    "drains count as pipeline_stalls in telemetry)")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="sampled engine: run progressively — rounds "
                    "of increasing sample-stream prefixes — and stop "
                    "early once the bootstrap MRC confidence band is "
                    "narrower than this width (0 disables early stop "
                    "but still streams per-round bands; a full "
                    "schedule is bit-identical to the one-shot run). "
                    "Out of the request fingerprint like --fuse-refs")
    ap.add_argument("--max-rounds", type=int, default=None,
                    help="progressive sampled engine: schedule length "
                    "when --round-schedule is not given (geometric "
                    "doubling 1/2^(R-1)..1; default 4)")
    ap.add_argument("--round-schedule", default=None,
                    help="progressive sampled engine: explicit "
                    "comma-separated increasing fractions of the "
                    "final sample count, ending at 1.0 — e.g. "
                    "0.25,0.5,1.0")
    ap.add_argument("--reps", type=int, default=10)
    ap.add_argument("--tid", type=int, default=0, help="trace mode thread")
    ap.add_argument("--min-reuse", type=int, default=512,
                    help="trace mode reuse-pair threshold (DEBUG >= 512)")
    ap.add_argument("--limit", type=int, default=50,
                    help="trace mode row limit")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="sample mode: persist finished per-ref results "
                    "here and resume an interrupted run")
    ap.add_argument("--mrc-out", default=None,
                    help="also write the MRC to this file")
    ap.add_argument("--analysis-json", action="store_true",
                    help="analyze mode: emit the full machine-"
                    "readable analysis report (diagnostics, "
                    "classified dependences, bounds) as JSON instead "
                    "of the summary table")
    ap.add_argument("--diff-against", default=None, metavar="ENGINE",
                    help="run a second engine and fail unless its dumps "
                    "are byte-identical (automates the reference's "
                    "output.txt diff protocol; compare full-traversal "
                    "engines with each other, or sampled with sharded)")
    ap.add_argument(
        "--runtime",
        choices=["v1", "v2"],
        default="v1",
        help="histogram runtime semantics: v1 pow2-bins noshare on "
        "insertion (pluss_utils.h:924-927), v2 keeps raw keys "
        "(pluss_utils_v2.h:915-918). oracle/sampled/sharded engines.",
    )
    ap.add_argument(
        "--r10",
        action="store_true",
        help="sample mode: distribute with the r10 generated-code quirk "
        "copies per reference (...rs-ri-opt-r10.cpp:42-131) instead of "
        "the runtime-v1 CRI model",
    )
    ap.add_argument(
        "--platform",
        default=None,
        help="JAX platform override (e.g. cpu). Must be applied before "
        "any backend initializes; plain env vars are too late when a "
        "site pins a TPU plugin (see tests/conftest.py).",
    )
    ap.add_argument(
        "--telemetry-out",
        default=None,
        metavar="PATH",
        help="record engine-stage spans, dispatch/fetch counters, "
        "jax compile events, and device/host metrics for this run and "
        "write them as structured JSON to PATH (schema: README "
        "\"Observability\"; validate with "
        "tools/check_telemetry_schema.py). A compact summary prints "
        "to stderr. Works in every mode.",
    )
    ap.add_argument(
        "--profile-dir",
        default=None,
        metavar="PATH",
        help="wrap the run in jax.profiler.trace(PATH) and write a "
        "Perfetto/XLA trace there (open at ui.perfetto.dev or via "
        "TensorBoard). Independent of --telemetry-out.",
    )
    ap.add_argument(
        "--trace-out",
        default=None,
        metavar="PATH",
        help="export this run's telemetry span tree as Chrome "
        "trace_event JSON at PATH — load it in Perfetto "
        "(ui.perfetto.dev) or chrome://tracing. Span nesting and "
        "device-sync timings are preserved; works in every mode "
        "(README \"Observability\").",
    )
    ap.add_argument(
        "--metrics-out",
        default=None,
        metavar="PATH",
        help="export this run's telemetry counters/gauges as "
        "Prometheus text exposition at PATH (counters as *_total, "
        "plus the run duration) — suits the node-exporter textfile "
        "collector. Works in every mode.",
    )
    ap.add_argument(
        "--ledger",
        default=None,
        metavar="PATH",
        help="append one row per engine/service execution to this "
        "JSONL run ledger (schema-versioned; fingerprint, engine, "
        "latency, cache tier, degradation chain, compile deltas, MRC "
        "digest). acc/speed/sample append directly (or via the "
        "service under --cache-dir), serve appends per request; "
        "`stats` mode aggregates a ledger and "
        "tools/check_ledger.py validates/GCs it.",
    )
    ap.add_argument(
        "--cache-dir",
        default=None,
        metavar="DIR",
        help="serve results through the analysis service's "
        "content-addressed store rooted at DIR (serve mode, and "
        "acc/speed/sample for the plain request pipeline): a repeated "
        "request returns the stored bit-identical result with zero "
        "engine work. See README \"Serving\".",
    )
    ap.add_argument(
        "--deadline-s",
        type=float,
        default=None,
        metavar="SECONDS",
        help="per-request deadline for service-routed runs "
        "(--cache-dir / serve mode): an engine overrunning it "
        "degrades down the chain (exact -> sampled, ...), recorded "
        "in the response and as a telemetry event",
    )
    ap.add_argument(
        "--requests",
        default="-",
        metavar="PATH",
        help="serve mode: JSONL request batch to process ('-' = "
        "stdin; one JSON request object per line, README \"Serving\")",
    )
    ap.add_argument(
        "--responses",
        default="-",
        metavar="PATH",
        help="serve mode: where to write the JSONL responses "
        "('-' = stdout)",
    )
    ap.add_argument(
        "--max-workers",
        type=int,
        default=4,
        metavar="N",
        help="serve mode: concurrent request executions (bounded "
        "pool; identical in-flight requests coalesce regardless)",
    )
    ap.add_argument(
        "--batch-window-ms",
        type=float,
        default=None,
        metavar="MS",
        help="service-routed runs (--cache-dir / serve mode): hold "
        "compatible concurrent sampled requests in an admission "
        "window up to MS milliseconds and run each flushed window as "
        "ONE batched engine execution over the union of their kernel "
        "buckets. Every member's MRC stays bit-identical to its solo "
        "run, so this is a pure latency-for-throughput knob (default: "
        "off). See README \"Cross-request batching\".",
    )
    ap.add_argument(
        "--batch-max-refs",
        type=int,
        default=64,
        metavar="N",
        help="with --batch-window-ms: flush a forming batch early "
        "once its summed tracked-ref count reaches N; overflow "
        "requests start the next batch (default: 64)",
    )
    ap.add_argument(
        "--replicas",
        type=int,
        default=None,
        metavar="K",
        help="service-routed runs (--cache-dir / serve mode): "
        "partition the devices into K independent replica executors "
        "(each with its own device group, mesh, and queue) and route "
        "every execution to the least-loaded one, with work stealing "
        "and failure quarantine. 0 = auto (one replica per device). "
        "Pure scheduling: MRC bytes are bit-identical for any K. "
        "Default: no pool (the single-device-set path). See README "
        "\"Replica serving\".",
    )
    ap.add_argument(
        "--fault-spec",
        default=None,
        metavar="FILE",
        help="serve mode: arm deterministic fault injection from a "
        "JSON spec ({\"seed\": S, \"rules\": [{\"site\": ..., "
        "\"kind\": ..., \"p\": ..., ...}]}). Sites: engine_execute, "
        "replica_dispatch, cache_load, cache_store, serve_line; "
        "kinds: raise, latency, hang, corrupt, compile_failure. "
        "Decisions come from a seeded counter hash, so a chaos run "
        "replays exactly from (seed, spec). See README \"Overload, "
        "retries & chaos testing\".",
    )
    ap.add_argument(
        "--attempt-timeout-s",
        type=float,
        default=None,
        metavar="SECONDS",
        help="service-routed runs (--cache-dir / serve mode): bound "
        "every engine attempt to SECONDS (tighter of this and the "
        "request deadline); an overrun attempt is abandoned and — "
        "with --max-retries — retried with seeded exponential "
        "backoff. Default: attempts are bounded by the request "
        "deadline only.",
    )
    ap.add_argument(
        "--max-retries",
        type=int,
        default=None,
        metavar="N",
        help="service-routed runs: retry a failed or timed-out "
        "engine attempt up to N times (deterministic seeded backoff "
        "jitter — replays exactly) before degrading down the chain "
        "(default: 0, no retries)",
    )
    ap.add_argument(
        "--hedge-after-s",
        type=float,
        default=None,
        metavar="SECONDS",
        help="service-routed runs with >= 2 replicas: duplicate a "
        "dispatch still unresolved after SECONDS onto a second "
        "replica; first result wins, the queued loser is cancelled. "
        "Results are bit-identical either way (tail-latency "
        "insurance only). Default: no hedging.",
    )
    ap.add_argument(
        "--queue-limit",
        type=int,
        default=None,
        metavar="N",
        help="service-routed runs: admission control — shed a "
        "submission (structured `shed: true` response in "
        "microseconds) when the executor queue is already N deep "
        "for its priority class (low sheds at 50%% of N, normal at "
        "75%%, high at 100%%). Default: unbounded queue, no "
        "shedding.",
    )
    ap.add_argument(
        "--no-shed",
        action="store_true",
        help="with --queue-limit: disable the shedding gate (keep "
        "the limit configured but admit everything) — the overload "
        "baseline tools/check_chaos.py and bench.py compare against",
    )
    ap.add_argument(
        "--breaker-failures",
        type=int,
        default=None,
        metavar="N",
        help="service-routed runs: consecutive failures that OPEN a "
        "per-engine/per-replica circuit breaker (default: 8)",
    )
    ap.add_argument(
        "--breaker-probation-s",
        type=float,
        default=None,
        metavar="SECONDS",
        help="service-routed runs: how long an open breaker fails "
        "fast before admitting one half-open probe; a failed probe "
        "re-opens with the probation escalated (default: 30)",
    )
    ap.add_argument(
        "--warmup-from-ledger",
        type=int,
        default=None,
        metavar="N",
        help="serve mode, with --ledger: before processing requests, "
        "pre-compile the sampled kernel signatures of the N most "
        "frequent fingerprints in the ledger — the first real request "
        "after a restart skips cold jit (its ledger row records "
        "near-zero compile deltas)",
    )
    ap.add_argument(
        "--compilation-cache-dir",
        default=None,
        metavar="DIR",
        help="persist XLA-compiled executables under DIR (wires "
        "jax_compilation_cache_dir with the min compile-time "
        "threshold dropped to 0): a warm second process loads "
        "executables instead of recompiling. Applies to every "
        "engine-executing mode.",
    )
    ap.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="serve mode: expose the live metrics registry on "
        "http://127.0.0.1:PORT/metrics in Prometheus text format "
        "(counters with rolling 30s/5m windows, gauges, per-stage "
        "request latency histograms with trace-id exemplars). 0 "
        "binds an ephemeral port, printed to stderr. The registry "
        "itself is always on in serve mode; this flag only adds the "
        "scrape endpoint. See README \"Live metrics & SLOs\".",
    )
    ap.add_argument(
        "--profile-hz",
        type=float,
        default=None,
        metavar="HZ",
        help="serve mode: run the sampling wall-clock profiler — a "
        "background thread samples every live thread's Python stack "
        "HZ times a second, tags each sample with the thread's "
        "current telemetry span path (draw/dispatch/fetch/merge/"
        "queue/... or 'unattributed'), and folds them into bounded "
        "collapsed-stack counts. Scrape the live snapshot at "
        "GET /debug/profile (with --metrics-port); anomaly "
        "post-mortem bundles carry it too. Default: off. See README "
        "\"Continuous profiling & utilization\".",
    )
    ap.add_argument(
        "--profile-out",
        default=None,
        metavar="PATH",
        help="with --profile-hz: at serve exit, write the collected "
        "profile as speedscope-compatible JSON to PATH (drop it on "
        "https://www.speedscope.app) and the collapsed-stack text "
        "to PATH + '.collapsed'",
    )
    ap.add_argument(
        "--slo-latency-p95-s",
        type=float,
        default=None,
        metavar="SECONDS",
        help="serve mode: run the SLO sentinel with a total-latency "
        "objective — at most 5%% of requests may exceed SECONDS; a "
        "multi-window burn rate above --slo-burn-threshold in BOTH "
        "rolling windows emits slo_breach telemetry",
    )
    ap.add_argument(
        "--slo-error-budget",
        type=float,
        default=None,
        metavar="FRACTION",
        help="serve mode: run the SLO sentinel with an error "
        "objective — at most FRACTION of requests may fail or "
        "complete degraded (burn-rate semantics as above)",
    )
    ap.add_argument(
        "--slo-burn-threshold",
        type=float,
        default=1.0,
        metavar="X",
        help="SLO sentinel burn-rate trip point (default 1.0 = "
        "budget consumed exactly as fast as the objective allows)",
    )
    ap.add_argument(
        "--slo-interval-s",
        type=float,
        default=10.0,
        metavar="SECONDS",
        help="SLO sentinel evaluation period (default 10); a final "
        "evaluation always runs when the serve batch completes",
    )
    ap.add_argument(
        "--debug-bundle-dir",
        default=None,
        metavar="DIR",
        help="serve mode: run the flight recorder — a bounded ring "
        "of per-request records with tail-based retention (errors, "
        "degradations, drift breaches, latency outliers kept) that "
        "writes an atomic schema-versioned post-mortem bundle under "
        "DIR on SLO breach, request failure, replica quarantine, "
        "drift breach, perf regression, an explicit dump_debug "
        "request, or SIGUSR2. See README \"Flight recorder & "
        "post-mortems\".",
    )
    ap.add_argument(
        "--regress-bench",
        default=None,
        metavar="GLOB",
        help="serve mode: additionally feed BENCH_r*.json evidence "
        "files matching GLOB into the SLO sentinel's perf-regression "
        "leg (the ledger tail is always evaluated when --ledger is "
        "set); a breach counts perf_regression and triggers a "
        "post-mortem bundle",
    )
    ap.add_argument(
        "--ledger-gc-interval-s",
        type=float,
        default=None,
        metavar="SECONDS",
        help="serve mode: compact the run ledger in the background "
        "every SECONDS (atomic rewrite dropping invalid lines and "
        "rows beyond --ledger-max-rows), so soak runs don't grow it "
        "unbounded; GC passes are counted in the live registry "
        "(ledger_gc_runs / ledger_gc_dropped). Needs --ledger.",
    )
    ap.add_argument(
        "--ledger-max-rows",
        type=int,
        default=0,
        metavar="N",
        help="with --ledger-gc-interval-s: keep only the newest N "
        "rows at each GC pass (0 = drop only invalid lines)",
    )
    ap.add_argument(
        "--listen",
        default=None,
        metavar="HOST:PORT",
        help="serve-worker: bind the fabric wire listener here "
        "(default 127.0.0.1:0 = ephemeral; the bound address prints "
        "as a 'fabric-worker ready' line on stdout). serve-router: "
        "additionally accept plain JSONL TCP clients here (loadgen "
        "--connect drives it); without it the router serves the "
        "--requests batch only. See README \"Multi-process serving\".",
    )
    ap.add_argument(
        "--worker",
        action="append",
        default=None,
        metavar="HOST:PORT",
        help="serve-router: a fabric worker's wire address "
        "(repeatable — one per externally-launched serve-worker "
        "process). Mutually exclusive with --workers.",
    )
    ap.add_argument(
        "--workers",
        type=int,
        default=None,
        metavar="N",
        help="serve-router: supervise N serve-worker subprocesses "
        "(ephemeral ports, worker ids 0..N-1), forwarding the "
        "service flags (--cache-dir is the SHARED disk tier, "
        "--ledger the shared run ledger) and reaping every child on "
        "exit — zero orphans. Mutually exclusive with --worker.",
    )
    ap.add_argument(
        "--worker-id",
        type=int,
        default=0,
        metavar="K",
        help="serve-worker: this worker's id — its position in the "
        "router's consistent-hash ring and the worker_id stamped on "
        "its ledger rows (default 0; the --workers supervisor "
        "assigns 0..N-1)",
    )
    ap.add_argument(
        "--worker-devices",
        type=int,
        default=None,
        metavar="D",
        help="serve-worker: pin this worker to a virtual D-device "
        "CPU slice (xla_force_host_platform_device_count, applied "
        "before jax initializes — CPU platform only; cross-host "
        "device slicing via jax.distributed is the ROADMAP "
        "residual). With --workers the supervisor forwards it to "
        "every child.",
    )
    ap.add_argument(
        "--hb-interval-s",
        type=float,
        default=None,
        metavar="SECONDS",
        help="fabric heartbeat period: the router pings every link "
        "this often (default 2)",
    )
    ap.add_argument(
        "--hb-timeout-s",
        type=float,
        default=None,
        metavar="SECONDS",
        help="fabric liveness bound: a link silent this long is "
        "declared failed and reconnected (default 10)",
    )
    ap.add_argument(
        "--reconnect-attempts",
        type=int,
        default=None,
        metavar="N",
        help="fabric: consecutive failed reconnects before a worker "
        "is declared DEAD and its in-flight requests re-dispatch to "
        "each fingerprint's ring successor (default 3)",
    )
    ap.add_argument(
        "--reconnect-delay-s",
        type=float,
        default=None,
        metavar="SECONDS",
        help="fabric: pause between reconnect attempts (default 0.2)",
    )
    ap.add_argument(
        "--stats-interval-s",
        type=float,
        default=None,
        metavar="SECONDS",
        help="serve-router: fleet telemetry poll period — how often "
        "the router pulls each worker's stats/metrics/slo_inputs "
        "snapshot over the wire (default 5)",
    )
    ap.add_argument(
        "--no-fabric-trace",
        action="store_true",
        help="serve-router: disable distributed tracing (no trace "
        "blocks on wire frames, no router ledger rows). MRC bytes "
        "are bit-identical either way — tracing is pure serving "
        "metadata (pinned by tests/test_fabric.py)",
    )
    args = ap.parse_args(argv)

    if args.list_models:
        return _list_models()
    if args.dump_ir or args.dump_ir_dir:
        # jax-free early exit like --list-models: dumping IR is pure
        # models/ + frontend/schema.py
        return _dump_ir(args)
    if args.mode is None:
        ap.error("mode is required (acc|speed|sample|trace|serve|"
                 "stats|analyze)")

    _SERVE_FAMILY = ("serve", "serve-worker", "serve-router")
    if args.program_json and args.mode in (
        "trace", "stats", *_SERVE_FAMILY
    ):
        raise SystemExit(
            "--program-json loads an inline frontend document for "
            "acc|speed|sample|analyze; serve modes take a 'program' "
            "field per request line instead"
        )

    if args.mode == "stats":
        return _stats(args)
    if args.mode == "analyze":
        # jax-free early dispatch like stats: the analysis passes are
        # pure numpy + stdlib
        return _analyze(args)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    if args.compilation_cache_dir:
        # process-global: every engine-executing mode benefits, and
        # service-routed sampled runs re-apply idempotently
        from .config import SamplerConfig
        from .sampler.sampled import _apply_compilation_cache

        _apply_compilation_cache(
            SamplerConfig(
                compilation_cache_dir=args.compilation_cache_dir
            )
        )

    _fabric_flags = [
        flag for flag, on in (
            ("--listen", args.listen is not None),
            ("--worker", args.worker is not None),
            ("--workers", args.workers is not None),
            ("--worker-id", args.worker_id != 0),
            ("--worker-devices", args.worker_devices is not None),
            ("--hb-interval-s", args.hb_interval_s is not None),
            ("--hb-timeout-s", args.hb_timeout_s is not None),
            ("--reconnect-attempts",
             args.reconnect_attempts is not None),
            ("--reconnect-delay-s",
             args.reconnect_delay_s is not None),
            ("--stats-interval-s",
             args.stats_interval_s is not None),
            ("--no-fabric-trace", args.no_fabric_trace),
        ) if on
    ]
    if _fabric_flags and args.mode not in ("serve-worker",
                                           "serve-router"):
        raise SystemExit(
            f"{', '.join(_fabric_flags)} configure(s) the serving "
            "fabric; they apply to serve-worker/serve-router only"
        )
    if args.mode == "serve-router":
        if (args.worker is None) == (args.workers is None):
            raise SystemExit(
                "serve-router needs exactly one of --worker "
                "HOST:PORT (repeatable, external workers) or "
                "--workers N (supervised subprocesses)"
            )
        if args.workers is not None and args.workers < 1:
            raise SystemExit("--workers must be >= 1")
        # --slo-latency-p95-s/--slo-error-budget are fleet-level on
        # the router: the sentinel evaluates over the workers' merged
        # slo_inputs (runtime/obs/fleet.FleetView), not local engine
        # counters
        _worker_side = [
            flag for flag, on in (
                ("--profile-hz", args.profile_hz is not None),
                ("--regress-bench", args.regress_bench is not None),
                ("--ledger-gc-interval-s",
                 args.ledger_gc_interval_s is not None),
            ) if on
        ]
        if _worker_side:
            raise SystemExit(
                f"{', '.join(_worker_side)} observe engine "
                "execution; run them on the serve-worker processes "
                "(the router executes no engine work)"
            )
    if args.mode == "serve-worker":
        if args.worker is not None or args.workers is not None:
            raise SystemExit(
                "--worker/--workers describe the router's worker "
                "set; serve-worker takes --listen/--worker-id"
            )
        if args.worker_id < 0:
            raise SystemExit("--worker-id must be >= 0")
        if args.worker_devices is not None and args.worker_devices < 1:
            raise SystemExit("--worker-devices must be >= 1")

    if args.mode not in _SERVE_FAMILY:
        if args.warmup_from_ledger is not None:
            raise SystemExit(
                "--warmup-from-ledger pre-compiles serving kernels at "
                "startup; it applies to serve mode only"
            )
        if args.metrics_port is not None:
            raise SystemExit(
                "--metrics-port exposes the live serving registry; "
                "it applies to serve mode only"
            )
        if args.profile_hz is not None or args.profile_out is not None:
            raise SystemExit(
                "--profile-hz/--profile-out run the serving "
                "sampling profiler; they apply to serve mode only "
                "(offline stage profiles come from "
                "tools/profile_tpu_stages.py)"
            )
        if (args.slo_latency_p95_s is not None
                or args.slo_error_budget is not None):
            raise SystemExit(
                "--slo-* flags run the serving SLO sentinel; they "
                "apply to serve mode only (offline ledgers are gated "
                "by tools/check_slo.py)"
            )
        if args.debug_bundle_dir is not None:
            raise SystemExit(
                "--debug-bundle-dir runs the serving flight "
                "recorder; it applies to serve mode only"
            )
        if args.regress_bench is not None:
            raise SystemExit(
                "--regress-bench feeds the serving perf-regression "
                "sentinel; it applies to serve mode only (offline "
                "history is gated by tools/check_regression.py)"
            )
        if args.ledger_gc_interval_s is not None:
            raise SystemExit(
                "--ledger-gc-interval-s runs background ledger "
                "compaction for serve mode only (offline ledgers are "
                "compacted by tools/check_ledger.py --gc)"
            )
        if args.fault_spec is not None:
            raise SystemExit(
                "--fault-spec arms deterministic fault injection on "
                "the serving hot paths; it applies to serve mode only"
            )
    if args.ledger_gc_interval_s is not None and not args.ledger:
        raise SystemExit(
            "--ledger-gc-interval-s compacts the run ledger; it "
            "needs --ledger PATH"
        )

    if args.profile_hz is not None and args.profile_hz <= 0:
        raise SystemExit("--profile-hz must be > 0 (samples per "
                         "second; omit the flag to keep the profiler "
                         "off)")
    if args.profile_out is not None and args.profile_hz is None:
        raise SystemExit("--profile-out exports the collected "
                         "profile; it needs --profile-hz")
    if args.replicas is not None and args.replicas < 0:
        raise SystemExit("--replicas must be >= 0 (0 = auto, one "
                         "replica per device)")
    if args.queue_limit is not None and args.queue_limit < 1:
        raise SystemExit("--queue-limit must be >= 1")
    if args.no_shed and args.queue_limit is None:
        raise SystemExit(
            "--no-shed disables the admission gate configured by "
            "--queue-limit; it needs --queue-limit N"
        )
    if args.max_retries is not None and args.max_retries < 0:
        raise SystemExit("--max-retries must be >= 0")
    if args.attempt_timeout_s is not None and args.attempt_timeout_s <= 0:
        raise SystemExit("--attempt-timeout-s must be > 0")
    if args.hedge_after_s is not None and args.hedge_after_s <= 0:
        raise SystemExit("--hedge-after-s must be > 0")
    if args.breaker_failures is not None and args.breaker_failures < 1:
        raise SystemExit("--breaker-failures must be >= 1")
    if args.breaker_probation_s is not None and args.breaker_probation_s <= 0:
        raise SystemExit("--breaker-probation-s must be > 0")
    if args.warmup_from_ledger is not None and not args.ledger:
        raise SystemExit(
            "--warmup-from-ledger reads kernel signatures from the "
            "run ledger; it needs --ledger PATH"
        )

    if args.mode in ("serve", "serve-worker"):
        return _observed(args, lambda: _serve(args))
    if args.mode == "serve-router":
        return _observed(args, lambda: _serve_router(args))

    from .config import MachineConfig

    machine = MachineConfig(thread_num=args.threads, chunk_size=args.chunk)
    if args.program_json:
        program, machine = _load_program_json(args, machine)
    else:
        program = _build_model(args.model, args.n, args.tsteps)
    engine = args.engine or ("sampled" if args.mode == "sample" else "dense")
    if args.checkpoint_dir is not None and engine != "sampled":
        raise SystemExit(
            "--checkpoint-dir is supported by the sampled engine only"
        )
    if args.mode == "sample" and engine not in ("sampled", "sharded"):
        raise SystemExit("sample mode needs --engine sampled|sharded")
    if args.shard and engine not in ("periodic", "analytic", "exact"):
        raise SystemExit(
            "--shard applies to the exact engines "
            "(periodic|analytic|exact); the sampled engine's mesh "
            "path is --engine sharded"
        )
    if args.pallas_hist and engine != "sharded":
        raise SystemExit(
            "--pallas-hist applies to --engine sharded only (other "
            "engines reduce exact sparse pairs, not binned histograms)"
        )
    if args.device_draw is not None and engine not in (
        "sampled", "sharded"
    ):
        raise SystemExit(
            "--device-draw applies to the sampled/sharded engines "
            "only (the exact engines do not sample)"
        )
    if args.kernel_backend is not None and engine != "sampled":
        raise SystemExit(
            "--kernel-backend applies to --engine sampled only (the "
            "sharded engine picks its kernels per mesh axis)"
        )
    if args.diff_against:
        if args.mode not in ("acc", "sample"):
            raise SystemExit(
                "--diff-against compares acc/sample dumps; it has no "
                "meaning in speed or trace mode"
            )
        _ENGINES = ("oracle", "numpy", "native", "native-par", "dense",
                    "stream", "periodic", "exact", "sampled", "sharded")
        if args.diff_against not in _ENGINES:
            raise SystemExit(
                f"unknown --diff-against engine {args.diff_against!r} "
                f"(have {', '.join(_ENGINES)})"
            )

    if args.ledger and args.mode == "trace":
        raise SystemExit(
            "--ledger records engine/service executions (acc|speed|"
            "sample|serve|stats); trace mode has none"
        )
    if args.cache_dir:
        if args.mode == "trace":
            raise SystemExit(
                "--cache-dir serves analysis results (acc|speed|"
                "sample|serve); trace mode has none"
            )
        from .service.executor import SERVICE_ENGINES

        if engine not in SERVICE_ENGINES:
            raise SystemExit(
                f"--cache-dir serves the request pipeline engines "
                f"({', '.join(SERVICE_ENGINES)}); {engine!r} is not "
                "one of them"
            )
        blocked = [
            flag for flag, on in (
                ("--r10", args.r10),
                ("--diff-against", args.diff_against),
                ("--checkpoint-dir", args.checkpoint_dir),
                ("--shard", args.shard),
                ("--pallas-hist", args.pallas_hist),
            ) if on
        ]
        if blocked:
            raise SystemExit(
                f"--cache-dir serves the plain request pipeline; it "
                f"does not compose with {', '.join(blocked)}"
            )
    elif args.deadline_s is not None:
        raise SystemExit(
            "--deadline-s bounds service-routed requests; it needs "
            "--cache-dir (or serve mode, where each request line "
            "carries its own deadline_s)"
        )
    if args.batch_window_ms is not None and not args.cache_dir:
        raise SystemExit(
            "--batch-window-ms batches service-routed requests; it "
            "needs --cache-dir (or serve mode)"
        )
    if args.replicas is not None and not args.cache_dir:
        raise SystemExit(
            "--replicas partitions the service's devices into "
            "replica executors; it needs --cache-dir (or serve mode)"
        )
    _res_flags = [
        flag for flag, on in (
            ("--attempt-timeout-s", args.attempt_timeout_s is not None),
            ("--max-retries", args.max_retries is not None),
            ("--hedge-after-s", args.hedge_after_s is not None),
            ("--queue-limit", args.queue_limit is not None),
            ("--breaker-failures", args.breaker_failures is not None),
            ("--breaker-probation-s",
             args.breaker_probation_s is not None),
        ) if on
    ]
    if _res_flags and not args.cache_dir:
        raise SystemExit(
            f"{', '.join(_res_flags)} configure(s) service-routed "
            "execution; they need --cache-dir (or serve mode)"
        )

    return _observed(
        args, lambda: _execute(args, machine, program, engine)
    )


def _observed(args, fn) -> int:
    """Run fn() under the observability flags (--telemetry-out /
    --trace-out / --metrics-out / --profile-dir) — shared by the mode
    executor and serve mode. The exporters read the SAME stopped run,
    so the Chrome trace's span tree is exactly `Telemetry.to_json`'s.
    """
    tele = None
    if args.telemetry_out or args.trace_out or args.metrics_out:
        from .runtime import telemetry

        tele = telemetry.enable()
    try:
        if args.profile_dir:
            import jax

            with jax.profiler.trace(args.profile_dir):
                return fn()
        return fn()
    finally:
        if tele is not None:
            from .runtime import telemetry
            from .runtime.obs import exporters

            telemetry.disable()
            if args.telemetry_out:
                tele.print_summary()
                tele.write_json(args.telemetry_out)
            if args.trace_out or args.metrics_out:
                doc = tele.to_json()
                if args.trace_out:
                    exporters.write_chrome_trace(args.trace_out, doc)
                if args.metrics_out:
                    exporters.write_prometheus(args.metrics_out, doc)


def _analyze(args) -> int:
    """`analyze` mode: the static preflight passes (analysis/) for one
    model — well-formedness diagnostics, dependence/race verdict, and
    the locality bounds — with no jax import and no engine run.
    `--analysis-json` emits the full machine-readable report instead
    of the table. Exit 0 when the IR is simulable (verdict ok or
    race — a race is a property of the modeled OpenMP program, not an
    input error), 1 when invalid."""
    import json as _json

    from . import analysis
    from .config import MachineConfig

    machine = MachineConfig(
        thread_num=args.threads, chunk_size=args.chunk
    )
    if args.program_json:
        program, machine = _load_program_json(args, machine)
    else:
        program = _build_model(args.model, args.n, args.tsteps)
    report = analysis.analyze_program(program, machine)
    if args.analysis_json:
        print(_json.dumps(report.to_dict(), indent=2, sort_keys=True))
        return 0 if report.ok else 1
    print(f"{program.name}: verdict {report.verdict} "
          f"({report.wall_s * 1e3:.1f} ms)")
    for d in report.diagnostics:
        print(f"  [{d.severity}] {d.code} at {d.path}: {d.message}")
    if report.bounds is not None:
        b = report.bounds
        print(f"  accesses {b.total_accesses}, compulsory-miss lower "
              f"bound {b.compulsory_lower} lines, "
              + (f"cold footprint {b.cold_model} lines (exact), "
                 f"MRC asymptote {b.asymptote:.6g}"
                 if b.exact else
                 "footprint bounded by interval analysis "
                 "(domain too large for exact enumeration)"))
        carried = sum(
            1 for dep in report.dependences
            if dep.kind == analysis.DEP_CARRIED
        )
        print(f"  dependences: {len(report.dependences)} classified "
              f"pairs, {carried} carried, {len(report.races)} "
              "race-flagged")
    return 0 if report.ok else 1


def _stats(args) -> int:
    """`stats` mode: aggregate a run ledger into the per-engine
    serving picture (p50/p95 latency, cache hit rates, degradation
    counts, drift status)."""
    from .runtime.obs import ledger as obs_ledger

    if not args.ledger:
        raise SystemExit("stats mode needs --ledger PATH")
    try:
        entries = list(obs_ledger.iter_rows(args.ledger))
    except OSError as e:
        raise SystemExit(f"cannot read ledger: {e}")
    rows = [row for _ln, row, _err in entries if row is not None]
    bad = [(ln, err) for ln, row, err in entries if row is None]
    for line in obs_ledger.format_stats(obs_ledger.aggregate(rows)):
        print(line)
    if bad:
        print(
            f"warning: {len(bad)} invalid line(s) skipped (run "
            "tools/check_ledger.py for details)",
            file=sys.stderr,
        )
    return 0


def _parse_round_schedule(spec: str) -> tuple:
    """"0.25,0.5,1.0" -> (0.25, 0.5, 1.0); validation happens where
    the schedule is resolved (sampler/confidence.py)."""
    try:
        return tuple(float(f) for f in spec.split(",") if f.strip())
    except ValueError:
        raise SystemExit(
            f"--round-schedule wants comma-separated floats, got "
            f"{spec!r}"
        )


def _request_from_args(args, engine):
    from .service import AnalysisRequest

    return AnalysisRequest(
        model=args.model, n=args.n, tsteps=args.tsteps, engine=engine,
        runtime=args.runtime, threads=args.threads, chunk=args.chunk,
        ratio=args.ratio, seed=args.seed, device_draw=args.device_draw,
        fuse_refs=args.fuse_refs, pipeline_depth=args.pipeline_depth,
        kernel_backend=args.kernel_backend,
        program=getattr(args, "_program_doc", None),
        deadline_s=args.deadline_s,
        tolerance=args.tolerance, max_rounds=args.max_rounds,
        round_schedule=(
            _parse_round_schedule(args.round_schedule)
            if args.round_schedule is not None else None
        ),
    )


def _resilience_from_args(args):
    """ResilienceConfig from the CLI flags, or None when every flag is
    at its default (the executor then runs the stock config — retries
    off, no admission gate, breakers at their defaults)."""
    if all(
        v is None for v in (
            args.attempt_timeout_s, args.max_retries,
            args.hedge_after_s, args.queue_limit,
            args.breaker_failures, args.breaker_probation_s,
        )
    ):
        return None
    from .config import ResilienceConfig

    kw = {}
    if args.attempt_timeout_s is not None:
        kw["attempt_timeout_s"] = args.attempt_timeout_s
    if args.max_retries is not None:
        kw["max_retries"] = args.max_retries
    if args.hedge_after_s is not None:
        kw["hedge_after_s"] = args.hedge_after_s
    if args.queue_limit is not None:
        kw["queue_limit"] = args.queue_limit
        kw["shed_enabled"] = not args.no_shed
    if args.breaker_failures is not None:
        kw["breaker_failures"] = args.breaker_failures
    if args.breaker_probation_s is not None:
        kw["breaker_probation_s"] = args.breaker_probation_s
    return ResilienceConfig(**kw)


def _fabric_from_args(args):
    """FabricConfig from the CLI timing flags (defaults where unset)."""
    from .config import FabricConfig

    kw = {}
    for attr in ("hb_interval_s", "hb_timeout_s",
                 "reconnect_attempts", "reconnect_delay_s",
                 "stats_interval_s"):
        v = getattr(args, attr)
        if v is not None:
            kw[attr] = v
    if args.no_fabric_trace:
        kw["trace_enabled"] = False
    return FabricConfig(**kw)


def _run_worker_front(args, svc) -> int:
    """The serve-worker serving front: a fabric WorkerServer over the
    already-wired AnalysisService. Blocks until the router drains this
    worker (`shutdown` frame -> bye) or SIGTERM/SIGINT lands; either
    way the service enters graceful drain so _serve's shutdown
    reporting (and final flight-recorder bundle) fires."""
    from .service import GracefulShutdown
    from .service.fabric import WorkerServer, parse_hostport

    host, port = ("127.0.0.1", 0)
    if args.listen:
        host, port = parse_hostport(args.listen)
    ws = WorkerServer(
        svc, worker_id=args.worker_id, host=host, port=port,
        fabric=_fabric_from_args(args),
    )
    host, port = ws.start()
    # the supervisor (serve-router --workers N) parses this exact
    # stdout line to learn the ephemeral port — keep it first + flushed
    print(f"fabric-worker ready {args.worker_id} {host}:{port}",
          flush=True)
    print(
        f"serve-worker: worker {args.worker_id} speaking the fabric "
        f"wire protocol on {host}:{port}",
        file=sys.stderr,
    )
    try:
        while not ws.join_drained(timeout=0.5):
            pass
        svc.begin_shutdown()
    except GracefulShutdown:
        svc.begin_shutdown()
        ws.drain_local()
    finally:
        ws.close()
    return 0


def _spawn_workers(args, children) -> list:
    """serve-router --workers N: launch N serve-worker subprocesses
    on ephemeral ports (worker ids 0..N-1), forwarding the service
    flags — ONE shared --cache-dir disk tier and ONE shared O_APPEND
    --ledger across the fleet — and return their wire addresses.
    Children are appended to `children` as they spawn so the caller's
    cleanup reaps every process even when a later one fails to come
    up (the zero-orphans guarantee tools/check_fabric.py pins)."""
    import subprocess

    from .service.fabric import parse_hostport

    forwarded = []
    for flag, value in (
        ("--cache-dir", args.cache_dir),
        ("--ledger", args.ledger),
        ("--max-workers", args.max_workers),
        ("--batch-window-ms", args.batch_window_ms),
        ("--batch-max-refs", args.batch_max_refs),
        ("--replicas", args.replicas),
        ("--worker-devices", args.worker_devices),
        ("--platform", args.platform),
        ("--compilation-cache-dir", args.compilation_cache_dir),
        ("--warmup-from-ledger", args.warmup_from_ledger),
        ("--fault-spec", args.fault_spec),
        ("--attempt-timeout-s", args.attempt_timeout_s),
        ("--max-retries", args.max_retries),
        ("--hedge-after-s", args.hedge_after_s),
        ("--queue-limit", args.queue_limit),
        ("--breaker-failures", args.breaker_failures),
        ("--breaker-probation-s", args.breaker_probation_s),
        ("--hb-interval-s", args.hb_interval_s),
        ("--hb-timeout-s", args.hb_timeout_s),
        ("--reconnect-attempts", args.reconnect_attempts),
        ("--reconnect-delay-s", args.reconnect_delay_s),
    ):
        if value is not None and flag != "--batch-max-refs":
            forwarded += [flag, str(value)]
        elif flag == "--batch-max-refs" and args.batch_window_ms:
            forwarded += [flag, str(value)]
    if args.no_shed:
        forwarded.append("--no-shed")
    addrs = []
    for i in range(args.workers):
        cmd = [
            sys.executable, "-m", "pluss_sampler_optimization_tpu.cli",
            "serve-worker", "--listen", "127.0.0.1:0",
            "--worker-id", str(i),
        ] + forwarded
        if args.debug_bundle_dir is not None:
            import os

            cmd += ["--debug-bundle-dir",
                    os.path.join(args.debug_bundle_dir, f"worker{i}")]
        children.append(subprocess.Popen(
            cmd, stdout=subprocess.PIPE, text=True, bufsize=1,
        ))
    for i, proc in enumerate(children):
        line = proc.stdout.readline().strip()
        parts = line.split()
        if (len(parts) != 4 or parts[:2] != ["fabric-worker", "ready"]
                or parts[2] != str(i)):
            raise SystemExit(
                f"serve-router: worker {i} failed to start "
                f"(got {line!r} instead of its ready line)"
            )
        addrs.append(parse_hostport(parts[3]))
        print(f"serve-router: worker {i} up at {parts[3]} "
              f"(pid {proc.pid})", file=sys.stderr)
    return addrs


def _serve_router(args) -> int:
    """`serve-router` mode: the fabric's dispatch plane — consistent-
    hash request fingerprints over N engine workers (supervised
    subprocesses via --workers, or externally-launched via --worker),
    serving the JSONL protocol from --requests/stdin and, with
    --listen, from TCP clients. SIGTERM/SIGINT drain the WHOLE
    fabric: the router stops accepting, in-flight entries resolve,
    every worker gets a `shutdown` frame and drains (each dumping its
    own final flight-recorder bundle when armed), and supervised
    children are reaped — zero orphans."""
    import signal

    from .runtime import faults
    from .runtime.obs import metrics as obs_metrics
    from .runtime.obs import profiler as obs_profiler
    from .runtime.obs import recorder as obs_recorder
    from .runtime.obs import slo as obs_slo
    from .service import GracefulShutdown
    from .service.fabric import Router, parse_hostport

    fabric = _fabric_from_args(args)
    fin = sys.stdin if args.requests == "-" else open(args.requests)
    fout = (
        sys.stdout if args.responses == "-"
        else open(args.responses, "w")
    )
    registry = obs_metrics.enable()
    injector = None
    recorder = None
    router = None
    server = None
    children: list = []
    prev_sigs = {}
    failures = 0
    graceful = False
    if args.fault_spec:
        # the router arms its own injector for the worker_conn site;
        # supervised workers get --fault-spec forwarded and draw from
        # their own (identically-seeded) streams
        injector = faults.install_from_file(args.fault_spec)
        print(
            f"serve-router: fault injection armed from "
            f"{args.fault_spec} (seed {injector.config.seed}, "
            f"{len(injector.config.rules)} rule(s))",
            file=sys.stderr,
        )
    if args.debug_bundle_dir is not None:
        recorder = obs_recorder.enable(
            args.debug_bundle_dir,
            ledger_path=args.ledger,
            config={
                k: getattr(args, k)
                for k in (
                    "cache_dir", "ledger", "workers", "worker",
                    "listen", "hb_interval_s", "hb_timeout_s",
                    "reconnect_attempts", "reconnect_delay_s",
                    "stats_interval_s", "no_fabric_trace",
                    "fault_spec", "debug_bundle_dir",
                )
            },
        )
        print(
            "serve-router: flight recorder on, post-mortem bundles "
            f"under {args.debug_bundle_dir}",
            file=sys.stderr,
        )
    try:
        def _graceful_sig(signum, frame):
            raise GracefulShutdown(f"signal {signum}")

        for _name in ("SIGTERM", "SIGINT"):
            _num = getattr(signal, _name, None)
            if _num is None:
                continue
            try:
                prev_sigs[_num] = signal.signal(_num, _graceful_sig)
            except ValueError:
                pass
        if args.workers is not None:
            addrs = _spawn_workers(args, children)
        else:
            addrs = [parse_hostport(spec) for spec in args.worker]
        # the router shares the workers' O_APPEND ledger: its rows
        # (source fabric.router, per-request span splits) join the
        # worker rows on trace_id — tools/assemble_trace.py
        router = Router(addrs, fabric=fabric,
                        ledger_path=args.ledger)
        if (args.slo_latency_p95_s is not None
                or args.slo_error_budget is not None):
            from .config import SLOConfig
            from .runtime.obs import fleet as obs_fleet

            kw = {"burn_rate_threshold": args.slo_burn_threshold}
            if args.slo_latency_p95_s is not None:
                kw["latency_p95_s"] = args.slo_latency_p95_s
            if args.slo_error_budget is not None:
                kw["error_budget"] = args.slo_error_budget
            slo_config = SLOConfig(**kw)
            # workers pre-digest their windows against this threshold
            # (fabric/worker.py _slo_inputs); the sentinel then reads
            # the fleet as one registry through FleetView. No ledger
            # leg here — the shared ledger holds router rows too, and
            # the workers' own sentinels already watch their tails
            router.slo_params = {
                "threshold": args.slo_latency_p95_s,
                "windows": list(slo_config.windows),
            }
            sentinel = obs_slo.SLOSentinel(
                slo_config, registry=obs_fleet.FleetView(router),
                interval_s=args.slo_interval_s,
            )
            router.slo_sentinel = sentinel
        router.start()
        if router.slo_sentinel is not None:
            router.slo_sentinel.start()
            print(
                "serve-router: fleet SLO sentinel on (burn rates "
                "over the merged worker windows, every "
                f"{args.slo_interval_s:g}s)",
                file=sys.stderr,
            )
        if recorder is not None:
            recorder.state_provider = lambda: {
                "healthz": router.healthz(),
                "stats": router.stats(),
            }
        if args.metrics_port is not None:
            server = obs_metrics.MetricsServer(
                registry, port=args.metrics_port,
                healthz=router.healthz,
                # cached snapshots (refreshed every stats_interval_s
                # by the poll loop) — a scrape never blocks on N
                # worker round-trips
                stats=(lambda: router.fleet_stats(refresh=False)),
                prometheus=router.fleet_prometheus_text,
                bundles=(
                    (lambda: {
                        "bundle_dir": recorder.bundle_dir,
                        "recorder": recorder.stats(),
                        "bundles": recorder.bundle_index(),
                    }) if recorder is not None else None
                ),
                profile=obs_profiler.snapshot,
            )
            print(
                f"serve-router: live metrics on "
                f"http://{server.host}:{server.port}/metrics",
                file=sys.stderr,
            )
        if args.listen is not None:
            th, tp = parse_hostport(args.listen)
            bh, bp = router.serve_tcp(th, tp)
            print(f"serve-router: JSONL TCP front on {bh}:{bp}",
                  file=sys.stderr)
        if args.requests != "-" or args.listen is None:
            failures = router.serve_stream(fin, fout)
        if args.listen is not None:
            # TCP daemon: serve until a shutdown signal lands
            import threading as _threading

            _forever = _threading.Event()
            while not _forever.wait(0.5):
                pass
    except GracefulShutdown:
        graceful = True
        print(
            "serve-router: graceful shutdown — stopped accepting, "
            "draining the fabric",
            file=sys.stderr,
        )
    finally:
        if router is not None and router.slo_sentinel is not None:
            try:
                # final fleet evaluation so short batches (finished
                # inside one interval) still report, matching _serve
                router.slo_sentinel.evaluate_once()
                for line in obs_slo.format_report(
                    router.slo_sentinel.last_report
                ):
                    print(f"serve-router: {line}", file=sys.stderr)
            except Exception:
                pass
            router.slo_sentinel.close()
        if router is not None:
            router.close(graceful=True)
        for proc in children:
            try:
                proc.wait(timeout=fabric.drain_timeout_s)
            except Exception:
                proc.kill()
                proc.wait()
            if proc.stdout is not None:
                proc.stdout.close()
        if graceful and recorder is not None:
            recorder.dump(
                "shutdown", trigger={"reason": "graceful_shutdown"}
            )
        if injector is not None:
            if injector.total_fired():
                print(
                    f"serve-router: faults fired "
                    f"{injector.total_fired()} time(s): "
                    f"{injector.stats()}",
                    file=sys.stderr,
                )
            faults.uninstall()
        if prev_sigs:
            for _num, _prev in prev_sigs.items():
                try:
                    signal.signal(_num, _prev)
                except ValueError:
                    pass
        if server is not None:
            server.close()
        if recorder is not None:
            obs_recorder.disable()
        obs_metrics.disable()
        if fin is not sys.stdin:
            fin.close()
        if fout is not sys.stdout:
            fout.close()
    if graceful and children:
        print(
            f"serve-router: graceful shutdown complete — "
            f"{len(children)} worker(s) drained and reaped",
            file=sys.stderr,
        )
    if failures:
        print(
            f"serve-router: {failures} request(s) failed (per-line "
            "status is in the responses)",
            file=sys.stderr,
        )
    return 0


def _serve(args) -> int:
    """`serve` mode: process a JSONL request batch end to end, under
    the live metrics registry (always on here — the `metrics` request
    type and the optional --metrics-port scrape read it), the
    optional SLO sentinel, the optional flight recorder
    (--debug-bundle-dir), the optional background ledger GC, and —
    when armed — deterministic fault injection (--fault-spec).
    SIGTERM/SIGINT trigger a graceful drain: in-flight work finishes,
    queued work is shed with structured responses, and the ledger
    (plus a final flight-recorder bundle) is flushed before exit.

    `serve-worker` mode runs HERE too — the identical stack and
    wiring, with the serving front swapped: instead of a JSONL batch
    from --requests, the service answers framed request lines from a
    fabric router (service/fabric/worker.py) until the router drains
    it or a signal lands. Same per-line semantics, same responses,
    same observability — which is what makes fabric results
    bit-identical to single-process serve."""
    from .runtime import faults
    from .runtime.obs import ledger as obs_ledger
    from .runtime.obs import metrics as obs_metrics
    from .runtime.obs import profiler as obs_profiler
    from .runtime.obs import recorder as obs_recorder
    from .service import AnalysisService, GracefulShutdown, serve_jsonl

    worker_mode = args.mode == "serve-worker"
    if worker_mode and args.worker_devices:
        # must land before ANY jax backend touch — the virtual CPU
        # slice is baked into XLA_FLAGS at client creation
        from . import _platform

        _platform.force_virtual_cpu(args.worker_devices)

    fin = sys.stdin if args.requests == "-" else open(args.requests)
    fout = (
        sys.stdout if args.responses == "-"
        else open(args.responses, "w")
    )
    registry = obs_metrics.enable()
    profiler = None
    if args.profile_hz is not None:
        profiler = obs_profiler.enable(hz=args.profile_hz)
        print(
            f"serve: sampling profiler on at {args.profile_hz:g} Hz "
            "(snapshot at GET /debug/profile)",
            file=sys.stderr,
        )
    server = None
    sentinel = None
    recorder = None
    gc = None
    prev_usr2 = None
    prev_sigs = {}
    injector = None
    failures = 0
    if args.fault_spec:
        injector = faults.install_from_file(args.fault_spec)
        print(
            f"serve: fault injection armed from {args.fault_spec} "
            f"(seed {injector.config.seed}, "
            f"{len(injector.config.rules)} rule(s))",
            file=sys.stderr,
        )
    if args.debug_bundle_dir is not None:
        recorder = obs_recorder.enable(
            args.debug_bundle_dir,
            ledger_path=args.ledger,
            # the resolved serving config rides every bundle, so a
            # post-mortem reader knows exactly what was running
            config={
                k: getattr(args, k)
                for k in (
                    "cache_dir", "ledger", "max_workers", "replicas",
                    "batch_window_ms", "batch_max_refs",
                    "slo_latency_p95_s", "slo_error_budget",
                    "slo_burn_threshold", "slo_interval_s",
                    "debug_bundle_dir", "regress_bench",
                    "ledger_gc_interval_s", "ledger_max_rows",
                    "fault_spec", "attempt_timeout_s", "max_retries",
                    "hedge_after_s", "queue_limit", "no_shed",
                    "breaker_failures", "breaker_probation_s",
                    "profile_hz", "profile_out",
                )
            },
        )
        print(
            "serve: flight recorder on, post-mortem bundles under "
            f"{args.debug_bundle_dir}",
            file=sys.stderr,
        )
        # SIGUSR2 = dump a bundle NOW, the kill(1)-reachable twin of
        # the dump_debug request type. Registration only works on the
        # main thread — embedders calling main() elsewhere just lose
        # the signal hook, never the recorder.
        import signal

        if hasattr(signal, "SIGUSR2"):
            try:
                prev_usr2 = signal.signal(
                    signal.SIGUSR2,
                    lambda signum, frame: recorder.dump(
                        "signal", trigger={"signal": "SIGUSR2"}
                    ),
                )
            except ValueError:
                prev_usr2 = None
    try:
        # SIGTERM/SIGINT = drain, don't drop: the handler raises
        # GracefulShutdown (a BaseException, so serve_jsonl's per-line
        # `except Exception` guards can't swallow it) on the main
        # thread; serve_jsonl catches it, stops admission, finishes
        # in-flight work, and sheds the rest with structured
        # responses. Same main-thread-only caveat as SIGUSR2 above.
        import signal

        def _graceful(signum, frame):
            raise GracefulShutdown(f"signal {signum}")

        for _name in ("SIGTERM", "SIGINT"):
            _num = getattr(signal, _name, None)
            if _num is None:
                continue
            try:
                prev_sigs[_num] = signal.signal(_num, _graceful)
            except ValueError:
                pass
        with AnalysisService(
            cache_dir=args.cache_dir, max_workers=args.max_workers,
            ledger_path=args.ledger,
            batch_window_ms=args.batch_window_ms,
            batch_max_refs=args.batch_max_refs,
            replicas=args.replicas,
            resilience=_resilience_from_args(args),
            worker_id=(args.worker_id if worker_mode else None),
        ) as svc:
            if recorder is not None:
                # live serving state for bundles: replica/mesh view +
                # executor counters at dump time
                recorder.state_provider = lambda: {
                    "healthz": svc.healthz(),
                    "executor": svc.executor.stats(),
                }
            if args.metrics_port is not None:
                server = obs_metrics.MetricsServer(
                    registry, port=args.metrics_port,
                    healthz=svc.healthz, stats=svc.stats,
                    bundles=(
                        (lambda: {
                            "bundle_dir": recorder.bundle_dir,
                            "recorder": recorder.stats(),
                            "bundles": recorder.bundle_index(),
                        }) if recorder is not None else None
                    ),
                    # always wired: the route answers a structured
                    # 404 JSON body when the profiler is off, so
                    # pollers never see a bare HTML error page
                    profile=obs_profiler.snapshot,
                )
                print(
                    f"serve: live metrics on "
                    f"http://{server.host}:{server.port}/metrics",
                    file=sys.stderr,
                )
            if args.warmup_from_ledger:
                warmed = svc.warm_from_ledger(args.warmup_from_ledger)
                print(
                    f"serve: warmed {warmed} kernel signature(s) "
                    "from the ledger",
                    file=sys.stderr,
                )
            if args.ledger_gc_interval_s is not None:
                gc = obs_ledger.LedgerGC(
                    args.ledger,
                    interval_s=args.ledger_gc_interval_s,
                    max_rows=args.ledger_max_rows,
                ).start()
            if (args.slo_latency_p95_s is not None
                    or args.slo_error_budget is not None):
                from .config import SLOConfig
                from .runtime.obs import slo as obs_slo

                kw = {"burn_rate_threshold": args.slo_burn_threshold}
                if args.slo_latency_p95_s is not None:
                    kw["latency_p95_s"] = args.slo_latency_p95_s
                if args.slo_error_budget is not None:
                    kw["error_budget"] = args.slo_error_budget
                import glob as glob_mod

                bench_paths = (
                    sorted(glob_mod.glob(args.regress_bench))
                    if args.regress_bench else None
                )
                sentinel = obs_slo.SLOSentinel(
                    SLOConfig(**kw), registry=registry,
                    ledger_path=args.ledger,
                    interval_s=args.slo_interval_s,
                    regress_bench=bench_paths,
                ).start()
                svc.slo_sentinel = sentinel
            if worker_mode:
                failures = _run_worker_front(args, svc)
            else:
                failures = serve_jsonl(svc, fin, fout)
            if svc.executor.draining:
                st = svc.executor.stats()
                print(
                    "serve: graceful shutdown — in-flight work "
                    f"drained, {st.get('shed', 0)} request(s) shed",
                    file=sys.stderr,
                )
                if recorder is not None:
                    recorder.dump(
                        "shutdown",
                        trigger={"reason": "graceful_shutdown"},
                    )
            if injector is not None and injector.total_fired():
                print(
                    f"serve: faults fired {injector.total_fired()} "
                    f"time(s): {injector.stats()}",
                    file=sys.stderr,
                )
            if sentinel is not None:
                # short batches finish inside one interval; the final
                # evaluation guarantees every serve run gets (at
                # least) one report and any breach events
                report = sentinel.evaluate_once()
                if not report["ok"]:
                    from .runtime.obs import slo as obs_slo

                    for line in obs_slo.format_report(report):
                        print(f"serve: {line}", file=sys.stderr)
            if gc is not None:
                # final compaction so the bound holds for whoever
                # reads the ledger after this process exits
                try:
                    gc.run_once()
                except Exception:
                    pass
    except GracefulShutdown:
        # signal landed outside serve_jsonl (startup/teardown window)
        # — still a clean exit, nothing was being served
        print("serve: shutdown signal received outside the serving "
              "loop; exiting", file=sys.stderr)
    finally:
        if injector is not None:
            faults.uninstall()
        if prev_sigs:
            import signal

            for _num, _prev in prev_sigs.items():
                try:
                    signal.signal(_num, _prev)
                except ValueError:
                    pass
        if gc is not None:
            gc.close()
        if sentinel is not None:
            sentinel.close()
        if server is not None:
            server.close()
        if recorder is not None:
            obs_recorder.disable()
            if prev_usr2 is not None:
                import signal

                try:
                    signal.signal(signal.SIGUSR2, prev_usr2)
                except ValueError:
                    pass
        if profiler is not None:
            obs_profiler.disable()
            if args.profile_out:
                try:
                    profiler.write_speedscope(args.profile_out)
                    profiler.write_collapsed(
                        args.profile_out + ".collapsed"
                    )
                    snap = profiler.snapshot()
                    print(
                        "serve: profile written to "
                        f"{args.profile_out} ({snap['samples']} "
                        "samples, attribution completeness "
                        f"{snap['attribution_completeness']})",
                        file=sys.stderr,
                    )
                except Exception as e:
                    print(f"serve: profile export failed: {e!r}",
                          file=sys.stderr)
        obs_metrics.disable()
        if fin is not sys.stdin:
            fin.close()
        if fout is not sys.stdout:
            fout.close()
    if failures:
        print(f"serve: {failures} request(s) failed (per-line "
              "status is in the responses)", file=sys.stderr)
    return 0


def _execute_via_service(args, machine, program, engine) -> int:
    """acc/speed/sample through the analysis service (--cache-dir):
    identical dumps to the direct path, served from the
    content-addressed store when warm."""
    import time

    from .runtime import report
    from .service import AnalysisService

    request = _request_from_args(args, engine)
    with AnalysisService(
        cache_dir=args.cache_dir, ledger_path=args.ledger,
        batch_window_ms=args.batch_window_ms,
        batch_max_refs=args.batch_max_refs,
        replicas=args.replicas,
        resilience=_resilience_from_args(args),
    ) as svc:
        if args.mode == "speed":
            times = []
            for rep in range(args.reps):
                t0 = time.perf_counter()
                resp = svc.analyze(request)
                dt = time.perf_counter() - t0
                if not resp.ok:
                    raise SystemExit(
                        f"service request failed: {resp.error}"
                    )
                times.append(dt)
                print(f"{engine} {program.name} run {rep}: "
                      f"{dt:.6f} s (cache {resp.cache})")
            print(
                f"{engine} {program.name}: best {min(times):.6f} s, "
                f"mean {sum(times) / len(times):.6f} s over "
                f"{len(times)} runs"
            )
            return 0
        resp = svc.analyze(request)
        if not resp.ok:
            raise SystemExit(f"service request failed: {resp.error}")
        if resp.degraded:
            print(f"service degraded: {resp.degraded}",
                  file=sys.stderr)
        lines = []
        if args.mode == "sample" and resp.per_ref_lines:
            lines += resp.per_ref_lines
        lines += resp.dump_lines
        report.emit(lines)
        if args.mrc_out:
            report.write_mrc_to_file(resp.mrc, args.mrc_out)
    return 0


def _cli_ledger_row(args, program, engine, engine_used, latency_s,
                    mrc=None, compiles0=None, reps=None) -> None:
    """One direct-path (no service) execution -> run-ledger row.

    Shares the service's content address when the engine is
    service-addressable, so direct and served executions of the same
    request join on one fingerprint in the aggregated ledger."""
    from .runtime import telemetry
    from .runtime.obs import ledger as obs_ledger
    from .service.executor import SERVICE_ENGINES

    fp = None
    if engine in SERVICE_ENGINES:
        try:
            fp = _request_from_args(args, engine).fingerprint(program)
        except Exception:
            pass
    row = {
        "kind": "request",
        "source": "cli",
        "ok": True,
        "fingerprint": fp,
        "engine_requested": engine,
        "engine_used": engine_used,
        "model": args.model,
        "n": args.n,
        "latency_s": round(latency_s, 6),
        "cache": None,
        "degraded": [],
        "mrc_digest": (
            obs_ledger.mrc_digest(mrc) if mrc is not None else None
        ),
    }
    if compiles0 is not None:
        now = telemetry.compile_counters_snapshot()
        row["compile_delta"] = {
            k: round(v - compiles0.get(k, 0), 4)
            if isinstance(v, float) else v - compiles0.get(k, 0)
            for k, v in now.items() if v - compiles0.get(k, 0)
        }
    if reps is not None:
        row["reps"] = reps
    obs_ledger.append(args.ledger, row)


def _execute(args, machine, program, engine) -> int:
    """Run the selected mode (spans/counters land in the active
    telemetry run, if any — main() owns enable/export)."""
    import time

    from .runtime import report
    from .runtime.aet import aet_mrc
    from .runtime.cri import cri_distribute

    if args.cache_dir and args.mode in ("acc", "speed", "sample"):
        return _execute_via_service(args, machine, program, engine)

    compiles0 = None
    if args.ledger:
        from .runtime import telemetry as _telemetry

        # compile-counter deltas need the process-global listeners
        try:
            _telemetry.register_jax_hooks()
        except Exception:
            pass
        compiles0 = _telemetry.compile_counters_snapshot()

    if args.mode == "trace":
        # the reference's -DDEBUG access/reuse logs (runtime/debug.py)
        from .core.trace import ProgramTrace
        from .runtime.debug import (
            access_trace,
            format_reuse_pairs,
            reuse_pairs,
        )

        trace = ProgramTrace(program, machine)
        print(f"access trace, tid {args.tid}:")
        for row in access_trace(program, machine, args.tid, args.limit,
                                trace=trace):
            print("  pos %d  %s line %d  %s" % row)
        pairs = reuse_pairs(
            program, machine, args.tid, args.min_reuse, args.limit,
            trace=trace,
        )
        print(f"reuse pairs >= {args.min_reuse}, tid {args.tid}:")
        for line in format_reuse_pairs(pairs):
            print("  " + line)
        return 0

    if args.mode == "speed":
        # Makefile:34-37 / main.rs:31-33: repeated timed runs after a
        # cache flush (pluss_timer_start flushes 2.5MB, pluss.cpp:86-94)
        from .runtime import telemetry
        from .runtime.timing import timed

        times, last, flushes = timed(
            lambda: _run_engine(engine, program, machine, args),
            reps=args.reps,
            flush_kb=machine.cache_kb,
        )
        if args.ledger:
            _cli_ledger_row(
                args, program, engine,
                getattr(last[0], "engine", None) or engine,
                sorted(times)[len(times) // 2],
                compiles0=compiles0, reps=args.reps,
            )
        for rep, dt in enumerate(times):
            print(f"{engine} {program.name} run {rep}: {dt:.6f} s")
        print(
            f"{engine} {program.name}: best {min(times):.6f} s, "
            f"mean {sum(times) / len(times):.6f} s over {len(times)} runs"
        )
        # flush cost is measured OUTSIDE the per-rep seconds (timed's
        # contract); surface it so slow-flush hosts are auditable
        telemetry.gauge(
            "cache_flush_s_per_rep",
            round(sum(flushes) / len(flushes), 6),
        )
        print(
            f"{engine} {program.name}: cache-flush overhead "
            f"{sum(flushes) / len(flushes):.6f} s/rep "
            "(excluded from the timings above)"
        )
        return 0

    def result_lines(eng: str):
        t0 = time.perf_counter()
        if args.ledger:
            from .runtime import telemetry as _t

            run_compiles0 = _t.compile_counters_snapshot()
        res, per_ref = _run_engine(eng, program, machine, args)
        lines: list[str] = []
        if args.mode == "sample" and per_ref is not None:
            # per-ref dumps (r10 prints each per-ref hist, :3277-3293)
            lines += [
                f"ref {r.name}: {r.n_samples} samples, cold {r.cold:g}"
                for r in per_ref
            ]
        lines += report.noshare_dump(res.state)
        lines += report.share_dump(res.state)
        if args.r10:
            if per_ref is None:
                raise SystemExit("--r10 needs a sampled engine (sample mode)")
            from .runtime.cri import r10_distribute

            rih, per_ref_hists = r10_distribute(per_ref, machine.thread_num)
            for name, h in per_ref_hists.items():
                lines += report.histogram_lines(name, h)
        else:
            rih = cri_distribute(
                res.state, machine.thread_num, machine.thread_num
            )
        lines += report.rih_dump(rih)
        mrc = aet_mrc(rih, machine)
        lines += report.mrc_lines(mrc)
        label = "samples" if per_ref is not None else "accesses"
        lines.append(f"max iteration count: {res.total_accesses} {label}")
        if args.ledger:
            # one row per engine execution — the --diff-against second
            # engine gets its own row too
            _cli_ledger_row(
                args, program, eng,
                getattr(res, "engine", None) or eng,
                time.perf_counter() - t0, mrc=mrc,
                compiles0=run_compiles0,
            )
        return lines, mrc

    lines, mrc = result_lines(engine)
    report.emit(lines)
    if args.mrc_out:
        report.write_mrc_to_file(mrc, args.mrc_out)

    if args.diff_against:
        # the reference's acc protocol appends each implementation's
        # dumps to output.txt for manual inspection (run.sh:3-12,
        # README.md:10-12); this automates the comparison
        other_lines, _ = result_lines(args.diff_against)
        if lines != other_lines:
            import difflib

            sys.stdout.writelines(
                difflib.unified_diff(
                    [l + "\n" for l in other_lines],
                    [l + "\n" for l in lines],
                    fromfile=args.diff_against,
                    tofile=engine,
                )
            )
            print(f"acc dumps DIFFER: {engine} vs {args.diff_against}")
            return 1
        print(f"acc dumps identical: {engine} vs {args.diff_against}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
