"""Runtime configuration.

Replaces the reference's compile-time `-D` macros
(`-DTHREAD_NUM=4 -DCHUNK_SIZE=4 -DDS=8 -DCLS=64`, c_lib/test/Makefile:15)
and the per-module Rust consts (src/gemm_sampler.rs:27-30,
src/chunk_dispatcher.rs:18, src/utils.rs:10-11) with one runtime object.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class MachineConfig:
    """Parameters of the *modeled* parallel machine.

    Attributes:
      thread_num: number of simulated OpenMP threads whose interleaving the
        sampler models (THREAD_NUM, c_lib/test/Makefile:15). These are
        modeled threads, not execution threads.
      chunk_size: static-scheduling chunk size in iterations of the
        parallel loop (CHUNK_SIZE, Makefile:15).
      ds: data size in bytes of one array element (DS, Makefile:15).
      cls: cache line size in bytes (CLS, Makefile:15).
      cache_kb: LRU cache capacity in KB used by the AET->MRC stage
        (POLYBENCH_CACHE_SIZE_KB 2560, c_lib/test/runtime/pluss.cpp:9-11;
        cache lines = cache_kb*1024/ds, pluss_utils.h:785).
    """

    thread_num: int = 4
    chunk_size: int = 4
    ds: int = 8
    cls: int = 64
    cache_kb: int = 2560

    @property
    def lines_per_element_block(self) -> int:
        """Array elements per cache line (CLS/DS = 8 by default)."""
        return self.cls // self.ds

    @property
    def cache_lines(self) -> int:
        """Cache capacity in units the AET loop uses (pluss_utils.h:785)."""
        return self.cache_kb * 1024 // self.ds

    def __post_init__(self) -> None:
        if self.cls % self.ds != 0:
            raise ValueError("cls must be a multiple of ds")
        if self.thread_num < 1 or self.chunk_size < 1:
            raise ValueError("thread_num and chunk_size must be >= 1")


@dataclasses.dataclass(frozen=True)
class SamplerConfig:
    """Parameters of the random-start sampling variant.

    The reference bakes these into generated code
    (c_lib/test/sampler/gemm-t4-pluss-pro-model-rs-ri-opt-r10.cpp:132-133,
    156: "random start sampling with ratio 10%", `num_samples = 2098`).

    num_samples per reference follows ceil((ratio * trip)^depth) where
    depth is the loop depth of the reference: at N=128, ratio=0.1 this
    reproduces the generated constants 2098 = ceil(12.8^3) (3-deep refs,
    r10 :156) and 164 = ceil(12.8^2) (2-deep refs, r10 :1688).

    exclude_last_iteration replicates the generated sampling expression
    `rand()%(((128-0)/1-((128-0)%1==0)))` (r10 :159), which draws from
    [0, trip-1) — the final iteration of each loop is never sampled when
    step divides the range evenly. Kept (default True) for parity with the
    reference; set False for uniform coverage.
    """

    ratio: float = 0.1
    seed: int = 0
    exclude_last_iteration: bool = True
    # Upper bound on distinct raw share-reuse values collected device-side
    # per (ref, shard) before host-side exact sparse accumulation.
    max_share_values: int = 64
    # Use the Pallas comparison-ladder histogram kernel
    # (ops/pallas_hist.py) for the sharded engine's dense noshare
    # reduction instead of the portable scatter-add. Default ON from
    # a real-device measurement (2026-07-31, TPU v5e via the axon
    # tunnel): bit-equal to exp_hist at 4M/12k/128 elements and 4.4x
    # faster at 4M intervals (75.9 ms vs 335.7 ms, median of 7) —
    # round-2 verdict weak-point 5 asked for exactly this evidence
    # before the default could be ON. bench.py's hist_kernel block
    # re-measures on device every TPU bench run. (The dispatcher
    # routes to the kernel only on a TPU backend, so the flag is
    # TPU-only in effect.)
    use_pallas_hist: bool = True
    # Draw, dedup, and thin sample keys ON the default device with the
    # threefry counter PRNG (sampler/draw.py) instead of numpy on the
    # host. None = auto: ON for accelerator backends, OFF for CPU —
    # each backend's measured best (GEMM N=1024, 3-rep medians):
    # on a tunneled TPU v5e the host path ships 8 bytes/sample over a
    # ~70 MB/s link with ~70 ms round trips and the device path wins
    # >4x end-to-end; on a host core numpy PCG + np.unique beats
    # threefry + two XLA sorts 2.3x (0.85 s vs 1.93 s). Explicit
    # True/False overrides. Each path is deterministic in the seed;
    # the two paths' sample SETS differ (statistically equivalent —
    # tests/test_draw.py pins the MRC agreement), so recorded per-seed
    # artifacts are comparable only within one path. Refs whose draw
    # buffer exceeds draw.DEVICE_DRAW_MAX_SLOTS fall back to the host
    # path either way.
    device_draw: bool | None = None
    # Cross-ref fused dispatch: refs sharing a kernel-signature bucket
    # (sampler/sampled.py::_kernel_sig) have their padded key buffers
    # stacked along a leading ref axis and classified by ONE vmapped
    # scan-fused dispatch per bucket, instead of one dispatch per ref.
    # Results are bit-identical to the per-ref path (the unique
    # reductions are exact and the per-ref seeds are unchanged), so
    # this is a pure dispatch-overhead knob; OFF preserves the legacy
    # serial loop as the parity oracle. None = auto per backend (like
    # device_draw): ON off-CPU, where every dispatch pays a round trip
    # worth amortizing; OFF on CPU, where dispatch is cheap and the
    # vmap-safe sorted merge costs more than the dispatches it saves
    # (measured ~1.3x per element, gemm N=1024).
    fuse_refs: bool | None = None
    # Which classify+histogram kernel implementation the sampled
    # engine's hot loop runs: "xla" (the scan/fused jit kernels,
    # the parity oracle), "pallas" (ops/pallas_sampled.py — the
    # draw-stream classify + comparison-ladder pow2 accumulation in
    # one on-chip kernel; interpret mode on CPU), "native" (the
    # SIMD batched classify+histogram entry in native/, CPU only,
    # via ctypes), or None/"auto" = "xla". Auto deliberately does NOT
    # pick native-on-CPU: the hist backends ladder-bin noshare reuse
    # inside the per-ref RESULT objects, and several standing
    # contracts compare those raw results across code paths
    # (fused-vs-serial, batched-vs-solo, checkpoint replay) that
    # would otherwise resolve differently — so "native"/"pallas" are
    # explicit per-call opt-ins whose callers consume folded states.
    # All three backends fold to bit-identical PRIStates/MRCs (pow2
    # binning is exact over integer counts; sub-1 and share reuse
    # ride an exact residual-pair stream), so like fuse_refs this is
    # a pure speed knob and stays OUT of the request fingerprint.
    # v2 raw-noshare runs force "xla" (the hist backends bin noshare
    # by construction).
    kernel_backend: str | None = None
    # Persistent XLA compilation cache directory (satellite of the
    # replica-pool PR): when set, the sampled entry points wire it into
    # jax.config ("jax_compilation_cache_dir") with the minimum
    # compile-time threshold dropped to 0 so even the CPU engines'
    # fast-compiling kernels persist. A warm second PROCESS then loads
    # executables instead of recompiling — its ledger rows record
    # smaller compile-counter deltas (pinned by tests/test_replicas.py
    # via subprocess). None = leave jax's global setting alone (the
    # CLI's --compilation-cache-dir sets this and the global config).
    compilation_cache_dir: str | None = None
    # Depth bound of the async dispatch pipeline: how many in-flight
    # dispatches (fused buckets, or host chunks on the legacy path)
    # may await their fetch before the oldest is drained. Each
    # in-flight entry pins one dispatch's output (and, fused, its
    # stacked input buffer) on device; raising it buys more
    # host/device overlap at that memory cost. A forced drain counts
    # as `pipeline_stalls` in telemetry.
    pipeline_depth: int = 4
    # Progressive-precision knobs (sampler/sampled.py::
    # run_sampled_progressive + sampler/confidence.py). The driver
    # splits the FINAL ratio's per-ref sample stream into prefix
    # rounds; after every round a seeded bootstrap over the per-ref
    # round sub-histograms yields an MRC confidence band. tolerance:
    # stop early once the band's max width is <= this (None = run the
    # whole schedule). round_schedule: increasing fractions of the
    # final per-ref sample count, last entry 1.0 (None = geometric
    # doubling over max_rounds). max_rounds: schedule length when
    # round_schedule is None (None = DEFAULT_MAX_ROUNDS). Because the
    # rounds are prefix slices of the SAME seed-derived stream, a run
    # that completes its schedule folds to MRC bytes bit-identical to
    # the one-shot sampled run at cfg.ratio — so, like fuse_refs/
    # pipeline_depth, these knobs stay OUT of the request fingerprint.
    tolerance: float | None = None
    max_rounds: int | None = None
    round_schedule: tuple | None = None

    def num_samples(self, trips) -> int:
        import math

        if isinstance(trips, int):
            trips = (trips,)
        prod = 1.0
        space = 1
        for t in trips:
            prod *= self.ratio * t
            space *= max(1, t - 1 if self.exclude_last_iteration else t)
        return max(1, min(int(math.ceil(prod)), space))


@dataclasses.dataclass(frozen=True)
class BatchConfig:
    """Admission-window parameters of the service's cross-request
    batching scheduler (service/executor.py::BatchScheduler).

    Pure scheduling knobs: batching changes WHICH dispatches run, never
    what any member computes — every member's MRC is bit-identical to
    its solo run (sampler/sampled.py::sampled_outputs_multi), so like
    fuse_refs/pipeline_depth these stay OUT of the request fingerprint.

    Attributes:
      window_ms: how long the first request of a forming batch may wait
        for compatible companions before the batch flushes. 0 still
        batches whatever arrived together but never waits.
      max_refs: flush early once the batch's summed tracked-ref count
        reaches this bound; a later overflow request starts the next
        batch (overflow splitting).
    """

    window_ms: float = 5.0
    max_refs: int = 64

    def __post_init__(self) -> None:
        if self.window_ms < 0:
            raise ValueError("window_ms must be >= 0")
        if self.max_refs < 1:
            raise ValueError("max_refs must be >= 1")


@dataclasses.dataclass(frozen=True)
class ReplicaConfig:
    """Device partitioning of the serving replica pool
    (service/replicas.py::ReplicaPool).

    The pool splits `jax.devices()` into `count` disjoint device
    groups; each replica owns its group, a per-replica mesh
    (parallel/mesh.py::build_mesh over just those devices), and an
    execution slot. Like BatchConfig this is a pure scheduling knob:
    engine placement moves WHERE a request runs, never what it
    computes — the per-ref sample streams are seed-derived, so MRC
    bytes are bit-identical for any replica count (the invariant
    tests/test_replicas.py pins at counts 1/2/4) and `count` stays OUT
    of the request fingerprint.

    Attributes:
      count: number of replicas. None or 0 = auto, one replica per
        device. A count above the device count clamps down (a replica
        needs at least one device).
    """

    count: int | None = None

    def __post_init__(self) -> None:
        if self.count is not None and self.count < 0:
            raise ValueError("replica count must be >= 0 (0 = auto)")

    def resolve(self, n_devices: int) -> int:
        """Actual replica count for a machine with n_devices."""
        if n_devices < 1:
            raise ValueError("need at least one device")
        if not self.count:  # None or 0: one replica per device
            return n_devices
        return min(self.count, n_devices)


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Service-level objectives evaluated by the burn-rate sentinel
    (runtime/obs/slo.py) over the live metrics registry's rolling
    windows and the ledger tail.

    Burn-rate semantics (the SRE multi-window formulation): each
    objective defines a budget — the fraction of requests allowed to
    violate it. The observed violation fraction divided by the budget
    is the burn rate (1.0 = consuming budget exactly as fast as
    allowed); a breach fires only when the burn rate exceeds
    `burn_rate_threshold` in BOTH the short and the long window, so a
    single slow request can't page anyone but a sustained regression
    fires within one short window.

    Attributes:
      latency_p95_s: total-latency objective — at most
        `latency_budget` of requests may take longer than this.
        None disables the latency check.
      latency_budget: allowed slow fraction for the latency objective
        (0.05 makes `latency_p95_s` a true p95 bound).
      error_budget: allowed fraction of requests that fail or complete
        degraded.
      burn_rate_threshold: multi-window burn-rate trip point.
      min_batch_occupancy: breach when the ledger's batch occupancy
        p50 falls below this (None disables; only meaningful under a
        batched workload).
      windows: (short, long) rolling-window labels, matching the
        registry's ring windows.
    """

    latency_p95_s: float | None = None
    latency_budget: float = 0.05
    error_budget: float = 0.01
    burn_rate_threshold: float = 1.0
    min_batch_occupancy: float | None = None
    windows: tuple = ("30s", "5m")

    def __post_init__(self) -> None:
        if self.latency_p95_s is not None and self.latency_p95_s <= 0:
            raise ValueError("latency_p95_s must be > 0")
        if not (0 < self.latency_budget <= 1):
            raise ValueError("latency_budget must be in (0, 1]")
        if not (0 < self.error_budget <= 1):
            raise ValueError("error_budget must be in (0, 1]")
        if self.burn_rate_threshold <= 0:
            raise ValueError("burn_rate_threshold must be > 0")
        if len(self.windows) != 2:
            raise ValueError("windows must be (short, long)")


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """Failure-handling policy of the request executor
    (service/executor.py) and the replica pool (service/replicas.py).

    Everything here is serving policy — retries, hedges, breakers, and
    admission control move WHEN and WHERE a request runs, never what
    it computes (retried/hedged results are seed-derived and therefore
    bit-identical to the first attempt; tools/check_chaos.py pins
    this) — so none of these knobs enter the request fingerprint.

    Attributes:
      attempt_timeout_s: per-attempt execution budget. An attempt that
        outlives it is abandoned (deadline_abandoned) and retried or
        degraded; None leaves only the request deadline in force.
      max_retries: bounded same-engine retries after a failed or
        timed-out attempt (0 = the pre-chaos behavior: fall straight
        down the degrade chain).
      backoff_base_s / backoff_max_s: exponential backoff bounds
        between retries. The jitter is SEEDED (runtime/faults.py::
        backoff_delay, a counter-hash construction), never wall-clock
        derived — tools/lint_determinism.py enforces this.
      backoff_seed: seed of that jitter stream.
      hedge_after_s: straggler bound — a routed execution still
        unresolved after this long is hedged onto a second replica;
        first result wins, the queued loser is cancelled. None
        disables hedging (and it is implicitly off without a pool of
        at least two replicas).
      breaker_failures: consecutive engine-attempt failures that open
        an engine's circuit breaker (service/breakers.py). Open
        breakers fail fast / degrade instead of burning an attempt.
      breaker_probation_s: how long a breaker stays open before
        half-open probation admits ONE probe; a probe failure re-opens
        with the probation escalated (x `breaker_escalation`, capped
        at `breaker_probation_max_s`). Also the replica pool's
        quarantine probation: a quarantined replica re-enters service
        through the same half-open probe cycle.
      breaker_escalation / breaker_probation_max_s: the escalation
        factor and cap above.
      queue_limit: admission bound on queued-not-yet-executing
        requests. None = unbounded (no admission control).
      shed_enabled: when a queue_limit is set, shed early at submit
        with a structured `shed` response instead of queueing past the
        limit. False keeps the limit visible in stats but never sheds
        (the chaos gate's collapse baseline).
    """

    attempt_timeout_s: float | None = None
    max_retries: int = 0
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    backoff_seed: int = 0
    hedge_after_s: float | None = None
    breaker_failures: int = 8
    breaker_probation_s: float = 30.0
    breaker_escalation: float = 2.0
    breaker_probation_max_s: float = 300.0
    queue_limit: int | None = None
    shed_enabled: bool = True

    def __post_init__(self) -> None:
        if (self.attempt_timeout_s is not None
                and self.attempt_timeout_s <= 0):
            raise ValueError("attempt_timeout_s must be > 0")
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.backoff_base_s < 0 or self.backoff_max_s < 0:
            raise ValueError("backoff bounds must be >= 0")
        if self.hedge_after_s is not None and self.hedge_after_s <= 0:
            raise ValueError("hedge_after_s must be > 0")
        if self.breaker_failures < 1:
            raise ValueError("breaker_failures must be >= 1")
        if self.breaker_probation_s <= 0:
            raise ValueError("breaker_probation_s must be > 0")
        if self.breaker_escalation < 1:
            raise ValueError("breaker_escalation must be >= 1")
        if self.queue_limit is not None and self.queue_limit < 1:
            raise ValueError("queue_limit must be >= 1")


@dataclasses.dataclass(frozen=True)
class FabricConfig:
    """Connection-health policy of the multi-process serving fabric
    (service/fabric/): the router<->worker heartbeat cadence, the
    bounded reconnect schedule, and the drain budget.

    Everything here is transport policy — it moves WHEN a request
    frame travels and how quickly a dead worker is declared, never
    what any worker computes: re-dispatched requests are re-submitted
    byte-identically (the raw request line is what travels), so MRC
    bytes and fingerprints are bit-identical whatever these knobs say
    (tests/test_fabric.py pins 1-vs-N-worker identity).

    Attributes:
      hb_interval_s: how often the router pings each worker link (and
        the bound on how long a healthy link stays silent — pongs
        count as traffic).
      hb_timeout_s: a link with no received frame for this long is
        treated as failed and enters the reconnect schedule.
      reconnect_attempts: bounded reconnects after a link failure;
        exhausting them declares the worker DEAD and re-dispatches
        its in-flight requests to the ring successor.
      reconnect_delay_s: pause between reconnect attempts.
      connect_timeout_s: TCP connect/handshake budget per attempt.
      drain_timeout_s: graceful-shutdown bound — how long the router
        waits for in-flight responses (and workers for in-flight
        executions) before giving up the drain.
      ring_vnodes: virtual nodes per worker on the consistent-hash
        ring (service/fabric/ring.py).
      trace_enabled: attach trace blocks to request frames, measure
        per-request wire/worker spans, and append router-side ledger
        rows. Pure observability — toggling it never changes MRC
        bytes or fingerprints (pinned in tests/test_fabric.py).
      stats_interval_s: how often the router polls each worker's
        telemetry snapshot over a `stats` frame (feeds the merged
        fleet stats/metrics view and the fleet SLO sentinel).
    """

    hb_interval_s: float = 2.0
    hb_timeout_s: float = 10.0
    reconnect_attempts: int = 3
    reconnect_delay_s: float = 0.2
    connect_timeout_s: float = 10.0
    drain_timeout_s: float = 60.0
    ring_vnodes: int = 64
    trace_enabled: bool = True
    stats_interval_s: float = 5.0

    def __post_init__(self) -> None:
        if self.hb_interval_s <= 0:
            raise ValueError("hb_interval_s must be > 0")
        if self.hb_timeout_s < self.hb_interval_s:
            raise ValueError(
                "hb_timeout_s must be >= hb_interval_s (a healthy "
                "link is only guaranteed one frame per interval)"
            )
        if self.reconnect_attempts < 0:
            raise ValueError("reconnect_attempts must be >= 0")
        if self.reconnect_delay_s < 0:
            raise ValueError("reconnect_delay_s must be >= 0")
        if self.connect_timeout_s <= 0:
            raise ValueError("connect_timeout_s must be > 0")
        if self.drain_timeout_s <= 0:
            raise ValueError("drain_timeout_s must be > 0")
        if self.ring_vnodes < 1:
            raise ValueError("ring_vnodes must be >= 1")
        if self.stats_interval_s <= 0:
            raise ValueError("stats_interval_s must be > 0")


# Sites and kinds the fault injector (runtime/faults.py) understands.
# Declared here so FaultConfig can validate a spec without importing
# the runtime layer.
FAULT_SITES = ("engine_execute", "replica_dispatch", "cache_load",
               "cache_store", "serve_line", "worker_conn",
               "worker_exec", "round_exec")
FAULT_KINDS = ("raise", "latency", "hang", "corrupt", "compile_failure",
               "disconnect")


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """A deterministic chaos scenario: (seed, rules) fully determine
    every injection decision (runtime/faults.py draws a counter-hash
    uniform per (site, key, occurrence) — a threefry-style counter
    construction — so a chaos run replays exactly from this object).

    Each rule is a mapping with:
      site: one of FAULT_SITES (where the fault fires)
      kind: one of FAULT_KINDS (what happens)
      p: firing probability per occurrence (default 1.0)
      max_fires: cap per (rule, key) — e.g. "fail only the first
        attempt of each request" (0 = unlimited)
      match: {ctx-field: value} equality filter on the site's context
        (e.g. {"engine": "sampled"})
      latency_s / hang_s: sleep durations for those kinds
      message: raise text override

    CLI: `--fault-spec FILE` loads a JSON document
    {"seed": N, "rules": [...]} (runtime/faults.py::load_spec).
    """

    seed: int = 0
    rules: tuple = ()

    def __post_init__(self) -> None:
        object.__setattr__(self, "rules", tuple(self.rules))
        for i, rule in enumerate(self.rules):
            if not isinstance(rule, dict):
                raise ValueError(f"rules[{i}] must be an object")
            site = rule.get("site")
            if site not in FAULT_SITES:
                raise ValueError(
                    f"rules[{i}].site {site!r} unknown "
                    f"(have {', '.join(FAULT_SITES)})"
                )
            kind = rule.get("kind")
            if kind not in FAULT_KINDS:
                raise ValueError(
                    f"rules[{i}].kind {kind!r} unknown "
                    f"(have {', '.join(FAULT_KINDS)})"
                )
            p = rule.get("p", 1.0)
            if not isinstance(p, (int, float)) or not 0 <= p <= 1:
                raise ValueError(f"rules[{i}].p must be in [0, 1]")
            mf = rule.get("max_fires", 0)
            if not isinstance(mf, int) or mf < 0:
                raise ValueError(
                    f"rules[{i}].max_fires must be an int >= 0"
                )
            match = rule.get("match", {})
            if not isinstance(match, dict):
                raise ValueError(f"rules[{i}].match must be an object")


DEFAULT_MACHINE = MachineConfig()
