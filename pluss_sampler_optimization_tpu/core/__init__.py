from .schedule import StaticSchedule
from .trace import NestTrace, ProgramTrace

__all__ = ["StaticSchedule", "NestTrace", "ProgramTrace"]
