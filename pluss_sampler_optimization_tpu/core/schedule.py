"""Closed-form OpenMP static-chunk scheduling arithmetic.

Replaces the reference's stateful `ChunkDispatcher`
(c_lib/test/runtime/pluss_utils.h:287-618, src/chunk_dispatcher.rs) with
index math. The dispatcher hands chunk `c` (CHUNK_SIZE consecutive
parallel-loop iterations) to simulated thread c % THREAD_NUM
(getNextStaticChunk, pluss_utils.h:410-425; per-thread start points
advance by chunk_size*THREAD_NUM*step, :420). The derived per-iteration
quantities below are the closed forms the reference itself documents:

  tid(i) = ((i-start)/step)/chunk_size mod THREAD_NUM
                                   (getStaticTid, pluss_utils.h:429-431)
  cid(i) = floor(((i-start)/step) / (chunk_size*THREAD_NUM))
                                   (getStaticChunkID, :433-435)
  pos(i) = ((i-start)/step) mod chunk_size
                                   (getStaticThreadLocalPos, :437-439)

plus the inverse map (thread-local index -> iteration value) that the
array engines need and the reference never materializes. Every function
is plain integer arithmetic and works elementwise on Python ints, numpy
arrays and traced jax arrays alike.

Only the static schedule is implemented. The C++ dispatcher carries a
FIFO dynamic-chunk arm (getNextChunk/hasNextChunk(false),
pluss_utils.h:391-411) but no live sampler ever drives it (every
generated walk calls getNextStaticChunk; the Rust port leaves the
dynamic trait `unimplemented!`, src/chunk_dispatcher.rs:34-69) — and
under the model's uniform interleaving, threads request chunks in tid
order, so FIFO assignment would reproduce the round-robin static map
anyway. It stays out by design.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class StaticSchedule:
    """Static chunk schedule of one parallel loop.

    `trip`, `start`, `step` describe the parallel loop (level 0);
    `chunk` is CHUNK_SIZE and `threads` is THREAD_NUM.
    Normalized index n = (i - start) // step ranges over [0, trip).
    """

    trip: int
    chunk: int
    threads: int
    start: int = 0
    step: int = 1

    # -- global-iteration queries (forward maps) ---------------------------

    def normalize(self, value):
        """Iteration value -> normalized index n."""
        return (value - self.start) // self.step

    def value(self, n):
        """Normalized index -> iteration value."""
        return self.start + n * self.step

    def owner_tid(self, n):
        """Simulated thread that executes normalized iteration n."""
        return (n // self.chunk) % self.threads

    def local_chunk_id(self, n):
        """Thread-local chunk id (cid) of normalized iteration n."""
        return n // (self.chunk * self.threads)

    def chunk_pos(self, n):
        """Position within its chunk (pos) of normalized iteration n."""
        return n % self.chunk

    def local_index(self, n):
        """Index of n within its owner thread's own iteration sequence.

        Only the globally-last chunk can be short, so every preceding
        chunk of the owner contributes exactly `chunk` iterations.
        """
        return self.local_chunk_id(n) * self.chunk + self.chunk_pos(n)

    # -- per-thread queries (inverse maps) ----------------------------------

    @property
    def n_chunks(self) -> int:
        return -(-self.trip // self.chunk)

    @property
    def last_chunk_len(self) -> int:
        rem = self.trip % self.chunk
        return rem if rem else self.chunk

    def local_count(self, tid: int) -> int:
        """Number of parallel-loop iterations simulated thread `tid` runs."""
        nch = self.n_chunks
        if tid >= nch:
            return 0
        mine = (nch - 1 - tid) // self.threads + 1
        total = mine * self.chunk
        if (nch - 1) % self.threads == tid:
            total += self.last_chunk_len - self.chunk
        return total

    def max_local_count(self) -> int:
        return max(self.local_count(t) for t in range(self.threads))

    def local_to_normalized(self, tid, m):
        """Thread-local index m of thread tid -> normalized iteration n."""
        cid = m // self.chunk
        pos = m % self.chunk
        return (cid * self.threads + tid) * self.chunk + pos

    def local_to_value(self, tid, m):
        return self.value(self.local_to_normalized(tid, m))

    def count_below(self, tid, n):
        """How many of thread `tid`'s iterations have normalized index
        < n — equivalently, the smallest thread-local index m whose
        global index is >= n. Elementwise over arrays; the caller
        clamps n to [0, trip]."""
        kp = self.chunk * self.threads
        q = n // kp
        r = n - q * kp - tid * self.chunk
        r = r.clip(0, self.chunk) if hasattr(r, "clip") else max(
            0, min(self.chunk, r)
        )
        return q * self.chunk + r


def interleaved_order_key(nest_trace, ref_idx: int, samples):
    """Interleaved-execution order of same-reference samples, as one
    int64 sort key.

    The reference's sampled variant processes each reference's random
    samples through a priority queue ordered by `IterationComp`
    (Iteration::compare, src/iteration.rs:63-134; same logic in
    pluss_utils.h:95-164): chunk round (cid) first, then position
    within the chunk, then the inner loop variables — the simulated
    thread id is deliberately never compared, because the uniform
    interleaving advances all threads' equal-cid/pos iterations
    together. Per-reference queues never compare across references, so
    the trailing priority tiebreak (ref program order) never fires
    there; sorting by this key reproduces the queue's pop order for
    the samples of one reference.

    `samples` is an (S, depth) array of normalized indices (as produced
    by sampler/sampled.py::draw_samples); returns (S,) int64 keys whose
    ascending order is the interleaved execution order.
    """
    import numpy as np

    t = nest_trace.tables
    sched = nest_trace.schedule
    lv = int(t.ref_levels[ref_idx])
    # widen the int32 wire format before radix math; .astype keeps
    # numpy arrays numpy and traced jax arrays traced
    samples = samples.astype(np.int64)
    n0 = samples[:, 0]
    key = sched.local_index(n0)  # (cid, pos) collapsed, tid excluded
    for l in range(1, lv + 1):
        # max_trips == trips for rectangular nests; triangular indices
        # range up to the nest-wide max trip
        key = key * int(nest_trace.max_trips[l]) + samples[:, l]
    return key


def dynamic_chunk_assignment(n_chunks: int, threads: int, chunk_costs):
    """FIFO chunk handout of the reference's dynamic dispatcher arm.

    `ChunkDispatcher.hasNextChunk(false)` / `getNextChunk`
    (pluss_utils.h:367-409; Rust stub surface chunk_dispatcher.rs:34-69)
    hand chunks to requesting threads in arrival order instead of the
    static round-robin. No live reference sampler calls this arm (every
    generated sampler passes isStatic=true or uses the static API), so
    there is no generated-code behavior to byte-match; the model here
    follows the uniform-interleaving machine the rest of the framework
    simulates: every simulated thread advances one access per turn, a
    thread requests its next chunk on the turn its current chunk
    completes, and simultaneous requests are served in tid order (the
    worker-list iteration order of the generated walks).

    With equal chunk costs — every rectangular nest, where each parallel
    iteration performs the same accesses — each request round resolves
    in tid order and the assignment IS the static round-robin; that
    closed-form equivalence is why the static arm alone reproduces the
    reference's live behavior (tests/test_schedule.py pins it). Costs
    only diverge for triangular nests.

    `chunk_costs[i]` = accesses in chunk i; returns per-tid lists of
    chunk indices in execution order.
    """
    import heapq

    ready = [(0, t) for t in range(threads)]
    heapq.heapify(ready)
    out: list = [[] for _ in range(threads)]
    for ci in range(n_chunks):
        turn, tid = heapq.heappop(ready)
        out[tid].append(ci)
        heapq.heappush(ready, (turn + int(chunk_costs[ci]), tid))
    return out
