"""Per-simulated-thread trace codec: the state machine as index math.

The reference walks the interleaved iteration space with a per-thread
state machine (`Progress` cursor + while(true) dispatch,
...ri-omp-seq.cpp:68-301) and counts accesses in `count[tid]`
(:45, incremented once per access). Two facts make that walk a
closed-form indexed sequence:

1. `count[tid]` IS the thread-local trace position: every access of
   simulated thread t increments only count[t], so the "time" recorded
   in the last-access tables (LAT_X[tid][addr] = count[tid], :119) is
   the position of that access in t's own stream, and a reuse interval
   (:110) is a difference of positions in that stream.
2. The stream itself is a mixed-radix enumeration of the loop nest:
   thread t executes its chunks in dispatch order
   (getNextStaticChunk, pluss_utils.h:410-425), and each parallel-loop
   iteration performs the same statically-known body access sequence
   (the ri-opt variant already straight-lines it,
   ...ri-opt.cpp:101-263).

So position(t, m, n1, n2, ref) =
    m * acc[0] + npre[0] + n1 * acc[1] + npre[1] + n2 * acc[2] + off(ref)
(terms beyond the ref's level dropped), where m is the thread-local
index of the parallel iteration, n_l the normalized inner indices,
acc[l] the per-level body access counts and off the ref's offset within
its level's body. Interleaving across simulated threads never enters RI
values — it only exists in the CRI probability model, exactly as in the
reference.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..config import MachineConfig
from ..ir import NestTables, Program, nest_tables
from .schedule import StaticSchedule


class NestTrace:
    """Static trace geometry of one parallel nest."""

    def __init__(self, program: Program, nest_index: int, machine: MachineConfig):
        self.machine = machine
        self.nest = program.nests[nest_index]
        self.tables: NestTables = nest_tables(
            program, nest_index, machine.thread_num - 1
        )
        lp0 = self.nest.loops[0]
        self.schedule = StaticSchedule(
            trip=lp0.trip,
            chunk=machine.chunk_size,
            threads=machine.thread_num,
            start=lp0.start,
            step=lp0.step,
        )
        self.npre = tuple(
            len(self.nest.refs_at(l, "pre")) for l in range(self.nest.depth)
        )
        self.npost = tuple(
            len(self.nest.refs_at(l, "post")) for l in range(self.nest.depth)
        )
        # The value overlay: every N-dependent number the TRACED engine
        # code reads, as arrays. Host engines read the same concrete
        # numpy defaults (identical numerics); the sampled kernels swap
        # in traced jnp arrays via with_vals() so one compiled kernel
        # serves every N with the same structure (sampler/sampled.py::
        # _kernel_sig). Structure (levels, steps, slots, npre, ...)
        # always comes from the concrete fields. Built before the
        # triangular tables: body_at/trip_at read it.
        t = self.tables
        self.vals = {
            "acc": t.acc_per_level,
            "off": t.ref_offsets,
            "coeff": t.ref_coeffs,
            "const": t.ref_consts,
            "thr": t.ref_share_thresholds,
            "trips": t.trips,
            "startb": t.starts,
            "lc": np.array(
                [self.schedule.local_count(tt) for tt in range(
                    self.schedule.threads)],
                dtype=np.int64,
            ),
            "vlo": np.array(
                [self.level_value_range(l)[0] for l in range(self.nest.depth)]
                + [0] * (len(t.trips) - self.nest.depth),
                dtype=np.int64,
            ),
            "vhi": np.array(
                [self.level_value_range(l)[1] for l in range(self.nest.depth)]
                + [0] * (len(t.trips) - self.nest.depth),
                dtype=np.int64,
            ),
        }
        # Triangular nests (inner bounds affine in the parallel value):
        # body sizes vary per parallel iteration, so the per-thread
        # position bases are prefix sums over the thread's dispatch
        # order instead of m * acc[0]. The table is small (threads x
        # local parallel iterations) and shared by every engine.
        self.tri = self.nest.is_triangular
        if self.tri:
            P = self.schedule.threads
            lmax = self.schedule.max_local_count()
            base = np.zeros((P, lmax + 1), dtype=np.int64)
            for tid in range(P):
                lc = self.schedule.local_count(tid)
                if lc:
                    m = np.arange(lc, dtype=np.int64)
                    v0 = self.schedule.local_to_value(tid, m)
                    base[tid, 1 : lc + 1] = np.cumsum(self.body_at(0, v0))
                base[tid, lc + 1 :] = base[tid, lc]
            self.tri_base = base
            v0_all = lp0.start + np.arange(lp0.trip, dtype=np.int64) * lp0.step
            self.max_trips = tuple(
                int(np.max(self.trip_at(l, v0_all)))
                for l in range(self.nest.depth)
            )
            self.max_body0 = int(np.max(self.body_at(0, v0_all)))
        else:
            self.max_trips = tuple(lp.trip for lp in self.nest.loops)
            self.max_body0 = int(self.acc[0])
        if self.tri:
            self.vals["tri_base"] = self.tri_base

    def with_vals(self, vals: dict) -> "NestTrace":
        """Shallow copy with the value overlay swapped (traced arrays
        inside a jit; concrete arrays otherwise). Structural fields are
        shared with self and MUST agree with the overlay's provenance —
        the kernel signature (sampler/sampled.py::_kernel_sig) is the
        contract that makes a structure-equal trace's values safe here."""
        import copy

        c = copy.copy(self)
        c.vals = vals
        return c

    @property
    def acc(self) -> np.ndarray:
        return self.tables.acc_per_level

    def trip_at(self, level: int, v0):
        """Level trip count at parallel value v0 (elementwise).

        Reads the trip base from the value overlay so traced kernels
        stay N-generic; the affine coefficient is structural."""
        tc = int(self.tables.trip_coeffs[level])
        base = self.vals["trips"][level]
        if tc == 0:
            return v0 * 0 + base
        return (base + tc * v0).clip(0)

    def start_at(self, level: int, v0):
        """First iteration value of a level at parallel value v0
        (elementwise; overlay-aware twin of Loop.start_at)."""
        sc = int(self.tables.start_coeffs[level])
        return self.vals["startb"][level] + sc * v0

    def body_at(self, level: int, v0):
        """Accesses of ONE full level-`level` iteration at parallel
        value v0 (elementwise over arrays; constant when rectangular)."""
        n = self.npre[level] + self.npost[level]
        if level + 1 < self.nest.depth:
            n = n + self.trip_at(level + 1, v0) * self.body_at(level + 1, v0)
        return n

    def ref_offset_at(self, ref_idx: int, v0):
        """Body offset of a ref within its level's iteration at v0."""
        r = self.nest.refs[ref_idx]
        pre = self.nest.refs_at(r.level, "pre")
        if r.slot == "pre":
            return pre.index(r)
        inner = (
            self.trip_at(r.level + 1, v0) * self.body_at(r.level + 1, v0)
            if r.level + 1 < self.nest.depth
            else 0
        )
        return len(pre) + inner + self.nest.refs_at(r.level, "post").index(r)

    def tri_position(self, ref_idx: int, v0, base, n1=0, n2=0):
        """Thread-local position in a triangular nest.

        `base` = accesses the thread performed before this parallel
        iteration (tri_base[tid, m] or a traced gather of it), `v0` the
        parallel value; elementwise over arrays.
        """
        lv = int(self.tables.ref_levels[ref_idx])
        p = base + self.ref_offset_at(ref_idx, v0)
        if lv >= 1:
            p = p + self.npre[0] + n1 * self.body_at(1, v0)
        if lv >= 2:
            p = p + self.npre[1] + n2 * self.body_at(2, v0)
        return p

    def level_value_range(self, level: int) -> tuple[int, int]:
        """[min, max] iteration value a level can take across the nest
        (exact for triangular levels: evaluated over the parallel
        values that give the level at least one iteration)."""
        lp = self.nest.loops[level]
        if level == 0 or not lp.is_triangular:
            return min(lp.start, lp.last), max(lp.start, lp.last)
        lp0 = self.nest.loops[0]
        v0 = lp0.start + np.arange(lp0.trip, dtype=np.int64) * lp0.step
        trips = lp.trip_at(v0)
        live = trips > 0
        if not live.any():
            return lp.start, lp.start
        first = lp.start_at(v0[live])
        last = first + (trips[live] - 1) * lp.step
        return int(min(first.min(), last.min())), int(
            max(first.max(), last.max())
        )

    def tid_length(self, tid: int) -> int:
        """Total accesses simulated thread `tid` performs in this nest."""
        if self.tri:
            return int(self.tri_base[tid, self.schedule.local_count(tid)])
        return self.schedule.local_count(tid) * int(self.acc[0])

    def access_position(self, ref_idx: int, m, n1=0, n2=0, rx=None):
        """Thread-local position of one access; elementwise over arrays.

        `m` is the thread-local parallel-iteration index; n1/n2 are
        normalized inner-loop indices (ignored beyond the ref's level).
        Rectangular nests only — triangular positions need the
        per-thread base table (tri_position). `rx` (default ref_idx)
        is the index used for VALUE lookups — a traced scalar in the
        shared sampled kernels, letting structurally identical refs
        (same level/array) reuse one compile while their offsets ride
        in as operands; ref_idx always supplies the static structure.
        """
        if self.tri:
            raise NotImplementedError(
                "access_position is undefined for triangular nests; "
                "use tri_position with tri_base"
            )
        rx = ref_idx if rx is None else rx
        level = int(self.tables.ref_levels[ref_idx])
        acc = self.vals["acc"]
        p = m * acc[0] + self.vals["off"][rx]
        if level >= 1:
            p = p + self.npre[0] + n1 * acc[1]
        if level >= 2:
            p = p + self.npre[1] + n2 * acc[2]
        return p

    def ref_flat(self, ref_idx: int, v0, v1=0, v2=0):
        """Affine flat element index from loop *values* (not normalized)."""
        c = self.vals["coeff"][ref_idx]
        return v0 * c[0] + v1 * c[1] + v2 * c[2] + self.vals["const"][ref_idx]

    def ref_addr(self, ref_idx: int, v0, v1=0, v2=0):
        """Cache-line address: flat*DS//CLS (GetAddress_*, ...ri-omp-seq.cpp:12-35)."""
        m = self.machine
        return self.ref_flat(ref_idx, v0, v1, v2) * m.ds // m.cls

    def iter_values(self, level: int, n):
        lp = self.nest.loops[level]
        return lp.start + n * lp.step

    def ref_space(self, ref_idx: int) -> tuple[int, ...]:
        """Iteration-space shape of one ref (trips of its enclosing loops)."""
        level = int(self.tables.ref_levels[ref_idx])
        return tuple(lp.trip for lp in self.nest.loops[: level + 1])

    def enumerate_ref(
        self, tid: int, ref_idx: int, schedule=None,
        m_lo: int = 0, m_hi: int | None = None,
    ):
        """All accesses of (tid, ref): returns (positions, addrs) int64.

        Vectorized numpy enumeration; the concatenation over refs is the
        thread's complete access stream (in arbitrary order — the
        position array carries the ordering). `schedule` overrides the
        nest's round-robin static schedule (any object with
        local_count/local_to_value; the executing profiler passes its
        contiguous row-block split, oracle/profiler.py). `m_lo`/`m_hi`
        restrict to a window of thread-local parallel iterations so
        long traces can stream in bounded memory (runtime/debug.py).
        """
        sched = schedule if schedule is not None else self.schedule
        level = int(self.tables.ref_levels[ref_idx])
        L = sched.local_count(tid)
        L = L if m_hi is None else min(L, m_hi)
        if L <= m_lo:
            z = np.zeros(0, dtype=np.int64)
            return z, z.copy()
        m = np.arange(m_lo, L, dtype=np.int64)
        v0 = sched.local_to_value(tid, m)
        if self.tri:
            return self._enumerate_ref_tri(tid, ref_idx, m, v0, sched)
        if level == 0:
            pos = self.access_position(ref_idx, m)
            addr = self.ref_addr(ref_idx, v0)
            return pos.astype(np.int64), addr.astype(np.int64)
        t1 = self.nest.loops[1].trip
        n1 = np.arange(t1, dtype=np.int64)
        if level == 1:
            pos = self.access_position(ref_idx, m[:, None], n1[None, :])
            addr = self.ref_addr(
                ref_idx, v0[:, None], self.iter_values(1, n1)[None, :]
            )
            addr = np.broadcast_to(addr, pos.shape)
            return pos.ravel().astype(np.int64), addr.ravel().astype(np.int64)
        t2 = self.nest.loops[2].trip
        n2 = np.arange(t2, dtype=np.int64)
        pos = self.access_position(
            ref_idx, m[:, None, None], n1[None, :, None], n2[None, None, :]
        )
        addr = self.ref_addr(
            ref_idx,
            v0[:, None, None],
            self.iter_values(1, n1)[None, :, None],
            self.iter_values(2, n2)[None, None, :],
        )
        addr = np.broadcast_to(addr, pos.shape)
        return pos.ravel().astype(np.int64), addr.ravel().astype(np.int64)

    def _enumerate_ref_tri(self, tid, ref_idx, m, v0, sched):
        """Triangular-nest enumeration: ragged inner grids via masks.

        Requires the nest's own static schedule (tri_base is built for
        it); alternative schedules would need their own base tables.
        """
        assert sched is self.schedule, (
            "triangular enumeration supports the nest schedule only"
        )
        level = int(self.tables.ref_levels[ref_idx])
        base = self.tri_base[tid, m]
        if level == 0:
            pos = self.tri_position(ref_idx, v0, base)
            addr = np.broadcast_to(self.ref_addr(ref_idx, v0), pos.shape)
            return pos.astype(np.int64), addr.astype(np.int64).copy()
        lp1 = self.nest.loops[1]
        t1 = lp1.trip_at(v0)
        n1 = np.arange(int(t1.max(initial=0)), dtype=np.int64)
        mask = n1[None, :] < t1[:, None]
        v1 = lp1.start_at(v0)[:, None] + n1[None, :] * lp1.step
        if level == 1:
            pos = self.tri_position(
                ref_idx, v0[:, None], base[:, None], n1[None, :]
            )
            addr = np.broadcast_to(
                self.ref_addr(ref_idx, v0[:, None], v1), pos.shape
            )
            return pos[mask].astype(np.int64), addr[mask].astype(np.int64)
        lp2 = self.nest.loops[2]
        t2 = lp2.trip_at(v0)
        n2 = np.arange(int(t2.max(initial=0)), dtype=np.int64)
        mask = mask[:, :, None] & (n2[None, None, :] < t2[:, None, None])
        v2 = lp2.start_at(v0)[:, None, None] + n2[None, None, :] * lp2.step
        pos = self.tri_position(
            ref_idx, v0[:, None, None], base[:, None, None],
            n1[None, :, None], n2[None, None, :],
        )
        addr = np.broadcast_to(
            self.ref_addr(ref_idx, v0[:, None, None], v1[:, :, None], v2),
            pos.shape,
        )
        return pos[mask].astype(np.int64), addr[mask].astype(np.int64)


class ProgramTrace:
    """Trace geometry of a whole program (nests concatenated per thread).

    The per-thread access clock persists across parallel nests (the
    reference keeps one `count` array across generated parallel loops),
    so nest k's positions are offset by the thread's total length of
    nests 0..k-1.
    """

    def __init__(self, program: Program, machine: MachineConfig):
        self.program = program
        self.machine = machine
        self.nests = [
            NestTrace(program, i, machine) for i in range(len(program.nests))
        ]
        P = machine.thread_num
        lengths = np.array(
            [[nt.tid_length(t) for t in range(P)] for nt in self.nests],
            dtype=np.int64,
        )  # (n_nests, P)
        self.nest_offsets = np.concatenate(
            [np.zeros((1, P), dtype=np.int64), np.cumsum(lengths, axis=0)]
        )  # (n_nests+1, P)

    def tid_total_length(self, tid: int) -> int:
        return int(self.nest_offsets[-1, tid])

    def nest_offset(self, nest_index: int, tid: int) -> int:
        return int(self.nest_offsets[nest_index, tid])

    def enumerate_tid(self, tid: int):
        """Full access stream of one simulated thread across all nests.

        Returns int64 arrays (positions, addrs, array_ids, ref_gids)
        where ref_gids index `self.program.refs`.
        """
        parts = [
            self.enumerate_tid_window(
                tid, k, 0, nt.schedule.local_count(tid)
            )
            for k, nt in enumerate(self.nests)
        ]
        return tuple(
            np.concatenate([p[c] for p in parts]) for c in range(4)
        )

    def enumerate_tid_window(
        self, tid: int, nest_index: int, m_lo: int, m_hi: int
    ):
        """One nest's accesses for thread-local parallel iterations
        [m_lo, m_hi) — same arrays as enumerate_tid, bounded memory."""
        nt = self.nests[nest_index]
        off = self.nest_offset(nest_index, tid)
        gid0 = sum(
            self.nests[k].tables.n_refs for k in range(nest_index)
        )
        pos_all, addr_all, arr_all, ref_all = [], [], [], []
        for ri in range(nt.tables.n_refs):
            pos, addr = nt.enumerate_ref(tid, ri, m_lo=m_lo, m_hi=m_hi)
            pos_all.append(pos + off)
            addr_all.append(addr)
            arr_all.append(
                np.full(pos.shape, nt.tables.ref_arrays[ri], dtype=np.int64)
            )
            ref_all.append(np.full(pos.shape, gid0 + ri, dtype=np.int64))
        return (
            np.concatenate(pos_all),
            np.concatenate(addr_all),
            np.concatenate(arr_all),
            np.concatenate(ref_all),
        )

    def ref_global_tables(self):
        """Program-wide ref tables: share thresholds/ratios per ref gid."""
        thr: list[int] = []
        ratio: list[int] = []
        names: list[str] = []
        for nt in self.nests:
            thr.extend(int(x) for x in nt.tables.ref_share_thresholds)
            ratio.extend(int(x) for x in nt.tables.ref_share_ratios)
            names.extend(nt.tables.ref_names)
        return (
            np.array(thr, dtype=np.int64),
            np.array(ratio, dtype=np.int64),
            tuple(names),
        )
