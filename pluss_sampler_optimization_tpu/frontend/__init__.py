"""Program frontend: arbitrary affine loop nests as request payloads.

"MRC-as-a-service" (ROADMAP item 4): the Program IR is fully general,
but until this package every servable scenario was one of the 18
hand-ported registry models. The frontend closes the gap with a
versioned JSON description of a parallel loop-nest program
(`schema.py`), a strict deserializer with machine-readable
diagnostics that shares the static-analysis code path with the
service preflight (`parse.py`), and a seeded generative fuzzer that
cross-checks the production engines against the numpy oracle on
random valid nests and asserts every invalid mutant is rejected with
a diagnostic (`fuzz.py`, driven by tools/fuzz_ir.py).

Pure numpy + stdlib at import time (no jax): the CLI `analyze` mode,
`--dump-ir`, and tools/check_ir.py stay instant; `fuzz.check_seed`
lazy-imports the engines it exercises.
"""

from .parse import (
    F_ACCESSES,
    F_FIELD,
    F_LIMIT,
    F_MACHINE,
    F_RANGE,
    F_TYPE,
    F_VERSION,
    MAX_TOTAL_ACCESSES,
    FrontendError,
    ParsedProgram,
    malformed_doc_fixtures,
    parse_program,
    parse_program_doc,
)
from .schema import (
    IR_SCHEMA_VERSION,
    machine_from_doc,
    program_from_json,
    program_to_json,
)

__all__ = [
    "F_ACCESSES",
    "F_FIELD",
    "F_LIMIT",
    "F_MACHINE",
    "F_RANGE",
    "F_TYPE",
    "F_VERSION",
    "MAX_TOTAL_ACCESSES",
    "FrontendError",
    "ParsedProgram",
    "malformed_doc_fixtures",
    "parse_program",
    "parse_program_doc",
    "IR_SCHEMA_VERSION",
    "machine_from_doc",
    "program_from_json",
    "program_to_json",
]
