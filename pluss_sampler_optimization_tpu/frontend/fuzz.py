"""Generative IR fuzzer: the standing correctness harness.

Each seed deterministically produces one random *valid* loop-nest
document inside the documented model-family caps (depth 1-3,
rectangular parallel loop, unit-step triangular inner loops, positive
suffix-product strides, 1-4 arrays, optional RMW write pairs, bounded
total accesses) and a batch of *invalid* mutants. `check_seed` then
asserts the full frontend contract on that seed:

- round-trip: parse(program_to_json(p)) reproduces p exactly;
- exact path: run_exact's PRIState is bit-identical to the numpy
  oracle's, and the folded MRC bytes match exactly;
- sampled path: run_sampled's folded MRC stays within `drift_max` of
  the oracle fold (sampling is approximate by design — the bound is
  the contract, bit-identity is not);
- rejection: every invalid mutant is refused by the frontend with a
  machine-readable diagnostic carrying the expected code — never a
  crash, never a silent acceptance.

Module import is numpy + stdlib only; engines (jax-backed sampled)
are imported inside `check_seed` so `tools/fuzz_ir.py --help` and the
frontend package itself stay instant. Drives: tools/fuzz_ir.py (the
standing gate), tests/test_frontend.py (25-seed tier-1 smoke, deep
sweep behind -m slow), bench.py's `custom_frontend` extra.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..config import MachineConfig
from ..ir import Loop, ParallelNest, Program, Ref
from .parse import (
    F_FIELD,
    F_LIMIT,
    F_MACHINE,
    F_RANGE,
    F_VERSION,
    MAX_DOC_DEPTH,
    parse_program_doc,
)
from .schema import program_to_json

ARRAYS = ("A", "B", "C", "D")

#: Default sampled-engine fidelity bound. MRC values live in [0, 1],
#: so real breakage (wrong reuse distances, broken interleaving)
#: drives the max-abs drift to O(1); the bound only needs to sit
#: above the estimator's granularity floor on fuzzer-scale programs.
#: That floor is NOT sampling noise: a MIN_ACCESSES-scale nest split
#: over 2-5 threads gives each per-thread trace a few hundred
#: accesses, the MRC is a coarse step function, and a single
#: histogram-bin shift between the sampled estimator and the exact
#: fold costs ~0.3 in max-abs even at ratio 1.0. Calibration
#: (100-seed sweep at ratio 0.5): worst 0.355, second-worst 0.274,
#: from small deep-triangular nests.
DRIFT_MAX = 0.40
RATIO = 0.5

#: Redraw floor: a program with only a handful of total accesses has
#: an MRC of 2-3 giant steps, where the sampled estimator's boundary
#: effects are O(1) of the curve — statistically meaningless to bound.
#: The generator redraws (from the same deterministic stream) until
#: the candidate clears this, so every fuzzed program is big enough
#: for the drift bound to be a real assertion.
MIN_ACCESSES = 600


def _nest_accesses(nest: ParallelNest) -> int:
    lp0 = nest.loops[0]
    total = 0
    for i in range(lp0.trip):
        v0 = lp0.start + i * lp0.step
        for r in nest.refs:
            c = 1
            for k in range(1, r.level + 1):
                c *= max(0, nest.loops[k].trip_at(v0))
            total += c
    return total


def generate_program(seed: int) -> Program:
    """One random valid Program (tests/test_fuzz.py's generator
    idiom, widened with 1-4 arrays and RMW write pairs so the
    frontend's write tri-state and the race lattice get exercised).
    Redraws until the candidate has >= MIN_ACCESSES total accesses."""
    rng = np.random.default_rng(seed)
    program = _candidate(rng, seed)
    for _ in range(50):
        if _nest_accesses(program.nests[0]) >= MIN_ACCESSES:
            break
        program = _candidate(rng, seed)
    return program


def _candidate(rng, seed: int) -> Program:
    depth = int(rng.integers(1, 4))
    tri = depth >= 2 and rng.random() < 0.35

    # the parallel trip scales inversely with depth so every depth
    # can clear MIN_ACCESSES (a depth-1 nest has only trip0 x refs
    # accesses; a depth-3 nest multiplies three levels)
    trip0_lo, trip0_hi = {1: (120, 400), 2: (16, 48),
                          3: (6, 16)}[depth]
    loops = []
    for l in range(depth):
        start = int(rng.integers(0, 3))
        step = 1 if tri else int(rng.choice([1, 1, 2]))
        trip = (int(rng.integers(trip0_lo, trip0_hi)) if l == 0
                else int(rng.integers(2, 8)))
        if tri and l == depth - 1:
            tc = int(rng.choice([-1, 1]))
            if tc < 0:
                lp0 = loops[0]
                v0_max = lp0.start + (lp0.trip - 1) * lp0.step
                trip = int(rng.integers(1, max(2, v0_max + 1)))
            loops.append(Loop(trip, start=start, step=1, trip_coeff=tc,
                              start_coeff=int(rng.choice([0, 1]))))
        else:
            loops.append(Loop(trip, start=start, step=step))
    nest_loops = tuple(loops)

    # exact per-level value extents (enumerate the small parallel
    # range); suffix products make head-dominant strides
    lp0 = nest_loops[0]
    v0s = [lp0.start + i * lp0.step for i in range(lp0.trip)]
    extents = []
    for lp in nest_loops:
        vmax = 0
        for v0 in v0s:
            tr = lp.trip_at(v0)
            if tr > 0:
                vmax = max(vmax, lp.start_at(v0) + (tr - 1) * lp.step)
        extents.append(max(1, vmax) + 1)

    def _coeffs(lv: int):
        coeffs = []
        for l in range(lv + 1):
            c = 1
            for k in range(l + 1, lv + 1):
                c *= extents[k]
            coeffs.append(c)
        if lv >= 1 and rng.random() < 0.4:
            z = int(rng.integers(0, lv + 1))
            coeffs[z] = 0
            if all(c == 0 for c in coeffs):
                coeffs[lv] = 1
        return tuple(coeffs)

    n_arrays = int(rng.integers(1, 5))
    refs = []
    n_refs = int(rng.integers(1, 6))
    ridx = 0
    for _ in range(n_refs):
        lv = int(rng.integers(0, depth))
        coeffs = _coeffs(lv)
        slot = "pre"
        if lv < depth - 1 and rng.random() < 0.25:
            slot = "post"
        thr = int(rng.integers(1, 60)) if rng.random() < 0.3 else None
        array = str(rng.choice(ARRAYS[:n_arrays]))
        const = int(rng.integers(0, 3))
        if rng.random() < 0.3:
            # RMW pair: read+write through one map (gemm's C0/C1
            # shape) — the duplicated-map case the write tri-state's
            # `None` derivation and the race detector key on
            refs.append(Ref(name=f"R{ridx}", array=array, level=lv,
                            coeffs=coeffs, const=const, slot=slot,
                            share_threshold=thr, write=False))
            refs.append(Ref(name=f"R{ridx + 1}", array=array,
                            level=lv, coeffs=coeffs, const=const,
                            slot=slot, write=True))
            ridx += 2
        else:
            write = bool(rng.random() < 0.15) or None
            refs.append(Ref(name=f"R{ridx}", array=array, level=lv,
                            coeffs=coeffs, const=const, slot=slot,
                            share_threshold=thr, write=write))
            ridx += 1

    return Program(name=f"fuzz{seed}", nests=(ParallelNest(
        loops=nest_loops, refs=tuple(refs)),))


def generate_machine(seed: int) -> MachineConfig:
    rng = np.random.default_rng(seed + 7919)
    return MachineConfig(
        thread_num=int(rng.integers(2, 6)),
        chunk_size=int(rng.integers(1, 5)),
    )


def generate_doc(seed: int) -> dict:
    """The frontend document for this seed (machine knobs embedded)."""
    return program_to_json(generate_program(seed),
                           machine=generate_machine(seed))


# Mutation table: name -> (mutator, expected diagnostic code). Every
# mutator takes a deep-copied valid document and damages it in place.

def _deep_list(levels: int):
    node = [1]
    for _ in range(levels):
        node = [node]
    return node


def _mutations():
    def bad_version(d):
        d["ir_version"] = 99

    def unknown_field(d):
        d["schedule"] = "static"

    def drop_trip(d):
        del d["nests"][0]["loops"][0]["trip"]

    def step_zero(d):
        d["nests"][0]["loops"][-1]["step"] = 0

    def trip_string(d):
        d["nests"][0]["loops"][0]["trip"] = "16"

    def coeffs_long(d):
        d["nests"][0]["refs"][0]["coeffs"].append(1)
        d["nests"][0]["refs"][0]["coeffs"].append(1)
        d["nests"][0]["refs"][0]["coeffs"].append(1)
        d["nests"][0]["refs"][0]["coeffs"].append(1)

    def bad_slot(d):
        d["nests"][0]["refs"][0]["slot"] = "mid"

    def huge_trip(d):
        d["nests"][0]["loops"][0]["trip"] = 1 << 50

    def no_nests(d):
        d["nests"] = []

    def parallel_tri(d):
        d["nests"][0]["loops"][0]["trip_coeff"] = 1

    def deep_coeffs(d):
        d["nests"][0]["refs"][0]["coeffs"] = _deep_list(
            MAX_DOC_DEPTH + 4)

    def bad_machine(d):
        d["machine"] = {"ds": 0}

    return {
        "bad_version": (bad_version, F_VERSION),
        "unknown_field": (unknown_field, F_FIELD),
        "drop_trip": (drop_trip, F_FIELD),
        "step_zero": (step_zero, "V_STEP_ZERO"),
        "trip_string": (trip_string, "V_COEFF_SHAPE"),
        "coeffs_long": (coeffs_long, "V_COEFF_SHAPE"),
        "bad_slot": (bad_slot, "V_SLOT"),
        "huge_trip": (huge_trip, F_RANGE),
        "no_nests": (no_nests, "V_NO_NESTS"),
        "parallel_tri": (parallel_tri, "V_PARALLEL_TRIANGULAR"),
        "deep_coeffs": (deep_coeffs, F_LIMIT),
        "bad_machine": (bad_machine, F_MACHINE),
    }


def mutate_invalid(doc: dict, seed: int, count: int = 4) -> list:
    """`count` deterministic (mutant_name, damaged_doc, expected_code)
    triples for this seed, each derived from a fresh copy of `doc`."""
    import copy

    rng = np.random.default_rng(seed + 104729)
    table = _mutations()
    names = rng.permutation(sorted(table))[:count]
    out = []
    for name in names:
        mutator, code = table[str(name)]
        damaged = copy.deepcopy(doc)
        mutator(damaged)
        out.append((str(name), damaged, code))
    return out


def _fold_mrc(state, machine: MachineConfig) -> np.ndarray:
    from ..runtime.aet import aet_mrc
    from ..runtime.cri import cri_distribute

    rih = cri_distribute(state, machine.thread_num, machine.thread_num)
    return np.asarray(aet_mrc(rih, machine), dtype=np.float64)


def _states_equal(a, b, thread_num: int) -> bool:
    for t in range(thread_num):
        if a.noshare[t] != b.noshare[t] or a.share[t] != b.share[t]:
            return False
    return True


def check_seed(seed: int, ratio: float = RATIO,
               drift_max: float = DRIFT_MAX,
               n_mutants: int = 4, sampled: bool = True,
               batched: bool = False, sharded: bool = False,
               kernel_backends: tuple = ()) -> dict:
    """Run the full contract for one seed; returns a result dict with
    `ok` plus per-check fields (never raises on a contract failure —
    failures land in `errors` so a sweep reports them all).

    `sampled=False` skips the sampled-engine drift check (each fresh
    program shape costs a jax trace+compile — the tier-1 smoke runs
    the cheap checks over many seeds and leaves the sampled sweep to
    the slow marker and the tools/fuzz_ir.py gate).

    `batched=True` additionally runs the seed's program through
    run_sampled_multi in a 3-job union bucket (primary, a companion
    from seed+1, primary again) and requires job 0 bit-identical to
    the solo run and job 2 bit-identical to job 0. `sharded=True`
    runs run_sampled_sharded on a 2-device mesh (the caller must have
    pinned a multi-device platform, e.g. force_virtual_cpu) and
    requires bit-identity to solo. Both imply a solo sampled run.

    `kernel_backends` re-runs the solo sampled config once per named
    backend ("xla" | "pallas" | "native") and requires each run's
    PRIState AND folded MRC bit-identical to the solo run — the solo
    run is itself drift-checked against the numpy oracle, so every
    backend is transitively pinned to the oracle. (An explicitly
    requested but unavailable backend falls back to xla with a
    warn_once, per _resolve_kernel_backend; the identity check then
    passes trivially.) Implies a solo sampled run."""
    from ..oracle.numpy_ref import run_numpy
    from ..sampler.periodic import run_exact

    errors = []
    program = generate_program(seed)
    machine = generate_machine(seed)
    doc = generate_doc(seed)

    res = parse_program_doc(doc)
    if res.program != program:
        errors.append("roundtrip: parsed program differs from source")

    oracle = run_numpy(program, machine)
    mrc_oracle = _fold_mrc(oracle.state, machine)

    exact = run_exact(program, machine)
    exact_ok = True
    for t in range(machine.thread_num):
        if (exact.state.noshare[t] != oracle.state.noshare[t]
                or exact.state.share[t] != oracle.state.share[t]):
            exact_ok = False
    mrc_exact = _fold_mrc(exact.state, machine)
    if not exact_ok or mrc_exact.tobytes() != mrc_oracle.tobytes():
        errors.append("exact: PRIState/MRC not bit-identical to oracle")

    drift = 0.0
    if sampled or batched or sharded or kernel_backends:
        from ..config import SamplerConfig
        from ..sampler.sampled import run_sampled

        cfg = SamplerConfig(ratio=ratio, seed=seed)
        state, _ = run_sampled(program, machine, cfg)
        mrc_sampled = _fold_mrc(state, machine)
        k = min(len(mrc_sampled), len(mrc_oracle))
        drift = float(np.max(
            np.abs(mrc_sampled[:k] - mrc_oracle[:k]))) if k else 0.0
        if sampled and drift > drift_max:
            errors.append(
                f"sampled: MRC drift {drift:.3f} exceeds {drift_max}")

    for backend in kernel_backends:
        import dataclasses as _dc

        state_b, _ = run_sampled(
            program, machine, _dc.replace(cfg, kernel_backend=backend))
        if (not _states_equal(state_b, state, machine.thread_num)
                or _fold_mrc(state_b, machine).tobytes()
                != mrc_sampled.tobytes()):
            errors.append(
                f"kernel_backend={backend}: PRIState/MRC not "
                "bit-identical to solo")

    if batched:
        from ..sampler.sampled import run_sampled_multi

        # a 3-job union bucket: the companion forces genuinely mixed
        # batch membership, and the repeated primary must come back
        # bit-identical to the first copy at zero extra compile cost
        companion = (generate_program(seed + 1),
                     generate_machine(seed + 1),
                     SamplerConfig(ratio=ratio, seed=seed + 1), False)
        outs = run_sampled_multi([
            (program, machine, cfg, False), companion,
            (program, machine, cfg, False),
        ])
        b0, b2 = outs[0][0], outs[2][0]
        if (not _states_equal(b0, state, machine.thread_num)
                or _fold_mrc(b0, machine).tobytes()
                != mrc_sampled.tobytes()):
            errors.append(
                "batched: job 0 PRIState/MRC not bit-identical to solo")
        if (not _states_equal(b2, b0, machine.thread_num)
                or _fold_mrc(b2, machine).tobytes()
                != _fold_mrc(b0, machine).tobytes()):
            errors.append(
                "batched: repeated member diverges inside one bucket")

    if sharded:
        from ..parallel.mesh import build_mesh
        from ..parallel.sharded import run_sampled_sharded

        state_sh, _ = run_sampled_sharded(
            program, machine, cfg, mesh=build_mesh(2))
        if (not _states_equal(state_sh, state, machine.thread_num)
                or _fold_mrc(state_sh, machine).tobytes()
                != mrc_sampled.tobytes()):
            errors.append(
                "sharded: PRIState/MRC not bit-identical to solo "
                "on the 2-device mesh")

    rejected = 0
    mutants = mutate_invalid(doc, seed, count=n_mutants)
    for name, damaged, code in mutants:
        try:
            mres = parse_program_doc(damaged)
        except Exception as e:  # a crash is exactly the bug we hunt
            errors.append(f"mutant {name}: parser raised {e!r}")
            continue
        codes = [d.code for d in mres.errors()]
        if mres.program is not None:
            errors.append(f"mutant {name}: accepted (expected {code})")
        elif code not in codes:
            errors.append(
                f"mutant {name}: rejected with {codes}, expected {code}")
        else:
            rejected += 1

    return {
        "seed": seed,
        "ok": not errors,
        "program": program.name,
        "depth": len(program.nests[0].loops),
        "refs": len(program.nests[0].refs),
        "accesses": res.total_accesses,
        "sampled_drift": round(drift, 4),
        "mutants_rejected": f"{rejected}/{len(mutants)}",
        "errors": errors,
    }


def run_seeds(n: int, start: int = 0, ratio: float = RATIO,
              drift_max: float = DRIFT_MAX, n_mutants: int = 4,
              sampled: bool = True, batched: bool = False,
              sharded: bool = False, kernel_backends: tuple = (),
              progress=None) -> dict:
    """Sweep seeds [start, start+n); summary dict with every failing
    seed's result embedded (empty `failures` == clean sweep)."""
    failures = []
    worst: Optional[dict] = None
    for seed in range(start, start + n):
        r = check_seed(seed, ratio=ratio, drift_max=drift_max,
                       n_mutants=n_mutants, sampled=sampled,
                       batched=batched, sharded=sharded,
                       kernel_backends=kernel_backends)
        if worst is None or r["sampled_drift"] > worst["sampled_drift"]:
            worst = r
        if not r["ok"]:
            failures.append(r)
        if progress is not None:
            progress(r)
    return {
        "seeds": n,
        "start": start,
        "ratio": ratio,
        "drift_max": drift_max,
        "passed": n - len(failures),
        "failed": len(failures),
        "worst_drift": worst["sampled_drift"] if worst else 0.0,
        "worst_drift_seed": worst["seed"] if worst else None,
        "failures": failures,
    }
