"""Strict deserialization of frontend JSON into `ir.Program`.

Two diagnostic families, one rejection discipline:

- **F_*** codes (this module) cover the JSON layer — wrong types,
  unknown/missing fields, unsupported versions, hostile payloads
  (out-of-range integers, over-deep documents, bounds products whose
  simulated access count would OOM an engine). Paths are JSON
  pointers into the document ("/nests/0/loops/1/trip").
- **V_*** codes (analysis/validate.py) cover the IR semantics — the
  SAME validator the service preflight runs on registry models, so a
  custom nest with a zero step rejects with exactly the V_STEP_ZERO
  diagnostic a malformed registry model would produce. Paths are IR
  paths ("nests[0].loops[1]").

`parse_program_doc` never raises on malformed input: it returns a
`ParsedProgram` whose diagnostics carry code / path / message
(`analysis.validate.Diagnostic`), mirroring the preflight contract.
`parse_program` is the raising form the service uses: its
`FrontendError` subclasses `analysis.PreflightError`, so serve_jsonl
surfaces the diagnostics on the structured error response through
the existing code path, with no frontend-specific handling.

The access cap is the preflight-side OOM guard: a document whose loop
bounds multiply out past `MAX_TOTAL_ACCESSES` is rejected before any
engine (or even the bounds pass) sees it — a hostile
`{"trip": 2**40}**3` product costs this module a few integer
multiplies, not an allocation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

from ..analysis import PreflightError
from ..analysis.validate import Diagnostic, canonicalize, validate_program
from ..config import MachineConfig
from ..ir import Program
from .schema import (
    IR_SCHEMA_VERSION,
    LOOP_FIELDS,
    LOOP_REQUIRED,
    MACHINE_FIELDS,
    REF_FIELDS,
    REF_REQUIRED,
)

# Frontend diagnostic codes (JSON layer; the V_* glossary lives in
# analysis/validate.py and README "Static analysis & preflight").
F_TYPE = "F_TYPE"  # wrong JSON type for a document node
F_FIELD = "F_FIELD"  # unknown or missing field
F_VERSION = "F_VERSION"  # missing/unsupported ir_version
F_RANGE = "F_RANGE"  # integer outside the safe magnitude range
F_LIMIT = "F_LIMIT"  # document size/depth/cardinality limit
F_MACHINE = "F_MACHINE"  # machine knob rejected by MachineConfig
F_ACCESSES = "F_ACCESSES"  # simulated access count above the cap

FRONTEND_CODES = frozenset({
    F_TYPE, F_FIELD, F_VERSION, F_RANGE, F_LIMIT, F_MACHINE, F_ACCESSES,
})

# Document limits. INT_ABS_LIMIT bounds every integer in the document
# (JSON bignums would otherwise reach numpy int64 conversions);
# MAX_TOTAL_ACCESSES bounds the simulated access count an accepted
# program can demand from an engine (the largest registry scenario,
# gemm at n=4096, is ~2.7e11 — the cap clears it with headroom while
# rejecting products that could only end in an OOM or a dead service
# worker). TRI_PARALLEL_TRIP_LIMIT bounds the parallel extent of
# triangular nests, whose access count needs a per-v0 evaluation.
MAX_DOC_DEPTH = 24
MAX_NESTS = 16
MAX_REFS_PER_NEST = 64
MAX_NAME_LEN = 120
INT_ABS_LIMIT = 1 << 40
MAX_TOTAL_ACCESSES = 1 << 40
TRI_PARALLEL_TRIP_LIMIT = 1 << 21


class FrontendError(PreflightError):
    """A program document rejected by the frontend. Subclasses
    `analysis.PreflightError` so every consumer of preflight
    rejections (serve_jsonl's structured errors, tools) handles
    frontend rejections identically; `diagnostics` holds dicts
    (Diagnostic.to_dict form), ready for a JSON response."""


class _Bag:
    """Attribute bag: the duck-typed program handed to the shared
    validator (analysis/validate.py checks duck-typed, not isinstance,
    precisely for frontends like this one)."""

    def __init__(self, **kw: Any) -> None:
        self.__dict__.update(kw)


@dataclasses.dataclass
class ParsedProgram:
    """Outcome of one document parse. `program` is None iff any error
    diagnostic was produced; `machine` echoes the document's machine
    section (already vetted against MachineConfig) or None; warnings
    (W_RACE never appears here — races are the analyzer's business)
    ride `diagnostics` alongside any errors."""

    program: Optional[Program]
    machine: Optional[dict]
    diagnostics: list
    total_accesses: Optional[int] = None

    @property
    def ok(self) -> bool:
        return self.program is not None

    def errors(self) -> list:
        return [d for d in self.diagnostics if d.severity == "error"]


def _is_int(v: Any) -> bool:
    return isinstance(v, int) and not isinstance(v, bool)


def _doc_depth(obj: Any) -> int:
    """Nesting depth of a parsed JSON value, iteratively (a 1000-deep
    document must not recurse this module into its own crash)."""
    depth = 0
    stack = [(obj, 1)]
    while stack:
        node, d = stack.pop()
        depth = max(depth, d)
        if d > MAX_DOC_DEPTH:
            return d  # deep enough to reject; stop walking
        if isinstance(node, dict):
            stack.extend((v, d + 1) for v in node.values())
        elif isinstance(node, (list, tuple)):
            stack.extend((v, d + 1) for v in node)
    return depth


def _range_check(d: dict, keys, path: str, diags: list) -> None:
    """F_RANGE for any integer field beyond INT_ABS_LIMIT (non-ints
    fall through to the shared validator's V_COEFF_SHAPE)."""
    for k in keys:
        v = d.get(k)
        vals = v if isinstance(v, list) else [v]
        for i, x in enumerate(vals):
            if _is_int(x) and abs(x) > INT_ABS_LIMIT:
                p = f"{path}/{k}/{i}" if isinstance(v, list) else f"{path}/{k}"
                diags.append(Diagnostic(
                    F_RANGE, p,
                    f"integer magnitude {x} exceeds 2^40"))


def _check_keys(d: dict, allowed, required, path: str,
                diags: list) -> bool:
    """Unknown/missing field diagnostics; False when required fields
    are absent (the node cannot be built)."""
    unknown = sorted(set(d) - set(allowed))
    for k in unknown:
        diags.append(Diagnostic(
            F_FIELD, f"{path}/{k}",
            f"unknown field {k!r} (have {', '.join(allowed)})"))
    missing = sorted(set(required) - set(d))
    for k in missing:
        diags.append(Diagnostic(
            F_FIELD, f"{path}/{k}", f"missing required field {k!r}"))
    return not missing


def _parse_machine(doc: dict, diags: list) -> Optional[dict]:
    machine = doc.get("machine")
    if machine is None:
        return None
    if not isinstance(machine, dict):
        diags.append(Diagnostic(F_TYPE, "/machine",
                                "machine must be a JSON object"))
        return None
    _check_keys(machine, MACHINE_FIELDS, (), "/machine", diags)
    bad = False
    for k in MACHINE_FIELDS:
        if k in machine and (not _is_int(machine[k])
                             or not 1 <= machine[k] <= INT_ABS_LIMIT):
            diags.append(Diagnostic(
                F_MACHINE, f"/machine/{k}",
                f"{k} must be a positive integer, got {machine[k]!r}"))
            bad = True
    if bad or set(machine) - set(MACHINE_FIELDS):
        return None
    try:
        kw = dataclasses.asdict(MachineConfig())
        kw.update({k: machine[k] for k in MACHINE_FIELDS if k in machine})
        MachineConfig(**kw)
    except ValueError as e:
        diags.append(Diagnostic(F_MACHINE, "/machine", str(e)))
        return None
    return {k: machine[k] for k in MACHINE_FIELDS if k in machine}


def _total_accesses(program: Program) -> "int | Diagnostic":
    """Exact (rectangular) or float-certified (triangular) simulated
    access count, in Python/np.float64 arithmetic that cannot
    overflow whatever the document's bounds multiply out to."""
    total = 0
    for ni, nest in enumerate(program.nests):
        l0 = nest.loops[0]
        if not any(lp.is_triangular for lp in nest.loops[1:]):
            for r in nest.refs:
                c = l0.trip
                for k in range(1, r.level + 1):
                    c *= nest.loops[k].trip
                total += c
            continue
        if l0.trip > TRI_PARALLEL_TRIP_LIMIT:
            return Diagnostic(
                F_LIMIT, f"/nests/{ni}/loops/0/trip",
                f"triangular nest parallel trip {l0.trip} exceeds the "
                f"frontend limit {TRI_PARALLEL_TRIP_LIMIT}")
        v0 = l0.start + l0.step * np.arange(l0.trip, dtype=np.float64)
        for r in nest.refs:
            prod = np.ones_like(v0)
            for k in range(1, r.level + 1):
                lp = nest.loops[k]
                prod = prod * np.clip(
                    lp.trip + lp.trip_coeff * v0, 0.0, None)
            total += int(min(float(prod.sum()), 2.0 ** 63))
    return total


def parse_program_doc(
    doc: Any, max_total_accesses: int = MAX_TOTAL_ACCESSES
) -> ParsedProgram:
    """Parse one document; never raises on malformed input.

    Order of gates: JSON shape (F_*), then the shared IR validator
    (V_*, identical to the service preflight on registry models),
    then canonicalization into real ir dataclasses, then the access
    cap (F_ACCESSES). The first failing gate's diagnostics come back;
    `program` is set only when every gate passes."""
    if not isinstance(doc, dict):
        return ParsedProgram(None, None, [Diagnostic(
            F_TYPE, "", "program document must be a JSON object")])
    if _doc_depth(doc) > MAX_DOC_DEPTH:
        return ParsedProgram(None, None, [Diagnostic(
            F_LIMIT, "",
            f"document nesting exceeds {MAX_DOC_DEPTH} levels")])

    diags: list = []
    _check_keys(doc, ("ir_version", "name", "nests", "machine"),
                ("nests",), "", diags)

    version = doc.get("ir_version")
    if version is None:
        diags.append(Diagnostic(
            F_VERSION, "/ir_version",
            f"missing ir_version (current: {IR_SCHEMA_VERSION})"))
    elif not _is_int(version) or version != IR_SCHEMA_VERSION:
        diags.append(Diagnostic(
            F_VERSION, "/ir_version",
            f"unsupported ir_version {version!r} "
            f"(this build reads {IR_SCHEMA_VERSION})"))

    name = doc.get("name", "custom")
    if not isinstance(name, str):
        diags.append(Diagnostic(F_TYPE, "/name", "name must be a string"))
        name = "custom"
    elif len(name) > MAX_NAME_LEN:
        diags.append(Diagnostic(
            F_LIMIT, "/name",
            f"name length {len(name)} exceeds {MAX_NAME_LEN}"))

    machine = _parse_machine(doc, diags)

    nests = doc.get("nests")
    nest_bags: list = []
    if nests is not None and not isinstance(nests, list):
        diags.append(Diagnostic(F_TYPE, "/nests",
                                "nests must be a JSON array"))
        nests = None
    if isinstance(nests, list) and len(nests) > MAX_NESTS:
        diags.append(Diagnostic(
            F_LIMIT, "/nests",
            f"{len(nests)} nests exceed the limit {MAX_NESTS}"))
        nests = None
    for ni, nd in enumerate(nests or []):
        npath = f"/nests/{ni}"
        if not isinstance(nd, dict):
            diags.append(Diagnostic(F_TYPE, npath,
                                    "nest must be a JSON object"))
            continue
        if not _check_keys(nd, ("loops", "refs"), ("loops", "refs"),
                           npath, diags):
            continue
        loops, refs = nd.get("loops"), nd.get("refs")
        if not isinstance(loops, list) or not isinstance(refs, list):
            diags.append(Diagnostic(
                F_TYPE, npath, "loops and refs must be JSON arrays"))
            continue
        if len(refs) > MAX_REFS_PER_NEST:
            diags.append(Diagnostic(
                F_LIMIT, f"{npath}/refs",
                f"{len(refs)} refs exceed the limit "
                f"{MAX_REFS_PER_NEST}"))
            continue
        loop_bags, ref_bags, bad = [], [], False
        for li, ld in enumerate(loops):
            lpath = f"{npath}/loops/{li}"
            if not isinstance(ld, dict):
                diags.append(Diagnostic(F_TYPE, lpath,
                                        "loop must be a JSON object"))
                bad = True
                continue
            if not _check_keys(ld, LOOP_FIELDS, LOOP_REQUIRED, lpath,
                               diags):
                bad = True
                continue
            _range_check(ld, LOOP_FIELDS, lpath, diags)
            loop_bags.append(_Bag(
                trip=ld.get("trip"), start=ld.get("start", 0),
                step=ld.get("step", 1),
                trip_coeff=ld.get("trip_coeff", 0),
                start_coeff=ld.get("start_coeff", 0)))
        for ri, rd in enumerate(refs):
            rpath = f"{npath}/refs/{ri}"
            if not isinstance(rd, dict):
                diags.append(Diagnostic(F_TYPE, rpath,
                                        "ref must be a JSON object"))
                bad = True
                continue
            if not _check_keys(rd, REF_FIELDS, REF_REQUIRED, rpath,
                               diags):
                bad = True
                continue
            _range_check(
                rd,
                ("level", "coeffs", "const", "share_threshold",
                 "share_ratio"),
                rpath, diags)
            coeffs = rd.get("coeffs")
            ref_bags.append(_Bag(
                name=rd.get("name"), array=rd.get("array"),
                level=rd.get("level"),
                coeffs=tuple(coeffs) if isinstance(coeffs, list)
                else coeffs,
                const=rd.get("const", 0), slot=rd.get("slot", "pre"),
                share_threshold=rd.get("share_threshold"),
                share_ratio=rd.get("share_ratio"),
                write=rd.get("write")))
        if not bad:
            nest_bags.append(_Bag(loops=tuple(loop_bags),
                                  refs=tuple(ref_bags)))

    if any(d.severity == "error" for d in diags):
        return ParsedProgram(None, machine, diags)

    bag = _Bag(name=name, nests=tuple(nest_bags))
    vdiags = validate_program(bag)
    if any(d.severity == "error" for d in vdiags):
        return ParsedProgram(None, machine, vdiags)
    program = canonicalize(bag)

    total = _total_accesses(program)
    if isinstance(total, Diagnostic):
        return ParsedProgram(None, machine, [total])
    if total > max_total_accesses:
        return ParsedProgram(None, machine, [Diagnostic(
            F_ACCESSES, "/nests",
            f"program demands {total} simulated accesses, above the "
            f"frontend cap {max_total_accesses}")],
            total_accesses=total)
    return ParsedProgram(program, machine, vdiags,
                         total_accesses=total)


def parse_program(doc: Any,
                  max_total_accesses: int = MAX_TOTAL_ACCESSES
                  ) -> Program:
    """The raising form: the canonical Program, or `FrontendError`
    with the full diagnostic list (as dicts) attached."""
    res = parse_program_doc(doc, max_total_accesses=max_total_accesses)
    if res.program is not None:
        return res.program
    errors = res.errors()
    first = errors[0]
    msg = (f"frontend rejected program: {first.code} at "
           f"{first.path or '/'}: {first.message}")
    if len(errors) > 1:
        msg += f" (+{len(errors) - 1} more)"
    raise FrontendError(msg, diagnostics=[d.to_dict() for d in errors])


# ---------------------------------------------------------------------------
# Malformed document fixtures (tests/test_frontend.py and
# tools/check_ir.py --fixtures run both this set and the IR-level
# analysis.malformed_fixtures set).
# ---------------------------------------------------------------------------


def _fixture_doc(**over: Any) -> dict:
    """A minimal valid document to mutate."""
    doc = {
        "ir_version": IR_SCHEMA_VERSION,
        "name": "fixture",
        "nests": [{
            "loops": [{"trip": 4}, {"trip": 4}],
            "refs": [{"name": "R0", "array": "A", "level": 1,
                      "coeffs": [4, 1]}],
        }],
    }
    doc.update(over)
    return doc


def malformed_doc_fixtures() -> dict:
    """name -> (document, expected diagnostic code). Spans both
    families: F_* for JSON-layer defects, V_* for semantic ones the
    shared validator flags (proving the no-drift property: the
    frontend rejects a bad nest with the SAME code the service
    preflight gives a malformed registry model)."""
    deep = [1]
    for _ in range(MAX_DOC_DEPTH + 2):
        deep = [deep]
    huge = {"loops": [{"trip": 1 << 12}, {"trip": 1 << 12},
                      {"trip": 1 << 12}],
            "refs": [{"name": "R0", "array": "A", "level": 2,
                      "coeffs": [1 << 24, 1 << 12, 1]},
                     {"name": "R1", "array": "A", "level": 2,
                      "coeffs": [1 << 24, 1 << 12, 1]}]}
    return {
        "not_an_object": ([1, 2, 3], F_TYPE),
        "missing_version": (
            {"name": "x", "nests": _fixture_doc()["nests"]}, F_VERSION),
        "future_version": (_fixture_doc(ir_version=99), F_VERSION),
        "unknown_top_field": (_fixture_doc(engine="dense"), F_FIELD),
        "missing_nests": (
            {"ir_version": IR_SCHEMA_VERSION, "name": "x"}, F_FIELD),
        "unknown_ref_field": (_fixture_doc(nests=[{
            "loops": [{"trip": 4}],
            "refs": [{"name": "R0", "array": "A", "level": 0,
                      "coeffs": [1], "stride": 2}]}]), F_FIELD),
        "missing_trip": (_fixture_doc(nests=[{
            "loops": [{"start": 0}],
            "refs": [{"name": "R0", "array": "A", "level": 0,
                      "coeffs": [1]}]}]), F_FIELD),
        "deep_document": (_fixture_doc(nests=[{
            "loops": [{"trip": 4}],
            "refs": [{"name": "R0", "array": "A", "level": 0,
                      "coeffs": deep}]}]), F_LIMIT),
        "huge_integer": (_fixture_doc(nests=[{
            "loops": [{"trip": 1 << 50}],
            "refs": [{"name": "R0", "array": "A", "level": 0,
                      "coeffs": [1]}]}]), F_RANGE),
        "hostile_bounds_product": (
            _fixture_doc(nests=[huge] * 16), F_ACCESSES),
        "bad_machine": (
            _fixture_doc(machine={"ds": 0}), F_MACHINE),
        "non_numeric_trip": (_fixture_doc(nests=[{
            "loops": [{"trip": "16"}],
            "refs": [{"name": "R0", "array": "A", "level": 0,
                      "coeffs": [1]}]}]), "V_COEFF_SHAPE"),
        "step_zero": (_fixture_doc(nests=[{
            "loops": [{"trip": 4, "step": 0}],
            "refs": [{"name": "R0", "array": "A", "level": 0,
                      "coeffs": [1]}]}]), "V_STEP_ZERO"),
        "parallel_triangular": (_fixture_doc(nests=[{
            "loops": [{"trip": 4, "trip_coeff": 1}, {"trip": 4}],
            "refs": [{"name": "R0", "array": "A", "level": 1,
                      "coeffs": [4, 1]}]}]), "V_PARALLEL_TRIANGULAR"),
        "coeff_length": (_fixture_doc(nests=[{
            "loops": [{"trip": 4}, {"trip": 4}],
            "refs": [{"name": "R0", "array": "A", "level": 1,
                      "coeffs": [4, 1, 1]}]}]), "V_COEFF_SHAPE"),
        "bad_slot": (_fixture_doc(nests=[{
            "loops": [{"trip": 4}],
            "refs": [{"name": "R0", "array": "A", "level": 0,
                      "coeffs": [1], "slot": "mid"}]}]), "V_SLOT"),
        "no_nests": (_fixture_doc(nests=[]), "V_NO_NESTS"),
    }
