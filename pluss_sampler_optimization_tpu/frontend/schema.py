"""Versioned JSON schema for frontend programs.

One document describes one `ir.Program` plus optional machine knobs:

    {
      "ir_version": 1,
      "name": "gemm-16x16x16",
      "nests": [
        {
          "loops": [
            {"trip": 16, "start": 0, "step": 1,
             "trip_coeff": 0, "start_coeff": 0},
            ...
          ],
          "refs": [
            {"name": "C0", "array": "C", "level": 1,
             "coeffs": [16, 1], "const": 0, "slot": "pre",
             "share_threshold": null, "share_ratio": null,
             "write": null},
            ...
          ]
        }
      ],
      "machine": {"thread_num": 4, "chunk_size": 4,
                  "ds": 8, "cls": 64, "cache_kb": 2560}   // optional
    }

Loop fields beyond `trip` and ref fields beyond name/array/level/
coeffs are optional with the ir.py defaults, so hand-written nests
stay short; `program_to_json` always emits every field explicitly so
dumps are self-documenting copy-paste templates. Triangular inner
bounds ride `trip_coeff`/`start_coeff` (affine in the parallel value
v0, ir.Loop), non-unit strides ride `step`, imperfect nests ride
`level`/`slot`, and the race detector's write tri-state rides
`write` (true/false/null = derive from duplicated maps).

The `name` participates in the canonical IR and therefore in the
request fingerprint (service/fingerprint.py hashes the Program
including its name, because dumps are labeled by it): a custom nest
that should share the cache slot of a registry model must carry the
registry program's name — which is exactly what `--dump-ir` emits.

`machine` knobs, when present, override the request-level machine
fields for service submissions (AnalysisRequest.machine), so a
document is a complete scenario description on its own.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ..config import MachineConfig
from ..ir import Program

# Bump on ANY change to the document shape; parse.py rejects other
# versions with F_VERSION so future readers never misinterpret v1
# documents.
IR_SCHEMA_VERSION = 1

MACHINE_FIELDS = ("thread_num", "chunk_size", "ds", "cls", "cache_kb")

LOOP_FIELDS = ("trip", "start", "step", "trip_coeff", "start_coeff")
LOOP_REQUIRED = ("trip",)
REF_FIELDS = ("name", "array", "level", "coeffs", "const", "slot",
              "share_threshold", "share_ratio", "write")
REF_REQUIRED = ("name", "array", "level", "coeffs")


def program_to_json(program: Program,
                    machine: Optional[MachineConfig] = None) -> dict:
    """The canonical JSON document for a Program (all fields
    explicit). With `machine`, the knobs are embedded so the document
    is a full scenario template."""
    doc: dict = {
        "ir_version": IR_SCHEMA_VERSION,
        "name": program.name,
        "nests": [
            {
                "loops": [dataclasses.asdict(lp) for lp in nest.loops],
                "refs": [
                    {
                        "name": r.name,
                        "array": r.array,
                        "level": r.level,
                        "coeffs": list(r.coeffs),
                        "const": r.const,
                        "slot": r.slot,
                        "share_threshold": r.share_threshold,
                        "share_ratio": r.share_ratio,
                        "write": r.write,
                    }
                    for r in nest.refs
                ],
            }
            for nest in program.nests
        ],
    }
    if machine is not None:
        doc["machine"] = dataclasses.asdict(machine)
    return doc


def program_from_json(doc: dict) -> Program:
    """Strict round-tripper: parse, validate, canonicalize. Raises
    `parse.FrontendError` (diagnostics attached) on any defect —
    `parse.parse_program_doc` is the non-raising form."""
    from .parse import parse_program

    return parse_program(doc)


def machine_from_doc(doc, defaults: MachineConfig) -> MachineConfig:
    """The document's machine knobs over `defaults`. Documents without
    a machine section (or non-dict input) return `defaults` unchanged.
    Raises ValueError for knob values MachineConfig rejects — callers
    on the service path see only documents parse.py already vetted."""
    machine = doc.get("machine") if isinstance(doc, dict) else None
    if not isinstance(machine, dict):
        return defaults
    kw = dataclasses.asdict(defaults)
    kw.update({k: machine[k] for k in MACHINE_FIELDS if k in machine})
    return MachineConfig(**kw)
