"""Loop-nest IR: the reference's per-benchmark generated samplers as data.

The reference ships one generated C++/Rust state machine per benchmark
(c_lib/test/sampler/gemm-t4-pluss-pro-model-*.cpp, src/gemm_sampler*.rs);
the loop structure, reference order (C0 -> C1 -> A0 -> B0 -> C2 -> C3),
address affine maps (GetAddress_*, e.g.
c_lib/test/sampler/gemm-t4-pluss-pro-model-ri-omp-seq.cpp:12-35) and
carried-dependence share thresholds (:203) are all baked into code.

Here the same information is a small IR interpreted by one generic engine:

- `Loop`: one loop level with static bounds (trip, start, step).
- `Ref`: a static array reference with an affine flat-index map
  flat(iv) = sum(coeffs[l] * iv[l]) + const, cache-line address
  flat * DS // CLS (GetAddress_* formula, ...ri-omp-seq.cpp:12-35).
- `ParallelNest`: an OpenMP-style `#pragma pluss parallel` loop nest
  (gemm.ppcg_omp.c:90): level 0 is the statically-chunk-scheduled
  parallel loop; refs appear in program order at each level, before
  ("pre") or after ("post") that level's subloop.
- `Program`: an ordered list of parallel nests sharing arrays. The
  simulated per-thread access clock runs on across nests, but the
  last-access tables do NOT: the generated sampler flushes surviving
  lines as -1 and clears every LAT after each parallel loop
  (...ri-omp-seq.cpp:303-319), so reuse never crosses a nest boundary.

Share rule: a reference whose reuse is carried across simulated threads
(its address map does not involve the parallel induction variable) is
classified per access: share iff |reuse - threshold| < |reuse - 0|
(`distance_to(reuse,0) > distance_to(reuse,THRESH)`,
...ri-omp-seq.cpp:203-207), recorded with share ratio THREAD_NUM-1.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

MAX_DEPTH = 3


@dataclasses.dataclass(frozen=True)
class Loop:
    """One loop level: iterates start, start+step, ... (trip values).

    Inner levels may be *triangular*: bounds affine in the PARALLEL
    loop's value v0 (the class PolyBench's symmetric/triangular kernels
    need — syrk/trmm's `j <= i`, trisolv's `j < i`, covariance's
    `j >= i`). At parallel value v0 the level iterates
        start + start_coeff*v0 + k*step   for k in [0, trip_at(v0)),
        trip_at(v0) = max(0, trip + trip_coeff*v0).
    The parallel level itself must be rectangular
    (trip_coeff == start_coeff == 0); bounds depending on non-parallel
    outer variables (doubly-triangular nests) are out of scope.
    """

    trip: int
    start: int = 0
    step: int = 1
    trip_coeff: int = 0
    start_coeff: int = 0

    def __post_init__(self) -> None:
        if self.trip_coeff == 0 and self.trip < 1:
            raise ValueError("trip must be >= 1")
        if self.step == 0:
            raise ValueError("step must be nonzero")

    @property
    def is_triangular(self) -> bool:
        return self.trip_coeff != 0 or self.start_coeff != 0

    def trip_at(self, v0):
        """Trip count at parallel value v0 (elementwise over arrays)."""
        if not self.is_triangular:
            return self.trip if not hasattr(v0, "shape") else (
                v0 * 0 + self.trip
            )
        t = self.trip + self.trip_coeff * v0
        if hasattr(t, "shape"):
            return t.clip(min=0) if isinstance(t, np.ndarray) else t.clip(0)
        return max(0, t)

    def start_at(self, v0):
        """First iteration value at parallel value v0."""
        return self.start + self.start_coeff * v0

    @property
    def last(self) -> int:
        """The last iteration value (pluss_utils.h:331); rectangular
        loops only — triangular levels use the nest-level value range
        helpers."""
        if self.is_triangular:
            raise ValueError("last is undefined for a triangular loop")
        return self.start + (self.trip - 1) * self.step


@dataclasses.dataclass(frozen=True)
class Ref:
    """A static array reference.

    Attributes:
      name: reference name as in the generated sampler ("C0", "A0", ...;
        mapping documented at gemm.ppcg_omp.c:93-95).
      array: array name ("A", "B", "C"); last-access tables are per
        (simulated thread, array) (LAT_A/LAT_B/LAT_C,
        ...ri-omp-seq.cpp:47-49).
      level: loop level the reference sits at (0-based; its depth is
        level+1 enclosing loops).
      coeffs: affine coefficients over loop levels, length == level+1.
      const: affine constant term.
      slot: "pre" if the access happens before this level's subloop in
        program order, "post" if after. Levels without a subloop use "pre".
      share_threshold: None for thread-private references; otherwise the
        carried-reuse threshold of the share classifier
        (...ri-omp-seq.cpp:203: (1*T+1)*T+1 for GEMM's B0).
      share_ratio: number of *other* simulated threads racing on the line
        (THREAD_NUM-1 at the update site, ...ri-omp-seq.cpp:204); None
        defaults to machine.thread_num - 1 at runtime.
      write: whether this reference is a store. The engines never read
        it (locality is direction-blind), but the static race detector
        (analysis/deps.py) needs it. None means "derive": under the
        generated-sampler convention every store is a read-modify-write
        *pair* of refs sharing one affine map, so a duplicated map marks
        a write — set False on repeated reads of one element (heat-3d's
        stencil center, gesummv's x) where that convention misreads.
    """

    name: str
    array: str
    level: int
    coeffs: tuple[int, ...]
    const: int = 0
    slot: str = "pre"
    share_threshold: Optional[int] = None
    share_ratio: Optional[int] = None
    write: Optional[bool] = None

    def __post_init__(self) -> None:
        if self.level < 0 or self.level >= MAX_DEPTH:
            raise ValueError(f"level must be in [0,{MAX_DEPTH})")
        if len(self.coeffs) != self.level + 1:
            raise ValueError("coeffs length must equal level+1")
        if self.slot not in ("pre", "post"):
            raise ValueError("slot must be 'pre' or 'post'")

    @property
    def depth(self) -> int:
        return self.level + 1

    def flat_index(self, iv) -> int:
        """Affine flat element index for an iteration vector."""
        acc = self.const
        for c, v in zip(self.coeffs, iv):
            acc += c * v
        return acc


@dataclasses.dataclass(frozen=True)
class ParallelNest:
    """One `#pragma pluss parallel` loop nest (level 0 is parallel)."""

    loops: tuple[Loop, ...]
    refs: tuple[Ref, ...]

    def __post_init__(self) -> None:
        if not 1 <= len(self.loops) <= MAX_DEPTH:
            raise ValueError(f"supported nest depth is 1..{MAX_DEPTH}")
        if self.loops[0].is_triangular:
            raise ValueError("the parallel loop must be rectangular")
        for r in self.refs:
            if r.level >= len(self.loops):
                raise ValueError(f"ref {r.name} deeper than nest")
            if r.level == len(self.loops) - 1 and r.slot == "post":
                raise ValueError(
                    f"ref {r.name}: deepest level has no subloop; use slot='pre'"
                )

    @property
    def depth(self) -> int:
        return len(self.loops)

    @property
    def is_triangular(self) -> bool:
        """Any inner level's bounds depend on the parallel value."""
        return any(lp.is_triangular for lp in self.loops[1:])

    def refs_at(self, level: int, slot: str) -> tuple[Ref, ...]:
        return tuple(r for r in self.refs if r.level == level and r.slot == slot)

    def accesses_per_level_iter(self) -> tuple[int, ...]:
        """acc[l] = accesses performed by one full iteration at level l.

        GEMM: acc[2]=4 (A0,B0,C2,C3), acc[1]=2+128*4=514 (C0,C1 + inner),
        acc[0]=128*514 (= the r10 B0 share threshold body,
        ...rs-ri-opt-r10.cpp:2482). Rectangular nests only — triangular
        body sizes depend on the parallel value (NestTrace.body_at).
        """
        if self.is_triangular:
            raise ValueError(
                "accesses_per_level_iter is undefined for triangular nests"
            )
        acc = [0] * self.depth
        for l in range(self.depth - 1, -1, -1):
            n = len(self.refs_at(l, "pre")) + len(self.refs_at(l, "post"))
            if l < self.depth - 1:
                n += self.loops[l + 1].trip * acc[l + 1]
            acc[l] = n
        return tuple(acc)

    def ref_body_offset(self, ref: Ref) -> int:
        """Offset of `ref` within one iteration of its level's body
        (rectangular nests; triangular use NestTrace.ref_offset_at)."""
        pre = self.refs_at(ref.level, "pre")
        if ref.slot == "pre":
            return pre.index(ref)
        acc = self.accesses_per_level_iter()
        inner = (
            self.loops[ref.level + 1].trip * acc[ref.level + 1]
            if ref.level < self.depth - 1
            else 0
        )
        return len(pre) + inner + self.refs_at(ref.level, "post").index(ref)


@dataclasses.dataclass(frozen=True)
class Program:
    """A benchmark: ordered parallel nests over shared arrays."""

    name: str
    nests: tuple[ParallelNest, ...]

    def __post_init__(self) -> None:
        if not self.nests:
            raise ValueError("program needs at least one nest")

    @property
    def arrays(self) -> tuple[str, ...]:
        seen: dict[str, None] = {}
        for nest in self.nests:
            for r in nest.refs:
                seen.setdefault(r.array, None)
        return tuple(seen)

    @property
    def refs(self) -> tuple[tuple[int, Ref], ...]:
        """All (nest_index, ref) pairs in program order."""
        return tuple((i, r) for i, nest in enumerate(self.nests) for r in nest.refs)

    def array_id(self, array: str) -> int:
        return self.arrays.index(array)


# ---------------------------------------------------------------------------
# Flattened numeric tables for the array-program engines.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class NestTables:
    """Static numpy views of one nest, consumed by trace/dense/sampled.

    All arrays are indexed by the nest-local ref index (program order).
    Coefficients are padded to MAX_DEPTH columns.
    """

    depth: int
    trips: np.ndarray  # (MAX_DEPTH,) int64, unused levels = 1
    starts: np.ndarray  # (MAX_DEPTH,) int64
    steps: np.ndarray  # (MAX_DEPTH,) int64
    trip_coeffs: np.ndarray  # (MAX_DEPTH,) int64, 0 for rectangular
    start_coeffs: np.ndarray  # (MAX_DEPTH,) int64, 0 for rectangular
    acc_per_level: np.ndarray  # (MAX_DEPTH,) int64 (-1 when triangular)
    n_refs: int
    ref_levels: np.ndarray  # (n_refs,) int64
    ref_coeffs: np.ndarray  # (n_refs, MAX_DEPTH) int64
    ref_consts: np.ndarray  # (n_refs,) int64
    ref_arrays: np.ndarray  # (n_refs,) int64 array ids (program-wide)
    ref_offsets: np.ndarray  # (n_refs,) int64 body offset within level iter
    ref_share_thresholds: np.ndarray  # (n_refs,) int64, -1 = thread-private
    ref_share_ratios: np.ndarray  # (n_refs,) int64
    ref_names: tuple[str, ...]


def nest_tables(
    program: Program, nest_index: int, default_share_ratio: int
) -> NestTables:
    nest = program.nests[nest_index]
    d = nest.depth
    trips = np.ones(MAX_DEPTH, dtype=np.int64)
    starts = np.zeros(MAX_DEPTH, dtype=np.int64)
    steps = np.ones(MAX_DEPTH, dtype=np.int64)
    trip_cf = np.zeros(MAX_DEPTH, dtype=np.int64)
    start_cf = np.zeros(MAX_DEPTH, dtype=np.int64)
    for l, lp in enumerate(nest.loops):
        trips[l], starts[l], steps[l] = lp.trip, lp.start, lp.step
        trip_cf[l], start_cf[l] = lp.trip_coeff, lp.start_coeff
    acc = np.zeros(MAX_DEPTH, dtype=np.int64)
    if nest.is_triangular:
        acc[:] = -1  # body sizes depend on v0: use NestTrace.body_at
        offsets = np.full(len(nest.refs), -1, dtype=np.int64)
    else:
        acc[:d] = nest.accesses_per_level_iter()
        offsets = np.array(
            [nest.ref_body_offset(r) for r in nest.refs], dtype=np.int64
        )
    refs = nest.refs
    coeffs = np.zeros((len(refs), MAX_DEPTH), dtype=np.int64)
    for i, r in enumerate(refs):
        coeffs[i, : r.level + 1] = r.coeffs
    return NestTables(
        depth=d,
        trips=trips,
        starts=starts,
        steps=steps,
        trip_coeffs=trip_cf,
        start_coeffs=start_cf,
        acc_per_level=acc,
        n_refs=len(refs),
        ref_levels=np.array([r.level for r in refs], dtype=np.int64),
        ref_coeffs=coeffs,
        ref_consts=np.array([r.const for r in refs], dtype=np.int64),
        ref_arrays=np.array([program.array_id(r.array) for r in refs], dtype=np.int64),
        ref_offsets=offsets,
        ref_share_thresholds=np.array(
            [r.share_threshold if r.share_threshold is not None else -1 for r in refs],
            dtype=np.int64,
        ),
        ref_share_ratios=np.array(
            [
                r.share_ratio if r.share_ratio is not None else default_share_ratio
                for r in refs
            ],
            dtype=np.int64,
        ),
        ref_names=tuple(r.name for r in refs),
    )
