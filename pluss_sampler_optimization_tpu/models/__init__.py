"""Benchmark models expressed in the loop-nest IR.

Each function returns a `Program` equivalent to one of the reference's
generated samplers (or the analogous PolyBench kernel for benchmarks the
reference's BASELINE configs name but ship no generated sampler for).
"""

from .adi import adi
from .atax import atax
from .bicg import bicg
from .covariance import covariance
from .doitgen import doitgen
from .fdtd2d import fdtd2d
from .gemm import gemm
from .gemver import gemver
from .gesummv import gesummv
from .heat3d import heat3d
from .jacobi2d import jacobi2d
from .mm2 import mm2
from .mm3 import mm3
from .mvt import mvt
from .syrk import syrk_rect
from .syrk_tri import syrk_tri
from .trisolv import trisolv
from .trmm import trmm

def build(name: str, n: int, tsteps: int = 1):
    """Build a registry model at size n (shared by cli.py and the
    analysis service). Raises KeyError for an unknown model and
    ValueError when tsteps is passed to a model without a time axis."""
    import inspect

    if name not in REGISTRY:
        raise KeyError(
            f"unknown model {name!r} (have {', '.join(sorted(REGISTRY))})"
        )
    fn = REGISTRY[name]
    if "tsteps" in inspect.signature(fn).parameters:
        return fn(n, tsteps=tsteps)
    if tsteps != 1:
        raise ValueError(f"model {name!r} has no time-step dimension")
    return fn(n)


REGISTRY = {
    "gemm": gemm,
    "2mm": mm2,
    "3mm": mm3,
    "syrk": syrk_rect,
    "jacobi-2d": jacobi2d,
    "mvt": mvt,
    "bicg": bicg,
    "gesummv": gesummv,
    "atax": atax,
    "gemver": gemver,
    "doitgen": doitgen,
    "fdtd-2d": fdtd2d,
    "heat-3d": heat3d,
    "syrk-tri": syrk_tri,
    "trmm": trmm,
    "trisolv": trisolv,
    "covariance": covariance,
    "adi": adi,
}

__all__ = [
    "gemm", "mm2", "mm3", "syrk_rect", "jacobi2d", "mvt", "bicg",
    "gesummv", "atax", "gemver", "doitgen", "fdtd2d", "heat3d",
    "syrk_tri", "trmm", "trisolv", "covariance", "adi", "REGISTRY",
    "build",
]
