"""PolyBench adi (alternating-direction implicit solver) as a PLUSS program.

Each time step performs a column sweep then a row sweep; each sweep is
a forward recurrence followed by a *backward* substitution (PolyBench/C
4.2, scalars a..f unmodeled as usual). Column sweep:

    for (i in 1..N-1) {                       // parallel i
      v[0][i] = 1; p[i][0] = 0; q[i][0] = v[0][i];
      for (j in 1..N-1) {
        p[i][j] = -c / (a*p[i][j-1] + b);
        q[i][j] = (-d*u[j][i-1] + (1+2d)*u[j][i] - f*u[j][i+1]
                   - a*q[i][j-1]) / (a*p[i][j-1] + b);
      }
      v[N-1][i] = 1;
      for (j = N-2; j >= 1; j--)
        v[j][i] = p[i][j] * v[j+1][i] + q[i][j];
    }

The row sweep is the transposed mirror: u is written row-major
(u[i][j]), the source reads are v[i-1][j], v[i][j], v[i+1][j], and the
backward substitution runs u[i][j] = p[i][j]*u[i][j+1] + q[i][j].

The sibling forward/backward loops inside one parallel iteration are
distributed into separate parallel regions (the doitgen pattern). The
backward substitutions are *descending* inner loops
(`Loop(trip=n-2, start=n-2, step=-1)`) — trace positions follow the
normalized index (execution order) while address maps use the
iteration values, exactly the split core/trace.py encodes; no other
model exercises a negative inner step. Every reference involves the
parallel variable, so there are no share references (the stencil
boundary sharing sits below the classifier's radar as in
models/jacobi2d.py). Reference order per statement: RHS reads in
source order, then the write (models/mvt.py conventions).
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def _sweep(n: int, column: bool, src: str, dst: str):
    """(forward-recurrence nest, backward nest) of one ADI sweep.

    `column` selects the column sweep's indexing (dst inner-major
    dst[j][i], src rows along the parallel axis); the row sweep uses
    dst[i][j] and src columns.
    """
    inner = Loop(n - 2, start=1)
    back = Loop(n - 2, start=n - 2, step=-1)
    pq = (n, 1)  # p[i][j], q[i][j] in both sweeps
    if column:
        dst0 = ((1,), 0)  # dst[0][i]
        dstN = ((1,), n * (n - 1))  # dst[N-1][i]
        s_c, s_lo, s_hi = (1, n), -1, 1  # src[j][i -/+ 1]
        d_c, d_nxt = (1, n), n  # dst[j][i], dst[j+1][i]
    else:
        dst0 = ((n,), 0)  # dst[i][0]
        dstN = ((n,), n - 1)  # dst[i][N-1]
        s_c, s_lo, s_hi = (n, 1), -n, n  # src[i -/+ 1][j]
        d_c, d_nxt = (n, 1), 1  # dst[i][j], dst[i][j+1]
    fwd = ParallelNest(
        loops=(Loop(n - 2, start=1), inner),
        refs=(
            Ref("D0", dst, level=0, coeffs=dst0[0], const=dst0[1]),
            Ref("P0", "p", level=0, coeffs=(n,)),
            Ref("D1", dst, level=0, coeffs=dst0[0], const=dst0[1]),
            Ref("Q0", "q", level=0, coeffs=(n,)),
            Ref("P1", "p", level=1, coeffs=pq, const=-1),
            Ref("P2", "p", level=1, coeffs=pq),
            Ref("S0", src, level=1, coeffs=s_c, const=s_lo),
            Ref("S1", src, level=1, coeffs=s_c),
            Ref("S2", src, level=1, coeffs=s_c, const=s_hi),
            Ref("Q1", "q", level=1, coeffs=pq, const=-1),
            Ref("P3", "p", level=1, coeffs=pq, const=-1),
            Ref("Q2", "q", level=1, coeffs=pq),
            Ref("D2", dst, level=0, coeffs=dstN[0], const=dstN[1],
                slot="post"),
        ),
    )
    bwd = ParallelNest(
        loops=(Loop(n - 2, start=1), back),
        refs=(
            Ref("P4", "p", level=1, coeffs=pq),
            Ref("D3", dst, level=1, coeffs=d_c, const=d_nxt),
            Ref("Q3", "q", level=1, coeffs=pq),
            Ref("D4", dst, level=1, coeffs=d_c),
        ),
    )
    return fwd, bwd


def adi(n: int, tsteps: int = 1) -> Program:
    """u <-> v alternate as source/destination across the two sweeps."""
    if n < 3:
        raise ValueError("adi needs n >= 3")
    nests: list[ParallelNest] = []
    for _ in range(tsteps):
        nests.extend(_sweep(n, True, "u", "v"))
        nests.extend(_sweep(n, False, "v", "u"))
    return Program(name=f"adi-{n}-t{tsteps}", nests=tuple(nests))
