"""PolyBench atax as a PLUSS program.

Generated-sampler conventions as in models/gemm.py (statement order,
RHS operands in source order before the write, the classifier rule of
...ri-omp-seq.cpp:203-207) applied to PolyBench/C atax:

    for (i < NY) y[i] = 0;                    // nest 1: Y_init
    for (i < NX) {
      tmp[i] = 0;                             // T0
      for (j < NY) tmp[i] += A[i][j] * x[j];  // T1, A0, X0, T2
    }
    for (j < NY)                              // y-update, interchanged
      for (i < NX) y[j] += A[i][j] * tmp[i];  // Y1, A1, T3, Y2

The y-update loop carries a reduction over its source-order outer i, so
the parallel codegen (`#pragma pluss parallel`, the ppcg schedule the
reference's samplers were generated from, gemm.ppcg_omp.c:90) legalizes
it by interchange: the parallel variable is j and i becomes the inner
loop. That makes A1 a *transposed* walk (flat = i*NY + j, inner
coefficient NY > outer coefficient 1, the mvt A[j][i] pattern) and
tmp[i] a share reference (omits the parallel j).

Depth-2 carried-dependence thresholds 1*inner_trip+1 as in models/mvt.py.
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def atax(nx: int, ny: int | None = None) -> Program:
    ny = nx if ny is None else ny
    nest1 = ParallelNest(
        loops=(Loop(ny),),
        refs=(Ref("Y0", "y", level=0, coeffs=(1,)),),
    )
    nest2 = ParallelNest(
        loops=(Loop(nx), Loop(ny)),
        refs=(
            Ref("T0", "tmp", level=0, coeffs=(1,)),
            Ref("T1", "tmp", level=1, coeffs=(1, 0)),
            Ref("A0", "A", level=1, coeffs=(ny, 1)),
            Ref("X0", "x", level=1, coeffs=(0, 1), share_threshold=1 * ny + 1),
            Ref("T2", "tmp", level=1, coeffs=(1, 0)),
        ),
    )
    nest3 = ParallelNest(
        loops=(Loop(ny), Loop(nx)),
        refs=(
            Ref("Y1", "y", level=1, coeffs=(1, 0)),
            Ref("A1", "A", level=1, coeffs=(1, ny)),  # A[i][j], i inner
            Ref("T3", "tmp", level=1, coeffs=(0, 1),
                share_threshold=1 * nx + 1),
            Ref("Y2", "y", level=1, coeffs=(1, 0)),
        ),
    )
    return Program(name=f"atax-{nx}x{ny}", nests=(nest1, nest2, nest3))
