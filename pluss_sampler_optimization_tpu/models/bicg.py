"""PolyBench bicg as a PLUSS program.

Generated-sampler conventions as in models/gemm.py (statement order,
RHS operands in source order before the write — the C2/C3 pattern of
...ri-omp-seq.cpp:102-265) applied to PolyBench/C bicg:

    for (i < M) s[i] = 0;                     // nest 1: S_init
    for (i < N) {
      q[i] = 0;                               // Q0
      for (j < M) {
        s[j] = s[j] + r[i] * A[i][j];         // S0, R0, A0, S1
        q[i] = q[i] + A[i][j] * p[j];         // Q1, A1, P0, Q2
      }
    }

Coverage this model adds:

- a 1-deep parallel nest (level-0 references only) ahead of a 2-deep
  one, so thread clocks advance across a nest whose body has no
  subloop;
- share references that are *written* (s[j] omits i and statement 1
  stores to it): both the read S0 and the write S1 classify per access
  against the carried threshold, like GEMM's read-only B0;
- two distinct references to the same array element within one
  statement pair (A0/A1 back to back) producing constant short reuses.

Depth-2 carried-dependence threshold 1*M+1 as in models/mvt.py.
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def bicg(n: int, m: int | None = None) -> Program:
    m = n if m is None else m
    thr = 1 * m + 1
    nest1 = ParallelNest(
        loops=(Loop(m),),
        refs=(Ref("SI", "s", level=0, coeffs=(1,)),),
    )
    nest2 = ParallelNest(
        loops=(Loop(n), Loop(m)),
        refs=(
            Ref("Q0", "q", level=0, coeffs=(1,)),
            Ref("S0", "s", level=1, coeffs=(0, 1), share_threshold=thr),
            Ref("R0", "r", level=1, coeffs=(1, 0)),
            Ref("A0", "A", level=1, coeffs=(m, 1)),
            Ref("S1", "s", level=1, coeffs=(0, 1), share_threshold=thr),
            Ref("Q1", "q", level=1, coeffs=(1, 0)),
            Ref("A1", "A", level=1, coeffs=(m, 1)),
            Ref("P0", "p", level=1, coeffs=(0, 1), share_threshold=thr),
            Ref("Q2", "q", level=1, coeffs=(1, 0)),
        ),
    )
    return Program(name=f"bicg-{n}x{m}", nests=(nest1, nest2))
