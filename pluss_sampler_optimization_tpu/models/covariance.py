"""PolyBench covariance as a PLUSS program.

Three parallel nests over M features x N observations (data is N x M):

    for (j < M) {                                  // parallel over j
      mean[j] = 0;                                 // ME0
      for (i < N) mean[j] += data[i][j];           // ME1, D0, ME2
      mean[j] /= float_n;                          // ME3, ME4 (post)
    }
    for (i < N) for (j < M) data[i][j] -= mean[j]; // D1, ME5, D2
    for (i < M) for (j = i; j < M; j++) {          // upper triangle
      cov[i][j] = 0;                               // CV0
      for (k < N)
        cov[i][j] += data[k][i] * data[k][j];      // D3, D4, CV1, CV2
      cov[i][j] /= (float_n - 1);                  // CV3, CV4 (post)
      cov[j][i] = cov[i][j];                       // CV5, CV6 (post)
    }

Coverage this model adds: an *ascending-start* triangular level
(j from i: `Loop(trip=m, trip_coeff=-1, start_coeff=1)`), mixed
rectangular and triangular nests in one program over shared arrays
(data written in nest 2, read in nest 3; the per-nest LAT flush
separates them), a transposed column walk (data[i][j] with parallel j
in nest 1), and the symmetric write-back cov[j][i] whose flat map
swaps coefficient magnitudes within one statement group.

Share references: data[i][j] in nest 1 involves the parallel j;
mean[j] in nest 2 and data[k][j] in nest 3 omit their parallel
variable. Thresholds from the generated family at maximum trips
(models/syrk_tri.py).
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def covariance(m: int, n: int | None = None) -> Program:
    n = m if n is None else n
    nest_mean = ParallelNest(
        loops=(Loop(m), Loop(n)),
        refs=(
            Ref("ME0", "mean", level=0, coeffs=(1,)),
            Ref("ME1", "mean", level=1, coeffs=(1, 0)),
            Ref("D0", "data", level=1, coeffs=(1, m)),  # data[i][j], j par
            Ref("ME2", "mean", level=1, coeffs=(1, 0)),
            Ref("ME3", "mean", level=0, coeffs=(1,), slot="post"),
            Ref("ME4", "mean", level=0, coeffs=(1,), slot="post"),
        ),
    )
    nest_center = ParallelNest(
        loops=(Loop(n), Loop(m)),
        refs=(
            Ref("D1", "data", level=1, coeffs=(m, 1)),
            Ref("ME5", "mean", level=1, coeffs=(0, 1),
                share_threshold=1 * m + 1),
            Ref("D2", "data", level=1, coeffs=(m, 1)),
        ),
    )
    nest_cov = ParallelNest(
        loops=(
            Loop(m),
            Loop(trip=m, trip_coeff=-1, start_coeff=1),  # j in [i, m)
            Loop(n),
        ),
        refs=(
            Ref("CV0", "cov", level=1, coeffs=(m, 1)),
            Ref("D3", "data", level=2, coeffs=(1, 0, m)),  # data[k][i]
            Ref("D4", "data", level=2, coeffs=(0, 1, m),  # data[k][j]
                share_threshold=(1 * m + 1) * n + 1),
            Ref("CV1", "cov", level=2, coeffs=(m, 1, 0)),
            Ref("CV2", "cov", level=2, coeffs=(m, 1, 0)),
            Ref("CV3", "cov", level=1, coeffs=(m, 1), slot="post"),
            Ref("CV4", "cov", level=1, coeffs=(m, 1), slot="post"),
            Ref("CV5", "cov", level=1, coeffs=(m, 1), slot="post"),
            Ref("CV6", "cov", level=1, coeffs=(1, m), slot="post"),
        ),
    )
    return Program(
        name=f"covariance-{m}x{n}",
        nests=(nest_mean, nest_center, nest_cov),
    )
