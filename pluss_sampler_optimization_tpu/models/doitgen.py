"""PolyBench doitgen as a PLUSS program.

Generated-sampler conventions as in models/gemm.py applied to
PolyBench/C doitgen (3.2 form, sum indexed sum[r][q][p]):

    for (r < NR) for (q < NQ) {
      for (p < NP) {
        sum[r][q][p] = 0;                           // S0
        for (s < NP)
          sum[r][q][p] += A[r][q][s] * C4[s][p];    // S1, A0, C40, S2
      }
      for (p < NP) A[r][q][p] = sum[r][q][p];       // S3, A1
    }

The two sibling p-loops inside one (r,q) iteration do not fit a single
chain-shaped nest, so the parallel schedule distributes them into two
`#pragma pluss parallel` regions with the (r,q) pair collapsed into one
parallel loop of NR*NQ iterations — the standard ppcg
distribute+collapse schedule for this kernel, and the reference codegen
emits one dispatcher per parallel region anyway
(...ri-omp-seq.cpp:59-60 allocates the dispatcher per loop). The
simulated thread clock runs across both regions; the write-back nest's
A/sum reuses start cold at the region boundary per the LAT flush
(...ri-omp-seq.cpp:303-319).

C4[s][p] omits the parallel variable -> share reference with the
depth-3 carried threshold (1*NP+1)*NP+1 (the gemm B0 family,
...ri-omp-seq.cpp:203).
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def doitgen(nr: int, nq: int | None = None, np_: int | None = None) -> Program:
    nq = nr if nq is None else nq
    np_ = nr if np_ is None else np_
    nest1 = ParallelNest(
        loops=(Loop(nr * nq), Loop(np_), Loop(np_)),
        refs=(
            Ref("S0", "sum", level=1, coeffs=(np_, 1)),
            Ref("S1", "sum", level=2, coeffs=(np_, 1, 0)),
            Ref("A0", "A", level=2, coeffs=(np_, 0, 1)),
            Ref("C40", "C4", level=2, coeffs=(0, 1, np_),
                share_threshold=(1 * np_ + 1) * np_ + 1),
            Ref("S2", "sum", level=2, coeffs=(np_, 1, 0)),
        ),
    )
    nest2 = ParallelNest(
        loops=(Loop(nr * nq), Loop(np_)),
        refs=(
            Ref("S3", "sum", level=1, coeffs=(np_, 1)),
            Ref("A1", "A", level=1, coeffs=(np_, 1)),
        ),
    )
    return Program(name=f"doitgen-{nr}x{nq}x{np_}", nests=(nest1, nest2))
