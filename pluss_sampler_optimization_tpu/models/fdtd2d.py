"""PolyBench fdtd-2d as a PLUSS program.

Generated-sampler conventions as in models/gemm.py applied to
PolyBench/C fdtd-2d (2-D finite-difference time domain); each time step
contributes four parallel nests, unrolled into the program's nest list
like models/jacobi2d.py:

    for (t < TSTEPS) {
      for (j < NY) ey[0][j] = _fict_[t];                 // F0, EY0
      for (i in 1..NX) for (j < NY)
        ey[i][j] = ey[i][j] - 0.5*(hz[i][j]-hz[i-1][j]); // EY1,HZ0,HZ1,EY2
      for (i < NX) for (j in 1..NY)
        ex[i][j] = ex[i][j] - 0.5*(hz[i][j]-hz[i][j-1]); // EX0,HZ2,HZ3,EX1
      for (i < NX-1) for (j < NY-1)
        hz[i][j] = hz[i][j] - 0.7*(ex[i][j+1] - ex[i][j]
                 + ey[i+1][j] - ey[i][j]);     // HZ4,EX2,EX3,EY3,EY4,HZ5
    }

Coverage this model adds: a *constant* reference (_fict_[t], no loop
variable at all — every simulated thread races on its single line, and
its address map degenerates to the affine constant); boundary nests
whose loop `start`/trip differ per nest over the same arrays; and the
jacobi-style +/-1 and +/-NY stencil constants in both dimensions.

F0 omits the parallel variable -> share reference; at depth 1 the
carried-threshold family (1*t1+1)*t2+1 / 1*t+1 (models/mvt.py)
degenerates to 1.
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def fdtd2d(nx: int, ny: int | None = None, tsteps: int = 1) -> Program:
    ny = nx if ny is None else ny
    if nx < 2 or ny < 2:
        raise ValueError("fdtd2d needs nx, ny >= 2")
    nests = []
    for t in range(tsteps):
        nests.append(ParallelNest(
            loops=(Loop(ny),),
            refs=(
                Ref("F0", "fict", level=0, coeffs=(0,), const=t,
                    share_threshold=1),
                Ref("EY0", "ey", level=0, coeffs=(1,)),
            ),
        ))
        nests.append(ParallelNest(
            loops=(Loop(nx - 1, start=1), Loop(ny)),
            refs=(
                Ref("EY1", "ey", level=1, coeffs=(ny, 1)),
                Ref("HZ0", "hz", level=1, coeffs=(ny, 1)),
                Ref("HZ1", "hz", level=1, coeffs=(ny, 1), const=-ny),
                Ref("EY2", "ey", level=1, coeffs=(ny, 1)),
            ),
        ))
        nests.append(ParallelNest(
            loops=(Loop(nx), Loop(ny - 1, start=1)),
            refs=(
                Ref("EX0", "ex", level=1, coeffs=(ny, 1)),
                Ref("HZ2", "hz", level=1, coeffs=(ny, 1)),
                Ref("HZ3", "hz", level=1, coeffs=(ny, 1), const=-1),
                Ref("EX1", "ex", level=1, coeffs=(ny, 1)),
            ),
        ))
        nests.append(ParallelNest(
            loops=(Loop(nx - 1), Loop(ny - 1)),
            refs=(
                Ref("HZ4", "hz", level=1, coeffs=(ny, 1)),
                Ref("EX2", "ex", level=1, coeffs=(ny, 1), const=1),
                Ref("EX3", "ex", level=1, coeffs=(ny, 1)),
                Ref("EY3", "ey", level=1, coeffs=(ny, 1), const=ny),
                Ref("EY4", "ey", level=1, coeffs=(ny, 1)),
                Ref("HZ5", "hz", level=1, coeffs=(ny, 1)),
            ),
        ))
    return Program(name=f"fdtd2d-{nx}x{ny}-t{tsteps}", nests=tuple(nests))
