"""PolyBench GEMM as a PLUSS program.

Source kernel: c_lib/test/gemm.ppcg_omp.c:86-100 —

    #pragma pluss parallel
    for (c0 in 0..NI)            // parallel, static chunks
      for (c1 in 0..NJ) {
        C[c0][c1] *= beta;       // refs C0 (read), C1 (write)
        for (c2 in 0..NK)
          C[c0][c1] += alpha * A[c0][c2] * B[c2][c1];
                                 // refs A0, B0, C2 (read), C3 (write)
      }

Reference-name mapping documented at gemm.ppcg_omp.c:93-95; access order
C0 -> C1 -> A0 -> B0 -> C2 -> C3 is the generated state machine
(...ri-omp-seq.cpp:102-265). Address maps are GetAddress_*
(...ri-omp-seq.cpp:12-35): flat = idx0*N + idx1.

B0 is the only cross-thread ("share") reference: B[c2][c1] does not
involve the parallel variable c0, so all simulated threads race on its
lines. The generated classifier compares the private reuse against a
carried-dependence threshold:

- full-traversal variants: (1*N+1)*N+1  (= 16513 at N=128,
  ...ri-omp-seq.cpp:203);
- sampled r10 variant:     (4*N+2)*N    (= 65792 at N=128,
  ...rs-ri-opt-r10.cpp:2482) — one full c0-iteration of accesses.
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def gemm(n: int, ni: int | None = None, nj: int | None = None, nk: int | None = None,
         share_threshold_variant: str = "ri") -> Program:
    """GEMM program; `n` is the default for all three trip counts."""
    ni = n if ni is None else ni
    nj = n if nj is None else nj
    nk = n if nk is None else nk
    if share_threshold_variant == "ri":
        b0_threshold = (1 * nj + 1) * nk + 1  # ...ri-omp-seq.cpp:203
    elif share_threshold_variant == "r10":
        b0_threshold = (4 * nk + 2) * nj  # ...rs-ri-opt-r10.cpp:2482
    else:
        raise ValueError("share_threshold_variant must be 'ri' or 'r10'")

    nest = ParallelNest(
        loops=(Loop(ni), Loop(nj), Loop(nk)),
        refs=(
            Ref("C0", "C", level=1, coeffs=(nj, 1)),
            Ref("C1", "C", level=1, coeffs=(nj, 1)),
            Ref("A0", "A", level=2, coeffs=(nk, 0, 1)),
            Ref("B0", "B", level=2, coeffs=(0, 1, nj), share_threshold=b0_threshold),
            Ref("C2", "C", level=2, coeffs=(nj, 1, 0)),
            Ref("C3", "C", level=2, coeffs=(nj, 1, 0)),
        ),
    )
    return Program(name=f"gemm-{ni}x{nj}x{nk}", nests=(nest,))
