"""PolyBench gemver as a PLUSS program.

Generated-sampler conventions as in models/gemm.py applied to
PolyBench/C gemver (scalars alpha/beta are unmodeled, exactly as the
reference's GEMM sampler models no scalar operands):

    for (i < N) for (j < N)
      A[i][j] = A[i][j] + u1[i]*v1[j] + u2[i]*v2[j];
                                     // A0, U10, V10, U20, V20, A1
    for (i < N) for (j < N)
      x[i] = x[i] + beta * A[j][i] * y[j];   // X0, A2, Y0, X1
    for (i < N) x[i] = x[i] + z[i];          // X2, Z0, X3
    for (i < N) for (j < N)
      w[i] = w[i] + alpha * A[i][j] * x[j];  // W0, A3, X4, W1

Coverage this model adds: four nests of mixed depth over one shared
array A that is written in nest 1, read transposed (A[j][i]) in nest 2
and read row-major in nest 4 — the per-nest LAT flush
(...ri-omp-seq.cpp:303-319) makes each nest's A reuse start cold; and
x crosses nests as well (written in 2/3, share-read in 4).

Depth-2 carried thresholds 1*N+1 as in models/mvt.py.
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def gemver(n: int) -> Program:
    thr = 1 * n + 1
    nest1 = ParallelNest(
        loops=(Loop(n), Loop(n)),
        refs=(
            Ref("A0", "A", level=1, coeffs=(n, 1)),
            Ref("U10", "u1", level=1, coeffs=(1, 0)),
            Ref("V10", "v1", level=1, coeffs=(0, 1), share_threshold=thr),
            Ref("U20", "u2", level=1, coeffs=(1, 0)),
            Ref("V20", "v2", level=1, coeffs=(0, 1), share_threshold=thr),
            Ref("A1", "A", level=1, coeffs=(n, 1)),
        ),
    )
    nest2 = ParallelNest(
        loops=(Loop(n), Loop(n)),
        refs=(
            Ref("X0", "x", level=1, coeffs=(1, 0)),
            Ref("A2", "A", level=1, coeffs=(1, n)),  # A[j][i]
            Ref("Y0", "y", level=1, coeffs=(0, 1), share_threshold=thr),
            Ref("X1", "x", level=1, coeffs=(1, 0)),
        ),
    )
    nest3 = ParallelNest(
        loops=(Loop(n),),
        refs=(
            Ref("X2", "x", level=0, coeffs=(1,)),
            Ref("Z0", "z", level=0, coeffs=(1,)),
            Ref("X3", "x", level=0, coeffs=(1,)),
        ),
    )
    nest4 = ParallelNest(
        loops=(Loop(n), Loop(n)),
        refs=(
            Ref("W0", "w", level=1, coeffs=(1, 0)),
            Ref("A3", "A", level=1, coeffs=(n, 1)),
            Ref("X4", "x", level=1, coeffs=(0, 1), share_threshold=thr),
            Ref("W1", "w", level=1, coeffs=(1, 0)),
        ),
    )
    return Program(name=f"gemver-{n}", nests=(nest1, nest2, nest3, nest4))
