"""PolyBench gesummv as a PLUSS program.

Generated-sampler conventions as in models/gemm.py applied to
PolyBench/C gesummv:

    for (i < N) {
      tmp[i] = 0;                             // T0
      y[i]   = 0;                             // Y0
      for (j < N) {
        tmp[i] = A[i][j] * x[j] + tmp[i];     // A0, X0, T1, T2
        y[i]   = B[i][j] * x[j] + y[i];       // B0, X1, Y1, Y2
      }
      y[i] = alpha * tmp[i] + beta * y[i];    // T3, Y3, Y4  (after the
    }                                         //  subloop: slot="post")

Coverage this model adds: level-0 references *after* the inner loop
(slot="post", the IR's placement arm that gemm/2mm/3mm/syrk/jacobi
never exercise — ref_body_offset must account for the whole subloop,
ir.py::ParallelNest.ref_body_offset), plus one share array (x) read by
two references in different statements. Depth-2 carried threshold
1*N+1 as in models/mvt.py.
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def gesummv(n: int) -> Program:
    thr = 1 * n + 1
    nest = ParallelNest(
        loops=(Loop(n), Loop(n)),
        refs=(
            Ref("T0", "tmp", level=0, coeffs=(1,)),
            Ref("Y0", "y", level=0, coeffs=(1,)),
            Ref("A0", "A", level=1, coeffs=(n, 1)),
            # x[j] is read by BOTH statements: the duplicated map is two
            # loads, not a read-modify-write pair (write=False keeps the
            # race detector from deriving a store here)
            Ref("X0", "x", level=1, coeffs=(0, 1), share_threshold=thr,
                write=False),
            Ref("T1", "tmp", level=1, coeffs=(1, 0)),
            Ref("T2", "tmp", level=1, coeffs=(1, 0)),
            Ref("B0", "B", level=1, coeffs=(n, 1)),
            Ref("X1", "x", level=1, coeffs=(0, 1), share_threshold=thr,
                write=False),
            Ref("Y1", "y", level=1, coeffs=(1, 0)),
            Ref("Y2", "y", level=1, coeffs=(1, 0)),
            Ref("T3", "tmp", level=0, coeffs=(1,), slot="post"),
            Ref("Y3", "y", level=0, coeffs=(1,), slot="post"),
            Ref("Y4", "y", level=0, coeffs=(1,), slot="post"),
        ),
    )
    return Program(name=f"gesummv-{n}", nests=(nest,))
