"""PolyBench heat-3d as a PLUSS program.

Generated-sampler conventions as in models/gemm.py applied to
PolyBench heat-3d (3-D heat equation); each time step contributes two
3-deep parallel nests (B from A, then A from B), unrolled like
models/jacobi2d.py:

    for (i,j,k in 1..N-1)^3
      B[i][j][k] = 0.125*(A[i+1][j][k] - 2*A[i][j][k] + A[i-1][j][k])
                 + 0.125*(A[i][j+1][k] - 2*A[i][j][k] + A[i][j-1][k])
                 + 0.125*(A[i][j][k+1] - 2*A[i][j][k] + A[i][j][k-1])
                 + A[i][j][k];
    ... then the same statement with A and B swapped.

RHS reads in source order (A0..A9, three of them the repeated center
point), then the write (B0). Coverage this model adds: references whose
flat map has THREE nonzero coefficients (N*N, N, 1) — the next-use
band enumeration must recurse through two stride levels before the
unit-stride window (sampler/nextuse.py) — with +/-N^2 plane-stencil
constants. All references involve the parallel variable i, so there are
no share references, exactly as models/jacobi2d.py.
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def _stencil_refs(read: str, write: str, n: int) -> tuple[Ref, ...]:
    c = (n * n, n, 1)
    reads = [n * n, 0, -n * n, n, 0, -n, 1, 0, -1, 0]
    # the center point repeats four times among the RHS reads; write=False
    # keeps the race detector's duplicated-map convention from deriving a
    # store out of them (the store goes to the OTHER array)
    refs = [
        Ref(f"{read.upper()}{k}", read, level=2, coeffs=c, const=d,
            write=False)
        for k, d in enumerate(reads)
    ]
    refs.append(Ref(f"{write.upper()}W", write, level=2, coeffs=c,
                    write=True))
    return tuple(refs)


def heat3d(n: int, tsteps: int = 1) -> Program:
    if n < 3:
        raise ValueError("heat3d needs n >= 3")
    inner = Loop(n - 2, start=1)
    nest_b = ParallelNest(
        loops=(inner, inner, inner), refs=_stencil_refs("a", "b", n)
    )
    nest_a = ParallelNest(
        loops=(inner, inner, inner), refs=_stencil_refs("b", "a", n)
    )
    return Program(name=f"heat3d-{n}-t{tsteps}", nests=(nest_b, nest_a) * tsteps)
