"""PolyBench jacobi-2d as a PLUSS program (BASELINE.json config 5).

The stencil/long-trace configuration: each time step contributes two
2-deep parallel nests (PolyBench/C jacobi-2d-imper):

    for (t < TSTEPS) {
      for (i in 1..N-1) for (j in 1..N-1)
        B[i][j] = 0.2*(A[i][j]+A[i][j-1]+A[i][1+j]+A[1+i][j]+A[i-1][j]);
      for (i in 1..N-1) for (j in 1..N-1)
        A[i][j] = B[i][j];
    }

The sequential t loop is unrolled into the program's nest list (the
reference codegen emits one dispatcher per parallel loop and keeps one
runtime across them, ...ri-omp-seq.cpp:59-60). Loop starts are 1, which
exercises non-zero `start` in the chunk arithmetic (pluss_utils.h:312).
All references involve the parallel variable i -> no share references;
cross-thread boundary-row sharing (A[i-1], A[1+i]) is below the share
classifier's radar exactly as it would be in the reference's codegen.
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def jacobi2d(n: int, tsteps: int = 1) -> Program:
    if n < 3:
        raise ValueError("jacobi2d needs n >= 3")
    inner = Loop(n - 2, start=1)
    nest_b = ParallelNest(
        loops=(inner, inner),
        refs=(
            Ref("A0", "A", level=1, coeffs=(n, 1)),
            Ref("A1", "A", level=1, coeffs=(n, 1), const=-1),
            Ref("A2", "A", level=1, coeffs=(n, 1), const=1),
            Ref("A3", "A", level=1, coeffs=(n, 1), const=n),
            Ref("A4", "A", level=1, coeffs=(n, 1), const=-n),
            Ref("B0", "B", level=1, coeffs=(n, 1)),
        ),
    )
    nest_a = ParallelNest(
        loops=(inner, inner),
        refs=(
            Ref("B1", "B", level=1, coeffs=(n, 1)),
            Ref("A5", "A", level=1, coeffs=(n, 1)),
        ),
    )
    return Program(name=f"jacobi2d-{n}-t{tsteps}", nests=(nest_b, nest_a) * tsteps)
