"""PolyBench 2mm as a PLUSS program (BASELINE.json config 4).

The reference ships generated samplers only for GEMM; 2mm follows the
same codegen conventions (statement-order references, read-before-write
per compound assignment, share classification for references not
involving the parallel induction variable) applied to PolyBench/C 2mm:

    // nest 1: tmp = alpha * A x B
    for (i < NI) for (j < NJ) { tmp[i][j] = 0;            // T0 (write)
      for (k < NK) tmp[i][j] += alpha*A[i][k]*B[k][j]; }  // A0,B0,T1,T2
    // nest 2: D = tmp x C + beta * D
    for (i < NI) for (j < NL) { D[i][j] *= beta;          // D0,D1
      for (k < NJ) D[i][j] += tmp[i][k]*C[k][j]; }        // T3,C0,D2,D3

B0 (nest 1) and C0 (nest 2) omit the parallel variable i -> share
references, thresholds per the full-traversal formula (1*Tmid+1)*Tinner+1
(...ri-omp-seq.cpp:203). Cross-nest reuse (tmp written in nest 1, read in
nest 2) exercises the multi-nest clock/LAT persistence.
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def mm2(n: int, ni: int | None = None, nj: int | None = None,
        nk: int | None = None, nl: int | None = None) -> Program:
    ni = n if ni is None else ni
    nj = n if nj is None else nj
    nk = n if nk is None else nk
    nl = n if nl is None else nl

    nest1 = ParallelNest(
        loops=(Loop(ni), Loop(nj), Loop(nk)),
        refs=(
            Ref("T0", "tmp", level=1, coeffs=(nj, 1)),
            Ref("A0", "A", level=2, coeffs=(nk, 0, 1)),
            Ref("B0", "B", level=2, coeffs=(0, 1, nj),
                share_threshold=(1 * nj + 1) * nk + 1),
            Ref("T1", "tmp", level=2, coeffs=(nj, 1, 0)),
            Ref("T2", "tmp", level=2, coeffs=(nj, 1, 0)),
        ),
    )
    nest2 = ParallelNest(
        loops=(Loop(ni), Loop(nl), Loop(nj)),
        refs=(
            Ref("D0", "D", level=1, coeffs=(nl, 1)),
            Ref("D1", "D", level=1, coeffs=(nl, 1)),
            Ref("T3", "tmp", level=2, coeffs=(nj, 0, 1)),
            Ref("C0", "C", level=2, coeffs=(0, 1, nl),
                share_threshold=(1 * nl + 1) * nj + 1),
            Ref("D2", "D", level=2, coeffs=(nl, 1, 0)),
            Ref("D3", "D", level=2, coeffs=(nl, 1, 0)),
        ),
    )
    return Program(name=f"2mm-{ni}", nests=(nest1, nest2))
