"""PolyBench 3mm as a PLUSS program (BASELINE.json config 4).

Same codegen conventions as models/gemm.py applied to PolyBench/C 3mm:

    for (i < NI) for (j < NJ) { E[i][j] = 0;              // E0 (write)
      for (k < NK) E[i][j] += A[i][k]*B[k][j]; }          // A0,B0,E1,E2
    for (i < NJ) for (j < NL) { F[i][j] = 0;              // F0
      for (k < NM) F[i][j] += C[i][k]*D[k][j]; }          // C0,D0,F1,F2
    for (i < NI) for (j < NL) { G[i][j] = 0;              // G0
      for (k < NJ) G[i][j] += E[i][k]*F[k][j]; }          // E3,F3,G1,G2

B0, D0 and F3 omit the parallel variable -> share references. E and F
carry cross-nest reuse into nest 3.
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def mm3(n: int, ni: int | None = None, nj: int | None = None, nk: int | None = None,
        nl: int | None = None, nm: int | None = None) -> Program:
    ni = n if ni is None else ni
    nj = n if nj is None else nj
    nk = n if nk is None else nk
    nl = n if nl is None else nl
    nm = n if nm is None else nm

    nest1 = ParallelNest(
        loops=(Loop(ni), Loop(nj), Loop(nk)),
        refs=(
            Ref("E0", "E", level=1, coeffs=(nj, 1)),
            Ref("A0", "A", level=2, coeffs=(nk, 0, 1)),
            Ref("B0", "B", level=2, coeffs=(0, 1, nj),
                share_threshold=(1 * nj + 1) * nk + 1),
            Ref("E1", "E", level=2, coeffs=(nj, 1, 0)),
            Ref("E2", "E", level=2, coeffs=(nj, 1, 0)),
        ),
    )
    nest2 = ParallelNest(
        loops=(Loop(nj), Loop(nl), Loop(nm)),
        refs=(
            Ref("F0", "F", level=1, coeffs=(nl, 1)),
            Ref("C0", "C", level=2, coeffs=(nm, 0, 1)),
            Ref("D0", "D", level=2, coeffs=(0, 1, nl),
                share_threshold=(1 * nl + 1) * nm + 1),
            Ref("F1", "F", level=2, coeffs=(nl, 1, 0)),
            Ref("F2", "F", level=2, coeffs=(nl, 1, 0)),
        ),
    )
    nest3 = ParallelNest(
        loops=(Loop(ni), Loop(nl), Loop(nj)),
        refs=(
            Ref("G0", "G", level=1, coeffs=(nl, 1)),
            Ref("E3", "E", level=2, coeffs=(nj, 0, 1)),
            Ref("F3", "F", level=2, coeffs=(0, 1, nl),
                share_threshold=(1 * nl + 1) * nj + 1),
            Ref("G1", "G", level=2, coeffs=(nl, 1, 0)),
            Ref("G2", "G", level=2, coeffs=(nl, 1, 0)),
        ),
    )
    return Program(name=f"3mm-{ni}", nests=(nest1, nest2, nest3))
