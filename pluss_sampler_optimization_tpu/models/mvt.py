"""PolyBench mvt as a PLUSS program.

The reference ships generated samplers only for GEMM; mvt follows the
same codegen conventions (statement-order references, operands in
source order then the write, share classification for references whose
address map omits the parallel induction variable — the rule documented
at ...ri-omp-seq.cpp:203-207) applied to PolyBench/C mvt:

    for (i < N) for (j < N)
      x1[i] = x1[i] + A[i][j] * y_1[j];   // X10, A0, Y10, X11
    for (i < N) for (j < N)
      x2[i] = x2[i] + A[j][i] * y_2[j];   // X20, A1, Y20, X21

Coverage this model adds over gemm/2mm/3mm/syrk:

- a *transposed* access A[j][i] (flat = j*N + i, coefficient on the
  inner variable larger than on the parallel one) — the closed-form
  next-use band enumeration (sampler/nextuse.py::_ref_vars orders
  coefficients descending) must treat the inner variable as the
  large-stride term;
- share references in a 2-deep nest (y_1/y_2 omit i). Their carried
  reuse across consecutive parallel iterations spans one inner loop of
  body accesses (~4N); the generated-code threshold family
  ((1*Tmid+1)*Tinner+1 at depth 3, ...ri-omp-seq.cpp:203) degenerates
  at depth 2 to 1*N+1, which separates the intra-line stride reuse
  (~body size) from the carried one exactly as GEMM's 16513 does.
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def mvt(n: int) -> Program:
    thr = 1 * n + 1
    nest1 = ParallelNest(
        loops=(Loop(n), Loop(n)),
        refs=(
            Ref("X10", "x1", level=1, coeffs=(1, 0)),
            Ref("A0", "A", level=1, coeffs=(n, 1)),
            Ref("Y10", "y_1", level=1, coeffs=(0, 1), share_threshold=thr),
            Ref("X11", "x1", level=1, coeffs=(1, 0)),
        ),
    )
    nest2 = ParallelNest(
        loops=(Loop(n), Loop(n)),
        refs=(
            Ref("X20", "x2", level=1, coeffs=(1, 0)),
            Ref("A1", "A", level=1, coeffs=(1, n)),  # A[j][i]
            Ref("Y20", "y_2", level=1, coeffs=(0, 1), share_threshold=thr),
            Ref("X21", "x2", level=1, coeffs=(1, 0)),
        ),
    )
    return Program(name=f"mvt-{n}", nests=(nest1, nest2))
