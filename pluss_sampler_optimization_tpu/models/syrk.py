"""PolyBench syrk (rectangular 3.2 variant) as a PLUSS program.

BASELINE.json config 4 names syrk. PolyBench/C 3.2's syrk is the
rectangular form; the 4.2 triangular form is models/syrk_tri.py:

    for (i < N) for (j < N) C[i][j] *= beta;              // C0,C1
    for (i < N) for (j < N)
      for (k < M) C[i][j] += alpha*A[i][k]*A[j][k];       // A0,A1,C2,C3

A1 = A[j][k] omits the parallel variable i -> share reference; note both
A0 and A1 hit the *same* array, the case where one array has both a
private-reuse and a shared-reuse reference.
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def syrk_rect(n: int, m: int | None = None) -> Program:
    m = n if m is None else m
    nest1 = ParallelNest(
        loops=(Loop(n), Loop(n)),
        refs=(
            Ref("C0", "C", level=1, coeffs=(n, 1)),
            Ref("C1", "C", level=1, coeffs=(n, 1)),
        ),
    )
    nest2 = ParallelNest(
        loops=(Loop(n), Loop(n), Loop(m)),
        refs=(
            Ref("A0", "A", level=2, coeffs=(m, 0, 1)),
            Ref("A1", "A", level=2, coeffs=(0, m, 1),
                share_threshold=(1 * n + 1) * m + 1),
            Ref("C2", "C", level=2, coeffs=(n, 1, 0)),
            Ref("C3", "C", level=2, coeffs=(n, 1, 0)),
        ),
    )
    return Program(name=f"syrk-{n}", nests=(nest1, nest2))
