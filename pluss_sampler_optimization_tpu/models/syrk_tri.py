"""PolyBench syrk, triangular (4.2) form, as a PLUSS program.

models/syrk.py covers the rectangular 3.2 variant; this is the 4.2
kernel whose inner j-loop runs only over the lower triangle:

    for (i < N) {
      for (j <= i) C[i][j] *= beta;                     // C0, C1
      for (k < M)
        for (j <= i) C[i][j] += alpha*A[i][k]*A[j][k];  // A0, A1, C2, C3
    }

The two sibling loops inside one i-iteration are distributed into two
parallel regions (the doitgen pattern, models/doitgen.py); the j levels
are triangular with trip i+1 (`Loop(trip=1, trip_coeff=1)`).

A1 = A[j][k] omits the parallel variable -> share reference. The
carried-threshold family of the generated code ((1*t_mid+1)*t_inner+1,
...ri-omp-seq.cpp:203) is evaluated at the triangular level's maximum
trip, the threshold a codegen run at the full rectangular bounding box
would emit.
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def syrk_tri(n: int, m: int | None = None) -> Program:
    m = n if m is None else m
    tri = Loop(trip=1, trip_coeff=1)  # j in [0, i]
    nest1 = ParallelNest(
        loops=(Loop(n), tri),
        refs=(
            Ref("C0", "C", level=1, coeffs=(n, 1)),
            Ref("C1", "C", level=1, coeffs=(n, 1)),
        ),
    )
    nest2 = ParallelNest(
        loops=(Loop(n), Loop(m), tri),
        refs=(
            Ref("A0", "A", level=2, coeffs=(m, 1, 0)),
            Ref("A1", "A", level=2, coeffs=(0, 1, m),
                share_threshold=(1 * m + 1) * n + 1),
            Ref("C2", "C", level=2, coeffs=(n, 0, 1)),
            Ref("C3", "C", level=2, coeffs=(n, 0, 1)),
        ),
    )
    return Program(name=f"syrk-tri-{n}x{m}", nests=(nest1, nest2))
