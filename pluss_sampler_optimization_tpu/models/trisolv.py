"""PolyBench trisolv (lower-triangular solve) as a PLUSS program.

    for (i < N) {
      x[i] = b[i];                          // B0, X0
      for (j < i)
        x[i] = x[i] - L[i][j] * x[j];       // X1, L0, X2, X3
      x[i] = x[i] / L[i][i];                // X4, L1, X5 (post, level 0)
    }

The source loop carries x[j] dependences across i; the PLUSS machine
models the static-chunk parallel schedule of the annotated loop exactly
as the reference would for any `#pragma pluss parallel` nest (the model
measures locality of the interleaving, not legality).

Coverage this model adds: a triangular level whose trip is *zero* at
the first parallel iterations (`Loop(trip=0, trip_coeff=1)`), post-slot
level-0 refs after a triangular subloop, a diagonal reference
(L[i][i] -> coefficient N+1), and a share reference (x[j], omits i)
that is also written at the same level. Depth-2 threshold family
1*T+1 at the maximum trip (models/mvt.py).
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def trisolv(n: int) -> Program:
    if n < 2:
        raise ValueError("trisolv needs n >= 2")
    thr = 1 * (n - 1) + 1
    nest = ParallelNest(
        loops=(Loop(n), Loop(trip=0, trip_coeff=1)),  # j in [0, i)
        refs=(
            Ref("B0", "b", level=0, coeffs=(1,)),
            Ref("X0", "x", level=0, coeffs=(1,)),
            Ref("X1", "x", level=1, coeffs=(1, 0)),
            Ref("L0", "L", level=1, coeffs=(n, 1)),
            Ref("X2", "x", level=1, coeffs=(0, 1), share_threshold=thr),
            Ref("X3", "x", level=1, coeffs=(1, 0)),
            Ref("X4", "x", level=0, coeffs=(1,), slot="post"),
            Ref("L1", "L", level=0, coeffs=(n + 1,), slot="post"),
            Ref("X5", "x", level=0, coeffs=(1,), slot="post"),
        ),
    )
    return Program(name=f"trisolv-{n}", nests=(nest,))
