"""PolyBench trmm (4.2, lower-triangular left multiply) as a PLUSS program.

    for (i < M) for (j < N) {
      for (k = i+1; k < M; k++)
        B[i][j] += A[k][i] * B[k][j];   // A0, B0, B1, B2
      B[i][j] = alpha * B[i][j];        // B3, B4 (post slot, level 1)
    }

Coverage this model adds: a *descending* triangular level (the k-loop
shrinks as i grows: start i+1, trip M-1-i -> `Loop(trip=m-1,
trip_coeff=-1, start=1, start_coeff=1)`), reaching trip 0 at the last
parallel iteration, plus post-slot references at a level whose subloop
is triangular (their body offset varies per parallel value,
core/trace.py::ref_offset_at).

B0 = B[k][j] omits the parallel variable -> share reference; threshold
family evaluated at the triangular level's maximum trip as in
models/syrk_tri.py.
"""

from __future__ import annotations

from ..ir import Loop, ParallelNest, Program, Ref


def trmm(m: int, n: int | None = None) -> Program:
    n = m if n is None else n
    if m < 2:
        raise ValueError("trmm needs m >= 2")
    nest = ParallelNest(
        loops=(
            Loop(m),
            Loop(n),
            Loop(trip=m - 1, trip_coeff=-1, start=1, start_coeff=1),
        ),
        refs=(
            Ref("A0", "A", level=2, coeffs=(1, 0, m)),
            Ref("B0", "B", level=2, coeffs=(0, 1, n),
                share_threshold=(1 * n + 1) * (m - 1) + 1),
            Ref("B1", "B", level=2, coeffs=(n, 1, 0)),
            Ref("B2", "B", level=2, coeffs=(n, 1, 0)),
            Ref("B3", "B", level=1, coeffs=(n, 1), slot="post"),
            Ref("B4", "B", level=1, coeffs=(n, 1), slot="post"),
        ),
    )
    return Program(name=f"trmm-{m}x{n}", nests=(nest,))
