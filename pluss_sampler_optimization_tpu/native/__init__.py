"""ctypes bindings for the native serial sampler runtime.

The C++ library (pluss_native.cpp) is the framework's native runtime
component — the TPU-native equivalent of the reference's C++ runtime +
generated serial sampler (c_lib/test/runtime/pluss_utils.h,
c_lib/test/sampler/...-ri-omp-seq.cpp), driven by the loop-nest IR
instead of per-benchmark codegen. It serves as the fast large-N oracle
and as bench.py's single-core speed baseline.

Built lazily with g++ on first use; `available()` reports whether a
toolchain/binary exists so callers can fall back to the Python oracle.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

from ..config import MachineConfig
from ..ir import MAX_DEPTH, Program, nest_tables
from ..oracle.serial import OracleResult
from ..runtime.hist import PRIState

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libplussnative.so")
_SRC = os.path.join(_DIR, "pluss_native.cpp")

N_NOSHARE_BINS = 64
_NOSHARE_SLOTS = N_NOSHARE_BINS + 1  # + the -1 cold bin

_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def ensure_built(force: bool = False) -> str:
    """Compile the shared library if missing/stale; returns its path."""
    stale = (
        not os.path.exists(_SO)
        or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
    )
    if force or stale:
        subprocess.run(
            ["make", "-C", _DIR, "libplussnative.so"],
            check=True,
            capture_output=True,
        )
    return _SO


def _load() -> ctypes.CDLL:
    global _lib, _build_error
    if _lib is not None:
        return _lib
    if _build_error is not None:
        raise RuntimeError(_build_error)
    try:
        lib = ctypes.CDLL(ensure_built())
    except (OSError, subprocess.CalledProcessError) as e:
        _build_error = f"native runtime unavailable: {e}"
        raise RuntimeError(_build_error) from e
    lib.pluss_run.restype = ctypes.c_int64
    lib.pluss_classify_reduce.restype = ctypes.c_int64
    _lib = lib
    return lib


def available() -> bool:
    try:
        _load()
        return True
    except RuntimeError:
        return False


def _i64(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, dtype=np.int64))


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def classify_reduce(
    packed, found, noshare_bins: np.ndarray, mask=None,
    share_cap: int = 64,
):
    """SIMD batched classify+histogram reduction for the sampled
    engine's CPU fast path (pluss_classify_reduce).

    `packed`/`found` are one classified chunk (the "raw" kernel form's
    outputs, already on the host); `noshare_bins` is the caller's
    per-ref (65,) int64 accumulator (64 pow2 bins + cold at [64]) that
    the C pass ADDS into; `mask` (optional bool array) marks valid
    elements. Share samples and sub-1 noshare samples come back as
    exact sorted (packed key, count) pairs for decode_pairs. Regrows
    the pair buffers internally on capacity overflow (the C side
    writes nothing on overflow, so a re-call cannot double-count).

    Returns (keys, counts, share_cap, regrows): the trimmed pair
    arrays, the (possibly grown) capacity to reuse for the next chunk,
    and how many regrow re-calls happened (for capacity_regrows).
    """
    lib = _load()
    packed = _i64(packed)
    found_u8 = np.ascontiguousarray(np.asarray(found, dtype=np.uint8))
    n = packed.shape[0]
    if found_u8.shape[0] != n:
        raise ValueError("packed/found length mismatch")
    assert noshare_bins.dtype == np.int64 and (
        noshare_bins.shape == (_NOSHARE_SLOTS,)
    )
    u8p = ctypes.POINTER(ctypes.c_uint8)
    mask_ptr = None
    if mask is not None:
        mask_u8 = np.ascontiguousarray(np.asarray(mask, dtype=np.uint8))
        if mask_u8.shape[0] != n:
            raise ValueError("packed/mask length mismatch")
        mask_ptr = mask_u8.ctypes.data_as(u8p)
    regrows = 0
    while True:
        keys = np.empty(share_cap, dtype=np.int64)
        counts = np.empty(share_cap, dtype=np.int64)
        sz = lib.pluss_classify_reduce(
            _ptr(packed), found_u8.ctypes.data_as(u8p), mask_ptr,
            ctypes.c_int64(n), _ptr(noshare_bins), _ptr(keys),
            _ptr(counts), ctypes.c_int64(share_cap),
        )
        if sz <= share_cap:
            return keys[:sz], counts[:sz], share_cap, regrows
        regrows += 1
        share_cap = max(share_cap * 4, int(sz))


def run_serial_native(
    program: Program, machine: MachineConfig, share_cap: int = 1 << 16
) -> OracleResult:
    """Native serial walk -> OracleResult, bit-exact vs oracle.run_serial."""
    return _run_native(program, machine, share_cap, parallel=False)


def run_parallel_native(
    program: Program, machine: MachineConfig, share_cap: int = 1 << 16
) -> OracleResult:
    """Native parallel walk: one OS thread per simulated thread (the
    reference `ri` variant's omp-over-tids execution model,
    ...ri.cpp:67), thread-local histograms merged at join. Bit-identical
    output to run_serial_native."""
    return _run_native(program, machine, share_cap, parallel=True)


def _run_native(
    program: Program, machine: MachineConfig, share_cap: int, parallel: bool
) -> OracleResult:
    lib = _load()
    n_nests = len(program.nests)
    tables = [
        nest_tables(program, k, machine.thread_num - 1)
        for k in range(n_nests)
    ]
    depths = _i64([t.depth for t in tables])
    trips = _i64(np.stack([t.trips for t in tables]))
    starts = _i64(np.stack([t.starts for t in tables]))
    steps = _i64(np.stack([t.steps for t in tables]))
    trip_cf = _i64(np.stack([t.trip_coeffs for t in tables]))
    start_cf = _i64(np.stack([t.start_coeffs for t in tables]))
    ref_off = _i64(np.cumsum([0] + [t.n_refs for t in tables]))
    levels = _i64(np.concatenate([t.ref_levels for t in tables]))
    coeffs = _i64(np.concatenate([t.ref_coeffs for t in tables]))
    consts = _i64(np.concatenate([t.ref_consts for t in tables]))
    arrays = _i64(np.concatenate([t.ref_arrays for t in tables]))
    slots = _i64(
        [
            0 if r.slot == "pre" else 1
            for nest in program.nests
            for r in nest.refs
        ]
    )
    thrs = _i64(np.concatenate([t.ref_share_thresholds for t in tables]))
    ratios = _i64(np.concatenate([t.ref_share_ratios for t in tables]))

    P = machine.thread_num
    while True:
        noshare_bins = np.zeros(P * _NOSHARE_SLOTS, dtype=np.int64)
        share_out = np.zeros(share_cap * 4, dtype=np.int64)
        share_count = np.zeros(1, dtype=np.int64)
        per_tid = np.zeros(P, dtype=np.int64)

        rc = lib.pluss_run(
            ctypes.c_int64(1 if parallel else 0),
            ctypes.c_int64(P),
            ctypes.c_int64(machine.chunk_size),
            ctypes.c_int64(machine.ds),
            ctypes.c_int64(machine.cls),
            ctypes.c_int64(n_nests),
            _ptr(depths), _ptr(trips), _ptr(starts), _ptr(steps),
            _ptr(trip_cf), _ptr(start_cf),
            _ptr(ref_off), _ptr(levels), _ptr(coeffs), _ptr(consts),
            _ptr(arrays), _ptr(slots), _ptr(thrs), _ptr(ratios),
            ctypes.c_int64(len(program.arrays)),
            _ptr(noshare_bins), _ptr(share_out), _ptr(share_count),
            ctypes.c_int64(share_cap), _ptr(per_tid),
        )
        if rc == 2:
            raise RuntimeError(
                "native parallel execution failed (thread spawn or "
                "worker exception)"
            )
        if rc == 0:
            break
        # capacity overflow: the ABI reports the exact required pair
        # count in share_count without corrupting anything, so regrow
        # once and re-walk (triangular nests at large N produce ~1e5+
        # distinct share (tid, ratio, value) triples — syrk-tri N=2048
        # needs ~4.6e5 — far past any useful fixed default)
        need = int(share_count[0])
        if need <= share_cap:  # defensive: rc!=0 must imply growth
            raise RuntimeError(
                f"native share capacity exceeded: need {need}, "
                f"have {share_cap}"
            )
        share_cap = need

    state = PRIState(P)
    bins = noshare_bins.reshape(P, _NOSHARE_SLOTS)
    for tid in range(P):
        h = state.noshare[tid]
        for e in np.nonzero(bins[tid, :N_NOSHARE_BINS])[0]:
            h[1 << int(e)] = float(bins[tid, e])
        if bins[tid, N_NOSHARE_BINS]:
            h[-1] = float(bins[tid, N_NOSHARE_BINS])
    for i in range(int(share_count[0])):
        tid, ratio, value, cnt = share_out[i * 4 : i * 4 + 4]
        state.update_share(int(tid), int(ratio), int(value), float(cnt))
    return OracleResult(
        state=state,
        total_accesses=int(per_tid.sum()),
        per_tid_accesses=[int(x) for x in per_tid],
    )
