// Native sampler runtime (serial + thread-parallel).
//
// C++ twin of the reference's generated samplers + runtime-v1
// histogram layer (c_lib/test/sampler/gemm-t4-pluss-pro-model-ri-omp-seq.cpp,
// c_lib/test/runtime/pluss_utils.h), generalized over the loop-nest IR
// (pluss_sampler_optimization_tpu/ir.py) instead of generated per
// benchmark. It plays three roles:
//
// 1. fast oracle: bit-exact against the Python serial oracle
//    (oracle/serial.py) at any size, hundreds of times faster — large-N
//    parity tests for the TPU engines anchor on it;
// 2. speed baseline: its single-core walk is the reference protocol's
//    "serial C++ sampler" (BASELINE.md) that bench.py compares the TPU
//    engines against;
// 3. parallel native engine: pluss_run(parallel=1) runs one std::thread
//    per *simulated* thread — the execution model of the reference's
//    `ri` variant (#pragma omp parallel for over tids, ...ri.cpp:67)
//    done with the thread-local-histogram + merge-at-join reduction
//    that is the reference's only genuinely race-free design
//    (src/unsafe_utils.rs:32-35,105-151). Every piece of sampler state
//    is tid-owned, so the output is bit-identical to the serial walk.
//
// The walk mirrors the reference exactly: per simulated thread, chunks
// in static dispatch order (pluss_utils.h:410-425), the body reference
// sequence in program order, a per-(thread, array) last-access-time
// hash map (LAT_*, ...ri-omp-seq.cpp:47-49), reuse = count[tid] - LAT
// (:110), share classification |reuse-0| vs |reuse-thr| (:203-207),
// noshare pow2-binned on insertion (pluss_utils.h:924-927, share kept
// raw :928-937), and the per-nest -1 flush + LAT clear (:303-319).
//
// Exposed as a flat-array C ABI consumed via ctypes (native/__init__.py).

#include <algorithm>
#include <array>
#include <atomic>
#include <cstdint>
#include <map>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kMaxDepth = 3;
constexpr int kNoShareBins = 64;  // pow2 exponent bins
constexpr int kColdBin = kNoShareBins;  // the -1 flush bin
constexpr int kNoShareSlots = kNoShareBins + 1;

struct Ref {
  int64_t level;
  std::array<int64_t, kMaxDepth> coeffs;
  int64_t cnst;
  int64_t array;
  int64_t slot;  // 0 = pre, 1 = post
  int64_t share_threshold;  // -1 = thread-private
  int64_t share_ratio;
};

struct Nest {
  int64_t depth;
  std::array<int64_t, kMaxDepth> trips, starts, steps;
  // triangular bounds: affine-in-parallel-value coefficients, 0 when
  // rectangular (ir.py::Loop.trip_at / start_at)
  std::array<int64_t, kMaxDepth> trip_coeffs, start_coeffs;
  // refs grouped per (level, slot), program order preserved
  std::array<std::vector<Ref>, kMaxDepth> pre, post;
};

struct State {
  int64_t thread_num, chunk_size, ds, cls, n_arrays;
  std::vector<int64_t> count;  // per-tid access clock (runs across nests)
  // LAT[tid * n_arrays + array]: line -> last access position
  std::vector<std::unordered_map<int64_t, int64_t>> lat;
  // noshare_bins[tid * kNoShareSlots + bin]
  int64_t* noshare_bins;
  // per-tid share[(ratio, raw reuse)] -> count. Keeping the maps
  // tid-local makes the parallel walk race-free by construction (the
  // TLS + merge-at-join reduction); the serial walk uses the same
  // layout so both paths emit identically ordered output.
  std::vector<std::map<std::array<int64_t, 2>, int64_t>> share;
};

inline int pow2_bin(int64_t reuse) {
  // _polybench_to_highest_power_of_two (pluss_utils.h:665-679): the bin
  // key is 1 << (63 - clz(reuse)); we store the exponent.
  return 63 - __builtin_clzll(static_cast<uint64_t>(reuse));
}

// `clock` is the thread's access counter, kept in a walk-local instead
// of s.count[tid]: the per-tid counters share cache lines, and the
// clock increments on EVERY simulated access — through the vector it
// would ping-pong between cores and erase the parallel walk's scaling.
inline void access(State& s, int64_t tid, const Ref& r,
                   const int64_t* ivs, int64_t& clock) {
  int64_t flat = r.cnst;
  for (int64_t l = 0; l <= r.level; ++l) flat += r.coeffs[l] * ivs[l];
  const int64_t addr = flat * s.ds / s.cls;
  auto& table = s.lat[tid * s.n_arrays + r.array];
  auto it = table.find(addr);
  if (it != table.end()) {
    const int64_t reuse = clock - it->second;
    bool is_share = false;
    if (r.share_threshold >= 0) {
      // distance_to(reuse, 0) > distance_to(reuse, threshold)
      const int64_t d0 = reuse < 0 ? -reuse : reuse;
      const int64_t dt = reuse - r.share_threshold < 0
                             ? r.share_threshold - reuse
                             : reuse - r.share_threshold;
      is_share = d0 > dt;
    }
    if (is_share) {
      s.share[tid][{r.share_ratio, reuse}] += 1;
    } else {
      s.noshare_bins[tid * kNoShareSlots + pow2_bin(reuse)] += 1;
    }
    it->second = clock;
  } else {
    table.emplace(addr, clock);
  }
  clock += 1;
}

void body(State& s, const Nest& nest, int64_t tid, int64_t level,
          int64_t* ivs, int64_t& clock) {
  for (const Ref& r : nest.pre[level]) access(s, tid, r, ivs, clock);
  if (level + 1 < nest.depth) {
    // triangular levels: bounds affine in the parallel value ivs[0]
    const int64_t trip =
        std::max<int64_t>(0, nest.trips[level + 1] +
                                 nest.trip_coeffs[level + 1] * ivs[0]);
    const int64_t start =
        nest.starts[level + 1] + nest.start_coeffs[level + 1] * ivs[0];
    const int64_t step = nest.steps[level + 1];
    for (int64_t n = 0; n < trip; ++n) {
      ivs[level + 1] = start + n * step;
      body(s, nest, tid, level + 1, ivs, clock);
    }
  }
  for (const Ref& r : nest.post[level]) access(s, tid, r, ivs, clock);
}

// One simulated thread's full chunk walk over one nest
// (getNextStaticChunk order, pluss_utils.h:410-425). Touches only
// tid-owned state, so it is safe to run tids concurrently.
void walk_tid(State& s, const Nest& nest, int64_t tid) {
  const int64_t trip0 = nest.trips[0];
  const int64_t n_chunks = (trip0 + s.chunk_size - 1) / s.chunk_size;
  int64_t clock = s.count[tid];  // clocks run across nests
  for (int64_t cid = tid; cid < n_chunks; cid += s.thread_num) {
    const int64_t lo = cid * s.chunk_size;
    const int64_t hi = std::min(lo + s.chunk_size, trip0);
    for (int64_t n = lo; n < hi; ++n) {
      int64_t ivs[kMaxDepth];
      ivs[0] = nest.starts[0] + n * nest.steps[0];
      body(s, nest, tid, 0, ivs, clock);
    }
  }
  s.count[tid] = clock;
}

int64_t run_impl(
    bool parallel,
    int64_t thread_num, int64_t chunk_size, int64_t ds, int64_t cls,
    int64_t n_nests, const int64_t* depths, const int64_t* trips,
    const int64_t* starts, const int64_t* steps,
    const int64_t* trip_coeffs, const int64_t* start_coeffs,
    const int64_t* nest_ref_off, const int64_t* ref_levels,
    const int64_t* ref_coeffs, const int64_t* ref_consts,
    const int64_t* ref_arrays, const int64_t* ref_slots,
    const int64_t* ref_share_thresholds, const int64_t* ref_share_ratios,
    int64_t n_arrays, int64_t* noshare_bins, int64_t* share_out,
    int64_t* share_count_out, int64_t share_cap,
    int64_t* per_tid_accesses) {
  State s;
  s.thread_num = thread_num;
  s.chunk_size = chunk_size;
  s.ds = ds;
  s.cls = cls;
  s.n_arrays = n_arrays;
  s.count.assign(thread_num, 0);
  s.lat.resize(thread_num * n_arrays);
  s.share.resize(thread_num);
  s.noshare_bins = noshare_bins;
  for (int64_t i = 0; i < thread_num * kNoShareSlots; ++i)
    noshare_bins[i] = 0;

  std::vector<Nest> nests(n_nests);
  for (int64_t k = 0; k < n_nests; ++k) {
    Nest& nest = nests[k];
    nest.depth = depths[k];
    for (int l = 0; l < kMaxDepth; ++l) {
      nest.trips[l] = trips[k * kMaxDepth + l];
      nest.starts[l] = starts[k * kMaxDepth + l];
      nest.steps[l] = steps[k * kMaxDepth + l];
      nest.trip_coeffs[l] = trip_coeffs[k * kMaxDepth + l];
      nest.start_coeffs[l] = start_coeffs[k * kMaxDepth + l];
    }
    for (int64_t i = nest_ref_off[k]; i < nest_ref_off[k + 1]; ++i) {
      Ref r;
      r.level = ref_levels[i];
      for (int l = 0; l < kMaxDepth; ++l)
        r.coeffs[l] = ref_coeffs[i * kMaxDepth + l];
      r.cnst = ref_consts[i];
      r.array = ref_arrays[i];
      r.slot = ref_slots[i];
      r.share_threshold = ref_share_thresholds[i];
      r.share_ratio = ref_share_ratios[i];
      (r.slot == 0 ? nest.pre : nest.post)[r.level].push_back(r);
    }
  }

  for (const Nest& nest : nests) {
    if (parallel) {
      // one OS thread per simulated thread, barrier per nest (the
      // implicit barrier of the reference's per-nest omp region).
      // Exceptions must not cross the extern "C" boundary or escape a
      // worker (either aborts the host interpreter): contain them and
      // surface rc 2.
      std::atomic<int> err{0};
      std::vector<std::thread> workers;
      workers.reserve(thread_num);
      try {
        for (int64_t tid = 0; tid < thread_num; ++tid)
          workers.emplace_back([&s, &nest, &err, tid] {
            try {
              walk_tid(s, nest, tid);
            } catch (...) {
              err.store(1);
            }
          });
      } catch (...) {  // thread spawn failed (resource exhaustion)
        err.store(1);
      }
      for (auto& w : workers)
        if (w.joinable()) w.join();
      if (err.load() != 0) return 2;
    } else {
      for (int64_t tid = 0; tid < thread_num; ++tid)
        walk_tid(s, nest, tid);
    }
    // per-nest -1 flush + LAT clear (...ri-omp-seq.cpp:303-319)
    for (int64_t tid = 0; tid < thread_num; ++tid) {
      for (int64_t a = 0; a < n_arrays; ++a) {
        auto& table = s.lat[tid * n_arrays + a];
        if (!table.empty()) {
          s.noshare_bins[tid * kNoShareSlots + kColdBin] +=
              static_cast<int64_t>(table.size());
          table.clear();
        }
      }
    }
  }

  int64_t total = 0;
  for (int64_t t = 0; t < thread_num; ++t)
    total += static_cast<int64_t>(s.share[t].size());
  *share_count_out = total;
  int64_t written = 0;
  // tid-major emit over per-tid sorted maps == the old global
  // {tid, ratio, reuse}-sorted map order
  for (int64_t t = 0; t < thread_num && written < share_cap; ++t) {
    for (const auto& kv : s.share[t]) {
      if (written >= share_cap) break;
      share_out[written * 4 + 0] = t;
      share_out[written * 4 + 1] = kv.first[0];
      share_out[written * 4 + 2] = kv.first[1];
      share_out[written * 4 + 3] = kv.second;
      ++written;
    }
  }
  for (int64_t t = 0; t < thread_num; ++t) per_tid_accesses[t] = s.count[t];
  return total > share_cap ? 1 : 0;
}

}  // namespace

extern "C" {

// parallel != 0 runs one std::thread per simulated thread (the
// reference `ri` variant's execution model) with bit-identical output
// to the serial walk. Returns 0 on success, 1 when share quadruples
// exceed share_cap (the required count is still written to
// share_count_out), 2 when parallel execution failed (thread spawn or
// a worker exception).
int64_t pluss_run(
    int64_t parallel,
    int64_t thread_num, int64_t chunk_size, int64_t ds, int64_t cls,
    int64_t n_nests, const int64_t* depths, const int64_t* trips,
    const int64_t* starts, const int64_t* steps,
    const int64_t* trip_coeffs, const int64_t* start_coeffs,
    const int64_t* nest_ref_off, const int64_t* ref_levels,
    const int64_t* ref_coeffs, const int64_t* ref_consts,
    const int64_t* ref_arrays, const int64_t* ref_slots,
    const int64_t* ref_share_thresholds, const int64_t* ref_share_ratios,
    int64_t n_arrays,
    int64_t* noshare_bins,  // (thread_num * kNoShareSlots), zeroed here
    int64_t* share_out,     // (share_cap * 4): tid, ratio, value, count
    int64_t* share_count_out, int64_t share_cap,
    int64_t* per_tid_accesses) {
  return run_impl(
      parallel != 0, thread_num, chunk_size, ds, cls, n_nests, depths,
      trips, starts, steps, trip_coeffs, start_coeffs, nest_ref_off,
      ref_levels, ref_coeffs, ref_consts, ref_arrays, ref_slots,
      ref_share_thresholds, ref_share_ratios, n_arrays, noshare_bins,
      share_out, share_count_out, share_cap, per_tid_accesses);
}

// Batched classify+histogram reduction: the sampled engine's CPU fast
// path (SamplerConfig.kernel_backend = "native"/auto). The classify
// stays in XLA (sampled.py's "raw" kernel form emits packed keys +
// found mask); this single -O3/-march=native pass replaces the
// sort-based unique reduction, which dominates the chunk wall on a
// host core. Semantics mirror sampled.py::decode_pairs +
// fold_results exactly:
//
//   packed = reuse * 16 + slot  (slot 15 = noshare; arithmetic
//   right-shift / low-mask reproduce Python's floored divmod for
//   negative keys)
//
// - noshare with reuse >= 1: pow2 bin 63 - clz(reuse) in
//   noshare_bins[0..63] (fold_results re-bins 2^e to 2^e, so the
//   folded state is bit-identical to the raw-key stream);
// - cold (!found): noshare_bins[64];
// - everything else (share slots, and noshare with reuse < 1, which
//   hist_update keeps raw): an exact residual (key, count) map.
//
// mask may be null (every element valid). Returns the residual pair
// count; when it exceeds share_cap NOTHING is written (no partial
// accumulation — a regrown re-call must not double-count) and the
// caller re-calls with bigger buffers. On success noshare_bins is
// ACCUMULATED into (callers keep one per-ref array across chunks)
// and the pairs are written key-sorted.
int64_t pluss_classify_reduce(
    const int64_t* packed, const uint8_t* found, const uint8_t* mask,
    int64_t n,
    int64_t* noshare_bins,  // (65,): 64 pow2 bins + cold at [64]
    int64_t* share_keys, int64_t* share_counts, int64_t share_cap) {
  std::array<int64_t, kNoShareSlots> local{};
  std::unordered_map<int64_t, int64_t> residual;
  for (int64_t i = 0; i < n; ++i) {
    if (mask != nullptr && mask[i] == 0) continue;
    if (found[i] == 0) {
      ++local[kColdBin];
      continue;
    }
    const int64_t p = packed[i];
    const int64_t reuse = p >> 4;
    const int64_t slot = p & 15;
    if (slot == 15 && reuse >= 1) {
      ++local[63 - __builtin_clzll(static_cast<uint64_t>(reuse))];
    } else {
      ++residual[p];
    }
  }
  const int64_t sz = static_cast<int64_t>(residual.size());
  if (sz > share_cap) return sz;
  for (int k = 0; k < kNoShareSlots; ++k) noshare_bins[k] += local[k];
  std::vector<std::pair<int64_t, int64_t>> pairs(residual.begin(),
                                                 residual.end());
  std::sort(pairs.begin(), pairs.end());
  int64_t w = 0;
  for (const auto& kv : pairs) {
    share_keys[w] = kv.first;
    share_counts[w] = kv.second;
    ++w;
  }
  return sz;
}

}  // extern "C"
