// Native serial sampler runtime.
//
// C++ twin of the reference's serial generated sampler + runtime-v1
// histogram layer (c_lib/test/sampler/gemm-t4-pluss-pro-model-ri-omp-seq.cpp,
// c_lib/test/runtime/pluss_utils.h), generalized over the loop-nest IR
// (pluss_sampler_optimization_tpu/ir.py) instead of generated per
// benchmark. It plays two roles:
//
// 1. fast oracle: bit-exact against the Python serial oracle
//    (oracle/serial.py) at any size, hundreds of times faster — large-N
//    parity tests for the TPU engines anchor on it;
// 2. speed baseline: its single-core walk is the reference protocol's
//    "serial C++ sampler" (BASELINE.md) that bench.py compares the TPU
//    engines against.
//
// The walk mirrors the reference exactly: per simulated thread, chunks
// in static dispatch order (pluss_utils.h:410-425), the body reference
// sequence in program order, a per-(thread, array) last-access-time
// hash map (LAT_*, ...ri-omp-seq.cpp:47-49), reuse = count[tid] - LAT
// (:110), share classification |reuse-0| vs |reuse-thr| (:203-207),
// noshare pow2-binned on insertion (pluss_utils.h:924-927, share kept
// raw :928-937), and the per-nest -1 flush + LAT clear (:303-319).
//
// Exposed as a flat-array C ABI consumed via ctypes (native/__init__.py).

#include <algorithm>
#include <array>
#include <cstdint>
#include <map>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kMaxDepth = 3;
constexpr int kNoShareBins = 64;  // pow2 exponent bins
constexpr int kColdBin = kNoShareBins;  // the -1 flush bin
constexpr int kNoShareSlots = kNoShareBins + 1;

struct Ref {
  int64_t level;
  std::array<int64_t, kMaxDepth> coeffs;
  int64_t cnst;
  int64_t array;
  int64_t slot;  // 0 = pre, 1 = post
  int64_t share_threshold;  // -1 = thread-private
  int64_t share_ratio;
};

struct Nest {
  int64_t depth;
  std::array<int64_t, kMaxDepth> trips, starts, steps;
  // triangular bounds: affine-in-parallel-value coefficients, 0 when
  // rectangular (ir.py::Loop.trip_at / start_at)
  std::array<int64_t, kMaxDepth> trip_coeffs, start_coeffs;
  // refs grouped per (level, slot), program order preserved
  std::array<std::vector<Ref>, kMaxDepth> pre, post;
};

struct State {
  int64_t thread_num, chunk_size, ds, cls, n_arrays;
  std::vector<int64_t> count;  // per-tid access clock (runs across nests)
  // LAT[tid * n_arrays + array]: line -> last access position
  std::vector<std::unordered_map<int64_t, int64_t>> lat;
  // noshare_bins[tid * kNoShareSlots + bin]
  int64_t* noshare_bins;
  // share[(tid, ratio, raw reuse)] -> count
  std::map<std::array<int64_t, 3>, int64_t> share;
};

inline int pow2_bin(int64_t reuse) {
  // _polybench_to_highest_power_of_two (pluss_utils.h:665-679): the bin
  // key is 1 << (63 - clz(reuse)); we store the exponent.
  return 63 - __builtin_clzll(static_cast<uint64_t>(reuse));
}

inline void access(State& s, int64_t tid, const Ref& r,
                   const int64_t* ivs) {
  int64_t flat = r.cnst;
  for (int64_t l = 0; l <= r.level; ++l) flat += r.coeffs[l] * ivs[l];
  const int64_t addr = flat * s.ds / s.cls;
  auto& table = s.lat[tid * s.n_arrays + r.array];
  auto it = table.find(addr);
  if (it != table.end()) {
    const int64_t reuse = s.count[tid] - it->second;
    bool is_share = false;
    if (r.share_threshold >= 0) {
      // distance_to(reuse, 0) > distance_to(reuse, threshold)
      const int64_t d0 = reuse < 0 ? -reuse : reuse;
      const int64_t dt = reuse - r.share_threshold < 0
                             ? r.share_threshold - reuse
                             : reuse - r.share_threshold;
      is_share = d0 > dt;
    }
    if (is_share) {
      s.share[{tid, r.share_ratio, reuse}] += 1;
    } else {
      s.noshare_bins[tid * kNoShareSlots + pow2_bin(reuse)] += 1;
    }
    it->second = s.count[tid];
  } else {
    table.emplace(addr, s.count[tid]);
  }
  s.count[tid] += 1;
}

void body(State& s, const Nest& nest, int64_t tid, int64_t level,
          int64_t* ivs) {
  for (const Ref& r : nest.pre[level]) access(s, tid, r, ivs);
  if (level + 1 < nest.depth) {
    // triangular levels: bounds affine in the parallel value ivs[0]
    const int64_t trip =
        std::max<int64_t>(0, nest.trips[level + 1] +
                                 nest.trip_coeffs[level + 1] * ivs[0]);
    const int64_t start =
        nest.starts[level + 1] + nest.start_coeffs[level + 1] * ivs[0];
    const int64_t step = nest.steps[level + 1];
    for (int64_t n = 0; n < trip; ++n) {
      ivs[level + 1] = start + n * step;
      body(s, nest, tid, level + 1, ivs);
    }
  }
  for (const Ref& r : nest.post[level]) access(s, tid, r, ivs);
}

}  // namespace

extern "C" {

// Returns 0 on success, 1 when share quadruples exceed share_cap (the
// required count is still written to share_count_out).
int64_t pluss_run_serial(
    int64_t thread_num, int64_t chunk_size, int64_t ds, int64_t cls,
    int64_t n_nests, const int64_t* depths, const int64_t* trips,
    const int64_t* starts, const int64_t* steps,
    const int64_t* trip_coeffs, const int64_t* start_coeffs,
    const int64_t* nest_ref_off, const int64_t* ref_levels,
    const int64_t* ref_coeffs, const int64_t* ref_consts,
    const int64_t* ref_arrays, const int64_t* ref_slots,
    const int64_t* ref_share_thresholds, const int64_t* ref_share_ratios,
    int64_t n_arrays,
    int64_t* noshare_bins,  // (thread_num * kNoShareSlots), zeroed here
    int64_t* share_out,     // (share_cap * 4): tid, ratio, value, count
    int64_t* share_count_out, int64_t share_cap,
    int64_t* per_tid_accesses) {
  State s;
  s.thread_num = thread_num;
  s.chunk_size = chunk_size;
  s.ds = ds;
  s.cls = cls;
  s.n_arrays = n_arrays;
  s.count.assign(thread_num, 0);
  s.lat.resize(thread_num * n_arrays);
  s.noshare_bins = noshare_bins;
  for (int64_t i = 0; i < thread_num * kNoShareSlots; ++i)
    noshare_bins[i] = 0;

  std::vector<Nest> nests(n_nests);
  for (int64_t k = 0; k < n_nests; ++k) {
    Nest& nest = nests[k];
    nest.depth = depths[k];
    for (int l = 0; l < kMaxDepth; ++l) {
      nest.trips[l] = trips[k * kMaxDepth + l];
      nest.starts[l] = starts[k * kMaxDepth + l];
      nest.steps[l] = steps[k * kMaxDepth + l];
      nest.trip_coeffs[l] = trip_coeffs[k * kMaxDepth + l];
      nest.start_coeffs[l] = start_coeffs[k * kMaxDepth + l];
    }
    for (int64_t i = nest_ref_off[k]; i < nest_ref_off[k + 1]; ++i) {
      Ref r;
      r.level = ref_levels[i];
      for (int l = 0; l < kMaxDepth; ++l)
        r.coeffs[l] = ref_coeffs[i * kMaxDepth + l];
      r.cnst = ref_consts[i];
      r.array = ref_arrays[i];
      r.slot = ref_slots[i];
      r.share_threshold = ref_share_thresholds[i];
      r.share_ratio = ref_share_ratios[i];
      (r.slot == 0 ? nest.pre : nest.post)[r.level].push_back(r);
    }
  }

  for (const Nest& nest : nests) {
    const int64_t trip0 = nest.trips[0];
    const int64_t n_chunks = (trip0 + chunk_size - 1) / chunk_size;
    for (int64_t tid = 0; tid < thread_num; ++tid) {
      // chunks of this thread in static dispatch order
      // (getNextStaticChunk, pluss_utils.h:410-425)
      for (int64_t cid = tid; cid < n_chunks; cid += thread_num) {
        const int64_t lo = cid * chunk_size;
        const int64_t hi = std::min(lo + chunk_size, trip0);
        for (int64_t n = lo; n < hi; ++n) {
          int64_t ivs[kMaxDepth];
          ivs[0] = nest.starts[0] + n * nest.steps[0];
          body(s, nest, tid, 0, ivs);
        }
      }
    }
    // per-nest -1 flush + LAT clear (...ri-omp-seq.cpp:303-319)
    for (int64_t tid = 0; tid < thread_num; ++tid) {
      for (int64_t a = 0; a < n_arrays; ++a) {
        auto& table = s.lat[tid * n_arrays + a];
        if (!table.empty()) {
          s.noshare_bins[tid * kNoShareSlots + kColdBin] +=
              static_cast<int64_t>(table.size());
          table.clear();
        }
      }
    }
  }

  *share_count_out = static_cast<int64_t>(s.share.size());
  int64_t written = 0;
  for (const auto& kv : s.share) {
    if (written >= share_cap) break;
    share_out[written * 4 + 0] = kv.first[0];
    share_out[written * 4 + 1] = kv.first[1];
    share_out[written * 4 + 2] = kv.first[2];
    share_out[written * 4 + 3] = kv.second;
    ++written;
  }
  for (int64_t t = 0; t < thread_num; ++t) per_tid_accesses[t] = s.count[t];
  return static_cast<int64_t>(s.share.size()) > share_cap ? 1 : 0;
}

}  // extern "C"
