from .histogram import exp_bin, fixed_k_unique, N_EXP_BINS

__all__ = ["exp_bin", "fixed_k_unique", "N_EXP_BINS"]
