"""Device-side histogram primitives.

The reference accumulates reuse intervals into hash maps
(`Histogram = unordered_map<long,double>`, pluss_utils.h:25) guarded by
mutexes or thread-locals (src/unsafe_utils.rs:32-35). Hash maps don't
vectorize; on TPU the same information is:

- noshare intervals: a dense vector of 64 power-of-two bins — the
  noshare update pow2-bins on insertion anyway (pluss_utils.h:924-927),
  so exponent scatter-adds lose nothing;
- share intervals: raw values are required downstream (the racetrack
  model uses raw interval lengths, pluss_utils.h:1060-1097), but the
  affine loop nests produce only a handful of distinct values, so a
  fixed-capacity exact unique reduction returns (value, count) pairs
  plus an overflow count the host reacts to — scatter-max hash rounds
  on the common path, a full sorted reduction as the in-graph
  fallback;
- cold (-1) counts: per-array scalars.

All outputs are dense, fixed-shape, and psum-able across a device mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

N_EXP_BINS = 64


def exp_bin(x):
    """floor(log2(x)) for positive int64 x, via count-leading-zeros."""
    return 63 - jax.lax.clz(x.astype(jnp.int64))


def exp_hist(values, weights, n_bins: int = N_EXP_BINS):
    """Scatter-add weights into pow2 exponent bins. values must be > 0
    where weights are nonzero (masked entries: pass weight 0, value 1)."""
    e = exp_bin(jnp.maximum(values, 1))
    return jnp.zeros(n_bins, dtype=jnp.int64).at[e].add(weights.astype(jnp.int64))


def sorted_k_unique(values, valid, k: int, weights=None):
    """Exact sparse histogram with capacity k over masked int64 values,
    via one full sort + segmented reduction.

    `weights=None` counts occurrences; an int64 array sums weights per
    key instead (the merge form: folding (key, count) pair sets into
    one). Returns (keys[k], counts[k], n_unique). Invalid entries are
    pushed to the end via an int64 sentinel; entries beyond capacity
    are dropped (detect via n_unique > k on host).
    """
    sentinel = jnp.int64(2**62)
    v = jnp.where(valid, values, sentinel)
    if weights is None:
        v = jnp.sort(v)
        w = None
    else:
        order = jnp.argsort(v)
        v = v[order]
        w = weights[order]
    first = jnp.concatenate(
        [jnp.array([True]), v[1:] != v[:-1]]
    ) & (v != sentinel)
    seg = jnp.cumsum(first.astype(jnp.int64)) - 1
    n_unique = seg[-1] + 1 if v.shape[0] else jnp.int64(0)
    is_valid = v != sentinel
    seg_c = jnp.where(is_valid, seg, k)  # overflow/invalid -> dropped slot
    keys = (
        jnp.full(k + 1, -1, dtype=jnp.int64)
        .at[jnp.where(first, seg_c, k)]
        .set(v)[:k]
    )
    add = is_valid.astype(jnp.int64) if w is None else jnp.where(
        is_valid, w, 0
    )
    counts = (
        jnp.zeros(k + 1, dtype=jnp.int64)
        .at[seg_c]
        .add(add)[:k]
    )
    return keys, counts, n_unique


def merge_pair_sets(ck, cc, k2, c2, capacity: int):
    """Fold two fixed-capacity (key, count) pair sets into one: the
    scan-fused kernels' between-chunk merge (weighted unique over the
    concatenated pairs; empty slots are identified by count 0, so the
    validity mask is `counts > 0`). Single source of truth for the
    single-device and mesh-sharded scan kernels — their bit-identity
    contract depends on this merge being the same code. Returns
    (keys[capacity], counts[capacity], n_unique)."""
    counts = jnp.concatenate([cc, c2])
    return fixed_k_unique(
        jnp.concatenate([ck, k2]),
        counts > 0,
        capacity,
        weights=counts,
    )


def _round_hash(values, salt: int, h_slots: int):
    """SplitMix64-style avalanche of (values ^ salt), masked to a slot.

    Full bit mixing per round (xor-shift + odd multiplies) makes the
    per-round hashes effectively independent — an affine reseed would
    preserve pairwise differences and leave some colliding pairs
    colliding in every round at every table size.
    """
    salt &= (1 << 64) - 1
    if salt >= 1 << 63:  # to signed two's complement
        salt -= 1 << 64
    x = values ^ jnp.int64(salt)
    x = (x ^ ((x >> 30) & 0x3FFFFFFFF)) * jnp.int64(-0x40A7B892E31B1A47)
    x = (x ^ ((x >> 27) & 0x1FFFFFFFFF)) * jnp.int64(-0x6B2FB644ECCEEE15)
    x = x ^ ((x >> 31) & 0x1FFFFFFFF)
    return x & (h_slots - 1)


def fixed_k_unique(
    values, valid, k: int, rounds: int | None = None, weights=None
):
    """Exact sparse histogram with capacity k over masked int64 values.

    Sort-free on the common path: a few rounds of scatter-max
    hash-table claiming, each O(n) elementwise work instead of the
    O(n log n) full sort the affine samplers' handful of distinct
    values never needed. Per round, every element hashes into an
    H-slot table, the maximum key claims each slot (ties are the same
    key), winners scatter-add their counts, and losers (distinct keys
    colliding in one slot) go to the next round with an independently
    mixed hash. If any element is still unresolved after the last
    round, a lax.cond falls back to the full sorted reduction — so the
    result (including the true n_unique) is always exact and callers
    need no collision awareness; the sort branch costs compile time
    but executes only on the rare collision pile-up.

    rounds=None resolves to 2 for k <= 64 and 3 above (measured on a
    host core, 2^17-value batches, 4*k-slot tables): each round costs
    ~1.1 ms, and the fallback probability after round 2 is ~0.2% for a
    FULL k=64 distinct load (C(2,2)-style birthday residue) — but ~40%
    for a full k=256 load, where the sort then runs 3-5x slower than
    just paying the third round. Small capacities take the fast path;
    large (typically regrown) capacities take the robust one.

    Use this on un-vmapped paths only: under jax.vmap the cond
    predicate is batched, lowering to a select that executes BOTH
    branches — the sort then runs every call and the hash rounds are
    pure overhead. The vmapped dense/stream engines call
    sorted_k_unique directly instead.

    Values must stay below the 2^62 invalid-entry sentinel of the
    sorted fallback (every packed reuse key does). `weights=None`
    counts occurrences; an int64 array sums weights per key instead
    (the merge form — folding (key, count) pair sets back into one,
    as the scan-fused kernels do per chunk; weights must be >= 0 and
    a valid entry's weight should be > 0 or its key may be reported
    with count 0). Returns (keys[k], counts[k], n_unique); empty
    output slots carry count 0 (the key field of an empty slot is -1,
    but only counts identify emptiness); entries beyond capacity are
    dropped (detect via n_unique > k on host).
    """
    if rounds is None:
        rounds = 2 if k <= 64 else 3
    if rounds < 1:  # degenerate: nothing can resolve, sort directly
        return sorted_k_unique(values, valid, k, weights=weights)
    h_slots = max(1024, 4 * k)
    h_slots = 1 << (h_slots - 1).bit_length()
    neg = jnp.iinfo(jnp.int64).min
    remaining = valid
    w_add = None if weights is None else weights.astype(jnp.int64)
    key_tabs, cnt_tabs = [], []
    for r in range(rounds):
        h = _round_hash(values, r * 0x9E3779B97F4A7C15 + r, h_slots)
        h_c = jnp.where(remaining, h, h_slots)  # masked -> dropped slot
        tab = (
            jnp.full(h_slots + 1, neg, dtype=jnp.int64).at[h_c].max(values)
        )
        won = remaining & (tab[h] == values)
        cnt = (
            jnp.zeros(h_slots + 1, dtype=jnp.int64)
            .at[jnp.where(won, h, h_slots)]
            .add(1 if w_add is None else w_add)
        )
        key_tabs.append(tab[:h_slots])
        cnt_tabs.append(cnt[:h_slots])
        remaining = remaining & ~won
    # each distinct key wins in exactly one (round, slot): the stacked
    # tables hold unique keys; compact the occupied slots to k outputs.
    # Occupancy is the primary sort key (no value sentinel, so any
    # int64 key — including -1 or >= 2^62 — compacts correctly); empty
    # output slots are identified by count 0, never by a key marker.
    allk = jnp.concatenate(key_tabs)
    allc = jnp.concatenate(cnt_tabs)
    occupied = allc > 0
    order = jnp.lexsort((allk, ~occupied))
    valid_out = jnp.arange(k) < occupied.sum()
    keys = jnp.where(valid_out, allk[order[:k]], jnp.int64(-1))
    counts = jnp.where(valid_out, allc[order[:k]], 0)
    n_unique = occupied.sum().astype(jnp.int64)
    return jax.lax.cond(
        jnp.any(remaining),
        lambda: sorted_k_unique(values, valid, k, weights=weights),
        lambda: (keys, counts, n_unique),
    )
