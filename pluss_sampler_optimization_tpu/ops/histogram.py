"""Device-side histogram primitives.

The reference accumulates reuse intervals into hash maps
(`Histogram = unordered_map<long,double>`, pluss_utils.h:25) guarded by
mutexes or thread-locals (src/unsafe_utils.rs:32-35). Hash maps don't
vectorize; on TPU the same information is:

- noshare intervals: a dense vector of 64 power-of-two bins — the
  noshare update pow2-bins on insertion anyway (pluss_utils.h:924-927),
  so exponent scatter-adds lose nothing;
- share intervals: raw values are required downstream (the racetrack
  model uses raw interval lengths, pluss_utils.h:1060-1097), but the
  affine loop nests produce only a handful of distinct values, so a
  fixed-capacity sorted-unique reduction returns exact (value, count)
  pairs plus an overflow flag the host asserts on;
- cold (-1) counts: per-array scalars.

All outputs are dense, fixed-shape, and psum-able across a device mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

N_EXP_BINS = 64


def exp_bin(x):
    """floor(log2(x)) for positive int64 x, via count-leading-zeros."""
    return 63 - jax.lax.clz(x.astype(jnp.int64))


def exp_hist(values, weights, n_bins: int = N_EXP_BINS):
    """Scatter-add weights into pow2 exponent bins. values must be > 0
    where weights are nonzero (masked entries: pass weight 0, value 1)."""
    e = exp_bin(jnp.maximum(values, 1))
    return jnp.zeros(n_bins, dtype=jnp.int64).at[e].add(weights.astype(jnp.int64))


def fixed_k_unique(values, valid, k: int):
    """Exact sparse histogram with capacity k over masked int64 values.

    Returns (keys[k], counts[k], n_unique). Invalid entries are pushed
    to the end via an int64 sentinel; entries beyond capacity are
    dropped (detect via n_unique > k on host).
    """
    sentinel = jnp.int64(2**62)
    v = jnp.where(valid, values, sentinel)
    v = jnp.sort(v)
    first = jnp.concatenate(
        [jnp.array([True]), v[1:] != v[:-1]]
    ) & (v != sentinel)
    seg = jnp.cumsum(first.astype(jnp.int64)) - 1
    n_unique = seg[-1] + 1 if v.shape[0] else jnp.int64(0)
    is_valid = v != sentinel
    seg_c = jnp.where(is_valid, seg, k)  # overflow/invalid -> dropped slot
    keys = (
        jnp.full(k + 1, -1, dtype=jnp.int64)
        .at[jnp.where(first, seg_c, k)]
        .set(v)[:k]
    )
    counts = (
        jnp.zeros(k + 1, dtype=jnp.int64)
        .at[seg_c]
        .add(is_valid.astype(jnp.int64))[:k]
    )
    return keys, counts, n_unique
