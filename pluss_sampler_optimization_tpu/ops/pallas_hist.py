"""Pallas TPU kernel for the pow2-binned reuse histogram.

The histogram update is the engines' innermost reduction (the
reference's `_pluss_histogram_update` hash insert per access,
pluss_utils.h:680-689; here `exp_hist`'s scatter-add,
ops/histogram.py). Scatter-adds serialize on the VPU; this kernel
avoids them entirely with a comparison ladder:

    c_k   = sum over masked values of [x >= 2^k]          (monotone)
    hist[e] = c_e - c_{e+1}

64 broadcast compares + reductions per block are pure VPU work with no
data-dependent memory traffic. int64 values are split into uint32
hi/lo planes before the kernel (TPU vector units are 32-bit native),
so the full 63-bit reuse range survives.

`pow2_hist` dispatches to the kernel on TPU (interpret mode elsewhere
only under test); `exp_hist` in ops/histogram.py remains the portable
default. Equality with exp_hist is pinned by tests/test_pallas.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

N_BINS = 64
_LANES = 128
_BLOCK_ROWS = 8
# numpy (not jnp): a module-level jnp scalar would initialize the
# backend at import time, before _platform pinning can take effect
_I0 = np.int32(0)


def _hist_kernel(hi_ref, lo_ref, w_ref, out_ref):
    # One (64,128) output block shared by every grid step (Mosaic
    # requires output blocks tiled to (8,128); a (1,128) row per step
    # fails to lower). TPU grid steps run sequentially, so step 0
    # zeroes the block and each later step accumulates into it.
    #
    # Row k holds PER-LANE partial counts for threshold 2^k; the
    # cheap cross-lane sum happens outside the kernel. Reducing to a
    # scalar in-kernel is a trap under x64: Mosaic's scalar-reduction
    # proxy re-enters jnp.sum without a dtype (mosaic/lowering.py,
    # reduce_lowering_rule _proxy_fun), which promotes int32 to int64
    # and fails to lower. Sublane (axis 0) reductions avoid the proxy.
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    hi = hi_ref[:]
    lo = lo_ref[:]
    w = w_ref[:]
    rows = []
    for k in range(N_BINS):
        if k < 32:
            ge = (hi > 0) | (lo >= jnp.uint32(1 << k))
        else:
            ge = hi >= jnp.uint32(1 << (k - 32))
        # dtype pinned: under x64, jnp.sum(int32) promotes to int64,
        # which Mosaic cannot lower
        rows.append(jnp.sum(jnp.where(ge, w, jnp.int32(0)), axis=0,
                            keepdims=True, dtype=jnp.int32))
    out_ref[:] += jnp.concatenate(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _ladder_counts(values, w, interpret: bool = False):
    """(64,) int64 monotone threshold counts c_k = sum w[x >= 2^k].

    One pallas call; `w` must be int32 with per-lane totals below
    2^31 (the caller's job — pow2_hist's widened path splits weights
    into 16-bit planes and chunks the grid to guarantee it)."""
    values = values.ravel().astype(jnp.int64)
    w = w.ravel().astype(jnp.int32)
    n = values.shape[0]
    block = _BLOCK_ROWS * _LANES
    pad = (-n) % block
    if pad:
        values = jnp.concatenate([values, jnp.ones(pad, jnp.int64)])
        w = jnp.concatenate([w, jnp.zeros(pad, jnp.int32)])
    rows = (n + pad) // _LANES
    hi = (values >> 32).astype(jnp.uint32).reshape(rows, _LANES)
    lo = (values & 0xFFFFFFFF).astype(jnp.uint32).reshape(rows, _LANES)
    w2 = w.reshape(rows, _LANES)
    grid = rows // _BLOCK_ROWS

    partial = pl.pallas_call(
        _hist_kernel,
        out_shape=jax.ShapeDtypeStruct((N_BINS, _LANES), jnp.int32),
        grid=(grid,),
        in_specs=[
            # the 0 column index must be int32: under x64 a Python 0
            # traces as i64 and Mosaic refuses the (i32, i64) index-map
            # return
            pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, _I0)),
            pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, _I0)),
            pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, _I0)),
        ],
        out_specs=pl.BlockSpec((N_BINS, _LANES), lambda i: (_I0, _I0)),
        interpret=interpret,
    )(hi, lo, w2)

    return jnp.sum(partial, axis=1, dtype=jnp.int64)


# widened-path super-chunk: at most 2048 grid steps per pallas call,
# so a 16-bit weight plane's per-lane int32 partial stays below
# 2048 * 8 rows * 65535 < 2^31 regardless of input size
_WIDE_CHUNK = _BLOCK_ROWS * _LANES * 2048

# the per-call weight-total budget of the fast path's int32 partials
_FAST_LIMIT = 1 << 31


def pow2_hist(values, weights, interpret: bool = False,
              widen: bool | None = None):
    """(64,) int64 histogram of floor(log2(x)) weighted by `weights`.

    `values` int64 (> 0 where weights are nonzero); `weights` are
    added per entry like exp_hist (bool masks and int32-range
    counts). Equivalent to ops/histogram.py::exp_hist over that
    domain.

    The fast path accumulates per-lane partials in int32 across all
    grid steps of one call, which silently wraps once a call's weight
    total reaches 2^31. `widen=None` (auto) guards it: bool weights
    can't get there below 2^38 elements; concrete integer weights are
    summed and the widened path taken at the boundary; weights
    arriving as tracers (a caller's jit) widen unconditionally, since
    the total can't be inspected — pass widen=False only when the
    caller pins its own per-call totals. The widened path splits
    weights into 16-bit planes and super-chunks the grid
    (hist = c_lo + (c_hi << 16), each plane's partials provably below
    2^31), so it is exact for the full int32 weight range at any
    input size.
    """
    values = jnp.asarray(values).ravel()
    weights = jnp.asarray(weights).ravel()
    n = values.shape[0]
    if n == 0:
        return jnp.zeros(N_BINS, dtype=jnp.int64)
    if widen is None:
        if weights.dtype == jnp.bool_:
            # per-lane partial <= n/128 entries: safe below 2^38
            widen = n >= _FAST_LIMIT * _LANES
        elif not isinstance(weights, jax.core.Tracer):
            widen = int(jnp.sum(weights, dtype=jnp.int64)) >= _FAST_LIMIT
        else:
            widen = True
    if not widen:
        c = _ladder_counts(values, weights.astype(jnp.int32), interpret)
    else:
        w32 = weights.astype(jnp.int32)
        c = jnp.zeros(N_BINS, dtype=jnp.int64)
        for s0 in range(0, n, _WIDE_CHUNK):
            v = values[s0:s0 + _WIDE_CHUNK]
            w = w32[s0:s0 + _WIDE_CHUNK]
            c = c + _ladder_counts(v, w & 0xFFFF, interpret)
            c = c + (_ladder_counts(v, (w >> 16) & 0xFFFF,
                                    interpret) << 16)
    # hist[e] = c_e - c_{e+1}; c_63 counts x >= 2^63 (none: reuse < 2^63)
    return c - jnp.concatenate([c[1:], jnp.zeros(1, jnp.int64)])


def pow2_hist_auto(values, weights):
    """Kernel on TPU, portable exp_hist elsewhere."""
    from .histogram import exp_hist

    if jax.default_backend() == "tpu":
        return pow2_hist(values, weights)
    return exp_hist(values, weights)
