"""Pallas fused classify+histogram kernel for the sampled engine.

The sampled engine's hot loop — decode the drawn mixed-radix sample
keys, classify each sample's reuse (sampler/sampled.py::
classify_samples), and accumulate the pow2 RI histogram — runs on the
XLA path as a `lax.scan` whose per-step sorted unique reduction
round-trips the (key, count) pair set through HBM on every chunk
(`_build_ref_kernel_fused`). This kernel fuses the whole buffer into
ONE pallas_call per ref: the classify runs inside the kernel body, and
the noshare pow2 histogram accumulates on-chip across every grid step
with the comparison-ladder trick proven in pallas_hist.py::pow2_hist
(hist[e] = c_e - c_{e+1} over monotone threshold counts) — one HBM
histogram write per ref instead of one pair-set round trip per chunk.

Exactness contract (the reason no fallback path is needed):

- noshare samples with ri >= 1 are ladder-binned to {2^e: count}.
  fold_results feeds those through hist_update's pow2 binning, and
  pow2_floor(2^e) == 2^e, so the folded PRIState is bit-identical to
  the XLA path's raw-key stream (integer counts are exact in float64
  and dict accumulation is order-insensitive);
- share samples AND the rare noshare samples with ri < 1 (binning
  applies only to keys > 0, runtime/hist.py::hist_update) ride an
  exact residual (packed key, count) pair stream, reduced by the same
  sorted_k_unique the XLA kernels use and decoded host-side by the
  same decode_pairs;
- cold (never-reused) samples count into a separate scalar.

The residual stream reuses sorted_k_unique's 2^62 sentinel (a packed
key ri*16+slot never reaches it for any representable nest), so the
capacity-regrow contract is unchanged: n_unique > capacity makes the
host regrow and re-dispatch, exactly like the fused XLA kernel.

Selection: SamplerConfig.kernel_backend = "pallas" routes the fused
runner here (interpret mode on the CPU backend — the configuration
tier-1 pins; TPU lowering additionally needs Mosaic to take the
int64 classify body and is exercised only on real hardware).
"""

from __future__ import annotations

import collections
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .histogram import sorted_k_unique

N_BINS = 64
_LANES = 128
_BLOCK_ROWS = 64  # 8192 samples per grid step
# residual-stream sentinel == sorted_k_unique's invalid sentinel;
# NOT -1: packed = ri*16+15 with ri = -1 IS -1
_SENTINEL = 1 << 62
# numpy, not jnp: a module-level jnp scalar would initialize the
# backend at import time (same rule as pallas_hist._I0)
_I0 = np.int32(0)

# (signature digest, interpret) -> jitted kernel; same bounded-LRU
# discipline as sampled.py::_SIG_KERNELS (each closure pins a trace)
_HIST_KERNELS: "collections.OrderedDict" = collections.OrderedDict()
_HIST_KERNELS_MAX = 64


def _full_spec(shape):
    """BlockSpec covering a whole operand in every grid step."""
    ndim = len(shape)
    return pl.BlockSpec(shape, lambda i, _n=ndim: (_I0,) * _n)


def _one_ref(nt, ref_idx, keys_B, mask_B, highs, vals, rx, capacity,
             interpret):
    """(share_keys[cap], share_counts[cap], n_unique, cold, hist[64])
    for one ref's whole sample buffer. Traced inside the shared jit."""
    from ..sampler.sampled import classify_samples, decode_sample_keys

    block = _BLOCK_ROWS * _LANES
    B = keys_B.shape[0]
    pad = (-B) % block
    if pad:
        # decodable padding (repeats of key 0), masked out
        keys_B = jnp.concatenate(
            [keys_B, jnp.full(pad, keys_B[0], jnp.int64)]
        )
        mask_B = jnp.concatenate([mask_B, jnp.zeros(pad, bool)])
    n_blocks = (B + pad) // block
    kr = keys_B.reshape(n_blocks * _BLOCK_ROWS, _LANES)
    mr = mask_B.astype(jnp.int32).reshape(n_blocks * _BLOCK_ROWS, _LANES)

    leaves, treedef = jax.tree_util.tree_flatten(vals)
    leaves = [jnp.asarray(x) for x in leaves]
    shapes = [x.shape for x in leaves]
    flat = [jnp.atleast_1d(x) for x in leaves]
    n_leaves = len(flat)
    highs = jnp.asarray(highs)
    rx1 = jnp.asarray(rx, jnp.int64).reshape(1)

    def _math(keys, highs_v, rx_v, *leaves1d):
        """The classify, as a pure function of arrays. Traced to a
        jaxpr OUTSIDE the pallas body so the structural array
        constants the trace bakes in (ref tables, band plans, ...)
        are hoisted into explicit kernel inputs — a pallas body may
        not capture array constants (and jax.closure_convert hoists
        only closed-over tracers, not trace-time literals)."""
        svals = jax.tree_util.tree_unflatten(
            treedef,
            [leaves1d[j].reshape(shapes[j]) for j in range(n_leaves)],
        )
        snt = nt.with_vals(svals)
        samples = decode_sample_keys(keys, highs_v)
        return classify_samples(snt, ref_idx, samples, rx_v[0])

    cjaxpr = jax.make_jaxpr(_math)(
        jnp.zeros(block, jnp.int64),
        jnp.zeros(highs.shape, highs.dtype),
        jnp.zeros(rx1.shape, rx1.dtype),
        *[jnp.zeros(x.shape, x.dtype) for x in flat],
    )
    const_shapes = [jnp.shape(c) for c in cjaxpr.consts]
    consts = [jnp.atleast_1d(jnp.asarray(c)) for c in cjaxpr.consts]
    n_consts = len(consts)

    def body(keys_ref, mask_ref, highs_ref, rx_ref, *refs):
        leaf_refs = refs[:n_leaves]
        const_refs = refs[n_leaves:n_leaves + n_consts]
        share_ref, hist_ref, misc_ref = refs[n_leaves + n_consts:]

        @pl.when(pl.program_id(0) == 0)
        def _init():
            hist_ref[:] = jnp.zeros_like(hist_ref)
            misc_ref[:] = jnp.zeros_like(misc_ref)

        keys = keys_ref[:].reshape(-1)
        msk = mask_ref[:].reshape(-1) != 0
        packed, ri, is_share, found = jax.core.eval_jaxpr(
            cjaxpr.jaxpr,
            [const_refs[j][:].reshape(const_shapes[j])
             for j in range(n_consts)],
            keys, highs_ref[:], rx_ref[:],
            *[leaf_refs[j][:] for j in range(n_leaves)],
        )
        live = found & msk
        nosh = live & (~is_share) & (ri >= 1)
        # residual = share + sub-1 noshare: the exact pair stream
        share_ref[:] = jnp.where(
            live & ~nosh, packed, jnp.int64(_SENTINEL)
        ).reshape(_BLOCK_ROWS, _LANES)
        riw = jnp.where(nosh, ri, 0).reshape(_BLOCK_ROWS, _LANES)
        # dtype pinned: under x64, jnp.sum(int32) promotes to int64
        # (same rule as pallas_hist._hist_kernel)
        rows = [
            jnp.sum(jnp.where(riw >= (jnp.int64(1) << k),
                              jnp.int32(1), jnp.int32(0)),
                    axis=0, keepdims=True, dtype=jnp.int32)
            for k in range(N_BINS - 1)
        ]
        # bin 63 is always empty (reuse < 2^63; 1 << 63 would wrap)
        rows.append(jnp.zeros((1, _LANES), jnp.int32))
        hist_ref[:] += jnp.concatenate(rows, axis=0)
        cold = ((~found) & msk).reshape(_BLOCK_ROWS, _LANES)
        misc_ref[0:1, :] += jnp.sum(
            jnp.where(cold, jnp.int32(1), jnp.int32(0)),
            axis=0, keepdims=True, dtype=jnp.int32,
        )

    share_flat, hist_part, misc = pl.pallas_call(
        body,
        out_shape=(
            jax.ShapeDtypeStruct(
                (n_blocks * _BLOCK_ROWS, _LANES), jnp.int64
            ),
            jax.ShapeDtypeStruct((N_BINS, _LANES), jnp.int32),
            jax.ShapeDtypeStruct((8, _LANES), jnp.int32),
        ),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, _I0)),
            pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, _I0)),
            _full_spec(highs.shape),
            _full_spec(rx1.shape),
            *[_full_spec(x.shape) for x in flat],
            *[_full_spec(c.shape) for c in consts],
        ],
        out_specs=(
            pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, _I0)),
            pl.BlockSpec((N_BINS, _LANES), lambda i: (_I0, _I0)),
            pl.BlockSpec((8, _LANES), lambda i: (_I0, _I0)),
        ),
        interpret=interpret,
    )(kr, mr, highs, rx1, *flat, *consts)

    c = jnp.sum(hist_part, axis=1, dtype=jnp.int64)
    nosh_hist = c - jnp.concatenate([c[1:], jnp.zeros(1, jnp.int64)])
    cold = jnp.sum(misc[0].astype(jnp.int64))
    sflat = share_flat.reshape(-1)
    sk, sc, nu = sorted_k_unique(sflat, sflat != _SENTINEL, capacity)
    return sk, sc, nu, cold, nosh_hist


def _build_hist_kernel(nt, ref_idx: int, interpret: bool):
    from ..sampler.sampled import check_packed_ratios

    check_packed_ratios(nt)

    @functools.partial(
        jax.jit, static_argnames=("capacity", "n_chunks")
    )
    def kernel(keys_RB, mask_RB, highs, vals, rx_R, capacity: int,
               n_chunks: int):
        # n_chunks kept for call-signature compatibility with the
        # fused XLA kernel; this kernel tiles by its own block size
        del n_chunks
        R = keys_RB.shape[0]
        outs = [
            _one_ref(nt, ref_idx, keys_RB[r], mask_RB[r], highs, vals,
                     rx_R[r], capacity, interpret)
            for r in range(R)
        ]
        return tuple(
            jnp.stack([o[j] for o in outs]) for j in range(5)
        )

    return kernel


def hist_kernel_for(nt, ref_idx: int, digest: str, interpret: bool):
    """Per-signature cached fused classify+histogram kernel.

    Same call shape as `_build_ref_kernel_fused`'s kernel; returns
    (share_keys[R,cap], share_counts[R,cap], max_nu[R], cold[R],
    noshare_hist[R,64]) — the first four mirror the fused form so the
    fused runner's drain/regrow contract applies unchanged, the fifth
    carries the on-chip pow2 histogram."""
    from ..sampler.sampled import lru_cached

    return lru_cached(
        _HIST_KERNELS,
        (digest, bool(interpret)),
        lambda: _build_hist_kernel(nt, ref_idx, interpret),
        _HIST_KERNELS_MAX,
    )
