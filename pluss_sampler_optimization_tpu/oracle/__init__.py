from .serial import OracleResult, run_serial
from .numpy_ref import run_numpy

__all__ = ["OracleResult", "run_serial", "run_numpy"]
