"""Vectorized exact full-traversal sampler (host, numpy).

Computes the same per-thread reuse intervals as the serial oracle via
sorting instead of a hash-map walk: the last-access-time lookup
(LAT_X[tid][addr], ...ri-omp-seq.cpp:107-119) is equivalent to, per
(thread, array, line), taking consecutive differences of that line's
access positions — obtained by lexsorting the thread's access stream by
(array, line, position). Reuse never crosses a parallel nest: the
reference flushes surviving lines as -1 and clears the LAT tables after
every parallel loop (:303-319), so each (thread, nest) is an independent
sort problem (positions still carry the cross-nest clock offset, which
cancels in the differences). This is the CPU twin of the TPU dense
sampler (sampler/dense.py) and the oracle used at sizes where the dict
walk is too slow.
"""

from __future__ import annotations

import numpy as np

from ..config import MachineConfig
from ..core.trace import ProgramTrace
from ..ir import Program
from ..runtime.hist import PRIState
from .serial import OracleResult


def _pow2_floor_arr(x: np.ndarray) -> np.ndarray:
    """Elementwise highest power of two <= x (x > 0, x < 2^53)."""
    _, e = np.frexp(x.astype(np.float64))
    return (np.int64(1) << (e.astype(np.int64) - 1)).astype(np.int64)


def fold_nest_numpy(nt, tid: int, state: PRIState) -> int:
    """Exact fold of one (nest, thread) into `state` via the host
    lexsort; returns the thread's access count in this nest.

    The body of run_numpy, exposed standalone because it is also the
    fastest exact evaluator for SMALL nests: below a few million
    accesses the whole per-thread sort costs milliseconds, where any
    device-kernel route pays per-ref-structure dispatch/compile costs
    first (sampler/analytic.py routes its small-nest case here)."""
    t = nt.tables
    parts = [nt.enumerate_ref(tid, ri) for ri in range(t.n_refs)]
    pos = np.concatenate([p for p, _ in parts])
    if len(pos) == 0:
        return 0
    addr = np.concatenate([a for _, a in parts])
    arr = np.concatenate(
        [
            np.full(len(parts[ri][0]), t.ref_arrays[ri], dtype=np.int64)
            for ri in range(t.n_refs)
        ]
    )
    ref = np.concatenate(
        [
            np.full(len(parts[ri][0]), ri, dtype=np.int64)
            for ri in range(t.n_refs)
        ]
    )
    order = np.lexsort((pos, addr, arr))
    pos_s, addr_s, arr_s, ref_s = (
        pos[order], addr[order], arr[order], ref[order],
    )
    same = np.zeros(len(pos), dtype=bool)
    same[1:] = (arr_s[1:] == arr_s[:-1]) & (addr_s[1:] == addr_s[:-1])
    reuse = np.where(same, pos_s - np.concatenate(([0], pos_s[:-1])), 0)

    r = reuse[same]
    snk = ref_s[same]
    s_thr = t.ref_share_thresholds[snk]
    s_ratio = t.ref_share_ratios[snk]
    is_share = (s_thr > 0) & (np.abs(r) > np.abs(r - s_thr))

    # noshare: pow2-binned accumulate (pluss_utils.h:924-927)
    ns = r[~is_share]
    if len(ns):
        binned = _pow2_floor_arr(ns)
        keys, cnts = np.unique(binned, return_counts=True)
        h = state.noshare[tid]
        for key, c in zip(keys.tolist(), cnts.tolist()):
            h[key] = h.get(key, 0.0) + float(c)

    # share: raw keys per ratio (pluss_utils.h:928-937)
    sh = r[is_share]
    sh_ratio = s_ratio[is_share]
    if len(sh):
        for rat in np.unique(sh_ratio).tolist():
            vals = sh[sh_ratio == rat]
            keys, cnts = np.unique(vals, return_counts=True)
            h = state.share[tid].setdefault(int(rat), {})
            for key, c in zip(keys.tolist(), cnts.tolist()):
                h[int(key)] = h.get(int(key), 0.0) + float(c)

    # per-nest -1 flush: one per distinct (array, line)
    # (...ri-omp-seq.cpp:303-319)
    n_cold = int((~same).sum())
    if n_cold:
        h = state.noshare[tid]
        h[-1] = h.get(-1, 0.0) + float(n_cold)
    return len(pos)


def run_numpy(program: Program, machine: MachineConfig) -> OracleResult:
    trace = ProgramTrace(program, machine)
    P = machine.thread_num
    state = PRIState(P)
    per_tid = [0] * P

    for k, nt in enumerate(trace.nests):
        for tid in range(P):
            per_tid[tid] += fold_nest_numpy(nt, tid, state)

    return OracleResult(
        state=state, total_accesses=sum(per_tid), per_tid_accesses=per_tid
    )
