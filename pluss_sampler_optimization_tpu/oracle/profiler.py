"""Ground-truth profiler: execute the real kernel, record exact RIs.

Port of the reference's executing profiler (src/gemm_profiler.rs) — the
oracle that the *model* (sampler + CRI) is validated against:

- real data: PolyBench init formulas (gemm_profiler.rs:101-122,
  mirroring gemm.ppcg_omp.c:37-45) and the actual GEMM float kernel
  C = beta*C + alpha*A@B (gemm_profiler.rs:147-168);
- parallel decomposition: each thread owns one *contiguous* block of C
  rows (`par_chunks_mut(rows/threads)`, gemm_profiler.rs:185) — note
  this differs from the samplers' round-robin CHUNK_SIZE schedule;
- exact reuse intervals: every access is clocked on its thread's
  private counter (gemm_profiler.rs:146,186-205); RI = clock delta to
  the previous touch of the same (array, cache line) on that thread
  (:62-77); first touches record RI = -1 (:70);
- output: one raw-keyed histogram per thread (pri_array, :30-36).

Two deviations from the reference, both documented here on purpose:
the reference indexes C and A with *chunk-local* row numbers in the
parallel kernel (c0 in 0..chunk_len, gemm_profiler.rs:188-197), making
different threads' addresses alias the same small row range; we use
global row indices (the addresses the real kernel touches). And the
reference tags samples with rayon's *execution* thread index (:191),
which depends on pool scheduling; we use the chunk owner, which is what
its per-thread chunk decomposition means.

The RI accounting is vectorized numpy (lexsort + segmented diff — the
same reduction the dense TPU engine uses), so the profiler scales to
N=1024+ where the reference's per-access hash walk is minutes.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..config import MachineConfig
from ..ir import Program
from ..runtime.hist import Hist


@dataclasses.dataclass(frozen=True)
class ContiguousSchedule:
    """Contiguous row-block decomposition (par_chunks_mut semantics).

    Thread t owns normalized iterations [offset(t), offset(t)+count(t));
    when trip % threads != 0 the first `trip % threads` threads own one
    extra iteration (the reference instead asserts divisibility,
    gemm_profiler.rs:183).
    """

    trip: int
    threads: int
    start: int = 0
    step: int = 1

    def local_count(self, tid: int) -> int:
        base, rem = divmod(self.trip, self.threads)
        return base + (1 if tid < rem else 0)

    def offset(self, tid: int) -> int:
        base, rem = divmod(self.trip, self.threads)
        return tid * base + min(tid, rem)

    def local_to_value(self, tid: int, m):
        return self.start + (self.offset(tid) + m) * self.step


@dataclasses.dataclass
class ProfilerResult:
    """Exact per-thread reuse histograms from a real execution."""

    hists: list  # per-tid Hist, raw reuse keys, -1 = first touch
    per_tid_accesses: list
    output: np.ndarray | None = None  # the executed kernel's result

    def merged(self) -> Hist:
        from ..runtime.hist import merge_hists

        return merge_hists(self.hists, in_log_format=False)


# ---------------------------------------------------------------------------
# Real kernel execution (GEMM)
# ---------------------------------------------------------------------------


def gemm_init(ni: int, nj: int, nk: int):
    """PolyBench GEMM init (gemm_profiler.rs:101-122): returns C, A, B."""
    r_c, c_c = np.meshgrid(np.arange(ni), np.arange(nj), indexing="ij")
    C = ((r_c * c_c + 1) % ni) / ni
    r_a, c_a = np.meshgrid(np.arange(ni), np.arange(nk), indexing="ij")
    A = (r_a * (c_a + 1) % nk) / nk
    r_b, c_b = np.meshgrid(np.arange(nk), np.arange(nj), indexing="ij")
    B = (r_b * (c_b + 2) % nj) / nj
    return C, A, B


def execute_gemm(
    ni: int, nj: int, nk: int, thread_num: int,
    alpha: float = 1.5, beta: float = 1.2,
) -> np.ndarray:
    """Run the real kernel per thread block (gemm_profiler.rs:170-209).

    The per-block computation is the same math the instrumented loops
    perform; float results are bit-identical to the serial kernel
    because each C element is owned by exactly one thread.
    """
    C, A, B = gemm_init(ni, nj, nk)
    sched = ContiguousSchedule(trip=ni, threads=thread_num)
    out = np.empty_like(C)
    for tid in range(thread_num):
        lo = sched.offset(tid)
        hi = lo + sched.local_count(tid)
        out[lo:hi] = beta * C[lo:hi] + alpha * (A[lo:hi] @ B)
    return out


# ---------------------------------------------------------------------------
# Exact RI accounting (generic over the IR)
# ---------------------------------------------------------------------------


def profile_program(
    program: Program, machine: MachineConfig, thread_num: int | None = None
) -> ProfilerResult:
    """Exact per-thread RI histograms under the contiguous schedule.

    Enumerates each thread's access stream in execution order (the
    recursive loop body order of oracle/serial.py) and computes exact
    reuse intervals per (array, cache line) with one lexsort per
    thread — numerically identical to the reference's per-access hash
    walk (gemm_profiler.rs:52-91), minus its chunk-local addressing
    (see module docstring).
    """
    from ..core.trace import NestTrace

    T = thread_num if thread_num is not None else machine.thread_num
    hists: list[Hist] = [dict() for _ in range(T)]
    per_tid = [0] * T
    # Per-tid running clock across nests (the reference's profiler keeps
    # one counter per thread for the whole kernel, gemm_profiler.rs:186).
    clocks = [0] * T

    for k in range(len(program.nests)):
        nt = NestTrace(program, k, machine)
        t = nt.tables
        nest = nt.nest
        sched = ContiguousSchedule(
            trip=nest.loops[0].trip, threads=T,
            start=nest.loops[0].start, step=nest.loops[0].step,
        )
        for tid in range(T):
            L = sched.local_count(tid)
            if L == 0:
                continue
            pos_all, addr_all, arr_all = [], [], []
            for ri in range(t.n_refs):
                pos, addr = nt.enumerate_ref(tid, ri, schedule=sched)
                pos_all.append(pos)
                addr_all.append(addr)
                arr_all.append(
                    np.full(pos.size, int(t.ref_arrays[ri]), dtype=np.int64)
                )
            pos_v = np.concatenate(pos_all) + clocks[tid]
            addr_v = np.concatenate(addr_all)
            arr_v = np.concatenate(arr_all)
            order = np.lexsort((pos_v, addr_v, arr_v))
            pos_s, addr_s, arr_s = pos_v[order], addr_v[order], arr_v[order]
            same = np.empty(len(pos_s), dtype=bool)
            same[0] = False
            same[1:] = (addr_s[1:] == addr_s[:-1]) & (arr_s[1:] == arr_s[:-1])
            reuse = np.where(same, pos_s - np.roll(pos_s, 1), -1)
            keys, counts = np.unique(reuse, return_counts=True)
            h = hists[tid]
            for key, cnt in zip(keys.tolist(), counts.tolist()):
                h[int(key)] = h.get(int(key), 0.0) + float(cnt)
            per_tid[tid] += len(pos_v)
            clocks[tid] += L * int(t.acc_per_level[0])
    return ProfilerResult(hists=hists, per_tid_accesses=per_tid)


def profile_gemm(
    n: int, machine: MachineConfig | None = None,
    thread_num: int | None = None, execute: bool = True,
) -> ProfilerResult:
    """gemm_profiler::acc equivalent (gemm_profiler.rs:279-295)."""
    from ..models.gemm import gemm

    machine = machine or MachineConfig()
    res = profile_program(gemm(n), machine, thread_num)
    if execute:
        T = thread_num if thread_num is not None else machine.thread_num
        res.output = execute_gemm(n, n, n, T)
    return res
