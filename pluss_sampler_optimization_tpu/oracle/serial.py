"""Serial oracle: a literal interpreter of the reference's sampler walk.

This is the in-repo correctness anchor (SURVEY.md section 7 step 3): a
direct, dict-based re-enactment of the serial C++ sampler
(c_lib/test/sampler/gemm-t4-pluss-pro-model-ri-omp-seq.cpp) generalized
over the loop-nest IR instead of generated per benchmark:

- each simulated thread walks its statically-scheduled chunks in
  dispatch order (:70-71), executing the body reference sequence
  (:102-288) with a per-(thread, array) last-access-time dict
  (LAT_C/LAT_A/LAT_B, :47-49) and a per-thread access clock (:45);
- private reuses go to the per-thread noshare histogram, pow2-binned
  (:117); share-classified references compare against their carried
  threshold (:203-207) and record raw intervals at ratio THREAD_NUM-1;
- lines never reused flush as -1 with multiplicity = surviving LAT
  entries per (thread, array), and the LAT tables are cleared, after
  EVERY parallel nest (:303-319: "reset both lists so they can be
  reused for later parallel loop"; LAT_X[i].clear() per loop) — so a
  line carried from one parallel loop to the next is a cold miss, while
  the per-thread access clock runs on across nests;
- `total_accesses` reproduces `max_iteration_count` =
  sum(count) (:332).

Thread-major order (each simulated thread runs to completion before the
next) is equivalent to any interleaving because all sampler state is
per-thread — the property the `ri` variant's `#pragma omp parallel for`
over tids (...ri.cpp:67) relies on.
"""

from __future__ import annotations

import dataclasses

from ..config import MachineConfig
from ..ir import Program
from ..runtime.hist import PRIState, share_classify


@dataclasses.dataclass
class OracleResult:
    state: PRIState
    total_accesses: int
    per_tid_accesses: list
    # which engine produced the result, when a router (e.g.
    # periodic.run_exact) chose one; None when the caller invoked an
    # engine directly
    engine: str | None = None


def run_serial(
    program: Program, machine: MachineConfig, v2: bool = False,
    schedule: str = "static",
) -> OracleResult:
    """v2=True selects the runtime-v2 histogram semantics (raw noshare
    keys, pluss_utils_v2.h:915-918). schedule="dynamic" replaces the
    static round-robin chunk ownership with the reference's FIFO
    dynamic-dispatcher arm (core/schedule.py::dynamic_chunk_assignment
    — dead code in the reference, modeled under uniform interleaving;
    identical to static for every rectangular nest)."""
    from ..core.schedule import StaticSchedule, dynamic_chunk_assignment

    P = machine.thread_num
    state = PRIState(P, bin_noshare=not v2)
    lat: dict[tuple[int, str], dict[int, int]] = {
        (t, a): {} for t in range(P) for a in program.arrays
    }
    count = [0] * P

    for nest in program.nests:
        lp0 = nest.loops[0]
        sched = StaticSchedule(
            trip=lp0.trip, chunk=machine.chunk_size, threads=P,
            start=lp0.start, step=lp0.step,
        )
        depth = nest.depth
        pre = [nest.refs_at(l, "pre") for l in range(depth)]
        post = [nest.refs_at(l, "post") for l in range(depth)]

        def access(tid: int, ref, ivs) -> None:
            flat = ref.flat_index(ivs)
            addr = flat * machine.ds // machine.cls
            table = lat[(tid, ref.array)]
            if addr in table:
                reuse = count[tid] - table[addr]
                if ref.share_threshold is not None and share_classify(
                    reuse, ref.share_threshold
                ):
                    ratio = (
                        ref.share_ratio
                        if ref.share_ratio is not None
                        else machine.thread_num - 1
                    )
                    state.update_share(tid, ratio, reuse, 1.0)
                else:
                    state.update_noshare(tid, reuse, 1.0)
            table[addr] = count[tid]
            count[tid] += 1

        def body(tid: int, level: int, ivs: list) -> None:
            for ref in pre[level]:
                access(tid, ref, ivs)
            if level + 1 < depth:
                lp = nest.loops[level + 1]
                # triangular levels: bounds affine in the parallel value
                for n in range(lp.trip_at(ivs[0])):
                    ivs.append(lp.start_at(ivs[0]) + n * lp.step)
                    body(tid, level + 1, ivs)
                    ivs.pop()
            for ref in post[level]:
                access(tid, ref, ivs)

        if schedule == "dynamic":
            n_chunks = -(-lp0.trip // machine.chunk_size)

            def period_cost(n: int) -> int:
                v0 = lp0.start + n * lp0.step
                total = 0
                for l in range(depth):
                    width = 1
                    for j in range(1, l + 1):
                        width *= nest.loops[j].trip_at(v0)
                    total += (len(pre[l]) + len(post[l])) * width
                return total

            costs = [
                sum(
                    period_cost(n)
                    for n in range(
                        ci * machine.chunk_size,
                        min((ci + 1) * machine.chunk_size, lp0.trip),
                    )
                )
                for ci in range(n_chunks)
            ]
            for tid, chunks in enumerate(
                dynamic_chunk_assignment(n_chunks, P, costs)
            ):
                for ci in chunks:
                    for n in range(
                        ci * machine.chunk_size,
                        min((ci + 1) * machine.chunk_size, lp0.trip),
                    ):
                        body(tid, 0, [lp0.start + n * lp0.step])
        else:
            for tid in range(P):
                for m in range(sched.local_count(tid)):
                    body(tid, 0, [sched.local_to_value(tid, m)])

        # per-nest -1 flush + LAT clear (...ri-omp-seq.cpp:303-319)
        for tid in range(P):
            for a in program.arrays:
                table = lat[(tid, a)]
                if table:
                    state.update_noshare(tid, -1, float(len(table)))
                    table.clear()

    return OracleResult(
        state=state, total_accesses=sum(count), per_tid_accesses=list(count)
    )
