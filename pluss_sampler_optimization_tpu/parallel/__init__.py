"""Multi-chip execution layer.

The reference's cross-thread reductions are shared-memory constructs:
mutex-guarded global histograms (src/utils.rs:13-19, pluss_utils.cpp:4-14),
thread-local histograms merged at thread exit
(src/unsafe_utils.rs:32-35,105-151), `omp critical` scalar merges
(c_lib/test/sampler/gemm-t4-pluss-pro-model-ri-opt.cpp termination block)
and a join-then-merge of six per-reference histograms
(...rs-ri-opt-r10.cpp:3258-3276).

The TPU-native equivalent replaces all of them with XLA collectives over
a `jax.sharding.Mesh`:

- the sampled engine shards the *sample axis* (the reference's serial
  amortized walk, the big win) with `jax.shard_map`; noshare histograms
  are dense pow2-bin vectors reduced with `lax.psum` over ICI; share
  histograms stay exact via per-device fixed-capacity unique pairs
  merged on host;
- the dense engine shards its vmapped simulated-thread axis with
  `NamedSharding` (the `ri` variant's `#pragma omp parallel for` over
  tids, ...ri.cpp:67-68, as SPMD);
- the EXACT engines shard too (round 6): the periodic engine's merged
  windows stack on one vmapped axis laid over the mesh
  (`run_periodic_sharded`), and the analytic engine's period/row-block
  classify mega-dispatches shard their key axis via GSPMD
  (`run_analytic_sharded`); `run_exact_sharded` is the auto-router.
  All are bit-identical to single-device (tests/test_parallel.py);
- multi-host scaling needs no new code: the same mesh spans hosts and
  XLA routes the psum over ICI within a slice and DCN across slices.
"""

from .distributed import build_global_mesh, initialize_distributed
from .mesh import build_mesh, local_device_count
from .sharded import (
    run_analytic_sharded,
    run_dense_sharded,
    run_exact_sharded,
    run_periodic_sharded,
    run_sampled_sharded,
    sampled_outputs_sharded,
)

__all__ = [
    "build_mesh",
    "build_global_mesh",
    "initialize_distributed",
    "local_device_count",
    "run_sampled_sharded",
    "sampled_outputs_sharded",
    "run_dense_sharded",
    "run_periodic_sharded",
    "run_analytic_sharded",
    "run_exact_sharded",
]
