"""Multi-host (multi-process) execution setup.

The reference is single-node shared memory; its "communication backend"
is mutexes and thread-local merges (SURVEY.md §2.4). Here the same mesh
that scales across chips scales across hosts: `jax.distributed` wires
the processes together, `jax.devices()` then spans every host's chips,
and the 1-D sample mesh built over them makes the sampled engine's
`lax.psum` ride ICI within a slice and DCN across slices — no engine
code changes between one chip and a multi-host fleet.

Typical launch (same program on every host):

    from pluss_sampler_optimization_tpu.parallel import (
        initialize_distributed, build_global_mesh,
    )

    initialize_distributed(coordinator, num_processes, process_id)
    mesh = build_global_mesh()
    state, results = run_sampled_sharded(prog, machine, cfg, mesh)

Every host draws the same deterministic sample batch but ships only
the rows its own devices hold (jax.make_array_from_process_local_data
in parallel/sharded.py); kernel outputs are fully replicated — the
dense histograms by psum, the exact (reuse, count) pairs by an
in-graph all_gather — so every host decodes identical results.
"""

from __future__ import annotations

from typing import Optional

import jax

from .mesh import SAMPLE_AXIS, build_mesh

_init_args: Optional[tuple] = None


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Wire this process into a multi-host run (jax.distributed).

    With no arguments, relies on the cluster environment's
    auto-detection (TPU pods populate it); a degenerate single-process
    setup needs no call at all. Idempotent for a REPEATED identical
    call; a re-call with a different topology raises instead of
    silently keeping the first one.
    """
    global _init_args
    args = (coordinator_address, num_processes, process_id)
    if _is_initialized():
        # Decide idempotency from state, not from parsing the wording
        # of jax's "already initialized" error (which may change
        # between versions): a repeated identical call — or a bare
        # auto-detect call — is a no-op; an explicit conflicting
        # topology must not silently keep the first one.
        if _init_args == args or args == (None, None, None):
            return
        raise ValueError(
            f"jax.distributed already initialized "
            f"({'with ' + repr(_init_args) if _init_args else 'externally'}); "
            f"conflicting re-initialization {args}"
        )
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # Backstop for when the internal client probe is UNAVAILABLE
        # (jax._src layout changed) and the cluster was wired up
        # outside this wrapper: the bare auto-detect call is tolerant
        # by contract, so treat jax's "already initialized" complaint
        # as a no-op rather than crashing the run. When the probe IS
        # available it already answered "not initialized" above, so
        # this RuntimeError is a genuine init failure — re-raise.
        # Explicit topologies always fail, but surface a ValueError of
        # the same shape the probed path produces (with the
        # RuntimeError chained) so one user error doesn't read two
        # different ways depending on the jax version. Without the
        # probe we cannot tell an external-init collision from a
        # genuine init failure (e.g. unreachable coordinator), so the
        # message names both and defers to the chained error.
        if args != (None, None, None) and _probe_client() is None:
            raise ValueError(
                f"jax.distributed.initialize({args}) failed: either "
                "the cluster was already initialized externally "
                "(conflicting re-initialization) or initialization "
                "itself failed — the chained RuntimeError has the "
                "underlying cause"
            ) from e
        if args != (None, None, None) or _probe_client() is not None:
            raise
        return
    _init_args = args


def _probe_client():
    """The distributed client handle, or None when the internal API is
    unavailable (jax._src layout changed). Returns a (client-or-None,)
    tuple so callers can distinguish "no client" from "can't tell"."""
    try:
        from jax._src.distributed import global_state

        return (global_state.client,)
    except Exception:
        return None


def _is_initialized() -> bool:
    """Whether this process already joined a jax.distributed cluster.

    jax exposes no public predicate; the distributed client handle on
    the global state object is the stable internal one (non-None after
    a successful initialize, reset to None by shutdown). If the
    internal layout ever changes, fall back to this wrapper's own
    record so repeated identical calls through it stay idempotent.
    """
    probed = _probe_client()
    if probed is not None:
        return probed[0] is not None
    return _init_args is not None


def build_global_mesh(axis: str = SAMPLE_AXIS) -> jax.sharding.Mesh:
    """1-D mesh over every device of every participating process.

    After initialize_distributed, jax.devices() is the global list
    ordered by process, so this is build_mesh() — named separately to
    document intent at call sites and to assert the precondition that
    each process contributes the same device count (required for the
    equal per-process input shards of the multi-host dispatch).
    """
    n_local = jax.local_device_count()
    n_total = len(jax.devices())
    if n_total != n_local * jax.process_count():
        raise RuntimeError(
            f"unequal device counts across processes: {n_total} global "
            f"!= {n_local} local x {jax.process_count()} processes"
        )
    return build_mesh(axis=axis)
