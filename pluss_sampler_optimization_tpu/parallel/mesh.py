"""Device-mesh construction helpers.

One flat axis ("samples") is the framework's scale axis: the sampled
engine shards sampled iteration points over it and psums histograms
across it. A single chip is the degenerate 1-device mesh, so every
engine has exactly one code path regardless of topology.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

SAMPLE_AXIS = "samples"


def local_device_count() -> int:
    """Devices attached to this process (jax.local_device_count)."""
    return jax.local_device_count()


def build_mesh(
    n_devices: Optional[int] = None,
    axis: str = SAMPLE_AXIS,
    devices: Optional[Sequence] = None,
) -> jax.sharding.Mesh:
    """A 1-D mesh over the first `n_devices` devices (default: all)."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}"
            )
        devs = devs[:n_devices]
    return jax.sharding.Mesh(np.array(devs), (axis,))
