"""Replica device placement: a thread-local device scope the engines
consult when committing host buffers to a device.

The replica pool (service/replicas.py) partitions `jax.devices()` into
disjoint groups; each replica worker thread enters `device_scope(its
devices)` around every engine execution. Inside the scope:

- `place(x)` commits a host buffer to the replica's primary device
  with an explicit `jax.device_put` (outside a scope it is plain
  `jnp.asarray`, byte-for-byte the engines' historical behavior);
- `jax.default_device` is set to the replica's primary device, so
  arrays the engines create WITHOUT going through `place` (threefry
  keys, iota scratch, ...) land on the same device and jit dispatch
  follows them there;
- `active_mesh()` exposes the replica's own 1-D sample mesh
  (parallel/mesh.py::build_mesh over just its devices), which the
  sharded entry points pick up when no explicit mesh is passed.

Placement is pure routing: the per-ref sample streams are derived
from seeds alone (numpy PCG on the host path, threefry counters on
the device path), never from device identity, so results are
bit-identical whichever replica — or how many replicas — served them
(pinned by tests/test_replicas.py at replicas 1/2/4).
"""

from __future__ import annotations

import contextlib
import threading

_tls = threading.local()


def active_devices():
    """The device group of the enclosing `device_scope`, or None."""
    return getattr(_tls, "devices", None)


def active_device():
    """Primary device of the enclosing scope, or None."""
    devs = active_devices()
    return devs[0] if devs else None


def active_mesh():
    """The enclosing scope's per-replica mesh, or None."""
    return getattr(_tls, "mesh", None)


def active_replica_id():
    """Replica id of the enclosing scope, or None (set by the replica
    pool's workers; fault-injection tests key on it)."""
    return getattr(_tls, "replica_id", None)


@contextlib.contextmanager
def device_scope(devices, mesh=None, replica_id=None):
    """Pin this thread's engine work to `devices` (a non-empty
    sequence): explicit `place()` transfers target devices[0], and
    jax.default_device covers every implicit array creation. Scopes
    nest; the innermost wins."""
    import jax

    prev = (
        getattr(_tls, "devices", None),
        getattr(_tls, "mesh", None),
        getattr(_tls, "replica_id", None),
    )
    _tls.devices = list(devices)
    _tls.mesh = mesh
    _tls.replica_id = replica_id
    try:
        with jax.default_device(_tls.devices[0]):
            yield _tls.devices
    finally:
        _tls.devices, _tls.mesh, _tls.replica_id = prev


def place(x):
    """Commit one host buffer to the active scope's primary device
    (explicit `jax.device_put`); outside any scope, plain
    `jnp.asarray` — exactly the transfer the engines always did."""
    import jax
    import jax.numpy as jnp

    dev = active_device()
    if dev is None:
        return jnp.asarray(x)
    return jax.device_put(x, dev)
