"""Mesh-sharded execution of the sampled and dense engines.

Sampled engine (the scale path): samples of one tracked reference are
sharded over the mesh's sample axis with `jax.shard_map`. Each device
solves its shard's closed-form next-use (sampler/nextuse.py) locally and
the reduction happens on-device:

- a dense pow2-binned noshare histogram reduced with `lax.psum` — the
  TPU-native replacement for the reference's mutex/TLS-merge reductions
  (src/unsafe_utils.rs:105-151, pluss_utils.cpp:4-14);
- exact (reuse, class) pairs per device via the fixed-capacity unique
  reduction, merged on host — these preserve raw interval values so the
  CRI stage (both runtime-v1 and the r10-quirks variant) sees exactly
  what the unsharded engine produces;
- cold-sample counts psum'd to a scalar.

The result is bit-identical to sampler/sampled.py on any mesh size
under either draw mode — the host numpy stream or the device threefry
stream (sampler/draw.py; same seed + batch bucketing => same sample
set, and the unique merge is exact) — which is the sharded path's
correctness test. Device drawing engages whenever the mesh size
divides the batch — including multi-host, where every process replays
the identical threefry draw on its own devices and contributes only
the rows it owns, so no draw data crosses hosts at all.

Dense engine: the jitted per-tid kernel (sampler/dense.py) is already
vmapped over simulated threads; `run_dense_sharded` lays that batch axis
out over the mesh with `NamedSharding` — the `ri` variant's
`#pragma omp parallel for num_threads(THREAD_NUM)` over tids
(c_lib/test/sampler/gemm-t4-pluss-pro-model-ri.cpp:67-68) as SPMD.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import MachineConfig, SamplerConfig
from ..core.trace import NestTrace, ProgramTrace
from ..ir import Program
from ..ops.histogram import (
    N_EXP_BINS,
    exp_hist,
    fixed_k_unique,
    merge_pair_sets,
    sorted_k_unique,
)
from ..runtime import telemetry
from ..runtime.hist import PRIState
from ..sampler.dense import run_dense
from ..sampler.draw import draw_bucket_keys_device, draw_sample_keys_device
from ..sampler.sampled import (
    default_batch,
    DEFAULT_CAPACITY,
    SampledRefResult,
    _bucket_rows,
    _host_fuse_plan,
    _kernel_sig,
    _pad_highs,
    _ref_sig_digest,
    _sample_highs,
    _use_device_draw,
    _use_fused,
    check_packed_ratios,
    classify_samples,
    decode_pairs,
    decode_sample_keys,
    draw_sample_keys,
    fold_results,
    pad_keys,
)
from .mesh import build_mesh
from .placement import active_mesh


def _default_mesh():
    """Mesh for entry points called without one: the enclosing replica
    scope's per-replica mesh when a replica pool routed the execution
    here (parallel/placement.py), otherwise the full-device mesh —
    the historical default."""
    return active_mesh() or build_mesh()


def _shard_map(f, mesh, in_specs, out_specs):
    """jax.shard_map across the API move: top-level `jax.shard_map`
    (with its `check_vma` varying-axes check) on current jax, the
    `jax.experimental.shard_map` form (whose equivalent knob is
    `check_rep`) on older installs. The check is disabled either way —
    the all_gather outputs ARE replicated, but the static analysis
    cannot infer that."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def _build_sharded_ref_kernel(
    nt: NestTrace, ref_idx: int, mesh: jax.sharding.Mesh, capacity: int,
    use_pallas_hist: bool, scan: bool = False,
):
    """jit(shard_map) kernel: sharded samples -> reduced histograms.

    scan=False (the host draw's form): one padded chunk, replicated
    valid-prefix count, one reduction per call. scan=True (the device
    draw's form, sampler/draw.py): the WHOLE drawn buffer arrives
    sharded along with its selection mask, each device lax.scans its
    local rows in chunk-sized slices with the sparse pair sets merged
    on device between steps (weighted fixed_k_unique), and the mesh
    reduction happens once at the end — one dispatch and one fetch
    per ref, no per-chunk host round trips. The pair merges and the
    psum'd histogram are partition- and order-invariant, so both
    forms produce identical results for the same sample set.
    """
    axis = mesh.axis_names[0]
    check_packed_ratios(nt)

    if use_pallas_hist:
        from ..ops.pallas_hist import pow2_hist_auto as _hist_fn
    else:
        _hist_fn = exp_hist

    def _classify(sample_keys, w, highs, vals, rx):
        """Shared per-slice body: classify + the three local outputs."""
        snt = nt.with_vals(vals)
        samples = decode_sample_keys(sample_keys, highs)
        packed, ri, is_share, found = classify_samples(
            snt, ref_idx, samples, rx
        )
        nosh = _hist_fn(jnp.maximum(ri, 1), (found & ~is_share & w))
        cold = jnp.sum((~found & w).astype(jnp.int64))
        keys, counts, n_unique = fixed_k_unique(packed, found & w, capacity)
        return nosh, cold, keys, counts, n_unique

    def _mesh_reduce(nosh, cold, keys, counts, n_u):
        """psum the dense outputs over ICI; all_gather the exact pairs
        so every output is fully replicated — a few KB over ICI, and
        the one thing that makes multi-host fetch work (device_get of
        an axis-sharded output would touch non-addressable devices on
        other hosts)."""
        return (
            jax.lax.psum(nosh, axis),
            jax.lax.psum(cold, axis),
            jax.lax.all_gather(keys, axis),  # (n_dev, capacity)
            jax.lax.all_gather(counts, axis),
            jax.lax.all_gather(n_u, axis),  # (n_dev,)
        )

    if scan:
        def local_fn(sample_keys, mask, highs, vals, rx, n_chunks):
            kb = sample_keys.reshape(n_chunks, -1)
            mb = mask.reshape(n_chunks, -1)

            def step(carry, xm):
                ck, cc, cold, max_nu, nh = carry
                x, msk = xm
                nosh, c, k2, c2, nu = _classify(x, msk, highs, vals, rx)
                mk, mc, mnu = merge_pair_sets(ck, cc, k2, c2, capacity)
                return (
                    mk, mc, cold + c,
                    jnp.maximum(max_nu, jnp.maximum(nu, mnu)),
                    nh + nosh,
                ), None

            init = (
                jnp.full(capacity, -1, dtype=jnp.int64),
                jnp.zeros(capacity, dtype=jnp.int64),
                jnp.int64(0),
                jnp.int64(0),
                jnp.zeros(N_EXP_BINS, dtype=jnp.int64),
            )
            (mk, mc, cold, max_nu, nh), _ = jax.lax.scan(
                step, init, (kb, mb)
            )
            return _mesh_reduce(nh, cold, mk, mc, max_nu)

        def entry(sample_keys, mask, highs, vals, rx, n_chunks: int):
            return _shard_map(
                functools.partial(local_fn, n_chunks=n_chunks),
                mesh=mesh,
                in_specs=(P(axis), P(axis), P(), P(), P()),
                out_specs=(P(), P(), P(), P(), P()),
            )(sample_keys, mask, highs, vals, rx)

        return jax.jit(entry, static_argnames=("n_chunks",))

    def local_fn(sample_keys, n_valid, highs, vals, rx):
        # int64 mixed-radix keys on the wire (8 bytes/sample); decode
        # and the padding weight mask both happen device-side
        local_b = sample_keys.shape[0]
        base = jax.lax.axis_index(axis).astype(jnp.int64) * local_b
        w = base + jnp.arange(local_b, dtype=jnp.int64) < n_valid
        return _mesh_reduce(*_classify(sample_keys, w, highs, vals, rx))

    def entry(sample_keys, n_valid, highs, vals, rx):
        return _shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(P(axis), P(), P(), P(), P()),
            out_specs=(P(), P(), P(), P(), P()),
        )(sample_keys, n_valid, highs, vals, rx)

    return jax.jit(entry)


def _build_sharded_ref_kernel_fused(
    nt: NestTrace, ref_idx: int, mesh: jax.sharding.Mesh, capacity: int,
):
    """Cross-ref fused twin of the scan-form sharded kernel: the
    bucket's stacked (R, B) key/mask buffers arrive sharded over the
    mesh along the SAMPLE axis (P(None, axis)), each device vmaps the
    per-ref local scan over the leading ref axis, and the mesh
    reduction runs once on the stacked outputs — one dispatch and one
    fetch per bucket instead of per ref.

    Two deliberate differences from the per-ref form, neither visible
    in results: the unique reductions are sorted_k_unique (under vmap
    fixed_k_unique's lax.cond would run its sort branch anyway — see
    its docstring) and the dense noshare histogram is exp_hist
    unconditionally (the Pallas ladder is pinned bit-equal to exp_hist
    where it engages, and vmapping a Pallas call is not a supported
    path here). Every reduction is exact integer math, so fused-bucket
    results are bit-identical to the per-ref sharded path — the
    sharded fusion tests pin it."""
    axis = mesh.axis_names[0]
    check_packed_ratios(nt)

    def local_fn(keys_RB, mask_RB, highs, vals, rx_R, n_chunks):
        snt = nt.with_vals(vals)

        def one_ref(keys_B, mask_B, rx):
            kb = keys_B.reshape(n_chunks, -1)
            mb = mask_B.reshape(n_chunks, -1)

            def step(carry, xm):
                ck, cc, cold, max_nu, nh = carry
                x, msk = xm
                samples = decode_sample_keys(x, highs)
                packed, ri_v, is_share, found = classify_samples(
                    snt, ref_idx, samples, rx
                )
                nosh = exp_hist(
                    jnp.maximum(ri_v, 1), (found & ~is_share & msk)
                )
                k2, c2, nu = sorted_k_unique(
                    packed, found & msk, capacity
                )
                w = jnp.concatenate([cc, c2])
                mk, mc, mnu = sorted_k_unique(
                    jnp.concatenate([ck, k2]), w > 0, capacity,
                    weights=w,
                )
                return (
                    mk, mc,
                    cold + jnp.sum((~found & msk).astype(jnp.int64)),
                    jnp.maximum(max_nu, jnp.maximum(nu, mnu)),
                    nh + nosh,
                ), None

            init = (
                jnp.full(capacity, -1, dtype=jnp.int64),
                jnp.zeros(capacity, dtype=jnp.int64),
                jnp.int64(0),
                jnp.int64(0),
                jnp.zeros(N_EXP_BINS, dtype=jnp.int64),
            )
            (mk, mc, cold, max_nu, nh), _ = jax.lax.scan(
                step, init, (kb, mb)
            )
            return mk, mc, cold, max_nu, nh

        mk, mc, cold, max_nu, nh = jax.vmap(
            one_ref, in_axes=(0, 0, 0)
        )(keys_RB, mask_RB, rx_R)
        return (
            jax.lax.psum(nh, axis),          # (R, bins)
            jax.lax.psum(cold, axis),        # (R,)
            jax.lax.all_gather(mk, axis),    # (n_dev, R, capacity)
            jax.lax.all_gather(mc, axis),
            jax.lax.all_gather(max_nu, axis),  # (n_dev, R)
        )

    def entry(keys_RB, mask_RB, highs, vals, rx_R, n_chunks: int):
        return _shard_map(
            functools.partial(local_fn, n_chunks=n_chunks),
            mesh=mesh,
            in_specs=(P(None, axis), P(None, axis), P(), P(), P()),
            out_specs=(P(), P(), P(), P(), P()),
        )(keys_RB, mask_RB, highs, vals, rx_R)

    return jax.jit(entry, static_argnames=("n_chunks",))


# (sig, mesh, capacity, pallas, scan) -> shared jitted kernel; same
# sharing rule as sampler/sampled.py::_SIG_KERNELS — structure in the
# closure, every N-dependent number in the highs/vals operands.
# Bounded (capacity regrows mint additional entries).
import collections as _collections

_SHARDED_SIG_KERNELS: "_collections.OrderedDict" = _collections.OrderedDict()
_SHARDED_SIG_KERNELS_MAX = 32


def _sharded_kernels_for(
    nt: NestTrace, ref_idx: int, mesh, capacity: int,
    use_pallas_hist: bool, scan: bool,
):
    from ..sampler.sampled import lru_cached
    from ..service.fingerprint import structure_digest

    # the structural half of the key is the canonical signature digest
    # (service/fingerprint.py), matching sampler/sampled.py; the mesh
    # rides alongside raw — its identity is process-local by nature
    return lru_cached(
        _SHARDED_SIG_KERNELS,
        (structure_digest(_kernel_sig(nt, ref_idx)), mesh, capacity,
         use_pallas_hist, scan),
        lambda: _build_sharded_ref_kernel(
            nt, ref_idx, mesh, capacity, use_pallas_hist, scan
        ),
        _SHARDED_SIG_KERNELS_MAX,
    )


def _sharded_fused_kernels_for(
    nt: NestTrace, ref_idx: int, mesh, capacity: int,
):
    """Fused-bucket variant of _sharded_kernels_for; keyed "fused" so
    it never collides with the per-ref forms."""
    from ..sampler.sampled import lru_cached
    from ..service.fingerprint import structure_digest

    return lru_cached(
        _SHARDED_SIG_KERNELS,
        (structure_digest(_kernel_sig(nt, ref_idx)), mesh, capacity,
         "fused"),
        lambda: _build_sharded_ref_kernel_fused(
            nt, ref_idx, mesh, capacity
        ),
        _SHARDED_SIG_KERNELS_MAX,
    )


@telemetry.counted_lru_cache(maxsize=16)
def _sharded_program_kernels(
    program: Program,
    machine: MachineConfig,
    mesh: jax.sharding.Mesh,
    capacity: int,
    use_pallas_hist: bool,
    scan: bool = False,
):
    trace = ProgramTrace(program, machine)
    kernels = []
    for k, nt in enumerate(trace.nests):
        if nt.tri and any(lp.step != 1 for lp in nt.nest.loops):
            raise NotImplementedError(
                f"{program.name}: the closed-form next-use supports "
                "triangular nests with unit steps only; use the dense "
                "or stream engine"
            )
        for ri in range(nt.tables.n_refs):
            kernels.append(
                [k, ri,
                 _sharded_kernels_for(
                     nt, ri, mesh, capacity, use_pallas_hist, scan
                 ),
                 capacity]  # capacity travels with the kernel: a
            )                # regrown kernel returns wider arrays
    return trace, kernels


def sampled_outputs_sharded(
    program: Program,
    machine: MachineConfig,
    cfg: SamplerConfig | None = None,
    mesh: jax.sharding.Mesh | None = None,
    batch: int | None = None,
    capacity: int = DEFAULT_CAPACITY,
):
    """Sharded sampled engine -> per-ref SampledRefResult (exact) plus
    the psum'd dense noshare histograms (per ref, for observability)."""
    cfg = cfg or SamplerConfig()
    mesh = mesh or _default_mesh()
    if batch is None:
        batch = default_batch()
    n_dev = mesh.devices.size
    trace, kernels = _sharded_program_kernels(
        program, machine, mesh, capacity, cfg.use_pallas_hist
    )
    n_proc = jax.process_count()
    in_sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
    # Device drawing on the mesh: batch must split evenly over the
    # mesh so the buffer's batch-sized chunks reshard without padding.
    # The realistic single-host TPU topologies (v4-8, v5e-8: power-of-
    # 2 meshes dividing the 2^20 batch) and the test suite's virtual
    # CPU mesh all qualify. Multi-host works because threefry is
    # deterministic: every process replays the identical draw on its
    # own device and contributes only the rows its devices own
    # (_buffer_to_global) — no cross-host draw traffic at all. An
    # EXPLICIT device_draw=True with a non-dividing mesh raises rather
    # than silently sampling from the other stream — the
    # bit-identity-with-run_sampled contract is the sharded path's
    # correctness anchor; the auto default (None) resolves to the
    # host stream in that case.
    use_dev_draw = _use_device_draw(cfg)
    if use_dev_draw and batch % n_dev != 0:
        if cfg.device_draw:
            raise ValueError(
                f"device_draw=True needs a mesh size dividing the "
                f"batch ({batch} % {n_dev} != 0): the device buffer "
                "cannot reshard evenly, and falling back would sample "
                "a different stream than run_sampled. Use a dividing "
                "mesh size or device_draw=None/False."
            )
        import warnings

        warnings.warn(
            f"device_draw auto-default downgrades to the host draw "
            f"stream: mesh size {n_dev} does not divide the batch "
            f"({batch}); results are statistically equivalent to "
            "run_sampled's device stream but not bit-identical. Pass "
            "a dividing mesh size (or device_draw=False on both "
            "engines) for bit-identity.",
            stacklevel=2,
        )
        use_dev_draw = False
    if _use_fused(cfg) and n_proc == 1:
        # Cross-ref fused dispatch (sampler/sampled.py's bucket plan)
        # on the mesh: one vmapped shard_map dispatch per kernel-
        # signature bucket. Single-process only — the multi-process
        # buffer assembly (_buffer_to_global) is per-ref 1-D and a
        # stacked 2-D equivalent is not worth the complexity for the
        # per-ref dispatch count multi-host runs already amortize —
        # so n_proc > 1 keeps the per-ref loop below.
        return _sampled_outputs_sharded_fused(
            trace, cfg, mesh, batch, capacity, use_dev_draw
        )
    scan_kernels = None
    if use_dev_draw:
        # lru-cached like the host-form kernels (scan=True keys a
        # separate entry), so repeat calls and capacity regrows are
        # paid once
        _, scan_kernels = _sharded_program_kernels(
            program, machine, mesh, capacity, cfg.use_pallas_hist,
            scan=True,
        )
    results = []
    dense_noshare = []
    for idx, (k, ri, kernel, cap) in enumerate(kernels):
        nt = trace.nests[k]
        name = nt.tables.ref_names[ri]
        ref_span = telemetry.span("ref", engine="sharded", ref=name)
        ref_span.__enter__()
        drawn = None
        if use_dev_draw:
            with telemetry.span("draw", where="device"):
                drawn = draw_sample_keys_device(
                    nt, ri, cfg, seed=cfg.seed * 1000003 + idx,
                    batch=batch,
                )
        if drawn is None:
            # key form until dispatch: a large run holds 1/3 the
            # memory (see draw_sample_keys)
            with telemetry.span("draw", where="host"):
                keys_all, highs = draw_sample_keys(
                    nt, ri, cfg, seed=cfg.seed * 1000003 + idx
                )
            n_samples = len(keys_all)
        else:
            dev_keys, dev_mask, n_samples, highs = drawn
        noshare: dict[int, float] = {}
        share: dict[int, dict[int, float]] = {}
        cold = 0.0
        dense = np.zeros(N_EXP_BINS, dtype=np.int64)
        step = max(n_dev, (batch // n_dev) * n_dev)

        def dispatch(holder, run_kernel, rebuild):
            """One chunk through holder's trailing [kernel, capacity]
            entries (holder is mutated IN PLACE — an lru-cached
            [k, ri, kernel, cap] row from either kernel list — so a
            capacity regrow is retained and paid once, not on every
            later chunk/call); mirrors sampler/sampled.py's drain
            loop."""
            nonlocal cold, dense
            while True:
                kern, c2 = holder[-2], holder[-1]
                with telemetry.span("dispatch_psum"):
                    telemetry.count("dispatches")
                    out = run_kernel(kern)
                with telemetry.span("gather_fetch"):
                    nh, c, keys, counts, n_unique = (
                        telemetry.record_fetch(jax.device_get(out))
                    )
                if int(n_unique.max(initial=0)) <= c2:
                    break
                telemetry.count("capacity_regrows")
                holder[-1] = max(c2 * 4, int(n_unique.max(initial=0)))
                holder[-2] = rebuild(holder[-1])
            dense += nh
            cold += float(c)
            with telemetry.span("merge"):
                for d in range(n_dev):
                    decode_pairs(keys[d], counts[d], noshare, share)

        def _buffer_to_global(buf):
            """The whole (process-local, identical on every process)
            draw buffer, laid out over the mesh axis. Single-process:
            a plain resharding device_put. Multi-process: each process
            device_puts only the contiguous block of rows its own
            devices hold and the global array is assembled from the
            single-device pieces — every process computed the same
            buffer, so the assembly is consistent by determinism."""
            if n_proc == 1:
                with telemetry.span("shard_put", rows=int(buf.shape[0])):
                    return jax.device_put(buf, in_sharding)
            B = buf.shape[0]
            rows = B // n_dev
            pid = jax.process_index()
            pieces = [
                jax.device_put(
                    jax.lax.slice(buf, (g * rows,), ((g + 1) * rows,)),
                    d,
                )
                for g, d in enumerate(mesh.devices.flat)
                if d.process_index == pid
            ]
            return jax.make_array_from_single_device_arrays(
                (B,), in_sharding, pieces
            )

        ph = _pad_highs(highs)
        rxv = np.int64(ri)
        if drawn is not None:
            n_chunks = dev_keys.shape[0] // batch
            kc = _buffer_to_global(dev_keys)
            mc = _buffer_to_global(dev_mask)
            dispatch(
                scan_kernels[idx],
                lambda kern, kc=kc, mc=mc, nc=n_chunks, ph=ph,
                nv=nt.vals, rxv=rxv: kern(kc, mc, ph, nv, rxv, nc),
                lambda c2, nt=nt, ri=ri: _sharded_kernels_for(
                    nt, ri, mesh, c2, cfg.use_pallas_hist, scan=True
                ),
            )
        else:
            for s0 in range(0, n_samples, step):
                chunk, n_valid = pad_keys(
                    keys_all[s0 : s0 + step], n_dev,
                    total=step if n_samples > step else None,
                )
                # every process draws the same batch (deterministic
                # host RNG) and ships only the rows its own devices
                # hold; jax assembles the global sharded array. One
                # path for any process count — single-process
                # degenerates to the full chunk, already pre-sharded
                # for the kernel.
                rows = len(chunk) // n_proc
                pid = jax.process_index()
                with telemetry.span("shard_put", rows=len(chunk)):
                    cj = jax.make_array_from_process_local_data(
                        in_sharding,
                        chunk[pid * rows : (pid + 1) * rows],
                        chunk.shape,
                    )
                dispatch(
                    kernels[idx],
                    lambda kern, cj=cj, n_valid=n_valid, ph=ph,
                    nv=nt.vals, rxv=rxv: kern(cj, n_valid, ph, nv, rxv),
                    lambda c2, nt=nt, ri=ri: _sharded_kernels_for(
                        nt, ri, mesh, c2, cfg.use_pallas_hist, scan=False
                    ),
                )
        ref_span.__exit__(None, None, None)
        results.append(
            SampledRefResult(
                name=name, noshare=noshare, share=share, cold=cold,
                n_samples=n_samples,
            )
        )
        dense_noshare.append(dense)
    return results, dense_noshare


def _sampled_outputs_sharded_fused(
    trace: ProgramTrace,
    cfg: SamplerConfig,
    mesh: jax.sharding.Mesh,
    batch: int,
    capacity: int,
    use_dev_draw: bool,
):
    """Cross-ref fused form of sampled_outputs_sharded (single
    process): refs are grouped into the same kernel-signature buckets
    as sampler/sampled.py and each bucket's stacked (R, B) buffers go
    through ONE vmapped shard_map dispatch
    (_build_sharded_ref_kernel_fused), with the capacity-regrow loop
    running per bucket dispatch. Same draw streams, same exact merges
    — bit-identical to both the per-ref sharded loop and run_sampled.
    """
    axis = mesh.axis_names[0]
    n_dev = mesh.devices.size
    n_proc = jax.process_count()
    assert n_proc == 1, "fused sharded path is single-process only"
    stack_sharding = NamedSharding(mesh, P(None, axis))
    rows = []
    for k, nt in enumerate(trace.nests):
        for ri in range(nt.tables.n_refs):
            rows.append((k, ri, None, _ref_sig_digest(nt, ri)))
    noshare = {idx: {} for idx in range(len(rows))}
    share = {idx: {} for idx in range(len(rows))}
    cold = {idx: 0.0 for idx in range(len(rows))}
    dense = {idx: np.zeros(N_EXP_BINS, dtype=np.int64)
             for idx in range(len(rows))}
    n_samples_of = {idx: 0 for idx in range(len(rows))}
    cap = capacity
    n_buckets = 0
    max_bucket_dispatches = 0
    n_fused = 0
    n_refs_fused = 0

    def run_bucket(nt, ri0, mem, make_inputs, ph, rx_R, n_chunks):
        """One fused bucket dispatch + its per-bucket regrow loop."""
        nonlocal cap, n_fused, n_refs_fused
        dispatch_cap = cap
        while True:
            kern = _sharded_fused_kernels_for(nt, ri0, mesh,
                                              dispatch_cap)
            keys_RB, mask_RB = make_inputs()
            with telemetry.span("dispatch_psum", form="fused",
                                refs=len(mem)):
                telemetry.count("dispatches")
                telemetry.count("dispatches_fused")
                out = kern(keys_RB, mask_RB, ph, nt.vals, rx_R,
                           n_chunks)
            with telemetry.span("gather_fetch", fused=True):
                nh, c, keys, counts, max_nu = telemetry.record_fetch(
                    jax.device_get(out)
                )
            if int(max_nu.max(initial=0)) <= dispatch_cap:
                break
            # regrow ONCE for the whole bucket dispatch, then re-run
            telemetry.count("capacity_regrows")
            dispatch_cap = max(dispatch_cap * 4,
                               int(max_nu.max(initial=0)))
            cap = max(cap, dispatch_cap)
        n_fused += 1
        n_refs_fused += len(mem)
        with telemetry.span("merge"):
            for j, idx in enumerate(mem):
                dense[idx] += nh[j]
                cold[idx] += float(c[j])
                for d in range(n_dev):
                    decode_pairs(keys[d, j], counts[d, j],
                                 noshare[idx], share[idx])

    step = max(n_dev, (batch // n_dev) * n_dev)
    for (k, sig), members in _bucket_rows(trace, rows).items():
        nt = trace.nests[k]
        ri0 = members[0][1]
        highs, s = _sample_highs(nt, ri0, cfg)
        if s == 0:
            continue
        n_buckets += 1
        bucket_dispatches = 0
        bspan = telemetry.span(
            "bucket", engine="sharded", nest=k,
            refs=",".join(nt.tables.ref_names[ri] for _, ri in members),
        )
        bspan.__enter__()
        ph = _pad_highs(highs)
        drawn = None
        if use_dev_draw:
            with telemetry.span("draw", where="device"):
                drawn = draw_bucket_keys_device(
                    nt, [ri for _, ri in members], cfg,
                    [cfg.seed * 1000003 + idx for idx, _ in members],
                    batch,
                )
        host_members = []
        dev_groups: dict[int, list] = {}
        if drawn is None:
            host_members = members
        else:
            for (idx, ri), d in zip(members, drawn):
                if d is None:
                    host_members.append((idx, ri))
                    continue
                sk, chosen, s_m, _hi = d
                n_samples_of[idx] = s_m
                dev_groups.setdefault(int(sk.shape[0]), []).append(
                    (idx, ri, sk, chosen)
                )
        for B, grp in dev_groups.items():
            rx_R = jnp.asarray([ri for _, ri, _, _ in grp], jnp.int64)

            def make_inputs(grp=grp):
                with telemetry.span("shard_put",
                                    rows=len(grp) * grp[0][2].shape[0]):
                    return (
                        jax.device_put(
                            jnp.stack([sk for _, _, sk, _ in grp]),
                            stack_sharding,
                        ),
                        jax.device_put(
                            jnp.stack([ch for _, _, _, ch in grp]),
                            stack_sharding,
                        ),
                    )

            run_bucket(nt, grp[0][1], [idx for idx, _, _, _ in grp],
                       make_inputs, ph, rx_R, B // batch)
            bucket_dispatches += 1
        if host_members:
            with telemetry.span("draw", where="host"):
                keys_list = []
                for idx, ri in host_members:
                    keys_all, _hi = draw_sample_keys(
                        nt, ri, cfg, seed=cfg.seed * 1000003 + idx
                    )
                    n_samples_of[idx] = len(keys_all)
                    keys_list.append(keys_all)
            n_samples = len(keys_list[0])
            g, n_groups = _host_fuse_plan(n_samples, step)
            span_len = g * step
            rx_R = jnp.asarray([ri for _, ri in host_members],
                               jnp.int64)
            mem = [idx for idx, _ in host_members]
            for gi in range(n_groups):
                lo = gi * span_len

                def make_inputs(lo=lo, kl=keys_list,
                                span_len=span_len):
                    buf = np.empty((len(kl), span_len),
                                   dtype=np.int64)
                    msk = np.zeros((len(kl), span_len), dtype=bool)
                    for j, ka in enumerate(kl):
                        seg = ka[lo:lo + span_len]
                        buf[j, :len(seg)] = seg
                        buf[j, len(seg):] = ka[0]  # decodable padding
                        msk[j, :len(seg)] = True
                    with telemetry.span("shard_put",
                                        rows=buf.size):
                        return (
                            jax.device_put(buf, stack_sharding),
                            jax.device_put(msk, stack_sharding),
                        )

                run_bucket(nt, host_members[0][1], mem, make_inputs,
                           ph, rx_R, g)
                bucket_dispatches += 1
        bspan.__exit__(None, None, None)
        max_bucket_dispatches = max(max_bucket_dispatches,
                                    bucket_dispatches)
    telemetry.gauge("fuse_refs", 1)
    telemetry.gauge("ref_buckets", n_buckets)
    telemetry.gauge("expected_chunks", max_bucket_dispatches)
    if n_fused:
        telemetry.gauge("refs_per_dispatch", n_refs_fused / n_fused)
    results = []
    dense_noshare = []
    for idx, (k, ri, _ks, _sig) in enumerate(rows):
        nt = trace.nests[k]
        results.append(SampledRefResult(
            name=nt.tables.ref_names[ri], noshare=noshare[idx],
            share=share[idx], cold=cold[idx],
            n_samples=n_samples_of[idx],
        ))
        dense_noshare.append(dense[idx])
    return results, dense_noshare


def run_sampled_sharded(
    program: Program,
    machine: MachineConfig,
    cfg: SamplerConfig | None = None,
    mesh: jax.sharding.Mesh | None = None,
    v2: bool = False,
    **kw,
) -> tuple[PRIState, list[SampledRefResult]]:
    """Sharded engine -> PRIState; bit-identical to sampler/sampled.py's
    run_sampled at any accepted mesh size (same draw stream — host or
    device per _use_device_draw — and exact merges; an explicit
    device_draw=True with a mesh size not dividing the batch raises
    instead of silently switching streams)."""
    cfg = cfg or SamplerConfig()
    results, _ = sampled_outputs_sharded(program, machine, cfg, mesh, **kw)
    return fold_results(results, machine.thread_num, v2), results


def run_periodic_sharded(
    program: Program,
    machine: MachineConfig,
    mesh: jax.sharding.Mesh | None = None,
    max_share: int = 64,
):
    """Periodic exact engine with the merged-window axis on the mesh.

    Each nest's merged (delta, phase) windows stack on one axis,
    evaluated by jit(vmap(window body)) with the axis laid over the
    devices via NamedSharding — the same idiom as run_dense_sharded's
    tid axis. Outputs come back per window (the per-tid multiplicity
    scaling happens on host, exactly as in run_periodic), so there is
    no cross-device reduction at all and the result is bit-identical
    to the single-device engine: the vmapped body is the same integer
    computation per window (tests/test_parallel.py pins it on the
    8-device virtual mesh). Windows short of the mesh size are padded
    with repeats of the last window; padded outputs are dropped."""
    mesh = mesh or _default_mesh()
    from ..sampler.periodic import _compiled_nest_batch, run_periodic

    axis = mesh.axis_names[0]
    sharding = NamedSharding(mesh, P(axis))
    n_dev = mesh.devices.size

    def window_eval(prog, nest_index, nt, merged):
        _, batch_kernels = _compiled_nest_batch(
            prog, nest_index, machine, max_share
        )
        outs: dict = {}
        for pair in (True, False):
            items = [
                (key, v0) for key, v0 in merged.items()
                if (key[0] is not None) == pair
            ]
            if not items:
                continue
            v0a = np.array([v0 for _, v0 in items], dtype=np.int64)
            v0b = np.array(
                [v0 + (key[0] or 0) for key, v0 in items],
                dtype=np.int64,
            )
            pad = (-len(items)) % n_dev
            if pad:
                v0a = np.concatenate([v0a, np.repeat(v0a[-1:], pad)])
                v0b = np.concatenate([v0b, np.repeat(v0b[-1:], pad)])
            with telemetry.span("shard_put", windows=len(v0a)):
                v0a_d = jax.device_put(v0a, sharding)
                v0b_d = jax.device_put(v0b, sharding)
            telemetry.count("dispatches")
            with telemetry.span("gather_fetch"):
                out = telemetry.record_fetch(
                    jax.device_get(batch_kernels[pair](v0a_d, v0b_d))
                )
            for i, (key, _v0) in enumerate(items):
                outs[key] = tuple(o[i] for o in out)
        return outs

    return run_periodic(program, machine, max_share,
                        window_eval=window_eval)


def run_analytic_sharded(
    program: Program,
    machine: MachineConfig,
    mesh: jax.sharding.Mesh | None = None,
    batch: int | None = None,
    seed: int = 0,
    host_cutoff: int | None = None,
):
    """Analytic exact engine with every classify dispatch's key axis
    on the mesh (sampler/analytic.py::_classify_keys): each key's
    closed-form solve is independent, so GSPMD partitions the
    period/row-block mega-dispatches with no cross-device traffic and
    the positionally reassembled outputs — and hence the fits, the
    folds, everything downstream — are bit-identical to the
    single-device engine (tests/test_parallel.py). Nests under the
    host-fold cutoff stay on the host lexsort (no device work exists
    to shard there); pass host_cutoff=0 to force the sharded engine
    path."""
    mesh = mesh or _default_mesh()
    from ..sampler.analytic import run_analytic

    return run_analytic(program, machine, batch=batch, seed=seed,
                        mesh=mesh, host_cutoff=host_cutoff)


def run_exact_sharded(
    program: Program,
    machine: MachineConfig,
    mesh: jax.sharding.Mesh | None = None,
    max_share: int = 64,
):
    """The exact router (periodic -> analytic -> dense) with whichever
    engine it picks running mesh-sharded; `res.engine` records the
    choice, same contract as sampler/periodic.py::run_exact."""
    mesh = mesh or _default_mesh()
    from ..sampler.periodic import run_exact

    return run_exact(program, machine, max_share, mesh=mesh)


def run_dense_sharded(
    program: Program,
    machine: MachineConfig,
    mesh: jax.sharding.Mesh | None = None,
    max_share: int = 64,
):
    """Dense engine with the simulated-thread axis laid out on the mesh.

    Requires thread_num % mesh size == 0 (each device owns an equal
    slice of the vmapped tid batch axis). Returns the same OracleResult
    as sampler/dense.py::run_dense.
    """
    mesh = mesh or _default_mesh()
    n_dev = mesh.devices.size
    if machine.thread_num % n_dev != 0:
        raise ValueError(
            f"thread_num {machine.thread_num} not divisible by mesh size "
            f"{n_dev}; use build_mesh(n_devices=...) with a divisor"
        )
    sharding = NamedSharding(mesh, P(mesh.axis_names[0]))
    return run_dense(program, machine, max_share, tid_sharding=sharding)
