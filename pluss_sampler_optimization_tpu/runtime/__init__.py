from .hist import Hist, PRIState, hist_update, pow2_floor
from .cri import cri_distribute, nbd_spread
from .aet import aet_mrc
from . import report

__all__ = [
    "Hist",
    "PRIState",
    "hist_update",
    "pow2_floor",
    "cri_distribute",
    "nbd_spread",
    "aet_mrc",
    "report",
]
