"""AET: reuse-interval histogram -> LRU miss-ratio curve.

Port of `pluss_AET` (pluss_utils.h:758-804):

1. P(t) = fraction of reuses with interval > t (built by descending
   accumulation seeded with the cold-miss count at key -1, :772-780),
   P(0) := 1 (:781).
2. A fill-time sweep: a cursor t advances while the accumulated P mass
   (`sum_P`, repeated float addition) is below the cache size c; the
   miss ratio at c is P(prev_t) where prev_t is the last histogram key
   passed (:782-802). c ranges over [0, min(max_RT, cache lines)]
   with cache lines = 2560KB/8B = 327680 (:785-786).

Two evaluation paths produce bit-identical curves:
- `_mrc_literal`: the verbatim loop, O(max_RT) scalar adds.
- `_mrc_runs`: observes that between histogram keys the addend is
  constant, so the repeated-addition sequence is exactly a numpy cumsum
  per run (cumsum performs the same left-to-right float additions);
  crossings are then binary searches. Used when max_RT is large (the
  GEMM N=4096 histogram reaches max_RT ~ 2.7e8, where the literal loop
  is impractical).
"""

from __future__ import annotations

import numpy as np

from ..config import MachineConfig
from .hist import Hist

_RUN_CHUNK = 1 << 22


def _build_p(histogram: Hist):
    total = float(sum(histogram.values()))
    keys_desc = sorted((k for k in histogram), reverse=True)
    accumulate = histogram.get(-1, 0.0)
    P: dict[int, float] = {}
    for k in keys_desc:
        if k == -1:
            break
        P[k] = accumulate / total
        accumulate += histogram[k]
    P[0] = 1.0
    return P


def _mrc_literal(P: dict[int, float], max_rt: int, cs: int) -> np.ndarray:
    C = min(max_rt, cs)
    out = np.empty(C + 1, dtype=np.float64)
    sum_p = 0.0
    t = 0
    prev_t = 0
    for c in range(C + 1):
        while sum_p < c and t <= max_rt:
            if t in P:
                sum_p += P[t]
                prev_t = t
            else:
                sum_p += P[prev_t]
            t += 1
        out[c] = P[prev_t]
    return out


def _mrc_runs(P: dict[int, float], max_rt: int, cs: int) -> np.ndarray:
    C = min(max_rt, cs)
    out = np.empty(C + 1, dtype=np.float64)
    keys = sorted(P)
    # run j covers t in [keys[j], next_key) with addend P[keys[j]]
    run_starts = keys
    run_ends = keys[1:] + [max_rt + 1]  # exclusive
    c = 0
    sum_p = 0.0
    # t == 0 is always the first run start (P[0] exists)
    for k, t_end_full in zip(run_starts, run_ends):
        if k > max_rt:
            break
        t_end = min(t_end_full, max_rt + 1)
        q = P[k]
        t = k
        while t < t_end:
            blk = min(t_end - t, _RUN_CHUNK)
            arr = np.full(blk, q, dtype=np.float64)
            arr[0] += sum_p
            S = np.cumsum(arr)
            sum_p = float(S[-1])
            # every c <= floor(sum_p) has its stop condition (sum_p >= c
            # after an addition) satisfied inside this block, with
            # prev_t equal to this run's key -> miss ratio q.
            hi = min(int(np.floor(sum_p)), C)
            if hi >= c:
                out[c : hi + 1] = q
                c = hi + 1
            t += blk
            if c > C:
                break
        if c > C:
            break
    # cursor exhausted (t > max_rt) while sum_p still < c: the loop body
    # no longer advances and every remaining c reads P[prev_t] of the
    # last key <= max_rt.
    if c <= C:
        last_key = max((k for k in keys if k <= max_rt), default=0)
        out[c:] = P[last_key]
    return out


def aet_mrc(
    histogram: Hist, machine: MachineConfig, force: str | None = None
) -> np.ndarray:
    """Miss-ratio curve MRC[c] for c in [0, min(max_RT, cache lines)].

    Returns a dense float64 array; index = cache size in lines
    (pluss_utils.h:785-786).
    """
    if not histogram or sum(histogram.values()) == 0:
        return np.ones(1, dtype=np.float64)
    max_rt = max(histogram)
    if max_rt < 0:
        return np.ones(1, dtype=np.float64)
    cs = machine.cache_lines
    P = _build_p(histogram)
    use = force or ("literal" if max_rt <= 1 << 21 else "runs")
    if use == "literal":
        return _mrc_literal(P, max_rt, cs)
    return _mrc_runs(P, max_rt, cs)


def mrc_l1_error(a: np.ndarray, b: np.ndarray) -> float:
    """Mean absolute difference over the common support — the accuracy
    metric of BASELINE.json (MRC L1 error vs the serial oracle)."""
    n = min(len(a), len(b))
    if n == 0:
        return 0.0
    return float(np.mean(np.abs(a[:n] - b[:n])))
