"""Recorded serial-oracle baselines for the benchmark.

The reference's accuracy/speed protocol compares against its serial
C++ sampler run on the same workload (Makefile:39-41, README.md:10-12)
— but a full serial traversal of the north-star config (GEMM N=4096,
~2.6e11 accesses) takes the better part of an hour, far too slow to
re-measure inside every benchmark invocation. This module records one
native serial run — PRIState histograms, measured wall time, machine
config — into a JSON file under `baselines/` so bench.py can score
sampled-engine accuracy (MRC L1 error) and speedup against the stored
oracle. `tools/make_baseline.py` produces the files.
"""

from __future__ import annotations

import dataclasses
import gzip
import json
import os

from ..config import MachineConfig
from .hist import PRIState

BASELINE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "baselines",
)


def _tag_fields(machine: MachineConfig) -> tuple:
    # cache_kb deliberately excluded: it doesn't affect the serial run
    return (machine.thread_num, machine.chunk_size, machine.ds, machine.cls)


def baseline_path(model: str, n: int, machine: MachineConfig) -> str:
    tag = f"{model}{n}"
    if _tag_fields(machine) != _tag_fields(MachineConfig()):
        tag += f"-t{machine.thread_num}c{machine.chunk_size}" \
               f"d{machine.ds}l{machine.cls}"
    return os.path.join(BASELINE_DIR, f"{tag}.json.gz")


def state_to_json(state: PRIState) -> dict:
    return {
        "thread_num": state.thread_num,
        "bin_noshare": state.bin_noshare,
        "noshare": [
            {str(k): v for k, v in h.items()} for h in state.noshare
        ],
        "share": [
            {
                str(r): {str(k): v for k, v in h.items()}
                for r, h in per.items()
            }
            for per in state.share
        ],
    }


def state_from_json(d: dict) -> PRIState:
    return PRIState(
        thread_num=d["thread_num"],
        bin_noshare=d["bin_noshare"],
        noshare=[
            {int(k): float(v) for k, v in h.items()} for h in d["noshare"]
        ],
        share=[
            {
                int(r): {int(k): float(v) for k, v in h.items()}
                for r, h in per.items()
            }
            for per in d["share"]
        ],
    )


def save_baseline(
    model: str,
    n: int,
    machine: MachineConfig,
    serial_seconds: float,
    total_accesses: int,
    state: PRIState,
    path: str | None = None,
    conditions: dict | None = None,
) -> str:
    path = path or baseline_path(model, n, machine)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    doc = {
        "model": model,
        "n": n,
        "machine": dataclasses.asdict(machine),
        "serial_seconds": serial_seconds,
        "total_accesses": total_accesses,
        "engine": "native-serial",
        # measurement conditions (reps, per-rep times, load average):
        # a recorded wall time without them is not reproducible
        "conditions": conditions or {},
        "state": state_to_json(state),
    }
    with gzip.open(path, "wt") as f:
        json.dump(doc, f)
    return path


def load_baseline(
    model: str, n: int, machine: MachineConfig, path: str | None = None
) -> dict | None:
    """Stored baseline dict with `state` decoded, or None if absent or
    recorded under a different machine config.

    cache_kb is excluded from the config comparison (and from the file
    tag): the serial traversal's histograms and wall time don't depend
    on it — it only parameterizes the AET->MRC stage, which consumers
    compute fresh.
    """
    path = path or baseline_path(model, n, machine)
    if not os.path.exists(path):
        return None
    with gzip.open(path, "rt") as f:
        doc = json.load(f)

    def sans_cache(d: dict) -> dict:
        return {k: v for k, v in d.items() if k != "cache_kb"}

    if sans_cache(doc["machine"]) != sans_cache(dataclasses.asdict(machine)):
        return None
    doc["state"] = state_from_json(doc["state"])
    return doc
