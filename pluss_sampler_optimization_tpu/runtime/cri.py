"""Concurrent-reuse-interval (CRI) model — host-side post-processing.

Exact port of the reference's probabilistic model that converts
per-simulated-thread private reuse intervals into concurrent reuse
intervals for the interleaved machine:

- `nbd_spread` == `_pluss_cri_nbd` (pluss_utils.h:987-1009): a private
  interval of length n becomes n + K where K ~ NegativeBinomial(n, p),
  p = 1/thread_cnt — the other threads' interleaved accesses. GSL's
  `gsl_ran_negative_binomial_pdf(k, p, n)` is replaced by an exact
  log-gamma evaluation of the same pmf.
- `noshare_distribute` == `_pluss_cri_noshare_distribute`
  (pluss_utils.h:1010-1039).
- `racetrack` == `_pluss_cri_racetrack` (pluss_utils.h:1040-1131): for
  line-shared references, n = share_ratio racing threads split the
  spread interval across pow2 bins with
  P(2^{i-1} <= ri < 2^i) = (1 - 2^{i-1}/ri')^n - (1 - 2^i/ri')^n
  (:1080), remainder folded into the last bin (:1088-1093, including the
  reference's overwrite of the last computed bin).
- `cri_distribute` == `pluss_cri_distribute` (pluss_utils.h:1204-1208).

The r10 generated sampler carries slightly different local copies
(...rs-ri-opt-r10.cpp:42-131); `R10Quirks` reproduces them:
stop threshold 0.999 instead of 0.9999 (:60), point mass placed at
THREAD_NUM * pow2_floor(n) instead of THREAD_NUM * n (:49-51), racetrack
exponent n-1 instead of n (:105), and the share-path NBD call degenerating
to the point mass because `simulate_negative_binomial(1.0/THREAD_NUM,...)`
truncates its int parameter to thread_cnt=0 (:94), making
n >= (4000*(thread_cnt-1))/thread_cnt == -inf always true.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

from .hist import Hist, hist_update, pow2_floor
from .pristate_typing import PRIStateLike  # small protocol, avoids cycle


@dataclasses.dataclass(frozen=True)
class R10Quirks:
    """Behavior switches of the r10 local distribute copies."""

    stop_threshold: float = 0.999
    point_mass_pow2: bool = True
    share_exponent_minus_one: bool = True
    share_nbd_degenerate: bool = True


def negative_binomial_pmf(k: int, p: float, n: float) -> float:
    """pmf of GSL's negative binomial: C(n+k-1, k) p^n (1-p)^k.

    gsl_ran_negative_binomial_pdf(k, p, n) == Gamma(n+k)/(Gamma(k+1)Gamma(n))
    * p^n * (1-p)^k, evaluated in log space for stability.
    """
    if k < 0:
        return 0.0
    if 1.0 - p <= 0.0:
        return 1.0 if k == 0 else 0.0
    lg = (
        math.lgamma(n + k)
        - math.lgamma(k + 1.0)
        - math.lgamma(n)
        + n * math.log(p)
        + k * math.log1p(-p)
    )
    return math.exp(lg)


def nbd_spread(
    thread_cnt: int,
    n: int,
    thread_num: int,
    stop_threshold: float = 0.9999,
    point_mass_pow2: bool = False,
) -> Hist:
    """`_pluss_cri_nbd` (pluss_utils.h:987-1009).

    Note the point-mass key multiplies the *machine* THREAD_NUM macro,
    not the thread_cnt argument (pluss_utils.h:996) — kept verbatim.
    """
    dist: Hist = {}
    p = 1.0 / thread_cnt
    if n >= (4000.0 * (thread_cnt - 1)) / thread_cnt:
        base = pow2_floor(n) if point_mass_pow2 else n
        dist[thread_num * base] = 1.0
        return dist
    k = 0
    prob_sum = 0.0
    while True:
        prob = negative_binomial_pmf(k, p, float(n))
        prob_sum += prob
        dist[k + n] = dist.get(k + n, 0.0) + prob
        if prob_sum > stop_threshold:
            break
        k += 1
    return dist


def _racetrack_split(ri: int, exponent: float, cnt: float, rih: Hist,
                     in_log_format: bool = True) -> None:
    """The pow2 split loop (pluss_utils.h:1076-1097), ported verbatim —
    including float equality on prob_sum and the last-bin overwrite."""
    prob: dict[int, float] = {}
    prob_sum = 0.0
    i = 1
    while True:
        if 2.0**i > ri:
            break
        prob[i] = (1 - (2.0 ** (i - 1)) / ri) ** exponent - (
            1 - (2.0**i) / ri
        ) ** exponent
        prob_sum += prob[i]
        i += 1
        if prob_sum == 1.0:
            break
    if prob_sum != 1.0:
        prob[i - 1] = 1 - prob_sum
    for b, pb in prob.items():
        new_ri = int(2.0 ** (b - 1))  # (long)pow(2, b-1); b==0 -> 0 (:1095)
        hist_update(rih, new_ri, pb * cnt, in_log_format)


def noshare_distribute(
    merged: Hist,
    rih: Hist,
    thread_cnt: int,
    thread_num: int,
    quirks: Optional[R10Quirks] = None,
    in_log_format: bool = True,
) -> None:
    """`_pluss_cri_noshare_distribute` (pluss_utils.h:1010-1039) over an
    already-merged thread histogram; r10's local copy
    (no_share_distribute, ...rs-ri-opt-r10.cpp:65-84) via quirks +
    in_log_format=False."""
    stop = quirks.stop_threshold if quirks else 0.9999
    pm_pow2 = quirks.point_mass_pow2 if quirks else False
    for ri, cnt in merged.items():
        if ri < 0:
            hist_update(rih, ri, cnt, in_log_format)
            continue
        if thread_cnt > 1:
            dist = nbd_spread(thread_cnt, ri, thread_num, stop, pm_pow2)
            for ri2, p in dist.items():
                hist_update(rih, ri2, cnt * p, in_log_format)
        else:
            hist_update(rih, ri, cnt, in_log_format)


def racetrack(
    merged_share,
    rih: Hist,
    thread_cnt: int,
    thread_num: int,
    quirks: Optional[R10Quirks] = None,
    in_log_format: bool = True,
) -> None:
    """`_pluss_cri_racetrack` (pluss_utils.h:1040-1131); r10's local copy
    (share_distribute, ...rs-ri-opt-r10.cpp:85-131) via quirks."""
    stop = quirks.stop_threshold if quirks else 0.9999
    pm_pow2 = quirks.point_mass_pow2 if quirks else False
    for ratio, h in merged_share.items():
        n = float(ratio)
        exponent = n - 1 if (quirks and quirks.share_exponent_minus_one) else n
        for ri, cnt in h.items():
            if thread_cnt <= 1:
                hist_update(rih, ri, cnt, in_log_format)
                continue
            if quirks and quirks.share_nbd_degenerate:
                # r10 passes 1.0/THREAD_NUM as the int thread_cnt (:94),
                # so the n >= -inf guard always fires: point mass at
                # THREAD_NUM * pow2_floor(ri) (:48-52).
                dist = {thread_num * pow2_floor(ri): 1.0}
            else:
                dist = nbd_spread(thread_cnt, ri, thread_num, stop, pm_pow2)
            for ri2, p in dist.items():
                _racetrack_split(int(ri2), exponent, cnt * p, rih, in_log_format)


def cri_distribute(
    state: PRIStateLike,
    thread_cnt: int,
    thread_num: int,
    rih: Optional[Hist] = None,
) -> Hist:
    """`pluss_cri_distribute` (pluss_utils.h:1204-1208): noshare NBD
    spread + share racetrack, both into the global RI histogram.

    The merged histograms are iterated in sorted-key order. The
    reference iterates an unordered_map (no meaningful order), but
    float accumulation into the shared rih bins is not associative, so
    insertion-order iteration would make the MRC depend on which
    dispatch path built the state (serial per-ref, fused, sharded, or
    the cross-request batched runner — each decodes pairs in a
    different order). Canonical order makes the output a pure function
    of histogram CONTENT, which is what the batched-vs-solo
    bit-identity contract (tests/test_batching.py) pins.
    """
    if rih is None:
        rih = {}
    merged = dict(sorted(state.merged_noshare().items()))
    share = {
        ratio: dict(sorted(h.items()))
        for ratio, h in sorted(state.merged_share().items())
    }
    noshare_distribute(merged, rih, thread_cnt, thread_num)
    racetrack(share, rih, thread_cnt, thread_num)
    return rih


def r10_distribute(
    results, thread_num: int, quirks: Optional[R10Quirks] = None
) -> tuple[Hist, dict]:
    """The r10 main flow: per-reference local distributes with the r10
    quirk copies, raw-keyed (no_share_distribute + share_distribute into
    each per-ref histogram, ...rs-ri-opt-r10.cpp:666-693, 42-131), then
    a pow2-binned merge of the per-ref histograms into the global RI
    histogram (pluss_histogram_update default in_log_format,
    :3258-3276). Returns (merged RIHist, {ref name: per-ref Hist}).

    `results` are SampledRefResult (sampler/sampled.py): raw noshare
    and share values with the cold (-1) multiplicity, exactly what the
    per-ref samplers hold at their END_SAMPLE block (:666-693).
    """
    quirks = quirks if quirks is not None else R10Quirks()
    per_ref: dict = {}
    merged: Hist = {}
    for r in results:
        rih: Hist = {}
        nosh = dict(r.noshare)
        if r.cold:
            nosh[-1] = nosh.get(-1, 0.0) + r.cold
        noshare_distribute(
            nosh, rih, thread_num, thread_num, quirks, in_log_format=False
        )
        racetrack(
            r.share, rih, thread_num, thread_num, quirks, in_log_format=False
        )
        per_ref[r.name] = rih
        for k, v in rih.items():
            hist_update(merged, int(k), v, in_log_format=True)
    return merged, per_ref
