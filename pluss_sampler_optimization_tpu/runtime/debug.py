"""Debug tracing — the reference's -DDEBUG surfaces as first-class API.

The reference compiles per-access logging only in DEBUG builds
(Makefile:15 commented flag): chunk assignment and access traces
(...ri.cpp:94-121), reuse source->sink pairs above a threshold
(...ri.cpp prints pairs >= 512; ...rs-ri-opt-r10.cpp:538-543,566-568),
and a full-Iteration LAT map (...ri.cpp:50-52). Here the same
information is always available, computed from the closed-form trace:

- `access_trace`: one simulated thread's access stream in execution
  order (position, array, cache line, ref) — the DEBUG access log;
- `reuse_pairs`: every (source position, sink position, interval) pair
  with interval >= min_reuse — the DEBUG reuse log, produced by the
  same lexsort the dense engine uses rather than a hash walk;
- the sampled engine's per-sample surface is sampler/sampled.py::
  per_sample_ri (the r10 DEBUG print equivalent).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..config import MachineConfig
from ..core.trace import ProgramTrace
from ..ir import Program


@dataclasses.dataclass
class ReusePair:
    source_pos: int
    sink_pos: int
    reuse: int
    array: int
    line: int
    source_ref: str
    sink_ref: str


def access_trace(
    program: Program, machine: MachineConfig, tid: int, limit: int = 100,
    trace: ProgramTrace | None = None,
):
    """First `limit` accesses of one simulated thread, execution order.

    Returns rows of (position, array name, cache line, ref name) — the
    DEBUG access log (...ri.cpp:94-121). Pass a prebuilt `trace` to
    reuse the enumeration across calls (the CLI's trace mode does).
    """
    trace = trace or ProgramTrace(program, machine)
    pos, addr, arr, ref = trace.enumerate_tid(tid)
    order = np.argsort(pos, kind="stable")[:limit]
    _, _, names = trace.ref_global_tables()
    arrays = program.arrays
    return [
        (int(pos[i]), arrays[int(arr[i])], int(addr[i]), names[int(ref[i])])
        for i in order
    ]


def reuse_pairs(
    program: Program,
    machine: MachineConfig,
    tid: int,
    min_reuse: int = 512,
    limit: int = 1000,
    trace: ProgramTrace | None = None,
):
    """All same-line reuse pairs of one thread with interval >= min_reuse
    (the DEBUG 'src -> sink' log, ...ri.cpp reuse prints)."""
    trace = trace or ProgramTrace(program, machine)
    pos, addr, arr, ref = trace.enumerate_tid(tid)
    if len(pos) == 0:  # idle simulated thread (fewer chunks than tids)
        return []
    order = np.lexsort((pos, addr, arr))
    pos_s, addr_s, arr_s, ref_s = (
        pos[order], addr[order], arr[order], ref[order]
    )
    same = np.empty(len(pos_s), dtype=bool)
    same[0] = False
    same[1:] = (addr_s[1:] == addr_s[:-1]) & (arr_s[1:] == arr_s[:-1])
    reuse = np.where(same, pos_s - np.roll(pos_s, 1), -1)
    take = np.flatnonzero(same & (reuse >= min_reuse))[:limit]
    _, _, names = trace.ref_global_tables()
    return [
        ReusePair(
            source_pos=int(pos_s[i - 1]),
            sink_pos=int(pos_s[i]),
            reuse=int(reuse[i]),
            array=int(arr_s[i]),
            line=int(addr_s[i]),
            source_ref=names[int(ref_s[i - 1])],
            sink_ref=names[int(ref_s[i])],
        )
        for i in take
    ]


def format_reuse_pairs(pairs) -> list[str]:
    """'[reuse] source -> sink' lines (r10 DEBUG format, :566-568)."""
    return [
        f"[{p.reuse}] {p.source_ref}@{p.source_pos} -> "
        f"{p.sink_ref}@{p.sink_pos} (array {p.array}, line {p.line})"
        for p in pairs
    ]
