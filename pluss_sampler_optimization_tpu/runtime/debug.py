"""Debug tracing — the reference's -DDEBUG surfaces as first-class API.

The reference compiles per-access logging only in DEBUG builds
(Makefile:15 commented flag): chunk assignment and access traces
(...ri.cpp:94-121), reuse source->sink pairs above a threshold
(...ri.cpp prints pairs >= 512; ...rs-ri-opt-r10.cpp:538-543,566-568),
and a full-Iteration LAT map (...ri.cpp:50-52). Here the same
information is always available, computed from the closed-form trace:

- `access_trace`: one simulated thread's access stream in execution
  order (position, array, cache line, ref) — the DEBUG access log;
- `reuse_pairs`: (source position, sink position, interval) pairs with
  interval >= min_reuse — the DEBUG reuse log;
- the sampled engine's per-sample surface is sampler/sampled.py::
  per_sample_ri (the r10 DEBUG print equivalent).

Both functions stream the trace in windows of parallel-loop iterations
(the reference's DEBUG build likewise logs incrementally as the walk
advances), so memory stays bounded at any problem size: `reuse_pairs`
carries a vectorized last-access table (key -> last position) across
windows exactly like the reference's LAT hash maps persist across
iterations, and both stop enumerating once `limit` rows exist.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..config import MachineConfig
from ..core.trace import ProgramTrace
from ..ir import Program

_WINDOW_ACCESSES = 1 << 22  # ~128 MB of int64 columns per window
_ARR_SHIFT = 48  # composite key = array_id << 48 | cache line


@dataclasses.dataclass
class ReusePair:
    source_pos: int
    sink_pos: int
    reuse: int
    array: int
    line: int
    source_ref: str
    sink_ref: str


def _windows(trace: ProgramTrace, tid: int, max_accesses: int | None = None):
    """Yield (nest_index, m_lo, m_hi) covering the thread's stream in
    position order, each window bounded to ~_WINDOW_ACCESSES (or to
    `max_accesses` when the caller only consumes that many rows)."""
    cap = _WINDOW_ACCESSES if max_accesses is None else max_accesses
    for k, nt in enumerate(trace.nests):
        total_m = nt.schedule.local_count(tid)
        if total_m == 0:
            continue
        acc0 = max(1, int(nt.acc[0]))
        step = max(1, min(_WINDOW_ACCESSES, cap + acc0 - 1) // acc0)
        for m_lo in range(0, total_m, step):
            yield k, m_lo, min(total_m, m_lo + step)


def access_trace(
    program: Program, machine: MachineConfig, tid: int, limit: int = 100,
    trace: ProgramTrace | None = None,
):
    """First `limit` accesses of one simulated thread, execution order.

    Returns rows of (position, array name, cache line, ref name) — the
    DEBUG access log (...ri.cpp:94-121). Streams the trace window by
    window and stops as soon as `limit` rows are collected.
    """
    trace = trace or ProgramTrace(program, machine)
    _, _, names = trace.ref_global_tables()
    arrays = program.arrays
    rows: list[tuple[int, str, int, str]] = []
    for k, m_lo, m_hi in _windows(trace, tid, max_accesses=limit):
        pos, addr, arr, ref = trace.enumerate_tid_window(tid, k, m_lo, m_hi)
        order = np.argsort(pos, kind="stable")[: limit - len(rows)]
        rows.extend(
            (int(pos[i]), arrays[int(arr[i])], int(addr[i]), names[int(ref[i])])
            for i in order
        )
        if len(rows) >= limit:
            break
    return rows


def reuse_pairs(
    program: Program,
    machine: MachineConfig,
    tid: int,
    min_reuse: int = 512,
    limit: int = 1000,
    trace: ProgramTrace | None = None,
):
    """Same-line reuse pairs of one thread with interval >= min_reuse
    (the DEBUG 'src -> sink' log, ...ri.cpp reuse prints), in sink
    position order within each streamed window, first `limit` pairs."""
    trace = trace or ProgramTrace(program, machine)
    _, _, names = trace.ref_global_tables()
    pairs: list[ReusePair] = []
    # carried last-access table, sorted by key (the LAT_<array> maps)
    c_keys = np.zeros(0, dtype=np.int64)
    c_pos = np.zeros(0, dtype=np.int64)
    c_ref = np.zeros(0, dtype=np.int64)

    def emit(src_pos, src_ref, snk_pos, snk_ref, key):
        reuse = snk_pos - src_pos
        take = np.flatnonzero(reuse >= min_reuse)
        take = take[np.argsort(snk_pos[take], kind="stable")]
        for i in take[: limit - len(pairs)]:
            pairs.append(
                ReusePair(
                    source_pos=int(src_pos[i]),
                    sink_pos=int(snk_pos[i]),
                    reuse=int(reuse[i]),
                    array=int(key[i] >> _ARR_SHIFT),
                    line=int(key[i] & ((1 << _ARR_SHIFT) - 1)),
                    source_ref=names[int(src_ref[i])],
                    sink_ref=names[int(snk_ref[i])],
                )
            )

    cur_nest = -1
    for k, m_lo, m_hi in _windows(trace, tid):
        if k != cur_nest:
            # the reference clears every LAT after each parallel loop —
            # reuse never crosses a nest boundary (ir.py, Program docs)
            c_keys = np.zeros(0, dtype=np.int64)
            c_pos = np.zeros(0, dtype=np.int64)
            c_ref = np.zeros(0, dtype=np.int64)
            cur_nest = k
        pos, addr, arr, ref = trace.enumerate_tid_window(tid, k, m_lo, m_hi)
        if len(pos) == 0:
            continue
        if np.any(addr < 0):
            raise ValueError("negative cache-line address")
        key = (arr << _ARR_SHIFT) | addr
        order = np.lexsort((pos, key))
        k_s, p_s, r_s = key[order], pos[order], ref[order]
        same = np.empty(len(k_s), dtype=bool)
        same[0] = False
        same[1:] = k_s[1:] == k_s[:-1]
        # pairs inside this window + window-first occurrences that hit
        # the carried table, emitted together in sink-position order
        within = np.flatnonzero(same)
        srcs = [p_s[within - 1]]
        srcr = [r_s[within - 1]]
        snks = [p_s[within]]
        snkr = [r_s[within]]
        keys = [k_s[within]]
        first = np.flatnonzero(~same)
        if len(c_keys):
            slot = np.searchsorted(c_keys, k_s[first])
            hit = (slot < len(c_keys)) & (
                c_keys[np.minimum(slot, len(c_keys) - 1)] == k_s[first]
            )
            f, s = first[hit], slot[hit]
            srcs.append(c_pos[s])
            srcr.append(c_ref[s])
            snks.append(p_s[f])
            snkr.append(r_s[f])
            keys.append(k_s[f])
        emit(*map(np.concatenate, (srcs, srcr, snks, snkr, keys)))
        # merge window-last occurrences into the carried table
        last = np.flatnonzero(np.append(~same[1:], True))
        merged_keys = np.concatenate([k_s[last], c_keys])
        merged_pos = np.concatenate([p_s[last], c_pos])
        merged_ref = np.concatenate([r_s[last], c_ref])
        uniq, idx = np.unique(merged_keys, return_index=True)
        c_keys, c_pos, c_ref = uniq, merged_pos[idx], merged_ref[idx]
        if len(pairs) >= limit:
            break
    return pairs


def format_reuse_pairs(pairs) -> list[str]:
    """'[reuse] source -> sink' lines (r10 DEBUG format, :566-568)."""
    return [
        f"[{p.reuse}] {p.source_ref}@{p.source_pos} -> "
        f"{p.sink_ref}@{p.sink_pos} (array {p.array}, line {p.line})"
        for p in pairs
    ]
