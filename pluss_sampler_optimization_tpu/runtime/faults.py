"""Deterministic seeded fault injection for chaos-hardened serving.

Every hot path of the serving stack carries a NAMED injection site:

    engine_execute    service/executor.py — one engine attempt
    replica_dispatch  service/replicas.py — a replica worker picking
                      up one work item
    cache_load        service/cache.py — a disk-tier record read
    cache_store       service/cache.py — a disk-tier record write
    serve_line        service/api.py — one serve_jsonl request line
    worker_conn       service/fabric/router.py — one frame send on a
                      router->worker link
    worker_exec       service/fabric/worker.py — one request frame
                      received by a worker
    round_exec        sampler/sampled.py::run_sampled_progressive —
                      one progressive-precision round about to
                      execute (latency/hang here overruns a request
                      deadline mid-run, forcing the deterministic
                      partial_final path tools/check_chaos.py pins)

With no injector installed (the default), every site is a two-opcode
no-op — `fire()` returns on a single module-global None check, so the
fault layer is compiled in at zero cost (tier-1 pins MRC bytes
bit-identical with the layer present but disabled).

With an injector installed (config.FaultConfig via `install()` /
`install_from_file()`, CLI `--fault-spec FILE`), each occurrence of a
site draws a uniform from a COUNTER-HASH stream — a threefry-style
construction: u = mix(seed, site, rule, key, occurrence#) — so a
chaos run is exactly reproducible from (seed, spec) regardless of
thread interleaving: the per-(site, key) occurrence counters make a
request's fault decisions a function of its own attempt history, not
of what other threads did in between. Fault kinds:

    raise            the site raises FaultInjected
    compile_failure  the site raises CompileFault (an XLA-build-like
                     failure: retried/degraded like any engine error)
    latency          the site sleeps `latency_s` (default 50 ms)
    hang             the site sleeps `hang_s` (default 2 s) — sized to
                     exceed a per-attempt timeout, this is the replica
                     -hang scenario that drives hedged dispatch
    corrupt          cache_load only (`mangle()`): the parsed record
                     is replaced with one that fails validation, so
                     the loader's quarantine path fires
    disconnect       fabric sites: the site raises DisconnectFault —
                     the router treats it as a link failure (bounded
                     reconnect, then re-dispatch to the ring
                     successor), a worker abruptly drops its router
                     connection (the partition-blip scenario
                     tools/check_chaos.py pins)

The same module hosts the SEEDED retry jitter (`backoff_delay`):
deterministic exponential backoff whose jitter comes from the same
counter-hash stream, never from wall clock or `random` —
tools/lint_determinism.py lints `_mix`/`counter_u01`/`backoff_delay`
with the wallclock rules extended to perf_counter/monotonic, so a
wall-clock-jitter regression is caught while the seeded form passes.
"""

from __future__ import annotations

import collections
import json
import threading
import time

from ..config import FaultConfig
from . import lockwitness, telemetry


class FaultInjected(RuntimeError):
    """An injected fault (kind "raise"/"corrupt" at a raise site)."""


class CompileFault(FaultInjected):
    """An injected compile failure (kind "compile_failure")."""


class DisconnectFault(FaultInjected):
    """An injected connection drop (kind "disconnect" at the fabric
    sites): the catcher severs the affected socket instead of
    answering, exercising the reconnect/re-dispatch path."""


_MASK = (1 << 64) - 1


def _mix(x: int) -> int:
    """64-bit splitmix finalizer: the avalanche step of the counter
    hash. Pure integer arithmetic — platform- and hash-seed-free."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK
    return (x ^ (x >> 31)) & _MASK


def counter_u01(seed: int, *path) -> float:
    """Uniform in [0, 1) from (seed, path) — a keyed counter hash in
    the threefry spirit: the value is a pure function of the inputs,
    so any consumer replays exactly from them."""
    x = _mix(seed & _MASK)
    for part in path:
        if isinstance(part, str):
            for b in part.encode("utf-8"):
                x = _mix(x ^ b)
        else:
            x = _mix(x ^ (int(part) & _MASK))
    return _mix(x) / float(1 << 64)


def backoff_delay(attempt: int, base_s: float, max_s: float,
                  seed: int, *key) -> float:
    """Deterministic exponential backoff with seeded jitter.

    bound = min(max_s, base_s * 2^attempt); the returned delay is
    uniform in [bound/2, bound) drawn from the counter-hash stream
    keyed on (seed, "backoff", attempt, key) — same (seed, request,
    attempt) => same delay, every run."""
    bound = min(float(max_s), float(base_s) * (2.0 ** attempt))
    u = counter_u01(seed, "backoff", attempt, *key)
    return bound * (0.5 + 0.5 * u)


class FaultInjector:
    """Rule matcher + deterministic occurrence counters for one
    installed FaultConfig."""

    def __init__(self, config: FaultConfig):
        self.config = config
        self._lock = lockwitness.make_lock("FaultInjector._lock")
        # occurrences per (site, key): the counter component of the
        # (seed, site, rule, key, occurrence) draw
        self._occurrences: collections.Counter = collections.Counter()
        # fires per (rule index, key): enforces per-key max_fires
        self._fired: collections.Counter = collections.Counter()
        self._fired_by_kind: collections.Counter = collections.Counter()

    def stats(self) -> dict:
        with self._lock:
            by_kind = dict(self._fired_by_kind)
        return {
            "seed": self.config.seed,
            "rules": len(self.config.rules),
            "fired": sum(by_kind.values()),
            "fired_by_kind": by_kind,
        }

    def total_fired(self) -> int:
        with self._lock:
            return sum(self._fired_by_kind.values())

    def match(self, site: str, key, kinds=None, **ctx):
        """The rule that fires for this occurrence of `site`, or None.

        ONE occurrence counter tick per call (whether or not anything
        fires), so the decision stream is stable under retries and
        hedges: attempt k of request `key` at `site` always sees
        occurrence number k."""
        with self._lock:
            self._occurrences[(site, key)] += 1
            occurrence = self._occurrences[(site, key)]
        for idx, rule in enumerate(self.config.rules):
            if rule.get("site") != site:
                continue
            kind = rule.get("kind")
            if kinds is not None and kind not in kinds:
                continue
            match = rule.get("match") or {}
            if any(ctx.get(k) != v for k, v in match.items()):
                continue
            u = counter_u01(
                self.config.seed, site, idx, str(key), occurrence
            )
            if u >= rule.get("p", 1.0):
                continue
            max_fires = rule.get("max_fires", 0)
            with self._lock:
                if max_fires and self._fired[(idx, key)] >= max_fires:
                    continue
                self._fired[(idx, key)] += 1
                self._fired_by_kind[kind] += 1
            telemetry.count("faults_injected")
            telemetry.count(f"fault_{site}_{kind}")
            telemetry.event(
                "fault_injected", site=site, kind=kind, rule=idx,
                key=str(key), occurrence=occurrence,
            )
            return rule
        return None


_INSTALL_LOCK = lockwitness.make_lock("faults._INSTALL_LOCK")
_INJECTOR: FaultInjector | None = None


def install(config: FaultConfig) -> FaultInjector:
    """Install (replacing any previous) the process-global injector."""
    global _INJECTOR
    with _INSTALL_LOCK:
        _INJECTOR = FaultInjector(config)
        return _INJECTOR


def load_spec(path: str) -> FaultConfig:
    """Parse a `--fault-spec` JSON document into a FaultConfig."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError("fault spec must be a JSON object")
    unknown = set(doc) - {"seed", "rules"}
    if unknown:
        raise ValueError(
            f"unknown fault-spec fields: {', '.join(sorted(unknown))}"
        )
    return FaultConfig(seed=int(doc.get("seed", 0)),
                       rules=tuple(doc.get("rules", ())))


def install_from_file(path: str) -> FaultInjector:
    return install(load_spec(path))


def uninstall() -> None:
    global _INJECTOR
    with _INSTALL_LOCK:
        _INJECTOR = None


def get() -> FaultInjector | None:
    return _INJECTOR


def fire(site: str, key=None, **ctx) -> None:
    """Maybe inject at `site`. THE hot-path entry point: with no
    injector installed this is one global load + None check."""
    inj = _INJECTOR
    if inj is None:
        return
    rule = inj.match(
        site, key, kinds=("raise", "latency", "hang",
                          "compile_failure", "disconnect"), **ctx
    )
    if rule is None:
        return
    kind = rule["kind"]
    if kind == "latency":
        time.sleep(float(rule.get("latency_s", 0.05)))
        return
    if kind == "hang":
        # a hang is just a long sleep; the executor's per-attempt
        # timeout (and hedged dispatch) are what bound it
        time.sleep(float(rule.get("hang_s", 2.0)))
        return
    message = rule.get("message") or (
        f"injected {kind} fault at {site}"
    )
    if kind == "compile_failure":
        raise CompileFault(message)
    if kind == "disconnect":
        raise DisconnectFault(message)
    raise FaultInjected(message)


def mangle(site: str, record, key=None, **ctx):
    """Maybe corrupt a just-parsed cache record (kind "corrupt" at
    `site`); returns the record unchanged when nothing fires. The
    corrupted stand-in fails service/cache.py::validate_record, so
    the loader's corruption path (count + quarantine + recompute)
    fires exactly as it would for real on-disk damage."""
    inj = _INJECTOR
    if inj is None:
        return record
    rule = inj.match(site, key, kinds=("corrupt",), **ctx)
    if rule is None:
        return record
    if isinstance(record, dict):
        return dict(record, mrc="corrupted-by-fault-injection")
    return "corrupted-by-fault-injection"
