"""Host-side sparse histograms: the reference's L1 runtime as plain dicts.

Mirrors `Histogram = unordered_map<long,double>` (pluss_utils.h:25) and
the global state `_NoSharePRI[THREAD_NUM]` / `_SharePRI[THREAD_NUM]`
(pluss_utils.cpp:4-14) as a value object instead of globals. Device-side
dense histograms (ops/histogram.py) are converted to this form before
the CRI/AET stages.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

Hist = Dict[int, float]


def pow2_floor(x: int) -> int:
    """Highest power of two <= x, for x > 0.

    `_polybench_to_highest_power_of_two` (pluss_utils.h:665-679). The
    Rust port disagrees between its two runtimes (utils.rs:121-134
    rounds down, unsafe_utils.rs:227-230 rounds *up*); we follow the C++
    serial oracle (round down), per SURVEY.md section 7.
    """
    if x <= 0:
        raise ValueError("pow2_floor needs x > 0")
    return 1 << (x.bit_length() - 1)


def hist_update(h: Hist, key: int, cnt: float, in_log_format: bool = True) -> None:
    """`_pluss_histogram_update` (pluss_utils.h:680-689): pow2-bin keys > 0
    when in_log_format, accumulate."""
    if key > 0 and in_log_format:
        key = pow2_floor(key)
    h[key] = h.get(key, 0.0) + cnt


def merge_hists(hists, in_log_format: bool = False) -> Hist:
    out: Hist = {}
    for h in hists:
        for k, v in h.items():
            hist_update(out, k, v, in_log_format)
    return out


@dataclasses.dataclass
class PRIState:
    """Per-simulated-thread private-reuse histograms.

    noshare[tid]: Hist with pow2-binned keys (plus -1 for cold lines);
    share[tid]: {share_ratio: Hist with *raw* reuse keys} — the share
    update deliberately skips binning (pluss_utils.h:928-937) because the
    racetrack model needs raw interval lengths (pluss_utils.h:1060-1097).

    bin_noshare=False selects the runtime-v2 semantics: v2's noshare
    update drops the pow2 binning on insertion (`false` argument,
    pluss_utils_v2.h:915-918 vs v1 pluss_utils.h:924-927), keeping raw
    reuse keys everywhere.
    """

    thread_num: int
    noshare: list = dataclasses.field(default_factory=list)
    share: list = dataclasses.field(default_factory=list)
    bin_noshare: bool = True

    def __post_init__(self) -> None:
        if not self.noshare:
            self.noshare = [dict() for _ in range(self.thread_num)]
        if not self.share:
            self.share = [dict() for _ in range(self.thread_num)]

    def update_noshare(self, tid: int, reuse: int, cnt: float) -> None:
        """pluss_cri_noshare_histogram_update (pluss_utils.h:924-927;
        v2: pluss_utils_v2.h:915-918 via bin_noshare=False)."""
        hist_update(
            self.noshare[tid], reuse, cnt, in_log_format=self.bin_noshare
        )

    def update_share(self, tid: int, ratio: int, reuse: int, cnt: float) -> None:
        """pluss_cri_share_histogram_update (pluss_utils.h:928-937)."""
        h = self.share[tid].setdefault(ratio, {})
        hist_update(h, reuse, cnt, in_log_format=False)

    # -- merges used by the distribute/print stages -------------------------

    def merged_noshare(self) -> Hist:
        """Raw-key accumulate across threads (pluss_utils.h:1013-1022)."""
        return merge_hists(self.noshare, in_log_format=False)

    def merged_share(self):
        """{ratio: Hist} accumulated across threads (pluss_utils.h:1042-1058)."""
        out: Dict[int, Hist] = {}
        for per_tid in self.share:
            for ratio, h in per_tid.items():
                tgt = out.setdefault(ratio, {})
                for k, v in h.items():
                    tgt[k] = tgt.get(k, 0.0) + v
        return out

    def total_counts(self) -> float:
        s = 0.0
        for h in self.noshare:
            s += sum(h.values())
        for per_tid in self.share:
            for h in per_tid.values():
                s += sum(h.values())
        return s


def share_classify(reuse: int, threshold: int) -> bool:
    """True if the access is a cross-thread ("share") reuse.

    `distance_to(reuse,0) > distance_to(reuse,THRESH)`
    (...ri-omp-seq.cpp:203, distance_to at pluss_utils.h:703-708).
    """
    d0 = abs(reuse)
    dt = abs(reuse - threshold)
    return d0 > dt
