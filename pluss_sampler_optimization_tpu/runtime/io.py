"""Durable file writes shared by every JSON/text sidecar producer.

Several layers persist artifacts mid-run — telemetry exports
(runtime/telemetry.py), bench evidence sidecars (bench.py), the MRC
file writer (runtime/report.py, the reference's
pluss_write_mrc_to_file), and the service result store
(service/cache.py). A process killed mid-`write()` must never leave a
truncated file behind: a half-written JSON poisons every later
consumer that parses it blind (the service cache would treat it as a
corrupt entry and recompute; the driver's artifact collectors would
just fail). The discipline is the standard one — write the full
payload to a uniquely-named temp file in the SAME directory, fsync,
then `os.replace` onto the final name, which POSIX guarantees is
atomic within a filesystem.
"""

from __future__ import annotations

import json
import os
import tempfile


def atomic_write_text(path: str, text: str) -> None:
    """Write `text` to `path` atomically (tmp + fsync + rename).

    The temp name is unique per call (mkstemp), so concurrent writers
    of the same path never interleave — last rename wins with either
    writer's complete content, never a mix.
    """
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(
        dir=d, prefix=os.path.basename(path) + ".", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, obj, indent: int | None = 1) -> None:
    """Serialize `obj` and write it atomically with a trailing newline.

    Floats round-trip exactly (json uses repr, the shortest string
    that parses back to the same double), so a record written here and
    re-loaded compares bit-identical — the service cache's warm-repeat
    contract depends on this.
    """
    atomic_write_text(path, json.dumps(obj, indent=indent) + "\n")


def append_text_line(path: str, line: str) -> None:
    """Append one newline-terminated line durably (O_APPEND + fsync).

    The append-only consumers (the run ledger, runtime/obs/ledger.py)
    need the complement of atomic_write_text: many writers growing ONE
    file. A single os.write under O_APPEND is atomic with respect to
    concurrent appenders on POSIX local filesystems — two processes'
    rows never interleave — and a crash mid-write can at worst leave
    one truncated line at the tail, which every ledger reader already
    skips as invalid.
    """
    if not line.endswith("\n"):
        line += "\n"
    if "\n" in line[:-1]:
        raise ValueError("append_text_line takes exactly one line")
    data = line.encode()
    fd = os.open(
        os.fspath(path),
        os.O_WRONLY | os.O_CREAT | os.O_APPEND,
        0o644,
    )
    try:
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)
