"""Opt-in runtime lockdep witness for the serving runtime.

The static analyzer (analysis/concurrency/) predicts the lock-order
graph; this module observes the real one. Every lock in the threaded
modules is created through the factories here — `make_lock`,
`make_rlock`, `make_condition` — each tagged with the SAME name the
static analyzer derives ("RequestExecutor._lock",
"BatchScheduler._cv", "telemetry._lock", ...). Disabled (the
default), the factories return plain `threading` primitives: zero
wrappers, zero overhead, and the decision is made once at lock
creation, not per acquire.

Enabled (PLUSS_LOCK_WITNESS=1 in the environment, or `enable()`
before the objects under test are constructed), each acquire records
an edge held -> acquired into a global observed-order graph and
checks it against the edges seen so far: an acquire whose REVERSE
edge is already on record is a lock-order inversion — the runtime
proof of what C_LOCK_CYCLE detects statically. Releases track hold
times; holds longer than `long_hold_s` are kept as outliers (the
runtime twin of C_BLOCKING_UNDER_LOCK).

Nothing is emitted inline: recording telemetry from inside the
witness would route through the telemetry sinks' own locks and
perturb the very graph being observed. Callers pull `report()` at a
quiet point (the chaos gate does, after its seeds) and forward the
inversions/outliers to telemetry themselves — `emit_report()` does
both. `tools/check_chaos.py` then asserts observed ⊆ static and zero
inversions, closing the soundness loop the ISSUE asks for.
"""

from __future__ import annotations

import os
import threading
import time

__all__ = [
    "enable", "disable", "enabled", "reset",
    "make_lock", "make_rlock", "make_condition",
    "held_names", "observed_edges", "report", "emit_report",
]

_enabled = bool(os.environ.get("PLUSS_LOCK_WITNESS"))
_long_hold_s = float(
    os.environ.get("PLUSS_LOCK_WITNESS_LONG_HOLD_S", "0.2")
)
_MAX_RECORDS = 200  # inversion/outlier records kept (not counts)

# witness bookkeeping lock — a plain Lock, never itself witnessed
_STATE = threading.Lock()
_edges: dict = {}        # (held, acquired) -> count
_inversions: list = []   # [{edge, reverse_first_seen, thread}]
_inversion_count = 0
_holds: dict = {}        # name -> [count, total_s, max_s]
_long_holds: list = []   # [{name, held_s, thread}]
_long_hold_count = 0
_tls = threading.local()


def enabled() -> bool:
    return _enabled


def enable(long_hold_s: float | None = None) -> None:
    """Turn the witness on for locks created AFTER this call."""
    global _enabled, _long_hold_s
    _enabled = True
    if long_hold_s is not None:
        _long_hold_s = float(long_hold_s)


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Drop all observations (not the enabled flag)."""
    global _inversion_count, _long_hold_count
    with _STATE:
        _edges.clear()
        _inversions.clear()
        _holds.clear()
        _long_holds.clear()
        _inversion_count = 0
        _long_hold_count = 0


def _stack() -> list:
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


def held_names() -> tuple:
    """Witnessed locks the CURRENT thread holds right now (test
    probes assert sinks run with this empty of source locks)."""
    return tuple(name for name, _t0 in _stack())


def _record_acquire(name: str) -> None:
    global _inversion_count
    stack = _stack()
    held = [h for h, _t0 in stack if h != name]
    if held:
        with _STATE:
            for h in held:
                _edges[(h, name)] = _edges.get((h, name), 0) + 1
                if (name, h) in _edges:
                    _inversion_count += 1
                    if len(_inversions) < _MAX_RECORDS:
                        _inversions.append({
                            "edge": [h, name],
                            "reverse": [name, h],
                            "thread":
                                threading.current_thread().name,
                        })
    stack.append((name, time.perf_counter()))


def _record_release(name: str) -> None:
    global _long_hold_count
    stack = _stack()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][0] == name:
            _n, t0 = stack.pop(i)
            held_s = time.perf_counter() - t0
            with _STATE:
                slot = _holds.setdefault(name, [0, 0.0, 0.0])
                slot[0] += 1
                slot[1] += held_s
                slot[2] = max(slot[2], held_s)
                if held_s >= _long_hold_s:
                    _long_hold_count += 1
                    if len(_long_holds) < _MAX_RECORDS:
                        _long_holds.append({
                            "name": name,
                            "held_s": round(held_s, 6),
                            "thread":
                                threading.current_thread().name,
                        })
            return


class _WitnessLock:
    """Wrapper around Lock/RLock recording order + hold times."""

    def __init__(self, inner, name: str):
        self._inner = inner
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            _record_acquire(self.name)
        return got

    def release(self) -> None:
        _record_release(self.name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()


class _WitnessCondition:
    """Condition wrapper; wait() un-records the lock while the
    underlying condition has it released, so a thread parked in
    wait() never reads as holding the lock."""

    def __init__(self, name: str, lock=None):
        self._inner = threading.Condition(lock)
        self.name = name

    def acquire(self, *a, **kw):
        got = self._inner.acquire(*a, **kw)
        if got:
            _record_acquire(self.name)
        return got

    def release(self) -> None:
        _record_release(self.name)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: float | None = None):
        _record_release(self.name)
        try:
            return self._inner.wait(timeout)
        finally:
            _record_acquire(self.name)

    def wait_for(self, predicate, timeout: float | None = None):
        _record_release(self.name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            _record_acquire(self.name)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


def make_lock(name: str):
    return _WitnessLock(threading.Lock(), name) if _enabled \
        else threading.Lock()


def make_rlock(name: str):
    return _WitnessLock(threading.RLock(), name) if _enabled \
        else threading.RLock()


def make_condition(name: str, lock=None):
    return _WitnessCondition(name, lock) if _enabled \
        else threading.Condition(lock)


def observed_edges() -> set:
    """The observed lock-order graph as {(held, acquired)} name
    pairs — directly comparable to the static analyzer's
    AnalysisResult.edge_pairs()."""
    with _STATE:
        return set(_edges)


def report() -> dict:
    """Snapshot of everything observed. Pure read; emits nothing."""
    with _STATE:
        return {
            "enabled": _enabled,
            "edges": [
                {"src": a, "dst": b, "count": c}
                for (a, b), c in sorted(_edges.items())
            ],
            "inversions": list(_inversions),
            "inversion_count": _inversion_count,
            "long_holds": list(_long_holds),
            "long_hold_count": _long_hold_count,
            "long_hold_s": _long_hold_s,
            "holds": {
                name: {
                    "count": c,
                    "total_s": round(t, 6),
                    "max_s": round(m, 6),
                }
                for name, (c, t, m) in sorted(_holds.items())
            },
        }


def emit_report() -> dict:
    """report(), then forward inversions and long-hold outliers to
    telemetry — called at a quiet point, never from inside a lock."""
    from . import telemetry

    doc = report()
    for inv in doc["inversions"]:
        telemetry.event("lock_witness_inversion", **inv)
    for lh in doc["long_holds"]:
        telemetry.event("lock_witness_long_hold", **lh)
    if doc["inversion_count"]:
        telemetry.count("lock_witness_inversions",
                        doc["inversion_count"])
    return doc
