"""Observability subsystem: run ledger, exporters, drift monitoring.

Three longitudinal layers over the per-run telemetry in
runtime/telemetry.py (which only ever describes ONE run and vanishes
once its JSON is written):

- **ledger** — an append-only, schema-versioned JSONL ledger with one
  row per engine/service execution (fingerprint, engine, latency,
  cache disposition, degradation chain, compile-counter deltas, MRC
  digest), written from the service executor, the CLI modes, bench.py,
  and the drift monitor; validated/GC'd by tools/check_ledger.py and
  aggregated by the CLI `stats` mode.
- **exporters** — the Telemetry span tree as Chrome `trace_event` JSON
  (Perfetto / chrome://tracing) and the counters/gauges as Prometheus
  text exposition, behind the CLI `--trace-out` / `--metrics-out`
  flags (also importable as `telemetry.exporters`).
- **drift** — small-config sampled-vs-exact MRC audits (max/mean
  absolute miss-ratio delta) appended to the ledger and gated by
  tools/check_drift.py, so the executor's silent exact→sampled
  degradation has a continuously watched accuracy bound.
- **metrics** — the LIVE view: a process-global registry of counters,
  gauges, and rolling-window latency histograms fed by the same
  telemetry.count/gauge write path, scrapeable in Prometheus text
  format (`--metrics-port` / the serve `metrics` request).
- **slo** — the burn-rate sentinel over the registry windows and the
  ledger tail (latency p95, error/degradation budget, drift status,
  batch occupancy), emitting `slo_breach` events and gated offline by
  tools/check_slo.py.
- **recorder** — the flight recorder: a bounded ring of per-request
  records with tail-based retention (errors, degradations, drift
  breaches, latency outliers kept; the boring majority dropped), fed
  by the executor and the telemetry event sink, dumping atomic
  schema-versioned post-mortem bundles on anomaly triggers (SLO
  breach, request failure, replica quarantine, drift breach,
  perf regression, explicit dump_debug / SIGUSR2); validated offline
  by tools/check_bundle.py.
- **regress** — the performance regression sentinel: per-engine /
  per-stage latency and compile-count distributions across ledger
  history plus the BENCH_r*.json headline trajectory, flagged beyond
  a noise band; gated offline by tools/check_regression.py and
  evaluated live by the serve-mode SLO sentinel.

Everything here is observation only: with no ledger path and no export
flag nothing in this package executes, and engine results are pinned
bit-identical with observability enabled vs disabled
(tests/test_obs.py, tests/test_live_obs.py).
"""

from . import (
    drift, exporters, ledger, metrics, recorder, regress, slo,
)

__all__ = [
    "drift", "exporters", "ledger", "metrics", "recorder",
    "regress", "slo",
]
