"""Per-request utilization attribution: where did the wall-clock go?

The ledger records *that* a request took latency_s; the per-stage
timings record what we measured. This module turns those numbers into
a normalized accounting — per request, the wall time is partitioned
into executing / device-sync / queue+batch-wait / fetch /
unattributed fractions that sum to ~1.0 — so "the engine is busy",
"the device is idle", and "nobody knows" become three different,
scrapeable numbers instead of one opaque latency.

Three consumers:

- **schema-v2 ledger rows** gain an optional `utilization` block
  (built by `request_utilization`, validated by `validate_block` /
  ledger.validate_row, aggregated by `check_ledger --stats` into the
  `utilization:` line);
- **the live metrics registry** gets windowed gauges —
  `utilization_busy_fraction`, `utilization_device_idle_fraction`,
  `utilization_unattributed_fraction` — via `record_gauges` (written
  through `telemetry.gauge`, the one write path, so the per-run
  telemetry and the registry both see them). These feed the SLO
  sentinel and future autoscaling (ROADMAP item 4);
- **bench / the profiler gate** use `sample_breakdown` to map a
  profiler snapshot's span-attributed samples onto the same
  executing/sync/queue/unattributed partition (fractions over total
  samples, summing to 1.0 by construction).

The modeled bytes/FLOPs ride along when known (the kernel_roofline
accounting: bytes = samples * 25, flops = samples * (4*depth + 16)),
so a utilization block also answers "how much useful traffic did the
busy fraction move".
"""

from __future__ import annotations

_NUM = (int, float)

# Fraction keys of a utilization block, in partition order. They sum
# to ~1.0 (clamping + rounding leaves epsilon slack).
FRACTION_KEYS = (
    "executing_fraction", "sync_fraction", "queue_fraction",
    "fetch_fraction", "unattributed_fraction",
)

# Span-path fragments -> partition group for profiler samples. First
# match wins; a sample whose span path matches none of these (but is
# attributed) counts as executing — it was inside *some* known span.
_SAMPLE_GROUPS = (
    ("sync", ("fetch", "block", "gather")),
    ("queue", ("queue", "batch_wait", "admission")),
    ("executing", ()),  # any other attributed span
)


def _is_num(v) -> bool:
    return isinstance(v, _NUM) and not isinstance(v, bool)


def _frac(part, wall: float) -> float:
    if part is None or wall <= 0:
        return 0.0
    return min(1.0, max(0.0, float(part) / wall))


def request_utilization(wall_s, execute_s=None, queue_s=None,
                        batch_wait_s=None, fetch_s=None, sync_s=None,
                        compile_s=None, modeled_bytes=None,
                        modeled_flops=None) -> "dict | None":
    """Build one request's `utilization` ledger block from its stage
    seconds; None when wall_s is unusable (nothing to attribute).

    `sync_s` (device-sync time, recorded only under
    device_sync-enabled telemetry) is accounted as part of execute_s
    when both are present — the partition subtracts it from executing
    so the two fractions never double-count."""
    if not _is_num(wall_s) or wall_s <= 0:
        return None
    wall = float(wall_s)
    sync = float(sync_s) if _is_num(sync_s) else 0.0
    execute = float(execute_s) if _is_num(execute_s) else 0.0
    executing = max(0.0, execute - min(sync, execute))
    queue = (
        (float(queue_s) if _is_num(queue_s) else 0.0)
        + (float(batch_wait_s) if _is_num(batch_wait_s) else 0.0)
    )
    fetch = float(fetch_s) if _is_num(fetch_s) else 0.0
    block: dict = {"wall_s": round(wall, 6)}
    for key, v in (("execute_s", execute_s), ("queue_s", queue_s),
                   ("batch_wait_s", batch_wait_s),
                   ("fetch_s", fetch_s), ("sync_s", sync_s),
                   ("compile_s", compile_s)):
        if _is_num(v):
            block[key] = round(float(v), 6)
    fr_exec = _frac(executing, wall)
    fr_sync = _frac(sync, wall)
    fr_queue = _frac(queue, wall)
    fr_fetch = _frac(fetch, wall)
    # Stage timers can overlap slightly (each clock is read
    # independently); normalize so the partition is exact and the
    # fractions always sum to ~1.0 with unattributed >= 0.
    attributed = fr_exec + fr_sync + fr_queue + fr_fetch
    if attributed > 1.0:
        scale = 1.0 / attributed
        fr_exec *= scale
        fr_sync *= scale
        fr_queue *= scale
        fr_fetch *= scale
        attributed = 1.0
    block["executing_fraction"] = round(fr_exec, 6)
    block["sync_fraction"] = round(fr_sync, 6)
    block["queue_fraction"] = round(fr_queue, 6)
    block["fetch_fraction"] = round(fr_fetch, 6)
    block["unattributed_fraction"] = round(
        max(0.0, 1.0 - attributed), 6
    )
    # busy = the engine-execution share of the wall (sync included:
    # the device being waited on is still this request's work);
    # device-idle = everything that wasn't execution at all.
    block["busy_fraction"] = round(
        min(1.0, fr_exec + fr_sync), 6
    )
    block["device_idle_fraction"] = round(
        max(0.0, 1.0 - min(1.0, fr_exec + fr_sync)), 6
    )
    if _is_num(modeled_bytes):
        block["modeled_bytes"] = int(modeled_bytes)
    if _is_num(modeled_flops):
        block["modeled_flops"] = int(modeled_flops)
    return block


def validate_block(u) -> list[str]:
    """All schema violations of one `utilization` block (empty =
    valid); called from ledger.validate_row for rows that carry one,
    and by tools/check_profile.py on bench evidence."""
    errors: list[str] = []
    if not isinstance(u, dict):
        return ["'utilization' must be an object"]
    if not _is_num(u.get("wall_s")) or u.get("wall_s", -1) < 0:
        errors.append(
            "'utilization.wall_s' must be a non-negative number"
        )
    for key in ("execute_s", "queue_s", "batch_wait_s", "fetch_s",
                "sync_s", "compile_s"):
        if key in u and not _is_num(u[key]):
            errors.append(f"'utilization.{key}' must be a number")
    total = 0.0
    for key in FRACTION_KEYS + (
        "busy_fraction", "device_idle_fraction",
    ):
        v = u.get(key)
        if not _is_num(v) or not (0.0 <= v <= 1.0):
            errors.append(
                f"'utilization.{key}' must be a number in [0, 1]"
            )
        elif key in FRACTION_KEYS:
            total += v
    if not errors and not (0.98 <= total <= 1.02):
        errors.append(
            "utilization fractions must sum to ~1.0, got "
            f"{total:.4f}"
        )
    for key in ("modeled_bytes", "modeled_flops"):
        if key in u and (
            not isinstance(u[key], int) or isinstance(u[key], bool)
            or u[key] < 0
        ):
            errors.append(
                f"'utilization.{key}' must be a non-negative integer"
            )
    return errors


def record_gauges(block: "dict | None") -> None:
    """Mirror one request's utilization fractions into the telemetry
    write path (and so the live registry when metrics.enable() has
    run). Last-write gauges: the scrape sees the most recent
    request's attribution, the windows come from scrape cadence."""
    if not block:
        return
    from .. import telemetry

    telemetry.gauge(
        "utilization_busy_fraction", block["busy_fraction"]
    )
    telemetry.gauge(
        "utilization_device_idle_fraction",
        block["device_idle_fraction"],
    )
    telemetry.gauge(
        "utilization_unattributed_fraction",
        block["unattributed_fraction"],
    )


def _sample_group(span_path: str) -> str:
    from .profiler import UNATTRIBUTED

    if not span_path or span_path == UNATTRIBUTED:
        return "unattributed"
    leaf = span_path.rsplit("/", 1)[-1]
    for group, fragments in _SAMPLE_GROUPS:
        for frag in fragments:
            if frag in leaf:
                return group
    return "executing"


def sample_breakdown(snapshot: dict) -> dict:
    """Partition a profiler snapshot's samples into the
    executing/sync/queue/unattributed groups (fractions over total
    samples; they sum to 1.0 by construction since every sample lands
    in exactly one group). Grouping is by the span path's leaf stage
    name: fetch/block/gather -> sync, queue/batch_wait -> queue, any
    other known span -> executing, no span -> unattributed."""
    hz = float(snapshot.get("hz") or 1.0)
    groups = {"executing": 0, "sync": 0, "queue": 0,
              "unattributed": 0}
    for stack in snapshot.get("stacks", []):
        groups[_sample_group(stack.get("span", ""))] += int(
            stack.get("count", 0)
        )
    total = sum(groups.values())
    out = {
        "samples": total,
        "seconds": round(total / hz, 6) if hz > 0 else 0.0,
    }
    for name, c in groups.items():
        out[f"{name}_fraction"] = (
            round(c / total, 6) if total else 0.0
        )
        out[f"{name}_samples"] = c
    return out
