"""Accuracy-drift monitor: sampled-vs-exact MRC audits.

PLUSS's value is a *model* — sampled MRCs standing in for exact
locality analysis — and the service executor even degrades
exact→sampled silently under deadline pressure. Nothing watched the
model-quality side until now: this module runs small-config audits
pitting the sampled engine against the exact router (the same
engines the service dispatches), computes MRC error metrics over the
curve, appends them to the run ledger, and flags threshold breaches
as telemetry events. tools/check_drift.py is the CI gate (nonzero
exit on breach), exercised from tier-1.

Metrics: `max_abs_delta` (worst-case miss-ratio error at any cache
size) and `mean_abs_delta` (average over the common support). The
default thresholds are calibrated against the measured seed-0 CPU
values at the default audit configs (gemm/mvt n=48, ratio 0.3:
max_abs ≈ 0.135 / 0.050) with ~2.5x headroom, so the gate trips on a
real sampler regression, not on the known sampling noise floor.

Progressive-precision audits carry their OWN noise floor: a bootstrap
confidence band (sampler/confidence.py) around the sampled curve.
When an audit (or a replayed ledger row) carries `band_width`, the
breach verdict is `max_abs_delta > band_width` — the sampled curve
left its statistical uncertainty — instead of the global calibrated
thresholds. Band-less rows (every pre-progressive row) keep the
global path, so an old ledger re-evaluates byte-for-byte.
"""

from __future__ import annotations

import math
import time

from .. import telemetry
from . import ledger as obs_ledger

# Gate thresholds for one audit; see module docstring for calibration.
DRIFT_THRESHOLDS = {
    "max_abs_delta": 0.35,
    "mean_abs_delta": 0.05,
}

# Default audit matrix for tools/check_drift.py: gemm (the reference's
# anchor model) plus mvt (a non-gemm family with a different curve
# shape) — small enough that the pair audits in seconds on CPU.
DEFAULT_AUDIT_MODELS = ("gemm", "mvt")
DEFAULT_AUDIT_N = 48
DEFAULT_AUDIT_RATIO = 0.3


def mrc_drift_metrics(mrc_exact, mrc_sampled) -> dict:
    """Max/mean absolute miss-ratio delta over the common support.

    The curves may differ in length (the sampled histogram's support
    can be smaller); the comparison runs over the common prefix — the
    same convention as runtime/aet.py::mrc_l1_error — and both lengths
    are recorded so a support collapse is itself visible.
    """
    import numpy as np

    a = np.asarray(mrc_exact, dtype=np.float64)
    b = np.asarray(mrc_sampled, dtype=np.float64)
    m = min(len(a), len(b))
    if m == 0:
        return {
            "max_abs_delta": 1.0, "mean_abs_delta": 1.0,
            "support": 0, "len_exact": len(a), "len_sampled": len(b),
        }
    d = np.abs(a[:m] - b[:m])
    return {
        "max_abs_delta": round(float(d.max()), 6),
        "mean_abs_delta": round(float(d.mean()), 6),
        "support": m,
        "len_exact": int(len(a)),
        "len_sampled": int(len(b)),
    }


def breach_verdict(metrics: dict, thresholds: dict | None = None,
                   band_width=None) -> bool:
    """Whether one audit's error metrics constitute a breach.

    With a finite non-negative `band_width` (a progressive-precision
    run's bootstrap confidence band), the verdict is per-row:
    max_abs_delta beyond the band means the error exceeds what the
    band attributes to sampling noise. Otherwise — band-less rows,
    one-shot audits, and every row written before bands existed — the
    global DRIFT_THRESHOLDS apply unchanged (the migration contract
    tests/test_precision.py pins)."""
    if (isinstance(band_width, (int, float))
            and not isinstance(band_width, bool)
            and math.isfinite(float(band_width))
            and float(band_width) >= 0.0):
        return float(metrics["max_abs_delta"]) > float(band_width)
    thresholds = thresholds or DRIFT_THRESHOLDS
    return any(
        metrics[key] > limit for key, limit in thresholds.items()
    )


def row_breach(row: dict, thresholds: dict | None = None) -> bool:
    """Re-evaluate a ledger drift row's breach verdict: band-aware
    when the row carries `band_width`, global-threshold otherwise."""
    return breach_verdict(row, thresholds=thresholds,
                          band_width=row.get("band_width"))


def drift_audit(
    model: str,
    n: int = DEFAULT_AUDIT_N,
    ratio: float = DEFAULT_AUDIT_RATIO,
    seed: int = 0,
    machine=None,
    thresholds: dict | None = None,
    ledger_path: str | None = None,
    source: str = "drift",
    band_width: float | None = None,
) -> dict:
    """One sampled-vs-exact audit -> the ledger "drift" row (appended
    to `ledger_path` when given, returned either way).

    Reuses the production engines end to end: the exact side goes
    through the exact router (sampler/periodic.py::run_exact — the
    periodic/analytic/dense auto-route), the sampled side through
    run_sampled with a deterministic seed, and both fold through the
    same CRI + AET pipeline the service serves. A threshold breach is
    recorded in the row (`breach`, `ok`), counted
    (`drift_breach` telemetry counter) and emitted as a `drift_breach`
    telemetry event; tools/check_drift.py turns it into a nonzero
    exit.
    """
    from ...config import MachineConfig, SamplerConfig
    from ...models import build as build_model
    from ..aet import aet_mrc
    from ..cri import cri_distribute

    machine = machine if machine is not None else MachineConfig()
    thresholds = dict(thresholds or DRIFT_THRESHOLDS)
    program = build_model(model, n)
    T = machine.thread_num

    t0 = time.perf_counter()
    with telemetry.span("drift_audit", model=model, n=n):
        from ...sampler.periodic import run_exact
        from ...sampler.sampled import run_sampled

        with telemetry.span("drift_exact"):
            exact = run_exact(program, machine)
            mrc_exact = aet_mrc(
                cri_distribute(exact.state, T, T), machine
            )
        with telemetry.span("drift_sampled"):
            state, results = run_sampled(
                program, machine,
                SamplerConfig(ratio=ratio, seed=seed),
            )
            mrc_sampled = aet_mrc(cri_distribute(state, T, T), machine)
    metrics = mrc_drift_metrics(mrc_exact, mrc_sampled)
    breach = breach_verdict(metrics, thresholds,
                            band_width=band_width)
    row = {
        "kind": "drift",
        "source": source,
        "ok": not breach,
        "breach": breach,
        "model": model,
        "n": n,
        "ratio": ratio,
        "seed": seed,
        "engine_exact": getattr(exact, "engine", "exact"),
        "samples": int(sum(r.n_samples for r in results)),
        "latency_s": round(time.perf_counter() - t0, 6),
        "thresholds": thresholds,
        "mrc_digest_exact": obs_ledger.mrc_digest(mrc_exact),
        "mrc_digest_sampled": obs_ledger.mrc_digest(mrc_sampled),
        **metrics,
    }
    if band_width is not None:
        row["band_width"] = round(float(band_width), 6)
    # static per-model priors (analysis/bounds.py): the facts the
    # audit row lets an offline reader sanity-check BOTH curves
    # against (compulsory-miss floor, exact cold footprint) — and the
    # exact curve is cross-checked right here, so a drift audit also
    # gates the analyzer's own bounds
    try:
        from ... import analysis

        report = analysis.analyze_program(program, machine)
        row["static_priors"] = analysis.drift_priors(report)
        row["static_bounds_violations"] = analysis.check_static_bounds(
            report, mrc_exact, machine
        )
    except Exception as e:  # priors are advisory, never sink an audit
        row["static_priors"] = {"error": repr(e)}
    if breach:
        telemetry.count("drift_breach")
        telemetry.event(
            "drift_breach", model=model, n=n,
            max_abs_delta=metrics["max_abs_delta"],
            mean_abs_delta=metrics["mean_abs_delta"],
        )
    if ledger_path:
        row = obs_ledger.append(ledger_path, row)
    return row
