"""Telemetry exporters: Chrome trace_event JSON and Prometheus text.

The telemetry layer's own JSON export (Telemetry.to_json) is the
stable machine-readable record, but neither of the two standard
tool ecosystems reads it directly:

- **Chrome trace_event** (`chrome_trace_json` / CLI `--trace-out`) —
  the span tree as complete ("X") events loadable in Perfetto
  (ui.perfetto.dev) or chrome://tracing. Nesting is preserved exactly:
  each ROOT span gets its own `tid` track (spans from concurrent
  service threads never interleave on one track), children nest by
  timestamp containment within their root's track, and a span's
  device-sync measurement (`Span.block` under device_sync=True) rides
  in `args.sync_s`. Telemetry events become instant ("i") events on
  tid 0.
- **Prometheus text exposition** (`prometheus_lines` / CLI
  `--metrics-out`) — counters as `<prefix><name>_total` counter
  samples, numeric gauges as `<prefix><name>` gauges, plus the run
  duration; names are sanitized to the Prometheus grammar
  (`[a-zA-Z_:][a-zA-Z0-9_:]*`). The file form suits the node-exporter
  textfile collector; a serving wrapper can expose it on /metrics
  verbatim.

Both exporters accept either a live `Telemetry` object or an
already-exported telemetry JSON document (so saved
`--telemetry-out` files convert offline), and both are deterministic
functions of the run: exporting the same stopped run twice is
byte-identical (pinned by tests/test_obs.py).
"""

from __future__ import annotations

import json
import re

from ..io import atomic_write_text


def _doc(tele_or_doc) -> dict:
    """Normalize the input: a Telemetry object exports itself, a dict
    (a parsed --telemetry-out file) passes through."""
    if isinstance(tele_or_doc, dict):
        return tele_or_doc
    return tele_or_doc.to_json()


# -- Chrome trace_event ------------------------------------------------


def _span_events(span: dict, tid: int, out: list) -> None:
    ev: dict = {
        "name": span["name"],
        "cat": "span",
        "ph": "X",
        # trace_event timestamps are microseconds; floats are legal and
        # keep the containment exact (no rounding can push a child's
        # end past its parent's)
        "ts": round(span["start_s"] * 1e6, 3),
        "dur": round(span["wall_s"] * 1e6, 3),
        "pid": 1,
        "tid": tid,
    }
    args = dict(span.get("attrs") or {})
    if span.get("sync_s") is not None:
        args["sync_s"] = span["sync_s"]
    if args:
        ev["args"] = args
    out.append(ev)
    for child in span.get("children", ()):
        _span_events(child, tid, out)


def chrome_trace_events(tele_or_doc) -> list[dict]:
    """The run's spans/events as a trace_event list, in deterministic
    order (metadata, then spans in preorder per root, then instants).
    """
    doc = _doc(tele_or_doc)
    events: list[dict] = [{
        "name": "process_name",
        "ph": "M",
        "pid": 1,
        "args": {"name": "pluss"},
    }]
    # one tid per ROOT span: root trees come from thread-local stacks,
    # so siblings from different service threads may overlap in time —
    # on separate tracks the viewer (and the round-trip test) can rely
    # purely on timestamp containment for nesting
    for i, root in enumerate(doc.get("spans", []), start=1):
        events.append({
            "name": "thread_name",
            "ph": "M",
            "pid": 1,
            "tid": i,
            "args": {"name": f"{root['name']} #{i}"},
        })
    for i, root in enumerate(doc.get("spans", []), start=1):
        _span_events(root, i, events)
    for ev in doc.get("events", []):
        data = {k: v for k, v in ev.items() if k not in ("name", "t_s")}
        ie: dict = {
            "name": ev.get("name", "event"),
            "cat": "event",
            "ph": "i",
            "s": "g",
            "ts": round(float(ev.get("t_s", 0.0)) * 1e6, 3),
            "pid": 1,
            "tid": 0,
        }
        if data:
            ie["args"] = data
        events.append(ie)
    return events


def chrome_trace_json(tele_or_doc) -> dict:
    return {
        "traceEvents": chrome_trace_events(tele_or_doc),
        "displayTimeUnit": "ms",
        "otherData": {"exporter": "pluss_sampler_optimization_tpu"},
    }


def chrome_trace_text(tele_or_doc) -> str:
    """Serialized trace, deterministic bytes for a given run."""
    return json.dumps(
        chrome_trace_json(tele_or_doc), sort_keys=True, indent=1
    ) + "\n"


def write_chrome_trace(path: str, tele_or_doc) -> None:
    atomic_write_text(path, chrome_trace_text(tele_or_doc))


# -- Prometheus text exposition ----------------------------------------

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def prometheus_metric_name(name: str, prefix: str = "pluss_") -> str:
    """Sanitize an arbitrary telemetry counter/gauge name into the
    Prometheus metric-name grammar (invalid chars -> '_', leading
    digit guarded by the prefix)."""
    out = prefix + re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    assert _NAME_OK.match(out), out
    return out


def resolve_prometheus_names(pairs) -> dict:
    """Collision-safe name assignment. `pairs` is a list of
    (raw_key, base_name); returns {raw_key: unique_metric_name}.

    Two distinct telemetry names can sanitize to the same Prometheus
    name (e.g. "cache/hits" and "cache.hits" both become
    "pluss_cache_hits") — emitting both would silently overwrite one
    sample in the scraper. When a sanitized name is claimed by more
    than one raw key, the first key in sorted order keeps the base
    name and every other key gets a deterministic 8-hex suffix derived
    from its raw key, so the mapping is stable across processes and
    insertion orders."""
    import hashlib

    groups: dict = {}
    for raw, base in pairs:
        groups.setdefault(base, []).append(raw)
    out: dict = {}
    for base, raws in groups.items():
        if len(raws) == 1:
            out[raws[0]] = base
            continue
        for i, raw in enumerate(sorted(raws, key=repr)):
            if i == 0:
                out[raw] = base
            else:
                digest = hashlib.sha1(
                    repr(raw).encode()
                ).hexdigest()[:8]
                out[raw] = f"{base}_{digest}"
    return out


def prometheus_lines(tele_or_doc, prefix: str = "pluss_") -> list[str]:
    """Counters (as `*_total`), numeric gauges, and the run duration
    in text exposition format, sorted by metric name (deterministic
    bytes for a given run). Non-numeric gauges are skipped — the
    exposition format has no string samples. Sanitization collisions
    get deterministic suffixes (resolve_prometheus_names)."""
    doc = _doc(tele_or_doc)
    pairs: list = []
    for name in doc.get("counters", {}):
        pairs.append(
            (("counter", name),
             prometheus_metric_name(name, prefix) + "_total")
        )
    for name, value in doc.get("gauges", {}).items():
        if isinstance(value, bool) or not isinstance(
            value, (int, float)
        ):
            continue
        pairs.append(
            (("gauge", name), prometheus_metric_name(name, prefix))
        )
    names = resolve_prometheus_names(pairs)
    metrics: list[tuple[str, str, float]] = []
    for name, value in doc.get("counters", {}).items():
        metrics.append(
            (names[("counter", name)], "counter", float(value))
        )
    for name, value in doc.get("gauges", {}).items():
        if isinstance(value, bool) or not isinstance(
            value, (int, float)
        ):
            continue
        metrics.append((names[("gauge", name)], "gauge", float(value)))
    metrics.append(
        (prefix + "run_duration_seconds", "gauge",
         float(doc.get("duration_s", 0.0)))
    )
    lines: list[str] = []
    for name, mtype, value in sorted(metrics):
        lines.append(f"# TYPE {name} {mtype}")
        lines.append(f"{name} {value:g}")
    return lines


def prometheus_registry_lines(registry,
                              prefix: str = "pluss_") -> list[str]:
    """Live-registry exposition: counters (with `_total`), numeric
    gauges, and histograms (cumulative `_bucket{le=...}` series plus
    `_sum`/`_count`, with OpenMetrics exemplars where a trace id was
    recorded). Shares the sanitizer and the collision policy with the
    per-run exporter; accepts a MetricsRegistry or its snapshot()
    dict."""
    snap = (registry if isinstance(registry, dict)
            else registry.snapshot())
    pairs: list = []
    for name in snap.get("counters", {}):
        pairs.append(
            (("counter", name),
             prometheus_metric_name(name, prefix) + "_total")
        )
    for name, value in snap.get("gauges", {}).items():
        if isinstance(value, bool) or not isinstance(
            value, (int, float)
        ):
            continue
        pairs.append(
            (("gauge", name), prometheus_metric_name(name, prefix))
        )
    for name in snap.get("histograms", {}):
        pairs.append(
            (("histogram", name), prometheus_metric_name(name, prefix))
        )
    names = resolve_prometheus_names(pairs)

    blocks: list[tuple[str, list[str]]] = []
    for name, value in snap.get("counters", {}).items():
        out = names[("counter", name)]
        blocks.append((out, [f"# TYPE {out} counter",
                             f"{out} {float(value):g}"]))
    for name, value in snap.get("gauges", {}).items():
        if isinstance(value, bool) or not isinstance(
            value, (int, float)
        ):
            continue
        out = names[("gauge", name)]
        blocks.append((out, [f"# TYPE {out} gauge",
                             f"{out} {float(value):g}"]))
    for name, hist in snap.get("histograms", {}).items():
        out = names[("histogram", name)]
        body = [f"# TYPE {out} histogram"]
        exemplars = hist.get("exemplars", {})
        for le, cum in hist["buckets"].items():
            line = f'{out}_bucket{{le="{le}"}} {cum}'
            ex = exemplars.get(le)
            if ex is not None:
                line += (f' # {{trace_id="{ex[0]}"}}'
                         f" {float(ex[1]):g}")
            body.append(line)
        body.append(f"{out}_sum {float(hist['sum']):g}")
        body.append(f"{out}_count {int(hist['count'])}")
        blocks.append((out, body))

    lines: list[str] = []
    for _, body in sorted(blocks, key=lambda b: b[0]):
        lines.extend(body)
    return lines


def prometheus_text(tele_or_doc, prefix: str = "pluss_") -> str:
    return "\n".join(prometheus_lines(tele_or_doc, prefix)) + "\n"


def write_prometheus(path: str, tele_or_doc,
                     prefix: str = "pluss_") -> None:
    atomic_write_text(path, prometheus_text(tele_or_doc, prefix))
