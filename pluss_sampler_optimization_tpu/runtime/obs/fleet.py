"""Fleet telemetry: merge per-worker snapshots, back the fleet SLO
sentinel, and assemble cross-process traces.

The fabric router (service/fabric/router.py) polls each worker's
telemetry over `stats` wire frames; everything here is the pure-data
half of that loop — jax-free, socket-free functions the router, the
offline tools, and the tests share:

- **merge_registry_snapshots** — N MetricsRegistry.snapshot() dicts
  into one: counters/windows summed, histogram buckets summed
  bucket-by-bucket (every process uses the same default bucket edges,
  so cumulative semantics survive the sum), window quantiles taken as
  the max across workers (a merged quantile cannot be computed from
  quantiles; the max is the conservative fleet tail).
- **fleet_stats / fleet_metrics** — the `stats`/`metrics` control
  lines' fleet answers: per-worker sections verbatim (the
  single-process shapes, labeled by worker id) plus the numeric fleet
  sums, so `fleet == sum(workers)` is checkable instrument by
  instrument.
- **FleetView** — duck-types the MetricsRegistry read methods the SLO
  sentinel uses (histogram_fraction_over / histogram_quantile /
  counter_window), backed by the workers' pre-digested `slo_inputs`
  snapshots: violation fractions merge count-weighted, counters sum,
  quantiles take the fleet max. One sentinel then evaluates
  fleet-level burn rates with the unmodified runtime/obs/slo.py.
- **trace_index / assemble_chrome_trace** — join router rows (source
  ledger.ROUTER_SOURCE, carrying the `router` span block) with worker
  rows (source "service") on trace_id and emit one Chrome trace per
  request: the router track shows router_queue/route/wire_out/
  worker_rtt/wire_back, the worker track shows queue/batch_wait/
  execute inside the worker's own span. Every duration is a
  monotonic delta measured on ONE host; the worker track is placed
  INSIDE the router's RTT via the wire split (RTT - worker_s halved),
  so no cross-host clock agreement is ever assumed.
"""

from __future__ import annotations

import json

from . import ledger as obs_ledger


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def deep_sum(dicts) -> dict:
    """Recursive numeric merge of dicts: numeric leaves sum, nested
    dicts recurse, everything else (strings, lists, mixed types) is
    dropped — the result is exactly the summable part of the inputs,
    in first-seen key order."""
    keys: list = []
    for d in dicts:
        if isinstance(d, dict):
            for k in d:
                if k not in keys:
                    keys.append(k)
    out: dict = {}
    for k in keys:
        vals = [d[k] for d in dicts if isinstance(d, dict) and k in d]
        nums = [v for v in vals if _is_num(v)]
        subs = [v for v in vals if isinstance(v, dict)]
        if subs and not nums:
            out[k] = deep_sum(subs)
        elif nums and not subs:
            out[k] = sum(nums)
    return out


def _merge_histograms(hists: list) -> dict:
    """Merge RollingHistogram.snapshot() dicts: buckets (cumulative,
    shared edges) and sum/count add; the first exemplar per bucket is
    kept; window counts/sums add while window quantiles take the max
    across workers (the conservative fleet tail)."""
    out: dict = {"count": 0, "sum": 0.0, "buckets": {},
                 "exemplars": {}, "windows": {}}
    for h in hists:
        if not isinstance(h, dict):
            continue
        out["count"] += int(h.get("count") or 0)
        out["sum"] += float(h.get("sum") or 0.0)
        for le, cum in (h.get("buckets") or {}).items():
            out["buckets"][le] = out["buckets"].get(le, 0) + int(cum)
        for le, ex in (h.get("exemplars") or {}).items():
            out["exemplars"].setdefault(le, ex)
        for lbl, win in (h.get("windows") or {}).items():
            m = out["windows"].setdefault(lbl, {
                "count": 0, "sum": 0.0,
                "p50": None, "p95": None, "p99": None,
            })
            m["count"] += int(win.get("count") or 0)
            m["sum"] += float(win.get("sum") or 0.0)
            for q in ("p50", "p95", "p99"):
                v = win.get(q)
                if v is not None and (m[q] is None or v > m[q]):
                    m[q] = v
    return out


def merge_registry_snapshots(snapshots) -> dict:
    """N MetricsRegistry.snapshot() dicts -> one snapshot of the same
    shape (fit for exporters.prometheus_registry_lines): counters and
    counter windows summed, numeric gauges summed (non-numeric
    dropped), histograms merged bucket-by-bucket."""
    snaps = [s for s in snapshots if isinstance(s, dict)]
    counters: dict = {}
    counter_windows: dict = {}
    gauges: dict = {}
    hist_names: list = []
    for s in snaps:
        for name, v in (s.get("counters") or {}).items():
            if _is_num(v):
                counters[name] = counters.get(name, 0.0) + v
        for name, wins in (s.get("counter_windows") or {}).items():
            m = counter_windows.setdefault(name, {})
            for lbl, v in (wins or {}).items():
                if _is_num(v):
                    m[lbl] = m.get(lbl, 0.0) + v
        for name, v in (s.get("gauges") or {}).items():
            if _is_num(v):
                gauges[name] = gauges.get(name, 0.0) + v
        for name in (s.get("histograms") or {}):
            if name not in hist_names:
                hist_names.append(name)
    histograms = {
        name: _merge_histograms([
            (s.get("histograms") or {}).get(name) for s in snaps
        ])
        for name in hist_names
    }
    return {
        "counters": counters,
        "counter_windows": counter_windows,
        "gauges": gauges,
        "histograms": histograms,
    }


def fleet_stats(router_stats: dict, worker_snapshots: dict) -> dict:
    """The `stats` control line's fleet document: the router-local
    stats verbatim (role/counters/workers), each worker's own `stats`
    section under worker_stats (per-worker labels, single-process
    shapes), and the numeric fleet sums under `fleet` — so
    fleet == sum(workers) is checkable key by key."""
    workers: dict = {}
    for wid in sorted(worker_snapshots, key=str):
        snap = worker_snapshots[wid]
        if isinstance(snap, dict) and isinstance(
            snap.get("stats"), dict
        ):
            workers[str(wid)] = snap["stats"]
    out = dict(router_stats)
    out["worker_stats"] = workers
    out["fleet"] = {
        "workers": len(workers),
        "executor": deep_sum([
            w.get("executor") for w in workers.values()
        ]),
        "cache": deep_sum([w.get("cache") for w in workers.values()]),
    }
    return out


def fleet_metrics(own_snapshot: dict | None,
                  worker_snapshots: dict) -> dict:
    """The `metrics` control line's fleet document: the merged
    registry snapshot at the top level (the exact keys a
    single-process `metrics` response carries), the merged Prometheus
    exposition, and each worker's unmerged snapshot under `workers`.
    """
    from . import exporters

    per_worker: dict = {}
    for wid in sorted(worker_snapshots, key=str):
        snap = worker_snapshots[wid]
        m = snap.get("metrics") if isinstance(snap, dict) else None
        if isinstance(m, dict) and m.get("enabled", True):
            per_worker[str(wid)] = {
                k: v for k, v in m.items() if k != "prometheus"
            }
    merged = merge_registry_snapshots(
        ([own_snapshot] if own_snapshot is not None else [])
        + list(per_worker.values())
    )
    out: dict = {
        "enabled": bool(per_worker) or own_snapshot is not None,
        "fleet": {"workers": len(per_worker)},
    }
    out.update(merged)
    out["prometheus"] = "\n".join(
        exporters.prometheus_registry_lines(merged)
    ) + "\n"
    out["workers"] = per_worker
    return out


class FleetView:
    """The fleet as one registry, for the SLO sentinel.

    Duck-types exactly the MetricsRegistry read methods
    slo._registry_checks calls, backed by each live link's last
    `slo_inputs` snapshot (the worker pre-digests its own rolling
    windows — every number here was computed against a single
    process's monotonic clock):

    - histogram_fraction_over: count-weighted mean of the workers'
      violation fractions (the exact fleet fraction, since each
      worker reports fraction * its own observation count);
    - counter_window: sum across workers;
    - histogram_quantile: max across workers (quantiles don't merge;
      the max is the conservative fleet tail, reported as burn-check
      detail only).
    """

    def __init__(self, router):
        self.router = router

    def _inputs(self):
        for link in self.router.links:
            snap = link.last_snapshot
            si = (snap.get("slo_inputs")
                  if isinstance(snap, dict) else None)
            if isinstance(si, dict) and si.get("enabled"):
                yield si

    def _windows(self, label: str):
        for si in self._inputs():
            win = (si.get("windows") or {}).get(label)
            if isinstance(win, dict):
                yield win

    def histogram_fraction_over(self, name: str, label: str,
                                threshold: float, now=None):
        num = 0.0
        den = 0
        for win in self._windows(label):
            n = int(win.get("latency_count") or 0)
            frac = win.get("latency_frac_over")
            if n > 0 and frac is not None:
                num += float(frac) * n
                den += n
        return (num / den) if den else None

    def histogram_quantile(self, name: str, label: str, q: float,
                           now=None):
        if abs(q - 0.95) > 1e-9:
            return None
        vals = [win.get("latency_p95")
                for win in self._windows(label)]
        vals = [v for v in vals if v is not None]
        return max(vals) if vals else None

    def counter_window(self, name: str, label: str, now=None
                       ) -> float:
        return sum(
            float(win.get(name) or 0.0)
            for win in self._windows(label)
        )


# -- cross-process trace assembly --------------------------------------


def trace_index(rows) -> dict:
    """{trace_id: {"router": row | None, "workers": [rows]}} over
    parsed ledger rows. Router rows are the fabric.router-source
    request rows; worker rows are the fabric workers' "service" rows
    (worker_id stamped). The LAST router row per trace_id wins — a
    re-dispatched request writes one row per resolution attempt only
    at the final owner, so duplicates only arise from replayed
    ledgers."""
    out: dict = {}
    for row in rows:
        if row.get("kind") != "request":
            continue
        tid = row.get("trace_id")
        if not tid:
            continue
        slot = out.setdefault(tid, {"router": None, "workers": []})
        if row.get("source") == obs_ledger.ROUTER_SOURCE:
            slot["router"] = row
        elif row.get("worker_id") is not None:
            slot["workers"].append(row)
    return out


def _event(name: str, ts_s: float, dur_s: float, pid: int, tid: int,
           args: dict | None = None) -> dict:
    ev: dict = {
        "name": name, "cat": "span", "ph": "X",
        "ts": round(ts_s * 1e6, 3),
        "dur": round(max(0.0, dur_s) * 1e6, 3),
        "pid": pid, "tid": tid,
    }
    if args:
        ev["args"] = {k: v for k, v in args.items() if v is not None}
    return ev


def assemble_chrome_trace(router_row: dict,
                          worker_rows: list | None = None) -> dict:
    """One request's end-to-end Chrome trace from ledger rows alone.

    t=0 is the router's submit; the router track (pid 1) lays out
    router_queue -> route -> wire_out -> worker_rtt -> wire_back from
    the row's `router` span block, and the worker track (pid 2)
    places the worker's own span inside the RTT at wire_out's end,
    with the worker row's queue_s/batch_wait_s/execute_s stages
    nested inside. All placements are sums of single-host monotonic
    deltas — no timestamp from one host is ever compared with a
    timestamp from another.
    """
    rb = router_row.get("router") or {}

    def _f(key, default=0.0):
        v = rb.get(key)
        return float(v) if v is not None else default

    queue = _f("router_queue_s")
    route = _f("route_s")
    wire_out = _f("wire_out_s")
    rtt = _f("worker_rtt_s")
    worker_s = _f("worker_s",
                  default=max(0.0, rtt - 2 * wire_out))
    wire_back = _f("wire_back_s")
    total = float(router_row.get("latency_s") or (
        queue + route + rtt
    ))
    t_sent = queue + route
    t_worker = t_sent + wire_out

    events: list = [
        {"name": "process_name", "ph": "M", "pid": 1,
         "args": {"name": "router"}},
        {"name": "process_name", "ph": "M", "pid": 2,
         "args": {"name": "worker %s" % rb.get("worker_id")}},
    ]
    events.append(_event("request", 0.0, total, 1, 1, {
        "trace_id": router_row.get("trace_id"),
        "span_id": router_row.get("span_id"),
        "fingerprint": router_row.get("fingerprint"),
        "model": router_row.get("model"),
        "engine": router_row.get("engine_requested"),
        "ok": router_row.get("ok"),
        "cache": router_row.get("cache"),
        "hops": rb.get("hops"),
    }))
    events.append(_event("router_queue", 0.0, queue, 1, 2))
    events.append(_event("route", queue, route, 1, 2,
                         {"worker_id": rb.get("worker_id")}))
    events.append(_event("worker_rtt", t_sent, rtt, 1, 2))
    events.append(_event("wire_out", t_sent, wire_out, 1, 3))
    events.append(_event("wire_back", t_sent + rtt - wire_back,
                         wire_back, 1, 3))

    for i, wrow in enumerate(worker_rows or [], start=1):
        events.append(_event("worker", t_worker, worker_s, 2, i, {
            "worker_id": wrow.get("worker_id"),
            "span_id": wrow.get("span_id"),
            "cache": wrow.get("cache"),
            "coalesced": wrow.get("coalesced"),
            "latency_s": wrow.get("latency_s"),
        }))
        cursor = t_worker
        for stage in ("queue_s", "batch_wait_s", "execute_s"):
            v = wrow.get(stage)
            if v is None:
                continue
            events.append(_event(stage[:-2], cursor, float(v), 2, i))
            cursor += float(v)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "exporter": "pluss_sampler_optimization_tpu.fleet",
            "trace_id": router_row.get("trace_id"),
        },
    }


def assemble_traces(rows, trace_id: str | None = None) -> dict:
    """{trace_id: chrome_trace_doc} for every joinable trace in the
    rows (router row present), or just the one requested."""
    idx = trace_index(rows)
    out: dict = {}
    for tid in sorted(idx):
        if trace_id is not None and tid != trace_id:
            continue
        slot = idx[tid]
        if slot["router"] is None:
            continue
        out[tid] = assemble_chrome_trace(
            slot["router"], slot["workers"]
        )
    return out


def trace_text(doc: dict) -> str:
    """Deterministic bytes for one assembled trace."""
    return json.dumps(doc, sort_keys=True, indent=1) + "\n"
