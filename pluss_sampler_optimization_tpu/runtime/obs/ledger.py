"""Append-only run ledger: one JSONL row per engine/service execution.

Every run of the engines/service used to vanish once its per-run
telemetry JSON was written — no history, no regression signal across
runs. The ledger is the longitudinal record: the service executor, the
CLI acc/speed/sample modes, bench.py, and the drift monitor each
append one row per execution, all into one file, so a single artifact
answers "what ran, how fast, from which cache tier, degraded how, and
did the MRC change".

Row contract (LEDGER_VERSION, enforced by `validate_row` — the single
source of truth shared with tools/check_ledger.py, the same pattern as
service/cache.py::validate_record):

- every row: `ledger_version`, `ts` (unix seconds), `kind`
  ("request" | "drift" | "bench"), `source` (who wrote it), `ok`;
- kind "request" (service executor / CLI modes): `engine_requested`,
  `engine_used`, `model`, `n`, `latency_s`, `cache` disposition
  (null = direct run, "miss" = cold, "mem"/"disk" = warm tiers),
  `degraded` chain ([{from, to, reason}]), optional `fingerprint`
  (the service content address — CLI rows carry it too when the
  engine is service-addressable, so direct and served executions of
  the same request join on one key), optional `compile_delta`
  (nonzero jax compile-counter movement during the execution) and
  `mrc_digest`, and — for members of a cross-request batched
  execution — `batch_id`/`batch_members`, so joined executions stay
  auditable (the `stats` aggregate rolls them into batch occupancy
  and batched-vs-solo latency); service rows executed under a
  replica pool also carry `replica_id` (which device group served
  the execution — the aggregate's per-replica occupancy) and the
  full `request` payload (what ledger-driven warm start replays);
  rows written by a fabric worker process (service/fabric/) also
  carry `worker_id`, so one shared ledger shards by worker — the
  aggregate's `workers` rollup, with tools/check_ledger.py --stats
  validating rows land on their fingerprint's ring assignment;
- kind "drift" (runtime/obs/drift.py): the sampled-vs-exact MRC error
  metrics (`max_abs_delta` / `mean_abs_delta`) and the `breach` flag;
- kind "bench" (bench.py): the headline `metric`/`value` plus the same
  mrc_digest/latency fields.

Appends are durable single-write O_APPEND lines
(runtime/io.py::append_text_line): concurrent writers never interleave
and a crash leaves at most one truncated tail line, which every reader
here skips (and tools/check_ledger.py --gc removes). Rows are
validated BEFORE hitting the file — a writer bug fails loudly at the
call site, never poisons the ledger.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time

from ..io import append_text_line, atomic_write_text

# v2 (current): request rows gain the trace context (`trace_id`
# joining the row to its — possibly shared — execution span via
# `span_id`) plus per-stage timings (`queue_s`, `batch_wait_s`,
# `execute_s`) and the singleflight `coalesced` join count. All new
# fields are optional, so v1 rows written by older processes remain
# valid: readers accept every version in ACCEPTED_VERSIONS, writers
# stamp LEDGER_VERSION.
LEDGER_VERSION = 2
ACCEPTED_VERSIONS = (1, 2)

KINDS = ("request", "drift", "bench")

# `source` of the fabric router's OWN request rows
# (service/fabric/router.py): one per traced routed response, carrying
# the `router` span block (wire/queue overhead, owning worker) and
# joining the worker's "service" row on trace_id. Aggregation rolls
# them into the `fleet` section — NEVER into the request/engine stats,
# which would double-count every fabric-served request.
ROUTER_SOURCE = "fabric.router"

# numeric span fields a `router` block may carry (all optional and
# nullable; tools/assemble_trace.py turns them into Chrome trace spans)
ROUTER_SPANS = ("router_queue_s", "route_s", "wire_out_s",
                "worker_rtt_s", "wire_back_s", "wire_s", "worker_s")

# cache dispositions a request row may carry: None = direct engine run
# (no store in the path), "miss" = cold service execution, "mem" /
# "disk" = warm service tiers
CACHE_TIERS = (None, "miss", "mem", "disk")

_NUM = (int, float)


def mrc_digest(mrc) -> str:
    """16-hex digest of an MRC's float64 bytes.

    Bit-identical curves (the warm-repeat / exact-engine contract)
    digest identically; any numeric drift changes the digest. Used to
    make degraded or drifted responses attributable in the ledger
    without storing the (up to 327k-entry) curve itself.
    """
    import numpy as np

    a = np.ascontiguousarray(np.asarray(mrc, dtype=np.float64))
    return hashlib.sha256(a.tobytes()).hexdigest()[:16]


def _is_num(v) -> bool:
    return isinstance(v, _NUM) and not isinstance(v, bool)


def validate_row(row) -> list[str]:
    """All schema violations of one parsed ledger row (empty = valid).

    Single source of truth for `append` (validate-before-write) AND
    the offline checker (tools/check_ledger.py). Unknown extra keys
    are allowed — rows may carry source-specific context (reps,
    thresholds, evidence pointers) without a version bump.
    """
    errors: list[str] = []
    if not isinstance(row, dict):
        return ["row is not a JSON object"]
    if row.get("ledger_version") not in ACCEPTED_VERSIONS:
        errors.append(
            f"ledger_version must be one of {ACCEPTED_VERSIONS}, got "
            f"{row.get('ledger_version')!r}"
        )
    if not _is_num(row.get("ts")) or row.get("ts", -1) < 0:
        errors.append("'ts' must be a non-negative number")
    kind = row.get("kind")
    if kind not in KINDS:
        errors.append(f"'kind' must be one of {KINDS}, got {kind!r}")
    if not isinstance(row.get("source"), str) or not row.get("source"):
        errors.append("'source' must be a non-empty string")
    if not isinstance(row.get("ok"), bool):
        errors.append("'ok' must be a boolean")

    def need_str(key, nullable=False):
        v = row.get(key)
        if v is None and nullable:
            return
        if not isinstance(v, str):
            errors.append(f"'{key}' must be a string"
                          + (" or null" if nullable else ""))

    def need_num(key, nullable=False):
        v = row.get(key)
        if v is None and nullable:
            return
        if not _is_num(v):
            errors.append(f"'{key}' must be a number"
                          + (" or null" if nullable else ""))

    if kind == "request":
        need_str("engine_requested")
        need_str("engine_used", nullable=True)
        need_str("model")
        need_num("n")
        need_num("latency_s", nullable=True)
        if row.get("cache") not in CACHE_TIERS:
            errors.append(
                f"'cache' must be one of {CACHE_TIERS}, got "
                f"{row.get('cache')!r}"
            )
        if not isinstance(row.get("degraded"), list):
            errors.append("'degraded' must be a list")
        need_str("fingerprint", nullable=True)
        need_str("mrc_digest", nullable=True)
        if "compile_delta" in row and not isinstance(
            row["compile_delta"], dict
        ):
            errors.append("'compile_delta' must be an object")
        # batched executions join on these (service/executor.py):
        # optional — solo rows simply omit them
        if "batch_id" in row:
            need_str("batch_id", nullable=True)
        if "batch_members" in row:
            need_num("batch_members", nullable=True)
        # v2 trace context + per-stage timings: optional in both
        # versions (a v1 row never carries them; a v2 row may omit
        # stages that did not apply, e.g. batch_wait for solo runs)
        if "trace_id" in row:
            need_str("trace_id", nullable=True)
        if "span_id" in row:
            need_str("span_id", nullable=True)
        for stage in ("queue_s", "batch_wait_s", "execute_s"):
            if stage in row:
                need_num(stage, nullable=True)
        if "coalesced" in row:
            need_num("coalesced", nullable=True)
        # replica-pool context: which device group served the
        # execution, and the replayable request payload warm start
        # reads — optional, solo/poolless rows simply omit them
        if "replica_id" in row:
            need_num("replica_id", nullable=True)
        # fabric context: which worker process of a multi-process
        # fabric appended this row (service/fabric/) — optional,
        # single-process rows omit it. tools/check_ledger.py --stats
        # additionally validates rows shard by ring assignment
        if "worker_id" in row:
            need_num("worker_id", nullable=True)
        # the fabric router's span block (source fabric.router rows):
        # which worker the request was routed to plus the router-side
        # monotonic-delta spans assemble_trace joins on trace_id
        if "router" in row:
            rb = row["router"]
            if not isinstance(rb, dict):
                errors.append("'router' must be an object")
            else:
                for key in ("worker_id", "hops") + ROUTER_SPANS:
                    v = rb.get(key)
                    if v is not None and not _is_num(v):
                        errors.append(
                            f"'router.{key}' must be a number or null"
                        )
        if "request" in row and not isinstance(row["request"], dict):
            errors.append("'request' must be an object")
        # ir-preflight verdict (service/api.py static-analysis gate):
        # "ok" | "race" on served rows, "invalid" on rejection rows —
        # optional, rows from preflight-disabled services omit it
        if "preflight" in row:
            need_str("preflight", nullable=True)
        # resilience outcomes (service/executor.py): shed = refused at
        # the admission gate; hedged = a duplicate dispatch raced for
        # this row; retries = extra attempts spent. All optional —
        # quiet rows omit them, keeping their pre-resilience bytes
        for flag in ("shed", "hedged"):
            if flag in row and not isinstance(row[flag], bool):
                errors.append(f"'{flag}' must be a boolean")
        if "retries" in row:
            need_num("retries", nullable=True)
        # progressive precision (service/executor.py): rounds actually
        # run, the final bootstrap confidence-band width, and whether
        # the band converged (vs a deadline partial_final). All
        # optional — non-progressive rows keep their exact bytes
        if "rounds" in row:
            need_num("rounds", nullable=True)
        if "band_width" in row:
            need_num("band_width", nullable=True)
        if "converged" in row and not isinstance(
            row["converged"], bool
        ):
            errors.append("'converged' must be a boolean")
        # per-request utilization attribution block
        # (runtime/obs/attribution.py): optional — rows written
        # without the attribution layer keep their exact shape
        if "utilization" in row and row["utilization"] is not None:
            from .attribution import validate_block

            errors.extend(validate_block(row["utilization"]))
    elif kind == "drift":
        need_str("model")
        need_num("n")
        need_num("max_abs_delta")
        need_num("mean_abs_delta")
        if not isinstance(row.get("breach"), bool):
            errors.append("'breach' must be a boolean")
        # progressive-precision audits may judge against their own
        # confidence band (runtime/obs/drift.py::breach_verdict) —
        # optional, band-less rows stay valid unchanged
        if "band_width" in row:
            need_num("band_width", nullable=True)
    elif kind == "bench":
        need_str("metric")
        need_num("value")
    return errors


def append(path: str, row: dict) -> dict:
    """Stamp, validate, and durably append one row; returns the row.

    Stamps `ledger_version` and `ts` when absent. Raises ValueError on
    an invalid row — writers that must never fail a request wrap this
    (service/executor.py counts `service_ledger_write_failed`).
    """
    row = dict(row)
    row.setdefault("ledger_version", LEDGER_VERSION)
    row.setdefault("ts", round(time.time(), 3))
    errors = validate_row(row)
    if errors:
        raise ValueError(
            "invalid ledger row: " + "; ".join(errors)
        )
    append_text_line(
        path, json.dumps(row, sort_keys=True, separators=(",", ":"))
    )
    return row


def iter_rows(path: str):
    """Yield (line_no, row | None, error | None) per non-blank line.

    Unparseable or schema-invalid lines come back with row=None and
    the reason — readers decide whether to skip (stats) or report
    (the checker). Never raises on content, only on an unreadable
    file.
    """
    with open(path) as f:
        for line_no, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError as e:
                yield line_no, None, f"invalid JSON: {e}"
                continue
            errors = validate_row(row)
            if errors:
                yield line_no, None, "; ".join(errors)
                continue
            yield line_no, row, None


def read_rows(path: str) -> list[dict]:
    """All valid rows, in file order (invalid lines skipped)."""
    return [row for _ln, row, _err in iter_rows(path) if row is not None]


def tail(path: str, n: int = 5) -> list[dict]:
    """The last n valid rows (empty list for a missing ledger)."""
    try:
        rows = read_rows(path)
    except OSError:
        return []
    return rows[-n:] if n > 0 else []


# -- scan / compaction (tools/check_ledger.py + serve-mode GC) ---------


def scan(path: str, max_age_days: float = 0.0,
         max_rows: int = 0, now: float | None = None) -> dict:
    """Classify every ledger line for compaction.

    Returns {"valid": [rows...], "invalid": [(line_no, error)],
    "stale": [rows...], "surplus": [rows...]} — stale (older than
    max_age_days, 0 = no age limit) and surplus (beyond the newest
    max_rows, 0 = unbounded) rows are valid rows that `compact` would
    drop. Single source of truth shared by tools/check_ledger.py and
    the serve-mode background GC.
    """
    out: dict = {"valid": [], "invalid": [], "stale": [], "surplus": []}
    if now is None:
        now = time.time()
    max_age_s = max_age_days * 86400.0
    fresh: list = []
    for line_no, row, error in iter_rows(path):
        if row is None:
            out["invalid"].append((line_no, error))
            continue
        if max_age_s > 0 and (now - float(row["ts"])) > max_age_s:
            out["stale"].append(row)
            continue
        fresh.append(row)
    if max_rows > 0 and len(fresh) > max_rows:
        out["surplus"] = fresh[: len(fresh) - max_rows]
        fresh = fresh[len(fresh) - max_rows:]
    out["valid"] = fresh
    return out


def compact(path: str, max_age_days: float = 0.0,
            max_rows: int = 0) -> dict:
    """Atomically rewrite the ledger keeping only valid, fresh rows.

    The scan classifies; when anything would be dropped, the kept rows
    are rewritten via atomic_write_text (tmp + fsync + rename), so a
    reader — or a concurrent appender racing the rename — always sees
    a complete file. Returns the scan dict with a "dropped" count
    added (0 = the file was already clean and was left untouched).
    """
    s = scan(path, max_age_days=max_age_days, max_rows=max_rows)
    dropped = (
        len(s["invalid"]) + len(s["stale"]) + len(s["surplus"])
    )
    if dropped:
        atomic_write_text(path, "".join(
            json.dumps(row, sort_keys=True, separators=(",", ":"))
            + "\n"
            for row in s["valid"]
        ))
    s["dropped"] = dropped
    return s


class LedgerGC:
    """Serve-mode background ledger compaction on a fixed interval.

    Soak runs append one row per request; without a bound the ledger
    grows past what `tail`/`aggregate` readers can usefully scan. This
    thread runs `compact(path, max_age_days, max_rows)` every
    `interval_s` seconds, counting each pass into telemetry (and so
    the live registry): `ledger_gc_runs`, and `ledger_gc_dropped` when
    rows were actually removed. A failing pass counts
    `ledger_gc_failed` and never takes the serving loop down.
    """

    def __init__(self, path: str, interval_s: float = 60.0,
                 max_rows: int = 0, max_age_days: float = 0.0):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.path = path
        self.interval_s = float(interval_s)
        self.max_rows = int(max_rows)
        self.max_age_days = float(max_age_days)
        self.last_scan: dict | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def run_once(self) -> dict:
        """One compaction pass (also the final flush on close)."""
        from .. import telemetry

        s = compact(self.path, max_age_days=self.max_age_days,
                    max_rows=self.max_rows)
        self.last_scan = s
        telemetry.count("ledger_gc_runs")
        if s["dropped"]:
            telemetry.count("ledger_gc_dropped", s["dropped"])
            telemetry.event(
                "ledger_gc", path=self.path, dropped=s["dropped"],
                kept=len(s["valid"]),
            )
        return s

    def _loop(self) -> None:
        from .. import telemetry

        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception:
                telemetry.count("ledger_gc_failed")

    def start(self) -> "LedgerGC":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="pluss-ledger-gc", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


# -- aggregation (the CLI `stats` mode) --------------------------------


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def aggregate(rows: list[dict]) -> dict:
    """Roll a ledger up into the per-engine serving picture: request
    counts, p50/p95 latency, cache-tier hit rates, degradation and
    failure counts, plus the latest drift metrics per (model, n) and
    the bench row count."""
    requests: dict = {}
    drift: dict = {}
    bench = 0
    by_kind: dict = {}
    batches: dict = {}
    lat_batched: list[float] = []
    lat_solo: list[float] = []
    # unified service counters (the ledger view of the same numbers
    # the executor's `stats` snapshot and the Prometheus export
    # report): one row per non-coalesced submit, plus the row's
    # `coalesced` count for singleflight joiners
    service = {"submitted": 0, "coalesced": 0, "completed": 0,
               "failed": 0, "degraded": 0, "preflight_rejected": 0,
               "race_flagged": 0, "shed": 0, "retried": 0,
               "hedged": 0}
    # per-replica occupancy at execution grain: one request row per
    # served execution, grouped by the replica that ran it — the
    # ledger face of the executor's `replicas` snapshot and the
    # requests_routed_r* counters
    replicas: dict = {}
    # per-fabric-worker rollup: a shared ledger written by N worker
    # processes (service/fabric/) shards by worker_id; this is the
    # offline face of the router's per-link dispatch counters
    workers: dict = {}
    # the router's OWN rows (source fabric.router): per-worker routed
    # share + wire/queue overhead percentiles. They describe the same
    # requests the worker rows do, so they are rolled up HERE and
    # excluded from every request/engine/service stat below — counting
    # them there would double every fabric-served request
    fleet_workers: dict = {}
    fleet_wire: list[float] = []
    fleet_overhead: list[float] = []
    for row in rows:
        kind = row["kind"]
        by_kind[kind] = by_kind.get(kind, 0) + 1
        if kind == "request" and row.get("source") == ROUTER_SOURCE:
            rb = row.get("router") or {}
            wid = rb.get("worker_id")
            f = fleet_workers.setdefault(
                int(wid) if wid is not None else -1,
                {"rows": 0, "ok": 0, "redispatched": 0},
            )
            f["rows"] += 1
            if row["ok"]:
                f["ok"] += 1
            if rb.get("hops"):
                f["redispatched"] += 1
            if rb.get("wire_s") is not None:
                fleet_wire.append(float(rb["wire_s"]))
            parts = [rb.get("router_queue_s"), rb.get("route_s"),
                     rb.get("wire_s")]
            if any(p is not None for p in parts):
                fleet_overhead.append(
                    sum(float(p) for p in parts if p is not None)
                )
            continue
        if kind == "request":
            if row.get("source") == "service":
                joiners = int(row.get("coalesced") or 0)
                service["submitted"] += 1 + joiners
                service["coalesced"] += joiners
                # shed rows are neither completions nor failures: the
                # service refused the work at the admission gate (the
                # same three-way split the executor's live counters
                # report)
                if row.get("shed"):
                    service["shed"] += 1
                else:
                    service[
                        "completed" if row["ok"] else "failed"
                    ] += 1
                if row.get("degraded"):
                    service["degraded"] += 1
                service["retried"] += int(row.get("retries") or 0)
                if row.get("hedged"):
                    service["hedged"] += 1
                pf = row.get("preflight")
                if pf == "invalid":
                    service["preflight_rejected"] += 1
                elif pf == "race":
                    service["race_flagged"] += 1
            rid = row.get("replica_id")
            if rid is not None:
                r = replicas.setdefault(
                    int(rid), {"rows": 0, "ok": 0, "degraded": 0}
                )
                r["rows"] += 1
                if row["ok"]:
                    r["ok"] += 1
                if row.get("degraded"):
                    r["degraded"] += 1
            wid = row.get("worker_id")
            if wid is not None:
                w = workers.setdefault(int(wid), {
                    "rows": 0, "ok": 0, "degraded": 0,
                    "latencies": [],
                    "cache": {"mem": 0, "disk": 0, "miss": 0,
                              "direct": 0},
                })
                w["rows"] += 1
                if row["ok"]:
                    w["ok"] += 1
                if row.get("degraded"):
                    w["degraded"] += 1
                if row.get("latency_s") is not None:
                    w["latencies"].append(float(row["latency_s"]))
                wtier = row.get("cache")
                w["cache"][wtier if wtier else "direct"] += 1
            bid = row.get("batch_id")
            if bid is not None:
                b = batches.setdefault(bid, {"rows": 0, "members": 0})
                b["rows"] += 1
                b["members"] = max(
                    b["members"], int(row.get("batch_members") or 0)
                )
            # cold executions only: warm tiers would swamp the
            # batched-vs-solo latency comparison
            if row["ok"] and row.get("cache") == "miss" and (
                row.get("latency_s") is not None
            ):
                (lat_batched if bid is not None
                 else lat_solo).append(float(row["latency_s"]))
            eng = row["engine_requested"]
            agg = requests.setdefault(eng, {
                "count": 0, "ok": 0, "failed": 0, "degraded": 0,
                "latencies": [], "busy_fractions": [],
                "unattributed_fractions": [],
                "cache": {"mem": 0, "disk": 0, "miss": 0, "direct": 0},
            })
            util = row.get("utilization")
            if isinstance(util, dict):
                bf = util.get("busy_fraction")
                uf = util.get("unattributed_fraction")
                if isinstance(bf, (int, float)):
                    agg["busy_fractions"].append(float(bf))
                if isinstance(uf, (int, float)):
                    agg["unattributed_fractions"].append(float(uf))
            agg["count"] += 1
            if row["ok"]:
                agg["ok"] += 1
            else:
                agg["failed"] += 1
            if row.get("degraded"):
                agg["degraded"] += 1
            if row.get("latency_s") is not None:
                agg["latencies"].append(float(row["latency_s"]))
            tier = row.get("cache")
            agg["cache"][tier if tier else "direct"] += 1
        elif kind == "drift":
            # latest row wins per (model, n): the monitor's current view
            drift[(row["model"], row["n"])] = row
        elif kind == "bench":
            bench += 1
    for agg in requests.values():
        lats = sorted(agg.pop("latencies"))
        agg["p50_latency_s"] = round(_percentile(lats, 0.50), 6)
        agg["p95_latency_s"] = round(_percentile(lats, 0.95), 6)
        warm = agg["cache"]["mem"] + agg["cache"]["disk"]
        served = warm + agg["cache"]["miss"]
        agg["cache_hit_rate"] = (
            round(warm / served, 3) if served else None
        )
        # utilization attribution rollup: mean busy + tail
        # unattributed per engine (rows without a block contribute
        # nothing — both stay None when no row carried one)
        busy = agg.pop("busy_fractions")
        unatt = sorted(agg.pop("unattributed_fractions"))
        agg["utilization_rows"] = len(busy)
        agg["mean_busy_fraction"] = (
            round(sum(busy) / len(busy), 4) if busy else None
        )
        agg["p95_unattributed_fraction"] = (
            round(_percentile(unatt, 0.95), 4) if unatt else None
        )
    for w in workers.values():
        wl = sorted(w.pop("latencies"))
        w["p50_latency_s"] = round(_percentile(wl, 0.50), 6)
        w["p95_latency_s"] = round(_percentile(wl, 0.95), 6)
        wwarm = w["cache"]["mem"] + w["cache"]["disk"]
        wserved = wwarm + w["cache"]["miss"]
        w["cache_hit_rate"] = (
            round(wwarm / wserved, 3) if wserved else None
        )
    occupancy = sorted(
        max(b["rows"], b["members"]) for b in batches.values()
    )
    lat_batched.sort()
    lat_solo.sort()
    batching = {
        "batches": len(batches),
        "batched_requests": sum(b["rows"] for b in batches.values()),
        "occupancy_p50": _percentile(occupancy, 0.50),
        "occupancy_p95": _percentile(occupancy, 0.95),
        "batched_p50_latency_s": round(
            _percentile(lat_batched, 0.50), 6
        ),
        "solo_p50_latency_s": round(_percentile(lat_solo, 0.50), 6),
    }
    fleet = None
    if fleet_workers:
        total = sum(f["rows"] for f in fleet_workers.values())
        for f in fleet_workers.values():
            f["share"] = round(f["rows"] / total, 3) if total else 0.0
        fleet_wire.sort()
        fleet_overhead.sort()
        fleet = {
            "rows": total,
            "workers": fleet_workers,
            "wire_p50_s": round(_percentile(fleet_wire, 0.50), 6),
            "wire_p95_s": round(_percentile(fleet_wire, 0.95), 6),
            "overhead_p50_s": round(
                _percentile(fleet_overhead, 0.50), 6),
            "overhead_p95_s": round(
                _percentile(fleet_overhead, 0.95), 6),
        }
    return {
        "rows": len(rows),
        "by_kind": by_kind,
        "requests": requests,
        "drift": [
            drift[k] for k in sorted(drift, key=lambda k: (k[0], k[1]))
        ],
        "bench_rows": bench,
        "batching": batching,
        "service": service,
        "replicas": replicas,
        "workers": workers,
        "fleet": fleet,
    }


def format_stats(agg: dict) -> list[str]:
    """The aggregate as the CLI `stats` mode's printed table."""
    lines = [
        "ledger: %d rows (%s)" % (
            agg["rows"],
            ", ".join(f"{k}={v}"
                      for k, v in sorted(agg["by_kind"].items()))
            or "empty",
        )
    ]
    if agg["requests"]:
        lines.append(
            f"{'engine':<10} {'reqs':>5} {'ok':>4} {'fail':>4} "
            f"{'p50_s':>9} {'p95_s':>9} {'mem':>4} {'disk':>4} "
            f"{'miss':>4} {'dir':>4} {'hit%':>5} {'degr':>4}"
        )
        for eng in sorted(agg["requests"]):
            a = agg["requests"][eng]
            c = a["cache"]
            hit = (
                f"{a['cache_hit_rate'] * 100:.0f}"
                if a["cache_hit_rate"] is not None else "-"
            )
            lines.append(
                f"{eng:<10} {a['count']:>5} {a['ok']:>4} "
                f"{a['failed']:>4} {a['p50_latency_s']:>9.4f} "
                f"{a['p95_latency_s']:>9.4f} {c['mem']:>4} "
                f"{c['disk']:>4} {c['miss']:>4} {c['direct']:>4} "
                f"{hit:>5} {a['degraded']:>4}"
            )
    util_parts = [
        "%s busy=%.2f p95_unattr=%.2f (%d rows)" % (
            eng, a["mean_busy_fraction"],
            a["p95_unattributed_fraction"], a["utilization_rows"],
        )
        for eng, a in sorted(agg["requests"].items())
        if a.get("mean_busy_fraction") is not None
    ]
    if util_parts:
        lines.append("utilization: " + ", ".join(util_parts))
    for row in agg["drift"]:
        lines.append(
            "drift %s n=%d: max_abs=%.4f mean_abs=%.5f %s" % (
                row["model"], row["n"], row["max_abs_delta"],
                row["mean_abs_delta"],
                "BREACH" if row["breach"] else "ok",
            )
        )
    b = agg.get("batching")
    if b and b["batches"]:
        lines.append(
            "batching: %d batches, %d member rows, occupancy "
            "p50=%g p95=%g, cold p50 batched=%.4fs solo=%.4fs" % (
                b["batches"], b["batched_requests"],
                b["occupancy_p50"], b["occupancy_p95"],
                b["batched_p50_latency_s"], b["solo_p50_latency_s"],
            )
        )
    svc = agg.get("service") or {}
    if svc.get("preflight_rejected") or svc.get("race_flagged"):
        lines.append(
            "preflight: %d rejected (invalid IR), %d served with a "
            "race verdict" % (
                svc.get("preflight_rejected", 0),
                svc.get("race_flagged", 0),
            )
        )
    reps = agg.get("replicas")
    if reps:
        parts = ", ".join(
            "r%d=%d%s" % (
                rid, r["rows"],
                (" (degraded %d)" % r["degraded"])
                if r["degraded"] else "",
            )
            for rid, r in sorted(reps.items())
        )
        lines.append(
            "replicas: %d active, executions %s" % (len(reps), parts)
        )
    fws = agg.get("workers")
    if fws:
        parts = ", ".join(
            "w%d=%d p50=%.4fs p95=%.4fs hit%%=%s%s" % (
                wid, w["rows"], w["p50_latency_s"],
                w["p95_latency_s"],
                ("%.0f" % (w["cache_hit_rate"] * 100))
                if w["cache_hit_rate"] is not None else "-",
                (" (degraded %d)" % w["degraded"])
                if w["degraded"] else "",
            )
            for wid, w in sorted(fws.items())
        )
        lines.append(
            "workers: %d fabric worker(s), %s" % (len(fws), parts)
        )
    fl = agg.get("fleet")
    if fl:
        parts = " ".join(
            "w%d=%.0f%%%s" % (
                wid, f["share"] * 100,
                (" (redisp %d)" % f["redispatched"])
                if f["redispatched"] else "",
            )
            for wid, f in sorted(fl["workers"].items())
        )
        lines.append(
            "fleet: %d routed rows, share %s, wire p50=%.6fs "
            "p95=%.6fs, overhead p50=%.6fs p95=%.6fs" % (
                fl["rows"], parts, fl["wire_p50_s"],
                fl["wire_p95_s"], fl["overhead_p50_s"],
                fl["overhead_p95_s"],
            )
        )
    svc = agg.get("service")
    if svc and svc["submitted"]:
        lines.append(
            "service: submitted=%d coalesced=%d completed=%d "
            "failed=%d degraded=%d" % (
                svc["submitted"], svc["coalesced"], svc["completed"],
                svc["failed"], svc["degraded"],
            )
        )
    if svc and (svc.get("shed") or svc.get("retried")
                or svc.get("hedged")):
        lines.append(
            "resilience: shed=%d retried=%d hedged=%d" % (
                svc.get("shed", 0), svc.get("retried", 0),
                svc.get("hedged", 0),
            )
        )
    if agg["bench_rows"]:
        lines.append(f"bench rows: {agg['bench_rows']}")
    return lines
