"""Process-global live metrics registry for the serving layer.

Per-run Telemetry (runtime/telemetry.py) answers "what happened during
this run" after the fact; a serving process needs the complementary
question — "what is happening right now" — answered continuously and
cheaply. This module is that second view over the SAME write path:
`metrics.enable()` installs the registry as the telemetry module's
metrics sink, so every existing `telemetry.count` / `telemetry.gauge`
call site (engines, `counted_lru_cache`, executor/cache/batch-scheduler
stats) feeds both the active per-run Telemetry (when one is enabled)
and the live registry, with no second instrumentation pass.

Three instrument kinds:

- **counters** — monotone floats with, in addition to the lifetime
  total, bounded rolling windows (30s ring of 1s slots, 5m ring of 10s
  slots) so rates and burn rates can be computed without scraping
  twice;
- **gauges** — last-write-wins scalars;
- **histograms** — fixed-bucket latency histograms (Prometheus
  cumulative-bucket semantics) with the same rolling windows per
  bucket, windowed quantile estimates by bucket interpolation, and an
  optional exemplar (trace id) retained per bucket for the OpenMetrics
  exposition.

Rolling windows are time-sliced rings: each slot covers `slot_s`
seconds and stores the slot's increments plus the epoch (absolute slot
index) it was written in; a reader sums only slots whose epoch is
still inside the window, so stale slots cost nothing to expire. All
instruments are thread-safe behind one registry lock; the fast path is
a dict lookup + a few float adds.

The scrape side lives here too: `MetricsServer` is a stdlib
`http.server` thread serving the registry in Prometheus text format
(name sanitization shared with runtime/obs/exporters.py) on
`GET /metrics`, for the CLI's `--metrics-port` flag. `serve_jsonl`'s
`metrics` control request returns the same snapshot as JSON.
"""

from __future__ import annotations

import json
import threading
import time

from .. import lockwitness


# Default latency buckets (seconds). Chosen to resolve both the
# sub-millisecond cache-hit path and multi-second exact-engine runs;
# +Inf is implicit as the last bucket.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# (label, window span seconds, number of ring slots). Slot width is
# span/slots: 30s of 1s slots, 300s of 10s slots.
DEFAULT_WINDOWS = (("30s", 30.0, 30), ("5m", 300.0, 30))


class _ScalarWindow:
    """Rolling sum of increments over `span_s` seconds, sliced into
    `slots` ring slots. Not self-locking — the owning registry
    serializes access."""

    __slots__ = ("label", "span_s", "slots", "slot_s", "_vals",
                 "_epochs")

    def __init__(self, label: str, span_s: float, slots: int):
        self.label = label
        self.span_s = float(span_s)
        self.slots = int(slots)
        self.slot_s = self.span_s / self.slots
        self._vals = [0.0] * self.slots
        self._epochs = [-1] * self.slots

    def add(self, value: float, now: float) -> None:
        epoch = int(now // self.slot_s)
        idx = epoch % self.slots
        if self._epochs[idx] != epoch:
            self._vals[idx] = 0.0
            self._epochs[idx] = epoch
        self._vals[idx] += value

    def total(self, now: float) -> float:
        oldest = int(now // self.slot_s) - self.slots + 1
        return sum(
            v for v, e in zip(self._vals, self._epochs) if e >= oldest
        )


class _HistogramWindow:
    """Rolling per-bucket counts + sum/count over one ring window."""

    __slots__ = ("label", "span_s", "slots", "slot_s", "_counts",
                 "_sums", "_ns", "_epochs")

    def __init__(self, label: str, span_s: float, slots: int,
                 n_buckets: int):
        self.label = label
        self.span_s = float(span_s)
        self.slots = int(slots)
        self.slot_s = self.span_s / self.slots
        self._counts = [[0] * n_buckets for _ in range(self.slots)]
        self._sums = [0.0] * self.slots
        self._ns = [0] * self.slots
        self._epochs = [-1] * self.slots

    def observe(self, bucket_i: int, value: float, now: float) -> None:
        epoch = int(now // self.slot_s)
        idx = epoch % self.slots
        if self._epochs[idx] != epoch:
            row = self._counts[idx]
            for i in range(len(row)):
                row[i] = 0
            self._sums[idx] = 0.0
            self._ns[idx] = 0
            self._epochs[idx] = epoch
        self._counts[idx][bucket_i] += 1
        self._sums[idx] += value
        self._ns[idx] += 1

    def aggregate(self, now: float):
        """(per-bucket counts, sum, n) over the live slots."""
        oldest = int(now // self.slot_s) - self.slots + 1
        n_buckets = len(self._counts[0]) if self._counts else 0
        counts = [0] * n_buckets
        total = 0.0
        n = 0
        for idx in range(self.slots):
            if self._epochs[idx] < oldest:
                continue
            row = self._counts[idx]
            for i in range(n_buckets):
                counts[i] += row[i]
            total += self._sums[idx]
            n += self._ns[idx]
        return counts, total, n


def _quantile_from_buckets(counts, uppers, q: float):
    """Quantile estimate from per-bucket (non-cumulative) counts by
    linear interpolation inside the target bucket; the +Inf bucket
    reports its lower edge (the last finite upper bound). None when
    empty."""
    n = sum(counts)
    if n <= 0:
        return None
    rank = q * n
    seen = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if seen + c >= rank:
            if i >= len(uppers):       # +Inf bucket
                return uppers[-1] if uppers else None
            lo = uppers[i - 1] if i > 0 else 0.0
            hi = uppers[i]
            frac = (rank - seen) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        seen += c
    return uppers[-1] if uppers else None


class RollingHistogram:
    """Fixed-bucket histogram: lifetime cumulative counts + sum/count,
    rolling windows, and one retained exemplar per bucket. Bucket i
    holds observations <= buckets[i]; the final slot is +Inf. Not
    self-locking — the registry serializes."""

    def __init__(self, name: str, buckets=DEFAULT_BUCKETS,
                 windows=DEFAULT_WINDOWS):
        self.name = name
        self.buckets = tuple(float(b) for b in buckets)
        n = len(self.buckets) + 1          # + the +Inf bucket
        self.counts = [0] * n
        self.sum = 0.0
        self.count = 0
        self.exemplars: list = [None] * n  # (exemplar_id, value) | None
        self.windows = [
            _HistogramWindow(lbl, span, slots, n)
            for lbl, span, slots in windows
        ]

    def _bucket_index(self, value: float) -> int:
        for i, ub in enumerate(self.buckets):
            if value <= ub:
                return i
        return len(self.buckets)

    def observe(self, value: float, exemplar=None,
                now: float | None = None) -> None:
        value = float(value)
        if now is None:
            now = time.time()
        i = self._bucket_index(value)
        self.counts[i] += 1
        self.sum += value
        self.count += 1
        if exemplar is not None:
            self.exemplars[i] = (str(exemplar), value)
        for w in self.windows:
            w.observe(i, value, now)

    def window_fraction_over(self, label: str, threshold: float,
                             now: float | None = None):
        """Estimated fraction of the window's observations strictly
        above `threshold` (linear interpolation inside the straddling
        bucket); None when the window is empty."""
        if now is None:
            now = time.time()
        for w in self.windows:
            if w.label != label:
                continue
            counts, _, n = w.aggregate(now)
            if n <= 0:
                return None
            below = 0.0
            for i, ub in enumerate(self.buckets):
                lo = self.buckets[i - 1] if i > 0 else 0.0
                if ub <= threshold:
                    below += counts[i]
                elif lo < threshold:
                    below += counts[i] * (threshold - lo) / (ub - lo)
                    break
                else:
                    break
            return min(1.0, max(0.0, 1.0 - below / n))
        raise KeyError(f"unknown window {label!r}")

    def window_quantile(self, label: str, q: float,
                        now: float | None = None):
        if now is None:
            now = time.time()
        for w in self.windows:
            if w.label == label:
                counts, _, _ = w.aggregate(now)
                return _quantile_from_buckets(counts, self.buckets, q)
        raise KeyError(f"unknown window {label!r}")

    def snapshot(self, now: float | None = None) -> dict:
        if now is None:
            now = time.time()
        cum = 0
        buckets = {}
        exemplars = {}
        for i, ub in enumerate(self.buckets):
            cum += self.counts[i]
            buckets[f"{ub:g}"] = cum
            if self.exemplars[i] is not None:
                exemplars[f"{ub:g}"] = list(self.exemplars[i])
        buckets["+Inf"] = cum + self.counts[-1]
        if self.exemplars[-1] is not None:
            exemplars["+Inf"] = list(self.exemplars[-1])
        out = {
            "count": self.count,
            "sum": self.sum,
            "buckets": buckets,
            "exemplars": exemplars,
            "windows": {},
        }
        for w in self.windows:
            counts, total, n = w.aggregate(now)
            out["windows"][w.label] = {
                "count": n,
                "sum": total,
                "p50": _quantile_from_buckets(counts, self.buckets, 0.50),
                "p95": _quantile_from_buckets(counts, self.buckets, 0.95),
                "p99": _quantile_from_buckets(counts, self.buckets, 0.99),
            }
        return out


class MetricsRegistry:
    """Thread-safe live instrument store. Instruments are created on
    first write; names are raw telemetry names (sanitization happens at
    exposition time, with deterministic collision suffixes — see
    exporters.prometheus_registry_lines)."""

    def __init__(self, buckets=DEFAULT_BUCKETS,
                 windows=DEFAULT_WINDOWS):
        self._lock = lockwitness.make_lock("MetricsRegistry._lock")
        self._buckets = tuple(buckets)
        self._windows = tuple(windows)
        self._counters: dict = {}          # name -> float total
        self._counter_windows: dict = {}   # name -> [_ScalarWindow...]
        self._gauges: dict = {}
        self._hists: dict = {}

    # -- write path (the telemetry sink protocol) ---------------------

    def inc(self, name: str, inc: float = 1,
            now: float | None = None) -> None:
        if now is None:
            now = time.time()
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + inc
            wins = self._counter_windows.get(name)
            if wins is None:
                wins = [_ScalarWindow(lbl, span, slots)
                        for lbl, span, slots in self._windows]
                self._counter_windows[name] = wins
            for w in wins:
                w.add(inc, now)

    def set_gauge(self, name: str, value) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float, exemplar=None,
                buckets=None, now: float | None = None) -> None:
        if now is None:
            now = time.time()
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = RollingHistogram(
                    name, buckets or self._buckets, self._windows
                )
                self._hists[name] = h
            h.observe(value, exemplar=exemplar, now=now)

    # -- read path ----------------------------------------------------

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def counter_window(self, name: str, label: str,
                       now: float | None = None) -> float:
        """Sum of increments to `name` inside the rolling window."""
        if now is None:
            now = time.time()
        with self._lock:
            wins = self._counter_windows.get(name)
            if not wins:
                return 0.0
            for w in wins:
                if w.label == label:
                    return w.total(now)
        raise KeyError(f"unknown window {label!r}")

    def gauge_value(self, name: str, default=None):
        with self._lock:
            return self._gauges.get(name, default)

    def histogram_quantile(self, name: str, label: str, q: float,
                           now: float | None = None):
        """Windowed quantile of histogram `name`; None when the
        histogram is absent or the window is empty."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                return None
            return h.window_quantile(label, q, now=now)

    def histogram_fraction_over(self, name: str, label: str,
                                threshold: float,
                                now: float | None = None):
        """Windowed fraction of observations above `threshold`; None
        when the histogram is absent or the window is empty."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                return None
            return h.window_fraction_over(label, threshold, now=now)

    def window_labels(self) -> tuple:
        return tuple(lbl for lbl, _, _ in self._windows)

    def snapshot(self, now: float | None = None) -> dict:
        """Point-in-time JSON-safe view of every instrument."""
        if now is None:
            now = time.time()
        with self._lock:
            out = {
                "counters": dict(self._counters),
                "counter_windows": {
                    name: {w.label: w.total(now) for w in wins}
                    for name, wins in self._counter_windows.items()
                },
                "gauges": dict(self._gauges),
                "histograms": {
                    name: h.snapshot(now)
                    for name, h in self._hists.items()
                },
            }
        return out

    def prometheus_text(self, prefix: str = "pluss_") -> str:
        """Prometheus exposition of the live registry (histogram
        buckets + exemplars included); delegates to the exporters
        module so run-export and live-scrape share one sanitizer and
        one collision policy."""
        from . import exporters

        return "\n".join(
            exporters.prometheus_registry_lines(self, prefix=prefix)
        ) + "\n"

    # Exposed for the exporter: a consistent (counters, gauges, hists)
    # view without copying histogram internals.
    def _export_view(self):
        with self._lock:
            return (dict(self._counters), dict(self._gauges),
                    dict(self._hists))


# -- process-global switch --------------------------------------------

_registry: "MetricsRegistry | None" = None
_registry_lock = lockwitness.make_lock("metrics._registry_lock")


def enable(buckets=DEFAULT_BUCKETS,
           windows=DEFAULT_WINDOWS) -> MetricsRegistry:
    """Install a fresh process-global registry and hook it into the
    telemetry write path (`telemetry.count`/`gauge` mirror into it).
    Returns the registry. Idempotent-per-call: each call replaces the
    previous registry."""
    from .. import telemetry

    global _registry
    with _registry_lock:
        reg = MetricsRegistry(buckets=buckets, windows=windows)
        _registry = reg
        telemetry.set_metrics_sink(reg)
    return reg


def disable() -> "MetricsRegistry | None":
    """Unhook and drop the global registry; returns it (None if
    idle)."""
    from .. import telemetry

    global _registry
    with _registry_lock:
        reg = _registry
        _registry = None
        telemetry.set_metrics_sink(None)
    return reg


def get() -> "MetricsRegistry | None":
    return _registry


def observe(name: str, value: float, exemplar=None) -> None:
    """Record into the global registry's histogram `name`; no-op when
    the registry is disabled. The serving hot path calls this, so the
    disabled cost is one global read + None check."""
    reg = _registry
    if reg is not None:
        reg.observe(name, value, exemplar=exemplar)


# -- scrape endpoint --------------------------------------------------


class MetricsServer:
    """Background stdlib HTTP server for external probes.

    Routes: `GET /metrics` (and `/`) always serve the registry in
    Prometheus text format; when the optional `healthz` / `stats` /
    `bundles` callables are wired (the serve-mode CLI passes the
    AnalysisService's introspection methods and the flight recorder's
    bundle index), `GET /healthz`, `GET /stats`, and
    `GET /debug/bundles` serve their JSON — the same bodies the JSONL
    control requests answer with, so liveness probes and dashboards
    don't need to speak the serving protocol. `/healthz` stays
    answerable even without a service callable (plain liveness of the
    scrape server itself). The `profile` callable (the serve CLI
    passes `profiler.snapshot`) backs `GET /debug/profile`: the live
    sampling-profiler snapshot when the profiler is running, and a
    structured 404 JSON body (not a bare HTML error page) when it is
    off, so pollers always get machine-readable state. The optional
    `prometheus` callable overrides the `/metrics` body entirely (the
    fabric router passes its merged fleet exposition,
    fabric/router.py `fleet_prometheus_text`, so one scrape covers
    every worker); when it raises, the local registry is served as
    the fallback. `port=0` binds an ephemeral port (read it back from
    `.port`). Serves 404 elsewhere and never raises into the serving
    thread."""

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 host: str = "127.0.0.1", prefix: str = "pluss_",
                 healthz=None, stats=None, bundles=None,
                 profile=None, prometheus=None):
        import http.server

        reg = registry

        def _json_route(path: str):
            """(status, payload) for `path`, or None for no route."""
            if path == "/healthz":
                return 200, (healthz() if healthz is not None else {
                    "status": "ok", "service": False,
                })
            if path == "/stats" and stats is not None:
                return 200, stats()
            if path == "/debug/bundles" and bundles is not None:
                return 200, bundles()
            if path == "/debug/profile" and profile is not None:
                snap = profile()
                if snap is None:
                    return 404, {
                        "error": "profiler not running",
                        "status": 404,
                        "hint": "start serve mode with "
                                "--profile-hz HZ to enable the "
                                "sampling profiler",
                    }
                return 200, snap
            return None

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib naming)
                path = self.path.split("?", 1)[0]
                status = 200
                if path in ("/metrics", "/"):
                    try:
                        if prometheus is not None:
                            try:
                                text = prometheus()
                            except Exception:
                                text = reg.prometheus_text(
                                    prefix=prefix
                                )
                        else:
                            text = reg.prometheus_text(prefix=prefix)
                        body = text.encode()
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    except Exception as e:  # pragma: no cover
                        self.send_error(500, repr(e))
                        return
                else:
                    try:
                        routed = _json_route(path)
                    except Exception as e:  # pragma: no cover
                        self.send_error(500, repr(e))
                        return
                    if routed is None:
                        self.send_error(404)
                        return
                    status, payload = routed
                    body = (json.dumps(payload) + "\n").encode()
                    ctype = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request stderr
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            (host, int(port)), _Handler
        )
        self._httpd.daemon_threads = True
        self.host = self._httpd.server_address[0]
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.1},
            name="pluss-metrics-scrape", daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
