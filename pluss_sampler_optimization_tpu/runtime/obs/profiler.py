"""Sampling wall-clock profiler with telemetry-span attribution.

The telemetry spans (runtime/telemetry.py) time what we chose to
instrument; the five bench rounds of a flat ~80–110× headline showed
the limits of that: the gaps BETWEEN spans — interpreter overhead
around dispatches, queue handoffs, device-idle stretches — are
exactly the time nobody can account for. This module is the
complementary view: a background thread samples every live thread's
Python stack at a configurable rate (`sys._current_frames()`-based —
no signals, works from any thread, never interrupts user code) and
folds the samples into bounded collapsed-stack counts.

The key move is the join with the span layer: each sample is tagged
with the sampled thread's *current telemetry span path* (read from
the cross-thread registry `telemetry.span_paths_by_thread()`), so
every flame cell is attributable to a request stage
(draw/dispatch/fetch/merge/queue/...) or explicitly `unattributed` —
the unattributed fraction is the finding, not noise to discard.

Exports:

- `snapshot()` — JSON-safe dict (schema `PROFILE_VERSION`): sample
  totals, attribution stats, per-span-path sample seconds, and the
  collapsed stacks sorted by weight (deterministic order);
- `write_speedscope(path)` — speedscope-compatible JSON
  (https://www.speedscope.app; "sampled" profile, one weighted sample
  per collapsed stack, a synthetic `span:<path>` root frame carrying
  the attribution);
- `write_collapsed(path)` — classic `frame;frame;frame count` text
  (flamegraph.pl / speedscope both ingest it).

All exports are atomic writes (runtime/io.py) and byte-stable given a
fixed sample log: folding is order-independent (a dict keyed by
(span path, frame tuple)) and every export sorts deterministically,
so exporting the same collected samples twice produces identical
bytes (tools/check_profile.py gates this).

Costs are bounded by construction: stack depth is capped, the fold
table is capped (overflow samples are counted, never grown), and the
sampler thread holds the profiler lock only to fold one sample.
Overhead on the hot engine path is pinned < 3% with MRC bytes
bit-identical profiler on vs off (tools/check_profile.py, tier-1 via
tests/test_profiler.py).
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time

from .. import lockwitness, telemetry
from ..io import atomic_write_text

PROFILE_VERSION = 1

# Fold-table bound: a pathological workload degrades to counting
# overflow samples under the sentinel key instead of growing without
# bound. 4096 distinct (span path, stack) keys is far beyond what the
# serving stack produces in practice.
MAX_STACKS = 4096
MAX_DEPTH = 64

UNATTRIBUTED = "unattributed"

# Frames from these path fragments are the profiler/observability
# machinery itself; samples landing there on the *sampler* thread are
# excluded at collection time (the sampler skips its own thread), and
# the package-path test below is how a sample on any other thread is
# classified as in-request work.
_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))


def _frame_name(code) -> str:
    """Stable frame label: module path relative to the repo when the
    file lives under it, else the basename — plus the function name.
    Uses co_firstlineno (stable per function) rather than the current
    line, so one function folds to one frame."""
    fn = code.co_filename
    if fn.startswith(_PKG_ROOT):
        fn = os.path.relpath(fn, _PKG_ROOT)
    else:
        fn = os.path.basename(fn)
    return f"{fn}:{code.co_name}:{code.co_firstlineno}"


class SamplingProfiler:
    """Background wall-clock sampler over every live thread.

    Not self-starting: `start()` spawns the daemon sampler thread,
    `stop()` joins it; `sample_once()` is the testable unit (and what
    the loop calls). The fold table and stats live behind one
    lockwitness-minted lock; the sampler thread takes it only to fold
    one pre-built sample batch, and no telemetry sink is ever called
    under it."""

    def __init__(self, hz: float = 99.0, max_stacks: int = MAX_STACKS,
                 max_depth: int = MAX_DEPTH):
        if hz <= 0:
            raise ValueError("hz must be > 0")
        self.hz = float(hz)
        self.max_stacks = int(max_stacks)
        self.max_depth = int(max_depth)
        self._lock = lockwitness.make_lock("SamplingProfiler._lock")
        # (span_path, frames_tuple) -> sample count. Guarded by _lock.
        self._counts_locked: dict = {}
        self._samples_locked = 0
        self._attributed_locked = 0
        self._in_request_locked = 0
        self._overflow_locked = 0
        self._t0 = time.perf_counter()
        self._duration_s: float | None = None
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None

    # -- collection ---------------------------------------------------

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._loop, name="pluss-profiler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> "SamplingProfiler":
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._duration_s is None:
            self._duration_s = time.perf_counter() - self._t0
        return self

    def _loop(self) -> None:
        interval = 1.0 / self.hz
        # Dither every wait uniformly over [0.5, 1.5] periods (the
        # mean stays 1/hz, so the count -> seconds weighting holds).
        # A fixed period phase-locks with periodic request loops:
        # every tick then lands at the same phase of the loop, which
        # biases the flame toward that phase and — when the phase is
        # a dispatch-critical section — charges a worst-case
        # preemption to every single request (observed as whole
        # processes where the overhead gate read 4-5% while dithered
        # runs of the same build read < 1%).
        rng = random.Random()
        while not self._stop_evt.wait(interval * (0.5 + rng.random())):
            try:
                self.sample_once()
            except Exception:
                # A single bad sample (thread torn down mid-walk)
                # must never kill the sampler; the next tick retries.
                pass

    def sample_once(self) -> int:
        """Sample every live thread (except the sampler itself) once;
        returns the number of samples folded. Builds the whole batch
        lock-free, then folds it under the profiler lock."""
        me = threading.get_ident()
        span_paths = telemetry.span_paths_by_thread()
        frames = sys._current_frames()
        batch = []
        for tid in sorted(frames):
            if tid == me:
                continue
            stack = []
            in_request = False
            f = frames[tid]
            depth = 0
            while f is not None and depth < self.max_depth:
                code = f.f_code
                stack.append(_frame_name(code))
                if not in_request and code.co_filename.startswith(
                    _PKG_ROOT
                ):
                    in_request = True
                f = f.f_back
                depth += 1
            stack.reverse()  # root -> leaf
            path = span_paths.get(tid, "")
            batch.append((path, tuple(stack), in_request))
        self._fold(batch)
        return len(batch)

    def _fold(self, batch) -> None:
        with self._lock:
            for path, stack, in_request in batch:
                self._samples_locked += 1
                if path:
                    self._attributed_locked += 1
                    self._in_request_locked += 1
                elif in_request:
                    self._in_request_locked += 1
                key = (path or UNATTRIBUTED, stack)
                cur = self._counts_locked.get(key)
                if cur is not None:
                    self._counts_locked[key] = cur + 1
                elif len(self._counts_locked) < self.max_stacks:
                    self._counts_locked[key] = 1
                else:
                    self._overflow_locked += 1

    def ingest(self, span_path: str, frames, count: int = 1,
               in_request: bool | None = None) -> None:
        """Fold a pre-recorded sample (the fixed-sample-log path the
        byte-stability tests and gate use): `frames` root->leaf."""
        if in_request is None:
            in_request = bool(span_path)
        self._fold(
            [(span_path, tuple(frames), bool(in_request))] * int(count)
        )

    # -- export -------------------------------------------------------

    def _state(self):
        with self._lock:
            return (
                dict(self._counts_locked),
                self._samples_locked,
                self._attributed_locked,
                self._in_request_locked,
                self._overflow_locked,
            )

    def snapshot(self) -> dict:
        """JSON-safe point-in-time view; deterministic given a fixed
        sample log (stacks sorted by descending count, then key)."""
        counts, samples, attributed, in_request, overflow = (
            self._state()
        )
        dur = self._duration_s
        if dur is None:
            dur = time.perf_counter() - self._t0
        sample_s = 1.0 / self.hz
        span_seconds: dict = {}
        for (path, _stack), c in counts.items():
            span_seconds[path] = span_seconds.get(path, 0) + c
        stacks = [
            {
                "span": path,
                "frames": list(stack),
                "count": c,
                "seconds": round(c * sample_s, 6),
            }
            for (path, stack), c in sorted(
                counts.items(), key=lambda kv: (-kv[1], kv[0])
            )
        ]
        completeness = (
            round(attributed / in_request, 4) if in_request else None
        )
        return {
            "profile_version": PROFILE_VERSION,
            "hz": self.hz,
            "duration_s": round(dur, 6),
            "samples": samples,
            "samples_attributed": attributed,
            "samples_in_request": in_request,
            "attribution_completeness": completeness,
            "stacks_overflowed": overflow,
            "span_seconds": {
                p: round(c * sample_s, 6)
                for p, c in sorted(span_seconds.items())
            },
            "stacks": stacks,
        }

    def collapsed_text(self) -> str:
        """`span:<path>;frame;frame count` lines, sorted — the
        flamegraph.pl/speedscope-ingestable collapsed format."""
        counts, *_ = self._state()
        lines = []
        for (path, stack), c in counts.items():
            cells = [f"span:{path}"] + list(stack)
            lines.append((";".join(cells), c))
        lines.sort()
        return "".join(f"{key} {c}\n" for key, c in lines)

    def speedscope(self, name: str = "pluss-profile") -> dict:
        """Speedscope file-format dict: one "sampled" profile whose
        samples are the collapsed stacks (weight = count / hz), each
        rooted at a synthetic `span:<path>` frame so the flame view
        groups by request stage."""
        counts, samples, *_ = self._state()
        sample_s = 1.0 / self.hz
        frame_index: dict = {}
        frames_out: list = []

        def fi(label: str) -> int:
            i = frame_index.get(label)
            if i is None:
                i = frame_index[label] = len(frames_out)
                frames_out.append({"name": label})
            return i

        samples_out = []
        weights = []
        for (path, stack), c in sorted(counts.items()):
            samples_out.append(
                [fi(f"span:{path}")] + [fi(s) for s in stack]
            )
            weights.append(round(c * sample_s, 6))
        end = round(sum(weights), 6)
        return {
            "$schema": "https://www.speedscope.app/"
                       "file-format-schema.json",
            "name": name,
            "activeProfileIndex": 0,
            "exporter": "pluss-profiler",
            "shared": {"frames": frames_out},
            "profiles": [{
                "type": "sampled",
                "name": name,
                "unit": "seconds",
                "startValue": 0,
                "endValue": end,
                "samples": samples_out,
                "weights": weights,
            }],
        }

    def write_speedscope(self, path: str,
                         name: str = "pluss-profile") -> None:
        import json

        atomic_write_text(
            path,
            json.dumps(self.speedscope(name=name), sort_keys=True,
                       separators=(",", ":")) + "\n",
        )

    def write_collapsed(self, path: str) -> None:
        atomic_write_text(path, self.collapsed_text())


def validate_snapshot(doc) -> list[str]:
    """All schema violations of a profiler snapshot (empty = valid);
    shared by tools/check_profile.py and the /debug/profile route's
    consumers."""
    errors: list[str] = []
    if not isinstance(doc, dict):
        return ["snapshot is not a JSON object"]
    if doc.get("profile_version") != PROFILE_VERSION:
        errors.append(
            f"profile_version must be {PROFILE_VERSION}, got "
            f"{doc.get('profile_version')!r}"
        )
    for key in ("hz", "duration_s"):
        v = doc.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or v < 0:
            errors.append(f"'{key}' must be a non-negative number")
    for key in ("samples", "samples_attributed",
                "samples_in_request", "stacks_overflowed"):
        v = doc.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(
                f"'{key}' must be a non-negative integer"
            )
    c = doc.get("attribution_completeness")
    if c is not None and (
        not isinstance(c, (int, float)) or isinstance(c, bool)
        or not (0.0 <= c <= 1.0)
    ):
        errors.append(
            "'attribution_completeness' must be in [0, 1] or null"
        )
    if not isinstance(doc.get("span_seconds"), dict):
        errors.append("'span_seconds' must be an object")
    stacks = doc.get("stacks")
    if not isinstance(stacks, list):
        errors.append("'stacks' must be a list")
    else:
        for i, s in enumerate(stacks):
            if not isinstance(s, dict):
                errors.append(f"stacks[{i}] is not an object")
                continue
            if not isinstance(s.get("span"), str) or not s["span"]:
                errors.append(
                    f"stacks[{i}].span must be a non-empty string"
                )
            if not isinstance(s.get("frames"), list):
                errors.append(f"stacks[{i}].frames must be a list")
            n = s.get("count")
            if not isinstance(n, int) or isinstance(n, bool) or n < 1:
                errors.append(
                    f"stacks[{i}].count must be a positive integer"
                )
    return errors


# -- process-global switch --------------------------------------------

_profiler: "SamplingProfiler | None" = None
_profiler_lock = lockwitness.make_lock("profiler._profiler_lock")


def enable(hz: float = 99.0, **kwargs) -> SamplingProfiler:
    """Start (replacing any active) process-global profiler and its
    sampler thread; returns it. The serve CLI calls this for
    --profile-hz."""
    global _profiler
    with _profiler_lock:
        prev = _profiler
        _profiler = None
    if prev is not None:
        prev.stop()
    prof = SamplingProfiler(hz=hz, **kwargs).start()
    with _profiler_lock:
        _profiler = prof
    return prof


def disable() -> "SamplingProfiler | None":
    """Stop and drop the global profiler; returns it (already
    stopped, so its snapshot/exports describe the whole enabled
    window), or None when idle."""
    global _profiler
    with _profiler_lock:
        prof = _profiler
        _profiler = None
    if prof is not None:
        prof.stop()
    return prof


def get() -> "SamplingProfiler | None":
    return _profiler


def snapshot() -> "dict | None":
    """The global profiler's snapshot, or None when off — the
    MetricsServer /debug/profile route and the flight recorder's
    bundle writer both read this."""
    prof = _profiler
    if prof is None:
        return None
    return prof.snapshot()
