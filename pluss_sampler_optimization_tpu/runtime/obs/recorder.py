"""Flight recorder: tail-retained request records + post-mortem bundles.

The live registry (runtime/obs/metrics.py) and the SLO sentinel
(runtime/obs/slo.py) tell you THAT something broke — a breached burn
rate, a quarantined replica, a drift excursion — but by the time a
human looks, the evidence is gone: counters have no per-request
detail, the per-run Telemetry belongs to one CLI run, and serve mode
handles thousands of requests between two scrapes. The flight recorder
closes that gap the way production trace systems do with tail-based
sampling: record everything cheaply in a bounded ring, keep the full
detail only for the interesting minority (errors, degradations, drift
breaches, latency outliers above a windowed p99), and on an anomaly
trigger dump an atomic, schema-versioned post-mortem bundle with
everything a debugging session needs.

Feed path — the existing telemetry sinks, extended by one leg:

- per-request records: the service executor assembles one dict per
  completed/failed/expired request (trace/span ids, stage timings,
  engine/cache/batch/replica outcome) and hands it to
  `record(outcome)` right where it already observes stage histograms;
- anomaly events: `telemetry.event()` mirrors into the recorder via
  `telemetry.set_record_sink` exactly like `count()`/`gauge()` mirror
  into the metrics registry — so `slo_breach`, `replica_quarantined`,
  `drift_breach`, and `perf_regression` emissions reach the trigger
  logic without their emit sites knowing the recorder exists.

Triggers (each rate-limited per reason so a breach storm writes one
bundle, not thousands): SLO sentinel breach, request failure, replica
quarantine, drift breach, a perf-regression sentinel breach
(runtime/obs/regress.py), and the explicit paths — a `dump_debug`
serve request or SIGUSR2 on the serve process.

Bundles are written with runtime/io.py::atomic_write_json under
`--debug-bundle-dir`, validated BEFORE the write by `validate_bundle`
— the single source of truth shared with tools/check_bundle.py, the
same validate()-reuse pattern as ledger.validate_row /
cache.validate_record.

Observation only: the recorder never touches engine inputs or
outputs, and MRC bytes are pinned bit-identical recorder on vs off
(tests/test_recorder.py).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from .. import lockwitness, telemetry
from ..io import atomic_write_json

BUNDLE_VERSION = 1
ACCEPTED_BUNDLE_VERSIONS = (1,)

# Bundle reasons: the five anomaly/explicit trigger paths plus the
# regression sentinel, the SIGUSR2 serve hook, and the final bundle
# the serve loop writes on graceful shutdown (SIGTERM/SIGINT drain).
REASONS = (
    "slo_breach",
    "request_failure",
    "replica_quarantine",
    "drift_breach",
    "perf_regression",
    "dump_debug",
    "signal",
    "shutdown",
)

# telemetry.event() names that fire a bundle when they reach the
# record sink, mapped to their bundle reason.
TRIGGER_EVENTS = {
    "slo_breach": "slo_breach",
    "replica_quarantined": "replica_quarantine",
    "drift_breach": "drift_breach",
    "perf_regression": "perf_regression",
}

# Ring-record retention classes (record["retained"] when kept).
RETAIN_REASONS = ("error", "degraded", "event", "latency_outlier")

_NUM = (int, float)


def _is_num(v) -> bool:
    return isinstance(v, _NUM) and not isinstance(v, bool)


def _span_tree(record: dict) -> dict:
    """Synthesize the request's span tree from its stage timings.

    Serve mode runs without a per-run Telemetry, so the recorder
    rebuilds the span shape the executor would have recorded: a
    `request` root spanning the whole latency with one child per
    non-null stage, in pipeline order. Matches Span.to_dict()'s
    {name, start_s, wall_s, children} shape so trace tooling that
    reads telemetry exports can read bundles too.
    """
    total = record.get("latency_s")
    root: dict = {
        "name": "request",
        "start_s": 0.0,
        "wall_s": float(total) if _is_num(total) else 0.0,
        "attrs": {
            k: record.get(k)
            for k in ("trace_id", "span_id", "engine_used", "cache")
            if record.get(k) is not None
        },
        "children": [],
    }
    t = 0.0
    for stage in ("queue_s", "batch_wait_s", "execute_s", "fetch_s"):
        v = record.get(stage)
        if not _is_num(v):
            continue
        root["children"].append({
            "name": stage[:-2],
            "start_s": round(t, 9),
            "wall_s": float(v),
            "children": [],
        })
        t += float(v)
    return root


def validate_bundle(doc) -> list[str]:
    """All schema violations of one parsed bundle (empty = valid).

    Single source of truth for the writer (validate-before-write, a
    recorder bug fails loudly rather than poisoning the bundle dir)
    AND the offline checker (tools/check_bundle.py). Unknown extra
    keys are allowed, same policy as ledger.validate_row.
    """
    errors: list[str] = []
    if not isinstance(doc, dict):
        return ["bundle is not a JSON object"]
    if doc.get("bundle_version") not in ACCEPTED_BUNDLE_VERSIONS:
        errors.append(
            f"bundle_version must be one of {ACCEPTED_BUNDLE_VERSIONS},"
            f" got {doc.get('bundle_version')!r}"
        )
    if doc.get("reason") not in REASONS:
        errors.append(
            f"'reason' must be one of {REASONS}, got "
            f"{doc.get('reason')!r}"
        )
    if not _is_num(doc.get("ts")) or doc.get("ts", -1) < 0:
        errors.append("'ts' must be a non-negative number")
    if not isinstance(doc.get("trigger"), dict):
        errors.append("'trigger' must be an object")
    if not isinstance(doc.get("records"), list):
        errors.append("'records' must be a list")
    else:
        for i, rec in enumerate(doc["records"]):
            if not isinstance(rec, dict):
                errors.append(f"records[{i}] is not an object")
                continue
            if rec.get("kind") not in ("request", "event"):
                errors.append(
                    f"records[{i}].kind must be 'request' or 'event'"
                )
            if not _is_num(rec.get("ts")) or rec.get("ts", -1) < 0:
                errors.append(
                    f"records[{i}].ts must be a non-negative number"
                )
            if not _is_num(rec.get("seq")):
                errors.append(f"records[{i}].seq must be a number")
            if rec.get("kind") == "request":
                if not isinstance(rec.get("ok"), bool):
                    errors.append(
                        f"records[{i}].ok must be a boolean"
                    )
                if not isinstance(rec.get("span_tree"), dict):
                    errors.append(
                        f"records[{i}].span_tree must be an object"
                    )
            r = rec.get("retained")
            if r is not None and r not in RETAIN_REASONS:
                errors.append(
                    f"records[{i}].retained must be one of "
                    f"{RETAIN_REASONS} or null, got {r!r}"
                )
    for key in ("registry", "config", "state"):
        v = doc.get(key)
        if v is not None and not isinstance(v, dict):
            errors.append(f"'{key}' must be an object or null")
    if not isinstance(doc.get("ledger_tail"), list):
        errors.append("'ledger_tail' must be a list")
    for key in ("host", "devices", "compile_counters", "stats"):
        if not isinstance(doc.get(key), dict):
            errors.append(f"'{key}' must be an object")
    p = doc.get("profile")
    if p is not None and not isinstance(p, str):
        errors.append("'profile' must be a string path or null")
    # the live sampling profiler's snapshot (runtime/obs/profiler.py)
    # rides bundles when --profile-hz is on — distinct from `profile`
    # (the jax device-memory capture's file path)
    ps = doc.get("profile_snapshot")
    if ps is not None and not isinstance(ps, dict):
        errors.append("'profile_snapshot' must be an object or null")
    return errors


def _profiler_snapshot(top: int = 50):
    """The live sampling profiler's snapshot with the stack list
    trimmed to the heaviest `top` entries (a bundle is point-in-time
    evidence, not a full export — /debug/profile serves the whole
    thing); None when the profiler is off. Never raises: a profiler
    problem must not sink a post-mortem dump."""
    try:
        from . import profiler

        snap = profiler.snapshot()
    except Exception:
        return None
    if snap is None:
        return None
    snap["stacks"] = snap["stacks"][:top]
    return snap


class FlightRecorder:
    """Bounded ring of request records + trigger-driven bundle writer.

    Constant memory by construction: one deque of at most `capacity`
    recent records (the context around an anomaly), one deque of at
    most `retain_capacity` interesting records promoted out of the
    ring instead of being evicted (the tail-retention keep set), and a
    fixed-size latency window for the outlier threshold. Everything
    else is O(1) counters.
    """

    def __init__(self, bundle_dir: str, capacity: int = 256,
                 retain_capacity: int = 128,
                 ledger_path: str | None = None,
                 ledger_tail_rows: int = 64,
                 config: dict | None = None,
                 min_interval_s: float = 300.0,
                 outlier_window: int = 512,
                 outlier_min_count: int = 20,
                 state_provider=None, profile: bool = False):
        if capacity < 1 or retain_capacity < 1:
            raise ValueError("capacity and retain_capacity must be >= 1")
        self.bundle_dir = os.fspath(bundle_dir)
        os.makedirs(self.bundle_dir, exist_ok=True)
        self.capacity = int(capacity)
        self.retain_capacity = int(retain_capacity)
        self.ledger_path = ledger_path
        self.ledger_tail_rows = int(ledger_tail_rows)
        self.config = dict(config) if config else None
        self.min_interval_s = float(min_interval_s)
        self.outlier_min_count = int(outlier_min_count)
        self.profile = bool(profile)
        # Called at dump time for live serving state (replica pool
        # snapshot, executor stats); attached by the CLI once the
        # service exists, so construction order stays flexible.
        self.state_provider = state_provider
        self._lock = lockwitness.make_rlock("FlightRecorder._lock")
        self._ring: deque = deque()
        self._retained: deque = deque(maxlen=self.retain_capacity)
        self._latencies: deque = deque(maxlen=max(8, outlier_window))
        self._seq = 0
        self._bundle_seq = 0
        self._seen = 0
        self._evicted = 0
        self._last_bundle: dict[str, float] = {}  # reason -> monotonic
        self._last_bundle_file: str | None = None
        self._triggers: dict[str, int] = {}
        self._suppressed = 0
        self._write_failed = 0

    # -- classification ------------------------------------------------

    def _latency_p99(self) -> float | None:
        """Nearest-rank p99 over the recorder's own rolling latency
        window; None until `outlier_min_count` samples exist (no
        threshold from thin data)."""
        if len(self._latencies) < self.outlier_min_count:
            return None
        vals = sorted(self._latencies)
        idx = max(0, min(len(vals) - 1,
                         int(round(0.99 * (len(vals) - 1)))))
        return vals[idx]

    def _classify(self, record: dict) -> str | None:
        """Retention class of a record, or None for the boring
        majority. Order matters: an error that is also slow retains
        as 'error'."""
        if record.get("kind") == "event":
            # Only anomaly events earn retention — routine emissions
            # (ledger_gc, export notices) ride the ring like any
            # boring record and age out.
            name = record.get("name") or ""
            if name in TRIGGER_EVENTS or name.endswith("_failed"):
                return "event"
            return None
        if record.get("ok") is False or record.get("error"):
            return "error"
        if record.get("degraded"):
            return "degraded"
        lat = record.get("latency_s")
        if _is_num(lat):
            p99 = self._latency_p99()
            if p99 is not None and float(lat) > p99:
                return "latency_outlier"
        return None

    # -- feed paths ----------------------------------------------------

    def record_request(self, record: dict) -> None:
        """Ingest one per-request record from the executor.

        Stamps seq/ts/kind and the synthesized span tree, classifies
        for retention, and — when the record is a failure — fires the
        request_failure trigger. Never raises into the serving path.
        """
        try:
            rec = dict(record)
            rec.setdefault("kind", "request")
            rec.setdefault("ok", not rec.get("error"))
            failed = rec["ok"] is False or bool(rec.get("error"))
            with self._lock:
                evicted = self._ingest_locked(rec)
                if _is_num(rec.get("latency_s")):
                    self._latencies.append(float(rec["latency_s"]))
            self._emit_retained_evicted(evicted)
            telemetry.count("recorder_records")
            if failed:
                self.trigger("request_failure", trigger={
                    k: rec.get(k)
                    for k in ("trace_id", "span_id", "model", "n",
                              "engine_requested", "error")
                })
        except Exception:
            telemetry.count("recorder_record_failed")

    def record_event(self, name: str, data: dict) -> None:
        """telemetry.event() sink leg: anomaly events become retained
        ring records, and trigger events fire a bundle."""
        rec = {"kind": "event", "name": name, "data": dict(data)}
        with self._lock:
            evicted = self._ingest_locked(rec)
        self._emit_retained_evicted(evicted)
        reason = TRIGGER_EVENTS.get(name)
        if reason is not None:
            self.trigger(reason, trigger={"event": name, **data})

    def _ingest_locked(self, rec: dict) -> int:
        """Stamp + append under the lock, promoting the interesting
        on eviction (tail-based retention). Returns how many retained
        records fell off so the caller can emit telemetry after
        releasing `_lock` — the sink legs (metrics registry, and
        record_event right back into this recorder) take their own
        locks."""
        self._seq += 1
        self._seen += 1
        rec["seq"] = self._seq
        rec.setdefault("ts", round(time.time(), 3))
        if rec.get("kind") == "request":
            rec["span_tree"] = _span_tree(rec)
        rec["retained"] = self._classify(rec)
        self._ring.append(rec)
        retained_evicted = 0
        while len(self._ring) > self.capacity:
            old = self._ring.popleft()
            if old.get("retained") is not None:
                if len(self._retained) == self._retained.maxlen:
                    retained_evicted += 1
                self._retained.append(old)
            else:
                self._evicted += 1
        return retained_evicted

    @staticmethod
    def _emit_retained_evicted(evicted: int) -> None:
        for _ in range(evicted):
            telemetry.count("recorder_retained_evicted")

    # -- triggers / bundles --------------------------------------------

    def trigger(self, reason: str, trigger: dict | None = None,
                force: bool = False) -> str | None:
        """Maybe write a bundle for `reason`; returns its path.

        Rate-limited per reason (min_interval_s, monotonic clock) so
        an SLO breach re-evaluated every sentinel tick or a failing
        replica in a tight loop yields ONE bundle per window; `force`
        (the explicit dump_debug / SIGUSR2 paths) bypasses the limit.
        Never raises: a failed write counts recorder_bundle_failed.
        """
        now = time.monotonic()
        with self._lock:
            last = self._last_bundle.get(reason)
            suppressed = (
                not force
                and last is not None
                and (now - last) < self.min_interval_s
            )
            if suppressed:
                self._suppressed += 1
            else:
                self._last_bundle[reason] = now
                self._triggers[reason] = (
                    self._triggers.get(reason, 0) + 1
                )
        if suppressed:
            # sink emission after release (C_SINK_UNDER_LOCK): the
            # suppressed counter must not extend the hold time
            telemetry.count("recorder_bundle_suppressed")
            return None
        try:
            path = self._write_bundle(reason, trigger or {})
        except Exception:
            with self._lock:
                self._write_failed += 1
            telemetry.count("recorder_bundle_failed")
            return None
        telemetry.count("debug_bundles_written")
        return path

    def dump(self, reason: str = "dump_debug",
             trigger: dict | None = None) -> str | None:
        """Explicit bundle (the serve `dump_debug` request / SIGUSR2
        hook): always writes, no rate limit."""
        return self.trigger(reason, trigger=trigger, force=True)

    def snapshot_records(self) -> list[dict]:
        """Retained keep-set + current ring, in ingest order."""
        with self._lock:
            return [dict(r) for r in self._retained] + [
                dict(r) for r in self._ring
            ]

    def stats(self) -> dict:
        with self._lock:
            return {
                "records_seen": self._seen,
                "ring": len(self._ring),
                "retained": len(self._retained),
                "evicted": self._evicted,
                "bundles_written": self._bundle_seq,
                "bundles_suppressed": self._suppressed,
                "bundle_write_failed": self._write_failed,
                "triggers": dict(self._triggers),
                "last_bundle": self._last_bundle_file,
                "latency_p99_s": self._latency_p99(),
            }

    def _write_bundle(self, reason: str, trigger: dict) -> str:
        from . import metrics as obs_metrics
        from . import ledger as obs_ledger

        reg = obs_metrics.get()
        state = None
        if self.state_provider is not None:
            try:
                state = self.state_provider()
            except Exception as e:
                state = {"error": repr(e)}
        ledger_tail: list = []
        if self.ledger_path:
            ledger_tail = obs_ledger.tail(
                self.ledger_path, self.ledger_tail_rows
            )
        with self._lock:
            self._bundle_seq += 1
            seq = self._bundle_seq
            records = self.snapshot_records()
        name = "BUNDLE_%s_%d_%04d_%s.json" % (
            time.strftime("%Y%m%dT%H%M%SZ", time.gmtime()),
            os.getpid(), seq, reason,
        )
        path = os.path.join(self.bundle_dir, name)
        profile_path = None
        if self.profile:
            # Optional jax.profiler capture: a point-in-time device
            # memory profile is the only capture that makes sense
            # post-hoc (a trace needs start/stop around the activity).
            # Gated: no jax / no profiler support degrades to None.
            try:
                import jax.profiler

                profile_path = path[:-5] + ".memprof.pb"
                jax.profiler.save_device_memory_profile(profile_path)
            except Exception:
                profile_path = None
        doc = {
            "bundle_version": BUNDLE_VERSION,
            "reason": reason,
            "ts": round(time.time(), 3),
            "bundle_seq": seq,
            "trigger": trigger,
            "records": records,
            "registry": reg.snapshot() if reg is not None else None,
            "ledger_tail": ledger_tail,
            "config": self.config,
            "state": state,
            "host": telemetry.host_fingerprint(speed_probe=False),
            "devices": telemetry.device_metrics(),
            "compile_counters": telemetry.compile_counters_snapshot(),
            "stats": self.stats(),
            "profile": profile_path,
            "profile_snapshot": _profiler_snapshot(),
        }
        errors = validate_bundle(doc)
        if errors:
            raise ValueError(
                "invalid bundle: " + "; ".join(errors)
            )
        atomic_write_json(path, doc)
        with self._lock:
            self._last_bundle_file = path
        return path

    def bundle_index(self) -> list[dict]:
        """Written bundles in this recorder's dir, oldest first —
        the `GET /debug/bundles` / dump_debug listing. Reads only
        dirents + stat (reason is embedded in the filename), so
        listing stays cheap with many bundles."""
        out = []
        try:
            names = sorted(
                n for n in os.listdir(self.bundle_dir)
                if n.startswith("BUNDLE_") and n.endswith(".json")
            )
        except OSError:
            return out
        for n in names:
            p = os.path.join(self.bundle_dir, n)
            try:
                st = os.stat(p)
            except OSError:
                continue
            stem = n[:-5].split("_")
            out.append({
                "file": n,
                "reason": "_".join(stem[4:]) if len(stem) > 4 else None,
                "bytes": st.st_size,
                "mtime": round(st.st_mtime, 3),
            })
        return out

    def close(self) -> None:
        pass  # symmetry with the other obs lifecycles; nothing owned


# -- process-global switch --------------------------------------------

_recorder: "FlightRecorder | None" = None
_recorder_lock = lockwitness.make_lock("recorder._recorder_lock")


def enable(bundle_dir: str, **kwargs) -> FlightRecorder:
    """Install a fresh process-global recorder and hook it into the
    telemetry event path (`telemetry.event` mirrors into it). Returns
    the recorder. Each call replaces the previous one."""
    global _recorder
    with _recorder_lock:
        rec = FlightRecorder(bundle_dir, **kwargs)
        _recorder = rec
        telemetry.set_record_sink(rec)
    return rec


def disable() -> "FlightRecorder | None":
    """Unhook and drop the global recorder; returns it (None if
    idle)."""
    global _recorder
    with _recorder_lock:
        rec = _recorder
        _recorder = None
        telemetry.set_record_sink(None)
    return rec


def get() -> "FlightRecorder | None":
    return _recorder


def record(outcome: dict) -> None:
    """Feed one per-request record into the global recorder; no-op
    when disabled. The serving hot path calls this, so the disabled
    cost is one global read + None check."""
    rec = _recorder
    if rec is not None:
        rec.record_request(outcome)
