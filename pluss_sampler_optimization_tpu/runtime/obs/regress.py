"""Performance regression sentinel over ledger history + bench evidence.

The ledger (runtime/obs/ledger.py) and the BENCH_r*.json evidence
sidecars record the repo's performance trajectory, but until now they
were passive artifacts: a silent 2x latency regression or a collapsed
benchmark headline only surfaced when a human re-read the numbers.
This module turns the trajectory into a guarded invariant:

- **ledger history** — per-engine request latency (p50), per-stage
  execute latency, and per-request compile-count distributions, each
  split into an older baseline half and a newer recent half by row
  timestamp; a recent half worse than baseline beyond the noise band
  is a regression;
- **bench evidence** — the headline metric series across BENCH_r*.json
  files (one value per round); the newest value falling below (for
  throughput) or above (for latency) the median of the prior rounds
  beyond the noise band is a regression.

The noise band is deliberately wide by default (25%): engines run on
shared CI hosts and the gate exists to catch step changes (an
accidental recompile per request, a lost fusion), not 3% jitter.

Consumed two ways, same evaluate():

- offline: tools/check_regression.py, the CI gate (nonzero exit on
  regression), run clean over the repo's real BENCH_r01–r05 history;
- live: the serve-mode SLO sentinel evaluates the ledger tail each
  tick; a breach counts `perf_regression` into the live registry and
  the emitted event reaches the flight recorder's bundle trigger
  (runtime/obs/recorder.py) through the record-sink path.
"""

from __future__ import annotations

import json
import os

from .ledger import _percentile

DEFAULT_NOISE_BAND = 0.25
# Ledger halves below this many rows per side say nothing: skip, don't
# guess. Bench series need fewer — each point is already a median-ish
# round headline.
DEFAULT_MIN_SAMPLES = 5
DEFAULT_MIN_BENCH_POINTS = 3

# Mean compiles/request may legitimately wobble by a fraction of a
# compile (one extra cold shape in the recent half); the absolute
# slack keeps tiny-denominator ratios from flagging noise.
COMPILE_ABS_SLACK = 0.5


def _higher_is_better(metric: str, unit: str | None) -> bool:
    """Direction of a bench headline: throughput-like metrics regress
    downward, latency-like metrics regress upward."""
    m = (metric or "").lower()
    u = (unit or "").lower()
    if "latency" in m or u in ("s", "ms", "us"):
        return False
    return True


def _split_halves(vals: list) -> tuple[list, list]:
    mid = len(vals) // 2
    return vals[:mid], vals[mid:]


def _check(name: str, baseline: float, recent: float,
           n_baseline: int, n_recent: int, noise_band: float,
           higher_is_better: bool = False,
           abs_slack: float = 0.0) -> dict:
    """One named comparison. Regressed when `recent` is worse than
    `baseline` by more than the band (plus any absolute slack)."""
    if higher_is_better:
        limit = baseline * (1.0 - noise_band) - abs_slack
        ok = recent >= limit
    else:
        limit = baseline * (1.0 + noise_band) + abs_slack
        ok = recent <= limit
    return {
        "check": name,
        "baseline": round(float(baseline), 6),
        "recent": round(float(recent), 6),
        "limit": round(float(limit), 6),
        "n_baseline": n_baseline,
        "n_recent": n_recent,
        "higher_is_better": higher_is_better,
        "ok": bool(ok),
    }


# -- ledger history ----------------------------------------------------


def evaluate_ledger_rows(rows: list[dict],
                         noise_band: float = DEFAULT_NOISE_BAND,
                         min_samples: int = DEFAULT_MIN_SAMPLES,
                         ) -> list[dict]:
    """Baseline-vs-recent checks over valid ledger request rows:
    per-engine p50 total latency, p50 execute-stage latency, and mean
    backend compiles per request. Engines without `min_samples` rows
    in BOTH halves are skipped (no check, not a pass)."""
    per_engine: dict = {}
    for row in rows:
        if row.get("kind") != "request" or not row.get("ok"):
            continue
        eng = row.get("engine_used") or row.get("engine_requested")
        if not eng:
            continue
        e = per_engine.setdefault(
            eng, {"latency": [], "execute": [], "compiles": []}
        )
        ts = float(row.get("ts", 0.0))
        lat = row.get("latency_s")
        e["latency"].append(
            (ts, float(lat)) if lat is not None else None
        )
        ex = row.get("execute_s")
        e["execute"].append(
            (ts, float(ex)) if ex is not None else None
        )
        cd = row.get("compile_delta")
        e["compiles"].append(
            (ts, float((cd or {}).get("backend_compiles", 0) or 0))
        )
    checks: list[dict] = []
    for eng in sorted(per_engine):
        e = per_engine[eng]
        series = {
            "latency_p50_s": ([p for p in e["latency"] if p], "p50"),
            "execute_p50_s": ([p for p in e["execute"] if p], "p50"),
            "compiles_mean": (e["compiles"], "mean"),
        }
        for label, (pairs, agg) in series.items():
            pairs = sorted(pairs)  # oldest -> newest by ts
            base, recent = _split_halves([v for _ts, v in pairs])
            if len(base) < min_samples or len(recent) < min_samples:
                continue
            if agg == "p50":
                b = _percentile(sorted(base), 0.50)
                r = _percentile(sorted(recent), 0.50)
                slack = 0.0
            else:
                b = sum(base) / len(base)
                r = sum(recent) / len(recent)
                slack = COMPILE_ABS_SLACK
            checks.append(_check(
                f"ledger:{eng}:{label}", b, r, len(base),
                len(recent), noise_band, abs_slack=slack,
            ))
    return checks


# -- bench evidence ----------------------------------------------------


def load_bench_history(paths: list[str]) -> list[dict]:
    """Parse BENCH_r*.json evidence files into headline points.

    Each file's "tail" holds the bench run's last stdout lines; the
    headline is the JSON metric line ({"metric", "value", "unit",
    ...}). Files without a parsable metric line (a crashed round)
    yield no point — the series simply has a hole, the same policy as
    every other ledger reader. Points come back in input path order,
    so sorted BENCH_r01..r05 paths give chronological order.
    """
    points: list[dict] = []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        found = None
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed \
                and "value" in parsed:
            found = parsed
        else:
            for line in doc.get("tail") or []:
                if not isinstance(line, str):
                    continue
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict) and "metric" in obj \
                        and "value" in obj:
                    found = obj
        if found is None:
            continue
        try:
            value = float(found["value"])
        except (TypeError, ValueError):
            continue
        points.append({
            "path": os.path.basename(path),
            "metric": str(found["metric"]),
            "value": value,
            "unit": found.get("unit"),
        })
    return points


def evaluate_bench_history(points: list[dict],
                           noise_band: float = DEFAULT_NOISE_BAND,
                           min_points: int = DEFAULT_MIN_BENCH_POINTS,
                           ) -> list[dict]:
    """Newest-vs-history checks per bench metric: the latest point
    against the median of all prior points. Series shorter than
    `min_points` are skipped."""
    by_metric: dict = {}
    for p in points:
        by_metric.setdefault(p["metric"], []).append(p)
    checks: list[dict] = []
    for metric in sorted(by_metric):
        series = by_metric[metric]
        if len(series) < min_points:
            continue
        prior = sorted(p["value"] for p in series[:-1])
        newest = series[-1]
        baseline = _percentile(prior, 0.50)
        checks.append(_check(
            f"bench:{metric}", baseline, newest["value"],
            len(prior), 1, noise_band,
            higher_is_better=_higher_is_better(
                metric, newest.get("unit")
            ),
        ))
    return checks


# -- combined ----------------------------------------------------------


def evaluate(rows: list[dict] | None = None,
             bench_paths: list[str] | None = None,
             noise_band: float = DEFAULT_NOISE_BAND,
             min_samples: int = DEFAULT_MIN_SAMPLES) -> dict:
    """The full regression report: ledger checks + bench checks.

    ok=True means no check regressed — including the vacuous case of
    too little history for any check at all ("insufficient data" is
    reported, never failed: a fresh deployment has no trajectory to
    regress against).
    """
    checks: list[dict] = []
    if rows:
        checks.extend(evaluate_ledger_rows(
            rows, noise_band=noise_band, min_samples=min_samples
        ))
    bench_points: list[dict] = []
    if bench_paths:
        bench_points = load_bench_history(bench_paths)
        checks.extend(evaluate_bench_history(
            bench_points, noise_band=noise_band
        ))
    return {
        "ok": all(c["ok"] for c in checks),
        "checks": checks,
        "regressed": [c for c in checks if not c["ok"]],
        "noise_band": noise_band,
        "ledger_rows": len(rows or ()),
        "bench_points": len(bench_points),
    }


def format_report(report: dict) -> list[str]:
    """The report as printable lines (the CI gate / serve stderr)."""
    lines = [
        "regression: %s (%d check(s), band ±%.0f%%, %d ledger row(s),"
        " %d bench point(s))" % (
            "ok" if report["ok"] else "REGRESSED",
            len(report["checks"]), report["noise_band"] * 100.0,
            report["ledger_rows"], report["bench_points"],
        )
    ]
    for c in report["checks"]:
        direction = "min" if c["higher_is_better"] else "max"
        lines.append(
            "  %-36s %s baseline=%g recent=%g (%s allowed %g, "
            "n=%d/%d)" % (
                c["check"], "ok" if c["ok"] else "REGRESSED",
                c["baseline"], c["recent"], direction, c["limit"],
                c["n_baseline"], c["n_recent"],
            )
        )
    if not report["checks"]:
        lines.append("  (insufficient history for any check)")
    return lines
