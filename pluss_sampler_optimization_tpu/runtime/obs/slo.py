"""SLO burn-rate sentinel over the live registry and the ledger tail.

The metrics registry (runtime/obs/metrics.py) answers "what are the
numbers right now"; this module answers the operator question one
level up — "are we inside budget, and if not, how fast are we burning
it". Objectives come from config.SLOConfig; the evaluation uses the
SRE multi-window burn-rate formulation:

- each objective defines a *budget*: the fraction of requests allowed
  to violate it (latency_budget for "slower than latency_p95_s",
  error_budget for "failed or degraded");
- the *burn rate* of a window is the observed violation fraction
  divided by the budget — 1.0 means the error budget is being spent
  exactly as fast as the SLO allows;
- a check breaches only when the burn rate exceeds the threshold in
  BOTH the short window (fast signal) and the long window (evidence
  the regression is sustained), so one slow request can't fire but a
  sustained regression fires within ~one short window.

Two evaluation sources, same report shape:

- **live** (`evaluate(config, registry=...)`) — latency from the
  `request_total_s` rolling histogram, error rates from the
  windowed `service_*` counters the executor mirrors into the
  registry; used by the serve-mode sentinel thread.
- **ledger tail** (`evaluate(config, rows=...)`) — the same checks
  recomputed from request-row timestamps/latencies (windows anchored
  at the newest row, so archived ledgers audit their own era), plus
  the drift-breach and batch-occupancy checks that only the ledger
  can answer; used by tools/check_slo.py as the offline CI gate.

`SLOSentinel` is the serve-mode background thread: every `interval_s`
it evaluates, stores the latest report (surfaced in the `metrics`
serve response), counts `slo_evaluations`, and on breach emits an
`slo_breach` telemetry event plus the `slo_breach` counter (both of
which mirror straight back into the registry — a scrape shows the
breach without reading the report).
"""

from __future__ import annotations

import re
import threading
import time

from .. import telemetry
from ...config import SLOConfig

DEFAULT_SLO = SLOConfig()

# Histogram the executor records total request latency into.
LATENCY_HISTOGRAM = "request_total_s"


def window_span_s(label: str) -> float:
    """Seconds covered by a window label like "30s" / "5m" / "1h"."""
    m = re.fullmatch(r"(\d+(?:\.\d+)?)([smh])", label)
    if not m:
        raise ValueError(f"bad window label {label!r}")
    mult = {"s": 1.0, "m": 60.0, "h": 3600.0}[m.group(2)]
    return float(m.group(1)) * mult


def _burn_check(name: str, fractions: dict, budget: float,
                threshold: float, detail: dict) -> dict:
    """Build one check from per-window violation fractions. A window
    with no data (None) contributes no evidence; breaching requires
    BOTH windows over threshold."""
    burn = {
        lbl: (None if frac is None else frac / budget)
        for lbl, frac in fractions.items()
    }
    over = [b is not None and b > threshold for b in burn.values()]
    breach = len(over) > 0 and all(over)
    out = {"name": name, "ok": not breach, "burn": burn,
           "budget": budget}
    out.update(detail)
    return out


def _registry_checks(config: SLOConfig, registry, now) -> list[dict]:
    checks: list[dict] = []
    short, long_ = config.windows
    if config.latency_p95_s is not None:
        fracs = {
            lbl: registry.histogram_fraction_over(
                LATENCY_HISTOGRAM, lbl, config.latency_p95_s, now=now
            )
            for lbl in (short, long_)
        }
        checks.append(_burn_check(
            "latency_p95", fracs, config.latency_budget,
            config.burn_rate_threshold,
            {"latency_p95_s": config.latency_p95_s,
             "observed_p95": {
                 lbl: registry.histogram_quantile(
                     LATENCY_HISTOGRAM, lbl, 0.95, now=now)
                 for lbl in (short, long_)
             }},
        ))
    fracs = {}
    for lbl in (short, long_):
        submitted = registry.counter_window("service_submitted", lbl,
                                            now=now)
        bad = (registry.counter_window("service_failed", lbl, now=now)
               + registry.counter_window("service_degraded", lbl,
                                         now=now))
        fracs[lbl] = (bad / submitted) if submitted > 0 else None
    checks.append(_burn_check(
        "error_budget", fracs, config.error_budget,
        config.burn_rate_threshold, {},
    ))
    return checks


def _row_checks(config: SLOConfig, rows: list, now) -> list[dict]:
    from . import ledger as ledger_mod

    checks: list[dict] = []
    short, long_ = config.windows
    spans = {short: window_span_s(short), long_: window_span_s(long_)}
    req = [r for r in rows if r.get("kind") == "request"]
    if now is None:
        now = max((float(r["ts"]) for r in req), default=time.time())

    def in_window(lbl):
        return [r for r in req
                if now - float(r["ts"]) <= spans[lbl]]

    if config.latency_p95_s is not None:
        fracs = {}
        for lbl in (short, long_):
            win = [r for r in in_window(lbl)
                   if r.get("latency_s") is not None]
            fracs[lbl] = (
                sum(1 for r in win
                    if float(r["latency_s"]) > config.latency_p95_s)
                / len(win)
            ) if win else None
        checks.append(_burn_check(
            "latency_p95", fracs, config.latency_budget,
            config.burn_rate_threshold,
            {"latency_p95_s": config.latency_p95_s},
        ))
    fracs = {}
    for lbl in (short, long_):
        win = in_window(lbl)
        # submit-weighted, matching the live counters: a row speaks
        # for itself plus its singleflight joiners
        total = sum(1 + int(r.get("coalesced") or 0) for r in win)
        bad = sum(
            (1 + int(r.get("coalesced") or 0))
            for r in win
            if (not r["ok"]) or r.get("degraded")
        )
        fracs[lbl] = (bad / total) if total > 0 else None
    checks.append(_burn_check(
        "error_budget", fracs, config.error_budget,
        config.burn_rate_threshold, {},
    ))

    # drift: any breached drift row inside the long window (latest per
    # (model, n) wins, same rule as the ledger aggregate)
    latest: dict = {}
    for r in rows:
        if r.get("kind") == "drift":
            latest[(r["model"], r["n"])] = r
    breached = [
        {"model": m, "n": n}
        for (m, n), r in sorted(latest.items())
        if r.get("breach") and now - float(r["ts"]) <= spans[long_]
    ]
    checks.append({
        "name": "drift", "ok": not breached, "burn": None,
        "breached": breached,
    })

    if config.min_batch_occupancy is not None:
        occ = ledger_mod.aggregate(req)["batching"]["occupancy_p50"]
        has_batches = any(r.get("batch_id") for r in req)
        ok = (not has_batches) or occ >= config.min_batch_occupancy
        checks.append({
            "name": "batch_occupancy", "ok": ok, "burn": None,
            "occupancy_p50": occ,
            "min_batch_occupancy": config.min_batch_occupancy,
        })
    return checks


def evaluate(config: SLOConfig = DEFAULT_SLO, registry=None,
             rows=None, now=None) -> dict:
    """Evaluate every applicable SLO check; returns
    {"ok", "checks": [...], "windows"}. With a registry the live
    latency/error checks run; with ledger rows the row-derived checks
    (including drift and occupancy) run; with both, both sets run
    (check names are distinct per source only for latency/error — the
    registry wins, rows add drift/occupancy)."""
    checks: list[dict] = []
    if registry is not None:
        checks.extend(_registry_checks(config, registry, now))
    if rows is not None:
        row_checks = _row_checks(config, rows, now)
        if registry is not None:
            # live counters already cover latency/error; keep only the
            # ledger-exclusive checks to avoid double reporting
            row_checks = [c for c in row_checks
                          if c["name"] in ("drift", "batch_occupancy")]
        checks.extend(row_checks)
    return {
        "ok": all(c["ok"] for c in checks),
        "checks": checks,
        "windows": list(config.windows),
    }


def format_report(report: dict) -> list[str]:
    """Human-readable lines, one per check, for the CLI gate."""
    lines = []
    for c in report["checks"]:
        status = "ok" if c["ok"] else "BREACH"
        if c.get("burn"):
            burns = " ".join(
                f"burn[{lbl}]={'-' if b is None else format(b, '.3g')}"
                for lbl, b in c["burn"].items()
            )
            lines.append(f"slo {c['name']}: {status} {burns} "
                         f"budget={c['budget']:g}")
        else:
            lines.append(f"slo {c['name']}: {status}")
    lines.append(
        "slo overall: " + ("ok" if report["ok"] else "BREACH")
    )
    return lines


class SLOSentinel:
    """Background evaluator for serve mode: periodically runs
    `evaluate` against the live registry (and the ledger tail when a
    path is configured), keeps the latest report, and emits
    `slo_breach` telemetry (event + counter, mirrored into the
    registry) for every breached check."""

    def __init__(self, config: SLOConfig = DEFAULT_SLO, registry=None,
                 ledger_path: str | None = None,
                 interval_s: float = 10.0, tail_rows: int = 512,
                 regress_bench: list[str] | None = None,
                 regress_noise_band: float | None = None):
        self.config = config
        self.registry = registry
        self.ledger_path = ledger_path
        self.interval_s = float(interval_s)
        self.tail_rows = int(tail_rows)
        # Perf-regression leg (runtime/obs/regress.py): evaluated on
        # the same tick over the same ledger tail, plus any BENCH_r*
        # evidence files handed in. None band = module default.
        self.regress_bench = list(regress_bench or [])
        self.regress_noise_band = regress_noise_band
        self.last_report: dict | None = None
        self.last_regression: dict | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def evaluate_once(self, now=None) -> dict:
        rows = None
        if self.ledger_path:
            from . import ledger as ledger_mod

            rows = ledger_mod.tail(self.ledger_path, self.tail_rows)
        report = evaluate(self.config, registry=self.registry,
                          rows=rows, now=now)
        self.last_report = report
        telemetry.count("slo_evaluations")
        for c in report["checks"]:
            if not c["ok"]:
                telemetry.count("slo_breach")
                burn = c.get("burn") or {}
                telemetry.event(
                    "slo_breach", check=c["name"],
                    **{f"burn_{lbl}": b for lbl, b in burn.items()
                       if b is not None},
                )
        self._evaluate_regression(rows)
        return report

    def _evaluate_regression(self, rows) -> None:
        """The perf-regression leg of the tick: ledger-tail + bench
        trajectory through regress.evaluate(). A breach counts
        `perf_regression` into the live registry and the event reaches
        the flight recorder's bundle trigger via the record sink; a
        broken evaluation only counts — neither takes serving down."""
        if rows is None and not self.regress_bench:
            return
        from . import regress

        try:
            kwargs = {}
            if self.regress_noise_band is not None:
                kwargs["noise_band"] = self.regress_noise_band
            rep = regress.evaluate(
                rows=rows, bench_paths=self.regress_bench, **kwargs
            )
        except Exception:
            telemetry.count("regress_eval_failed")
            return
        self.last_regression = rep
        if not rep["ok"]:
            telemetry.count("perf_regression")
            telemetry.event(
                "perf_regression",
                regressed=[c["check"] for c in rep["regressed"]],
            )

    def start(self) -> "SLOSentinel":
        if self._thread is not None:
            return self

        def _loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.evaluate_once()
                except Exception:  # never kill serving on a bad eval
                    telemetry.count("slo_eval_failed")

        self._thread = threading.Thread(
            target=_loop, name="pluss-slo-sentinel", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
