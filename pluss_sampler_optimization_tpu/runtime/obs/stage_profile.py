"""Per-stage device micro-profile of the sampled engine (library form).

This is the offline complement to the sampling wall-clock profiler
(runtime/obs/profiler.py): where the sampler answers "where does wall
time go across the whole serving path", this module answers "how long
does each engine stage take on the live device" — key decode,
geometry, next-use solve, classify, the fixed_k_unique reduction, the
on-device draw, and the scan-fused whole-buffer kernel, each timed as
a device-synced telemetry span (`Span.block` under
`enable(device_sync=True)`; wall alone would time only the async
dispatch).

`tools/profile_tpu_stages.py` is the CLI wrapper around
`profile_stages()` — both profiling entry points now live under
runtime/obs. Pass `profile_hz` to run the sampling profiler over the
same stage reps and get its snapshot alongside the stage medians, so
one invocation yields both views of the same work.
"""

from __future__ import annotations

import time


def profile_stages(n: int = 512, model: str = "gemm", ref: int = 0,
                   reps: int = 5, telemetry_out: str | None = None,
                   profile_hz: float | None = None,
                   out=print) -> dict:
    """Time each sampled-engine stage on the claimed device; returns
    `{"device": ..., "batch": ..., "stage_ms": {stage: median_ms},
    "profile": snapshot-or-None}` and prints a human summary via
    `out` (pass `out=lambda *a: None` to silence)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir", ".jax_cache")
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", 1.0
    )
    out(f"device: {jax.devices()[0]}")

    from ... import MachineConfig, SamplerConfig
    from ...core.trace import ProgramTrace
    from ...models import REGISTRY
    from ...ops.histogram import fixed_k_unique
    from ...sampler.sampled import (
        _best_sink,
        _sample_geometry,
        _sample_highs,
        classify_samples,
        decode_sample_keys,
        default_batch,
    )
    from .. import telemetry
    from . import profiler as obs_profiler

    # device_sync=True: each stage span's .block() records the
    # span-start -> block_until_ready latency as sync_s — the
    # device-complete time, which is what a stage profile must report
    tele = telemetry.enable(device_sync=True)
    prof = (obs_profiler.enable(hz=profile_hz)
            if profile_hz else None)
    stage_ms: dict = {}

    def med_time(name, fn, *fn_args, n_reps=reps):
        """Median device-synced seconds of `n_reps` span-wrapped calls
        (one warm call first so compile time stays out of the reps —
        it still lands in the telemetry compile counters)."""
        jax.block_until_ready(fn(*fn_args))
        for _ in range(n_reps):
            with telemetry.span(name, stage=True) as sp:
                sp.block(fn(*fn_args))
        ts = sorted(
            s.sync_s for s in tele.find_spans(name)
            if s.sync_s is not None
        )[-n_reps:]
        med = ts[len(ts) // 2]
        stage_ms[name] = round(med * 1e3, 3)
        return med

    machine = MachineConfig()
    prog = REGISTRY[model](n)
    trace = ProgramTrace(prog, machine)
    nt = trace.nests[0]
    cfg = SamplerConfig(ratio=0.1, seed=0)
    highs, _ = _sample_highs(nt, ref, cfg)
    batch = default_batch()
    rng = np.random.default_rng(0)
    space = int(np.prod(highs))
    keys = jnp.asarray(
        rng.integers(0, space, size=batch, dtype=np.int64)
    )
    out(f"batch={batch} highs={highs}")

    result = {
        "device": str(jax.devices()[0].platform),
        "model": model,
        "n": n,
        "ref": ref,
        "batch": batch,
        "stage_ms": stage_ms,
        "profile": None,
    }

    dec = jax.jit(lambda k: decode_sample_keys(k, tuple(highs)))
    t = med_time("decode", dec, keys)
    out(f"decode:          {t * 1e3:9.2f} ms")

    samples = dec(keys)

    geo = jax.jit(lambda s: _sample_geometry(nt, ref, s))
    t = med_time("geometry", geo, samples)
    out(f"geometry:        {t * 1e3:9.2f} ms")

    tid, p0, line, m0 = geo(samples)

    sink = jax.jit(
        lambda a, b, c, d: _best_sink(nt, ref, a, b, c, d)
    )
    t = med_time("best_sink", sink, tid, p0, line, m0)
    out(f"best_sink:       {t * 1e3:9.2f} ms")

    cls = jax.jit(lambda s: classify_samples(nt, ref, s))
    t = med_time("classify", cls, samples)
    out(f"classify (all):  {t * 1e3:9.2f} ms")

    packed, _, _, found = cls(samples)
    w = jnp.arange(batch, dtype=jnp.int64) < (batch - 7)

    uniq = jax.jit(
        lambda v, m: fixed_k_unique(v, m, 64), static_argnums=()
    )
    t = med_time("fixed_k_unique", uniq, packed, found & w)
    out(f"fixed_k_unique:  {t * 1e3:9.2f} ms")

    # The redesigned engine's stages: on-device draw (threefry +
    # sort-dedup + priority thinning) and the scan-fused whole-buffer
    # kernel — the two dispatches a ref actually costs since the
    # round-3 transfer redesign.
    from ...sampler.draw import draw_sample_keys_device
    from ...sampler.sampled import _build_ref_kernel_scan, _pad_highs

    cfg_draw = SamplerConfig(ratio=0.1, seed=0, device_draw=True)
    t0 = time.perf_counter()
    drawn = draw_sample_keys_device(nt, ref, cfg_draw, 0, batch)
    t_cold = time.perf_counter() - t0
    if drawn is None:
        out("device draw:     declined (over budget / empty space)")
        _finish(result, tele, prof, telemetry_out, out)
        return result
    dk, dm, s, dhighs = drawn
    for r in range(1, reps + 1):
        with telemetry.span("device_draw", stage=True) as sp:
            sp.block(draw_sample_keys_device(
                nt, ref, cfg_draw, r, batch
            )[0])
    ts = sorted(
        sp.sync_s for sp in tele.find_spans("device_draw")
        if sp.sync_s is not None
    )
    med = ts[len(ts) // 2]
    stage_ms["device_draw"] = round(med * 1e3, 3)
    out(f"device draw:     {med * 1e3:9.2f} ms  "
        f"(cold {t_cold:.1f} s; B={dk.shape[0]}, s={s})")

    kscan = _build_ref_kernel_scan(nt, ref)
    nc = dk.shape[0] // batch
    t = med_time(
        "scan_kernel",
        lambda: kscan(
            dk, dm, _pad_highs(dhighs), nt.vals, np.int64(ref), 64, nc
        ),
        n_reps=min(3, reps),
    )
    out(f"scan kernel:     {t * 1e3:9.2f} ms  (n_chunks={nc})")
    _finish(result, tele, prof, telemetry_out, out)
    return result


def _finish(result: dict, tele, prof, telemetry_out, out) -> None:
    from .. import telemetry
    from . import profiler as obs_profiler

    if prof is not None:
        obs_profiler.disable()
        result["profile"] = prof.snapshot()
    telemetry.disable()
    tele.print_summary()
    if telemetry_out:
        tele.write_json(telemetry_out)
        out(f"telemetry JSON -> {telemetry_out}")
