"""Structural typing for PRI-state consumers (avoids import cycles)."""

from __future__ import annotations

from typing import Protocol

from .hist import Hist


class PRIStateLike(Protocol):
    def merged_noshare(self) -> Hist: ...

    def merged_share(self): ...
