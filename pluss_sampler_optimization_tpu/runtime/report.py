"""Output formats matching the reference's stdout dumps.

The reference's observability surface is CSV-ish stdout dumps
(`_pluss_histogram_print`, pluss_utils.h:690-702; MRC print with
run-length compression of flat segments, pluss_utils.h:851-883; file
writer, :885-913). The accuracy harness diffs these dumps across
implementations (Makefile:39-41, README.md:10-12), so the formats are
kept byte-compatible where the reference's are deterministic (sorted
keys; unordered_map iteration order itself is not deterministic, which
is why the reference sorts into a std::map before printing, :692-698).
"""

from __future__ import annotations

from typing import Iterable

import numpy as np

from .hist import Hist, merge_hists


def _fmt(v: float) -> str:
    # std::cout default formatting for double: 6 significant digits.
    return f"{v:.6g}"


def histogram_lines(title: str, hist: Hist) -> list[str]:
    """`_pluss_histogram_print` (pluss_utils.h:690-702)."""
    out = [title]
    total = sum(hist.values())
    for k in sorted(hist):
        frac = hist[k] / total if total else float("nan")
        out.append(f"{k},{_fmt(hist[k])},{_fmt(frac)}")
    return out


def noshare_dump(state) -> list[str]:
    """pluss_cri_noshare_print_histogram (pluss_utils.h:938-948)."""
    merged = merge_hists(state.noshare, in_log_format=False)
    return histogram_lines("Start to dump noshare private reuse time", merged)


def share_dump(state) -> list[str]:
    """pluss_cri_share_print_histogram (pluss_utils.h:949-960)."""
    merged: Hist = {}
    for per_tid in state.share:
        for h in per_tid.values():
            for k, v in h.items():
                merged[k] = merged.get(k, 0.0) + v
    return histogram_lines("Start to dump share private reuse time", merged)


def rih_dump(rih: Hist) -> list[str]:
    """pluss_print_histogram (pluss_utils.h:748-751)."""
    return histogram_lines("Start to dump reuse time", rih)


def mrc_lines(mrc: np.ndarray, header: bool = True) -> list[str]:
    """pluss_print_mrc run-length compression (pluss_utils.h:851-883).

    Prints the first index of each flat segment and, when the segment is
    longer than one entry, its last index; flatness is
    value[start] - value[next] < 0.00001 (:863).
    """
    out = ["miss ratio"] if header else []
    n = len(mrc)
    i1 = 0
    while i1 < n:
        i2 = i1
        while i2 + 1 < n and mrc[i1] - mrc[i2 + 1] < 0.00001:
            i2 += 1
        out.append(f"{i1}, {_fmt(mrc[i1])}")
        if i2 != i1:
            out.append(f"{i2}, {_fmt(mrc[i2])}")
        i1 = i2 + 1
    return out


def write_mrc_to_file(mrc: np.ndarray, path: str) -> None:
    """pluss_write_mrc_to_file (pluss_utils.h:885-913); written
    atomically (runtime/io.py) so a killed process never leaves a
    truncated curve behind."""
    from .io import atomic_write_text

    atomic_write_text(
        path, "".join(line + "\n" for line in mrc_lines(mrc))
    )


def emit(lines: Iterable[str]) -> None:
    print("\n".join(lines))
