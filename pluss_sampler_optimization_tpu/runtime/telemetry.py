"""Telemetry runtime — spans, counters, and device metrics for every
engine.

Before this layer, all observability was ad-hoc host code inside
bench.py (compile counters, host fingerprint, cgroup throttle reads)
and the one-off tools/profile_tpu_stages.py — the engines were black
boxes between dispatch and fetch. This module is the shared spine:

- **Hierarchical spans.** `with span("draw"):` records wall time into
  the per-run `Telemetry` object's span tree; spans nest via a
  thread-local stack. The span handle's `.block(value)` optionally
  adds device-sync timing (`jax.block_until_ready`, recorded as
  `sync_s`) when the run was enabled with `device_sync=True`; with
  device sync off it returns the value untouched, so instrumented
  code never changes the engines' async dispatch pipelines.
- **Counters / gauges / events.** `count("dispatch")` style counters
  (the engines count dispatches and bytes fetched to host), free-form
  gauges, and bounded structured events (`event("accel_probe", ...)`).
  The sampled engine's cross-ref fusion adds a small contract here:
  `dispatches_fused` counts dispatches that carried a stacked ref
  bucket, `pipeline_stalls` counts forced drains of the depth-bounded
  async pipeline, and the end-of-run gauges `ref_buckets`,
  `expected_chunks`, `refs_per_dispatch`, and `pipeline_overlap_s`
  describe the bucket plan — tools/check_dispatch_stats.py audits
  `dispatches <= ref_buckets * expected_chunks + capacity_regrows`
  from an exported run to catch silent fusion regressions.
  The service's cross-request batching extends the contract:
  `batches_formed` / `batch_members` count admission-window flushes
  and their member totals, `dispatches_batched` marks dispatches that
  carried rows from several requests, the `batch_occupancy` /
  `batch_queue_depth` gauges track the scheduler, `batch_jobs` +
  `ref_buckets_union` describe the union bucket plan (the checker
  prefers `ref_buckets_union` for its bound when present), and
  `service_batch_failed` / `service_batch_fallback_solo` count
  batch-level failures and members degraded to solo execution.
- **jax.monitoring capture.** A process-global listener pair
  (registered once — jax listeners cannot be unregistered) accumulates
  EVERY monitoring event count and duration by key; each `Telemetry`
  snapshots the store at enable and exports the delta, so a run's JSON
  reports only its own compile events / compile seconds. This
  generalizes bench.py's old `_register_compile_counters`;
  `compile_counters_snapshot()` keeps that function's exact dict shape
  for the bench evidence files.
- **Host/device metrics.** `host_fingerprint()` (identity + optional
  measured speed probe), `cpu_features_hash()` (cache-dir scoping),
  `read_cpu_throttle()` (cgroup-v2 counters), and `device_metrics()`
  (platform, device count, per-device memory_stats when the backend
  reports them) all live here; bench.py consumes them.
- **Structured JSON export** with a stable schema
  (`SCHEMA_VERSION`; validated by tools/check_telemetry_schema.py and
  pinned by tests/test_telemetry.py) plus a compact stderr summary.

The module-level enable switch keeps the disabled path a no-op: when
no run is active, `span()` returns a shared singleton context manager
and `count()`/`record_fetch()` are a single attribute check — the
overhead bound is pinned by test (test_telemetry.py), and with
telemetry disabled the instrumented engines are bit-identical to the
uninstrumented code because nothing in this module executes.
"""

from __future__ import annotations

import json
import sys
import threading
import time

from . import lockwitness

SCHEMA_VERSION = 1

# Recorded-span cap: a pathological run (millions of chunks) degrades
# to counting dropped spans instead of growing without bound.
_MAX_SPANS = 50_000
_MAX_EVENTS = 1_000

_lock = lockwitness.make_lock("telemetry._lock")
_tls = threading.local()
_current: "Telemetry | None" = None

# Cross-thread span visibility for the sampling profiler
# (runtime/obs/profiler.py): thread-local span stacks are invisible
# from any other thread, but the profiler's sampler thread must join
# `sys._current_frames()` (keyed by thread ident) with "what span is
# that thread inside right now". Each thread registers its own stack
# list here (under _lock) the first time it opens a span; the sampler
# reads a copied snapshot under the same lock. The stack lists
# themselves are mutated lock-free by their owning thread (append/pop
# in Span.__enter__/__exit__) — a concurrent reader may observe a
# stack mid-push and attribute one sample to the parent span instead
# of the child, which is exactly the tolerance a statistical profiler
# has anyway.
_thread_stacks: dict = {}

# Live metrics sink (runtime/obs/metrics.py registry) — when set by
# metrics.enable(), count()/gauge() mirror every write into it, so the
# per-run Telemetry and the live serving registry are two views of one
# write path. Kept as a bare module global read on the hot path: the
# disabled cost is one load + None check per call.
_metrics_sink = None


def set_metrics_sink(sink) -> None:
    """Install (or with None, remove) the live metrics sink. Called by
    runtime.obs.metrics.enable()/disable(); the sink needs `inc(name,
    v)` and `set_gauge(name, v)`."""
    global _metrics_sink
    _metrics_sink = sink


def metrics_sink():
    return _metrics_sink


# Flight-recorder sink (runtime/obs/recorder.py) — the second leg of
# the sink path: where _metrics_sink mirrors numeric count()/gauge()
# writes, _record_sink mirrors event() emissions, so anomaly events
# (slo_breach, replica_quarantined, drift_breach, ...) reach the
# recorder's trigger logic without every emit site knowing about it.
# Same discipline as the metrics sink: bare global, one None check on
# the disabled path, and a failing sink never takes the caller down.
_record_sink = None


def set_record_sink(sink) -> None:
    """Install (or with None, remove) the flight-recorder sink. Called
    by runtime.obs.recorder.enable()/disable(); the sink needs
    `record_event(name, data)`."""
    global _record_sink
    _record_sink = sink


def record_sink():
    return _record_sink


class _NullSpan:
    """Shared no-op span: the entire disabled-telemetry hot path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def block(self, value):
        return value


_NULL_SPAN = _NullSpan()


class Span:
    __slots__ = ("name", "attrs", "start_s", "wall_s", "sync_s",
                 "children", "_t0", "_tele")

    def __init__(self, tele: "Telemetry", name: str, attrs: dict):
        self._tele = tele
        self.name = name
        self.attrs = attrs
        self.children: list = []
        self.start_s = 0.0
        self.wall_s = 0.0
        self.sync_s = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        self.start_s = round(self._t0 - self._tele._t0, 6)
        stack = _span_stack()
        if stack:
            stack[-1].children.append(self)
        else:
            with _lock:
                self._tele.roots.append(self)
        stack.append(self)
        return self

    def __exit__(self, *exc):
        self.wall_s = round(time.perf_counter() - self._t0, 6)
        stack = _span_stack()
        if stack and stack[-1] is self:
            stack.pop()
        return False

    def block(self, value):
        """Optionally record device-sync time: with the run enabled
        under device_sync=True, block until `value` is ready and
        record the span-start -> ready latency as sync_s; otherwise
        pass the value through untouched (no extra synchronization —
        the engines' async pipelines stay async)."""
        if self._tele.device_sync:
            import jax

            jax.block_until_ready(value)
            self.sync_s = round(time.perf_counter() - self._t0, 6)
        return value

    def to_dict(self) -> dict:
        d: dict = {"name": self.name, "start_s": self.start_s,
                   "wall_s": self.wall_s}
        if self.attrs:
            d["attrs"] = self.attrs
        if self.sync_s is not None:
            d["sync_s"] = self.sync_s
        d["children"] = [c.to_dict() for c in self.children]
        return d


def _span_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _reset_span_stack()
    return stack


def _reset_span_stack() -> list:
    """Install a fresh span stack for the calling thread and register
    it in the cross-thread registry the profiler samples."""
    stack = _tls.stack = []
    with _lock:
        _thread_stacks[threading.get_ident()] = stack
    return stack


def span_paths_by_thread() -> dict:
    """Snapshot {thread_ident: "root/child/..."} of every registered
    thread's current span path ("" when the thread is idle between
    spans). Prunes entries for threads that no longer exist, so the
    registry stays bounded by the live thread count. Called from the
    profiler's sampler thread next to `sys._current_frames()`, which
    uses the same ident keys."""
    live = {
        t.ident for t in threading.enumerate() if t.ident is not None
    }
    with _lock:
        for tid in [t for t in _thread_stacks if t not in live]:
            del _thread_stacks[tid]
        snap = {
            tid: list(stack)
            for tid, stack in _thread_stacks.items()
        }
    return {
        tid: "/".join(s.name for s in stack)
        for tid, stack in snap.items()
    }


class Telemetry:
    """One run's recorded telemetry: span tree, counters, gauges,
    events, and the jax.monitoring baseline for delta export."""

    def __init__(self, device_sync: bool = False):
        self.device_sync = device_sync
        self.roots: list[Span] = []
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        self.events: list[dict] = []
        self._n_spans = 0
        self._t0 = time.perf_counter()
        self._duration_s: float | None = None
        self._jax_base = _monitor_snapshot()
        self._jax_final: dict | None = None

    # -- recording ----------------------------------------------------

    def _span(self, name: str, attrs: dict):
        if self._n_spans >= _MAX_SPANS:
            self.counters["spans_dropped"] = (
                self.counters.get("spans_dropped", 0) + 1
            )
            return _NULL_SPAN
        self._n_spans += 1
        return Span(self, name, attrs)

    def count(self, name: str, inc: float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + inc

    def gauge(self, name: str, value) -> None:
        self.gauges[name] = value

    def event(self, name: str, **data) -> None:
        if len(self.events) >= _MAX_EVENTS:
            self.counters["events_dropped"] = (
                self.counters.get("events_dropped", 0) + 1
            )
            return
        self.events.append({"name": name, "t_s": round(
            time.perf_counter() - self._t0, 6), **data})

    # -- export -------------------------------------------------------

    def find_spans(self, name: str) -> list[Span]:
        """All recorded spans with this name, in tree preorder."""
        out: list[Span] = []

        def walk(s: Span) -> None:
            if s.name == name:
                out.append(s)
            for c in s.children:
                walk(c)

        for r in self.roots:
            walk(r)
        return out

    def jax_delta(self) -> dict:
        """This run's jax.monitoring activity: event counts and
        duration totals since enable (final snapshot once disabled)."""
        now = self._jax_final or _monitor_snapshot()
        events = {
            k: v - self._jax_base["events"].get(k, 0)
            for k, v in now["events"].items()
            if v - self._jax_base["events"].get(k, 0)
        }
        durations = {}
        for k, (tot, cnt) in now["durations"].items():
            b_tot, b_cnt = self._jax_base["durations"].get(k, (0.0, 0))
            if cnt - b_cnt:
                durations[k] = {
                    "total_s": round(tot - b_tot, 4),
                    "count": cnt - b_cnt,
                }
        return {"events": events, "durations": durations}

    def to_json(self, speed_probe: bool = False) -> dict:
        dur = self._duration_s
        if dur is None:
            dur = time.perf_counter() - self._t0
        return {
            "schema_version": SCHEMA_VERSION,
            "enabled": True,
            "duration_s": round(dur, 6),
            "spans": [r.to_dict() for r in self.roots],
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "events": list(self.events),
            "jax_monitoring": self.jax_delta(),
            "device": device_metrics(),
            "host": host_fingerprint(speed_probe=speed_probe),
        }

    def write_json(self, path: str, speed_probe: bool = False) -> None:
        from .io import atomic_write_json

        atomic_write_json(path, self.to_json(speed_probe=speed_probe))

    def summary_lines(self, top: int = 12) -> list[str]:
        """Compact human summary: root spans with their heaviest
        children, counters, and the compile totals."""
        lines = []
        dur = self._duration_s
        if dur is None:
            dur = time.perf_counter() - self._t0
        lines.append(f"telemetry: run {dur:.3f}s, "
                     f"{self._n_spans} spans")

        agg: dict[str, tuple[float, int]] = {}

        def walk(s: Span) -> None:
            tot, cnt = agg.get(s.name, (0.0, 0))
            agg[s.name] = (tot + s.wall_s, cnt + 1)
            for c in s.children:
                walk(c)

        for r in self.roots:
            walk(r)
        for name, (tot, cnt) in sorted(
            agg.items(), key=lambda kv: -kv[1][0]
        )[:top]:
            lines.append(f"  span {name:<24s} {tot:9.3f}s  x{cnt}")
        if self.counters:
            parts = ", ".join(
                f"{k}={v:g}" for k, v in sorted(self.counters.items())
            )
            lines.append(f"  counters: {parts}")
        jd = self.jax_delta()
        bc = jd["durations"].get(
            "/jax/core/compile/backend_compile_duration"
        )
        if bc:
            lines.append(
                f"  compiles: {bc['count']} backend compiles, "
                f"{bc['total_s']:.2f}s"
            )
        for ev in self.events:
            lines.append(f"  event: {json.dumps(ev)[:160]}")
        return lines

    def print_summary(self, file=None) -> None:
        file = file if file is not None else sys.stderr
        for line in self.summary_lines():
            print(line, file=file)


# -- module-level switch ----------------------------------------------


def enable(device_sync: bool = False) -> Telemetry:
    """Start a telemetry run (replacing any active one) and return its
    Telemetry. Registers the jax.monitoring listeners (idempotent) so
    compile events land in the run's delta."""
    global _current
    try:
        register_jax_hooks()
    except Exception:
        pass  # jax absent/broken: spans and counters still work
    tele = Telemetry(device_sync=device_sync)
    _reset_span_stack()
    _current = tele
    return tele


def disable() -> "Telemetry | None":
    """Stop recording; stamps the run duration and the final
    jax.monitoring snapshot so later exports describe exactly the
    enabled window. Returns the stopped Telemetry (None if idle)."""
    global _current
    tele = _current
    _current = None
    if tele is not None:
        tele._duration_s = time.perf_counter() - tele._t0
        tele._jax_final = _monitor_snapshot()
    return tele


def current() -> "Telemetry | None":
    return _current


def span(name: str, **attrs):
    """Context manager recording one hierarchical span; the shared
    no-op singleton when telemetry is disabled."""
    tele = _current
    if tele is None:
        return _NULL_SPAN
    return tele._span(name, attrs)


def count(name: str, inc: float = 1) -> None:
    tele = _current
    if tele is not None:
        tele.count(name, inc)
    sink = _metrics_sink
    if sink is not None:
        sink.inc(name, inc)


def gauge(name: str, value) -> None:
    tele = _current
    if tele is not None:
        tele.gauge(name, value)
    sink = _metrics_sink
    if sink is not None:
        sink.set_gauge(name, value)


def event(name: str, **data) -> None:
    tele = _current
    if tele is not None:
        tele.event(name, **data)
    sink = _record_sink
    if sink is not None:
        # Observation must never sink the observed: a recorder bug
        # (full disk under the bundle dir, a bad state provider) is
        # its own problem, not the serving request's.
        try:
            sink.record_event(name, dict(data))
        except Exception:
            pass


def record_fetch(host_tree):
    """Count a device->host fetch's payload bytes (and the fetch
    itself) into the active run; pass-through, engines wrap their
    `jax.device_get` results: `out = record_fetch(jax.device_get(x))`.
    """
    tele = _current
    if tele is None:
        return host_tree
    nbytes = 0
    stack = [host_tree]
    while stack:
        x = stack.pop()
        if isinstance(x, (list, tuple)):
            stack.extend(x)
        elif isinstance(x, dict):
            stack.extend(x.values())
        else:
            nbytes += int(getattr(x, "nbytes", 0))
    tele.count("fetches")
    tele.count("bytes_fetched_to_host", nbytes)
    return host_tree


def counted_lru_cache(maxsize: int = 128,
                      counter: str = "kernel_cache"):
    """functools.lru_cache with telemetry hit/miss counters and an
    occupancy gauge.

    Drop-in for the kernel caches scattered across the engines
    (stream/draw/periodic/dense/sharded program-kernel caches): every
    lookup lands in `<counter>_hits` / `<counter>_misses` of the
    active run, so a telemetry export shows compiled-kernel reuse next
    to the result-cache counters the service records, and the
    `<counter>_size` / `<counter>_maxsize` gauges expose current
    occupancy vs capacity (cache pressure is visible in the
    Prometheus export before evictions start). `cache_clear` /
    `cache_info` pass through (tests clear these caches directly).
    The hit/miss attribution reads cache_info around the call — exact
    single-threaded; under concurrent lookups a race can misattribute
    a count, never miscompute a result."""
    import functools

    def deco(fn):
        cached = functools.lru_cache(maxsize=maxsize)(fn)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            # count() below feeds both the run and the live registry;
            # skip the cache_info bookkeeping only when neither view
            # is listening.
            if _current is None and _metrics_sink is None:
                return cached(*args, **kwargs)
            before = cached.cache_info().hits
            out = cached(*args, **kwargs)
            info = cached.cache_info()
            if info.hits > before:
                count(counter + "_hits")
            else:
                count(counter + "_misses")
            gauge(counter + "_size", info.currsize)
            gauge(counter + "_maxsize", info.maxsize)
            return out

        wrapper.cache_clear = cached.cache_clear
        wrapper.cache_info = cached.cache_info
        wrapper.__wrapped__ = cached
        return wrapper

    return deco


_warned_once: set = set()


def warn_once(key, message: str, **data) -> None:
    """One-line stderr warning, once per key per process, recorded as
    a telemetry event when a run is active (the event records every
    occurrence; only the stderr line dedupes)."""
    event("warning", key=str(key), message=message, **data)
    if key in _warned_once:
        return
    _warned_once.add(key)
    print(message, file=sys.stderr)


def __getattr__(name: str):
    """`telemetry.exporters` resolves to runtime/obs/exporters.py —
    the exporters live in the obs package (they pull in the ledger's
    neighbors), but callers reach them through the telemetry module
    they export. Lazy so the disabled-telemetry import stays light."""
    if name == "exporters":
        from .obs import exporters

        return exporters
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


# -- jax.monitoring capture -------------------------------------------

# Process-global accumulator: jax listeners cannot be unregistered, so
# one pair feeds this store forever and every run exports deltas.
_monitor: dict | None = None


def register_jax_hooks() -> dict:
    """Register the process-global jax.monitoring listeners (once) and
    return the live accumulator {"events": {key: n}, "durations":
    {key: [total_s, n]}}. Call after `import jax` and before the first
    backend touch to catch every compile event (the bench does)."""
    global _monitor
    if _monitor is not None:
        return _monitor
    import jax

    store: dict = {"events": {}, "durations": {}}

    def on_event(key, **kw):
        store["events"][key] = store["events"].get(key, 0) + 1

    def on_duration(key, dur, **kw):
        tot, cnt = store["durations"].get(key, (0.0, 0))
        # raw accumulation; rounding happens once at export so
        # per-event rounding error never piles up
        store["durations"][key] = (tot + dur, cnt + 1)

    jax.monitoring.register_event_listener(on_event)
    jax.monitoring.register_event_duration_secs_listener(on_duration)
    _monitor = store
    return store


def _monitor_snapshot() -> dict:
    if _monitor is None:
        return {"events": {}, "durations": {}}
    return {
        "events": dict(_monitor["events"]),
        "durations": dict(_monitor["durations"]),
    }


_COMPILE_EVENT_KEYS = {
    "cache_hits": "/jax/compilation_cache/cache_hits",
    "cache_misses": "/jax/compilation_cache/cache_misses",
    "compile_requests": "/jax/compilation_cache/compile_requests_use_cache",
}


def compile_counters_snapshot() -> dict:
    """The bench evidence files' compile-counter dict (cache hits/
    misses/requests + backend compile count/seconds), derived from the
    process-global store — byte-compatible with the shape bench.py's
    old private `_register_compile_counters`/`_snap_counters` emitted.
    """
    store = _monitor or {"events": {}, "durations": {}}
    snap = {
        name: store["events"].get(key, 0)
        for name, key in _COMPILE_EVENT_KEYS.items()
    }
    tot, cnt = store["durations"].get(
        "/jax/core/compile/backend_compile_duration", (0.0, 0)
    )
    snap["backend_compile_s"] = round(tot, 2)
    snap["backend_compiles"] = cnt
    return snap


# -- host / device metrics --------------------------------------------


def cpu_features_hash() -> str:
    """8-hex digest of the host CPU's model + ISA flags.

    XLA:CPU AOT cache entries bake in machine features INCLUDING
    tuning pseudo-features (prefer-no-gather/prefer-no-scatter) that
    are not part of the cache key; loading an entry compiled on a
    different host logs 'machine type ... doesn't match' warnings,
    risks SIGILL, and silently skews timings. bench.py scopes its
    CPU-fallback cache dir by this hash so executables never cross
    hosts; the model+flags lines cover every input XLA's feature
    detection uses.
    """
    import hashlib
    import platform

    try:
        with open("/proc/cpuinfo") as f:
            txt = f.read()
    except OSError:
        txt = ""
    lines = [
        ln for ln in txt.splitlines()
        # x86 naming first; ARM and friends spell identity differently
        # ('Features', 'CPU implementer', ...) — match those stable
        # identity lines explicitly rather than hashing the whole
        # first block, which contains per-boot-calibrated fields
        # (BogoMIPS, cpu MHz on some kernels) that would churn the
        # scoped cache dir across boots for no codegen-relevant reason
        if ln.startswith((
            "model name", "flags",
            "Features", "CPU implementer", "CPU architecture",
            "CPU variant", "CPU part", "CPU revision",
        ))
    ]
    # /proc/cpuinfo repeats identity lines once per logical CPU;
    # dedupe so the digest is invariant to the visible core count (two
    # containers on the same CPU model must share a cache dir)
    lines = list(dict.fromkeys(lines))[:8]
    # last resort (exotic /proc/cpuinfo): the whole first block, minus
    # lines with known per-boot fields
    ident = "\n".join(lines) if lines else "\n".join(
        ln for ln in txt.split("\n\n")[0].splitlines()
        if not ln.lower().startswith(("bogomips", "cpu mhz"))
    )
    ident += "|" + platform.machine()
    return hashlib.sha256(ident.encode()).hexdigest()[:8]


def host_fingerprint(speed_probe: bool = True) -> dict:
    """Identity + (optionally) measured speed of the host.

    Identity: /proc/cpuinfo model/frequency, boot/machine ids
    (same-container detection), hostname, and the CPU features hash.
    The speed probe is a fixed numpy workload (int64 sort + matmul,
    the engines' two dominant CPU primitives, ~0.5 s) whose wall time
    directly ranks hosts even when nominal frequencies lie (VMs pin
    cpu MHz to a constant); bench.py records it on every run — it was
    what explained the round-3 33% driver-vs-validation spread.
    Telemetry JSON exports skip it by default to stay cheap.
    """
    fp: dict = {}
    try:
        with open("/proc/cpuinfo") as f:
            txt = f.read()
        for key, tag in (("model name", "cpu_model"),
                         ("cpu MHz", "cpu_mhz"),
                         ("bogomips", "bogomips")):
            for line in txt.splitlines():
                if line.startswith(key):
                    fp[tag] = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    for path, tag in (("/proc/sys/kernel/random/boot_id", "boot_id"),
                      ("/etc/machine-id", "machine_id")):
        try:
            with open(path) as f:
                fp[tag] = f.read().strip()
        except OSError:
            pass
    try:
        import socket

        fp["hostname"] = socket.gethostname()
    except OSError:
        pass
    fp["cpu_features_hash"] = cpu_features_hash()
    if speed_probe:
        import numpy as np

        rng = np.random.default_rng(0)
        vals = rng.integers(0, 1 << 62, size=1 << 21, dtype=np.int64)
        mat = rng.standard_normal((256, 256))
        t0 = time.perf_counter()
        for _ in range(4):
            np.sort(vals)
        acc = mat
        for _ in range(8):
            acc = acc @ mat
        fp["speed_probe_s"] = round(time.perf_counter() - t0, 3)
    return fp


def read_cpu_throttle():
    """cgroup-v2 CPU throttle counters, or None when unreadable. A
    contended/quota-limited container shows up here even when loadavg
    looks calm."""
    try:
        with open("/sys/fs/cgroup/cpu.stat") as f:
            d = dict(
                line.split() for line in f if len(line.split()) == 2
            )
        return {
            k: int(d[k])
            for k in ("nr_throttled", "throttled_usec")
            if k in d
        }
    except (OSError, ValueError):
        return None


def device_metrics(max_devices: int = 8) -> dict:
    """Backend platform + per-device memory stats (bytes in use / peak
    / limit where the PJRT client reports them; CPU reports none).
    Never raises — a dead backend yields {"error": ...} so telemetry
    export cannot sink a run."""
    try:
        import jax

        devs = jax.devices()
        out: dict = {
            "platform": str(devs[0].platform),
            "device_count": len(devs),
            "devices": [],
        }
        for d in devs[:max_devices]:
            entry: dict = {"id": d.id, "kind": str(d.device_kind)}
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if ms:
                entry["memory"] = {
                    k: int(v) for k, v in ms.items()
                    if isinstance(v, (int, float)) and (
                        "bytes" in k or "size" in k
                    )
                }
            out["devices"].append(entry)
        return out
    except Exception as e:
        return {"platform": "unknown", "device_count": 0,
                "devices": [], "error": repr(e)}
