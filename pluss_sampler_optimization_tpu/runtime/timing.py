"""Timing runtime — the reference's timer + cache-flush protocol.

Mirrors c_lib/test/runtime/pluss.cpp:

- wall timer: `gettimeofday` delta in seconds (rtclock, pluss.cpp:45-54;
  start/stop/print :86-124) -> time.perf_counter here;
- optional cycle-accurate counter (`PLUSS_CYCLE_ACCURATE_TIMER`, RDTSC,
  pluss.cpp:57-69) -> time.perf_counter_ns;
- `_polybench_flush_cache` before timing: sum over a 2.5 MB calloc'd
  buffer to evict the LLC (pluss.cpp:71-81, POLYBENCH_CACHE_SIZE_KB
  2560 :9-11). Meaningful for the native CPU baseline; on TPU the
  equivalent staleness guard is executing with fresh device buffers,
  so flush() is a host-side no-op cost there.
"""

from __future__ import annotations

import time

import numpy as np

_CACHE_SIZE_KB = 2560  # POLYBENCH_CACHE_SIZE_KB, pluss.cpp:9-11


def flush_cache(cache_kb: int = _CACHE_SIZE_KB) -> float:
    """`_polybench_flush_cache` (pluss.cpp:71-81): walk a buffer larger
    than the LLC; returns the sum so the work cannot be elided."""
    cs = cache_kb * 1024 // 8
    # np.empty + fill dirties distinct physical pages; calloc-backed
    # np.zeros would alias every read onto the shared zero page and
    # leave the LLC warm.
    buf = np.empty(cs, dtype=np.float64)
    buf.fill(0.0)
    s = float(buf.sum())
    assert s <= 10.0  # polybench's own guard (pluss.cpp:79)
    return s


class Timer:
    """pluss_timer_start/stop/print (pluss.cpp:86-124).

    The cache flush runs BEFORE the timed region and its cost is
    recorded separately (`flush_s`, reset at every start): on hosts
    where the 2.5 MB walk is slow it must never pollute the measured
    per-rep seconds, and recording it makes the overhead auditable
    (`timed` returns the per-rep flush costs alongside the rep times).
    """

    def __init__(self, cycle_accurate: bool = False, flush: bool = True,
                 flush_kb: int = _CACHE_SIZE_KB) -> None:
        self.cycle_accurate = cycle_accurate
        self.flush = flush
        self.flush_kb = flush_kb
        self.elapsed = 0.0
        self.cycles = 0
        self.flush_s = 0.0
        self._t0 = 0.0
        self._c0 = 0

    def start(self) -> None:
        if self.flush:
            t0 = time.perf_counter()
            flush_cache(self.flush_kb)
            self.flush_s = time.perf_counter() - t0
        else:
            self.flush_s = 0.0
        if self.cycle_accurate:
            self._c0 = time.perf_counter_ns()
        self._t0 = time.perf_counter()

    def stop(self) -> float:
        self.elapsed = time.perf_counter() - self._t0
        if self.cycle_accurate:
            self.cycles = time.perf_counter_ns() - self._c0
        return self.elapsed

    def print(self) -> None:
        # pluss_timer_print emits the bare seconds value (pluss.cpp:120-124)
        if self.cycle_accurate:
            print(f"{self.elapsed:.6f} ({self.cycles} ns)")
        else:
            print(f"{self.elapsed:.6f}")


def timed(fn, reps: int = 1, cycle_accurate: bool = False,
          flush: bool = True, flush_kb: int = _CACHE_SIZE_KB):
    """Run fn() `reps` times; returns (per-rep seconds, last result,
    per-rep cache-flush seconds). The flush cost is measured outside
    the timed region — per-rep seconds contain only fn() — and
    returned so callers can audit the flush overhead instead of it
    silently disappearing (or, worse, leaking into the reps on hosts
    where the flush walk is slow)."""
    t = Timer(cycle_accurate=cycle_accurate, flush=flush,
              flush_kb=flush_kb)
    times = []
    flushes = []
    result = None
    for _ in range(reps):
        t.start()
        result = fn()
        times.append(t.stop())
        flushes.append(t.flush_s)
    return times, result, flushes
