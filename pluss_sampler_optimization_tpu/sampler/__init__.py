from .dense import run_dense, dense_nest_outputs

__all__ = ["run_dense", "dense_nest_outputs"]
