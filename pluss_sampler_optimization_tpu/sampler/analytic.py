"""Analytic exact engine: closed-form next-use, aggregated per period.

The periodic engine (sampler/periodic.py) rejects two program classes
the round-4 verdict called out — triangular nests (per-period trip
counts) and arrays mixing parallel-loop coefficients (syrk's A[i][k]
vs A[j][k]) — and the dense/stream fallbacks lose ~12-20x to the
native serial walk on a CPU host. This engine gives those classes an
exact path that beats the serial walk on CPU and is a vectorized
array program on TPU.

Two facts make it work:

1. **The closed-form next-use solver is exact per access.** For any
   supported nest (affine refs, unit-step triangular bounds), every
   access's reuse interval is solved in O(1) by the same machinery the
   sampled engine uses (sampler/nextuse.py) — over the thread's whole
   remaining trace, so NO skip-free-reuse precondition is needed. The
   exact histogram of one period (all inner iterations of one parallel
   iteration v0) is one vectorized classify over the period's box —
   reusing the sampled engine's compiled kernels verbatim.

2. **Per-period histograms are piecewise affine in v0.** Within a
   class of structurally equivalent periods — same chunk position
   (hence the same thread-local successor-period pattern), same
   line-granule phase (v0's affine image mod CLS/DS), away from the
   thread's trailing chunks (no truncation effects) — the histogram's
   slot values and slot counts are affine functions of v0: each extra
   parallel value translates the touched-line pattern and (for
   triangular nests) appends a fixed marginal row pattern. The engine
   VERIFIES this at >= _MIN_PROBES probe periods per class (ends,
   middle, and seeded random interiors, all exact evaluations); an
   exact affine fit through all probes is then summed over the class
   in closed form. Any class that fails the fit — or is too small to
   probe — is evaluated period-by-period (exact, just slower), so a
   structural surprise degrades speed, never correctness.

Verification ledger (what makes the result exact, and the one residual
assumption): probe and direct evaluations are exact by fact 1; fitted
classes additionally satisfy (a) an exact integer affine fit at every
probe including randomized ones, and (b) the per-period total-count
identity sum(slot counts) + cold == box size, checked across each
class via exact affine algebra (an identity miss bisects to the sound
path, it never aborts and never emits the suspect model). The residual
assumption is the piecewise-affine STRUCTURE itself: deviation
locations must be either enumerated (schedule-derived coincidence
rows/margins) or caught by a probe. An isolated interior deviation
that evades every enumerated set and every randomized probe would pass
undetected — the identity check is blind to pure value shifts. The
defenses are layered for exactly that case: randomized probes per
segment, coincidence sets derived from the schedule (not tuned
constants — the reach covers the source thread's own and entire next
chunk), and exhaustive per-period sweeps against brute-force
evaluation in the tests for every rejected model family at multiple N
(tests/test_analytic.py). Programs outside the tested families get the
same defenses but inherit the assumption; bit-exactness there is
backed by the probes, not proven — `tools/verify_analytic.py` removes
it for a concrete (program, machine) by brute-force classifying every
period (auditing the row-level fits) AND comparing run_analytic's
final state against the all-periods-direct fold (auditing the
v0-level class fits).

Execution strategy (round 6): every evaluation route is sized to its
work. Nests at or below _HOST_FOLD_MAX_ACCESSES fold through the host
lexsort (oracle/numpy_ref.py — the oracle's own code, so exact by
construction), because per-ref KERNEL costs (a ~2 s XLA compile or a
~0.3 s eager graph walk per distinct ref structure) dwarf any possible
device win there; adi N=20 went from 52.9 s to 0.04 s on this route.
Above the cutoff, direct (non-fitted) periods and probe sets evaluate
as ref-major blocked mega-dispatches (_period_blocks +
_eval_periods_block) instead of one dispatch per (ref, period), and
tiny dispatches on still-uncompiled kernels run op-by-op under
jax.disable_jit (_take_eager_path) — same ops, no compile. A 1-D mesh
shards every classify dispatch's key axis (run_analytic(mesh=...) /
parallel/sharded.py::run_analytic_sharded), bit-identical to
single-device because each key's solve is independent.

The reference has no analog of this decomposition: its exact samplers
walk the full trace access-by-access with hash-map LATs
(c_lib/test/sampler/gemm-t4-pluss-pro-model-ri-omp-seq.cpp:37-301);
the r10 sampler amortizes the walk but stays approximate. Here the
walk is gone entirely: ~(probes + boundary) period-box classifies,
each a batched device dispatch.
"""

from __future__ import annotations

import functools
import re

import numpy as np

from ..config import MachineConfig
from ..core.trace import NestTrace
from ..ir import Program
from ..oracle.serial import OracleResult
from ..runtime import telemetry
from ..runtime.hist import PRIState
from .periodic import _phase_count
from .sampled import (
    _NOSHARE_SLOT,
    _RATIO_SLOTS,
    _kernels_for,
    _pad_highs,
    _program_kernels,
    default_batch,
)

_MIN_PROBES = 6  # exact evaluations per fitted class (incl. random)
_COLD_KEY = "cold"

# Model families whose analytic-route exactness is PROVEN — pinned
# bit-equal vs the oracle across sizes/geometries by
# tests/test_analytic.py and/or covered by recorded
# tools/verify_analytic.py audits. `run_exact`'s analytic route warns
# (stderr + telemetry event) for any family outside this set: those
# inherit the probe-backed verification ledger (module docstring), not
# a proof. Names record the *provenance* of the audits (the
# Program.name prefix before the size suffix); the membership test
# itself is signature-derived — see `audited_family`.
AUDITED_FAMILIES = frozenset({
    "gemm", "syrk", "syrk-tri", "trmm", "trisolv", "covariance",
    "adi", "fdtd2d",
})


@functools.lru_cache(maxsize=None)
def _registry_family_builders() -> dict:
    """family name (Program.name prefix) -> (builder, takes_tsteps)
    for every registry model, so a bare name can be re-anchored to the
    IR its family's builder produces."""
    import inspect

    from ..models import REGISTRY

    out: dict = {}
    for fn in REGISTRY.values():
        has_t = "tsteps" in inspect.signature(fn).parameters
        prog = fn(8, tsteps=1) if has_t else fn(8)
        out[re.split(r"-\d", prog.name)[0]] = (fn, has_t)
    return out


@functools.lru_cache(maxsize=None)
def _audited_signatures(families: frozenset) -> frozenset:
    """Structural signatures of the audited families' IR.

    The audits in tests/test_analytic.py pin exactness of the analytic
    route against the oracle for specific loop-nest STRUCTURES, and
    the structure — not the name — is what the route dispatches on.
    Deriving the membership set from `structural_signature` over the
    registry builders (token size n=8; signatures are size-invariant,
    verified by tests/test_analysis.py) means a renamed or aliased
    registry entry with an audited structure stays audited, and a
    same-named model whose builder diverges from the audited IR stops
    silently inheriting the proof. Time-axis models are seeded at
    tsteps in {1, 2, 3}: fdtd2d's first time step lacks the previous
    iteration's state, so ts=1/ts=2/ts>=3 are three distinct (all
    audited) signature variants."""
    from ..analysis.validate import structural_signature

    sigs = set()
    for fam, (fn, has_t) in _registry_family_builders().items():
        if fam not in families:
            continue
        for ts in (1, 2, 3) if has_t else (1,):
            prog = fn(8, tsteps=ts) if has_t else fn(8)
            sigs.add(structural_signature(prog))
    return frozenset(sigs)


def audited_family(name_or_program) -> bool:
    """True when a Program (or a Program.name, e.g. 'syrk-tri-24x24')
    has the structural signature of an audited family.

    A Program is matched by its own signature. A bare name is mapped
    family -> registry builder -> signature (rebuilt at a token size;
    for time-axis names the '-t<k>' suffix picks the signature
    variant); names from families the registry does not know fall back
    to plain `AUDITED_FAMILIES` membership."""
    families = AUDITED_FAMILIES  # module attr: tests monkeypatch it
    sigs = _audited_signatures(families)
    if isinstance(name_or_program, Program):
        from ..analysis.validate import structural_signature

        return structural_signature(name_or_program) in sigs
    name = name_or_program
    fam = re.split(r"-\d", name)[0]
    builders = _registry_family_builders()
    if fam not in builders:
        return fam in families
    fn, has_t = builders[fam]
    if not has_t:
        return structural_signature_of(fn(8)) in sigs
    m = re.search(r"-t(\d+)$", name)
    ts = min(int(m.group(1)), 3) if m else 1
    return structural_signature_of(fn(8, tsteps=max(ts, 1))) in sigs


def structural_signature_of(program: Program):
    """Thin call-time import shim (keeps module import free of the
    analysis package)."""
    from ..analysis.validate import structural_signature

    return structural_signature(program)


def warn_if_unaudited(program: Program) -> None:
    """Exact-router guard (ADVICE round 5, medium): emit a telemetry
    event + one-line stderr warning (once per family per process) when
    the analytic route serves a model whose structure is outside the
    audited set, instead of silently claiming bit-exactness."""
    if audited_family(program):
        return
    family = re.split(r"-\d", program.name)[0]
    telemetry.warn_once(
        ("analytic_unaudited", family),
        f"exact router: model {program.name!r} is outside the audited "
        "analytic-engine allowlist (tests/test_analytic.py); exactness "
        "is probe-backed, not proven — run tools/verify_analytic.py "
        "once for this (program, machine) to remove the assumption",
        kind="analytic_unaudited", model=program.name,
    )


def _analytic_default_batch() -> int:
    """Per-dispatch classify size, resolved at call time per backend.

    Smaller than the sampled engine's CPU default (sampled.py::
    default_batch): the analytic engine classifies mega-batches
    back-to-back, and a 2^15 working set stays in a host core's cache
    (batch sweep 2^15..2^18 at syrk-tri N=768: 26.2/27.9/35.8/37.9 s).
    Accelerators keep the dispatch-amortizing sampled default."""
    import jax

    return 1 << 15 if jax.default_backend() == "cpu" else default_batch()


def _box_geometry(nt: NestTrace, ref_idx: int, n0: int):
    """(t1, t2, box, highs) of one ref's inner box at period n0.

    `highs` is the CANONICAL radix — nest-wide maximum trips, not this
    period's box — so every period of a (possibly triangular) nest
    shares one decode radix and a whole block of periods classifies in
    one dispatch group (_eval_periods_block); only keys inside the
    period's real box are ever generated."""
    lv = int(nt.tables.ref_levels[ref_idx])
    v0 = nt.schedule.value(n0)
    t1 = int(nt.trip_at(1, v0)) if lv >= 1 else 1
    t2 = int(nt.trip_at(2, v0)) if lv >= 2 else 1
    highs = [
        nt.nest.loops[0].trip,
        max(nt.max_trips[1], 1) if lv >= 1 else 1,
        max(nt.max_trips[2], 1) if lv >= 2 else 1,
    ]
    return t1, t2, t1 * t2, highs


def _probe_positions(n: int, rng) -> set[int]:
    """Indices of one segment's probe members: both ends, the middle,
    and random draws until _MIN_PROBES distinct positions (the dedup
    loop keeps the documented probe count even when a draw collides
    with a fixed position). Single source for every fit level."""
    pos = {0, 1, n // 2, n - 2, n - 1}
    while len(pos) < min(_MIN_PROBES, n):
        pos.add(int(rng.integers(0, n)))
    return pos


_ROW_FIT_MIN = 96  # rows below this: classify the whole box directly
_ROW_MARGIN = 4  # leading/trailing rows always evaluated directly
# (margins and special-row neighborhoods are deliberately tight: a row
# outside them that deviates just fails its segment's fit and bisects —
# slower, never wrong — so these control speed, not soundness)


def _bucket_len(n: int, batch: int) -> int:
    """Chunk shape for n keys: pow2, capped at batch, floor 4096 — a
    bounded set of compiled shapes across all row/box sizes."""
    b = 4096
    while b < n and b < batch:
        b *= 2
    return min(b, batch)


_EAGER_MAX_KEYS = 1 << 13  # per-call ceiling for the compile-free path
_EAGER_MAX_CALLS = 4  # per-kernel eager calls before compiling anyway
_eager_spent: dict[int, int] = {}


def _take_eager_path(kernel, n: int, sharding) -> bool:
    """True when a classify call should run op-by-op (jit disabled)
    instead of compiling its kernel: a tiny key set on a kernel with
    no executable yet. A multi-nest stencil at small N (adi: 4 nests,
    18 distinct ref-kernel structures per time step) classifies a few
    hundred keys per kernel; compiling each costs ~2 s on the CPU
    backend (measured — 31 s of the 44 s adi N=20 wall) while eager
    execution of the same integer op sequence costs ~0.3 s of graph
    walking regardless of key count — and is bit-identical, being the
    same ops run one at a time. Because that cost is per CALL, the
    budget counts calls: a kernel that keeps receiving small
    dispatches (probe/bisection sequences at large triangular N) flips
    to the compiled path after _EAGER_MAX_CALLS, bounding the eager
    detour at ~1 s per kernel either way; sharded dispatches always
    compile (GSPMD partitioning is the point there)."""
    if sharding is not None or n > _EAGER_MAX_KEYS:
        return False
    try:
        if kernel._cache_size() > 0:
            return False
    except Exception:
        return False  # no cache introspection: always compile
    spent = _eager_spent.get(id(kernel), 0)
    if spent >= _EAGER_MAX_CALLS:
        return False
    _eager_spent[id(kernel)] = spent + 1
    return True


def _classify_keys(nt, kernel, ref_idx, keys, highs, batch, sharding=None):
    """(packed, found) for an arbitrary key vector, chunked+padded to
    bucketed shapes.

    `sharding` (a NamedSharding over a 1-D mesh) lays each chunk's key
    axis over the device mesh: every key's classification is an
    independent closed-form solve, so GSPMD partitions the dispatch
    with no cross-device traffic and the positionally reassembled
    outputs are bit-identical to the single-device call."""
    import jax

    ph = _pad_highs(highs)
    rxv = np.int64(ref_idx)
    outs_p, outs_f = [], []
    n = len(keys)
    n_dev = 1 if sharding is None else sharding.mesh.devices.size
    with telemetry.span("classify", keys=n):
        for s0 in range(0, n, batch):
            n_valid = min(batch, n - s0)
            telemetry.count("dispatches")
            if _take_eager_path(kernel, n_valid, sharding):
                # no padding either: shapes are free without a compile
                telemetry.count("eager_dispatches")
                with jax.disable_jit():
                    p, f = kernel(
                        keys[s0 : s0 + n_valid], ph, nt.vals, rxv
                    )
                outs_p.append(np.asarray(p))
                outs_f.append(np.asarray(f))
                continue
            blen = _bucket_len(n_valid, batch)
            if blen % n_dev:  # each device must own an equal key slice
                blen += n_dev - blen % n_dev
            chunk = np.full(blen, keys[0], dtype=np.int64)
            chunk[:n_valid] = keys[s0 : s0 + n_valid]
            if sharding is not None:
                with telemetry.span("shard_put", keys=blen):
                    chunk = jax.device_put(chunk, sharding)
            p, f = kernel(chunk, ph, nt.vals, rxv)
            with telemetry.span("fetch"):
                p = np.asarray(p)[:n_valid]
                f = np.asarray(f)[:n_valid]
                telemetry.count(
                    "bytes_fetched_to_host", p.nbytes + f.nbytes
                )
                telemetry.count("fetches")
            outs_p.append(p)
            outs_f.append(f)
    return np.concatenate(outs_p), np.concatenate(outs_f)


def _slots_of(packed, found):
    slots: dict[int, int] = {}
    u, c = np.unique(packed[found], return_counts=True)
    for kk, cc in zip(u.tolist(), c.tolist()):
        slots[int(kk)] = int(cc)
    return slots, int((~found).sum())


def _plan_period_ref(nt, ref_idx: int, n0: int):
    """Host-only row plan for one (ref, period): which rows are
    evaluated directly (margins, enumerated special rows), the
    per-phase row classes with their first-round probe rows, and the
    initial `want` set — everything a batched prefetch needs before
    any classify runs. Returns None for an empty box; kind "full" for
    shallow/small boxes that classify every point."""
    from .sampled import _sink_groups

    t1, t2, box, highs = _box_geometry(nt, ref_idx, n0)
    if box == 0:
        return None
    base = n0 * highs[1] * highs[2]
    lv = int(nt.tables.ref_levels[ref_idx])
    if lv < 2 or t1 < _ROW_FIT_MIN:
        return {"kind": "full", "box": box, "base": base, "highs": highs,
                "t1": t1, "t2": t2}

    W = nt.machine.lines_per_element_block
    t = nt.tables
    sched = nt.schedule
    v0 = int(sched.value(n0))
    # rows whose inner value coincides with a parallel value the
    # source thread is about to execute (mixed-coefficient special
    # rows): this period's own v0 (syrk's j == i) AND the thread's
    # next few period values — an inter-chunk source's translating
    # reuse lands in the next chunk, so rows aligned with THAT
    # period's parallel value deviate too (found by the exhaustive
    # per-period sweep; tests/test_analytic.py pins it). Each center
    # gets a +-2 neighborhood evaluated directly.
    spec: set[int] = set()
    lp1 = nt.nest.loops[1]
    s1 = int(nt.start_at(1, v0))
    tid0 = int(sched.owner_tid(n0))
    m0 = int(sched.local_index(n0))
    lc0 = sched.local_count(tid0)
    # reach: the source thread's own remaining chunk plus the WHOLE
    # next chunk (2K periods) — a translating reuse lands at most one
    # owned chunk ahead for every registered model, and a model whose
    # reuse lands beyond the enumerated centers degrades to bisection
    # via the probe verification, not to a wrong result when a probe
    # catches it (see the soundness note in the module docstring)
    centers = [v0] + [
        int(sched.local_to_value(tid0, m0 + q))
        for q in range(1, 2 * sched.chunk + 1)
        if m0 + q < lc0
    ]
    for vc in centers:
        for dd in range(-2, 3):
            num = vc + dd - s1
            if num % lp1.step == 0:
                n1c = num // lp1.step
                if 0 <= n1c < t1:
                    spec.update(
                        x for x in range(n1c - 2, n1c + 3)
                        if 0 <= x < t1
                    )
    direct_rows = (
        set(range(min(_ROW_MARGIN, t1)))
        | set(range(max(t1 - _ROW_MARGIN, 0), t1))
        | spec
    )
    # line-granule phase along n1: rows repeat mod W unless every
    # relevant level-1 coefficient is granule-aligned
    sinks_all = {ref_idx}
    for grp in _sink_groups(nt, ref_idx):
        sinks_all.update(grp)
    phase = (
        W if any(int(t.ref_coeffs[j][1]) % W for j in sinks_all) else 1
    )
    rng = np.random.default_rng((n0, ref_idx))
    interior = [r for r in range(t1) if r not in direct_rows]
    classes = []
    want: set[int] = set(direct_rows)
    for p in range(phase):
        members = [r for r in interior if r % phase == p]
        if not members:
            continue
        if len(members) <= _MIN_PROBES + 4:
            want.update(members)
            classes.append((members, None))
            continue
        probe_rows = sorted(
            members[i] for i in _probe_positions(len(members), rng)
        )
        want.update(probe_rows)
        classes.append((members, probe_rows))
    return {
        "kind": "rows", "t1": t1, "t2": t2, "base": base,
        "highs": highs, "direct": sorted(direct_rows),
        "classes": classes, "want": sorted(want), "rng": rng,
    }


def _finish_period_ref(nt, kernel, ref_idx, n0, plan, row_memo, batch,
                       sharding=None):
    """Fit + aggregate one (ref, period) from a prefilled row memo.

    Large 3-deep boxes apply the engine's affine-fit machinery ONE
    LEVEL DOWN, along the n1 (row) axis inside the period: per-row
    histograms are piecewise affine in n1 by the same translation
    argument as the v0 level (each row shifts the touched-line pattern
    by a fixed amount), with the same defenses — exact row probes
    incl. randomized ones, exact integer fits, bisection on structural
    breaks (e.g. the coincidence row v1 == v0 of a mixed-coefficient
    array), margins and enumerated special rows evaluated directly,
    and the per-row count identity sum(slots)+cold == t2 enforced
    across each fitted segment. This is what makes a period cost ~40
    classified rows instead of t1: the classify itself is the engine's
    dominant cost (measured ~5.6M points/s single-core). Bisection
    rows missing from the memo are classified on demand.
    """
    t2 = plan["t2"]
    base = plan["base"]
    highs = plan["highs"]
    rng = plan["rng"]

    stride = plan["highs"][2]  # canonical radix row stride (>= t2)

    def eval_rows(rows: list) -> None:
        rows = [r for r in rows if r not in row_memo]
        if not rows:
            return
        keys = np.concatenate([
            base + r * stride + np.arange(t2, dtype=np.int64)
            for r in rows
        ])
        packed, found = _classify_keys(
            nt, kernel, ref_idx, keys, highs, batch, sharding
        )
        for i, r in enumerate(rows):
            row_memo[r] = _slots_of(
                packed[i * t2 : (i + 1) * t2],
                found[i * t2 : (i + 1) * t2],
            )

    def row_dict(r: int) -> dict:
        slots, cold = row_memo[r]
        d = {(0, kk): cc for kk, cc in slots.items()}
        if cold:
            d[(0, _COLD_KEY)] = cold
        return d

    out: dict[int, int] = {}
    cold_total = 0

    def add_direct(r: int) -> None:
        slots, cold = row_memo[r]
        nonlocal cold_total
        cold_total += cold
        for kk, cc in slots.items():
            out[kk] = out.get(kk, 0) + cc

    def fit_rows(members: list, probe_rows=None) -> None:
        nonlocal cold_total
        if len(members) <= _MIN_PROBES + 4:
            eval_rows(members)
            for r in members:
                add_direct(r)
            return
        if probe_rows is None:
            probe_rows = sorted(
                members[p] for p in _probe_positions(len(members), rng)
            )
        with telemetry.span("probe_verify", level="row",
                            probes=len(probe_rows)):
            eval_rows(probe_rows)
            model = _fit_affine(
                probe_rows, [row_dict(r) for r in probe_rows]
            )
        if model is None:
            mid = len(members) // 2
            fit_rows(members[:mid])
            fit_rows(members[mid:])
            return
        # per-row count identity across the whole segment: the model
        # total is affine in n1 and must equal the constant t2
        for r_chk in (members[0], members[len(members) // 2],
                      members[-1]):
            total = sum(c + d * r_chk for (a, b, c, d) in model.values())
            if total != t2:
                # identity miss = structural surprise: take the sound
                # path (bisect toward direct evaluation), never abort
                # and never emit the suspect model
                mid = len(members) // 2
                fit_rows(members[:mid])
                fit_rows(members[mid:])
                return
        ms = np.asarray(members, dtype=np.int64)
        for (_ri, _si, is_cold), (a, b, c, d) in model.items():
            cnts = c + d * ms
            if is_cold:
                cold_total += int(cnts.sum())
            elif b == 0:
                out[a] = out.get(a, 0) + int(cnts.sum())
            else:
                for vv, cc in zip((a + b * ms).tolist(), cnts.tolist()):
                    if cc:
                        out[vv] = out.get(vv, 0) + cc

    for r in plan["direct"]:
        add_direct(r)
    for members, probe_rows in plan["classes"]:
        fit_rows(members, probe_rows)
    return out, cold_total


def _first_round_keys_estimate(nt, ref_idx: int, n0) -> int:
    """Host-side estimate of one (ref, period)'s first-dispatch key
    volume — the full box for shallow/small boxes, ~the probed/direct
    row set otherwise. Only block sizing depends on this (memory and
    dispatch granularity), never results."""
    t1, t2, box, _ = _box_geometry(nt, ref_idx, int(n0))
    lv = int(nt.tables.ref_levels[ref_idx])
    if lv < 2 or t1 < _ROW_FIT_MIN:
        return max(box, 1)
    return max(min(box, 64 * max(t2, 1)), 1)


def _period_blocks(nt, ref_idx: int, n0s, batch: int):
    """Split a period list into dispatch blocks whose estimated
    first-round key volume stays near a few batches, so an arbitrarily
    long period list (adi's all-direct head) becomes a handful of
    mega-dispatches instead of one dispatch per period, while a block
    of large boxes (syrk N>=1024 rows plans) never concatenates an
    unbounded host key buffer."""
    budget = max(4 * batch, 1 << 18)
    blocks: list[list[int]] = []
    cur: list[int] = []
    acc = 0
    for n0 in n0s:
        cur.append(int(n0))
        acc += _first_round_keys_estimate(nt, ref_idx, n0)
        if acc >= budget:
            blocks.append(cur)
            cur, acc = [], 0
    if cur:
        blocks.append(cur)
    return blocks


def _eval_periods_block(nt, kernel, ref_idx, n0s, batch, sharding=None):
    """{n0: (slots, cold)} for a BLOCK of periods of one ref: all the
    periods' first-round rows (and full small boxes) classify in one
    chunked mega-dispatch, killing the per-call overhead that
    dominated period-by-period evaluation (measured ~3 ms/dispatch
    against ~10k-point row sets at syrk-tri N=1536)."""
    with telemetry.span("period_block", ref=int(ref_idx),
                        periods=len(n0s)):
        return _eval_periods_block_inner(
            nt, kernel, ref_idx, n0s, batch, sharding
        )


def _eval_periods_block_inner(nt, kernel, ref_idx, n0s, batch,
                              sharding=None):
    plans = {}
    segs = []  # (n0, row | "full", start, length)
    parts = []
    off = 0
    for n0 in n0s:
        plan = _plan_period_ref(nt, ref_idx, n0)
        plans[n0] = plan
        if plan is None:
            continue
        stride = plan["highs"][2]
        if plan["kind"] == "full":
            grid = (
                plan["base"]
                + np.arange(plan["t1"], dtype=np.int64)[:, None] * stride
                + np.arange(plan["t2"], dtype=np.int64)[None, :]
            ).ravel()
            parts.append(grid)
            segs.append((n0, "full", off, plan["box"]))
            off += plan["box"]
        else:
            t2, base = plan["t2"], plan["base"]
            for r in plan["want"]:
                parts.append(
                    base + r * stride + np.arange(t2, dtype=np.int64)
                )
                segs.append((n0, r, off, t2))
                off += t2
    results: dict = {}
    if off:
        # the canonical radix (_box_geometry) is n0-invariant, so the
        # whole block classifies in one chunked call
        packed, found = _classify_keys(
            nt, kernel, ref_idx, np.concatenate(parts),
            plans[segs[0][0]]["highs"], batch, sharding,
        )
        memos: dict[int, dict] = {}
        for n0, r, s, ln in segs:
            pf = (packed[s : s + ln], found[s : s + ln])
            if r == "full":
                results[n0] = _slots_of(*pf)
            else:
                memos.setdefault(n0, {})[r] = _slots_of(*pf)
        for n0 in n0s:
            plan = plans[n0]
            if plan is None:
                results[n0] = ({}, 0)
            elif plan["kind"] == "rows":
                results[n0] = _finish_period_ref(
                    nt, kernel, ref_idx, n0, plan, memos.get(n0, {}),
                    batch, sharding,
                )
    else:
        for n0 in n0s:
            results[n0] = ({}, 0)
    return results


def _eval_period_ref(nt, kernel, ref_idx, n0, batch, sharding=None):
    """Exact histogram of ONE ref's accesses in ONE period, as
    {packed_key: count} plus the cold count (see _finish_period_ref
    for the row-fit machinery)."""
    return _eval_periods_block(
        nt, kernel, ref_idx, [n0], batch, sharding
    )[n0]


def _eval_period(nt, nest_kernels, n0, batch, sharding=None):
    """{(ref_idx, packed) | (ref_idx, "cold"): count} for one period."""
    out: dict = {}
    for ri, kernel in nest_kernels:
        slots, cold = _eval_period_ref(nt, kernel, ri, n0, batch, sharding)
        for kk, cc in slots.items():
            out[(ri, kk)] = cc
        if cold:
            out[(ri, _COLD_KEY)] = cold
    return out


def _fit_affine(ns: list, evals: list) -> dict | None:
    """Exact affine model {slot_id: (a, b, c, d)} with value = a + b*n,
    count = c + d*n, fitted through EVERY probe (integers, no
    residual), or None when the class is not affine.

    The model is derived from the two CLOSEST-spaced probes (matched
    by sorted value — slot value curves can cross over a class's full
    span, but between adjacent members a crossing would break the
    verification below and soundly reject the fit) and then verified
    against every other probe as a MULTISET: the predicted
    {(value(n), count(n))} must equal the evaluated set exactly,
    independent of order.
    """
    order = sorted(range(len(ns)), key=lambda i: ns[i])
    ns = [ns[i] for i in order]
    evals = [evals[i] for i in order]
    gaps = [ns[i + 1] - ns[i] for i in range(len(ns) - 1)]
    i0 = gaps.index(min(gaps))
    na, nb = ns[i0], ns[i0 + 1]

    def grouped(ev):
        per: dict = {}
        for (ri, kk), cc in ev.items():
            per.setdefault((ri, kk == _COLD_KEY), []).append((kk, cc))
        for items in per.values():
            items.sort(key=lambda t: (
                (t[0] if t[0] != _COLD_KEY else -2), t[1]
            ))
        return per

    ga, gb = grouped(evals[i0]), grouped(evals[i0 + 1])
    if set(ga) != set(gb):
        return None
    dn = nb - na
    model = {}
    for gk in ga:
        ia, ib = ga[gk], gb[gk]
        if len(ia) != len(ib):
            return None
        for si, ((ka, ca), (kb, cb)) in enumerate(zip(ia, ib)):
            if ka == _COLD_KEY:
                a, b = _COLD_KEY, 0
            else:
                if (kb - ka) % dn:
                    return None
                b = (kb - ka) // dn
                a = ka - b * na
            if (cb - ca) % dn:
                return None
            d = (cb - ca) // dn
            c = ca - d * na
            model[(gk[0], si, gk[1])] = (a, b, c, d)
    # multiset verification at every other probe
    for i, n in enumerate(ns):
        if i in (i0, i0 + 1):
            continue
        predicted: dict = {}
        for (ri, _si, is_cold), (a, b, c, d) in model.items():
            kk = _COLD_KEY if is_cold else a + b * n
            cnt = c + d * n
            if cnt < 0:
                return None
            if cnt:
                predicted[(ri, kk)] = predicted.get((ri, kk), 0) + cnt
        if predicted != evals[i]:
            return None
    return model


def _fold(state: PRIState, tid: int, packed, count: float) -> None:
    """One slot into the PRIState with runtime-v1 conventions (noshare
    pow2-binned on insertion, share raw, cold as the raw -1 key)."""
    if packed == _COLD_KEY:
        state.update_noshare(tid, -1, count)
        return
    value, slot = divmod(int(packed), _RATIO_SLOTS)
    if slot == _NOSHARE_SLOT:
        state.update_noshare(tid, value, count)
    else:
        state.update_share(tid, slot, value, count)


def validate_analytic(program: Program, machine: MachineConfig) -> None:
    """Raise NotImplementedError when a nest is outside the solver's
    closed-form family (the same gate as the sampled engine: affine
    refs with dominant positive strides, unit-step triangular bounds).
    """
    _program_kernels(program, machine)


# Nests at or below this many total accesses fold through the host
# lexsort (oracle/numpy_ref.py::fold_nest_numpy) instead of the device
# classify machinery: the whole per-thread sort is milliseconds there,
# while the kernel route pays per-ref-STRUCTURE costs first (adi has 18
# distinct ref-kernel structures per time step at ~2 s compile or
# ~0.3 s eager-graph walk each — measured round 6, the 52.9 s adi N=20
# crawl). Exactness is unchanged: the host fold is the numpy oracle's
# own code.
_HOST_FOLD_MAX_ACCESSES = 1 << 22


def run_analytic(
    program: Program,
    machine: MachineConfig,
    batch: int | None = None,
    seed: int = 0,
    mesh=None,
    host_cutoff: int | None = None,
) -> OracleResult:
    """Exact engine for any nest the closed-form solver covers;
    bit-identical to the serial oracle / dense / stream engines.

    `mesh` (a 1-D jax.sharding.Mesh) shards every classify dispatch's
    key axis over the devices (see _classify_keys) — same results,
    bit-identical, because each key's solve is independent and the
    outputs reassemble positionally (tests/test_parallel.py).

    `host_cutoff` (default _HOST_FOLD_MAX_ACCESSES) is the nest size at
    or below which the exact fold runs as one host lexsort per thread
    instead of period-level device dispatch — the fix for multi-nest
    stencils whose many tiny nests made per-ref kernel costs the whole
    wall time (adi N=20: 52.9 s -> well under a second). Pass 0 to
    force every nest through the period/fit machinery (the exhaustive
    engine-path tests do).

    The backend (and with it the default batch) is resolved only AFTER
    the _program_kernels gate: a routing/validation caller probing an
    out-of-family program gets its NotImplementedError without this
    function ever initializing an accelerator plugin — plugin init can
    hang in this environment and must stay inside bench's watchdog
    (ADVICE round 5, low #4).
    """
    trace, _ = _program_kernels(program, machine)  # gate + kernel cache
    if batch is None:
        batch = _analytic_default_batch()
    sharding = None
    if mesh is not None and mesh.devices.size > 1:
        from jax.sharding import NamedSharding, PartitionSpec

        sharding = NamedSharding(mesh, PartitionSpec(mesh.axis_names[0]))
    if host_cutoff is None:
        host_cutoff = _HOST_FOLD_MAX_ACCESSES
    P = machine.thread_num
    state = PRIState(P)
    rng = np.random.default_rng(seed)
    per_tid = [0] * P
    for tid in range(P):
        per_tid[tid] = sum(nt.tid_length(tid) for nt in trace.nests)
    engine_span = telemetry.span("engine", engine="analytic")
    engine_span.__enter__()
    for k, nt in enumerate(trace.nests):
        if sum(nt.tid_length(t) for t in range(P)) <= host_cutoff:
            from ..oracle.numpy_ref import fold_nest_numpy

            with telemetry.span("fold", nest=k, route="host_lexsort"):
                for tid in range(P):
                    fold_nest_numpy(nt, tid, state)
            continue
        nest_kernels = [
            (ri, _kernels_for(nt, ri)["raw"])
            for ri in range(nt.tables.n_refs)
        ]
        sched = nt.schedule
        trip0 = sched.trip
        K, T = sched.chunk, sched.threads
        if nt.tri:
            # v0-level fitting cannot engage on a triangular nest: the
            # per-period histogram's own slot count grows with the
            # period's row count, so no two periods share a slot
            # structure. Every period is evaluated exactly instead —
            # the per-period row fits already cut a period to ~40
            # classified rows, and ref-major BLOCKS amortize the
            # dispatch overhead that would otherwise dominate.
            tid_of_t = np.asarray(
                sched.owner_tid(np.arange(trip0, dtype=np.int64))
            )
            for ri, kern in nest_kernels:
                for blk in _period_blocks(nt, ri, range(trip0), batch):
                    res = _eval_periods_block(
                        nt, kern, ri, blk, batch, sharding
                    )
                    for n0, (slots, cold) in res.items():
                        tid = int(tid_of_t[n0])
                        for kk, cc in slots.items():
                            _fold(state, tid, kk, float(cc))
                        if cold:
                            _fold(state, tid, _COLD_KEY, float(cold))
            continue
        g = _phase_count(nt)
        n_all = np.arange(trip0, dtype=np.int64)
        tid_of = np.asarray(sched.owner_tid(n_all))
        m_of = np.asarray(sched.local_index(n_all))
        lc = np.array([sched.local_count(t) for t in range(T)])
        # Trailing-chunk periods see end-of-thread truncation (their
        # reuses may have no successor period); evaluate them directly.
        tail = m_of >= np.maximum(lc[tid_of] - 2 * K, 0)
        # Leading periods can deviate from the class's affine line at
        # v0-coincidence values (e.g. the special row j == v0 sitting
        # inside the first line block deviated at exactly v0 == W for
        # syrk): for the zero-const affine maps of this family, such
        # thresholds live within O(W) of the parallel range's edges,
        # so a 2W + chunk-round head margin is evaluated directly.
        # The trailing edge is inside the tail mask already.
        head = n_all < (
            2 * nt.machine.lines_per_element_block + K * T
        )
        v0_all = np.asarray(sched.value(n_all))
        phase = (v0_all % g) if g > 1 else np.zeros_like(n_all)
        cls_key = (n_all % K) * g + phase
        direct: list[int] = n_all[tail | (head & ~tail)].tolist()
        eval_memo: dict[int, dict] = {}

        def peval(n: int) -> dict:
            if n not in eval_memo:
                eval_memo[n] = _eval_period(
                    nt, nest_kernels, n, batch, sharding
                )
            return eval_memo[n]

        def peval_block(ns) -> None:
            """Prefetch many periods' exact evaluations into the memo
            as ref-major key-bounded mega-dispatches — the batching
            that turns a long all-direct period list (adi's multi-nest
            stencils reject every fit: head/tail margins cover the
            whole parallel range at small N, and interior classes stay
            under the probe minimum) from one dispatch per (ref,
            period) into a handful of dispatches per ref. Results are
            identical to per-period peval calls by construction: the
            memo entries are built from the same _eval_periods_block
            evaluations, only grouped."""
            missing = sorted(
                {int(n) for n in ns} - eval_memo.keys()
            )
            if not missing:
                return
            per_ref: dict[int, dict] = {}
            for ri, kern in nest_kernels:
                res: dict = {}
                for blk in _period_blocks(nt, ri, missing, batch):
                    res.update(_eval_periods_block(
                        nt, kern, ri, blk, batch, sharding
                    ))
                per_ref[ri] = res
            for n in missing:
                out: dict = {}
                for ri, _ in nest_kernels:
                    slots, cold = per_ref[ri][n]
                    for kk, cc in slots.items():
                        out[(ri, kk)] = cc
                    if cold:
                        out[(ri, _COLD_KEY)] = cold
                eval_memo[n] = out

        def fit_or_split(members: np.ndarray) -> None:
            """Fit one affine segment over `members`, bisecting on
            failure: mid-class structural breaks exist and are
            N-dependent (e.g. syrk's translating reuse value crosses
            the share threshold at some v0, flipping its packed slot),
            so the class is piecewise affine and recursive bisection
            finds the segments. Exhausted segments fall back to exact
            period-by-period evaluation — the fit never gates
            correctness, only speed."""
            if len(members) <= _MIN_PROBES + 4:
                direct.extend(members.tolist())
                return
            probe_ns = sorted(
                int(members[p])
                for p in _probe_positions(len(members), rng)
            )
            with telemetry.span("probe_verify", level="v0",
                                probes=len(probe_ns)):
                peval_block(probe_ns)
                model = _fit_affine(
                    probe_ns, [peval(n) for n in probe_ns]
                )
            if model is None:
                mid = len(members) // 2
                fit_or_split(members[:mid])
                fit_or_split(members[mid:])
                return
            # the per-period total-count identity must hold for EVERY
            # member: sum over slots of (c + d*n) + cold == box(n). The
            # model total is affine; box(n) is affine or (doubly
            # triangular) quadratic in n, so checking THREE points
            # separates them — an affine function agreeing with the
            # model at 3 points is the model.
            for n_chk in (
                int(members[0]),
                int(members[len(members) // 2]),
                int(members[-1]),
            ):
                total = sum(
                    c + d * n_chk for (a, b, c, d) in model.values()
                )
                box_chk = sum(
                    _box_geometry(nt, ri, n_chk)[2]
                    for ri, _ in nest_kernels
                )
                if total != box_chk:
                    # identity miss = structural surprise: take the
                    # sound path instead of emitting the suspect model
                    mid = len(members) // 2
                    fit_or_split(members[:mid])
                    fit_or_split(members[mid:])
                    return
            for (ri, si, is_cold), (a, b, c, d) in model.items():
                for n in members.tolist():
                    cnt = c + d * n
                    if cnt:
                        _fold(
                            state, int(tid_of[n]),
                            a if is_cold else a + b * n, float(cnt),
                        )

        for ck in np.unique(cls_key):
            members = n_all[(cls_key == ck) & ~tail & ~head]
            if len(members):
                fit_or_split(members)
        peval_block(direct)
        with telemetry.span("fold", nest=k, route="direct"):
            for n in direct:
                ev = peval(int(n))
                for (ri, kk), cc in ev.items():
                    _fold(state, int(tid_of[n]), kk, float(cc))
    engine_span.__exit__(None, None, None)
    return OracleResult(
        state=state,
        total_accesses=sum(per_tid),
        per_tid_accesses=per_tid,
    )
