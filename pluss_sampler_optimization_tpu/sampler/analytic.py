"""Analytic exact engine: closed-form next-use, aggregated per period.

The periodic engine (sampler/periodic.py) rejects two program classes
the round-4 verdict called out — triangular nests (per-period trip
counts) and arrays mixing parallel-loop coefficients (syrk's A[i][k]
vs A[j][k]) — and the dense/stream fallbacks lose ~12-20x to the
native serial walk on a CPU host. This engine gives those classes an
exact path that beats the serial walk on CPU and is a vectorized
array program on TPU.

Two facts make it work:

1. **The closed-form next-use solver is exact per access.** For any
   supported nest (affine refs, unit-step triangular bounds), every
   access's reuse interval is solved in O(1) by the same machinery the
   sampled engine uses (sampler/nextuse.py) — over the thread's whole
   remaining trace, so NO skip-free-reuse precondition is needed. The
   exact histogram of one period (all inner iterations of one parallel
   iteration v0) is one vectorized classify over the period's box —
   reusing the sampled engine's compiled kernels verbatim.

2. **Per-period histograms are piecewise affine in v0.** Within a
   class of structurally equivalent periods — same chunk position
   (hence the same thread-local successor-period pattern), same
   line-granule phase (v0's affine image mod CLS/DS), away from the
   thread's trailing chunks (no truncation effects) — the histogram's
   slot values and slot counts are affine functions of v0: each extra
   parallel value translates the touched-line pattern and (for
   triangular nests) appends a fixed marginal row pattern. The engine
   VERIFIES this at >= _MIN_PROBES probe periods per class (ends,
   middle, and seeded random interiors, all exact evaluations); an
   exact affine fit through all probes is then summed over the class
   in closed form. Any class that fails the fit — or is too small to
   probe — is evaluated period-by-period (exact, just slower), so a
   structural surprise degrades speed, never correctness.

Verification ledger (why the result is exact): probe and direct
evaluations are exact by fact 1; fitted classes additionally satisfy
(a) an exact integer affine fit at every probe including randomized
ones, and (b) the per-period total-count identity
sum(slot counts) + cold == box size, checked for EVERY period in the
class via exact affine algebra, not just the probes. Tests pin
bit-equality against the serial oracle for every rejected model family
at multiple N (tests/test_analytic.py).

The reference has no analog of this decomposition: its exact samplers
walk the full trace access-by-access with hash-map LATs
(c_lib/test/sampler/gemm-t4-pluss-pro-model-ri-omp-seq.cpp:37-301);
the r10 sampler amortizes the walk but stays approximate. Here the
walk is gone entirely: ~(probes + boundary) period-box classifies,
each a batched device dispatch.
"""

from __future__ import annotations

import numpy as np

from ..config import MachineConfig
from ..core.trace import NestTrace
from ..ir import Program
from ..oracle.serial import OracleResult
from ..runtime.hist import PRIState
from .periodic import _phase_count
from .sampled import (
    _NOSHARE_SLOT,
    _RATIO_SLOTS,
    _pad_highs,
    _program_kernels,
    default_batch,
)

_MIN_PROBES = 6  # exact evaluations per fitted class (incl. 2 random)
_COLD_KEY = "cold"


def _box_geometry(nt: NestTrace, ref_idx: int, n0: int):
    """(t1, t2, box, highs) of one ref's inner box at period n0."""
    lv = int(nt.tables.ref_levels[ref_idx])
    v0 = nt.schedule.value(n0)
    t1 = int(nt.trip_at(1, v0)) if lv >= 1 else 1
    t2 = int(nt.trip_at(2, v0)) if lv >= 2 else 1
    highs = [nt.nest.loops[0].trip, max(t1, 1), max(t2, 1)]
    return t1, t2, t1 * t2, highs


def _eval_period_ref(nt, kernel, ref_idx, n0, batch, cap_box):
    """Exact histogram of ONE ref's accesses in ONE period, as
    {packed_key: count} plus the cold count — a chunked run of the
    sampled engine's per-ref kernel over the period's full inner box
    (keys are a contiguous range in the period's own radix)."""
    t1, t2, box, highs = _box_geometry(nt, ref_idx, n0)
    if box == 0:
        return {}, 0
    base = n0 * highs[1] * highs[2]
    ph = _pad_highs(highs)
    rxv = np.int64(ref_idx)
    slots: dict[int, int] = {}
    cold = 0
    cap = cap_box[0]
    for s0 in range(0, box, batch):
        n_valid = min(batch, box - s0)
        # every chunk is exactly `batch` long (pad with the base key),
        # so one compiled shape serves every period of every nest —
        # triangular boxes vary per v0 and would otherwise compile per
        # size
        chunk = np.full(batch, base, dtype=np.int64)
        chunk[:n_valid] = base + np.arange(s0, s0 + n_valid, dtype=np.int64)
        while True:
            keys, counts, n_unique, c = (
                np.asarray(x) for x in kernel(
                    chunk, np.int64(n_valid), ph, nt.vals, rxv, cap
                )
            )
            if int(n_unique) <= cap:
                break
            cap = max(cap * 4, int(n_unique))
            cap_box[0] = cap
        cold += int(c)
        for kk, cc in zip(keys.tolist(), counts.tolist()):
            if cc > 0:
                slots[int(kk)] = slots.get(int(kk), 0) + int(cc)
    return slots, cold


def _eval_period(nt, nest_kernels, n0, batch, cap_box):
    """{(ref_idx, packed) | (ref_idx, "cold"): count} for one period."""
    out: dict = {}
    for ri, kernel in nest_kernels:
        slots, cold = _eval_period_ref(nt, kernel, ri, n0, batch, cap_box)
        for kk, cc in slots.items():
            out[(ri, kk)] = cc
        if cold:
            out[(ri, _COLD_KEY)] = cold
    return out


def _fit_affine(ns: list, evals: list) -> dict | None:
    """Exact affine model {slot_id: (a, b, c, d)} with value = a + b*n,
    count = c + d*n, fitted through EVERY probe (integers, no residual),
    or None when the class is not affine.

    Slots are matched across probes per (ref, kind) by sorted packed
    value — sound because an affine family's order can only change by
    crossing, which would break the exact fit at some probe and reject
    the class.
    """
    groups: dict = {}
    for n, ev in zip(ns, evals):
        per: dict = {}
        for (ri, kk), cc in ev.items():
            per.setdefault((ri, kk == _COLD_KEY), []).append((kk, cc))
        for gk, items in per.items():
            items.sort(key=lambda t: (t[0] if t[0] != _COLD_KEY else -2))
            groups.setdefault(gk, {})[n] = items
    model = {}
    for gk, by_n in groups.items():
        if len(by_n) != len(ns):
            return None  # a slot group absent at some probe
        lens = {len(v) for v in by_n.values()}
        if len(lens) != 1:
            return None
        for si in range(lens.pop()):
            pts = [(n, by_n[n][si]) for n in ns]
            (na, (ka, ca)), (nb, (kb, cb)) = pts[0], pts[-1]
            dn = nb - na
            if ka == _COLD_KEY:
                b = 0
                a = _COLD_KEY
            else:
                if (kb - ka) % dn:
                    return None
                b = (kb - ka) // dn
                a = ka - b * na
            if (cb - ca) % dn:
                return None
            d = (cb - ca) // dn
            c = ca - d * na
            for n, (kk, cc) in pts:
                want = a if a == _COLD_KEY else a + b * n
                if kk != want or cc != c + d * n:
                    return None
            model[(gk[0], si, gk[1])] = (a, b, c, d)
    return model


def _fold(state: PRIState, tid: int, packed, count: float) -> None:
    """One slot into the PRIState with runtime-v1 conventions (noshare
    pow2-binned on insertion, share raw, cold as the raw -1 key)."""
    if packed == _COLD_KEY:
        state.update_noshare(tid, -1, count)
        return
    value, slot = divmod(int(packed), _RATIO_SLOTS)
    if slot == _NOSHARE_SLOT:
        state.update_noshare(tid, value, count)
    else:
        state.update_share(tid, slot, value, count)


def validate_analytic(program: Program, machine: MachineConfig) -> None:
    """Raise NotImplementedError when a nest is outside the solver's
    closed-form family (the same gate as the sampled engine: affine
    refs with dominant positive strides, unit-step triangular bounds).
    """
    _program_kernels(program, machine)


def run_analytic(
    program: Program,
    machine: MachineConfig,
    batch: int | None = None,
    seed: int = 0,
) -> OracleResult:
    """Exact engine for any nest the closed-form solver covers;
    bit-identical to the serial oracle / dense / stream engines."""
    if batch is None:
        batch = default_batch()
    trace, kernels = _program_kernels(program, machine)
    P = machine.thread_num
    state = PRIState(P)
    rng = np.random.default_rng(seed)
    per_tid = [0] * P
    for tid in range(P):
        per_tid[tid] = sum(nt.tid_length(tid) for nt in trace.nests)
    for k, nt in enumerate(trace.nests):
        nest_kernels = [
            (ri, plain) for (kk, ri, plain, _scan) in kernels if kk == k
        ]
        sched = nt.schedule
        trip0 = sched.trip
        K, T = sched.chunk, sched.threads
        g = _phase_count(nt)
        n_all = np.arange(trip0, dtype=np.int64)
        tid_of = np.asarray(sched.owner_tid(n_all))
        m_of = np.asarray(sched.local_index(n_all))
        lc = np.array([sched.local_count(t) for t in range(T)])
        # Trailing-chunk periods see end-of-thread truncation (their
        # reuses may have no successor period); evaluate them directly.
        tail = m_of >= np.maximum(lc[tid_of] - 2 * K, 0)
        v0_all = np.asarray(sched.value(n_all))
        phase = (v0_all % g) if g > 1 else np.zeros_like(n_all)
        cls_key = (n_all % K) * g + phase
        cap_box = [64]
        direct: list[int] = n_all[tail].tolist()
        for ck in np.unique(cls_key):
            members = n_all[(cls_key == ck) & ~tail]
            if len(members) == 0:
                continue
            if len(members) <= _MIN_PROBES + 4:
                direct.extend(members.tolist())
                continue
            # leading periods can carry start-of-loop boundary effects;
            # evaluating them directly keeps one odd early period from
            # failing the fit and dragging the whole class onto the
            # slow path
            direct.extend(members[:2].tolist())
            members = members[2:]
            probe_pos = {0, 1, len(members) // 2,
                         len(members) - 2, len(members) - 1}
            while len(probe_pos) < min(_MIN_PROBES, len(members)):
                probe_pos.add(int(rng.integers(0, len(members))))
            probe_ns = sorted(int(members[p]) for p in probe_pos)
            evals = [
                _eval_period(nt, nest_kernels, n, batch, cap_box)
                for n in probe_ns
            ]
            model = _fit_affine(probe_ns, evals)
            if model is None:
                # not affine: exact period-by-period evaluation (the
                # sound slow path; correctness never depends on the fit)
                direct.extend(members.tolist())
                continue
            # the per-period total-count identity must hold for EVERY
            # member: sum over slots of (c + d*n) + cold == box(n). The
            # model total is affine; box(n) is affine or (doubly
            # triangular) quadratic in n, so checking THREE points
            # separates them — an affine function agreeing with the
            # model at 3 points is the model.
            for n_chk in (
                int(members[0]),
                int(members[len(members) // 2]),
                int(members[-1]),
            ):
                total = sum(
                    c + d * n_chk for (a, b, c, d) in model.values()
                )
                box_chk = sum(
                    _box_geometry(nt, ri, n_chk)[2]
                    for ri, _ in nest_kernels
                )
                if total != box_chk:
                    raise AssertionError(
                        f"{program.name} nest {k} class {ck}: fitted "
                        f"counts {total} != box {box_chk} at n={n_chk}"
                    )
            for (ri, si, is_cold), (a, b, c, d) in model.items():
                for n in members.tolist():
                    cnt = c + d * n
                    if cnt:
                        _fold(
                            state, int(tid_of[n]),
                            a if is_cold else a + b * n, float(cnt),
                        )
        for n in direct:
            ev = _eval_period(nt, nest_kernels, int(n), batch, cap_box)
            for (ri, kk), cc in ev.items():
                _fold(state, int(tid_of[n]), kk, float(cc))
    return OracleResult(
        state=state,
        total_accesses=sum(per_tid),
        per_tid_accesses=per_tid,
    )
