"""MRC confidence bands for the progressive sampled engine.

run_sampled_progressive (sampler/sampled.py) executes the sampled
engine in rounds of increasing sample-stream prefixes and, between
rounds, asks this module how uncertain the interim MRC still is. The
estimate is a seeded bootstrap over per-ref SUB-histograms: each round
splits every ref's newly-classified slice into SUB_BLOCKS_PER_ROUND
independent blocks, and a bootstrap replicate refolds each ref from a
with-replacement resample of its blocks. The band at a cache size is
the max-minus-min across replicate curves; the reported width is the
max over cache sizes — the classic percentile-bootstrap spread, coarse
but cheap (the blocks are already-decoded sparse histograms, so a
replicate costs one fold + distribute, never a re-classification).

Determinism contract (tools/lint_determinism.py lints this whole
file): resample indices come from runtime/faults.py::counter_u01 — a
keyed counter hash of (request seed, "mrc_bootstrap", round, ref,
replicate, draw) — never from `random`/np.random or any clock, so the
band sequence (and with it the round count a tolerance stops at, and
the partial_final a deadline produces) replays exactly from the
request (seed, knobs). All fold loops iterate in sorted-key order so
float accumulation is a pure function of histogram content, the same
canonicalization cri_distribute applies.
"""

from __future__ import annotations

import numpy as np

from ..runtime.aet import aet_mrc
from ..runtime.cri import cri_distribute
from ..runtime.faults import counter_u01
from ..runtime.hist import PRIState, hist_update

# Schedule length when neither round_schedule nor max_rounds is set:
# geometric doubling 1/8 -> 1/4 -> 1/2 -> 1 of the final sample count.
DEFAULT_MAX_ROUNDS = 4

# Bootstrap replicates per band estimate. 8 keeps the between-round
# cost at a handful of fold+distribute passes; the band only gates
# EARLY stopping (a full schedule is bit-identical to one-shot
# regardless), so a coarse spread estimate is the right trade.
DEFAULT_REPLICATES = 8

# Independent sub-histogram blocks each round contributes per ref —
# so even round 1 resamples over a non-degenerate block set (a
# one-block bootstrap has zero spread by construction).
SUB_BLOCKS_PER_ROUND = 4


def resolve_schedule(cfg) -> tuple:
    """The round schedule as an increasing tuple of fractions of the
    final per-ref sample count, always ending at 1.0.

    cfg.round_schedule wins verbatim (validated); otherwise geometric
    doubling over cfg.max_rounds (default DEFAULT_MAX_ROUNDS) rounds:
    (1/2^(R-1), ..., 1/4, 1/2, 1)."""
    sched = getattr(cfg, "round_schedule", None)
    if sched is not None:
        fracs = tuple(float(f) for f in sched)
        if not fracs:
            raise ValueError("round_schedule must be non-empty")
        for a, b in zip(fracs, fracs[1:]):
            if b <= a:
                raise ValueError(
                    f"round_schedule must be strictly increasing, "
                    f"got {fracs}"
                )
        if fracs[0] <= 0.0:
            raise ValueError("round_schedule fractions must be > 0")
        if fracs[-1] != 1.0:
            raise ValueError(
                f"round_schedule must end at 1.0, got {fracs[-1]}"
            )
        return fracs
    rounds = getattr(cfg, "max_rounds", None) or DEFAULT_MAX_ROUNDS
    rounds = max(1, int(rounds))
    return tuple(1.0 / (1 << (rounds - 1 - r)) for r in range(rounds))


def round_counts(total: int, schedule: tuple) -> list:
    """Cumulative per-round sample counts for one ref: ceil(frac *
    total) per schedule entry, final round pinned to exactly `total`
    (the full stream — the bit-identity invariant)."""
    counts = []
    for frac in schedule:
        counts.append(min(total, int(-(-total * frac // 1))))
    if counts:
        counts[-1] = total
    return counts


def block_bounds(lo: int, hi: int, blocks: int = SUB_BLOCKS_PER_ROUND):
    """Split the half-open sample range [lo, hi) into up to `blocks`
    contiguous non-empty sub-ranges (fewer when the range is small).
    Returned as a list of (start, end) pairs; empty when lo == hi."""
    n = hi - lo
    if n <= 0:
        return []
    k = min(blocks, n)
    out = []
    for i in range(k):
        a = lo + (n * i) // k
        b = lo + (n * (i + 1)) // k
        out.append((a, b))
    return out


def fold_blocks(ref_blocks, thread_num: int, v2: bool,
                weights=None) -> PRIState:
    """Fold per-ref block histograms into one PRIState, mirroring
    sampled.py::fold_results (all counts on simulated thread 0).

    `ref_blocks` is [per ref] -> [per block] -> (noshare dict, share
    dict, cold count); `weights` (same shape, integer multiplicities)
    is the bootstrap resample — None folds every block once, which
    reproduces the cumulative state exactly (integer-count float
    addition is exact, and sorted-key iteration canonicalizes the
    order)."""
    state = PRIState(thread_num, bin_noshare=not v2)
    for ref_idx, blocks in enumerate(ref_blocks):
        for blk_idx, (noshare, share, cold) in enumerate(blocks):
            w = 1 if weights is None else weights[ref_idx][blk_idx]
            if not w:
                continue
            for ri_val in sorted(noshare):
                state.update_noshare(0, ri_val, noshare[ri_val] * w)
            if cold:
                hist_update(state.noshare[0], -1, cold * w,
                            in_log_format=False)
            for ratio in sorted(share):
                h = share[ratio]
                for ri_val in sorted(h):
                    state.update_share(
                        0, int(ratio), ri_val, h[ri_val] * w
                    )
    return state


def _resample_weights(ref_blocks, seed: int, round_idx: int,
                      replicate: int) -> list:
    """Integer multiplicities of one with-replacement resample: per
    ref, R draws over its R blocks, indices from the counter-hash
    stream keyed (seed, "mrc_bootstrap", round, ref, replicate,
    draw)."""
    weights = []
    for ref_idx, blocks in enumerate(ref_blocks):
        n = len(blocks)
        m = [0] * n
        for k in range(n):
            u = counter_u01(
                seed, "mrc_bootstrap", round_idx, ref_idx,
                replicate, k,
            )
            m[min(n - 1, int(u * n))] += 1
        weights.append(m)
    return weights


def mrc_from_state(state, machine) -> np.ndarray:
    """state -> MRC, exactly the service record pipeline
    (executor.py::build_record): cri_distribute then aet_mrc."""
    rih = cri_distribute(state, machine.thread_num, machine.thread_num)
    return aet_mrc(rih, machine)


def bootstrap_band(ref_blocks, machine, *, seed: int, round_idx: int,
                   v2: bool = False,
                   replicates: int = DEFAULT_REPLICATES) -> float:
    """Max-over-cache-sizes width of the bootstrap MRC band after
    `round_idx` (0-based) rounds. Pure function of (blocks, machine,
    seed, round_idx, v2, replicates) — no entropy, no clock."""
    if not ref_blocks or all(not b for b in ref_blocks):
        return float("inf")
    curves = []
    for b in range(replicates):
        weights = _resample_weights(ref_blocks, seed, round_idx, b)
        state = fold_blocks(
            ref_blocks, machine.thread_num, v2, weights
        )
        curves.append(mrc_from_state(state, machine))
    length = max(len(c) for c in curves)
    mat = np.stack([
        np.concatenate([c, np.full(length - len(c), c[-1])])
        if len(c) < length else c
        for c in curves
    ])
    return float(np.max(mat.max(axis=0) - mat.min(axis=0)))
