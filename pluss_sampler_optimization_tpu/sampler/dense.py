"""Dense (full-traversal) TPU sampler.

The XLA twin of the reference's full-traversal samplers
(`ri`/`ri-omp`/`ri-omp-seq`/`ri-opt`, c_lib/test/sampler/): every access
of every simulated thread is enumerated and its reuse interval measured
exactly. The hash-map walk becomes one sort per (thread, nest):

  1. enumerate each reference's iteration grid -> (position, line) pairs
     (closed forms, core/trace.py);
  2. pack (group=(array,line), position, ref) into one int64 key; a
     single ascending sort then places consecutive accesses to the same
     line next to each other in trace order;
  3. reuse intervals are adjacent position differences within groups —
     exactly `count[tid] - LAT_X[tid][addr]` (...ri-omp-seq.cpp:110);
  4. scatter-add into dense pow2 histograms; share-classified intervals
     go through a fixed-capacity exact unique reduction; group starts
     (cold lines) count into the per-array -1 totals (:305-319).

Everything is jit-compiled; simulated threads are vmapped (each is an
independent sort, the property the `ri` variant's
`#pragma omp parallel for` over tids exploits, ...ri.cpp:67-68).
Thread ragged-ness (short/missing last chunks) is handled by masking
padded entries into a dedicated invalid group.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from ..config import MachineConfig
from ..core.trace import NestTrace, ProgramTrace
from ..ir import Program
from ..ops.histogram import N_EXP_BINS, exp_bin, sorted_k_unique
from ..oracle.serial import OracleResult
from ..runtime import telemetry
from ..runtime.hist import PRIState

_REF_BITS = 5  # up to 32 refs per nest


def _ceil_log2(x: int) -> int:
    return max(1, int(x - 1).bit_length())


def nest_geometry(nt: NestTrace):
    """(n_arrays, max_addr, n_groups) for the packed-key group space.

    Validates the packing preconditions: negative flats would corrupt
    the packed sort keys, and share ratios must fit the radix-8 share
    key. Shared by the one-shot (this module) and streaming
    (sampler/stream.py) dense engines.
    """
    t = nt.tables
    machine = nt.machine
    n_arrays = int(t.ref_arrays.max()) + 1 if t.n_refs else 1
    max_addr = 1
    for ri in range(t.n_refs):
        level = int(t.ref_levels[ri])
        hi = int(t.ref_consts[ri])
        lo = int(t.ref_consts[ri])
        for l in range(level + 1):
            c = int(t.ref_coeffs[ri][l])
            lo_v, hi_v = nt.level_value_range(l)
            hi += max(c * lo_v, c * hi_v)
            lo += min(c * lo_v, c * hi_v)
        if lo < 0:
            raise NotImplementedError(
                f"ref {t.ref_names[ri]}: affine map can reach negative "
                f"element index {lo}; negative addresses are unsupported"
            )
        if int(t.ref_share_ratios[ri]) >= 8:
            raise NotImplementedError(
                f"ref {t.ref_names[ri]}: share ratio "
                f"{int(t.ref_share_ratios[ri])} >= 8 does not fit the "
                "packed share key (radix 8)"
            )
        max_addr = max(max_addr, hi * machine.ds // machine.cls + 1)
    return n_arrays, max_addr, n_arrays * max_addr + 1  # +1 invalid group


def packed_ref_keys(
    nt: NestTrace, ri: int, v0, mrel, valid_m, pos_bits: int,
    max_addr: int, n_groups: int, base=None,
):
    """Packed (group, position, ref) sort keys of one ref's accesses
    over an m-grid.

    `v0` are the parallel-loop values, `mrel` the position-relative
    parallel indices (equal to the thread-local m for the one-shot
    engine, chunk-relative for the streaming engine), `valid_m` the
    raggedness mask. Invalid entries land in group n_groups-1.

    Triangular nests pass `base` — the position-relative access base of
    each m (a tri_base gather) replacing mrel * acc[0]; inner grids pad
    to the nest-wide max trip and mask the dead tail, and positions go
    through tri_position.
    """
    t = nt.tables
    machine = nt.machine
    level = int(t.ref_levels[ri])
    c = t.ref_coeffs[ri]
    if nt.tri:
        assert base is not None, "triangular packed keys need a base"
        if level == 0:
            pos = nt.tri_position(ri, v0, base)
            flat = v0 * int(c[0]) + int(t.ref_consts[ri])
            valid = valid_m
        else:
            lp1 = nt.nest.loops[1]
            t1v = nt.trip_at(1, v0)
            n1 = jnp.arange(nt.max_trips[1], dtype=jnp.int64)
            v1 = lp1.start_at(v0)[:, None] + n1[None, :] * lp1.step
            valid = valid_m[:, None] & (n1[None, :] < t1v[:, None])
            if level == 1:
                pos = nt.tri_position(ri, v0[:, None], base[:, None],
                                      n1[None, :])
                flat = (
                    v0[:, None] * int(c[0])
                    + v1 * int(c[1])
                    + int(t.ref_consts[ri])
                )
            else:
                lp2 = nt.nest.loops[2]
                t2v = nt.trip_at(2, v0)
                n2 = jnp.arange(nt.max_trips[2], dtype=jnp.int64)
                v2 = (lp2.start_at(v0)[:, None, None]
                      + n2[None, None, :] * lp2.step)
                valid = valid[:, :, None] & (
                    n2[None, None, :] < t2v[:, None, None]
                )
                pos = nt.tri_position(
                    ri, v0[:, None, None], base[:, None, None],
                    n1[None, :, None], n2[None, None, :],
                )
                flat = (
                    v0[:, None, None] * int(c[0])
                    + v1[:, :, None] * int(c[1])
                    + v2 * int(c[2])
                    + int(t.ref_consts[ri])
                )
        pos = jnp.broadcast_to(pos, valid.shape)
        flat = jnp.broadcast_to(flat, valid.shape)
        # masked entries carry pos 0 so the packed key stays in range
        pos = jnp.where(valid, pos, 0)
    elif level == 0:
        a0 = int(t.acc_per_level[0])
        off = int(t.ref_offsets[ri])
        pos = mrel * a0 + off
        flat = v0 * int(c[0]) + int(t.ref_consts[ri])
        valid = valid_m
    elif level == 1:
        a0 = int(t.acc_per_level[0])
        off = int(t.ref_offsets[ri])
        t1 = nt.nest.loops[1]
        n1 = jnp.arange(t1.trip, dtype=jnp.int64)
        v1 = t1.start + n1 * t1.step
        pos = (
            mrel[:, None] * a0
            + nt.npre[0]
            + n1[None, :] * int(t.acc_per_level[1])
            + off
        )
        flat = (
            v0[:, None] * int(c[0])
            + v1[None, :] * int(c[1])
            + int(t.ref_consts[ri])
        )
        valid = jnp.broadcast_to(valid_m[:, None], pos.shape)
    else:
        a0 = int(t.acc_per_level[0])
        off = int(t.ref_offsets[ri])
        t1, t2 = nt.nest.loops[1], nt.nest.loops[2]
        n1 = jnp.arange(t1.trip, dtype=jnp.int64)
        n2 = jnp.arange(t2.trip, dtype=jnp.int64)
        v1 = t1.start + n1 * t1.step
        v2 = t2.start + n2 * t2.step
        pos = (
            mrel[:, None, None] * a0
            + nt.npre[0]
            + n1[None, :, None] * int(t.acc_per_level[1])
            + nt.npre[1]
            + n2[None, None, :] * int(t.acc_per_level[2])
            + off
        )
        flat = (
            v0[:, None, None] * int(c[0])
            + v1[None, :, None] * int(c[1])
            + v2[None, None, :] * int(c[2])
            + int(t.ref_consts[ri])
        )
        valid = jnp.broadcast_to(valid_m[:, None, None], pos.shape)
    addr = flat * machine.ds // machine.cls
    grp = jnp.where(
        valid, int(t.ref_arrays[ri]) * max_addr + addr, n_groups - 1
    )
    key = (((grp << pos_bits) | pos.astype(jnp.int64)) << _REF_BITS) | ri
    return key.ravel()


def _nest_device_arrays(nt: NestTrace, max_share_values: int):
    """Build the jitted per-nest kernel: tid -> dense histogram outputs."""
    t = nt.tables
    sched = nt.schedule
    machine = nt.machine
    lmax = sched.max_local_count()
    # static per-tid local counts (device-selectable by tid)
    local_counts = jnp.array(
        [sched.local_count(tt) for tt in range(sched.threads)], dtype=jnp.int64
    )
    n_arrays, max_addr, n_groups = nest_geometry(nt)
    pos_bound = max(
        (nt.tid_length(tt) for tt in range(sched.threads)), default=1
    )
    pos_bits = _ceil_log2(pos_bound + 1)
    grp_bits = _ceil_log2(n_groups + 1)
    assert grp_bits + pos_bits + _REF_BITS <= 63, "key packing overflow"

    K = machine.chunk_size
    P = sched.threads
    step0, start0 = sched.step, sched.start
    base_tab = jnp.asarray(nt.tri_base) if nt.tri else None

    def per_tid(tid, zero):
        # `zero` is a traced 0: mixing it into the index grids keeps
        # them (and everything downstream) out of XLA's compile-time
        # constant folder — with no runtime inputs the whole sampler
        # would be folded into a literal at compile time.
        m = jnp.arange(lmax, dtype=jnp.int64) + zero
        valid_m = m < local_counts[tid]
        v0 = start0 + (((m // K) * P + tid) * K + (m % K)) * step0
        base = base_tab[tid, :lmax] if nt.tri else None
        keys = [
            packed_ref_keys(
                nt, ri, v0, m, valid_m, pos_bits, max_addr, n_groups,
                base=base,
            )
            for ri in range(t.n_refs)
        ]
        key = jnp.sort(jnp.concatenate(keys))
        ref_s = (key & ((1 << _REF_BITS) - 1)).astype(jnp.int32)
        pos_s = (key >> _REF_BITS) & ((1 << pos_bits) - 1)
        grp_s = key >> (_REF_BITS + pos_bits)
        is_valid = grp_s != (n_groups - 1)
        same = jnp.concatenate(
            [jnp.array([False]), (grp_s[1:] == grp_s[:-1]) & is_valid[1:]]
        )
        reuse = jnp.where(
            same, pos_s - jnp.concatenate([jnp.zeros(1, jnp.int64), pos_s[:-1]]), 0
        )
        thr = jnp.array(t.ref_share_thresholds, dtype=jnp.int64)[ref_s]
        is_share = same & (thr > 0) & (jnp.abs(reuse) > jnp.abs(reuse - thr))
        is_noshare = same & ~is_share

        e = exp_bin(jnp.maximum(reuse, 1))
        noshare_hist = jnp.zeros(N_EXP_BINS, dtype=jnp.int64).at[e].add(
            is_noshare.astype(jnp.int64)
        )
        # share: pack (reuse, ratio) so one unique pass keeps both
        ratio = jnp.array(t.ref_share_ratios, dtype=jnp.int64)[ref_s]
        share_key = reuse * 8 + ratio
        sk, sc, n_unique = sorted_k_unique(share_key, is_share, max_share_values)
        # cold lines: first element of each valid group, per array
        is_first = is_valid & ~same
        arr_of = jnp.where(is_valid, grp_s // max_addr, n_arrays)
        cold = jnp.zeros(n_arrays + 1, dtype=jnp.int64).at[
            jnp.where(is_first, arr_of, n_arrays)
        ].add(1)[:n_arrays]
        n_acc = jnp.sum(is_valid.astype(jnp.int64))
        return noshare_hist, sk, sc, n_unique, cold, n_acc

    return per_tid


@telemetry.counted_lru_cache(maxsize=32)
def _compiled_program(program: Program, machine: MachineConfig, max_share: int):
    trace = ProgramTrace(program, machine)
    fns = [
        _nest_device_arrays(nt, max_share) for nt in trace.nests
    ]

    @jax.jit
    def run(tids, zero):
        outs = []
        for fn in fns:
            outs.append(jax.vmap(fn, in_axes=(0, None))(tids, zero))
        return outs

    return trace, run


def _run_outputs(program: Program, machine: MachineConfig, max_share: int,
                 tid_sharding=None):
    """Execute the jitted program; optionally lay the vmapped simulated-
    thread batch axis out over a mesh (parallel/sharded.py)."""
    trace, run = _compiled_program(program, machine, max_share)
    tids = jnp.arange(machine.thread_num)
    if tid_sharding is not None:
        with telemetry.span("shard_put", engine="dense"):
            tids = jax.device_put(tids, tid_sharding)
    with telemetry.span("dispatch", engine="dense"):
        telemetry.count("dispatches")
        out = run(tids, jnp.int64(0))
    with telemetry.span("fetch", engine="dense"):
        out = telemetry.record_fetch(jax.device_get(out))
    return trace, out


def dense_nest_outputs(program: Program, machine: MachineConfig,
                       max_share: int = 64):
    """Run the jitted dense sampler; returns per-nest, per-tid outputs."""
    _, outs = _run_outputs(program, machine, max_share)
    return outs


def dense_bytes_estimate(program: Program, machine: MachineConfig) -> int:
    """Predicted peak bytes of the one-shot dense sort, from the trace
    geometry alone: per nest, the vmapped kernel materializes every
    tid's padded per-ref grids as int64 keys (lmax x inner sizes,
    packed_ref_keys), concatenates, and sorts — XLA holds roughly the
    keys plus the sorted copy plus the derived pos/grp/ref columns, so
    4x the key bytes is the working-set estimate the router uses."""
    trace = ProgramTrace(program, machine)
    total = 0
    for nt in trace.nests:
        sched = nt.schedule
        lmax = sched.max_local_count()
        per_m = 0
        for ri in range(nt.tables.n_refs):
            sz = 1
            for l in range(1, int(nt.tables.ref_levels[ri]) + 1):
                sz *= (nt.max_trips[l] if nt.tri
                       else nt.nest.loops[l].trip)
            per_m += sz
        total += machine.thread_num * lmax * per_m
    return total * 8 * 4


def _available_bytes() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 1 << 62  # unknown: never route


def run_dense(program: Program, machine: MachineConfig,
              max_share: int = 64, tid_sharding=None,
              auto_route: bool = True) -> OracleResult:
    """Dense TPU sampler -> host PRIState (same shape as the oracles).

    With `auto_route` (default), a run whose predicted sort working
    set exceeds available host memory is routed to an equivalent exact
    engine instead of letting XLA OOM (GEMM N=1024 requests ~279 GB on
    a 125 GB host): the periodic engine when its preconditions hold,
    else the streaming engine. Both produce bit-identical PRIStates.
    """
    if auto_route and tid_sharding is None:
        est = dense_bytes_estimate(program, machine)
        avail = _available_bytes()
        if est > 0.6 * avail:
            import sys as _sys

            from .periodic import run_periodic, validate_periodic

            try:
                validate_periodic(program, machine)
                routed = "periodic"
            except NotImplementedError:
                routed = "stream"
            print(
                f"dense: predicted sort working set "
                f"{est / 1e9:.0f} GB exceeds available "
                f"{avail / 1e9:.0f} GB; routing to the {routed} "
                "engine (bit-identical output)",
                file=_sys.stderr,
            )
            if routed == "periodic":
                return run_periodic(program, machine, max_share)
            from .stream import run_stream

            return run_stream(program, machine, max_share=max_share)
    with telemetry.span("engine", engine="dense"):
        trace, outs = _run_outputs(
            program, machine, max_share, tid_sharding
        )
        with telemetry.span("merge", engine="dense"):
            return _fold_dense_outputs(machine, outs)


def _fold_dense_outputs(machine: MachineConfig, outs) -> OracleResult:
    P = machine.thread_num
    state = PRIState(P)
    per_tid = [0] * P
    for (noshare, sk, sc, n_unique, cold, n_acc) in outs:
        if int(n_unique.max(initial=0)) > sk.shape[1]:
            raise RuntimeError(
                "share-value capacity exceeded; raise max_share "
                f"(needed {int(n_unique.max())}, have {sk.shape[1]})"
            )
        for tid in range(P):
            h = state.noshare[tid]
            for e_idx in np.nonzero(noshare[tid])[0]:
                key = 1 << int(e_idx)
                h[key] = h.get(key, 0.0) + float(noshare[tid][e_idx])
            c = int(cold[tid].sum())
            if c:
                h[-1] = h.get(-1, 0.0) + float(c)
            for key, cnt in zip(sk[tid], sc[tid]):
                if cnt > 0:
                    reuse, ratio = divmod(int(key), 8)
                    hs = state.share[tid].setdefault(ratio, {})
                    hs[reuse] = hs.get(reuse, 0.0) + float(cnt)
            per_tid[tid] += int(n_acc[tid])
    return OracleResult(
        state=state, total_accesses=sum(per_tid), per_tid_accesses=per_tid
    )
