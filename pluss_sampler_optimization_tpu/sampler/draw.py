"""Device-side sample drawing for the random-start sampled engine.

The reference's r10 sampler draws its random start points with rand()
on the host (c_lib/test/sampler/gemm-t4-pluss-pro-model-rs-ri-opt-r10.cpp:159-185,
draw-until-s-unique). Round 2's engine kept host drawing (numpy PCG)
and shipped one int64 key per sample to the device — the minimal wire
format, but still 8 bytes/sample across a link that, when the TPU sits
behind a network tunnel, moves ~70 MB/s with ~70 ms per round trip
(measured; the device-side compute for the same batch is ~0.1 ms).
At GEMM N=4096 the keys alone are 2.2 GB: the engine was >95%
host->device transfer.

This module moves the draw onto the device, so nothing crosses the
link but a per-ref RNG key and a handful of scalars:

- candidates are drawn with JAX's threefry counter PRNG — the bit
  stream is deterministic AND backend-invariant, so a seed produces
  the same sample set on CPU and TPU (numpy's host stream could never
  be replayed on-device);
- dedup is one global sort + neighbor-compare (the draw-until-unique
  loop's set semantics, vectorized);
- thinning to exactly s is select-by-random-priority: every candidate
  gets an independent uint64 priority, and the s smallest priorities
  among the unique representatives win — a uniform s-subset of the
  uniques, like the host path's rng.choice drop-set (priority ties at
  the threshold have probability ~2^-64 and are re-drawn);
- triangular nests draw from the bounding box and reject out-of-bounds
  points before dedup (same box-rejection scheme as the host path).

The one scalar that must come back is the unique count U (to certify
U >= s); the host retries with a fresh fold and a larger buffer on
the rare shortfall — exactness never depends on a probabilistic
margin.

Buffer shapes are bucketed to multiples of the dispatch batch so the
downstream classify kernels see ONE compiled shape per (ref, batch)
regardless of N, and rectangular refs share a single draw kernel per
bucket size (the triangular rejection mask needs per-nest geometry,
so tri refs compile per nest).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from ..runtime import telemetry

# Above this many int64 buffer slots (~2.2e8 -> ~10 GB across the
# sort/priority temporaries) the draw falls back to the host path:
# a v5e chip has 16 GB of HBM and the global-sort dedup needs several
# B-sized temporaries live at once.
DEVICE_DRAW_MAX_SLOTS = 1 << 28

# Rejection sentinel: strictly greater than every valid flat key.
# plan_draw routes space_box >= 2^46 to the host path (see
# _DEVICE_DRAW_MAX_SPACE below), so device-drawn keys are always
# far below _SENT.
_SENT = np.iinfo(np.int64).max

# jax.random.randint maps 64 random bits onto [0, space) by modulo, a
# systematic bias of ~space/2^64 relative toward low keys. Capping the
# device path at 2^46 keeps that bias below 2^-18 — the bound the
# docstring promises — and routes anything larger to the host numpy
# draw, which is unbiased (Lemire-style bounded rejection). Every
# registered model's box is far below this (GEMM N=8192 depth-3 refs:
# ~2^39); only hypothetical nests near the int64 edge are affected.
_DEVICE_DRAW_MAX_SPACE = 1 << 46


def bucket_size(m: int, batch: int) -> int:
    """Round the candidate count up to batch * 2^k with at least one
    batch. Geometric bucketing (round 5; previously any batch multiple)
    caps the number of distinct buffer shapes at ~log2(max/batch), so
    the scan-fused classify kernels — compiled per (structure, n_chunks)
    — and this module's draw kernels stay within a handful of compiles
    across every model and N instead of one per (ref, N). Costs at most
    2x padded draw compute, which is noise next to a single kernel
    compile through the tunneled AOT helper (~1-1.5 min)."""
    n_chunks = 1
    while n_chunks * batch < m:
        n_chunks *= 2
    return n_chunks * batch


def plan_draw(nt, ref_idx: int, cfg, batch: int):
    """The device-draw plan for one ref: (B, tri?, s, highs, excl,
    space_box), or None when the ref cannot take the device path
    (s == 0, empty tri space, a buffer beyond DEVICE_DRAW_MAX_SLOTS,
    or a box beyond _DEVICE_DRAW_MAX_SPACE, where randint's modulo
    bias would exceed the documented 2^-18 bound — the host draw is
    unbiased at any size, and raises its documented error only past
    int64 flat keys). Single source of truth for
    draw_sample_keys_device and warmup()."""
    from .sampled import _sample_plan

    highs, s, space_valid = _sample_plan(nt, ref_idx, cfg)
    if s == 0 or space_valid == 0:
        return None
    tri = nt.tri and int(nt.tables.ref_levels[ref_idx]) >= 1
    excl = 1 if cfg.exclude_last_iteration else 0
    space_box = 1
    for h in highs:
        space_box *= h
    if space_box >= _DEVICE_DRAW_MAX_SPACE:
        # modulo bias would exceed the documented 2^-18 bound (and at
        # >= 2^63-1 the sentinel would alias valid keys); host path
        return None
    if tri:
        # margin scales by the box/valid ratio the rejection will eat
        m = (s + s // 8 + 64) * space_box // space_valid + 64
    else:
        m = s + s // 8 + 64
    B = bucket_size(m, batch)
    if B > DEVICE_DRAW_MAX_SLOTS:
        return None
    return B, tri, s, tuple(highs), excl, space_box


def _select_exact(sk, valid_first, s, pri_key):
    """Uniform s-subset of the unique representatives in sorted keys.

    `valid_first` marks the first occurrence of each non-sentinel key.
    Returns (chosen mask, U, n_chosen): priorities are independent
    uint64 draws, the s smallest among representatives win; the counts
    come back to the host to certify exactness (U >= s and
    n_chosen == s), everything else stays on device.
    """
    B = sk.shape[0]
    U = jnp.sum(valid_first.astype(jnp.int64))
    pri = jr.bits(pri_key, (B,), dtype=jnp.uint64)
    pri = jnp.where(valid_first, pri, jnp.uint64(np.iinfo(np.uint64).max))
    spri = jnp.sort(pri)
    # threshold = s-th smallest priority among representatives; s is
    # traced so any s shares the compile
    thr = jnp.take(spri, jnp.clip(s - 1, 0, B - 1))
    chosen = valid_first & (pri <= thr)
    return chosen, U, jnp.sum(chosen.astype(jnp.int64))


def _rect_draw_body(rng_key, space, s, B: int):
    """One rectangular draw+dedup+thin: shared by the per-ref kernel
    and its vmapped bucket twin (threefry streams are counter-based per
    key, so the vmapped rows are bit-identical to per-ref calls —
    pinned by tests/test_draw.py)."""
    k1, k2 = jr.split(rng_key)
    keys = jr.randint(k1, (B,), 0, space, dtype=jnp.int64)
    sk = jnp.sort(keys)
    first = jnp.concatenate(
        [jnp.ones(1, bool), sk[1:] != sk[:-1]]
    )
    chosen, U, n_chosen = _select_exact(sk, first, s, k2)
    return sk, chosen, U, n_chosen


@telemetry.counted_lru_cache(maxsize=32)
def _rect_draw_kernel(B: int):
    """Shared draw kernel for rectangular refs: every ref/model/N with
    the same bucket size reuses one compile (space and s are traced)."""

    @jax.jit
    def draw(rng_key, space, s):
        return _rect_draw_body(rng_key, space, s, B)

    return draw


@telemetry.counted_lru_cache(maxsize=32)
def _rect_draw_kernel_batch(R: int, B: int):
    """Bucket form of _rect_draw_kernel: one dispatch draws every
    member of a signature bucket, vmapped over the (R,) stacked rng
    keys. Same per-row bits as R separate per-ref dispatches."""

    @jax.jit
    def draw(rng_keys, space, s):
        return jax.vmap(
            _rect_draw_body, in_axes=(0, None, None, None)
        )(rng_keys, space, s, B)

    return draw


def _draw_base_key(seed: int):
    """The per-ref threefry base key; split out so the bucket draw
    folds attempt 0 exactly as draw_sample_keys_device's retry loop."""
    base = jr.key(np.uint32(seed & 0xFFFFFFFF))
    return jr.fold_in(base, np.uint32((seed >> 32) & 0xFFFFFFFF))


def draw_bucket_keys_device(nt, ref_indices, cfg, seeds, batch: int):
    """Device draw for a whole kernel-signature bucket in (ideally) one
    vmapped dispatch.

    `ref_indices` share one kernel signature, hence one draw plan
    (same highs, s, buffer size B); `seeds` are their per-ref seeds in
    the same order. Returns a list parallel to ref_indices of
    (keys (B,), chosen (B,), s, highs) entries — an entry is None when
    that member cannot take the device path (the caller routes it to
    the host draw, exactly like the per-ref fallback). Returns None
    when the whole bucket is off the device path (no plan, or a
    triangular bucket — tri signatures are per-ref, so those buckets
    are singletons and take the per-ref draw).

    Bit-identity contract: attempt 0 is the vmapped twin of the
    per-ref kernel (same fold sequence, same threefry rows — pinned by
    tests/test_draw.py); the rare shortfall member replays the full
    per-ref retry loop, which deterministically re-fails attempt 0 and
    continues with the identical grown-buffer stream.
    """
    plan = plan_draw(nt, ref_indices[0], cfg, batch)
    if plan is None:
        return None
    B, tri, s, highs, excl, space_box = plan
    if tri or len(ref_indices) == 1:
        out = [
            draw_sample_keys_device(nt, ri, cfg, seed=sd, batch=batch)
            for ri, sd in zip(ref_indices, seeds)
        ]
        return None if all(o is None for o in out) else out
    bases = jnp.stack([jr.fold_in(_draw_base_key(sd), 0) for sd in seeds])
    kern = _rect_draw_kernel_batch(len(seeds), B)
    sk, chosen, U, n_chosen = kern(
        bases, jnp.int64(space_box), jnp.int64(s)
    )
    Uh, nh = np.asarray(U), np.asarray(n_chosen)
    out = []
    for j, (ri, sd) in enumerate(zip(ref_indices, seeds)):
        if int(Uh[j]) >= s and int(nh[j]) == s:
            out.append((sk[j], chosen[j], s, highs))
        else:
            # shortfall or 2^-64 priority tie: replay this member
            # through the per-ref retry loop (deterministic)
            out.append(draw_sample_keys_device(
                nt, ri, cfg, seed=sd, batch=batch
            ))
    return out


@telemetry.counted_lru_cache(maxsize=32)
def _rect_draw_kernel_batch_multi(R: int, B: int):
    """Cross-request form of _rect_draw_kernel_batch: rows may come
    from DIFFERENT programs/configs, so space and s are per-row
    operands instead of shared scalars. Each row's bits are still the
    per-ref kernel's (threefry is counter-based per key; the row's own
    space/s feed the same randint/thin as its solo call)."""

    @jax.jit
    def draw(rng_keys, spaces, ss):
        return jax.vmap(
            _rect_draw_body, in_axes=(0, 0, 0, None)
        )(rng_keys, spaces, ss, B)

    return draw


def draw_bucket_keys_device_multi(entries, batch: int):
    """Device draw for one cross-request UNION bucket.

    `entries` is [(nt, ref_idx, cfg, seed)] — members of one
    signature bucket that may span several programs and sampler
    configs, so unlike draw_bucket_keys_device they do NOT share a
    draw plan: each member plans with its own nest/config, and only
    members whose plans land on the same buffer size B stack into one
    vmapped dispatch (per-row space/s operands). Triangular members
    and singleton groups take the per-ref kernel.

    Returns a list parallel to entries of (keys (B,), chosen (B,), s,
    highs) — None for members off the device path (caller routes them
    to the host draw). Bit-identity: a member's group is keyed by ITS
    OWN planned B, its row consumes its own folded base key and
    space/s, and threefry rows are counter-per-key — so every member's
    buffer equals its solo draw_sample_keys_device attempt 0, with the
    shortfall replay running the identical per-ref retry loop.
    """
    out: list = [None] * len(entries)
    rect: dict[int, list] = {}
    for i, (nt, ri, cfg, sd) in enumerate(entries):
        plan = plan_draw(nt, ri, cfg, batch)
        if plan is None:
            continue
        B, tri, s, highs, excl, space_box = plan
        if tri:
            out[i] = draw_sample_keys_device(
                nt, ri, cfg, seed=sd, batch=batch
            )
            continue
        rect.setdefault(B, []).append((i, s, highs, space_box, sd))
    for B, grp in rect.items():
        if len(grp) == 1:
            i, s, highs, space_box, sd = grp[0]
            nt, ri, cfg, _sd = entries[i]
            out[i] = draw_sample_keys_device(
                nt, ri, cfg, seed=sd, batch=batch
            )
            continue
        bases = jnp.stack(
            [jr.fold_in(_draw_base_key(sd), 0)
             for _i, _s, _h, _sp, sd in grp]
        )
        spaces = jnp.asarray(
            [sp for _i, _s, _h, sp, _sd in grp], jnp.int64
        )
        ss = jnp.asarray([s for _i, s, _h, _sp, _sd in grp], jnp.int64)
        kern = _rect_draw_kernel_batch_multi(len(grp), B)
        sk, chosen, U, n_chosen = kern(bases, spaces, ss)
        Uh, nh = np.asarray(U), np.asarray(n_chosen)
        for j, (i, s, highs, _sp, sd) in enumerate(grp):
            if int(Uh[j]) >= s and int(nh[j]) == s:
                out[i] = (sk[j], chosen[j], s, highs)
            else:
                nt, ri, cfg, _sd = entries[i]
                out[i] = draw_sample_keys_device(
                    nt, ri, cfg, seed=sd, batch=batch
                )
    return out


def _build_tri_draw_kernel(nt, ref_idx: int, highs: tuple, excl: int, B: int):
    """Box-draw + rejection for one triangular ref (per-nest geometry
    lives in the closure, so these compile per ref)."""
    from .sampled import decode_sample_keys

    lv = int(nt.tables.ref_levels[ref_idx])
    space_box = 1
    for h in highs:
        space_box *= h

    @jax.jit
    def draw(rng_key, s):
        k1, k2 = jr.split(rng_key)
        keys = jr.randint(k1, (B,), 0, space_box, dtype=jnp.int64)
        cols = decode_sample_keys(keys, highs)
        v0 = nt.nest.loops[0].start + cols[:, 0] * nt.nest.loops[0].step
        ok = jnp.ones(B, dtype=bool)
        for l in range(1, lv + 1):
            ok &= cols[:, l] < (nt.nest.loops[l].trip_at(v0) - excl)
        sk = jnp.sort(jnp.where(ok, keys, jnp.int64(_SENT)))
        first = jnp.concatenate(
            [jnp.ones(1, bool), sk[1:] != sk[:-1]]
        ) & (sk < _SENT)
        chosen, U, n_chosen = _select_exact(sk, first, s, k2)
        return sk, chosen, U, n_chosen

    return draw


def draw_sample_keys_device(
    nt, ref_idx: int, cfg, seed: int, batch: int
):
    """Exactly-s distinct uniform sample keys, drawn and thinned on the
    default device.

    Returns (keys (B,) int64 device array, chosen (B,) bool device
    array with exactly s True entries, s, highs) — the masked form
    feeds the masked classify kernels without ever compacting to a
    per-ref shape. Returns None when plan_draw declines the ref (the
    caller falls back to the host draw).

    Deterministic in (cfg.seed-derived seed): threefry bits are
    backend-invariant, so CPU tests and TPU benches see the same
    sample sets. The [0, space) draw carries jax.random.randint's
    modulo bias of at most space/2^64 relative; plan_draw enforces
    space < _DEVICE_DRAW_MAX_SPACE = 2^46, keeping it below 2^-18 —
    orders of magnitude under sampling noise (the host numpy path is
    unbiased; the two paths are statistically, not bitwise,
    identical).
    """
    plan = plan_draw(nt, ref_idx, cfg, batch)
    if plan is None:
        return None
    B, tri, s, highs, excl, space_box = plan

    base = _draw_base_key(seed)
    for attempt in range(8):
        rng_key = jr.fold_in(base, attempt)
        if tri:
            kern = _get_tri_kernel(nt, ref_idx, highs, excl, B)
            sk, chosen, U, n_chosen = kern(rng_key, jnp.int64(s))
        else:
            kern = _rect_draw_kernel(B)
            sk, chosen, U, n_chosen = kern(
                rng_key, jnp.int64(space_box), jnp.int64(s)
            )
        if int(U) >= s and int(n_chosen) == s:
            return sk, chosen, s, highs
        # shortfall (not enough uniques in the buffer) or a 2^-64
        # priority tie: grow the buffer and redraw from a fresh fold
        B = bucket_size(B + B // 2, batch)
        if B > DEVICE_DRAW_MAX_SLOTS:
            return None
    raise RuntimeError(
        f"device draw failed to reach {s} unique samples in 8 attempts "
        f"(ref {nt.tables.ref_names[ref_idx]}; last buffer {B})"
    )


def _get_tri_kernel(nt, ref_idx, highs, excl, B):
    """Tri draw kernels cached ON the NestTrace: the kernel closure
    references nt (trip_at etc. in the jitted body), so any external
    registry keyed by nt — weak or strong — would keep the trace alive
    through its own values; an attribute cache gives the kernels
    exactly the trace's lifetime and cannot serve another nest's
    geometry after an id() reuse."""
    per_nt = nt.__dict__.setdefault("_tri_draw_kernels", {})
    key = (ref_idx, highs, excl, B)
    if key not in per_nt:
        per_nt[key] = _build_tri_draw_kernel(nt, ref_idx, highs, excl, B)
    return per_nt[key]
