"""Closed-form next-use solver — the heart of the sampled TPU engine.

The reference's sampled variant (rs-ri-opt-r10,
c_lib/test/sampler/gemm-t4-pluss-pro-model-rs-ri-opt-r10.cpp) finds a
sample's reuse by fast-forwarding the whole interleaved 4-thread walk
from the sample's chunk (dispatcher.setStartPoint, :233) and stepping
the state machine until the tracked line is touched again — a serial
O(trace) scan amortized over samples via a priority queue and en-route
sample absorption (:546-556). Because reuse intervals are differences
of the per-thread clock (count[tid] - LAT[tid][addr], :333), the answer
it computes is exactly:

    RI(sample) = min over same-array refs r' of
                 (first position p' > p0 in the sample thread's own
                  stream where r' touches the sample's cache line)
                 - p0

For affine references in row-major arrays, that "first position" has a
closed form. Every reference in the PolyBench family factors as

    flat = M*u + v + d,   line A  <=>  flat in [A*W, A*W + W),  W=CLS/DS

with u, v loop variables, M the row stride (>= W) and v's coefficient 1
(either var may be absent). The solutions are a tiny static candidate
set: at most ceil((W-1+span_v)/M)+2 values of u, and a window of W
values of v per u. Each candidate fixes some loop levels; the remaining
levels are free, and the minimal trace position beyond p0 over a
(fixed/free)^levels box is mixed-radix successor arithmetic.

So each sample's reuse costs O(candidates) = O(1) integer vector ops —
no walk, no hash map, no data-dependent loop — vectorized over all
samples at once. This is the re-design that makes sampling TPU-shaped:
the reference amortizes a serial scan; we eliminate it.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from ..core.trace import NestTrace

# plain int, not a jnp scalar: module import must not initialize a
# backend (jax.distributed.initialize requires none exists yet)
INF = 2**62


def _cdiv(a, b):
    """Ceil division for int arrays, exact for negative numerators."""
    return -((-a) // b)


@dataclasses.dataclass(frozen=True)
class _LevelSpec:
    """Domain of one loop level in a candidate: fixed to a value (with a
    validity mask), an interval [lo, hi) of normalized indices, or free
    over [0, bound)."""

    fixed: bool
    value: object = None  # jnp int64 array when fixed
    valid: object = None  # jnp bool array when fixed
    bound: object = None  # jnp/int upper bound when free
    lo: object = None  # jnp int64 array when interval
    hi: object = None  # jnp int64 array when interval (empty if hi<=lo)

    @staticmethod
    def free(bound):
        return _LevelSpec(fixed=False, bound=bound)

    @staticmethod
    def fix(value, valid):
        return _LevelSpec(fixed=True, value=value, valid=valid)

    @staticmethod
    def interval(lo, hi):
        return _LevelSpec(fixed=False, lo=lo, hi=hi)

    def min_val(self):
        """Smallest element, INF-marked when empty/invalid."""
        if self.fixed:
            return jnp.where(self.valid, self.value, INF)
        if self.lo is not None:
            return jnp.where(self.lo < self.hi, self.lo, INF)
        return jnp.zeros((), dtype=jnp.int64)

    def min_gt(self, x):
        """Smallest element > x, INF when none."""
        if self.fixed:
            ok = self.valid & (self.value > x)
            return jnp.where(ok, self.value, INF)
        if self.lo is not None:
            nxt = jnp.maximum(self.lo, x + 1)
            return jnp.where(nxt < self.hi, nxt, INF)
        nxt = jnp.maximum(jnp.int64(0), x + 1)
        return jnp.where(nxt < self.bound, nxt, INF)

    def eq(self, x):
        """x if x is in the domain, else INF."""
        if self.fixed:
            ok = self.valid & (self.value == x)
            return jnp.where(ok, x, INF)
        if self.lo is not None:
            return jnp.where((x >= self.lo) & (x < self.hi), x, INF)
        return jnp.where((x >= 0) & (x < self.bound), x, INF)

    def min_scaled_gt(self, scale, x):
        """Smallest element v with v*scale > x, INF when none (scale>0)."""
        if self.fixed:
            ok = self.valid & (self.value * scale > x)
            return jnp.where(ok, self.value, INF)
        if self.lo is not None:
            nxt = jnp.maximum(self.lo, x // scale + 1)
            return jnp.where(nxt < self.hi, nxt, INF)
        nxt = jnp.maximum(jnp.int64(0), x // scale + 1)
        return jnp.where(nxt < self.bound, nxt, INF)


def min_position_after(nt: NestTrace, ref_idx: int, p0, specs):
    """Minimal position of `ref_idx` strictly after p0 over a level box.

    `specs`: list of _LevelSpec, one per level 0..ref.level. Positions
    follow core/trace.py::access_position. Returns INF where empty.
    """
    t = nt.tables
    lv = int(t.ref_levels[ref_idx])
    off = nt.vals["off"][ref_idx]
    a0 = nt.vals["acc"][0]
    np0, np1 = nt.npre[0], (nt.npre[1] if nt.nest.depth > 1 else 0)

    m0 = p0 // a0
    r0 = p0 - m0 * a0

    def pos(m, n1=None, n2=None):
        p = m * a0 + off
        if lv >= 1:
            p = p + np0 + n1 * nt.vals["acc"][1]
        if lv >= 2:
            p = p + np1 + n2 * nt.vals["acc"][2]
        return p

    def guard(p, *parts):
        bad = jnp.zeros_like(p, dtype=bool)
        for q in parts:
            bad = bad | (q >= INF)
        return jnp.where(bad, INF, p)

    cands = []
    if lv == 0:
        # strategy A: bump m; strategy B: same m, later body offset
        mA = specs[0].min_gt(m0)
        cands.append(guard(pos(mA), mA))
        mB = specs[0].eq(m0)
        pB = guard(pos(mB), mB)
        cands.append(jnp.where(pB > p0, pB, INF))
        return jnp.minimum(*cands) if len(cands) > 1 else cands[0]

    a1 = nt.vals["acc"][1]
    j0 = (r0 - np0) // a1
    rr0 = r0 - np0 - j0 * a1

    if lv == 1:
        mA = specs[0].min_gt(m0)
        n1A = specs[1].min_val()
        cands.append(guard(pos(mA, n1A), mA, n1A))
        mB = specs[0].eq(m0)
        n1B = specs[1].min_gt(j0)
        cands.append(guard(pos(mB, n1B), mB, n1B))
        mC = specs[0].eq(m0)
        n1C = specs[1].eq(j0)
        pC = guard(pos(mC, n1C), mC, n1C)
        cands.append(jnp.where(pC > p0, pC, INF))
    else:
        a2 = nt.vals["acc"][2]
        mA = specs[0].min_gt(m0)
        n1A = specs[1].min_val()
        n2A = specs[2].min_val()
        cands.append(guard(pos(mA, n1A, n2A), mA, n1A, n2A))
        mB = specs[0].eq(m0)
        n1B = specs[1].min_gt(j0)
        n2B = specs[2].min_val()
        cands.append(guard(pos(mB, n1B, n2B), mB, n1B, n2B))
        mC = specs[0].eq(m0)
        n1C = specs[1].eq(j0)
        # need np1 + n2*a2 + off > rr0
        n2C = specs[2].min_scaled_gt(a2, rr0 - np1 - off)
        pC = guard(pos(mC, n1C, n2C), mC, n1C, n2C)
        cands.append(jnp.where(pC > p0, pC, INF))

    out = cands[0]
    for c in cands[1:]:
        out = jnp.minimum(out, c)
    return out


# Per-level candidate cap for _band_candidates. Well-separated strides
# (the whole PolyBench family: n^2, n, 1, ...) give n_u <= W/c + 3, i.e.
# single digits at the default W=8; anything past this cap means the
# head coefficient does not dominate and the enumeration is not O(1).
_MAX_BAND_CANDIDATES = 128


def _ref_vars_static(nt: NestTrace, ref_idx: int):
    """Nonzero (level, concrete coeff) terms of a ref's flat map, coeff
    descending — the STRUCTURE of the band enumeration (the traced math
    reads the coefficient values from nt.vals).

    The row-major PolyBench family always yields positive coefficients
    (strides n^2, n, 1 ...); negative strides have no closed-form band
    enumeration here and raise.
    """
    t = nt.tables
    lv = int(t.ref_levels[ref_idx])
    nz = [(l, int(t.ref_coeffs[ref_idx][l])) for l in range(lv + 1)
          if int(t.ref_coeffs[ref_idx][l]) != 0]
    for _, c in nz:
        if c <= 0:
            raise NotImplementedError(
                f"ref {t.ref_names[ref_idx]}: negative stride unsupported"
            )
    nz.sort(key=lambda p: -p[1])
    return nz


def band_plan(nt: NestTrace, sink_idx: int, W: int) -> tuple:
    """The static shape of one ref's band enumeration, from CONCRETE
    trace values: a nested tuple of nodes

      ("head", level, n_u, child)   enumerate n_u head-variable values
      ("interval", level)           unit-stride terminal, one interval
      ("window", level, W)          unit-stride terminal, W fixed values
      ("check",)                    constant-terminal band check

    _band_candidates follows this plan with traced math, so the plan is
    exactly the part of the enumeration that a compiled kernel bakes
    in — it is the band component of the kernel signature
    (sampler/sampled.py::_kernel_sig): two traces with equal plans (and
    equal structural tables) can share one compiled kernel, with every
    numeric difference riding in as operands.
    """
    nz = _ref_vars_static(nt, sink_idx)

    def node(vars_left):
        if not vars_left:
            return ("check",)
        if len(vars_left) == 1 and vars_left[0][1] == 1:
            l, _ = vars_left[0]
            if l != 0 and nt.nest.loops[l].step == 1:
                return ("interval", l)
            return ("window", l, W)
        (l, c), rest = vars_left[0], vars_left[1:]
        r_min = sum(cr * nt.level_value_range(lr)[0] for lr, cr in rest)
        r_max = sum(cr * nt.level_value_range(lr)[1] for lr, cr in rest)
        n_u = (W - 1 + (r_max - r_min)) // c + 2  # static bound
        if n_u > _MAX_BAND_CANDIDATES:
            # O(1) only holds when the head coefficient dominates the
            # residual span (true for row-major affine maps, strides
            # n^2 > n > 1). Two comparable coefficients (e.g. flat =
            # i + j) would make n_u O(trip), silently unrolling
            # thousands of emit() calls into the traced graph; fail
            # fast like the negative-stride gate instead.
            raise NotImplementedError(
                f"ref {nt.tables.ref_names[sink_idx]}: head stride {c} "
                f"does not dominate the residual span "
                f"[{r_min}, {r_max}] ({n_u} band candidates > cap "
                f"{_MAX_BAND_CANDIDATES}); no O(1) closed-form band "
                "enumeration for this flat map"
            )
        return ("head", l, n_u, node(rest))

    return node(nz)


def _band_candidates(nt: NestTrace, sink_idx: int, lo, W: int, true_, emit):
    """Enumerate level-value assignments whose flat map lands in the
    band [lo, lo+W), following band_plan's static structure: each head
    value divides the residual band, the innermost unit-stride variable
    takes an exact W-wide window (one value-space interval where the
    level permits, W per-value candidates otherwise), and a trailing
    band check covers every other terminal. All numeric inputs (coeffs,
    const, value spans) come from nt.vals, so the emitted graph is
    N-generic under with_vals. Shared by the rectangular and triangular
    solvers; `emit(fixed_vals, ok)` receives value-space encodings
    {level: ("fixval", u) | ("interval", va, vb)}.
    """
    plan = band_plan(nt, sink_idx, W)
    nz = _ref_vars_static(nt, sink_idx)
    coeff_v = {l: nt.vals["coeff"][sink_idx][l] for l, _ in nz}
    lo = lo - nt.vals["const"][sink_idx]
    vlo_v, vhi_v = nt.vals["vlo"], nt.vals["vhi"]

    def follow(pnode, vars_left, lo_cur, ok, fixed_vals):
        kind = pnode[0]
        if kind == "check":
            # remaining contribution is 0: valid iff 0 in [lo_cur, lo_cur+W)
            emit(fixed_vals, ok & (lo_cur <= 0) & (lo_cur > -W))
            return
        if kind == "interval":
            l = pnode[1]
            # one contiguous interval replaces W per-value candidates
            # (band membership by construction); level 0 is excluded
            # because thread ownership chops its range
            emit({**fixed_vals, l: ("interval", lo_cur, lo_cur + W)}, ok)
            return
        if kind == "window":
            l = pnode[1]
            for k in range(pnode[2]):  # exact window
                emit({**fixed_vals, l: ("fixval", lo_cur + k)}, ok)
            return
        _, l, n_u, child = pnode
        cv = coeff_v[l]
        rest = vars_left[1:]
        r_min = sum(coeff_v[lr] * vlo_v[lr] for lr, _ in rest)
        r_max = sum(coeff_v[lr] * vhi_v[lr] for lr, _ in rest)
        u_min = _cdiv(lo_cur - r_max, cv)
        u_max = (lo_cur + W - 1 - r_min) // cv
        for iu in range(n_u):
            u = u_min + iu
            follow(child, rest, lo_cur - cv * u, ok & (u <= u_max),
                   {**fixed_vals, l: ("fixval", u)})

    follow(plan, nz, lo, true_, {})


def next_use_candidates_group(
    nt: NestTrace, sinks: tuple, tid, p0, line
):
    """Min positions > p0 where each sink in `sinks` touches `line` on
    thread tid, for sinks sharing one flat map (level, coeffs, const) —
    only their body offsets differ, so the band candidates and level
    specs are built once and each sink pays only its own
    min_position_after reduction. Returns {sink_idx: positions}.

    Vectorized over samples (tid, p0, line are arrays). Band candidates
    come from _band_candidates; each is reduced with
    min_position_after over a (fixed/interval/free)^levels box.
    """
    sink_idx = sinks[0]
    t = nt.tables
    machine = nt.machine
    sched = nt.schedule
    lv = int(t.ref_levels[sink_idx])
    W = machine.lines_per_element_block

    # per-sample local-count bound for free level 0
    local_counts = jnp.asarray(nt.vals["lc"])
    l_bound = local_counts[tid]
    trips_v = nt.vals["trips"]

    def level_bound(l):
        return l_bound if l == 0 else trips_v[l]

    def spec_from_value(l, value, extra_valid):
        """Fix level l to loop *value* `value` (normalize + validate)."""
        lp = nt.nest.loops[l]
        n = (value - lp.start) // lp.step
        ok = extra_valid & ((value - lp.start) % lp.step == 0)
        ok = ok & (n >= 0) & (n < trips_v[l])
        if l == 0:
            ok = ok & (sched.owner_tid(n) == tid)
            return _LevelSpec.fix(sched.local_index(n), ok)
        return _LevelSpec.fix(n, ok)

    def assemble(fixed_vals, ok):
        """fixed_vals: value-space encodings; `ok` ANDs into each."""
        specs = []
        for l in range(lv + 1):
            if l in fixed_vals:
                kind = fixed_vals[l][0]
                if kind == "interval":
                    lp = nt.nest.loops[l]
                    _, va, vb = fixed_vals[l]
                    n_lo = jnp.maximum(va - lp.start, 0)
                    n_hi = jnp.minimum(vb - lp.start, trips_v[l])
                    specs.append(_LevelSpec.interval(
                        n_lo, jnp.where(ok, n_hi, n_lo)
                    ))
                else:
                    specs.append(spec_from_value(l, fixed_vals[l][1], ok))
            else:
                specs.append(_LevelSpec.free(level_bound(l)))
        return specs

    bests = {
        j: jnp.full(jnp.shape(p0), INF, dtype=jnp.int64)
        for j in sinks
    }
    true_ = jnp.ones(jnp.shape(p0), dtype=bool)

    def emit(fixed_vals, ok):
        specs = assemble(fixed_vals, ok)
        for j in sinks:
            p = min_position_after(nt, j, p0, specs)
            if not fixed_vals:  # constant ref: no spec carries validity
                p = jnp.where(ok, p, INF)
            bests[j] = jnp.minimum(bests[j], p)

    _band_candidates(nt, sink_idx, line * W, W, true_, emit)
    return bests


def next_use_candidates_tri_group(
    nt: NestTrace, sinks: tuple, tid, p0, line, m0
):
    """Triangular-nest twin of next_use_candidates_group (sinks share
    one flat map; candidates, domain bounds and the later-iteration
    schedule query are built once, each sink pays only its own
    position reductions). Returns {sink_idx: positions}.

    Same band enumeration (the flat map must land in the line's W-wide
    band), but positions come from the per-thread prefix-sum base table
    and every inner-level domain is evaluated at a concrete parallel
    value v0, because bounds (and so body sizes and offsets) are affine
    in v0. Three position strategies survive unchanged in shape:

    - same parallel iteration (v0 known per sample): bump the level-1
      index past p0's, or keep it and bump the level-2 index — exactly
      min_position_after's B/C arms with v0-dependent body sizes;
    - a later parallel iteration: every candidate's inner domain is
      nonempty over an affine *interval* of v0 (each bound contributes
      one halfspace), so the minimal valid m' > m0 is a closed-form
      schedule query (count_below) and positions at m' are gathers of
      the base table.

    Requires every loop step == 1 (all triangular PolyBench kernels;
    enforced by the caller's gate). `m0` is each sample's thread-local
    parallel index. Vectorized over samples; returns INF where no later
    touch exists.
    """
    sink_idx = sinks[0]
    t = nt.tables
    machine = nt.machine
    sched = nt.schedule
    nest = nt.nest
    lv = int(t.ref_levels[sink_idx])
    W = machine.lines_per_element_block

    base_tab = jnp.asarray(nt.vals["tri_base"])
    lmax = base_tab.shape[1] - 1  # == sched.max_local_count(), static
    local_counts = jnp.asarray(nt.vals["lc"])
    l_count = local_counts[tid]
    start0, trip0 = nest.loops[0].start, nt.vals["trips"][0]
    np0 = nt.npre[0]
    np1 = nt.npre[1] if nest.depth > 1 else 0
    a2 = (
        nt.npre[2] + nt.npost[2] if nest.depth > 2 else 1
    )  # deepest-level body = its refs

    def base_of(m):
        return base_tab[tid, jnp.clip(m, 0, lmax)]

    v0_0 = sched.local_to_value(tid, m0)
    base_0 = base_of(m0)

    def dom_bounds(l, dom, v0m):
        """Half-open index interval [lo, hi) of domain `dom` at v0m."""
        tripv = nt.trip_at(l, v0m)
        if dom is None:  # free
            return jnp.zeros_like(tripv), tripv
        kind = dom[0]
        if kind == "fixval":
            n = dom[1] - nt.start_at(l, v0m)
            ok = (n >= 0) & (n < tripv)
            return n, jnp.where(ok, n + 1, n)
        va, vb = dom[1], dom[2]  # value-space interval [va, vb)
        lo_i = jnp.maximum(va - nt.start_at(l, v0m), 0)
        hi_i = jnp.minimum(vb - nt.start_at(l, v0m), tripv)
        return lo_i, jnp.maximum(hi_i, lo_i)

    def min_inner_pos(doms, v0m, basem, okm, j):
        """Min position of sink `j` > p0 within iteration (v0m, basem)."""
        offv = nt.ref_offset_at(j, v0m)
        if lv == 0:
            pos = basem + offv
            return jnp.where(okm & (pos > p0), pos, INF)
        b1 = jnp.maximum(nt.body_at(1, v0m), 1)
        d1lo, d1hi = dom_bounds(1, doms.get(1), v0m)
        if lv == 1:
            rel = p0 - basem - np0 - offv
            n1 = jnp.maximum(d1lo, rel // b1 + 1)
            pos = basem + np0 + n1 * b1 + offv
            return jnp.where(okm & (n1 < d1hi), pos, INF)
        d2lo, d2hi = dom_bounds(2, doms.get(2), v0m)
        r = p0 - basem - np0
        j_a = r // b1
        rr = r - j_a * b1
        n1a = jnp.maximum(d1lo, j_a + 1)
        pos_a = basem + np0 + n1a * b1 + np1 + d2lo * a2 + offv
        ok_a = okm & (n1a < d1hi) & (d2lo < d2hi)
        n2 = jnp.maximum(d2lo, (rr - np1 - offv) // a2 + 1)
        pos_b = basem + np0 + j_a * b1 + np1 + n2 * a2 + offv
        ok_b = okm & (j_a >= d1lo) & (j_a < d1hi) & (n2 < d2hi)
        return jnp.minimum(
            jnp.where(ok_a, pos_a, INF), jnp.where(ok_b, pos_b, INF)
        )

    def later_m_context(doms, ok):
        """(v0, base, ok) of the earliest parallel iteration m' > m0
        whose inner domains are nonempty — shared by every sink of the
        group.

        Each inner domain is nonempty over an affine v0 halfspace
        intersection; the minimal valid m' is a count_below query.
        """
        z = jnp.zeros(jnp.shape(p0), dtype=jnp.int64)
        vlo = z + start0
        vhi = z + start0 + trip0 - 1
        okc = ok

        def add(a, b):
            """Accumulate constraint a*v0 + b >= 0 (a static int)."""
            nonlocal vlo, vhi, okc
            b = jnp.asarray(b, dtype=jnp.int64)
            if a > 0:
                vlo = jnp.maximum(vlo, _cdiv(-b, a))
            elif a < 0:
                vhi = jnp.minimum(vhi, b // (-a))
            else:
                okc = okc & (b >= 0)

        for l in range(1, lv + 1):
            lp = nest.loops[l]
            s, sc = nt.vals["startb"][l], lp.start_coeff
            tr, tc = nt.vals["trips"][l], lp.trip_coeff
            dom = doms.get(l)
            if dom is None:
                add(tc, tr - 1)  # trip(v0) >= 1
            elif dom[0] == "fixval":
                u = dom[1]
                add(-sc, u - s)  # index >= 0
                add(tc + sc, tr - u + s - 1)  # index < trip(v0)
            else:
                va, vb = dom[1], dom[2]
                add(tc, tr - 1)
                add(-sc, vb - s - 1)  # interval reaches index > 0
                add(tc + sc, tr - va + s - 1)  # interval start < trip
        n_lo = jnp.clip(vlo - start0, 0, trip0)
        m_a = jnp.maximum(m0 + 1, sched.count_below(tid, n_lo))
        ok_a = okc & (m_a < l_count)
        m_ac = jnp.clip(m_a, 0, lmax)
        v0a = sched.local_to_value(tid, m_ac)
        ok_a = ok_a & (v0a >= vlo) & (v0a <= vhi)
        return v0a, base_of(m_ac), ok_a

    bests = {
        j: jnp.full(jnp.shape(p0), INF, dtype=jnp.int64)
        for j in sinks
    }
    true_ = jnp.ones(jnp.shape(p0), dtype=bool)

    def emit(fixed_vals, ok):
        doms = {l: v for l, v in fixed_vals.items() if l != 0}
        if 0 in fixed_vals:
            u0 = fixed_vals[0][1]
            n0 = u0 - start0
            okf = ok & (n0 >= 0) & (n0 < trip0)
            okf = okf & (sched.owner_tid(n0) == tid)
            basef = base_of(jnp.clip(sched.local_index(n0), 0, lmax))
            for j in sinks:
                bests[j] = jnp.minimum(
                    bests[j], min_inner_pos(doms, u0, basef, okf, j)
                )
        else:
            v0a, base_a, ok_a = later_m_context(doms, ok)
            for j in sinks:
                pos = jnp.minimum(
                    min_inner_pos(doms, v0_0, base_0, ok, j),
                    min_inner_pos(doms, v0a, base_a, ok_a, j),
                )
                bests[j] = jnp.minimum(bests[j], pos)

    _band_candidates(nt, sink_idx, line * W, W, true_, emit)
    return bests
