"""Closed-form next-use solver — the heart of the sampled TPU engine.

The reference's sampled variant (rs-ri-opt-r10,
c_lib/test/sampler/gemm-t4-pluss-pro-model-rs-ri-opt-r10.cpp) finds a
sample's reuse by fast-forwarding the whole interleaved 4-thread walk
from the sample's chunk (dispatcher.setStartPoint, :233) and stepping
the state machine until the tracked line is touched again — a serial
O(trace) scan amortized over samples via a priority queue and en-route
sample absorption (:546-556). Because reuse intervals are differences
of the per-thread clock (count[tid] - LAT[tid][addr], :333), the answer
it computes is exactly:

    RI(sample) = min over same-array refs r' of
                 (first position p' > p0 in the sample thread's own
                  stream where r' touches the sample's cache line)
                 - p0

For affine references in row-major arrays, that "first position" has a
closed form. Every reference in the PolyBench family factors as

    flat = M*u + v + d,   line A  <=>  flat in [A*W, A*W + W),  W=CLS/DS

with u, v loop variables, M the row stride (>= W) and v's coefficient 1
(either var may be absent). The solutions are a tiny static candidate
set: at most ceil((W-1+span_v)/M)+2 values of u, and a window of W
values of v per u. Each candidate fixes some loop levels; the remaining
levels are free, and the minimal trace position beyond p0 over a
(fixed/free)^levels box is mixed-radix successor arithmetic.

So each sample's reuse costs O(candidates) = O(1) integer vector ops —
no walk, no hash map, no data-dependent loop — vectorized over all
samples at once. This is the re-design that makes sampling TPU-shaped:
the reference amortizes a serial scan; we eliminate it.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from ..core.trace import NestTrace

INF = jnp.int64(2**62)


def _cdiv(a, b):
    """Ceil division for int arrays, exact for negative numerators."""
    return -((-a) // b)


@dataclasses.dataclass(frozen=True)
class _LevelSpec:
    """Domain of one loop level in a candidate: fixed to a value (with a
    validity mask), an interval [lo, hi) of normalized indices, or free
    over [0, bound)."""

    fixed: bool
    value: object = None  # jnp int64 array when fixed
    valid: object = None  # jnp bool array when fixed
    bound: object = None  # jnp/int upper bound when free
    lo: object = None  # jnp int64 array when interval
    hi: object = None  # jnp int64 array when interval (empty if hi<=lo)

    @staticmethod
    def free(bound):
        return _LevelSpec(fixed=False, bound=bound)

    @staticmethod
    def fix(value, valid):
        return _LevelSpec(fixed=True, value=value, valid=valid)

    @staticmethod
    def interval(lo, hi):
        return _LevelSpec(fixed=False, lo=lo, hi=hi)

    def min_val(self):
        """Smallest element, INF-marked when empty/invalid."""
        if self.fixed:
            return jnp.where(self.valid, self.value, INF)
        if self.lo is not None:
            return jnp.where(self.lo < self.hi, self.lo, INF)
        return jnp.zeros((), dtype=jnp.int64)

    def min_gt(self, x):
        """Smallest element > x, INF when none."""
        if self.fixed:
            ok = self.valid & (self.value > x)
            return jnp.where(ok, self.value, INF)
        if self.lo is not None:
            nxt = jnp.maximum(self.lo, x + 1)
            return jnp.where(nxt < self.hi, nxt, INF)
        nxt = jnp.maximum(jnp.int64(0), x + 1)
        return jnp.where(nxt < self.bound, nxt, INF)

    def eq(self, x):
        """x if x is in the domain, else INF."""
        if self.fixed:
            ok = self.valid & (self.value == x)
            return jnp.where(ok, x, INF)
        if self.lo is not None:
            return jnp.where((x >= self.lo) & (x < self.hi), x, INF)
        return jnp.where((x >= 0) & (x < self.bound), x, INF)

    def min_scaled_gt(self, scale, x):
        """Smallest element v with v*scale > x, INF when none (scale>0)."""
        if self.fixed:
            ok = self.valid & (self.value * scale > x)
            return jnp.where(ok, self.value, INF)
        if self.lo is not None:
            nxt = jnp.maximum(self.lo, x // scale + 1)
            return jnp.where(nxt < self.hi, nxt, INF)
        nxt = jnp.maximum(jnp.int64(0), x // scale + 1)
        return jnp.where(nxt < self.bound, nxt, INF)


def min_position_after(nt: NestTrace, ref_idx: int, p0, specs):
    """Minimal position of `ref_idx` strictly after p0 over a level box.

    `specs`: list of _LevelSpec, one per level 0..ref.level. Positions
    follow core/trace.py::access_position. Returns INF where empty.
    """
    t = nt.tables
    lv = int(t.ref_levels[ref_idx])
    off = int(t.ref_offsets[ref_idx])
    a0 = int(t.acc_per_level[0])
    np0, np1 = nt.npre[0], (nt.npre[1] if nt.nest.depth > 1 else 0)

    m0 = p0 // a0
    r0 = p0 - m0 * a0

    def pos(m, n1=None, n2=None):
        p = m * a0 + off
        if lv >= 1:
            p = p + np0 + n1 * int(t.acc_per_level[1])
        if lv >= 2:
            p = p + np1 + n2 * int(t.acc_per_level[2])
        return p

    def guard(p, *parts):
        bad = jnp.zeros_like(p, dtype=bool)
        for q in parts:
            bad = bad | (q >= INF)
        return jnp.where(bad, INF, p)

    cands = []
    if lv == 0:
        # strategy A: bump m; strategy B: same m, later body offset
        mA = specs[0].min_gt(m0)
        cands.append(guard(pos(mA), mA))
        mB = specs[0].eq(m0)
        pB = guard(pos(mB), mB)
        cands.append(jnp.where(pB > p0, pB, INF))
        return jnp.minimum(*cands) if len(cands) > 1 else cands[0]

    a1 = int(t.acc_per_level[1])
    j0 = (r0 - np0) // a1
    rr0 = r0 - np0 - j0 * a1

    if lv == 1:
        mA = specs[0].min_gt(m0)
        n1A = specs[1].min_val()
        cands.append(guard(pos(mA, n1A), mA, n1A))
        mB = specs[0].eq(m0)
        n1B = specs[1].min_gt(j0)
        cands.append(guard(pos(mB, n1B), mB, n1B))
        mC = specs[0].eq(m0)
        n1C = specs[1].eq(j0)
        pC = guard(pos(mC, n1C), mC, n1C)
        cands.append(jnp.where(pC > p0, pC, INF))
    else:
        a2 = int(t.acc_per_level[2])
        mA = specs[0].min_gt(m0)
        n1A = specs[1].min_val()
        n2A = specs[2].min_val()
        cands.append(guard(pos(mA, n1A, n2A), mA, n1A, n2A))
        mB = specs[0].eq(m0)
        n1B = specs[1].min_gt(j0)
        n2B = specs[2].min_val()
        cands.append(guard(pos(mB, n1B, n2B), mB, n1B, n2B))
        mC = specs[0].eq(m0)
        n1C = specs[1].eq(j0)
        # need np1 + n2*a2 + off > rr0
        n2C = specs[2].min_scaled_gt(a2, rr0 - np1 - off)
        pC = guard(pos(mC, n1C, n2C), mC, n1C, n2C)
        cands.append(jnp.where(pC > p0, pC, INF))

    out = cands[0]
    for c in cands[1:]:
        out = jnp.minimum(out, c)
    return out


def _ref_vars(nt: NestTrace, ref_idx: int):
    """Nonzero (level, coeff) terms of a ref's flat map, coeff descending.

    The row-major PolyBench family always yields positive coefficients
    (strides n^2, n, 1 ...); negative strides have no closed-form band
    enumeration here and raise.
    """
    t = nt.tables
    lv = int(t.ref_levels[ref_idx])
    nz = [(l, int(t.ref_coeffs[ref_idx][l])) for l in range(lv + 1)
          if int(t.ref_coeffs[ref_idx][l]) != 0]
    for _, c in nz:
        if c <= 0:
            raise NotImplementedError(
                f"ref {t.ref_names[ref_idx]}: negative stride unsupported"
            )
    nz.sort(key=lambda p: -p[1])
    return nz, int(t.ref_consts[ref_idx])


def next_use_candidates(nt: NestTrace, sink_idx: int, tid, p0, line):
    """Min position > p0 where `sink_idx` touches `line` on thread tid.

    Vectorized over samples (tid, p0, line are arrays). The flat map
    sum_i c_i*x_i + d must land in the line's band [line*W, line*W + W);
    candidates for the x_i are enumerated recursively, largest stride
    first: each head value divides the residual band, the innermost
    unit-stride variable takes an exact W-wide window, and a trailing
    band check covers every other terminal. The candidate count is a
    static O(1) bound per level, so the whole solve stays a fixed
    vector program. Reduces with min_position_after.
    """
    t = nt.tables
    machine = nt.machine
    sched = nt.schedule
    lv = int(t.ref_levels[sink_idx])
    W = machine.lines_per_element_block
    nz, d = _ref_vars(nt, sink_idx)
    lo = line * W - d  # target flat-offset band [lo, lo+W)

    # per-sample local-count bound for free level 0
    local_counts = jnp.array(
        [sched.local_count(tt) for tt in range(sched.threads)], dtype=jnp.int64
    )
    l_bound = local_counts[tid]

    def level_bound(l):
        return l_bound if l == 0 else jnp.int64(nt.nest.loops[l].trip)

    def spec_from_value(l, value, extra_valid):
        """Fix level l to loop *value* `value` (normalize + validate)."""
        lp = nt.nest.loops[l]
        n = (value - lp.start) // lp.step
        ok = extra_valid & ((value - lp.start) % lp.step == 0)
        ok = ok & (n >= 0) & (n < lp.trip)
        if l == 0:
            ok = ok & (sched.owner_tid(n) == tid)
            return _LevelSpec.fix(sched.local_index(n), ok)
        return _LevelSpec.fix(n, ok)

    def assemble(fixed_vals, ok):
        """fixed_vals: {level: value or ('interval', n_lo, n_hi)};
        `ok` ANDs into every fixed/interval spec."""
        specs = []
        for l in range(lv + 1):
            if l in fixed_vals:
                fv = fixed_vals[l]
                if isinstance(fv, tuple) and fv[0] == "interval":
                    _, n_lo, n_hi = fv
                    specs.append(_LevelSpec.interval(
                        n_lo, jnp.where(ok, n_hi, n_lo)
                    ))
                else:
                    specs.append(spec_from_value(l, fv, ok))
            else:
                specs.append(_LevelSpec.free(level_bound(l)))
        return specs

    def value_span(l):
        lp = nt.nest.loops[l]
        return min(lp.start, lp.last), max(lp.start, lp.last)

    best = jnp.full(jnp.shape(p0), INF.item(), dtype=jnp.int64)
    true_ = jnp.ones(jnp.shape(p0), dtype=bool)

    def emit(fixed_vals, ok):
        nonlocal best
        p = min_position_after(nt, sink_idx, p0, assemble(fixed_vals, ok))
        if not fixed_vals:  # constant ref: no spec carries the validity
            p = jnp.where(ok, p, INF)
        best = jnp.minimum(best, p)

    def recurse(vars_left, lo_cur, ok, fixed_vals):
        if not vars_left:
            # remaining contribution is 0: valid iff 0 in [lo_cur, lo_cur+W)
            emit(fixed_vals, ok & (lo_cur <= 0) & (lo_cur > -W))
            return
        if len(vars_left) == 1 and vars_left[0][1] == 1:
            l, _ = vars_left[0]
            lp = nt.nest.loops[l]
            if l != 0 and lp.step == 1:
                # The W-wide value window [lo_cur, lo_cur+W) maps to one
                # contiguous normalized-index interval: a single spec
                # replaces W per-value candidates (band membership and
                # trip clipping by construction). Level 0 is excluded
                # because ownership chops its index range per thread.
                n_lo = jnp.maximum(lo_cur - lp.start, 0)
                n_hi = jnp.minimum(lo_cur - lp.start + W, lp.trip)
                emit({**fixed_vals, l: ("interval", n_lo, n_hi)}, ok)
                return
            for k in range(W):  # exact window, band membership by construction
                emit({**fixed_vals, l: lo_cur + k}, ok)
            return
        (l, c), rest = vars_left[0], vars_left[1:]
        r_min = sum(cr * value_span(lr)[0] for lr, cr in rest)
        r_max = sum(cr * value_span(lr)[1] for lr, cr in rest)
        u_min = _cdiv(lo_cur - r_max, c)
        u_max = (lo_cur + W - 1 - r_min) // c
        n_u = (W - 1 + (r_max - r_min)) // c + 2  # static bound
        for iu in range(n_u):
            u = u_min + iu
            recurse(rest, lo_cur - c * u, ok & (u <= u_max),
                    {**fixed_vals, l: u})

    recurse(nz, lo, true_, {})
    return best
